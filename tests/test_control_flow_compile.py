"""While/conditional_block XLA lowering tests.

The contract (SURVEY.md §7 step 3): programs containing control flow
must still whole-program compile (lax.while_loop / lax.cond), and the
compiled results must agree with the op-by-op interpreter
(/root/reference/paddle/fluid/operators/controlflow/while_op.cc
semantics: body writes parent-scope vars by name each trip)."""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu.core.executor_core import CoreExecutor


def _build_while_program():
    """x doubles 5 times: while(i < 5) { x = 2x; i += 1 }"""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x0 = fluid.data(name="x0", shape=[4], dtype="float32")
        x = fluid.layers.assign(x0)
        i = fluid.layers.fill_constant(shape=[1], dtype="int64", value=0)
        n = fluid.layers.fill_constant(shape=[1], dtype="int64", value=5)
        cond = fluid.layers.less_than(i, n)
        w = fluid.layers.While(cond)
        with w.block():
            doubled = fluid.layers.elementwise_add(x, x)
            fluid.layers.assign(doubled, output=x)
            fluid.layers.increment(i, value=1, in_place=True)
            fluid.layers.less_than(i, n, cond=cond)
    return main, startup, x, i


class TestWhileCompile:
    def test_compiles_and_matches_interpreter(self):
        main, startup, x, i = _build_while_program()
        exe = fluid.Executor(fluid.CPUPlace())
        assert exe._can_whole_compile(main), \
            "while program must be traceable"
        feed = {"x0": np.array([1.0, 2.0, 3.0, 4.0], dtype="float32")}

        scope1 = fluid.Scope()
        with fluid.scope_guard(scope1):
            exe.run(startup)
            from paddle_tpu.core.compiler_engine import run_compiled_program

            out_c, i_c = run_compiled_program(exe._core, main, scope1, feed,
                                              [x, i])
        scope2 = fluid.Scope()
        with fluid.scope_guard(scope2):
            exe.run(startup)
            core = CoreExecutor(fluid.CPUPlace())
            out_i, i_i = core.run_program(main, scope2, feed, [x, i], True)

        np.testing.assert_allclose(np.asarray(out_c),
                                   feed["x0"] * 32.0, rtol=1e-6)
        np.testing.assert_allclose(np.asarray(out_c), np.asarray(out_i),
                                   rtol=1e-6)
        assert int(np.asarray(i_c).ravel()[0]) == 5
        assert int(np.asarray(i_i).ravel()[0]) == 5

    def test_executor_routes_through_compiler(self):
        main, startup, x, i = _build_while_program()
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            (out,) = exe.run(main, feed={
                "x0": np.ones(4, dtype="float32")}, fetch_list=[x])
        np.testing.assert_allclose(np.asarray(out), np.full(4, 32.0),
                                   rtol=1e-6)


class TestConditionalBlockCompile:
    def _build(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.data(name="x", shape=[4], dtype="float32")
            flag = fluid.data(name="flag", shape=[1], dtype="bool")
            y = fluid.layers.assign(x)
            blk = main.current_block()
            sub = main._create_block()
            # sub-block body: y = y * 3 (writes the parent var by name)
            tripled = fluid.layers.scale(y, scale=3.0)
            fluid.layers.assign(tripled, output=y)
            main._rollback()
            blk.append_op(
                "conditional_block",
                inputs={"Cond": [flag]},
                outputs={},
                attrs={"sub_block": sub, "is_scalar_condition": True},
            )
        return main, startup, y

    def test_both_branches(self):
        main, startup, y = self._build()
        exe = fluid.Executor(fluid.CPUPlace())
        assert exe._can_whole_compile(main)
        for flag, want in [(True, 3.0), (False, 1.0)]:
            scope = fluid.Scope()
            with fluid.scope_guard(scope):
                exe.run(startup)
                (out,) = exe.run(main, feed={
                    "x": np.ones(4, dtype="float32"),
                    "flag": np.array([flag])}, fetch_list=[y])
            np.testing.assert_allclose(np.asarray(out), np.full(4, want),
                                       rtol=1e-6)
