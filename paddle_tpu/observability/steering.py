"""The ``profile report → plan`` interface (ROADMAP: one registry any
subsystem can steer from).

A *steerer* is a named callable ``fn(report, **context) -> plan`` that
turns a saved step-profile report (``profiler.profile_step`` output, or
a bench record wrapping one) into a subsystem-specific plan. The PR-10
profile-guided bucket planner was the first instance; the placement
search (``paddle_tpu/placement``) is the second. Future consumers —
the serving bucket ladder, lazy dygraph's recompile policy, the PS
hot-shard migrator — register here instead of growing private report
plumbing.

Contract:

- ``register_steerer(name, fn)`` — idempotent per name (re-registering
  replaces; modules that register at import stay reload-safe);
- ``steer(name, report, **context)`` — dispatch, with a
  ``steering.plans{steerer=}`` counter per invocation;
- ``load_report(path)`` — the ONE report loader every steerer shares:
  accepts a raw ``profile_step`` dict, a bench record (unwraps its
  ``profile`` block), or a path/env naming either; returns None (never
  raises) on missing/garbage/field-incomplete documents so a deleted
  report can never break a training step.
"""
from __future__ import annotations

import hashlib
import json
import os
import threading
from typing import Callable, Dict, List, Optional

__all__ = ["register_steerer", "get_steerer", "steerers", "steer",
           "load_report", "REPORT_FIELDS", "plan_digest",
           "plan_jsonable"]

# the measured fields every steerer keys on: per-collective cost points
# (the cost-model fit) and the backward compute timeline (the hide
# budget). A document missing either is not a usable report.
REPORT_FIELDS = ("per_bucket", "backward_segments")

_lock = threading.Lock()
_STEERERS: Dict[str, Callable] = {}


def register_steerer(name: str, fn: Callable,
                     description: str = "") -> Callable:
    """Register ``fn(report, **context) -> plan`` under ``name``.
    Re-registration replaces (import-reload safe). Returns ``fn`` so it
    can be used as a decorator tail."""
    if not name or not callable(fn):
        raise ValueError("steerer needs a name and a callable")
    with _lock:
        _STEERERS[name] = fn
        if description:
            fn.__steering_doc__ = description
    return fn


def get_steerer(name: str) -> Optional[Callable]:
    with _lock:
        return _STEERERS.get(name)


def steerers() -> List[str]:
    with _lock:
        return sorted(_STEERERS)


def steer(name: str, report, **context):
    """Dispatch ``report`` to the named steerer. Raises ``KeyError``
    for an unknown steerer (a typo should fail loudly, unlike a
    missing report)."""
    fn = get_steerer(name)
    if fn is None:
        raise KeyError("no steerer registered under %r (have: %s)"
                       % (name, ", ".join(steerers()) or "none"))
    from . import inc as _inc

    _inc("steering.plans", steerer=name)
    return fn(report, **context)


def load_report(path: Optional[str] = None,
                env: str = "PADDLE_TPU_BUCKET_PROFILE",
                required_fields=REPORT_FIELDS) -> Optional[Dict]:
    """Load a step-profile report from ``path`` (or the env var when
    path is None/empty). Unwraps a bench record's ``profile`` block.
    Returns None — never raises — when the path is unset, unreadable,
    not JSON, or missing any of ``required_fields``."""
    if path is None:
        path = os.environ.get(env, "").strip()
    if not path:
        return None
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    return coerce_report(doc, required_fields=required_fields)


def plan_jsonable(plan):
    """A JSON-serializable view of any plan a steerer can return: a
    ``PlacementPlan``-style object (``to_doc()``), a plain container,
    or a tuple ladder. The canonical form the daemon writes into a
    proposal artifact and the digest hashes."""
    if hasattr(plan, "to_doc"):
        return plan.to_doc()
    if hasattr(plan, "to_dict"):
        return plan.to_dict()
    if isinstance(plan, tuple):
        return list(plan)
    return plan


def plan_digest(plan) -> str:
    """Stable content digest of a plan — the identity every steering
    decision is audited under. Plans that carry their own digest
    (``PlacementPlan.digest``) keep it; anything else hashes its
    canonical JSON form."""
    d = getattr(plan, "digest", None)
    if isinstance(d, str) and d:
        return d
    body = json.dumps(plan_jsonable(plan), sort_keys=True,
                      separators=(",", ":"), default=str)
    return hashlib.sha1(body.encode()).hexdigest()


def coerce_report(doc, required_fields=REPORT_FIELDS) -> Optional[Dict]:
    """The in-memory half of ``load_report``: unwrap + field-check an
    already-parsed document (a plan artifact embeds its source report
    inline — same validation, no file)."""
    if not isinstance(doc, dict):
        return None
    if isinstance(doc.get("profile"), dict):
        doc = doc["profile"]
    for field in required_fields:
        if not isinstance(doc.get(field), list):
            return None
    return doc
