"""Serving subsystem (paddle_tpu/serving): bucket ladder, dynamic
batcher assembly/padding, engine admission control + deadlines + warmup
+ drain, HTTP front end, and the thread-safety contract the engine
demands of a shared PaddlePredictor.

The compile-boundedness property (jit cache == bucket ladder, not
observed batch sizes) is asserted here on a real model AND in CI gate 5
via tools/serving_bench.py --smoke.
"""
import json
import tempfile
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import observability as obs
from paddle_tpu import serving
from paddle_tpu.serving.batcher import (BatchPolicy, DynamicBatcher,
                                        PendingRequest, default_ladder,
                                        pick_bucket)


@pytest.fixture(autouse=True)
def _clean_registry():
    """Serving metrics are always-on; isolate counters per test and
    leave the layer disabled (other files assume default-off)."""
    obs.reset()
    obs.enable()
    yield
    obs.reset()
    obs.disable()


# -- bucket ladder ---------------------------------------------------------

def test_default_ladder_powers_of_two_plus_max():
    assert default_ladder(1) == (1,)
    assert default_ladder(8) == (1, 2, 4, 8)
    assert default_ladder(12) == (1, 2, 4, 8, 12)


def test_pick_bucket_smallest_fit():
    ladder = (1, 2, 4, 8)
    assert [pick_bucket(ladder, r) for r in (1, 2, 3, 5, 8)] == \
        [1, 2, 4, 8, 8]
    with pytest.raises(ValueError):
        pick_bucket(ladder, 9)


def test_policy_validation():
    with pytest.raises(ValueError):
        BatchPolicy(max_batch_size=0)
    with pytest.raises(ValueError):
        BatchPolicy(max_batch_size=8, ladder=(1, 2, 4))  # max unreachable
    with pytest.raises(ValueError, match="exceeds max_batch_size"):
        # a gap below an oversized entry would pad every batch past
        # the documented per-dispatch cap
        BatchPolicy(max_batch_size=8, ladder=(1, 16))
    p = BatchPolicy(max_batch_size=6, ladder=(4, 1, 2, 6, 2))
    assert p.ladder == (1, 2, 4, 6)


# -- batcher assembly ------------------------------------------------------

def _pending(rows, dim=3, fill=1.0):
    return PendingRequest({"x": np.full((rows, dim), fill, "float32")},
                          rows)


def test_assemble_pads_to_bucket_and_splits_back():
    b = DynamicBatcher(BatchPolicy(max_batch_size=8))
    batch = [_pending(1, fill=1.0), _pending(2, fill=2.0)]
    feed, slices, bucket, pad = b.assemble(batch)
    assert bucket == 4 and pad == 1
    assert feed["x"].shape == (4, 3)
    # padding rows are zeros, real rows in request order
    np.testing.assert_array_equal(feed["x"][0], np.ones(3))
    np.testing.assert_array_equal(feed["x"][3], np.zeros(3))
    outs = DynamicBatcher.split_outputs({"y": feed["x"] * 10}, slices,
                                        bucket)
    assert [o["y"].shape[0] for o in outs] == [1, 2]
    np.testing.assert_array_equal(outs[1]["y"],
                                  np.full((2, 3), 20, "float32"))


def test_split_outputs_refuses_non_batch_major():
    """A scalar / per-batch aggregate fetch cannot be attributed to
    requests; slicing it silently would hand back wrong data."""
    slices = [(0, 1), (1, 2)]
    with pytest.raises(ValueError, match="not batch-major"):
        DynamicBatcher.split_outputs({"m": np.float32(0.5)}, slices, 4)
    with pytest.raises(ValueError, match="not batch-major"):
        DynamicBatcher.split_outputs({"agg": np.zeros(2)}, slices, 4)


def test_assemble_exact_bucket_no_padding():
    b = DynamicBatcher(BatchPolicy(max_batch_size=8))
    feed, slices, bucket, pad = b.assemble([_pending(2), _pending(2)])
    assert bucket == 4 and pad == 0


def test_try_put_refuses_unschedulable_request():
    """An oversized request admitted to the queue could never be
    popped — it would pin the head and spin consumers forever."""
    b = DynamicBatcher(BatchPolicy(max_batch_size=4))
    with pytest.raises(ValueError, match="exceed max_batch_size"):
        b.try_put(_pending(5))


def test_try_put_bounds_queue():
    b = DynamicBatcher(BatchPolicy(max_batch_size=4), max_queue=2)
    assert b.try_put(_pending(1))
    assert b.try_put(_pending(1))
    assert not b.try_put(_pending(1))
    assert b.depth() == 2
    b.close()
    assert not b.try_put(_pending(1))


def test_next_batch_respects_row_cap():
    b = DynamicBatcher(BatchPolicy(max_batch_size=4, batch_timeout_ms=0))
    for rows in (2, 2, 3):
        b.try_put(_pending(rows))
    first = b.next_batch(0.1)
    assert sum(p.rows for p in first) == 4  # 2+2; the 3-row stays queued
    second = b.next_batch(0.1)
    assert [p.rows for p in second] == [3]


def test_next_batch_idle_poll_returns_none():
    b = DynamicBatcher(BatchPolicy(max_batch_size=4))
    assert b.next_batch(0.01) is None


# -- engine over a stub predictor -----------------------------------------

class _StubTensor:
    def __init__(self, name, data):
        self.name, self.data = name, data


class _StubPredictor:
    """PaddlePredictor surface; y = 2x. `delay` throttles dispatch so
    backpressure/deadline tests are deterministic."""

    def __init__(self, delay=0.0):
        self.delay = delay
        self.calls = []

    def get_input_names(self):
        return ["x"]

    def get_output_names(self):
        return ["y"]

    def run(self, feed):
        if self.delay:
            time.sleep(self.delay)
        x = np.asarray(feed["x"])
        self.calls.append(x.shape[0])
        return [_StubTensor("y", x * 2.0)]


def _stub_engine(delay=0.0, **cfg):
    cfg.setdefault("max_batch_size", 4)
    cfg.setdefault("num_workers", 1)
    cfg.setdefault("warmup", False)
    return serving.ServingEngine(_StubPredictor(delay),
                                 serving.ServingConfig(**cfg))


def test_engine_predict_roundtrip_and_unpadding():
    with _stub_engine() as eng:
        x = np.arange(6, dtype="float32").reshape(2, 3)
        out = eng.predict({"x": x}, timeout=10)
        np.testing.assert_array_equal(out["y"], x * 2)


def test_engine_feed_validation():
    with _stub_engine() as eng:
        with pytest.raises(ValueError, match="mismatch"):
            eng.submit({"wrong": np.ones((1, 3), "f4")})
        with pytest.raises(ValueError, match="batch axis"):
            eng.submit({"x": np.float32(3.0)})
        with pytest.raises(serving.RequestTooLarge):
            eng.submit({"x": np.ones((5, 3), "f4")})
        with pytest.raises(ValueError, match="no rows"):
            eng.submit({"x": np.ones((0, 3), "f4")})


def test_engine_rejects_wrong_row_shape_at_submit():
    """One malformed request must get ITS OWN 400-class error at
    submit — not poison co-batched valid requests at concatenate."""
    eng = serving.ServingEngine(
        _StubPredictor(), serving.ServingConfig(max_batch_size=4,
                                                num_workers=1),
        sample_feed={"x": np.zeros((1, 3), "float32")}).start()
    with pytest.raises(ValueError, match="rows have shape"):
        eng.submit({"x": np.ones((1, 5), "float32")})
    out = eng.predict({"x": np.ones((1, 3), "float32")}, timeout=10)
    assert out["y"].shape == (1, 3)
    eng.stop()


def test_engine_coerces_feed_dtype_to_model_dtype():
    """Integer JSON payloads arrive int64; without coercion every
    off-dtype request is a novel jit signature past the bucket
    ladder."""
    stub = _StubPredictor()
    seen = []
    real_run = stub.run
    stub.run = lambda feed: (seen.append(np.asarray(feed["x"]).dtype),
                             real_run(feed))[1]
    eng = serving.ServingEngine(
        stub, serving.ServingConfig(max_batch_size=4, num_workers=1,
                                    warmup=False),
        sample_feed={"x": np.zeros((1, 3), "float32")}).start()
    out = eng.predict({"x": np.ones((2, 3), "int64")}, timeout=10)
    eng.stop()
    assert all(dt == np.float32 for dt in seen), seen
    assert out["y"].dtype == np.float32


def test_stop_never_strands_futures():
    """Every queued future resolves at stop — drain timeout and
    no-drain abort both fail leftovers with EngineStopped instead of
    hanging their callers forever."""
    # no-drain: queued work is failed, not dispatched
    eng = _stub_engine(delay=0.05, max_queue=32).start()
    futures = [eng.submit({"x": np.ones((1, 3), "f4")})
               for _ in range(10)]
    eng.stop(drain=False, timeout=5)
    for f in futures:
        assert f.done()
        try:
            f.result(0)
        except serving.EngineStopped:
            pass
    # drain with a timeout too short to finish: every future still
    # resolves in bounded time — dispatched by an in-flight worker or
    # failed by stop()'s leftover flush; none hang forever
    eng2 = _stub_engine(delay=0.1, max_queue=32).start()
    futures2 = [eng2.submit({"x": np.ones((1, 3), "f4")})
                for _ in range(6)]
    eng2.stop(drain=True, timeout=0.15)
    for f in futures2:
        try:
            f.result(5)
        except serving.EngineStopped:
            pass


def test_engine_warmup_uses_sample_feed_and_covers_ladder():
    stub = _StubPredictor()
    eng = serving.ServingEngine(
        stub, serving.ServingConfig(max_batch_size=4, num_workers=1),
        sample_feed={"x": np.zeros((1, 3), "float32")}).start()
    assert eng.warmed_buckets == (1, 2, 4)
    assert stub.calls == [1, 2, 4]
    eng.stop()


def test_engine_backpressure_rejects_and_counts():
    eng = _stub_engine(delay=0.03, max_queue=2).start()
    rejected, futures = 0, []
    for _ in range(12):
        try:
            futures.append(eng.submit({"x": np.ones((1, 3), "f4")}))
        except serving.ServerOverloaded:
            rejected += 1
    for f in futures:
        assert f.result(10)["y"].shape == (1, 3)
    eng.stop()
    assert rejected > 0
    assert obs.counter_value("serving.rejected") == rejected
    assert obs.counter_value("serving.requests") == len(futures)


def test_engine_deadline_dropped_before_dispatch():
    stub = _StubPredictor(delay=0.08)
    eng = serving.ServingEngine(
        stub, serving.ServingConfig(max_batch_size=1, num_workers=1,
                                    warmup=False)).start()
    f1 = eng.submit({"x": np.ones((1, 3), "f4")})      # occupies worker
    f2 = eng.submit({"x": np.ones((1, 3), "f4")}, deadline_ms=1)
    with pytest.raises(serving.DeadlineExpired):
        f2.result(10)
    f1.result(10)
    eng.stop()
    assert obs.counter_value("serving.deadline_expired") == 1
    # the expired request never reached the predictor
    assert len(stub.calls) == 1


def test_engine_drain_completes_queued_work():
    eng = _stub_engine(delay=0.01, max_queue=32).start()
    futures = [eng.submit({"x": np.ones((1, 3), "f4")}) for _ in range(8)]
    eng.stop(drain=True)
    assert all(f.result(0)["y"].shape == (1, 3) for f in futures)
    with pytest.raises(serving.EngineStopped):
        eng.submit({"x": np.ones((1, 3), "f4")})


def test_engine_submit_before_start_refused():
    eng = _stub_engine()
    with pytest.raises(serving.EngineStopped):
        eng.submit({"x": np.ones((1, 3), "f4")})


def test_submit_racing_stop_maps_to_engine_stopped_not_overload():
    """A submit that passes the _stopping check just before stop()
    closes the batcher must surface EngineStopped — not count a
    shutdown as an admission-control rejection."""
    eng = _stub_engine().start()
    orig = eng._batcher.try_put

    def racing_put(p):
        eng._stopping = True       # stop() lands mid-submit
        eng._batcher.close()
        return orig(p)

    eng._batcher.try_put = racing_put
    before = obs.counter_value("serving.rejected")
    with pytest.raises(serving.EngineStopped):
        eng.submit({"x": np.ones((1, 3), "f4")})
    assert obs.counter_value("serving.rejected") == before


def test_engine_restart_raises_not_a_dead_engine():
    eng = _stub_engine().start()
    eng.stop()
    with pytest.raises(serving.EngineStopped, match="restarted"):
        eng.start()


def test_engine_aggregate_output_fails_request_loudly():
    class AggStub(_StubPredictor):
        def run(self, feed):
            x = np.asarray(feed["x"])
            return [_StubTensor("mean", x.mean())]  # scalar, no batch axis

    eng = serving.ServingEngine(
        AggStub(), serving.ServingConfig(max_batch_size=4, num_workers=1,
                                         warmup=False)).start()
    f = eng.submit({"x": np.ones((1, 3), "f4")})
    with pytest.raises(ValueError, match="not batch-major"):
        f.result(10)
    eng.stop()


def test_engine_model_error_fails_batch_not_process():
    class Boom(_StubPredictor):
        def run(self, feed):
            raise RuntimeError("kaboom")

    eng = serving.ServingEngine(
        Boom(), serving.ServingConfig(max_batch_size=4, num_workers=1,
                                      warmup=False)).start()
    f = eng.submit({"x": np.ones((1, 3), "f4")})
    with pytest.raises(RuntimeError, match="kaboom"):
        f.result(10)
    assert obs.counter_value("serving.errors") == 1
    eng.stop()


def test_dispatch_assembly_failure_resolves_futures():
    """A shape-mismatched pair landing in one batch must fail THOSE
    futures (never strand them / kill the worker thread)."""
    eng = _stub_engine()  # not started: _dispatch runs synchronously
    p1 = PendingRequest({"x": np.ones((1, 3), "float32")}, 1)
    p2 = PendingRequest({"x": np.ones((1, 5), "float32")}, 1)
    eng._dispatch([p1, p2])
    for p in (p1, p2):
        with pytest.raises(ValueError):
            p.future.result(0)
    assert obs.counter_value("serving.errors") == 2


def test_batching_actually_batches_concurrent_requests():
    """8 concurrent 1-row requests through a throttled predictor must
    dispatch in fewer than 8 batches (the collection window merges
    them) and each caller still gets its own rows back."""
    stub = _StubPredictor(delay=0.01)
    eng = serving.ServingEngine(
        stub, serving.ServingConfig(max_batch_size=8, batch_timeout_ms=20,
                                    num_workers=1, warmup=False)).start()
    results = {}

    def client(i):
        x = np.full((1, 3), float(i), "float32")
        results[i] = eng.predict({"x": x}, timeout=10)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    eng.stop()
    for i in range(8):
        np.testing.assert_array_equal(results[i]["y"],
                                      np.full((1, 3), 2.0 * i))
    assert len(stub.calls) < 8
    assert obs.counter_value("serving.batches") == len(stub.calls)


# -- real predictor: compile boundedness + shared-predictor safety ---------

def _build_predictor(tmpdir, dim=6, classes=3):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data(name="x", shape=[-1, dim], dtype="float32")
        pred = fluid.layers.fc(fluid.layers.fc(x, 8, act="relu"),
                               classes, act="softmax")
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        fluid.io.save_inference_model(tmpdir, ["x"], [pred], exe,
                                      main_program=main)
    from paddle_tpu.inference import AnalysisConfig, create_paddle_predictor

    config = AnalysisConfig(tmpdir)
    config.disable_gpu()
    return create_paddle_predictor(config), pred.name


def test_bucketed_serving_bounds_jit_compiles():
    """The tentpole property: ragged concurrent traffic compiles one
    XLA program per LADDER BUCKET, not per observed batch size."""
    with tempfile.TemporaryDirectory() as d:
        predictor, out_name = _build_predictor(d)
        traces0 = obs.counter_value("executor.jit_traces")
        eng = serving.ServingEngine(
            predictor, serving.ServingConfig(max_batch_size=4,
                                             batch_timeout_ms=1.0,
                                             num_workers=2)).start()
        warm = obs.counter_value("executor.jit_traces") - traces0
        assert warm == len(eng.config.policy.ladder) == 3

        errors = []

        def client(i):
            try:
                x = np.full((1 + i % 3, 6), 0.1 * i, "float32")
                out = eng.predict({"x": x}, timeout=60)
                assert out[out_name].shape == (1 + i % 3, 3)
                # softmax rows must be real rows, not padding
                np.testing.assert_allclose(out[out_name].sum(axis=1),
                                           1.0, rtol=1e-4)
            except Exception as e:  # noqa: BLE001
                errors.append(repr(e))

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(24)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        eng.stop()
        assert not errors, errors[:3]
        assert obs.counter_value("executor.jit_traces") - traces0 == warm


def test_shared_predictor_concurrent_run_is_safe():
    """Satellite: one predictor, 8 threads calling run() directly —
    the run lock must keep results request-correct."""
    with tempfile.TemporaryDirectory() as d:
        predictor, out_name = _build_predictor(d)
        refs = {}
        for i in range(8):
            x = np.full((2, 6), float(i), "float32")
            refs[i] = predictor.run({"x": x})[0].data
        errors = []

        def worker(i):
            x = np.full((2, 6), float(i), "float32")
            out = predictor.run({"x": x})[0].data
            if not np.allclose(out, refs[i], rtol=1e-5):
                errors.append(i)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, "cross-request clobbering on threads %s" % errors


def test_concurrent_predictor_construction_isolated():
    """Regression: construction pushes onto the process-global
    scope_guard stack; without the construction lock, two threads
    building predictors cross-load params into each other's scope."""
    from paddle_tpu.inference import AnalysisConfig, create_paddle_predictor

    with tempfile.TemporaryDirectory() as d1, \
            tempfile.TemporaryDirectory() as d2:
        p_ref1, _ = _build_predictor(d1, dim=6, classes=3)
        p_ref2, _ = _build_predictor(d2, dim=4, classes=2)
        x1 = np.full((2, 6), 0.3, "float32")
        x2 = np.full((2, 4), -0.3, "float32")
        ref1 = p_ref1.run({"x": x1})[0].data
        ref2 = p_ref2.run({"x": x2})[0].data
        errors = []

        def construct_and_check(d, x, ref):
            try:
                cfg = AnalysisConfig(d)
                cfg.disable_gpu()
                p = create_paddle_predictor(cfg)
                out = p.run({"x": x})[0].data
                if not np.allclose(out, ref, rtol=1e-5):
                    errors.append("wrong outputs from %s" % d)
            except Exception as e:  # noqa: BLE001
                errors.append(repr(e))

        threads = [threading.Thread(target=construct_and_check, args=a)
                   for a in ((d1, x1, ref1), (d2, x2, ref2)) * 2]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors[:3]


def test_two_predictors_concurrent_runs_use_own_scopes():
    """Regression: run() must pass its scope explicitly — the
    scope_guard stack is process-global, so two predictors on two
    threads used to resolve each other's scope mid-run."""
    with tempfile.TemporaryDirectory() as d1, \
            tempfile.TemporaryDirectory() as d2:
        p1, out1 = _build_predictor(d1, dim=6, classes=3)
        p2, out2 = _build_predictor(d2, dim=4, classes=2)
        x1 = np.full((2, 6), 0.5, "float32")
        x2 = np.full((2, 4), -0.5, "float32")
        ref1 = p1.run({"x": x1})[0].data
        ref2 = p2.run({"x": x2})[0].data
        errors = []

        def hammer(p, x, ref):
            for _ in range(10):
                out = p.run({"x": x})[0].data
                if not np.allclose(out, ref, rtol=1e-5):
                    errors.append(out.shape)

        threads = [threading.Thread(target=hammer, args=a)
                   for a in ((p1, x1, ref1), (p2, x2, ref2)) * 2]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors[:3]


def test_concurrent_zero_copy_callers_are_isolated():
    """Regression: staging is per-thread, so N zero-copy callers on one
    predictor each get THEIR OWN results (the shared-dict version made
    every caller read the last-staged input)."""
    with tempfile.TemporaryDirectory() as d:
        predictor, _ = _build_predictor(d)
        out_name = predictor.get_output_names()[0]
        barrier = threading.Barrier(4)
        errors = []

        def caller(i):
            x = np.full((2, 6), float(i), "float32")
            ref = predictor.run({"x": x})[0].data
            predictor.get_input_tensor("x").copy_from_cpu(x)
            barrier.wait()  # everyone staged before anyone runs
            predictor.zero_copy_run()
            out = predictor.get_output_tensor(out_name).copy_to_cpu()
            if not np.allclose(np.asarray(out), ref, rtol=1e-5):
                errors.append(i)

        threads = [threading.Thread(target=caller, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, "caller(s) %s read another caller's results" \
            % errors


def test_zero_copy_state_initialized_and_locked():
    """Satellite: _staged/_results exist from __init__ (no lazy
    hasattr materialization) and the run path holds a lock."""
    with tempfile.TemporaryDirectory() as d:
        predictor, _ = _build_predictor(d)
        assert predictor._staged == {}
        assert predictor._results == {}
        assert predictor._run_lock is not None
        inp = predictor.get_input_tensor("x")
        inp.copy_from_cpu(np.ones((2, 6), "float32"))
        predictor.zero_copy_run()
        out = predictor.get_output_tensor(
            predictor.get_output_names()[0]).copy_to_cpu()
        assert np.asarray(out).shape == (2, 3)


# -- HTTP front end --------------------------------------------------------

@pytest.fixture()
def http_server():
    eng = serving.ServingEngine(
        _StubPredictor(), serving.ServingConfig(max_batch_size=4,
                                                num_workers=1),
        sample_feed={"x": np.zeros((1, 3), "float32")}).start()
    server, thread = serving.start_http_server(eng)
    host, port = server.server_address
    yield eng, "http://%s:%d" % (host, port)
    server.shutdown()
    eng.stop()


def _post(url, payload):
    req = urllib.request.Request(url, json.dumps(payload).encode(),
                                 {"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=10) as r:
        return r.status, json.loads(r.read())


def test_http_predict_and_healthz(http_server):
    eng, base = http_server
    with urllib.request.urlopen(base + "/healthz", timeout=10) as r:
        assert r.status == 200
    status, body = _post(base + "/predict",
                         {"inputs": {"x": [[1, 2, 3], [4, 5, 6]]}})
    assert status == 200
    np.testing.assert_array_equal(np.asarray(body["outputs"]["y"]),
                                  [[2, 4, 6], [8, 10, 12]])
    assert body["latency_ms"] > 0


def test_http_metrics_prometheus_text(http_server):
    eng, base = http_server
    _post(base + "/predict", {"inputs": {"x": [[1, 2, 3]]}})
    with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
        text = r.read().decode()
    assert "# TYPE paddle_tpu_serving_requests counter" in text
    assert "paddle_tpu_serving_batch_size" in text


def test_http_error_mapping(http_server):
    eng, base = http_server
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(base + "/predict", {"not_inputs": 1})
    assert ei.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(base + "/predict", {"inputs": {"x": [[1, 2, 3]]},
                                  "deadline_ms": "soon"})
    assert ei.value.code == 400  # client input error, not a 500
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(base + "/nowhere", {})
    assert ei.value.code == 404


def test_http_healthz_unhealthy_after_stop(http_server):
    eng, base = http_server
    eng.stop()
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(base + "/healthz", timeout=10)
    assert ei.value.code == 503
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(base + "/predict", {"inputs": {"x": [[1, 2, 3]]}})
    assert ei.value.code == 503


# -- lifecycle (machine-readable /healthz contract) ------------------------

def test_engine_lifecycle_states():
    """starting -> warming (observed from inside the warmup dispatch)
    -> serving -> draining -> stopped."""
    states = []

    class Watching(_StubPredictor):
        def __init__(self, engine_ref):
            super().__init__()
            self.engine_ref = engine_ref

        def run(self, feed):
            if self.engine_ref:  # warmup runs inside start()
                states.append(self.engine_ref[0].health())
            return super().run(feed)

    ref = []
    stub = Watching(ref)
    eng = serving.ServingEngine(
        stub, serving.ServingConfig(max_batch_size=2, num_workers=1,
                                    warmup=True),
        sample_feed={"x": np.zeros((1, 3), "float32")})
    ref.append(eng)
    assert eng.health() == "starting"
    eng.start()
    assert states and all(s == "warming" for s in states), states
    assert eng.health() == "serving"
    assert eng.stats()["state"] == "serving"
    eng.stop()
    assert eng.health() == "stopped"


def test_http_healthz_body_is_machine_readable(http_server):
    eng, base = http_server
    with urllib.request.urlopen(base + "/healthz", timeout=10) as r:
        assert json.loads(r.read())["status"] == "serving"
    eng.stop()
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(base + "/healthz", timeout=10)
    assert json.loads(ei.value.read())["status"] in ("draining",
                                                     "stopped")


def test_http_deadline_expired_504_typed_body():
    """Satellite 1: a queued-expired request surfaces as 504 with a
    machine-readable type, never a silent drop."""
    eng = serving.ServingEngine(
        _StubPredictor(delay=0.2),
        serving.ServingConfig(max_batch_size=1, num_workers=1,
                              warmup=False),
        sample_feed={"x": np.zeros((1, 3), "float32")}).start()
    server, _ = serving.start_http_server(eng)
    base = "http://%s:%d" % server.server_address
    try:
        occupier = eng.submit({"x": np.ones((1, 3), "f4")})
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(base + "/predict", {"inputs": {"x": [[1, 2, 3]]},
                                      "deadline_ms": 20})
        assert ei.value.code == 504
        assert json.loads(ei.value.read())["type"] == "DeadlineExpired"
        occupier.result(10)
    finally:
        server.shutdown()
        server.server_close()
        eng.stop()


# -- idempotent request ids -------------------------------------------------

def test_engine_request_id_idempotent_submit():
    stub = _StubPredictor()
    eng = serving.ServingEngine(
        stub, serving.ServingConfig(max_batch_size=4, num_workers=1,
                                    warmup=False),
        sample_feed={"x": np.zeros((1, 3), "float32")}).start()
    try:
        x = np.ones((1, 3), "float32")
        f1 = eng.submit({"x": x}, request_id="a")
        f2 = eng.submit({"x": x}, request_id="a")
        assert f1 is f2
        f1.result(10)
        # completed ids stay joinable (bounded LRU) — a late duplicate
        # delivery must not re-run the predictor
        f3 = eng.submit({"x": x}, request_id="a")
        assert f3 is f1
        assert len(stub.calls) == 1
        assert obs.counter_value("serving.requests") == 1
        assert obs.counter_value("serving.dedup_hits") == 2
        # distinct ids are distinct requests
        f4 = eng.submit({"x": x}, request_id="b")
        assert f4 is not f1
        f4.result(10)
    finally:
        eng.stop()


def test_engine_request_id_cache_bounded():
    eng = serving.ServingEngine(
        _StubPredictor(),
        serving.ServingConfig(max_batch_size=4, num_workers=1,
                              warmup=False, request_id_cache=4),
        sample_feed={"x": np.zeros((1, 3), "float32")}).start()
    try:
        x = np.ones((1, 3), "float32")
        futures = [eng.submit({"x": x}, request_id="id-%d" % i)
                   for i in range(10)]
        for f in futures:
            f.result(10)
        assert len(eng._ids) <= 4
        # an evicted id re-executes (the window is a cache, not a log)
        f = eng.submit({"x": x}, request_id="id-0")
        assert f is not futures[0]
        f.result(10)
    finally:
        eng.stop()


def test_http_request_id_header_joins_duplicate():
    stub = _StubPredictor()
    eng = serving.ServingEngine(
        stub, serving.ServingConfig(max_batch_size=4, num_workers=1,
                                    warmup=False),
        sample_feed={"x": np.zeros((1, 3), "float32")}).start()
    server, _ = serving.start_http_server(eng)
    base = "http://%s:%d" % server.server_address
    try:
        def post_with_id(rid):
            req = urllib.request.Request(
                base + "/predict",
                json.dumps({"inputs": {"x": [[1, 2, 3]]}}).encode(),
                {"Content-Type": "application/json",
                 "X-Request-Id": rid})
            with urllib.request.urlopen(req, timeout=10) as r:
                return json.loads(r.read())

        b1 = post_with_id("dup-1")
        b2 = post_with_id("dup-1")      # duplicate delivery
        assert b1["outputs"] == b2["outputs"]
        assert len(stub.calls) == 1     # executed once
        assert obs.counter_value("serving.dedup_hits") == 1
    finally:
        server.shutdown()
        server.server_close()
        eng.stop()


# -- batcher edge cases under faults (satellite 3) --------------------------

def test_requests_racing_drain_never_strand():
    """Submitters racing stop(drain=True): every future resolves in
    bounded time — served, or failed with EngineStopped — and the jit
    ladder property holds for whatever was served."""
    eng = _stub_engine(delay=0.01, max_queue=64, num_workers=2).start()
    outcomes = []
    lock = threading.Lock()

    def submitter():
        for _ in range(20):
            try:
                f = eng.submit({"x": np.ones((1, 3), "f4")})
            except serving.EngineStopped:
                with lock:
                    outcomes.append("refused")
                return
            try:
                f.result(15)
                with lock:
                    outcomes.append("served")
            except serving.EngineStopped:
                with lock:
                    outcomes.append("stopped")

    threads = [threading.Thread(target=submitter) for _ in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.05)
    eng.stop(drain=True, timeout=20)
    for t in threads:
        t.join(30)
        assert not t.is_alive(), "a submitter hung across drain"
    assert outcomes.count("served") > 0
    assert obs.counter_value("serving.errors") == 0


def test_zero_timeout_batches_serve_correctly():
    """batch_timeout_ms=0: dispatch whatever is queued the moment a
    worker frees — every request still gets its own correct rows."""
    stub = _StubPredictor(delay=0.005)
    eng = serving.ServingEngine(
        stub, serving.ServingConfig(max_batch_size=8,
                                    batch_timeout_ms=0,
                                    num_workers=1, warmup=False),
        sample_feed={"x": np.zeros((1, 3), "float32")}).start()
    try:
        results = {}

        def client(i):
            x = np.full((1, 3), float(i), "float32")
            results[i] = eng.predict({"x": x}, timeout=10)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(12)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i in range(12):
            np.testing.assert_array_equal(results[i]["y"],
                                          np.full((1, 3), 2.0 * i))
        assert obs.counter_value("serving.batches") >= 1
    finally:
        eng.stop()


def test_predictor_raising_mid_batch_fails_co_batched_survivors_typed():
    """A poison request co-batched with innocents: the batch fails as
    a unit with the typed BatchExecutionError for EVERY member (the
    innocents were in the same dispatch — they cannot have partial
    results), the engine survives, and the next batch is clean."""

    class Poison(_StubPredictor):
        def run(self, feed):
            x = np.asarray(feed["x"])
            if (x == 666.0).any():
                raise RuntimeError("mid-batch NaN")
            return super().run(feed)

    eng = serving.ServingEngine(
        Poison(), serving.ServingConfig(max_batch_size=8,
                                        batch_timeout_ms=50,
                                        num_workers=1, warmup=False),
        sample_feed={"x": np.zeros((1, 3), "float32")}).start()
    try:
        # the window is long (50ms): both requests land in ONE batch
        poison = eng.submit({"x": np.full((1, 3), 666.0, "f4")})
        innocent = eng.submit({"x": np.ones((1, 3), "f4")})
        for f in (poison, innocent):
            with pytest.raises(serving.engine.BatchExecutionError,
                               match="mid-batch NaN"):
                f.result(10)
        assert obs.counter_value("serving.batch_errors") == 1
        assert obs.counter_value("serving.errors") == 2
        # the worker thread survived: a clean request serves normally
        out = eng.predict({"x": np.ones((1, 3), "f4")}, timeout=10)
        np.testing.assert_array_equal(out["y"],
                                      np.full((1, 3), 2.0))
        assert eng.health() == "serving"
    finally:
        eng.stop()
