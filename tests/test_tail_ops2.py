"""Second tail wave: conv transposes, sequence conv/scatter,
SelectedRows utilities, lstmp."""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu.core.tensor import LoDTensor, SelectedRows

from test_tail_ops import _run_op


def test_conv3d_transpose_shape_and_sum():
    rng = np.random.RandomState(0)
    x = rng.randn(1, 2, 3, 3, 3).astype("float32")
    w = rng.randn(2, 4, 2, 2, 2).astype("float32")
    (o,) = _run_op("conv3d_transpose",
                   {"Input": ["x"], "Filter": ["w"]},
                   {"Output": ["o"]},
                   {"strides": [1, 1, 1], "paddings": [0, 0, 0],
                    "dilations": [1, 1, 1], "groups": 1},
                   {"x": x, "w": w}, ["o"])
    assert o.shape == (1, 4, 4, 4, 4)
    # total mass: each input element contributes through every kernel tap
    np.testing.assert_allclose(
        o.sum(), (x.sum(axis=(0, 2, 3, 4)) * w.sum(axis=(1, 2, 3, 4))
                  ).sum(), rtol=1e-4)


def test_depthwise_conv2d_transpose():
    rng = np.random.RandomState(1)
    x = rng.randn(1, 3, 4, 4).astype("float32")
    w = rng.randn(3, 1, 2, 2).astype("float32")
    (o,) = _run_op("depthwise_conv2d_transpose",
                   {"Input": ["x"], "Filter": ["w"]},
                   {"Output": ["o"]},
                   {"strides": [2, 2], "paddings": [0, 0],
                    "dilations": [1, 1], "groups": 3},
                   {"x": x, "w": w}, ["o"])
    assert o.shape == (1, 3, 8, 8)
    # channel 0 output depends only on channel 0 input
    x2 = x.copy()
    x2[0, 1:] = 0
    (o2,) = _run_op("depthwise_conv2d_transpose",
                    {"Input": ["x2"], "Filter": ["w2"]},
                    {"Output": ["o2"]},
                    {"strides": [2, 2], "paddings": [0, 0],
                     "dilations": [1, 1], "groups": 3},
                    {"x2": x2, "w2": w}, ["o2"])
    np.testing.assert_allclose(np.asarray(o2)[0, 0], np.asarray(o)[0, 0],
                               rtol=1e-5)


def _lod_feed(arr, lod):
    t = LoDTensor()
    t.set(arr)
    t.set_lod(lod)
    return t


def test_sequence_conv_matches_numpy():
    rng = np.random.RandomState(2)
    x = rng.randn(5, 3).astype("float32")   # seqs [2, 3]
    filt = rng.randn(9, 4).astype("float32")  # length 3 * D 3 -> 4
    (o,) = _run_op("sequence_conv",
                   {"X": ["x"], "Filter": ["f"]}, {"Out": ["o"]},
                   {"contextLength": 3, "contextStart": -1},
                   {"x": _lod_feed(x, [[0, 2, 5]]), "f": filt}, ["o"])
    # numpy oracle: context [t-1, t, t+1] zero-padded per sequence
    ref = np.zeros((5, 4), "float32")
    for lo, hi in [(0, 2), (2, 5)]:
        for t in range(lo, hi):
            ctx = []
            for s in (-1, 0, 1):
                j = t + s
                ctx.append(x[j] if lo <= j < hi else np.zeros(3))
            ref[t] = np.concatenate(ctx) @ filt
    np.testing.assert_allclose(o, ref, rtol=1e-4, atol=1e-5)


def test_sequence_scatter():
    x = np.zeros((2, 5), "float32")
    ids = np.array([[1], [3], [0], [4]], "int64")  # seq0: [1,3]; seq1: [0,4]
    upd = np.array([[10.0], [20.0], [30.0], [40.0]], "float32")
    (o,) = _run_op("sequence_scatter",
                   {"X": ["x"], "Ids": ["i"], "Updates": ["u"]},
                   {"Out": ["o"]}, {},
                   {"x": x, "i": _lod_feed(ids, [[0, 2, 4]]),
                    "u": _lod_feed(upd, [[0, 2, 4]])}, ["o"])
    ref = np.zeros((2, 5), "float32")
    ref[0, 1], ref[0, 3] = 10, 20
    ref[1, 0], ref[1, 4] = 30, 40
    np.testing.assert_allclose(o, ref)


def test_split_and_merge_ids():
    prog, _ = fluid.Program(), fluid.Program()
    blk = prog.global_block()
    for n in ("ids", "s0", "s1", "r0", "r1", "x0", "x1", "out"):
        blk.create_var(name=n, dtype="float32")
    blk.append_op("split_ids", {"Ids": ["ids"]}, {"Out": ["s0", "s1"]},
                  {}, infer_shape=False)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(prog, feed={"ids": np.array([[0], [3], [4], [1]],
                                            "int64")}, fetch_list=[])
        s0 = scope.find_var("s0").get_tensor().numpy().ravel()
        s1 = scope.find_var("s1").get_tensor().numpy().ravel()
    assert sorted(s0.tolist()) == [0, 4]
    assert sorted(s1.tolist()) == [1, 3]

    # merge back embeddings looked up per shard
    prog2, _ = fluid.Program(), fluid.Program()
    blk = prog2.global_block()
    for n in ("ids", "r0", "r1", "x0", "x1", "out"):
        blk.create_var(name=n, dtype="float32")
    blk.append_op("merge_ids",
                  {"Ids": ["ids"], "Rows": ["r0", "r1"],
                   "X": ["x0", "x1"]},
                  {"Out": ["out"]}, {}, infer_shape=False)
    scope2 = fluid.Scope()
    emb = {i: np.full((2,), float(i), "float32") for i in range(5)}
    with fluid.scope_guard(scope2):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(prog2, feed={
            "ids": np.array([[0], [3], [4], [1]], "int64"),
            "r0": np.array([0, 4], "int64"),
            "r1": np.array([3, 1], "int64"),
            "x0": np.stack([emb[0], emb[4]]),
            "x1": np.stack([emb[3], emb[1]])}, fetch_list=[])
        out = scope2.find_var("out").get_tensor().numpy()
    np.testing.assert_allclose(out, np.stack(
        [emb[0], emb[3], emb[4], emb[1]]))


def test_split_selected_rows():
    prog, _ = fluid.Program(), fluid.Program()
    blk = prog.global_block()
    for n in ("sr", "p0", "p1"):
        blk.create_var(name=n, dtype="float32")
    blk.append_op("split_selected_rows", {"X": ["sr"]},
                  {"Out": ["p0", "p1"]},
                  {"height_sections": [4, 4]}, infer_shape=False)
    scope = fluid.Scope()
    sr = SelectedRows(rows=[1, 5, 6], height=8,
                      value=np.arange(6, dtype="float32").reshape(3, 2))
    scope.var("sr").set(sr)
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(prog, feed={}, fetch_list=[])
        p0 = scope.find_var("p0").raw()
        p1 = scope.find_var("p1").raw()
    assert list(p0.rows()) == [1]
    assert list(p1.rows()) == [1, 2]  # 5-4, 6-4
    np.testing.assert_allclose(np.asarray(p1.get_tensor().numpy()),
                               [[2, 3], [4, 5]])


def test_lstmp_runs_and_projects():
    rng = np.random.RandomState(3)
    T, D, P = 5, 4, 3
    x = rng.randn(T, 4 * D).astype("float32")
    w = rng.randn(P, 4 * D).astype("float32") * 0.3
    pw = rng.randn(D, P).astype("float32") * 0.3
    b = rng.randn(1, 4 * D).astype("float32") * 0.1
    (proj, cell) = _run_op(
        "lstmp",
        {"Input": ["x"], "Weight": ["w"], "ProjWeight": ["pw"],
         "Bias": ["b"]},
        {"Projection": ["proj"], "Cell": ["cell"]}, {},
        {"x": _lod_feed(x, [[0, 2, 5]]), "w": w, "pw": pw, "b": b},
        ["proj", "cell"])
    assert proj.shape == (T, P) and cell.shape == (T, D)
    # sequence boundaries reset state: step 2 (start of seq 1) must not
    # depend on seq 0's rows
    x2 = x.copy()
    x2[:2] = 0
    (proj2, _) = _run_op(
        "lstmp",
        {"Input": ["x2"], "Weight": ["w2"], "ProjWeight": ["pw2"],
         "Bias": ["b2"]},
        {"Projection": ["proj2"], "Cell": ["cell2"]}, {},
        {"x2": _lod_feed(x2, [[0, 2, 5]]), "w2": w, "pw2": pw, "b2": b},
        ["proj2", "cell2"])
    np.testing.assert_allclose(proj2[2:], proj[2:], rtol=1e-5)


def test_lstmp_identity_projection_equals_lstm():
    """With P=D and ProjWeight=I, lstmp must reproduce the lstm op —
    pins the (candidate, input, forget, output) gate layout against an
    independent implementation."""
    rng = np.random.RandomState(7)
    T, D = 5, 3
    x = rng.randn(T, 4 * D).astype("float32")
    w = (rng.randn(D, 4 * D) * 0.3).astype("float32")
    b = (rng.randn(1, 4 * D) * 0.1).astype("float32")
    (h, c) = _run_op("lstm",
                     {"Input": ["x"], "Weight": ["w"], "Bias": ["b"]},
                     {"Hidden": ["h"], "Cell": ["c"]},
                     {"use_peepholes": False},
                     {"x": _lod_feed(x, [[0, 2, 5]]), "w": w, "b": b},
                     ["h", "c"])
    (p2, c2) = _run_op(
        "lstmp",
        {"Input": ["x2"], "Weight": ["w2"], "ProjWeight": ["pw"],
         "Bias": ["b2"]},
        {"Projection": ["p2"], "Cell": ["c2"]}, {},
        {"x2": _lod_feed(x, [[0, 2, 5]]), "w2": w,
         "pw": np.eye(D, dtype="float32"), "b2": b}, ["p2", "c2"])
    np.testing.assert_allclose(p2, h, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(c2, c, rtol=1e-5, atol=1e-6)


def _np_yolov3_loss(x, gt_box, gt_label, anchors, mask, C, ignore,
                    down, use_smooth, gt_score=None):
    """Literal numpy port of yolov3_loss_op.h for the oracle."""
    def sce(v, lab):
        return max(v, 0.0) - v * lab + np.log1p(np.exp(-abs(v)))

    def sig(v):
        return 1.0 / (1.0 + np.exp(-v))

    N, _, H, W = x.shape
    M, B = len(mask), gt_box.shape[1]
    an_num = len(anchors) // 2
    input_size = down * H
    xr = x.reshape(N, M, 5 + C, H, W)
    loss = np.zeros(N)
    obj_mask = np.zeros((N, M, H, W))
    if use_smooth:
        sm = min(1.0 / C, 1.0 / 40)
        posl, negl = 1 - sm, sm
    else:
        posl, negl = 1.0, 0.0

    def iou(b1, b2):
        lo = max(b1[0] - b1[2] / 2, b2[0] - b2[2] / 2)
        hi = min(b1[0] + b1[2] / 2, b2[0] + b2[2] / 2)
        iw = max(hi - lo, 0)
        lo = max(b1[1] - b1[3] / 2, b2[1] - b2[3] / 2)
        hi = min(b1[1] + b1[3] / 2, b2[1] + b2[3] / 2)
        ih = max(hi - lo, 0)
        inter = iw * ih
        return inter / (b1[2] * b1[3] + b2[2] * b2[3] - inter + 1e-10)

    for i in range(N):
        for j in range(M):
            for k in range(H):
                for l in range(W):
                    bx = (l + sig(xr[i, j, 0, k, l])) / H  # ref quirk
                    by = (k + sig(xr[i, j, 1, k, l])) / H
                    bw = np.exp(xr[i, j, 2, k, l]) * anchors[
                        2 * mask[j]] / input_size
                    bh = np.exp(xr[i, j, 3, k, l]) * anchors[
                        2 * mask[j] + 1] / input_size
                    best = 0.0
                    for t in range(B):
                        if gt_box[i, t, 2] < 1e-6 or gt_box[i, t, 3] < 1e-6:
                            continue
                        best = max(best, iou((bx, by, bw, bh),
                                             gt_box[i, t]))
                    if best > ignore:
                        obj_mask[i, j, k, l] = -1
        for t in range(B):
            if gt_box[i, t, 2] < 1e-6 or gt_box[i, t, 3] < 1e-6:
                continue
            g = gt_box[i, t]
            gi, gj = int(g[0] * W), int(g[1] * H)
            best_iou, best_n = 0.0, 0
            for a in range(an_num):
                ab = (0, 0, anchors[2 * a] / input_size,
                      anchors[2 * a + 1] / input_size)
                v = iou(ab, (0, 0, g[2], g[3]))
                if v > best_iou:
                    best_iou, best_n = v, a
            if best_n not in mask:
                continue
            mi = mask.index(best_n)
            score = 1.0 if gt_score is None else gt_score[i, t]
            scale = (2.0 - g[2] * g[3]) * score
            tx = g[0] * H - gi  # ref quirk: grid_size = h
            ty = g[1] * H - gj
            tw = np.log(g[2] * input_size / anchors[2 * best_n])
            th = np.log(g[3] * input_size / anchors[2 * best_n + 1])
            loss[i] += sce(xr[i, mi, 0, gj, gi], tx) * scale
            loss[i] += sce(xr[i, mi, 1, gj, gi], ty) * scale
            loss[i] += abs(tw - xr[i, mi, 2, gj, gi]) * scale
            loss[i] += abs(th - xr[i, mi, 3, gj, gi]) * scale
            obj_mask[i, mi, gj, gi] = score
            for c in range(C):
                lab = posl if c == gt_label[i, t] else negl
                loss[i] += sce(xr[i, mi, 5 + c, gj, gi], lab) * score
        for j in range(M):
            for k in range(H):
                for l in range(W):
                    o = obj_mask[i, j, k, l]
                    if o > 1e-5:
                        loss[i] += sce(xr[i, j, 4, k, l], 1.0) * o
                    elif o > -0.5:
                        loss[i] += sce(xr[i, j, 4, k, l], 0.0)
    return loss


def test_yolov3_loss_matches_numpy_oracle():
    rng = np.random.RandomState(9)
    N, H, W, C = 2, 4, 4, 3
    anchors = [10, 13, 16, 30, 33, 23]
    mask = [0, 1]
    M = len(mask)
    x = (rng.randn(N, M * (5 + C), H, W) * 0.5).astype("float32")
    gt_box = rng.rand(N, 3, 4).astype("float32") * 0.4 + 0.1
    gt_box[0, 2] = 0  # invalid gt
    gt_label = rng.randint(0, C, (N, 3)).astype("int32")
    (loss, obj, match) = _run_op(
        "yolov3_loss",
        {"X": ["x"], "GTBox": ["gb"], "GTLabel": ["gl"]},
        {"Loss": ["loss"], "ObjectnessMask": ["obj"],
         "GTMatchMask": ["match"]},
        {"anchors": anchors, "anchor_mask": mask, "class_num": C,
         "ignore_thresh": 0.5, "downsample_ratio": 32,
         "use_label_smooth": True},
        {"x": x, "gb": gt_box, "gl": gt_label}, ["loss", "obj", "match"])
    ref = _np_yolov3_loss(x, gt_box, gt_label, anchors, mask, C, 0.5,
                          32, True)
    np.testing.assert_allclose(loss, ref, rtol=1e-4, atol=1e-4)
    assert match.shape == (N, 3)
    assert match[0, 2] == -1  # invalid gt unmatched


def test_yolov3_loss_grads_flow():
    rng = np.random.RandomState(10)
    N, H, W, C = 1, 4, 4, 2
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        xv = fluid.data(name="x", shape=[N, 2 * (5 + C), H, W],
                        dtype="float32")
        gb = fluid.data(name="gb", shape=[N, 2, 4], dtype="float32")
        gl = fluid.data(name="gl", shape=[N, 2], dtype="int32")
        feat = fluid.layers.conv2d(
            xv, num_filters=2 * (5 + C), filter_size=1,
            param_attr=fluid.ParamAttr(name="yolo_w"), bias_attr=False)
        out = prog.global_block().create_var(name="yl", dtype="float32")
        out.shape = (N,)
        obj = prog.global_block().create_var(name="om", dtype="float32")
        mm = prog.global_block().create_var(name="mm", dtype="int32")
        prog.global_block().append_op(
            "yolov3_loss",
            inputs={"X": [feat.name], "GTBox": ["gb"], "GTLabel": ["gl"]},
            outputs={"Loss": ["yl"], "ObjectnessMask": ["om"],
                     "GTMatchMask": ["mm"]},
            attrs={"anchors": [10, 13, 16, 30], "anchor_mask": [0, 1],
                   "class_num": C, "ignore_thresh": 0.5,
                   "downsample_ratio": 32, "use_label_smooth": False},
            infer_shape=False)
        loss = fluid.layers.mean(prog.global_block().var("yl"))
        fluid.optimizer.SGD(0.01).minimize(loss)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        w0 = np.asarray(scope.find_var("yolo_w").raw().array).copy()
        exe.run(prog, feed={
            "x": rng.randn(N, 2 * (5 + C), H, W).astype("float32"),
            "gb": (rng.rand(N, 2, 4) * 0.4 + 0.1).astype("float32"),
            "gl": rng.randint(0, C, (N, 2)).astype("int32")},
            fetch_list=[loss])
        w1 = np.asarray(scope.find_var("yolo_w").raw().array)
    assert not np.allclose(w0, w1)


def test_yolov3_loss_nonsquare_and_scores():
    """Non-square grid (reference's grid_size=h quirk) + GTScore
    (mixup) weighting, both against the numpy oracle."""
    rng = np.random.RandomState(11)
    N, H, W, C = 1, 3, 6, 2
    anchors = [10, 13, 16, 30]
    mask = [0, 1]
    x = (rng.randn(N, 2 * (5 + C), H, W) * 0.5).astype("float32")
    gt_box = (rng.rand(N, 2, 4) * 0.3 + 0.1).astype("float32")
    gt_label = rng.randint(0, C, (N, 2)).astype("int32")
    gt_score = np.array([[0.7, 0.3]], "float32")
    (loss, obj, match) = _run_op(
        "yolov3_loss",
        {"X": ["x"], "GTBox": ["gb"], "GTLabel": ["gl"],
         "GTScore": ["gs"]},
        {"Loss": ["loss"], "ObjectnessMask": ["obj"],
         "GTMatchMask": ["match"]},
        {"anchors": anchors, "anchor_mask": mask, "class_num": C,
         "ignore_thresh": 0.5, "downsample_ratio": 32,
         "use_label_smooth": False},
        {"x": x, "gb": gt_box, "gl": gt_label, "gs": gt_score},
        ["loss", "obj", "match"])
    ref = _np_yolov3_loss(x, gt_box, gt_label, anchors, mask, C, 0.5,
                          32, False, gt_score=gt_score)
    np.testing.assert_allclose(loss, ref, rtol=1e-4, atol=1e-4)
    # matched cells carry the mixup score, not 1.0
    matched_vals = obj[obj > 1e-5]
    assert matched_vals.size > 0
    rounded = set(np.round(matched_vals.astype(np.float64), 3))
    assert rounded <= {0.7, 0.3}, rounded


def test_psroi_pool():
    # C = oc(2) * PH(2) * PW(2) = 8
    rng = np.random.RandomState(12)
    x = rng.randn(1, 8, 6, 6).astype("float32")
    rois = np.array([[0, 0, 3, 3]], "float32")
    (o,) = _run_op("psroi_pool", {"X": ["x"], "ROIs": ["r"]},
                   {"Out": ["o"]},
                   {"output_channels": 2, "pooled_height": 2,
                    "pooled_width": 2, "spatial_scale": 1.0},
                   {"x": x, "r": _lod_feed(rois, [[0, 1]])}, ["o"])
    assert o.shape == (1, 2, 2, 2)
    # bin (c=0, ph=0, pw=0): channel 0, window rows/cols [0, 2)
    np.testing.assert_allclose(o[0, 0, 0, 0], x[0, 0, 0:2, 0:2].mean(),
                               rtol=1e-5)
    # bin (c=1, ph=1, pw=1): channel (1*2+1)*2+1 = 7, rows/cols [2, 4)
    np.testing.assert_allclose(o[0, 1, 1, 1], x[0, 7, 2:4, 2:4].mean(),
                               rtol=1e-5)


def test_psroi_pool_grads_flow():
    """Masked-mean formulation keeps psroi differentiable — a backbone
    conv upstream must receive gradients."""
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        xv = fluid.data(name="x", shape=[1, 8, 6, 6], dtype="float32")
        feat = fluid.layers.conv2d(
            xv, num_filters=8, filter_size=1,
            param_attr=fluid.ParamAttr(name="ps_w"), bias_attr=False)
        r = fluid.layers.data(name="r", shape=[4], dtype="float32",
                              lod_level=1)
        blk = prog.global_block()
        out = blk.create_var(name="ps_out", dtype="float32")
        out.shape = (1, 2, 2, 2)
        blk.append_op("psroi_pool",
                      inputs={"X": [feat.name], "ROIs": ["r"]},
                      outputs={"Out": ["ps_out"]},
                      attrs={"output_channels": 2, "pooled_height": 2,
                             "pooled_width": 2, "spatial_scale": 1.0},
                      infer_shape=False)
        loss = fluid.layers.mean(
            fluid.layers.square(blk.var("ps_out")))
        fluid.optimizer.SGD(0.1).minimize(loss)
    scope = fluid.Scope()
    t = _lod_feed(np.array([[0, 0, 3, 3]], "float32"), [[0, 1]])
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        w0 = np.asarray(scope.find_var("ps_w").raw().array).copy()
        exe.run(prog,
                feed={"x": np.random.RandomState(0).randn(
                    1, 8, 6, 6).astype("float32"), "r": t},
                fetch_list=[loss])
        w1 = np.asarray(scope.find_var("ps_w").raw().array)
    assert not np.allclose(w0, w1)


def test_sample_logits_contract():
    rng = np.random.RandomState(13)
    N, K, S = 4, 12, 5
    logits = rng.randn(N, K).astype("float32")
    labels = rng.randint(0, K, (N, 1)).astype("int64")
    (samples, probs, slog, slab) = _run_op(
        "sample_logits",
        {"Logits": ["lg"], "Labels": ["lb"]},
        {"Samples": ["sm"], "Probabilities": ["pr"],
         "SampledLogits": ["sl"], "SampledLabels": ["sb"]},
        {"num_samples": S, "remove_accidental_hits": True,
         "use_customized_samples": False, "uniq": True, "seed": 3},
        {"lg": logits, "lb": labels}, ["sm", "pr", "sl", "sb"])
    assert samples.shape == (N, 1 + S)
    # col 0 is the true label; sampled columns are unique per row
    np.testing.assert_array_equal(samples[:, 0], labels.ravel())
    for r in range(N):
        assert len(set(samples[r, 1:].tolist())) == S
    # sampled logits = logits - log q (+ accidental-hit knockdown)
    q = probs
    gathered = np.take_along_axis(logits, samples.astype(int), axis=1)
    acc = samples[:, 1:] == labels
    expected = gathered - np.log(q)
    expected[:, 1:][acc] -= 1e20
    np.testing.assert_allclose(slog, expected, rtol=1e-4)
    np.testing.assert_array_equal(slab, np.zeros((N, 1)))


def test_sampled_softmax_equals_full_when_covering():
    """With customized samples covering every class and uniform q, the
    sampled loss reduces to full softmax cross entropy."""
    rng = np.random.RandomState(14)
    N, K = 3, 6
    logits_v = rng.randn(N, K).astype("float32")
    labels_v = rng.randint(0, K, (N, 1)).astype("int64")
    # row: [label, all other classes]
    samples_v = np.stack([
        np.concatenate([labels_v[i], np.setdiff1d(np.arange(K),
                                                  labels_v[i])])
        for i in range(N)]).astype("int64")
    probs_v = np.full((N, K), 1.0, "float32")  # log q = 0

    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        lg = fluid.data(name="lg", shape=[N, K], dtype="float32")
        lb = fluid.data(name="lb", shape=[N, 1], dtype="int64")
        cs = fluid.data(name="cs", shape=[N, K], dtype="int64")
        cp = fluid.data(name="cp", shape=[N, K], dtype="float32")
        loss = fluid.layers.sampled_softmax_with_cross_entropy(
            lg, lb, num_samples=K - 1, use_customized_samples=True,
            customized_samples=cs, customized_probabilities=cp,
            remove_accidental_hits=False)
        full = fluid.layers.softmax_with_cross_entropy(lg, lb)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        (a, b) = exe.run(prog, feed={"lg": logits_v, "lb": labels_v,
                                     "cs": samples_v, "cp": probs_v},
                         fetch_list=[loss, full])
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4,
                               atol=1e-5)
