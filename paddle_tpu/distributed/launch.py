"""Multi-process training launcher.

Parity: /root/reference/python/paddle/distributed/launch.py:353 — spawn
one worker process per device/host slot with the PADDLE_TRAINER_*
environment contract. TPU-native: each worker also gets the
jax.distributed coordination variables, so dygraph prepare_context /
the collective fleet initialize over the coordination service instead
of a NCCL TCP id broadcast.

Usage:  python -m paddle_tpu.distributed.launch --nproc_per_node=2 \
            train.py --your-args
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys

__all__ = ["launch", "get_cluster_env"]


def _parse_args(argv=None):
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="worker processes on this node")
    p.add_argument("--ips", default="127.0.0.1",
                   help="comma-separated node IPs (this node must be "
                        "included)")
    p.add_argument("--node_rank", type=int, default=0)
    p.add_argument("--started_port", type=int, default=6170)
    p.add_argument("--log_dir", default=None)
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def get_cluster_env(node_ips, node_rank, nproc_per_node, started_port,
                    local_rank):
    """The PADDLE_* env contract for one worker (reference launch.py:175)."""
    nnodes = len(node_ips)
    nranks = nnodes * nproc_per_node
    rank = node_rank * nproc_per_node + local_rank
    endpoints = [
        "%s:%d" % (ip, started_port + i)
        for ip in node_ips for i in range(nproc_per_node)
    ]
    env = {
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(nranks),
        "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
        "PADDLE_CURRENT_ENDPOINT": endpoints[rank],
        "FLAGS_selected_tpus": str(local_rank),
        # jax.distributed contract: coordinator is rank 0's endpoint
        "JAX_COORDINATOR_ADDRESS": endpoints[0],
        "JAX_NUM_PROCESSES": str(nranks),
        "JAX_PROCESS_ID": str(rank),
    }
    return env


def launch(args=None):
    args = args if args is not None else _parse_args()
    node_ips = [ip for ip in args.ips.split(",") if ip]
    procs = []
    log_fps = []
    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)
    try:
        # workers must import paddle_tpu even when it runs from a source
        # checkout (script-dir sys.path[0] replaces the launcher's cwd)
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        for local_rank in range(args.nproc_per_node):
            env = dict(os.environ)
            env["PYTHONPATH"] = pkg_root + os.pathsep + \
                env.get("PYTHONPATH", "")
            env.update(get_cluster_env(node_ips, args.node_rank,
                                       args.nproc_per_node,
                                       args.started_port, local_rank))
            cmd = [sys.executable, "-u", args.training_script] + \
                list(args.training_script_args)
            stdout = stderr = None
            if args.log_dir:
                fp = open(os.path.join(
                    args.log_dir, "workerlog.%d" % local_rank), "w")
                log_fps.append(fp)
                stdout = stderr = fp
            procs.append(subprocess.Popen(cmd, env=env, stdout=stdout,
                                          stderr=stderr))
        rc = 0
        for p in procs:
            p.wait()
            rc = rc or p.returncode
        return rc
    except KeyboardInterrupt:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            p.wait()
        return 1
    finally:
        for fp in log_fps:
            fp.close()


def main():
    sys.exit(launch())


if __name__ == "__main__":
    main()
