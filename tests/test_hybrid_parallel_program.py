"""Hybrid parallelism through the PROGRAM path (round-4 item: mp/ep/sp
must ride the same `fluid.Program` -> Executor surface a user touches,
not raw-JAX side libraries).

Each test: build a user Program with standard layers, transpile via the
fleet DistributedStrategy knobs (sharded_embedding / sequence_parallel /
expert_parallel -> parallel/transpiler passes), train one step densely
on a single device, then the SAME program through
`exe.run(CompiledProgram(...).with_data_parallel(places=mesh))` on a
multi-axis CPU mesh — loss and updated params must match.

Reference contract being mirrored: transpiler/collective.py:92-131
(program rewrite) + test_dist_base.py:506 (multi-device loss parity vs
a single-process run).
"""
import numpy as np
import pytest

import paddle_tpu as fluid
from __graft_entry__ import _program_parity_step as _run_dense_then_mesh
from paddle_tpu.incubate.fleet.collective import (CollectiveOptimizer,
                                                  DistributedStrategy)
from paddle_tpu.parallel.mesh_utils import make_mesh


def test_program_path_sharded_embedding():
    """dp(2) x mp(4): embedding table row-sharded over mp via
    strategy.sharded_embedding; loss + updated table match dense."""
    dp, mp = 2, 4
    V, D, N = 16, 8, 8
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.data(name="ids", shape=[N, 1], dtype="int64")
        tgt = fluid.data(name="tgt", shape=[N, D], dtype="float32")
        emb = fluid.layers.embedding(ids, size=[V, D],
                                     param_attr=fluid.ParamAttr(
                                         name="emb_w"))
        loss = fluid.layers.reduce_mean(
            fluid.layers.square(fluid.layers.elementwise_sub(emb, tgt)))
        strat = DistributedStrategy()
        strat.sharded_embedding = True
        strat.mp_degree = mp
        CollectiveOptimizer(
            fluid.optimizer.MomentumOptimizer(0.1, 0.9), strat).minimize(
                loss)

    assert any(op.type == "c_sharded_lookup"
               for op in main.global_block().ops)
    assert main._var_shard_specs["emb_w"] == ("mp",)

    rng = np.random.RandomState(3)
    feed = {"ids": rng.randint(0, V, (N, 1)).astype("int64"),
            "tgt": rng.randn(N, D).astype("float32")}
    mesh = make_mesh([dp, mp], ["dp", "mp"])
    l_dense, l_mesh, p_dense, p_mesh = _run_dense_then_mesh(
        main, startup, loss, feed, mesh)
    assert np.isfinite(l_dense) and np.isfinite(l_mesh)
    assert abs(l_dense - l_mesh) < 1e-5, (l_dense, l_mesh)
    np.testing.assert_allclose(p_mesh["emb_w"], p_dense["emb_w"],
                               rtol=1e-5, atol=1e-6)


def test_program_path_ring_attention():
    """dp(2) x sp(4): flash_attention rewritten to ring attention over
    sp; sequence-sharded feeds; loss + updated projection match dense."""
    dp, sp = 2, 4
    B, H, S, D = 2 * dp, 2, 4 * sp, 8
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data(name="x", shape=[B, H, S, D], dtype="float32")
        tgt = fluid.data(name="tgt", shape=[B, H, S, D], dtype="float32")
        w = fluid.layers.create_parameter([D, D], "float32", name="w_q")
        q = fluid.layers.matmul(x, w)
        o = fluid.layers.flash_attention(q, x, x, causal=True)
        loss = fluid.layers.reduce_mean(
            fluid.layers.square(fluid.layers.elementwise_sub(o, tgt)))
        strat = DistributedStrategy()
        strat.sequence_parallel = True
        strat.sp_degree = sp
        strat.feed_shard_specs = {"x": ("dp", None, "sp"),
                                  "tgt": ("dp", None, "sp")}
        CollectiveOptimizer(
            fluid.optimizer.SGDOptimizer(0.05), strat).minimize(loss)

    assert any(op.type == "c_ring_attention"
               for op in main.global_block().ops)
    assert main._data_axes == ("dp", "sp")

    rng = np.random.RandomState(5)
    feed = {"x": rng.randn(B, H, S, D).astype("float32"),
            "tgt": rng.randn(B, H, S, D).astype("float32")}
    mesh = make_mesh([dp, sp], ["dp", "sp"])
    l_dense, l_mesh, p_dense, p_mesh = _run_dense_then_mesh(
        main, startup, loss, feed, mesh)
    assert np.isfinite(l_dense) and np.isfinite(l_mesh)
    assert abs(l_dense - l_mesh) / max(abs(l_dense), 1e-6) < 1e-4, (
        l_dense, l_mesh)
    np.testing.assert_allclose(p_mesh["w_q"], p_dense["w_q"],
                               rtol=1e-4, atol=1e-6)


def test_program_path_expert_parallel():
    """ep(8): switch_moe experts sharded over ep, tokens routed by
    all_to_all; dense fallback chunks routing identically, so loss and
    updated expert weights match exactly."""
    ep = 8
    T, D, H, E = 8 * ep, 6, 8, 16
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data(name="x", shape=[T, D], dtype="float32")
        tgt = fluid.data(name="tgt", shape=[T, D], dtype="float32")
        y = fluid.layers.switch_moe(x, num_experts=E, hidden_dim=H,
                                    capacity_factor=2.0)
        loss = fluid.layers.reduce_mean(
            fluid.layers.square(fluid.layers.elementwise_sub(y, tgt)))
        strat = DistributedStrategy()
        strat.expert_parallel = True
        strat.ep_degree = ep
        CollectiveOptimizer(
            fluid.optimizer.SGDOptimizer(0.05), strat).minimize(loss)

    moe_ops = [op for op in main.global_block().ops if op.type == "moe"]
    assert moe_ops and moe_ops[0].attrs["shard_axis"] == "ep"
    assert main._data_axes == ("ep",)

    rng = np.random.RandomState(7)
    feed = {"x": rng.randn(T, D).astype("float32"),
            "tgt": rng.randn(T, D).astype("float32")}
    mesh = make_mesh([ep], ["ep"])
    l_dense, l_mesh, p_dense, p_mesh = _run_dense_then_mesh(
        main, startup, loss, feed, mesh)
    assert np.isfinite(l_dense) and np.isfinite(l_mesh)
    assert abs(l_dense - l_mesh) / max(abs(l_dense), 1e-6) < 1e-4, (
        l_dense, l_mesh)
    win = moe_ops[0].input("WIn")[0]
    np.testing.assert_allclose(p_mesh[win], p_dense[win],
                               rtol=1e-4, atol=1e-6)


def test_program_path_pure_model_parallel_mesh():
    """mp-only mesh (no data axis): the batch is replicated, grads need
    no allreduce, and the engine must NOT promote the model axis to a
    data axis (that would shard the feeds and silently drop cross-shard
    gradient contributions)."""
    mp = 4
    V, D, N = 16, 8, 6  # N deliberately NOT divisible by mp
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.data(name="ids", shape=[N, 1], dtype="int64")
        tgt = fluid.data(name="tgt", shape=[N, D], dtype="float32")
        emb = fluid.layers.embedding(ids, size=[V, D],
                                     param_attr=fluid.ParamAttr(
                                         name="emb_w"))
        loss = fluid.layers.reduce_mean(
            fluid.layers.square(fluid.layers.elementwise_sub(emb, tgt)))
        strat = DistributedStrategy()
        strat.sharded_embedding = True
        strat.mp_degree = mp
        CollectiveOptimizer(
            fluid.optimizer.SGDOptimizer(0.5), strat).minimize(loss)

    rng = np.random.RandomState(9)
    feed = {"ids": rng.randint(0, V, (N, 1)).astype("int64"),
            "tgt": rng.randn(N, D).astype("float32")}
    mesh = make_mesh([mp], ["mp"])
    l_dense, l_mesh, p_dense, p_mesh = _run_dense_then_mesh(
        main, startup, loss, feed, mesh)
    assert np.isfinite(l_dense) and np.isfinite(l_mesh)
    assert abs(l_dense - l_mesh) < 1e-5, (l_dense, l_mesh)
    np.testing.assert_allclose(p_mesh["emb_w"], p_dense["emb_w"],
                               rtol=1e-5, atol=1e-6)
