"""Program / Block / Operator / Variable — the static-graph IR.

Behavioral counterpart of the reference's ProgramDesc tree and its Python
mirror (/root/reference/paddle/fluid/framework.py:806,1706,2176,3602 and
paddle/fluid/framework/framework.proto). Differences by design:

- The IR is Python-native (dataclass-style objects) rather than protobuf
  descs shadowed by C++ wrappers; serialization goes through a compact
  JSON form (``Program.to_json``) used by save/load_inference_model.
- Shape/dtype inference runs at ``append_op`` time through the SAME jax
  ``eval_shape`` path the executor compiles, so there is no separate
  compile-time InferShape (reference shape_inference.h duality).
- Ops never mutate vars in place at the IR level; "in-place" outputs
  (e.g. optimizer ParamOut==Param) are expressed by binding the same
  variable name, and executors handle rebinding/donation.
"""
from __future__ import annotations

import contextlib
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from .core import dtypes as _dt
from .core.registry import OpInfoMap, GRAD_SUFFIX
from .utils import unique_name

_SENTINEL = 1223  # stands in for -1 (unknown batch) during eval_shape


class Variable:
    """Symbolic variable inside a Block (graph-build time).

    Mirrors python/paddle/fluid/framework.py:806. The runtime value lives
    in a Scope under the same name.
    """

    def __init__(
        self,
        block: "Block",
        name: Optional[str] = None,
        shape: Optional[Sequence[int]] = None,
        dtype="float32",
        lod_level: int = 0,
        persistable: bool = False,
        stop_gradient: bool = False,
        is_data: bool = False,
        type: str = "lod_tensor",
        initializer=None,
        **kwargs,
    ):
        self.block = block
        self.name = name if name is not None else unique_name.generate("_generated_var")
        self.shape = tuple(int(s) for s in shape) if shape is not None else None
        self.dtype = _dt.convert_dtype(dtype)
        self.lod_level = lod_level
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.is_data = is_data
        self.type = type  # "lod_tensor" | "selected_rows" | "lod_tensor_array" | ...
        self.op: Optional[Operator] = None  # last writer

    # numpy-style helpers used by layers code
    @property
    def ndim(self):
        return len(self.shape) if self.shape is not None else None

    def astype(self, dtype):
        from .layers import tensor as _lt

        return _lt.cast(self, dtype)

    def __repr__(self):
        return "Variable(%s, shape=%s, dtype=%s%s)" % (
            self.name,
            self.shape,
            self.dtype,
            ", persistable" if self.persistable else "",
        )

    __str__ = __repr__

    # Operator overloads are patched in by layers.math_op_patch (monkey
    # patch like the reference) to avoid import cycles here.


class Parameter(Variable):
    """A persistable, trainable variable (framework.py:4631)."""

    def __init__(self, block, shape, dtype, **kwargs):
        self.trainable = kwargs.pop("trainable", True)
        self.optimize_attr = kwargs.pop("optimize_attr", {"learning_rate": 1.0})
        self.regularizer = kwargs.pop("regularizer", None)
        self.do_model_average = kwargs.pop("do_model_average", None)
        self.is_distributed = kwargs.pop("is_distributed", False)
        kwargs.setdefault("persistable", True)
        kwargs.setdefault("stop_gradient", False)
        super().__init__(block, shape=shape, dtype=dtype, **kwargs)


class OpRole:
    """Op phase tags (reference framework.py op_role attr / OpProto roles).

    Bitmask: Loss may combine with Forward/Backward."""

    Forward = 0x0000
    Backward = 0x0001
    Optimize = 0x0002
    RPC = 0x0004
    Dist = 0x0008
    LRSched = 0x0010
    Loss = 0x0100


class Operator:
    """One op in a Block: (type, slot->var-names, attrs).

    Mirrors framework.py:1706 / OpDesc. Input/output maps store *names*;
    resolve via block.var().
    """

    def __init__(self, block, type, inputs=None, outputs=None, attrs=None):
        self.block = block
        self.type = type
        self.inputs: Dict[str, List[str]] = {}
        self.outputs: Dict[str, List[str]] = {}
        self.attrs: Dict[str, object] = dict(attrs or {})
        self._id = None  # set by Block.append_op
        # role of the phase appending this op (reference: the op_role attr
        # set by Program.op_role / _optimized_guard, framework.py:3602);
        # clone(for_test=True) prunes Backward/Optimize ops by it.
        prog = getattr(block, "program", None) if block is not None else None
        self._role = getattr(prog, "_current_role", 0)

        for slot, arg in (inputs or {}).items():
            self.inputs[slot] = _to_name_list(arg)
        for slot, arg in (outputs or {}).items():
            self.outputs[slot] = _to_name_list(arg)

    def input(self, slot) -> List[str]:
        return self.inputs.get(slot, [])

    def output(self, slot) -> List[str]:
        return self.outputs.get(slot, [])

    @property
    def input_arg_names(self):
        return [n for ns in self.inputs.values() for n in ns]

    @property
    def output_arg_names(self):
        return [n for ns in self.outputs.values() for n in ns]

    def attr(self, name):
        return self.attrs.get(name)

    def _set_attr(self, name, val):
        self.attrs[name] = val

    def has_attr(self, name):
        return name in self.attrs

    def __repr__(self):
        return "Op(%s: %s -> %s)" % (self.type, self.inputs, self.outputs)


def _to_name_list(arg) -> List[str]:
    if arg is None:
        return []
    if isinstance(arg, (list, tuple)):
        return [a.name if isinstance(a, Variable) else str(a) for a in arg]
    return [arg.name if isinstance(arg, Variable) else str(arg)]


class Block:
    def __init__(self, program: "Program", idx: int, parent_idx: int = -1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars: Dict[str, Variable] = {}
        self.ops: List[Operator] = []

    @property
    def parent_block(self) -> Optional["Block"]:
        if self.parent_idx < 0:
            return None
        return self.program.block(self.parent_idx)

    # -- vars -------------------------------------------------------------
    def create_var(self, **kwargs) -> Variable:
        name = kwargs.get("name")
        if name and name in self.vars:
            return self.vars[name]
        v = Variable(self, **kwargs)
        self.vars[v.name] = v
        return v

    def create_parameter(self, **kwargs) -> Parameter:
        p = Parameter(self, **kwargs)
        # Parameters live in the top (global) block, like the reference.
        gb = self.program.global_block()
        gb.vars[p.name] = p
        if self is not gb:
            self.vars[p.name] = p
        return p

    def var(self, name: str) -> Variable:
        v = self._find_var_recursive(name)
        if v is None:
            raise ValueError("variable %r not found in block %d" % (name, self.idx))
        return v

    def has_var(self, name: str) -> bool:
        return self._find_var_recursive(name) is not None

    def has_var_local(self, name: str) -> bool:
        return name in self.vars

    def _find_var_recursive(self, name: str) -> Optional[Variable]:
        b: Optional[Block] = self
        while b is not None:
            v = b.vars.get(name)
            if v is not None:
                return v
            b = b.parent_block
        return None

    @property
    def all_parameters(self) -> List[Parameter]:
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    # -- ops --------------------------------------------------------------
    def append_op(self, type, inputs=None, outputs=None, attrs=None,
                  infer_shape=True) -> Operator:
        op = Operator(self, type, inputs, outputs, attrs)
        op._id = self.program._next_op_id()
        self.ops.append(op)
        if infer_shape:
            try:
                infer_op_shapes(self, op)
            except Exception as e:
                if OpInfoMap.instance().has(type):
                    # roll the failed op back out so a caller that
                    # catches the build error isn't left with a
                    # poisoned block that re-raises at exe.run
                    self.ops.pop()
                    from .core.enforce import annotate_op_error

                    annotate_op_error(e, op, "shape inference")
                    raise
        return op

    def prepend_op(self, type, inputs=None, outputs=None, attrs=None) -> Operator:
        op = Operator(self, type, inputs, outputs, attrs)
        op._id = self.program._next_op_id()
        self.ops.insert(0, op)
        try:
            infer_op_shapes(self, op)
        except Exception:
            pass
        return op

    def __repr__(self):
        return "Block(%d, %d ops, %d vars)" % (self.idx, len(self.ops), len(self.vars))


# ---------------------------------------------------------------------------
# Shape inference (shared compile-time path)
# ---------------------------------------------------------------------------


def infer_op_shapes(block: Block, op: Operator) -> None:
    """Set output var shapes/dtypes from input metadata via the op's fn.

    Unknown dims (-1) round-trip through a sentinel prime so eval_shape can
    run on concrete ints.
    """
    import jax

    info = OpInfoMap.instance().get(op.type)
    if info.fn is None and info.infer_shape is None:
        return  # host op with no declared shape semantics

    def meta_of(name):
        v = block.var(name)
        if v.shape is None:
            raise ValueError("input %r has no shape" % name)
        shape = tuple(_SENTINEL if d < 0 else d for d in v.shape)
        return jax.ShapeDtypeStruct(shape, _dt.to_numpy_dtype(v.dtype))

    ins = {}
    for slot in info.inputs:
        names = op.input(slot.name)
        if not names:
            ins[slot.name] = None
            continue
        metas = [meta_of(n) for n in names]
        ins[slot.name] = metas if slot.duplicable else metas[0]

    attrs = dict(op.attrs)
    if info.needs_lod and info.infer_shape is None:
        # LoD-dependent output shapes are runtime information (they vary
        # with the fed sequence lengths); running the kernel for
        # eval_shape would raise. Default every float output to
        # [-1, trailing dims of the first input].
        first = None
        for slot in info.inputs:
            names = op.input(slot.name)
            if names:
                first = block._find_var_recursive(names[0])
                break
        for slot in info.outputs:
            for n in op.output(slot.name):
                v = block._find_var_recursive(n)
                if v is None:
                    v = block.create_var(name=n)
                if v.shape is None and first is not None \
                        and first.shape is not None:
                    v.shape = (-1,) + tuple(first.shape[1:])
                    if v.dtype is None:
                        v.dtype = first.dtype
                v.op = op
        return
    from .core.registry import BOUND_OUTPUTS_ATTR, RNG_SEED_ATTR

    attrs[BOUND_OUTPUTS_ATTR] = tuple(
        s.name for s in info.outputs if op.output(s.name)
    )

    if info.infer_shape is not None:
        out_meta = info.infer_shape(ins, attrs)
    else:
        if info.needs_rng:
            ins[RNG_SEED_ATTR] = jax.ShapeDtypeStruct((), np.uint32)
        out_meta = jax.eval_shape(lambda kw: info.fn(kw, attrs), ins)

    for slot in info.outputs:
        names = op.output(slot.name)
        if not names:
            continue
        m = out_meta.get(slot.name)
        if m is None:
            continue
        metas = m if isinstance(m, (list, tuple)) else [m]
        for n, mm in zip(names, metas):
            v = block._find_var_recursive(n)
            if v is None:
                v = block.create_var(name=n)
            if mm is None:
                continue
            v.shape = tuple(-1 if d == _SENTINEL else int(d) for d in mm.shape)
            v.dtype = _dt.convert_dtype(mm.dtype)
            v.op = op


# ---------------------------------------------------------------------------
# Program
# ---------------------------------------------------------------------------


_program_uid_counter = [0]


class Program:
    def __init__(self):
        # process-unique id for compile caches: unlike id(), never reused
        # after GC, so a fresh Program can't alias a dead one's cache entry
        _program_uid_counter[0] += 1
        self._uid = _program_uid_counter[0]
        self.blocks: List[Block] = [Block(self, 0)]
        self._current_block_idx = 0
        self._op_id = 0
        self._seed = 0
        self.random_seed = 0
        # op-role bookkeeping used by backward/optimizer passes
        self._appending_grad_times = 0
        self._current_role = OpRole.Forward

    def _next_op_id(self):
        self._op_id += 1
        return self._op_id

    @contextlib.contextmanager
    def _role_guard(self, role):
        """Ops appended inside carry `role` (reference _optimized_guard /
        _backward_role_guard, framework.py:3602)."""
        prev = self._current_role
        self._current_role = role
        try:
            yield
        finally:
            self._current_role = prev

    def _optimized_guard(self, param_and_grads=None):
        return self._role_guard(OpRole.Optimize)

    def _backward_role_guard(self):
        return self._role_guard(OpRole.Backward)

    def global_block(self) -> Block:
        return self.blocks[0]

    def current_block(self) -> Block:
        return self.blocks[self._current_block_idx]

    def block(self, idx: int) -> Block:
        return self.blocks[idx]

    @property
    def num_blocks(self):
        return len(self.blocks)

    def _create_block(self, parent_idx: Optional[int] = None) -> Block:
        parent = self._current_block_idx if parent_idx is None else parent_idx
        b = Block(self, len(self.blocks), parent)
        self.blocks.append(b)
        self._current_block_idx = b.idx
        return b

    def _rollback(self):
        self._current_block_idx = self.current_block().parent_idx

    def all_parameters(self) -> List[Parameter]:
        return self.global_block().all_parameters

    def list_vars(self):
        for b in self.blocks:
            for v in b.vars.values():
                yield v

    # -- cloning / pruning ------------------------------------------------
    def clone(self, for_test: bool = False) -> "Program":
        import copy

        p = Program.__new__(Program)
        _program_uid_counter[0] += 1
        p._uid = _program_uid_counter[0]
        p.blocks = []
        p._current_block_idx = 0
        p._current_role = OpRole.Forward
        p._op_id = self._op_id
        p._seed = self._seed
        p.random_seed = self.random_seed
        p._appending_grad_times = self._appending_grad_times
        for b in self.blocks:
            nb = Block(p, b.idx, b.parent_idx)
            p.blocks.append(nb)
        for b, nb in zip(self.blocks, p.blocks):
            for name, v in b.vars.items():
                nv = copy.copy(v)
                nv.block = nb
                nb.vars[name] = nv
            for op in b.ops:
                if for_test and op.type in _TRAIN_ONLY_SKIP:
                    continue
                if for_test and op._role & (OpRole.Backward
                                            | OpRole.Optimize):
                    continue  # reference clone(for_test) prunes by op_role
                nop = Operator(nb, op.type, None, None, dict(op.attrs))
                nop.inputs = {k: list(v) for k, v in op.inputs.items()}
                nop.outputs = {k: list(v) for k, v in op.outputs.items()}
                nop._id = op._id
                nop._role = op._role
                if for_test and "is_test" in _op_attr_names(op.type):
                    nop.attrs["is_test"] = True
                nb.ops.append(nop)
        return p

    def __repr__(self):
        return "Program(%d blocks, %d ops)" % (
            len(self.blocks),
            sum(len(b.ops) for b in self.blocks),
        )


def _op_attr_names(op_type):
    try:
        return OpInfoMap.instance().get(op_type).attrs.keys()
    except KeyError:
        return ()


_TRAIN_ONLY_SKIP = set()  # op types dropped by clone(for_test=True)


# ---------------------------------------------------------------------------
# Default programs & guards (reference framework.py:4845,4879)
# ---------------------------------------------------------------------------

_main_program_ = Program()
_startup_program_ = Program()


def default_main_program() -> Program:
    return _main_program_


def default_startup_program() -> Program:
    return _startup_program_


def switch_main_program(program: Program) -> Program:
    global _main_program_
    old = _main_program_
    _main_program_ = program
    return old


def switch_startup_program(program: Program) -> Program:
    global _startup_program_
    old = _startup_program_
    _startup_program_ = program
    return old


@contextlib.contextmanager
def program_guard(main_program: Program, startup_program: Optional[Program] = None):
    old_main = switch_main_program(main_program)
    old_startup = None
    if startup_program is not None:
        old_startup = switch_startup_program(startup_program)
    try:
        yield
    finally:
        switch_main_program(old_main)
        if old_startup is not None:
            switch_startup_program(old_startup)


# ---------------------------------------------------------------------------
# dygraph mode switch (tracer set by dygraph.guard)
# ---------------------------------------------------------------------------

_dygraph_tracer_ = None
_dygraph_place_ = None


def in_dygraph_mode() -> bool:
    return _dygraph_tracer_ is not None


def _dygraph_tracer():
    return _dygraph_tracer_


def _current_expected_place():
    from .core.place import _current_expected_place_default

    if _dygraph_place_ is not None:
        return _dygraph_place_
    return _current_expected_place_default()


def grad_var_name(name: str) -> str:
    return name + GRAD_SUFFIX


def default_startup_seed():
    return _startup_program_.random_seed
