"""Collective fleet mode (reference incubate/fleet/collective/__init__.py
:45 Collective(Fleet), :182 CollectiveOptimizer, :134 DistributedStrategy).

TPU-native semantics: distributed_optimizer().minimize() runs the normal
minimize then the collective transpiler (loss-grad 1/nranks scaling +
per-grad c_allreduce_sum); main_program executes through the mesh engine
(CompiledProgram.with_data_parallel), whose shard_map lowers the
collectives to lax.psum over ICI. Multi-host: the same program under
jax.distributed initialization — no NCCL rings to bootstrap.
"""
from __future__ import annotations

from ....compiler import BuildStrategy, CompiledProgram, ExecutionStrategy
from ..base.fleet_base import DistributedOptimizer, Fleet


class DistributedStrategy:
    """Knobs (reference DistributedStrategy extends BuildStrategy).

    Hybrid-parallelism knobs (the axes the reference lacks — SURVEY §2.5
    "NOT present" row — designed here as program-rewrite passes over the
    same transpiler pattern, transpiler/collective.py:92-131):

    - ``sharded_embedding`` (+ ``mp_degree``): every embedding table is
      row-sharded over an 'mp' mesh axis (pslib sparse-PS replacement).
    - ``sequence_parallel`` (+ ``sp_degree``, ``feed_shard_specs``):
      attention runs ring attention over an 'sp' axis for long context;
      feed_shard_specs declares feed layouts, e.g.
      {"x": ("dp", None, "sp")}.
    - ``expert_parallel`` (+ ``ep_degree``): MoE experts are sharded
      over an 'ep' axis, tokens routed by two all_to_alls.

    The rewritten program still runs densely on one device (ops fall
    back to exact dense math), which is how the driver checks mesh-vs-
    single-device parity through `exe.run`.
    """

    def __init__(self):
        self.build_strategy = BuildStrategy()
        self.exec_strategy = ExecutionStrategy()
        self.nccl_comm_num = 1
        self.use_local_sgd = False
        self.local_sgd_k_steps = 1
        self.forward_recompute = False
        self.recompute_checkpoints = []
        self.use_amp = False
        self.amp_loss_scaling = 1.0
        # hybrid parallelism
        self.sharded_embedding = False
        self.mp_degree = 1
        self.sequence_parallel = False
        self.sp_degree = 1
        self.feed_shard_specs = {}
        self.expert_parallel = False
        self.ep_degree = 1
        # pipeline parallelism over a 'pp' mesh axis — composes with a
        # dp axis (stage replicas) and the model axes above
        # (dp x pp x mp in one Program)
        self.pipeline = False
        self.pipeline_cut_list = None
        self.pipeline_num_microbatches = 1


class Collective(Fleet):
    def __init__(self):
        super().__init__("collective")
        self._main_program = None
        self._compiled_program = None
        self._loss = None

    def distributed_optimizer(self, optimizer, strategy=None):
        self._optimizer = CollectiveOptimizer(optimizer, strategy, self)
        return self._optimizer

    def init_worker(self):
        pass

    def init_server(self, model_dir=None):
        raise NotImplementedError(
            "Collective mode has no servers; use the transpiler PS mode")

    def run_server(self):
        raise NotImplementedError(
            "Collective mode has no servers; use the transpiler PS mode")

    def stop_worker(self):
        pass

    def save_inference_model(self, executor, dirname, feeded_var_names,
                             target_vars, main_program=None,
                             export_for_deployment=True):
        from .... import io

        io.save_inference_model(dirname, feeded_var_names, target_vars,
                                executor, main_program or self._main_program)

    def save_persistables(self, executor, dirname, main_program=None):
        from .... import io

        io.save_persistables(executor, dirname,
                             main_program or self._main_program)

    @property
    def main_program(self):
        """The mesh-executable program (reference: fleet.main_program is
        the compiled data-parallel program)."""
        return self._compiled_program or self._main_program


class CollectiveOptimizer(DistributedOptimizer):
    def __init__(self, optimizer, strategy=None, fleet_instance=None):
        super().__init__(optimizer, strategy or DistributedStrategy())
        self._fleet = fleet_instance

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from ....parallel.transpiler import (apply_expert_parallel,
                                             apply_sequence_parallel,
                                             apply_sharded_embedding,
                                             insert_allreduce_ops,
                                             insert_local_sgd_ops,
                                             shard_optimizer_state)

        opt = self._optimizer
        strategy = self._strategy
        program = loss.block.program
        # hybrid rewrites run BEFORE backward generation so
        # append_backward differentiates through the collective ops
        # (auto-VJP), not the dense originals
        if getattr(strategy, "sharded_embedding", False):
            from .... import framework as _fw

            apply_sharded_embedding(
                program, "mp", int(strategy.mp_degree or 0),
                startup_program=(startup_program
                                 or _fw.default_startup_program()))
        if getattr(strategy, "sequence_parallel", False):
            apply_sequence_parallel(
                program, "sp", int(strategy.sp_degree or 0),
                feed_specs=getattr(strategy, "feed_shard_specs", None))
        if getattr(strategy, "expert_parallel", False):
            apply_expert_parallel(program, "ep",
                                  int(strategy.ep_degree or 1))
        if getattr(strategy, "use_amp", False):
            from ....contrib import mixed_precision as mp

            opt = mp.decorate(opt)
        if getattr(strategy, "forward_recompute", False):
            from ....optimizer import RecomputeOptimizer

            opt = RecomputeOptimizer(opt)
            opt._set_checkpoints(strategy.recompute_checkpoints)
        if getattr(strategy, "pipeline", False):
            from ....optimizer import PipelineOptimizer

            opt = PipelineOptimizer(
                opt, cut_list=strategy.pipeline_cut_list,
                num_microbatches=int(
                    strategy.pipeline_num_microbatches or 1))
        optimize_ops, params_grads = opt.minimize(
            loss, startup_program, parameter_list, no_grad_set)
        shard_optimizer_state(program)

        nranks = self._fleet.worker_num() if self._fleet else 1
        if nranks > 1:
            # skip only grads sharded over a DATA axis (their collective
            # transposes already total every shard) — a grad sharded
            # over an orthogonal model axis still needs the dp allreduce
            skip_axes = getattr(program, "_allreduce_skip_grads",
                                None) or {}
            data_axes = set(getattr(program, "_data_axes", None)
                            or ("dp",))
            insert_allreduce_ops(
                program, nranks,
                skip_grads={g for g, axes in skip_axes.items()
                            if set(axes) & data_axes})
            if getattr(strategy, "use_local_sgd", False):
                insert_local_sgd_ops(program, nranks,
                                     strategy.local_sgd_k_steps)
        if self._fleet is not None:
            self._fleet._main_program = program
            self._fleet._loss = loss
            self._fleet._compiled_program = CompiledProgram(
                program).with_data_parallel(
                    loss_name=loss.name,
                    build_strategy=strategy.build_strategy,
                    exec_strategy=strategy.exec_strategy)
        return optimize_ops, params_grads


fleet = Collective()
