"""VarBase / ParamBase — eager tensors.

Parity: /root/reference/paddle/fluid/imperative/layer.h (VarBase),
variable_wrapper.h, and the pybind surface imperative.cc. A VarBase wraps
a jax.Array; autograd metadata (`_grad_node`) links it to the tape record
that produced it (tracer.py).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..core import dtypes as _dt
from ..utils import unique_name

__all__ = ["VarBase", "ParamBase"]


class VarBase:
    def __init__(self, value=None, name=None, stop_gradient=True,
                 persistable=False, zero_copy=False, dtype=None):
        import jax.numpy as jnp

        if value is not None and not hasattr(value, "dtype"):
            value = np.asarray(value)
        if isinstance(value, np.ndarray):
            if dtype is not None:
                value = value.astype(_dt.to_numpy_dtype(dtype))
            value = jnp.asarray(value)
        self._array = value
        self.name = name or unique_name.generate("generated_var")
        self.stop_gradient = stop_gradient
        self.persistable = persistable
        self._grad_node = None  # tape record that produced this var
        self._grad: Optional[object] = None  # accumulated gradient array

    # -- data -------------------------------------------------------------
    @property
    def array(self):
        return self._array

    def numpy(self):
        return np.asarray(self._array)

    def __array__(self, dtype=None):
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    @property
    def shape(self):
        return tuple(self._array.shape) if self._array is not None else None

    @property
    def dtype(self):
        return _dt.convert_dtype(self._array.dtype)

    @property
    def ndim(self):
        return self._array.ndim

    def detach(self):
        v = VarBase(self._array, name=self.name + ".detached",
                    stop_gradient=True)
        return v

    def clone(self):
        return VarBase(self._array, stop_gradient=self.stop_gradient)

    def astype(self, dtype):
        from .tracer import current_tracer

        return current_tracer().trace_op(
            "cast", {"X": [self]}, {},
            {"in_dtype": _dt.dtype_to_enum(self.dtype),
             "out_dtype": _dt.dtype_to_enum(dtype)})["Out"][0]

    # -- autograd ---------------------------------------------------------
    def backward(self, backward_strategy=None, retain_graph=False):
        from .tracer import current_tracer

        current_tracer().engine.backward(self, retain_graph=retain_graph)

    def gradient(self):
        if self._grad is None:
            return None
        return np.asarray(self._grad)

    @property
    def grad(self):
        return self._grad

    def clear_gradient(self):
        self._grad = None

    def set_value(self, value):
        import jax.numpy as jnp

        if isinstance(value, VarBase):
            value = value._array
        elif isinstance(value, np.ndarray):
            value = jnp.asarray(value)
        self._array = value

    # -- python niceties --------------------------------------------------
    def __len__(self):
        return int(self._array.shape[0])

    def __float__(self):
        return float(np.asarray(self._array).reshape(()))

    def __repr__(self):
        return "VarBase(name=%s, shape=%s, dtype=%s, stop_gradient=%s)\n%s" % (
            self.name, self.shape, self.dtype, self.stop_gradient,
            np.asarray(self._array) if self._array is not None else None)

    def __getitem__(self, idx):
        from .tracer import current_tracer

        # slice through the tracer so gradients flow
        arr = self._array
        sliced = arr[idx]
        out = VarBase(sliced, stop_gradient=self.stop_gradient)
        if not self.stop_gradient:
            tracer = current_tracer()
            if tracer is not None:
                out = tracer.trace_getitem(self, idx)
        return out


class ParamBase(VarBase):
    def __init__(self, value=None, name=None, trainable=True, **kw):
        super().__init__(value, name=name, stop_gradient=not trainable,
                         persistable=True)
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.is_distributed = False

    @classmethod
    def create(cls, name, shape, dtype, initializer, trainable=True):
        """Materialize a parameter eagerly by running the initializer's op
        through a throwaway one-op program."""
        import numpy as np

        from .. import framework
        from ..core import CoreExecutor, Scope
        from ..core.place import _current_expected_place_default

        prog = framework.Program()
        block = prog.global_block()
        v = block.create_var(name="p", shape=list(shape),
                             dtype=_dt.convert_dtype(dtype), persistable=True)
        initializer(v, block)
        scope = Scope()
        core = CoreExecutor(_current_expected_place_default())
        vals = core.run_program(prog, scope, fetch_list=["p"],
                                return_numpy=False)
        p = cls(vals[0].array, name=name, trainable=trainable)
        return p
