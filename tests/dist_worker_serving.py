"""Supervised serving replica for the fleet chaos drill.

Run under ``paddle_tpu.distributed.launch --serving_script=<this>``:
builds a tiny DETERMINISTIC MLP (fixed weights — every replica serves
the identical function, so a hedged duplicate answered by a different
replica returns the same bytes), saves/loads it through the REAL
inference path (``save_inference_model`` -> ``AnalysisConfig`` ->
``create_paddle_predictor``), and serves it with a ``ServingEngine`` +
HTTP front on ``$PADDLE_SERVING_ENDPOINT``.

Drill hooks (env):

- ``SERVING_DIE_REPLICA`` / ``SERVING_DIE_AFTER`` — the named replica
  index SIGKILLs ITSELF (no cleanup, no drain — the real failure mode)
  after serving that many ``/predict`` requests, but only on its first
  incarnation (``PADDLE_RESTART_COUNT=0``): the supervisor relaunches
  it and the relaunched incarnation must rejoin the fleet and serve.
- ``SERVING_REPLICA_DELAY_MS`` — artificial per-dispatch latency, so
  overload/hedge phases are deterministic on arbitrarily fast hosts.

The driver side of the drill imports ``build_model_dir`` to build the
SAME model locally and verify fleet responses value-for-value.
"""
from __future__ import annotations

import os
import signal
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

DIM = 16
HIDDEN = 32
CLASSES = 4


def build_model_dir(tmpdir: str):
    """Save the deterministic MLP into ``tmpdir`` through the real
    inference-model path; returns the output var name. Weights are a
    fixed function of a seed, NOT of initializer state — every process
    that calls this builds bit-identical parameters."""
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data(name="x", shape=[-1, DIM], dtype="float32")
        h = fluid.layers.fc(x, HIDDEN, act="relu")
        pred = fluid.layers.fc(h, CLASSES, act="softmax")
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        # overwrite every persistable with a seed-derived value: the
        # served function must be identical across replicas AND in the
        # driver's local reference copy
        rng = np.random.RandomState(0xC0FFEE)
        for var in sorted(main.global_block().all_parameters,
                          key=lambda v: v.name):
            t = scope.find_var(var.name).get_tensor()
            shape = np.asarray(t).shape
            t.set(rng.uniform(-0.5, 0.5, size=shape).astype("float32"),
                  fluid.CPUPlace())
        fluid.io.save_inference_model(tmpdir, ["x"], [pred], exe,
                                      main_program=main)
    return pred.name


def make_predictor(tmpdir: str):
    from paddle_tpu.inference import (AnalysisConfig,
                                      create_paddle_predictor)

    config = AnalysisConfig(tmpdir)
    config.disable_gpu()
    return create_paddle_predictor(config)


class _CountingPredictor:
    """Wraps the real predictor: per-dispatch drill delay + a request
    counter armed to SIGKILL this process mid-flight."""

    def __init__(self, inner, delay_s: float, die_after: int):
        self._inner = inner
        self._delay = delay_s
        self._die_after = die_after  # 0 = never
        self._served = 0
        self._lock = threading.Lock()
        # the engine derives its warmup sample feed from the
        # predictor's program — without this proxy, warmup silently
        # no-ops and the first live request per bucket eats a compile
        self._program = getattr(inner, "_program", None)

    def get_input_names(self):
        return self._inner.get_input_names()

    def run(self, feed):
        if self._delay:
            time.sleep(self._delay)
        out = self._inner.run(feed)
        if self._die_after:
            with self._lock:
                self._served += 1
                boom = self._served >= self._die_after
            if boom:
                # the drill's replica death: SIGKILL mid-flight, with
                # co-batched requests in the engine and the HTTP reply
                # unsent — exactly what a machine loss looks like
                os.kill(os.getpid(), signal.SIGKILL)
        return out


def main() -> int:
    import tempfile

    from paddle_tpu import serving

    endpoint = os.environ.get("PADDLE_SERVING_ENDPOINT", "127.0.0.1:8200")
    index = int(os.environ.get("PADDLE_SERVING_REPLICA_INDEX", "0") or 0)
    restart = int(os.environ.get("PADDLE_RESTART_COUNT", "0") or 0)
    delay_ms = float(os.environ.get("SERVING_REPLICA_DELAY_MS", "0") or 0)
    die_replica = int(os.environ.get("SERVING_DIE_REPLICA", "-1") or -1)
    die_after = int(os.environ.get("SERVING_DIE_AFTER", "0") or 0)
    if index != die_replica or restart > 0:
        die_after = 0  # only the named replica's FIRST incarnation dies

    host, _, port = endpoint.rpartition(":")
    with tempfile.TemporaryDirectory(prefix="serving_rep_") as d:
        build_model_dir(d)
        predictor = _CountingPredictor(make_predictor(d), delay_ms / 1e3,
                                       die_after)
        engine = serving.ServingEngine(
            predictor,
            serving.ServingConfig(
                max_batch_size=int(os.environ.get(
                    "SERVING_MAX_BATCH", "8")),
                batch_timeout_ms=float(os.environ.get(
                    "SERVING_BATCH_TIMEOUT_MS", "2")),
                max_queue=int(os.environ.get("SERVING_MAX_QUEUE", "64")),
                num_workers=2)).start()
        server = serving.ServingHTTPServer(engine, host or "127.0.0.1",
                                           int(port))
        thread = threading.Thread(target=server.serve_forever,
                                  name="replica-http", daemon=True)
        thread.start()
        print("[replica %d r%d] serving %s (die_after=%d delay=%gms)"
              % (index, restart, endpoint, die_after, delay_ms),
              flush=True)
        stop = threading.Event()
        signal.signal(signal.SIGTERM, lambda *a: stop.set())
        try:
            while not stop.wait(0.2):
                pass
        finally:
            engine.stop()
            server.shutdown()
            server.server_close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
