"""Per-op device microbenchmark harness.

Parity: /root/reference/paddle/fluid/operators/benchmark/op_tester.cc
(config-driven single-op timing) and operators/jit/benchmark.cc — the
producer for BASELINE.md's "track per-op TPU timings" row.

Usage:
    python -m paddle_tpu.tools.op_bench                 # hot-op table
    python -m paddle_tpu.tools.op_bench --op=conv2d     # one op
    python -m paddle_tpu.tools.op_bench --repeat=50 --json

Each case builds the single op as a jitted XLA callable on the default
device (the TPU under the tunnel, CPU otherwise), runs `repeat` timed
iterations after warmup, and reports the per-call wall time with a
device sync per timing window (one d2h fetch — the only hard sync the
tunnel honors; see BASELINE.md protocol).
"""
from __future__ import annotations

import argparse
import json
import time

import numpy as np

# (name, op_type, input builder -> {slot: array}, attrs)
# the 20 hottest op configs across the five north-star models
_F32 = "float32"


def _rng():
    return np.random.RandomState(0)


def _cases():
    r = _rng()
    B = 64
    return [
        ("matmul_512", "matmul",
         {"X": r.randn(B, 512).astype(_F32),
          "Y": r.randn(512, 512).astype(_F32)},
         {"transpose_X": False, "transpose_Y": False, "alpha": 1.0}),
        ("matmul_bert_ffn", "matmul",
         {"X": r.randn(32 * 128, 768).astype(_F32),
          "Y": r.randn(768, 3072).astype(_F32)},
         {"transpose_X": False, "transpose_Y": False, "alpha": 1.0}),
        ("mul_fc", "mul",
         {"X": r.randn(B, 2048).astype(_F32),
          "Y": r.randn(2048, 1000).astype(_F32)},
         {"x_num_col_dims": 1, "y_num_col_dims": 1}),
        ("conv2d_3x3_s1", "conv2d",
         {"Input": r.randn(B, 64, 56, 56).astype(_F32),
          "Filter": r.randn(64, 64, 3, 3).astype(_F32)},
         {"strides": [1, 1], "paddings": [1, 1], "dilations": [1, 1],
          "groups": 1}),
        ("conv2d_1x1", "conv2d",
         {"Input": r.randn(B, 256, 56, 56).astype(_F32),
          "Filter": r.randn(64, 256, 1, 1).astype(_F32)},
         {"strides": [1, 1], "paddings": [0, 0], "dilations": [1, 1],
          "groups": 1}),
        ("conv2d_7x7_s2", "conv2d",
         {"Input": r.randn(B, 3, 224, 224).astype(_F32),
          "Filter": r.randn(64, 3, 7, 7).astype(_F32)},
         {"strides": [2, 2], "paddings": [3, 3], "dilations": [1, 1],
          "groups": 1}),
        ("batch_norm", "batch_norm",
         {"X": r.randn(B, 64, 56, 56).astype(_F32),
          "Scale": r.rand(64).astype(_F32),
          "Bias": r.rand(64).astype(_F32),
          "Mean": np.zeros(64, _F32),
          "Variance": np.ones(64, _F32)},
         {"epsilon": 1e-5, "momentum": 0.9, "is_test": True}),
        ("layer_norm", "layer_norm",
         {"X": r.randn(32 * 128, 768).astype(_F32),
          "Scale": r.rand(768).astype(_F32),
          "Bias": r.rand(768).astype(_F32)},
         {"epsilon": 1e-5, "begin_norm_axis": 1}),
        ("softmax_seq", "softmax",
         {"X": r.randn(32 * 12 * 128, 128).astype(_F32)}, {"axis": -1}),
        ("softmax_with_ce", "softmax_with_cross_entropy",
         {"Logits": r.randn(B, 1000).astype(_F32),
          "Label": r.randint(0, 1000, (B, 1)).astype("int64")},
         {"soft_label": False}),
        ("relu_large", "relu",
         {"X": r.randn(B, 256, 56, 56).astype(_F32)}, {}),
        ("gelu", "gelu",
         {"X": r.randn(32 * 128, 3072).astype(_F32)}, {}),
        ("elementwise_add_bcast", "elementwise_add",
         {"X": r.randn(B, 256, 56, 56).astype(_F32),
          "Y": r.randn(256).astype(_F32)}, {"axis": 1}),
        ("lookup_table", "lookup_table_v2",
         {"W": r.randn(30522, 768).astype(_F32),
          "Ids": r.randint(0, 30522, (32, 128)).astype("int64")},
         {"padding_idx": -1}),
        ("dropout", "dropout",
         {"X": r.randn(32 * 128, 768).astype(_F32)},
         {"dropout_prob": 0.1, "is_test": False,
          "dropout_implementation": "upscale_in_train", "seed": 7}),
        ("reduce_mean", "reduce_mean",
         {"X": r.randn(B, 256, 56, 56).astype(_F32)},
         {"dim": [2, 3], "keep_dim": False}),
        ("transpose_attn", "transpose2",
         {"X": r.randn(32, 128, 12, 64).astype(_F32)},
         {"axis": [0, 2, 1, 3]}),
        ("pool2d_avg_global", "pool2d",
         {"X": r.randn(B, 2048, 7, 7).astype(_F32)},
         {"pooling_type": "avg", "global_pooling": True,
          "ksize": [1, 1]}),
        ("adam_update", "adam",
         {"Param": r.randn(2048, 1000).astype(_F32),
          "Grad": r.randn(2048, 1000).astype(_F32),
          "LearningRate": np.array([1e-3], _F32),
          "Moment1": np.zeros((2048, 1000), _F32),
          "Moment2": np.zeros((2048, 1000), _F32),
          "Beta1Pow": np.array([0.9], _F32),
          "Beta2Pow": np.array([0.999], _F32)},
         {"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8}),
        ("topk", "top_k",
         {"X": r.randn(B, 1000).astype(_F32)}, {"k": 5}),
    ]


def bench_op(op_type, inputs, attrs, repeat=30, warmup=5):
    """Time one op as a jitted callable; returns (mean_us, result)."""
    import jax
    import jax.numpy as jnp

    from ..core.registry import (BOUND_OUTPUTS_ATTR, RNG_SEED_ATTR,
                                 OpInfoMap)

    info = OpInfoMap.instance().get(op_type)
    attrs = dict(attrs)
    attrs[BOUND_OUTPUTS_ATTR] = tuple(s.name for s in info.outputs)
    dev_inputs = {k: jax.device_put(jnp.asarray(v))
                  for k, v in inputs.items()}
    if info.needs_rng:
        dev_inputs[RNG_SEED_ATTR] = jnp.uint32(attrs.get("seed", 7))

    def call(ins):
        outs = info.fn(ins, attrs)
        return [v for v in outs.values() if v is not None]

    fn = jax.jit(call)
    outs = fn(dev_inputs)
    for _ in range(warmup):
        outs = fn(dev_inputs)
    np.asarray(outs[0]).ravel()[:1]  # sync point
    t0 = time.perf_counter()
    for _ in range(repeat):
        outs = fn(dev_inputs)
    np.asarray(outs[0]).ravel()[:1]  # d2h = the hard sync
    dt = (time.perf_counter() - t0) / repeat
    return dt * 1e6


def main(argv=None):
    p = argparse.ArgumentParser("paddle_tpu.tools.op_bench")
    p.add_argument("--op", default=None,
                   help="bench only cases whose op type matches")
    p.add_argument("--repeat", type=int, default=30)
    p.add_argument("--json", action="store_true")
    args = p.parse_args(argv)

    import jax

    device = str(jax.devices()[0])
    rows = []
    for name, op_type, inputs, attrs in _cases():
        if args.op and args.op != op_type:
            continue
        try:
            us = bench_op(op_type, inputs, attrs, repeat=args.repeat)
            rows.append({"case": name, "op": op_type,
                         "mean_us": round(us, 1)})
        except Exception as e:  # keep the table going
            rows.append({"case": name, "op": op_type,
                         "error": repr(e)[:120]})
    if args.json:
        print(json.dumps({"device": device, "repeat": args.repeat,
                          "cases": rows}))
    else:
        print("device: %s   repeat: %d" % (device, args.repeat))
        print("%-22s %-28s %12s" % ("case", "op", "mean_us"))
        for r in rows:
            print("%-22s %-28s %12s"
                  % (r["case"], r["op"],
                     r.get("mean_us", "ERR: " + r.get("error", "?"))))
    return rows


if __name__ == "__main__":
    main()
