"""fluid.unique_name public API (re-export of utils.unique_name)."""
from .utils.unique_name import generate, guard, switch  # noqa: F401
