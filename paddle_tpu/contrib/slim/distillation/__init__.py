"""Knowledge distillation over Programs.

Parity: /root/reference/python/paddle/fluid/contrib/slim/distillation/
distiller.py:25 (L2Distiller), :103 (FSPDistiller), :200
(SoftLabelDistiller) and the graph-merge the reference's GraphWrapper
provides. TPU-native formulation: ``merge_programs`` clones the
teacher's inference ops into the student Program under a name prefix
with gradients stopped (the teacher is a frozen feature extractor
compiled into the SAME XLA program — one fused step, no second
executor); each distiller then appends its loss with plain layers.
"""
from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

__all__ = ["merge_programs", "L2Distiller", "SoftLabelDistiller",
           "FSPDistiller", "fsp_matrix"]

TEACHER_PREFIX = "teacher_"


def _teacher_var(block, name):
    """Prefixed (merged) teacher var, else the bare name. Explicit None
    checks — `a or b` would call Variable.__bool__, which raises at
    graph-build time by design."""
    v = block._find_var_recursive(TEACHER_PREFIX + name)
    if v is None:
        v = block._find_var_recursive(name)
    return v


def merge_programs(student_program, teacher_program, scope,
                   teacher_scope=None, prefix=TEACHER_PREFIX,
                   feed_map=None):
    """Append the teacher's ops/vars into ``student_program`` with
    ``prefix`` on every var name; teacher params are copied into
    ``scope`` under the prefixed names and frozen (stop_gradient).
    ``feed_map`` maps teacher feed var -> student var so both nets read
    the same inputs. Returns {teacher var name -> merged name}."""
    import jax.numpy as jnp

    feed_map = feed_map or {}
    s_block = student_program.global_block()
    t_block = teacher_program.global_block()
    renames: Dict[str, str] = dict(feed_map)
    for name, var in t_block.vars.items():
        if name in feed_map:
            continue
        new = prefix + name
        renames[name] = new
        if not s_block.has_var_local(new):
            v = s_block.create_var(
                name=new, shape=tuple(var.shape) if var.shape else None,
                dtype=var.dtype,
                persistable=getattr(var, "persistable", False))
            v.stop_gradient = True
    src_scope = teacher_scope or scope
    for name, var in t_block.vars.items():
        if getattr(var, "persistable", False):
            sv = src_scope.find_var(name)
            if sv is not None and sv.is_initialized():
                scope.var(renames[name]).get_tensor()._array = \
                    jnp.asarray(np.asarray(sv.raw().array))
    for op in t_block.ops:
        ins = {slot: [renames.get(n, prefix + n) for n in names]
               for slot, names in op.inputs.items()}
        outs = {slot: [renames.get(n, prefix + n) for n in names]
                for slot, names in op.outputs.items()}
        s_block.append_op(op.type, inputs=ins, outputs=outs,
                          attrs=dict(op.attrs), infer_shape=False)
    return renames


def fsp_matrix(a, b):
    """Flow-of-solution-procedure matrix of two NCHW feature maps with
    equal spatial dims (reference fsp op, distiller.py:103):
    out[n, i, j] = mean over pixels of a[n, i, :, :] * b[n, j, :, :]."""
    from .... import layers

    N, C1 = int(a.shape[0]), int(a.shape[1])
    C2 = int(b.shape[1])
    HW = int(np.prod(a.shape[2:]))
    a2 = layers.reshape(a, [N, C1, HW])
    b2 = layers.reshape(b, [N, C2, HW])
    prod = layers.matmul(a2, layers.transpose(b2, [0, 2, 1]))
    return layers.scale(prod, scale=1.0 / HW)


class L2Distiller:
    """L2 loss between a student and a (merged) teacher feature map
    (reference distiller.py:25)."""

    def __init__(self, student_feature_map, teacher_feature_map,
                 distillation_loss_weight=1.0):
        self.student_feature_map = student_feature_map
        self.teacher_feature_map = teacher_feature_map
        self.weight = distillation_loss_weight

    def distiller_loss(self, program, student_loss=None):
        from .... import framework, layers

        block = program.global_block()
        with framework.program_guard(program):
            s = block._find_var_recursive(self.student_feature_map)
            t = _teacher_var(block, self.teacher_feature_map)
            l2 = layers.reduce_mean(layers.square(
                layers.elementwise_sub(s, t)))
            loss = layers.scale(l2, scale=float(self.weight))
            if student_loss is not None:
                loss = layers.elementwise_add(loss, student_loss)
        return loss


class SoftLabelDistiller:
    """Cross entropy of softened logits (reference distiller.py:200):
    softmax(teacher/T2) as the soft target for softmax(student/T1)."""

    def __init__(self, student_feature_map, teacher_feature_map,
                 student_temperature=1.0, teacher_temperature=1.0,
                 distillation_loss_weight=1.0):
        self.student_feature_map = student_feature_map
        self.teacher_feature_map = teacher_feature_map
        self.student_temperature = student_temperature
        self.teacher_temperature = teacher_temperature
        self.weight = distillation_loss_weight

    def distiller_loss(self, program, student_loss=None):
        from .... import framework, layers

        block = program.global_block()
        with framework.program_guard(program):
            s = block._find_var_recursive(self.student_feature_map)
            t = _teacher_var(block, self.teacher_feature_map)
            s_soft = layers.softmax(layers.scale(
                s, scale=1.0 / self.student_temperature))
            t_soft = layers.softmax(layers.scale(
                t, scale=1.0 / self.teacher_temperature))
            ce = layers.cross_entropy(s_soft, t_soft, soft_label=True)
            loss = layers.scale(layers.reduce_mean(ce),
                                scale=float(self.weight))
            if student_loss is not None:
                loss = layers.elementwise_add(loss, student_loss)
        return loss


class FSPDistiller:
    """FSP-matrix loss over (start, end) feature pairs (reference
    distiller.py:103)."""

    def __init__(self, student_pairs, teacher_pairs,
                 distillation_loss_weight=1.0):
        self.student_pairs = student_pairs
        self.teacher_pairs = teacher_pairs
        self.weight = distillation_loss_weight

    def distiller_loss(self, program, student_loss=None):
        from .... import framework, layers

        block = program.global_block()
        with framework.program_guard(program):
            losses = []
            for (s0, s1), (t0, t1) in zip(self.student_pairs,
                                          self.teacher_pairs):
                sv0 = block._find_var_recursive(s0)
                sv1 = block._find_var_recursive(s1)
                tv0 = _teacher_var(block, t0)
                tv1 = _teacher_var(block, t1)
                diff = layers.elementwise_sub(fsp_matrix(sv0, sv1),
                                              fsp_matrix(tv0, tv1))
                losses.append(layers.reduce_mean(layers.square(diff)))
            total = losses[0]
            for l in losses[1:]:
                total = layers.elementwise_add(total, l)
            loss = layers.scale(total, scale=float(self.weight))
            if student_loss is not None:
                loss = layers.elementwise_add(loss, student_loss)
        return loss
