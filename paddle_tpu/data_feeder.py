"""DataFeeder — converts python samples into feed dicts.

Parity: /root/reference/python/paddle/fluid/data_feeder.py.
"""
from __future__ import annotations

from typing import List

import numpy as np

from . import framework
from .core import dtypes as _dt
from .core.tensor import LoDTensor

__all__ = ["DataFeeder", "convert_dtype", "check_variable_and_dtype"]

convert_dtype = _dt.convert_dtype


def check_variable_and_dtype(input, input_name, expected_dtype, op_name):
    if not isinstance(input, framework.Variable):
        raise TypeError(
            "The input %s of %s must be Variable, got %s"
            % (input_name, op_name, type(input)))
    if _dt.convert_dtype(input.dtype) not in expected_dtype:
        raise TypeError(
            "The dtype of %s of %s must be one of %s, got %s"
            % (input_name, op_name, expected_dtype, input.dtype))


def check_type(input, input_name, expected_type, op_name):
    if not isinstance(input, expected_type):
        raise TypeError("The type of %s of %s must be %s, got %s"
                        % (input_name, op_name, expected_type, type(input)))


def check_dtype(dtype, input_name, expected_dtype, op_name):
    if _dt.convert_dtype(dtype) not in expected_dtype:
        raise TypeError("dtype of %s of %s must be one of %s, got %s"
                        % (input_name, op_name, expected_dtype, dtype))


class DataFeeder:
    def __init__(self, feed_list, place=None, program=None):
        self.feed_names: List[str] = []
        self.feed_dtypes = []
        self.feed_shapes = []
        self.feed_lod_level = []
        program = program or framework.default_main_program()
        for v in feed_list:
            if isinstance(v, str):
                v = program.global_block().var(v)
            self.feed_names.append(v.name)
            self.feed_dtypes.append(_dt.to_numpy_dtype(v.dtype))
            self.feed_shapes.append(v.shape)
            self.feed_lod_level.append(v.lod_level)
        self.place = place

    def feed(self, iterable):
        rows = list(iterable)
        feed = {}
        for i, name in enumerate(self.feed_names):
            col = [row[i] for row in rows]
            if self.feed_lod_level[i]:
                # variable-length samples -> concat + LoD offsets
                lengths = [np.asarray(c).shape[0] for c in col]
                flat = np.concatenate(
                    [np.asarray(c, dtype=self.feed_dtypes[i]).reshape(
                        len(c) if np.asarray(c).ndim == 1 else -1,
                        *np.asarray(c).shape[1:]) for c in col], axis=0)
                offsets = [0]
                for l in lengths:
                    offsets.append(offsets[-1] + l)
                t = LoDTensor()
                t.set(flat)
                t.set_lod([offsets])
                feed[name] = t
            else:
                arr = np.asarray(col, dtype=self.feed_dtypes[i])
                shape = self.feed_shapes[i]
                if shape is not None and len(shape) == arr.ndim + 1:
                    pass  # batch of scalars already stacked
                elif shape is not None and arr.ndim == len(shape) and \
                        all(s == -1 or s == d for s, d in
                            zip(shape[1:], arr.shape[1:])):
                    pass
                elif shape is not None:
                    want = [d for d in shape if d != -1]
                    arr = arr.reshape([len(rows)] + list(shape[1:])) \
                        if -1 in shape else arr.reshape(shape)
                feed[name] = arr
        return feed
