"""Plain MLP — the book MNIST softmax/multilayer models.

Parity: /root/reference/python/paddle/fluid/tests/book/
test_recognize_digits.py:38 (multilayer_perceptron).
"""
from __future__ import annotations

from .. import layers


def mlp(x, hidden_sizes=(512, 512), class_dim=10, act="relu"):
    for h in hidden_sizes:
        x = layers.fc(x, size=h, act=act)
    return layers.fc(x, size=class_dim, act="softmax")
