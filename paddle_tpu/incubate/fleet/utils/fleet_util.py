"""Fleet job utilities.

Parity: /root/reference/python/paddle/fluid/incubate/fleet/utils/
fleet_util.py:53 (FleetUtil — rank-0 logging, global AUC over
distributed stat buckets, model save/load around fluid.io, online
pass-interval planning). TPU-native reduction: the cross-worker
allreduce of the AUC buckets rides jax collectives when a multi-process
mesh is initialized (jax.distributed), and is the identity in single
process — the reference uses the role-maker's MPI all_reduce the same
way.
"""
from __future__ import annotations

import logging
import os
import shutil
import tempfile
from typing import List, Optional

import numpy as np

__all__ = ["FleetUtil"]

_logger = logging.getLogger("FleetUtil")


class FleetUtil:
    """(reference fleet_util.py:53)"""

    def __init__(self, mode: str = "pslib", role_maker=None):
        self.mode = mode
        self._role_maker = role_maker

    # -- rank-0 logging ---------------------------------------------------
    def _worker_index(self) -> int:
        if self._role_maker is not None:
            return int(self._role_maker.worker_index())
        try:
            import jax

            return int(jax.process_index())
        except Exception:
            return 0

    def rank0_print(self, s: str) -> None:
        if self._worker_index() == 0:
            print(s, flush=True)

    def rank0_info(self, s: str) -> None:
        if self._worker_index() == 0:
            _logger.info(s)

    def rank0_error(self, s: str) -> None:
        if self._worker_index() == 0:
            _logger.error(s)

    # -- metric helpers ---------------------------------------------------
    def set_zero(self, var_name, scope=None, param_type="int64"):
        """Zero a metric accumulator var (reference fleet_util.py:121)."""
        import jax.numpy as jnp

        import paddle_tpu as fluid

        scope = scope or fluid.global_scope()
        var = scope.find_var(var_name)
        if var is None or not var.is_initialized():
            return
        arr = np.asarray(var.raw().array)
        scope.var(var_name).get_tensor()._array = jnp.zeros(
            arr.shape, dtype=param_type)

    def _all_reduce(self, arr: np.ndarray) -> np.ndarray:
        """Sum across workers; identity in single-process mode."""
        try:
            import jax

            if jax.process_count() > 1:
                from jax.experimental.multihost_utils import (
                    process_allgather)

                return np.sum(process_allgather(arr), axis=0)
        except Exception:
            pass
        return arr

    def get_global_auc(self, scope=None, stat_pos="_generated_var_2",
                       stat_neg="_generated_var_3"):
        """Global AUC from the auc op's pos/neg bucket stats summed over
        all workers (reference fleet_util.py:186 — trapezoid over the
        bucketed ROC, walked from the highest-score bucket down)."""
        import paddle_tpu as fluid

        scope = scope or fluid.global_scope()
        pv, nv = scope.find_var(stat_pos), scope.find_var(stat_neg)
        if pv is None or nv is None or not pv.is_initialized() \
                or not nv.is_initialized():
            self.rank0_print("not found auc bucket")
            return None
        global_pos = self._all_reduce(
            np.asarray(pv.raw().array, dtype=np.float64).reshape(1, -1))
        global_neg = self._all_reduce(
            np.asarray(nv.raw().array, dtype=np.float64).reshape(1, -1))

        num_bucket = global_pos.shape[1]
        area = pos = neg = 0.0
        total_ins_num = 0.0
        for i in range(num_bucket):
            index = num_bucket - 1 - i
            new_pos = pos + global_pos[0][index]
            total_ins_num += global_pos[0][index]
            new_neg = neg + global_neg[0][index]
            total_ins_num += global_neg[0][index]
            area += (new_neg - neg) * (pos + new_pos) / 2
            pos, neg = new_pos, new_neg
        if pos * neg == 0 or total_ins_num == 0:
            return 0.5
        return float(area / (pos * neg))

    def print_global_auc(self, scope=None, stat_pos="_generated_var_2",
                         stat_neg="_generated_var_3",
                         print_prefix=""):
        auc = self.get_global_auc(scope, stat_pos, stat_neg)
        self.rank0_print("%s global auc = %s" % (print_prefix, auc))
        return auc

    # -- model save/load around fluid.io ----------------------------------
    def save_paddle_inference_model(self, executor, scope, program,
                                    feeded_vars, target_vars,
                                    output_path, day, pass_id,
                                    hadoop_fs=None):
        """Save the inference model under the day/pass layout the
        reference's online pipeline uses (fleet_util.py:876), uploading
        via the fs client when given."""
        import paddle_tpu as fluid

        staging = tempfile.mkdtemp(prefix="dnn_plugin_")
        try:
            local_dir = os.path.join(staging, "model")
            with fluid.scope_guard(scope):
                fluid.io.save_inference_model(
                    local_dir,
                    [v if isinstance(v, str) else v.name
                     for v in feeded_vars],
                    target_vars, executor, main_program=program)
            dest = "%s/%s/%s/dnn_plugin" % (output_path, day, pass_id)
            fs = hadoop_fs or _default_fs()
            if not fs.makedirs(os.path.dirname(dest) or "."):
                raise IOError("makedirs failed for %r" % dest)
            if not fs.upload(dest, local_dir, overwrite=True):
                raise IOError("upload failed for %r" % dest)
            return dest
        finally:
            shutil.rmtree(staging, ignore_errors=True)

    def save_paddle_params(self, executor, scope, program, model_name,
                           output_path, day, pass_id, var_names,
                           hadoop_fs=None):
        """Persist selected params (fleet_util.py:965)."""
        import paddle_tpu as fluid

        staging = tempfile.mkdtemp(prefix="dnn_plugin_params_")
        try:
            local_dir = os.path.join(staging, "params")
            with fluid.scope_guard(scope):
                fluid.io.save_vars(
                    executor, local_dir, main_program=program,
                    vars=[program.global_block()._find_var_recursive(n)
                          for n in var_names])
            dest = "%s/%s/%s/%s" % (output_path, day, pass_id,
                                    model_name)
            fs = hadoop_fs or _default_fs()
            if not fs.makedirs(os.path.dirname(dest) or "."):
                raise IOError("makedirs failed for %r" % dest)
            if not fs.upload(dest, local_dir, overwrite=True):
                raise IOError("upload failed for %r" % dest)
            return dest
        finally:
            shutil.rmtree(staging, ignore_errors=True)

    def write_model_donefile(self, output_path, day, pass_id, xbox_base_key,
                             donefile_name="donefile.txt",
                             hadoop_fs=None):
        """Append the day/pass done record (fleet_util.py:362)."""
        if self._worker_index() != 0:
            return
        fs = hadoop_fs or _default_fs()
        model_path = "%s/%s/%s" % (output_path, day, pass_id)
        content = "%s\t%s\t%s\t%s\t%s" % (day, pass_id, xbox_base_key,
                                          model_path, int(pass_id) - 1)
        done = "%s/%s" % (output_path, donefile_name)
        prev = fs.cat(done) if fs.is_exist(done) else ""
        fd, tmp = tempfile.mkstemp(suffix=".donefile")
        try:
            with os.fdopen(fd, "w") as f:
                f.write((prev + "\n" if prev else "") + content + "\n")
            if not fs.makedirs(output_path):
                raise IOError("makedirs failed for %r" % output_path)
            if not fs.upload(done, tmp, overwrite=True):
                raise IOError("donefile upload failed for %r" % done)
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)
        return content

    def get_last_save_model(self, output_path,
                            donefile_name="donefile.txt",
                            hadoop_fs=None):
        """(day, pass_id, path) of the newest donefile record
        (fleet_util.py:1158); (-1, -1, "") when absent."""
        fs = hadoop_fs or _default_fs()
        done = "%s/%s" % (output_path, donefile_name)
        if not fs.is_exist(done):
            return -1, -1, ""
        lines = [l for l in fs.cat(done).splitlines() if l.strip()]
        if not lines:
            return -1, -1, ""
        cols = lines[-1].split("\t")
        return int(cols[0]), int(cols[1]), cols[3]

    # -- schedule planning -------------------------------------------------
    def get_online_pass_interval(self, days, hours, split_interval,
                                 split_per_pass,
                                 is_data_hourly_placed=False):
        """Partition a day's N-minute splits into training passes
        (reference fleet_util.py:1207). ``days``/``hours`` accept the
        brace-expansion strings the reference pipes through echo, or
        plain lists."""
        hours = _expand(hours)
        split_interval = int(split_interval)
        split_per_pass = int(split_per_pass)
        splits_per_day = 24 * 60 // split_interval
        left = int(hours[0])
        right = int(hours[-1])
        start = 0
        split_path = []
        for i in range(splits_per_day):
            h = start // 60
            m = start % 60
            if left <= h <= right:
                if is_data_hourly_placed:
                    split_path.append("%02d" % h)
                else:
                    split_path.append("%02d%02d" % (h, m))
            start += split_interval
        start = 0
        online_pass_interval = []
        while start < len(split_path):
            online_pass_interval.append(
                split_path[start:start + split_per_pass])
            start += split_per_pass
        return online_pass_interval


def _expand(spec):
    """'{0..23}' / '0 1 2' / list -> list of strings."""
    if isinstance(spec, (list, tuple)):
        return [str(s) for s in spec]
    s = str(spec).strip()
    if s.startswith("{") and ".." in s:
        a, b = s.strip("{}").split("..")
        return [str(i) for i in range(int(a), int(b) + 1)]
    return s.split()


def _default_fs():
    from ....core.fs import LocalFS

    return LocalFS()
