"""Tensor creation / shaping / indexing ops.

Parity with the corresponding files under
/root/reference/paddle/fluid/operators/: fill_constant_op.cc,
uniform_random_op.cc, gaussian_random_op.cc, truncated_gaussian_random_op.cc,
assign_op.cc, reshape_op.cc (reshape2 + XShape), transpose_op.cc, concat_op.cc,
split_op.cc, slice_op.cc, squeeze_op.cc, unsqueeze_op.cc, stack_op.cc,
expand_op.cc, gather_op.cc, scatter_op.cc, lookup_table_op.cc, one_hot_op.cc,
shape_op.cc, top_k_op.cc, arg_min_max_op_base.h, argsort_op.cc, pad_op.cc,
flatten_op.cc, fill_zeros_like_op.cc, fill_any_like_op.cc, assign_value_op.cc,
where_op (select) and where_index_op.cc, cast handled in math_ops.

RNG ops draw from a traced uint32 seed supplied by the executor
(registry.RNG_SEED_ATTR) so steps don't recompile; shape attrs are static,
which is exactly XLA's static-shape model.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtypes as _dt
from ..core.registry import RNG_SEED_ATTR, In, Out, register_host_op, register_op


# -- creation ---------------------------------------------------------------


@register_op(
    "fill_constant",
    inputs=[In("ShapeTensor", dispensable=True, no_grad=True),
            In("ValueTensor", dispensable=True, no_grad=True)],
    outputs=[Out("Out")],
    attrs={"shape": [], "dtype": 5, "value": 0.0, "force_cpu": False,
           "str_value": ""},
    grad=None,
)
def _fill_constant(ins, attrs):
    dt = _dt.to_numpy_dtype(attrs["dtype"])
    val = ins.get("ValueTensor")
    if val is None:
        sval = attrs.get("str_value", "")
        val = float(sval) if sval else attrs.get("value", 0.0)
        out = jnp.full(tuple(attrs["shape"]), val, dtype=dt)
    else:
        out = jnp.broadcast_to(val.reshape(()).astype(dt), tuple(attrs["shape"]))
    return {"Out": out}


@register_op(
    "fill_constant_batch_size_like",
    inputs=[In("Input", no_grad=True)],
    outputs=[Out("Out")],
    attrs={"shape": [], "dtype": 5, "value": 0.0, "input_dim_idx": 0,
           "output_dim_idx": 0, "force_cpu": False},
    grad=None,
)
def _fill_constant_bsl(ins, attrs):
    shape = list(attrs["shape"])
    shape[attrs.get("output_dim_idx", 0)] = ins["Input"].shape[
        attrs.get("input_dim_idx", 0)
    ]
    dt = _dt.to_numpy_dtype(attrs["dtype"])
    return {"Out": jnp.full(tuple(shape), attrs.get("value", 0.0), dtype=dt)}


@register_op(
    "uniform_random",
    inputs=[In("ShapeTensor", dispensable=True, no_grad=True)],
    outputs=[Out("Out")],
    attrs={"shape": [], "min": -1.0, "max": 1.0, "seed": 0, "dtype": 5},
    grad=None,
    needs_rng=True,
)
def _uniform_random(ins, attrs):
    dt = _dt.to_numpy_dtype(attrs["dtype"])
    key = jax.random.PRNGKey(ins[RNG_SEED_ATTR])
    return {
        "Out": jax.random.uniform(
            key,
            tuple(attrs["shape"]),
            dtype=jnp.float32,
            minval=attrs.get("min", -1.0),
            maxval=attrs.get("max", 1.0),
        ).astype(dt)
    }


@register_op(
    "gaussian_random",
    inputs=[],
    outputs=[Out("Out")],
    attrs={"shape": [], "mean": 0.0, "std": 1.0, "seed": 0, "dtype": 5},
    grad=None,
    needs_rng=True,
)
def _gaussian_random(ins, attrs):
    dt = _dt.to_numpy_dtype(attrs["dtype"])
    key = jax.random.PRNGKey(ins[RNG_SEED_ATTR])
    out = jax.random.normal(key, tuple(attrs["shape"]), dtype=jnp.float32)
    out = out * attrs.get("std", 1.0) + attrs.get("mean", 0.0)
    return {"Out": out.astype(dt)}


@register_op(
    "truncated_gaussian_random",
    inputs=[],
    outputs=[Out("Out")],
    attrs={"shape": [], "mean": 0.0, "std": 1.0, "seed": 0, "dtype": 5},
    grad=None,
    needs_rng=True,
)
def _truncated_gaussian_random(ins, attrs):
    dt = _dt.to_numpy_dtype(attrs["dtype"])
    key = jax.random.PRNGKey(ins[RNG_SEED_ATTR])
    out = jax.random.truncated_normal(key, -2.0, 2.0, tuple(attrs["shape"]))
    out = out * attrs.get("std", 1.0) + attrs.get("mean", 0.0)
    return {"Out": out.astype(dt)}


@register_op(
    "assign",
    inputs=[In("X")],
    outputs=[Out("Out")],
)
def _assign(ins, attrs):
    return {"Out": ins["X"]}


@register_op(
    "assign_value",
    inputs=[],
    outputs=[Out("Out")],
    attrs={"shape": [], "dtype": 5, "fp32_values": [], "int32_values": [],
           "int64_values": [], "bool_values": []},
    grad=None,
)
def _assign_value(ins, attrs):
    dt = _dt.to_numpy_dtype(attrs["dtype"])
    for k in ("fp32_values", "int32_values", "int64_values", "bool_values"):
        vals = attrs.get(k)
        if vals:
            return {"Out": jnp.asarray(np.array(vals), dtype=dt).reshape(
                tuple(attrs["shape"]))}
    return {"Out": jnp.zeros(tuple(attrs["shape"]), dtype=dt)}


@register_op(
    "fill_zeros_like",
    inputs=[In("X", no_grad=True)],
    outputs=[Out("Out")],
    grad=None,
)
def _fill_zeros_like(ins, attrs):
    return {"Out": jnp.zeros_like(ins["X"])}


@register_op(
    "fill_any_like",
    inputs=[In("X", no_grad=True)],
    outputs=[Out("Out")],
    attrs={"value": 0.0, "dtype": -1},
    grad=None,
)
def _fill_any_like(ins, attrs):
    x = ins["X"]
    dt = x.dtype if attrs.get("dtype", -1) == -1 else _dt.to_numpy_dtype(attrs["dtype"])
    return {"Out": jnp.full(x.shape, attrs.get("value", 0.0), dtype=dt)}


@register_op(
    "eye",
    inputs=[],
    outputs=[Out("Out")],
    attrs={"num_rows": 0, "num_columns": -1, "dtype": 5},
    grad=None,
)
def _eye(ins, attrs):
    n = attrs["num_rows"]
    m = attrs.get("num_columns", -1)
    m = n if m in (-1, 0) else m
    return {"Out": jnp.eye(n, m, dtype=_dt.to_numpy_dtype(attrs["dtype"]))}


@register_op(
    "linspace",
    inputs=[In("Start", no_grad=True), In("Stop", no_grad=True),
            In("Num", no_grad=True)],
    outputs=[Out("Out")],
    attrs={"dtype": 5, "num": 0},
    grad=None,
    infer_shape=lambda ins, attrs: {
        "Out": jax.ShapeDtypeStruct((attrs.get("num") or 1,),
                                    _dt.to_numpy_dtype(attrs["dtype"]))},
)
def _linspace(ins, attrs):
    # Num must be statically known (attr "num"); tensor Num kept for parity.
    n = attrs.get("num") or 1
    start = ins["Start"].reshape(())
    stop = ins["Stop"].reshape(())
    return {"Out": jnp.linspace(start, stop, n,
                                dtype=_dt.to_numpy_dtype(attrs["dtype"]))}


# -- shaping ----------------------------------------------------------------


def _reshape_shape(x, shape_attr):
    shape = list(shape_attr)
    for i, s in enumerate(shape):
        if s == 0:
            shape[i] = x.shape[i]
    return shape


def _xshape(x):
    # Reference stores the pre-op shape in XShape (first dim 0) for the
    # grad op; our VJP doesn't need it but parity keeps the slot.
    return jnp.zeros((0,) + tuple(x.shape), dtype=x.dtype)


@register_op(
    "reshape2",
    inputs=[In("X"), In("Shape", dispensable=True, no_grad=True),
            In("ShapeTensor", dispensable=True, no_grad=True, duplicable=True)],
    outputs=[Out("Out"), Out("XShape", no_grad=True)],
    attrs={"shape": []},
)
def _reshape2(ins, attrs):
    x = ins["X"]
    out = x.reshape(_reshape_shape(x, attrs["shape"]))
    return {"Out": out, "XShape": _xshape(x)}


@register_op(
    "reshape",
    inputs=[In("X"), In("Shape", dispensable=True, no_grad=True)],
    outputs=[Out("Out")],
    attrs={"shape": []},
)
def _reshape(ins, attrs):
    x = ins["X"]
    return {"Out": x.reshape(_reshape_shape(x, attrs["shape"]))}


@register_op(
    "transpose2",
    inputs=[In("X")],
    outputs=[Out("Out"), Out("XShape", no_grad=True)],
    attrs={"axis": []},
)
def _transpose2(ins, attrs):
    x = ins["X"]
    return {"Out": jnp.transpose(x, attrs["axis"]), "XShape": _xshape(x)}


@register_op(
    "transpose",
    inputs=[In("X")],
    outputs=[Out("Out")],
    attrs={"axis": []},
)
def _transpose(ins, attrs):
    return {"Out": jnp.transpose(ins["X"], attrs["axis"])}


@register_op(
    "flatten2",
    inputs=[In("X")],
    outputs=[Out("Out"), Out("XShape", no_grad=True)],
    attrs={"axis": 1},
)
def _flatten2(ins, attrs):
    x = ins["X"]
    ax = attrs.get("axis", 1)
    lead = int(np.prod(x.shape[:ax])) if ax > 0 else 1
    return {"Out": x.reshape(lead, -1), "XShape": _xshape(x)}


@register_op(
    "flatten",
    inputs=[In("X")],
    outputs=[Out("Out")],
    attrs={"axis": 1},
)
def _flatten(ins, attrs):
    x = ins["X"]
    ax = attrs.get("axis", 1)
    lead = int(np.prod(x.shape[:ax])) if ax > 0 else 1
    return {"Out": x.reshape(lead, -1)}


@register_op(
    "flatten_contiguous_range",
    inputs=[In("X")],
    outputs=[Out("Out"), Out("XShape", no_grad=True)],
    attrs={"start_axis": 1, "stop_axis": -1},
)
def _flatten_range(ins, attrs):
    x = ins["X"]
    start = attrs.get("start_axis", 1) % max(x.ndim, 1)
    stop = attrs.get("stop_axis", -1) % max(x.ndim, 1)
    mid = int(np.prod(x.shape[start : stop + 1]))
    shape = x.shape[:start] + (mid,) + x.shape[stop + 1 :]
    return {"Out": x.reshape(shape), "XShape": _xshape(x)}


def normalize_squeeze_axes(x, explicit, op_name, at_infer=False):
    """Shared squeeze/squeeze2 axis resolution: explicit axes must be in
    [-ndim, ndim) (squeeze_op.cc axis enforce) and name size-1 dims;
    empty axes means every size-1 dim. The size==1 check goes beyond
    the reference (squeeze_op.cc drops a listed non-unit dim
    unconditionally, silently corrupting numel) — we fail loudly
    instead. At graph-build infer (at_infer=True) dims marked unknown
    (-1 → sentinel) are exempt and dropped like the reference does;
    runtime shapes are always concrete and fully checked. Duplicate
    axes (e.g. 1 and -2 on rank 3) collapse to one."""
    if not explicit:
        return sorted(i for i, d in enumerate(x.shape) if d == 1)
    axes = set()
    for a in (int(a) for a in explicit):
        if not -x.ndim <= a < x.ndim:
            raise ValueError(
                "%s: axis %d out of range for input of rank %d"
                % (op_name, a, x.ndim))
        axes.add(a + x.ndim if a < 0 else a)
    from ..framework import _SENTINEL

    bad = [a for a in sorted(axes)
           if x.shape[a] != 1
           and not (at_infer and x.shape[a] == _SENTINEL)]
    if bad:
        raise ValueError(
            "%s: axes %r have size != 1 in input shape %r (each "
            "explicitly listed axis must have size 1)"
            % (op_name, bad, tuple(x.shape)))
    return sorted(axes)


def _squeeze_infer(ins, attrs, op_name, with_xshape):
    """Graph-build shape infer that, like squeeze_op.cc GetOutputShape,
    drops explicitly listed unknown-size dims instead of tripping
    eval_shape (jnp.squeeze would reject the -1 sentinel)."""
    x = ins["X"]
    axes = normalize_squeeze_axes(x, attrs.get("axes"), op_name,
                                  at_infer=True)
    shape = tuple(d for i, d in enumerate(x.shape) if i not in axes)
    out = {"Out": jax.ShapeDtypeStruct(shape, x.dtype)}
    if with_xshape:
        out["XShape"] = jax.ShapeDtypeStruct((0,) + tuple(x.shape),
                                             x.dtype)
    return out


@register_op(
    "squeeze2",
    inputs=[In("X")],
    outputs=[Out("Out"), Out("XShape", no_grad=True)],
    attrs={"axes": []},
    infer_shape=lambda ins, attrs: _squeeze_infer(ins, attrs, "squeeze2",
                                                  True),
)
def _squeeze2(ins, attrs):
    x = ins["X"]
    axes = normalize_squeeze_axes(x, attrs.get("axes"), "squeeze2")
    return {"Out": jnp.squeeze(x, axis=tuple(axes)), "XShape": _xshape(x)}


@register_op(
    "unsqueeze2",
    inputs=[In("X")],
    outputs=[Out("Out"), Out("XShape", no_grad=True)],
    attrs={"axes": []},
)
def _unsqueeze2(ins, attrs):
    x = ins["X"]
    out = x
    for a in sorted(attrs["axes"]):
        out = jnp.expand_dims(out, a)
    return {"Out": out, "XShape": _xshape(x)}


@register_op(
    "concat",
    inputs=[In("X", duplicable=True), In("AxisTensor", dispensable=True, no_grad=True)],
    outputs=[Out("Out")],
    attrs={"axis": 0},
)
def _concat(ins, attrs):
    return {"Out": jnp.concatenate(ins["X"], axis=attrs.get("axis", 0))}


@register_op(
    "split",
    inputs=[In("X")],
    outputs=[Out("Out", duplicable=True)],
    attrs={"num": 0, "sections": [], "axis": 0},
)
def _split(ins, attrs):
    x = ins["X"]
    axis = attrs.get("axis", 0)
    sections = attrs.get("sections") or []
    if sections:
        # allow one -1 in sections
        total = x.shape[axis]
        known = sum(s for s in sections if s > 0)
        sections = [s if s > 0 else total - known for s in sections]
        idx = np.cumsum(sections[:-1]).tolist()
        outs = jnp.split(x, idx, axis=axis)
    else:
        outs = jnp.split(x, attrs["num"], axis=axis)
    return {"Out": list(outs)}


@register_op(
    "stack",
    inputs=[In("X", duplicable=True)],
    outputs=[Out("Y")],
    attrs={"axis": 0},
)
def _stack(ins, attrs):
    return {"Y": jnp.stack(ins["X"], axis=attrs.get("axis", 0))}


@register_op(
    "unstack",
    inputs=[In("X")],
    outputs=[Out("Y", duplicable=True)],
    attrs={"axis": 0, "num": 0},
)
def _unstack(ins, attrs):
    x = ins["X"]
    axis = attrs.get("axis", 0)
    n = x.shape[axis]
    return {"Y": [jnp.squeeze(a, axis=axis) for a in jnp.split(x, n, axis=axis)]}


@register_op(
    "slice",
    inputs=[In("Input"), In("StartsTensor", dispensable=True, no_grad=True),
            In("EndsTensor", dispensable=True, no_grad=True)],
    outputs=[Out("Out")],
    attrs={"axes": [], "starts": [], "ends": [], "decrease_axis": [],
           "infer_flags": []},
)
def _slice(ins, attrs):
    x = ins["Input"]
    idx = [slice(None)] * x.ndim
    for ax, st, en in zip(attrs["axes"], attrs["starts"], attrs["ends"]):
        d = x.shape[ax]
        st = max(st + d, 0) if st < 0 else min(st, d)
        en = max(en + d, 0) if en < 0 else min(en, d)
        idx[ax] = slice(st, en)
    out = x[tuple(idx)]
    dec = attrs.get("decrease_axis") or []
    if dec:
        out = jnp.squeeze(out, axis=tuple(dec))
    return {"Out": out}


@register_op(
    "strided_slice",
    inputs=[In("Input")],
    outputs=[Out("Out")],
    attrs={"axes": [], "starts": [], "ends": [], "strides": [],
           "decrease_axis": [], "infer_flags": []},
)
def _strided_slice(ins, attrs):
    x = ins["Input"]
    idx = [slice(None)] * x.ndim
    strides = attrs.get("strides") or [1] * len(attrs["axes"])
    for ax, st, en, sd in zip(attrs["axes"], attrs["starts"], attrs["ends"], strides):
        idx[ax] = slice(st, en, sd)
    out = x[tuple(idx)]
    dec = attrs.get("decrease_axis") or []
    if dec:
        out = jnp.squeeze(out, axis=tuple(dec))
    return {"Out": out}


@register_op(
    "expand",
    inputs=[In("X"), In("ExpandTimes", dispensable=True, no_grad=True)],
    outputs=[Out("Out")],
    attrs={"expand_times": []},
)
def _expand(ins, attrs):
    return {"Out": jnp.tile(ins["X"], tuple(attrs["expand_times"]))}


@register_op(
    "expand_as",
    inputs=[In("X"), In("target_tensor", no_grad=True)],
    outputs=[Out("Out")],
)
def _expand_as(ins, attrs):
    x, t = ins["X"], ins["target_tensor"]
    times = [td // xd for td, xd in zip(t.shape, x.shape)]
    return {"Out": jnp.tile(x, tuple(times))}


@register_op(
    "pad",
    inputs=[In("X")],
    outputs=[Out("Out")],
    attrs={"paddings": [], "pad_value": 0.0},
)
def _pad(ins, attrs):
    x = ins["X"]
    p = attrs["paddings"]
    pads = [(p[2 * i], p[2 * i + 1]) for i in range(x.ndim)]
    return {"Out": jnp.pad(x, pads, constant_values=attrs.get("pad_value", 0.0))}


@register_op(
    "pad2d",
    inputs=[In("X")],
    outputs=[Out("Out")],
    attrs={"paddings": [0, 0, 0, 0], "mode": "constant", "pad_value": 0.0,
           "data_format": "NCHW"},
)
def _pad2d(ins, attrs):
    x = ins["X"]
    t, b, l, r = attrs["paddings"]
    mode = attrs.get("mode", "constant")
    if attrs.get("data_format", "NCHW") == "NCHW":
        pads = [(0, 0), (0, 0), (t, b), (l, r)]
    else:
        pads = [(0, 0), (t, b), (l, r), (0, 0)]
    if mode == "constant":
        return {"Out": jnp.pad(x, pads, constant_values=attrs.get("pad_value", 0.0))}
    jmode = {"reflect": "reflect", "edge": "edge"}[mode]
    return {"Out": jnp.pad(x, pads, mode=jmode)}


@register_op(
    "tril_triu",
    inputs=[In("X")],
    outputs=[Out("Out")],
    attrs={"diagonal": 0, "lower": True},
)
def _tril_triu(ins, attrs):
    x = ins["X"]
    k = attrs.get("diagonal", 0)
    return {"Out": jnp.tril(x, k) if attrs.get("lower", True) else jnp.triu(x, k)}


@register_op(
    "roll",
    inputs=[In("X")],
    outputs=[Out("Out")],
    attrs={"shifts": [], "axis": []},
)
def _roll(ins, attrs):
    axes = attrs.get("axis") or None
    return {"Out": jnp.roll(ins["X"], tuple(attrs["shifts"]),
                            axis=tuple(axes) if axes else None)}


@register_op(
    "flip",
    inputs=[In("X")],
    outputs=[Out("Out")],
    attrs={"axis": []},
)
def _flip(ins, attrs):
    return {"Out": jnp.flip(ins["X"], axis=tuple(attrs["axis"]))}


# -- indexing ---------------------------------------------------------------


@register_op(
    "gather",
    inputs=[In("X"), In("Index", no_grad=True)],
    outputs=[Out("Out")],
    attrs={"overwrite": True},
)
def _gather(ins, attrs):
    return {"Out": jnp.take(ins["X"], ins["Index"].reshape(-1), axis=0)}


@register_op(
    "gather_nd",
    inputs=[In("X"), In("Index", no_grad=True)],
    outputs=[Out("Out")],
)
def _gather_nd(ins, attrs):
    x, idx = ins["X"], ins["Index"]
    k = idx.shape[-1]
    flat_idx = tuple(idx[..., i] for i in range(k))
    return {"Out": x[flat_idx]}


@register_op(
    "scatter",
    inputs=[In("X"), In("Ids", no_grad=True), In("Updates")],
    outputs=[Out("Out")],
    attrs={"overwrite": True},
)
def _scatter(ins, attrs):
    x, ids, upd = ins["X"], ins["Ids"].reshape(-1), ins["Updates"]
    if attrs.get("overwrite", True):
        return {"Out": x.at[ids].set(upd)}
    # accumulate mode zero-fills target rows first (reference semantics)
    zeroed = x.at[ids].set(jnp.zeros_like(upd))
    return {"Out": zeroed.at[ids].add(upd)}


@register_op(
    "scatter_nd_add",
    inputs=[In("X"), In("Index", no_grad=True), In("Updates")],
    outputs=[Out("Out")],
)
def _scatter_nd_add(ins, attrs):
    x, idx, upd = ins["X"], ins["Index"], ins["Updates"]
    k = idx.shape[-1]
    flat_idx = tuple(idx[..., i] for i in range(k))
    return {"Out": x.at[flat_idx].add(upd)}


def _embedding_lookup(w, ids, padding_idx):
    out = jnp.take(w, ids, axis=0)
    if padding_idx is not None and padding_idx >= 0:
        mask = (ids != padding_idx)[..., None].astype(out.dtype)
        out = out * mask
    return out


def _lookup_table_grad_maker(block, op, pending, finalize):
    """Grad maker honoring ``is_sparse`` (lookup_table_op.h grad path):
    dense mode emits the XLA scatter-add grad op; sparse mode emits a
    host op producing a SelectedRows (rows = the looked-up ids, values
    = the incoming out-grad rows) — the representation change the
    reference makes, which downstream sum/optimizer ops consume."""
    og = finalize(op.output("Out")[0])
    if og is None:
        return
    from .control_flow_ops import _bind_partial_grad

    w = op.input("W")[0]
    gname = _bind_partial_grad(block, pending, w)
    gtype = ("lookup_table_sparse_grad" if op.attrs.get("is_sparse")
             else op.type + "_grad")
    block.append_op(
        gtype,
        {"W": [w], "Ids": [op.input("Ids")[0]], "Out@GRAD": [og]},
        {"W@GRAD": [gname]},
        {"padding_idx": op.attrs.get("padding_idx", -1),
         "is_v2": op.type == "lookup_table_v2"},
        infer_shape=False)


def _lookup_table_dense_grad_impl(ins, attrs):
    w, ids, og = ins["W"], ins["Ids"], ins["Out@GRAD"]
    if not attrs.get("is_v2") and ids.ndim >= 2 and ids.shape[-1] == 1:
        ids = ids.squeeze(-1)
    pad = attrs.get("padding_idx", -1)
    if pad is not None and pad >= 0:
        og = og * (ids != pad)[..., None].astype(og.dtype)
    flat = og.reshape(-1, w.shape[-1])
    g = jnp.zeros_like(w).at[ids.reshape(-1)].add(flat.astype(w.dtype))
    return {"W@GRAD": g}


for _lt_gtype, _lt_v2 in (("lookup_table_grad", False),
                          ("lookup_table_v2_grad", True)):
    register_op(
        _lt_gtype,
        inputs=[In("W", no_grad=True), In("Ids", no_grad=True),
                In("Out@GRAD", no_grad=True)],
        outputs=[Out("W@GRAD")],
        attrs={"padding_idx": -1, "is_v2": _lt_v2},
        grad=None,
    )(_lookup_table_dense_grad_impl)


@register_op(
    "lookup_table",
    inputs=[In("W"), In("Ids", no_grad=True)],
    outputs=[Out("Out")],
    attrs={"padding_idx": -1, "is_sparse": False, "is_distributed": False,
           "remote_prefetch": False},
    grad=_lookup_table_grad_maker,
)
def _lookup_table(ins, attrs):
    ids = ins["Ids"]
    if ids.ndim >= 2 and ids.shape[-1] == 1:
        ids = ids.squeeze(-1)
    out = _embedding_lookup(ins["W"], ids, attrs.get("padding_idx", -1))
    return {"Out": out}


@register_op(
    "lookup_table_v2",
    inputs=[In("W"), In("Ids", no_grad=True)],
    outputs=[Out("Out")],
    attrs={"padding_idx": -1, "is_sparse": False, "is_distributed": False},
    grad=_lookup_table_grad_maker,
)
def _lookup_table_v2(ins, attrs):
    return {"Out": _embedding_lookup(ins["W"], ins["Ids"],
                                     attrs.get("padding_idx", -1))}


@register_host_op(
    "lookup_table_sparse_grad",
    inputs=[In("W", no_grad=True), In("Ids", no_grad=True),
            In("Out@GRAD", no_grad=True)],
    outputs=[Out("W@GRAD")],
    attrs={"padding_idx": -1, "is_v2": False},
)
def _lookup_table_sparse_grad(executor, op, scope):
    """Sparse embedding grad: emits SelectedRows(rows=ids, values=dOut)
    instead of a dense scatter — the reference's is_sparse grad
    representation (lookup_table_op.h SparseGradKernel). Host tier: the
    ragged row set is host metadata; programs carrying it run on the
    interpreter (the compiled path keeps dense grads by design)."""
    import jax.numpy as jnp

    from ..core.tensor import LoDTensor, SelectedRows

    w = executor._read_var(scope, op.input("W")[0])
    ids = np.asarray(executor._read_var(scope, op.input("Ids")[0]))
    og = executor._read_var(scope, op.input("Out@GRAD")[0])
    if not op.attrs.get("is_v2") and ids.ndim >= 2 and ids.shape[-1] == 1:
        ids = ids[..., 0]
    rows = ids.reshape(-1)
    vals = jnp.asarray(og).reshape(-1, w.shape[-1]).astype(w.dtype)
    pad = op.attrs.get("padding_idx", -1)
    if pad is not None and pad >= 0:
        keep = rows != pad
        rows = rows[keep]
        vals = vals[np.asarray(keep)]
    sr = SelectedRows(rows=np.asarray(rows).tolist(),
                      height=int(w.shape[0]), value=LoDTensor(vals))
    executor._write_var(scope, op.output("W@GRAD")[0], sr)


@register_op(
    "one_hot",
    inputs=[In("X", no_grad=True)],
    outputs=[Out("Out")],
    attrs={"depth": 1, "dtype": 5, "allow_out_of_range": False},
    grad=None,
)
def _one_hot(ins, attrs):
    x = ins["X"]
    if x.ndim >= 2 and x.shape[-1] == 1:
        x = x.squeeze(-1)
    out = jax.nn.one_hot(x, attrs["depth"],
                         dtype=_dt.to_numpy_dtype(attrs.get("dtype", 5)))
    return {"Out": out}


@register_op(
    "one_hot_v2",
    inputs=[In("X", no_grad=True)],
    outputs=[Out("Out")],
    attrs={"depth": 1, "dtype": 5, "allow_out_of_range": False},
    grad=None,
)
def _one_hot_v2(ins, attrs):
    return {"Out": jax.nn.one_hot(ins["X"], attrs["depth"],
                                  dtype=_dt.to_numpy_dtype(attrs.get("dtype", 5)))}


@register_op(
    "shape",
    inputs=[In("Input", no_grad=True)],
    outputs=[Out("Out")],
    grad=None,
)
def _shape(ins, attrs):
    return {"Out": jnp.asarray(np.array(ins["Input"].shape, dtype=np.int32))}


@register_op(
    "size",
    inputs=[In("Input", no_grad=True)],
    outputs=[Out("Out")],
    grad=None,
)
def _size(ins, attrs):
    return {"Out": jnp.asarray(int(np.prod(ins["Input"].shape)), dtype=jnp.int64)}


# -- ordering / argmax ------------------------------------------------------


@register_op(
    "top_k",
    inputs=[In("X"), In("K", dispensable=True, no_grad=True)],
    outputs=[Out("Out"), Out("Indices", no_grad=True)],
    attrs={"k": 1},
)
def _top_k(ins, attrs):
    vals, idx = jax.lax.top_k(ins["X"], attrs.get("k", 1))
    return {"Out": vals, "Indices": idx.astype(jnp.int64)}


@register_op(
    "top_k_v2",
    inputs=[In("X")],
    outputs=[Out("Out"), Out("Indices", no_grad=True)],
    attrs={"k": 1, "axis": -1, "largest": True, "sorted": True},
)
def _top_k_v2(ins, attrs):
    x = ins["X"]
    axis = attrs.get("axis", -1) % x.ndim
    k = attrs.get("k", 1)
    moved = jnp.moveaxis(x, axis, -1)
    if attrs.get("largest", True):
        vals, idx = jax.lax.top_k(moved, k)
    else:
        vals, idx = jax.lax.top_k(-moved, k)
        vals = -vals
    return {
        "Out": jnp.moveaxis(vals, -1, axis),
        "Indices": jnp.moveaxis(idx, -1, axis).astype(jnp.int64),
    }


@register_op(
    "arg_max",
    inputs=[In("X", no_grad=True)],
    outputs=[Out("Out")],
    attrs={"axis": -1, "keepdims": False, "dtype": 3},
    grad=None,
)
def _arg_max(ins, attrs):
    out = jnp.argmax(ins["X"], axis=attrs.get("axis", -1))
    if attrs.get("keepdims", False):
        out = jnp.expand_dims(out, attrs.get("axis", -1))
    return {"Out": out.astype(_dt.to_numpy_dtype(attrs.get("dtype", 3)))}


@register_op(
    "arg_min",
    inputs=[In("X", no_grad=True)],
    outputs=[Out("Out")],
    attrs={"axis": -1, "keepdims": False, "dtype": 3},
    grad=None,
)
def _arg_min(ins, attrs):
    out = jnp.argmin(ins["X"], axis=attrs.get("axis", -1))
    if attrs.get("keepdims", False):
        out = jnp.expand_dims(out, attrs.get("axis", -1))
    return {"Out": out.astype(_dt.to_numpy_dtype(attrs.get("dtype", 3)))}


@register_op(
    "argsort",
    inputs=[In("X")],
    outputs=[Out("Out"), Out("Indices", no_grad=True)],
    attrs={"axis": -1, "descending": False},
)
def _argsort(ins, attrs):
    x = ins["X"]
    axis = attrs.get("axis", -1)
    if attrs.get("descending", False):
        idx = jnp.flip(jnp.argsort(x, axis=axis), axis=axis)
    else:
        idx = jnp.argsort(x, axis=axis)
    out = jnp.take_along_axis(x, idx, axis=axis)
    return {"Out": out, "Indices": idx.astype(jnp.int64)}


@register_op(
    "index_select",
    inputs=[In("X"), In("Index", no_grad=True)],
    outputs=[Out("Out")],
    attrs={"dim": 0},
)
def _index_select(ins, attrs):
    return {"Out": jnp.take(ins["X"], ins["Index"].reshape(-1),
                            axis=attrs.get("dim", 0))}


@register_op(
    "where",
    inputs=[In("Condition", no_grad=True), In("X"), In("Y")],
    outputs=[Out("Out")],
)
def _where(ins, attrs):
    return {"Out": jnp.where(ins["Condition"], ins["X"], ins["Y"])}


@register_host_op(
    "where_index",
    inputs=[In("Condition", no_grad=True)],
    outputs=[Out("Out")],
)
def _where_index(executor, op, scope):
    # Output shape is data-dependent (count of nonzeros) -> host op, like
    # the reference's CPU-only where_index kernel.
    cond = executor._read_var(scope, op.input("Condition")[0])
    idx = np.stack(np.nonzero(np.asarray(cond)), axis=1).astype(np.int64)
    executor._write_var(scope, op.output("Out")[0], idx)


@register_op(
    "unique_with_counts",
    inputs=[In("X", no_grad=True)],
    outputs=[Out("Out"), Out("Index"), Out("Count")],
    attrs={"dtype": 2},
    grad=None,
    infer_shape=lambda ins, attrs: {
        "Out": ins["X"],
        "Index": jax.ShapeDtypeStruct(ins["X"].shape, np.int32),
        "Count": jax.ShapeDtypeStruct(ins["X"].shape, np.int32),
    },
)
def _unique_with_counts(ins, attrs):
    # Static-shape variant: emits full-length arrays (XLA-compatible);
    # host-side consumers trim via the Count vector.
    x = ins["X"]
    out, idx, counts = jnp.unique(x, return_inverse=True, return_counts=True,
                                  size=x.shape[0], fill_value=0)
    return {"Out": out, "Index": idx.astype(jnp.int32),
            "Count": counts.astype(jnp.int32)}


@register_op(
    "diag",
    inputs=[In("Diagonal")],
    outputs=[Out("Out")],
)
def _diag(ins, attrs):
    return {"Out": jnp.diag(ins["Diagonal"].reshape(-1))}


@register_op(
    "meshgrid",
    inputs=[In("X", duplicable=True)],
    outputs=[Out("Out", duplicable=True)],
)
def _meshgrid(ins, attrs):
    outs = jnp.meshgrid(*[x.reshape(-1) for x in ins["X"]], indexing="ij")
    return {"Out": list(outs)}


@register_op(
    "kron",
    inputs=[In("X"), In("Y")],
    outputs=[Out("Out")],
)
def _kron(ins, attrs):
    return {"Out": jnp.kron(ins["X"], ins["Y"])}


@register_host_op(
    "range",
    inputs=[In("Start", no_grad=True), In("End", no_grad=True),
            In("Step", no_grad=True)],
    outputs=[Out("Out")],
    const_foldable=True,
)
def _range(executor, op, scope):
    # Output length is value-dependent -> host op (the reference's range
    # kernel is CPU-side too, operators/range_op.cc).
    start = np.asarray(executor._read_var(scope, op.input("Start")[0])).reshape(())
    end = np.asarray(executor._read_var(scope, op.input("End")[0])).reshape(())
    step = np.asarray(executor._read_var(scope, op.input("Step")[0])).reshape(())
    executor._write_var(scope, op.output("Out")[0], np.arange(start, end, step))


def _merge_rows(rows, vals):
    """Sum duplicate rows: (ids, values) -> (unique ids, summed rows)."""
    uniq, inv = np.unique(rows, return_inverse=True)
    merged = np.zeros((len(uniq),) + vals.shape[1:], dtype=vals.dtype)
    np.add.at(merged, inv, vals)
    return uniq, merged


@register_host_op(
    "merge_selected_rows",
    inputs=[In("X", no_grad=True)],
    outputs=[Out("Out")],
)
def _merge_selected_rows(executor, op, scope):
    """Sum duplicate rows of a SelectedRows (reference
    operators/math/selected_rows_functor.cc MergeAdd)."""
    from ..core.tensor import LoDTensor, SelectedRows

    sr = scope.find_var(op.input("X")[0]).raw()
    if not isinstance(sr, SelectedRows):
        raise TypeError("merge_selected_rows expects SelectedRows input")
    rows = np.asarray(sr.rows(), dtype=np.int64)
    vals = np.asarray(sr.get_tensor().array)
    uniq, merged = _merge_rows(rows, vals)
    out = SelectedRows(rows=uniq.tolist(), height=sr.height(),
                       value=LoDTensor(merged))
    scope.var(op.output("Out")[0]).set(out)


@register_host_op(
    "get_tensor_from_selected_rows",
    inputs=[In("X", no_grad=True)],
    outputs=[Out("Out")],
)
def _get_tensor_from_selected_rows(executor, op, scope):
    """SelectedRows -> dense rows tensor (reference
    operators/get_tensor_from_selected_rows_op.cc)."""
    from ..core.tensor import SelectedRows

    sr = scope.find_var(op.input("X")[0]).raw()
    if not isinstance(sr, SelectedRows):
        raise TypeError("expects SelectedRows input")
    executor._write_var(scope, op.output("Out")[0],
                        np.asarray(sr.get_tensor().array))


@register_host_op(
    "lookup_sparse_table_grad_split",
    inputs=[In("Grad", no_grad=True), In("Ids", no_grad=True)],
    outputs=[Out("Out")],
    attrs={"height": 0},
)
def _lookup_sparse_table_grad_split(executor, op, scope):
    """Dense embedding grad + ids -> SelectedRows (rows=unique ids,
    values=summed grad rows) — the host-side bridge from the compiled
    dense-grad path into SelectedRows consumers (save, PS send)."""
    from ..core.tensor import LoDTensor, SelectedRows

    grad = np.asarray(executor._read_var(scope, op.input("Grad")[0]))
    ids = np.asarray(executor._read_var(scope, op.input("Ids")[0])).reshape(-1)
    # grad rows: [n_ids, D]; numpy rejects reshape(0, -1) on size-0
    # arrays, so build the empty case from the trailing dims directly
    if len(ids):
        g = grad.reshape(len(ids), -1)
    else:
        d = int(np.prod(grad.shape[1:])) if grad.ndim > 1 else 1
        g = np.zeros((0, d), dtype=grad.dtype)
    uniq, merged = _merge_rows(ids, g)
    out = SelectedRows(rows=uniq.tolist(),
                       height=int(op.attrs.get("height", 0)),
                       value=LoDTensor(merged))
    scope.var(op.output("Out")[0]).set(out)
