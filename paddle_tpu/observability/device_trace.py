"""Device-truth profiling: XPlane capture, phase folding, host cross-check.

The PR-7 step profiler attributes step time by *host-side re-execution*
of phase slices — a measurement, but not device truth: dispatch floors,
sync overhead and XLA's scheduler all sit between the host numbers and
what the chip actually did. This module closes that gap:

**Capture** (``capture_xspace`` / ``device_profile_step``). One bench
step is re-jitted with phase annotation armed (the
``jax.named_scope("<phase>/<op_type>")`` labels the PR-7 hook already
injects at the shared trace entry in ``core/compiler_engine``) and run
a few times under ``jax.profiler`` — the same XPlane capture
TensorBoard's profiler plugin consumes. Compilation happens *before*
the trace starts, so the capture holds steady-state steps only.

**Parse** (``parse_xspace``). A minimal, dependency-free protobuf
wire-format reader for the XSpace container (planes → lines → events,
with the interned event/stat metadata tables) plus the serialized HLO
proto the ``/host:metadata`` plane carries per compiled module. Only
varint / length-delimited / fixed fields are touched; unknown fields
are skipped — the schema additions land as silently-ignored fields,
exactly the protobuf forward-compat contract. Nothing here imports
tensorflow or protobuf.

**Fold** (``fold_device_phases``). Device op events resolve to an HLO
instruction (by event name, or the ``hlo_op`` stat), the instruction's
``metadata.op_name`` carries the named_scope path, and the first path
component matching a known phase claims the interval. Per-phase device
time is the interval *union* (concurrent thunks don't double-count),
collective-vs-compute overlap and the busy-time critical path come
from the same ``analyze_timeline`` the host profiler uses — one
analyzer, two input sources. Ops whose scope resolves to no known
phase are tolerated (accounted as ``unattributed_ms``); a trace with
NO phase-attributed events folds to ``None`` and the caller keeps the
host numbers (the explicit fallback contract — a missing device story
must never fabricate one).

**Cross-check** (``cross_check``). Per-phase agreement ratio
``min(host, device) / max(host, device)`` plus a duration-weighted
overall ``agreement`` — surfaced in the bench ``profile`` block and
watched by ``tools/bench_diff.py``, so a silently-diverging host
estimate fails the perf gate instead of quietly steering the bucket
planner wrong.

Env contract: ``PADDLE_TPU_DEVICE_TRACE=1`` arms capture in bench runs
(multichip configs default it ON, single-chip OFF — the same
convention as ``PADDLE_TPU_PROFILE_BENCH``). Default-off costs one env
read; ci gate 4 guards it.
"""
from __future__ import annotations

import glob
import os
import struct
import tempfile
from typing import Dict, List, Optional, Tuple

__all__ = [
    "PHASES", "capture_enabled", "parse_xspace", "encode_xspace",
    "find_xplane_files", "load_trace_dir", "capture_xspace",
    "phase_of_op_name", "fold_device_phases", "cross_check",
    "device_profile_step",
]

PHASES = ("forward", "backward", "collective", "optimizer")


def capture_enabled(default: bool = False) -> bool:
    """``PADDLE_TPU_DEVICE_TRACE`` switch; unset keeps the caller's
    default (bench: ON for multichip configs, OFF single-chip)."""
    raw = os.environ.get("PADDLE_TPU_DEVICE_TRACE", "").strip().lower()
    if not raw:
        return bool(default)
    return raw in ("1", "true", "yes", "on")


# -- protobuf wire reader ---------------------------------------------------
#
# XSpace schema (tsl/profiler/protobuf/xplane.proto), fields used:
#   XSpace.planes=1
#   XPlane.name=2 .lines=3 .event_metadata=4(map) .stat_metadata=5(map)
#   XLine.name=2 .timestamp_ns=3 .events=4
#   XEvent.metadata_id=1 .offset_ps=2 .duration_ps=3 .stats=4
#   XStat.metadata_id=1 double=2 uint64=3 int64=4 str=5 bytes=6 ref=7
#   XEventMetadata.id=1 .name=2 .stats=5
#   XStatMetadata.id=1 .name=2
# HLO proto (xla/service/hlo.proto), fields used:
#   HloProto.hlo_module=1; HloModuleProto.computations=3
#   HloComputationProto.instructions=2
#   HloInstructionProto.name=1 .metadata=7; OpMetadata.op_name=2


def _read_varint(b: bytes, i: int) -> Tuple[int, int]:
    x = 0
    s = 0
    while True:
        c = b[i]
        i += 1
        x |= (c & 0x7F) << s
        if not (c & 0x80):
            return x, i
        s += 7
        if s > 70:
            raise ValueError("varint overflow")


def _iter_fields(b: bytes):
    """Yield (field_number, wire_type, value) over one message's bytes.
    value: int for varint fields, raw bytes otherwise."""
    i, n = 0, len(b)
    while i < n:
        key, i = _read_varint(b, i)
        fnum, wt = key >> 3, key & 7
        if wt == 0:
            v, i = _read_varint(b, i)
        elif wt == 1:
            v = b[i:i + 8]
            i += 8
        elif wt == 2:
            ln, i = _read_varint(b, i)
            v = b[i:i + ln]
            i += ln
        elif wt == 5:
            v = b[i:i + 4]
            i += 4
        else:
            raise ValueError("unsupported wire type %d" % wt)
        if i > n:
            raise ValueError("truncated field")
        yield fnum, wt, v


def _utf8(v) -> str:
    return v.decode("utf-8", "replace") if isinstance(v, bytes) else str(v)


def _decode_stat(b: bytes, stat_names: Dict[int, str]):
    """(stat_name, value) from one XStat. ``ref_value`` stats resolve
    through the interned stat_metadata table (XLA interns hlo_op names
    this way)."""
    name = None
    val = None
    for fn, _wt, v in _iter_fields(b):
        if fn == 1:
            name = stat_names.get(v, str(v))
        elif fn == 2:
            val = struct.unpack("<d", v)[0]
        elif fn in (3, 4):
            val = v
        elif fn == 5:
            val = _utf8(v)
        elif fn == 6:
            val = bytes(v)
        elif fn == 7:
            val = stat_names.get(v, v)
    return name, val


def _parse_map_entry(b: bytes):
    """(key:int, value:bytes) of one map<int64, Message> entry."""
    k, v = None, b""
    for fn, _wt, fv in _iter_fields(b):
        if fn == 1:
            k = fv
        elif fn == 2:
            v = fv
    return k, v


def _parse_hlo_op_names(hlo_proto: bytes) -> Dict[str, str]:
    """{instruction name: metadata.op_name} over every computation of
    an HloProto — the join key between a device op event and the
    named_scope path the annotated trace stamped on it."""
    out: Dict[str, str] = {}
    for fn, _wt, module in _iter_fields(hlo_proto):
        if fn != 1:
            continue
        for fn2, _wt2, comp in _iter_fields(module):
            if fn2 != 3:
                continue
            for fn3, _wt3, instr in _iter_fields(comp):
                if fn3 != 2:
                    continue
                iname = opname = None
                for fn4, _wt4, v4 in _iter_fields(instr):
                    if fn4 == 1:
                        iname = _utf8(v4)
                    elif fn4 == 7:
                        for fn5, _wt5, v5 in _iter_fields(v4):
                            if fn5 == 2:
                                opname = _utf8(v5)
                if iname and opname:
                    out[iname] = opname
    return out


def _parse_event_metadata(b: bytes) -> Dict:
    meta = {"name": "", "stats_raw": []}
    for fn, _wt, v in _iter_fields(b):
        if fn == 2:
            meta["name"] = _utf8(v)
        elif fn == 5:
            meta["stats_raw"].append(v)
    return meta


def _parse_line(b: bytes, emeta: Dict, smeta: Dict) -> Dict:
    name = ""
    ts_ns = 0
    event_bufs: List[bytes] = []
    for fn, _wt, v in _iter_fields(b):
        if fn == 2:
            name = _utf8(v)
        elif fn == 3:
            ts_ns = v
        elif fn == 4:
            event_bufs.append(v)
    events = []
    for eb in event_bufs:
        mid = None
        off_ps = 0
        dur_ps = 0
        stats: Dict[str, object] = {}
        for fn, _wt, v in _iter_fields(eb):
            if fn == 1:
                mid = v
            elif fn == 2:
                off_ps = v
            elif fn == 3:
                dur_ps = v
            elif fn == 4:
                try:
                    sname, sval = _decode_stat(v, smeta)
                except (ValueError, IndexError, struct.error):
                    continue
                if sname is not None:
                    stats[sname] = sval
        meta = emeta.get(mid) or {}
        events.append({"name": meta.get("name", ""),
                       "ts_ps": ts_ns * 1000 + off_ps,
                       "dur_ps": dur_ps, "stats": stats})
    return {"name": name, "timestamp_ns": ts_ns, "events": events}


def _parse_plane(b: bytes) -> Dict:
    name = ""
    line_bufs: List[bytes] = []
    emeta: Dict[int, Dict] = {}
    smeta: Dict[int, str] = {}
    for fn, _wt, v in _iter_fields(b):
        if fn == 2:
            name = _utf8(v)
        elif fn == 3:
            line_bufs.append(v)
        elif fn == 4:
            k, mv = _parse_map_entry(v)
            if k is not None:
                emeta[k] = _parse_event_metadata(mv)
        elif fn == 5:
            k, mv = _parse_map_entry(v)
            if k is not None:
                for fn2, _wt2, v2 in _iter_fields(mv):
                    if fn2 == 2:
                        smeta[k] = _utf8(v2)
    hlo: Dict[str, str] = {}
    for m in emeta.values():
        for sb in m["stats_raw"]:
            try:
                sname, sval = _decode_stat(sb, smeta)
            except (ValueError, IndexError, struct.error):
                continue
            if sname == "Hlo Proto" and isinstance(sval, bytes):
                try:
                    hlo.update(_parse_hlo_op_names(sval))
                except (ValueError, IndexError):
                    continue
    return {"name": name,
            "lines": [_parse_line(lb, emeta, smeta) for lb in line_bufs],
            "hlo_op_names": hlo}


def parse_xspace(data: bytes) -> Dict:
    """Decode one ``*.xplane.pb`` into ``{"planes": [...]}`` — each
    plane with its lines, timestamped events (name / ts_ps / dur_ps /
    stats) and any HLO instruction → op_name map embedded in its
    metadata. Raises ValueError on bytes that are not an XSpace."""
    planes = []
    for fn, _wt, v in _iter_fields(data):
        if fn == 1:
            planes.append(_parse_plane(v))
    return {"planes": planes}


# -- encoder (fixtures / tests) ---------------------------------------------


def _enc_varint(x: int) -> bytes:
    out = bytearray()
    while True:
        b = x & 0x7F
        x >>= 7
        if x:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _enc_len(fnum: int, payload: bytes) -> bytes:
    return _enc_varint(fnum << 3 | 2) + _enc_varint(len(payload)) + payload


def _enc_int(fnum: int, v: int) -> bytes:
    return _enc_varint(fnum << 3) + _enc_varint(int(v))


def _enc_hlo_proto(op_names: Dict[str, str]) -> bytes:
    instrs = b""
    for iname, opname in sorted(op_names.items()):
        meta = _enc_len(2, opname.encode())
        instrs += _enc_len(2, _enc_len(1, iname.encode())
                           + _enc_len(7, meta))
    comp = _enc_len(1, b"main") + instrs
    module = _enc_len(1, b"module") + _enc_len(3, comp)
    return _enc_len(1, module)


def encode_xspace(space: Dict) -> bytes:
    """Inverse of ``parse_xspace`` for the subset the fold reads —
    canned-fixture XPlane bytes for tests, no device needed. Plane
    dicts: ``{"name", "lines": [{"name", "timestamp_ns", "events":
    [{"name", "ts_ps", "dur_ps", "stats": {str: str}}]}],
    "hlo_op_names": {instr: op_name}}``."""
    out = b""
    for plane in space.get("planes") or []:
        ev_names: Dict[str, int] = {}
        st_names: Dict[str, int] = {}

        def _ev_id(name: str) -> int:
            if name not in ev_names:
                ev_names[name] = len(ev_names) + 1
            return ev_names[name]

        def _st_id(name: str) -> int:
            if name not in st_names:
                st_names[name] = len(st_names) + 1
            return st_names[name]

        lines_b = b""
        for line in plane.get("lines") or []:
            ts_ns = int(line.get("timestamp_ns") or 0)
            evs_b = b""
            for ev in line.get("events") or []:
                body = _enc_int(1, _ev_id(ev.get("name") or ""))
                body += _enc_int(2, int(ev.get("ts_ps", 0)) - ts_ns * 1000)
                body += _enc_int(3, int(ev.get("dur_ps", 0)))
                for sn, sv in (ev.get("stats") or {}).items():
                    stat = _enc_int(1, _st_id(sn)) + _enc_len(
                        5, str(sv).encode())
                    body += _enc_len(4, stat)
                evs_b += _enc_len(4, body)
            lines_b += _enc_len(3, _enc_len(2, (line.get("name")
                                                or "").encode())
                                + _enc_int(3, ts_ns) + evs_b)
        hlo = plane.get("hlo_op_names") or {}
        hlo_meta = b""
        if hlo:
            stat = _enc_int(1, _st_id("Hlo Proto")) + _enc_len(
                6, _enc_hlo_proto(hlo))
            mod_meta = (_enc_int(1, len(ev_names) + 1)
                        + _enc_len(2, b"hlo_module")
                        + _enc_len(5, stat))
            hlo_meta = _enc_len(4, _enc_int(1, len(ev_names) + 1)
                                + _enc_len(2, mod_meta))
        emeta_b = b""
        for name, mid in ev_names.items():
            entry = _enc_int(1, mid) + _enc_len(2, name.encode())
            emeta_b += _enc_len(4, _enc_int(1, mid) + _enc_len(2, entry))
        smeta_b = b""
        for name, sid in st_names.items():
            entry = _enc_int(1, sid) + _enc_len(2, name.encode())
            smeta_b += _enc_len(5, _enc_int(1, sid) + _enc_len(2, entry))
        plane_b = (_enc_len(2, (plane.get("name") or "").encode())
                   + emeta_b + hlo_meta + smeta_b + lines_b)
        out += _enc_len(1, plane_b)
    return out


# -- capture ----------------------------------------------------------------


def find_xplane_files(trace_dir: str) -> List[str]:
    """``*.xplane.pb`` files of the NEWEST profiler run under
    ``trace_dir`` (jax writes ``plugins/profile/<stamp>/<host>.xplane.pb``
    per capture)."""
    runs = [d for d in glob.glob(os.path.join(
        trace_dir, "plugins", "profile", "*")) if os.path.isdir(d)]
    if runs:
        newest = max(runs, key=os.path.getmtime)
        return sorted(glob.glob(os.path.join(newest, "*.xplane.pb")))
    return sorted(glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                            recursive=True))


def load_trace_dir(trace_dir: str) -> Dict:
    """Parse every XPlane file of the newest capture under
    ``trace_dir`` into one merged ``{"planes": [...]}``; unreadable
    files are skipped (a torn capture degrades, never raises)."""
    planes: List[Dict] = []
    for path in find_xplane_files(trace_dir):
        try:
            with open(path, "rb") as f:
                planes.extend(parse_xspace(f.read())["planes"])
        except (OSError, ValueError, IndexError):
            continue
    return {"planes": planes}


def capture_xspace(run, trace_dir: Optional[str] = None) -> Dict:
    """Run ``run()`` under a ``jax.profiler`` trace and return the
    parsed XSpace. The caller is responsible for compiling OUTSIDE the
    capture window (or the trace times XLA's compiler, not the step).
    A caller-supplied ``trace_dir`` is kept on disk (TensorBoard can
    open it); without one, the scratch capture dir is removed once
    parsed — captures are MBs each and a CI host must not accumulate
    them."""
    import jax

    d = trace_dir or tempfile.mkdtemp(prefix="ptpu_devtrace_")
    jax.profiler.start_trace(d)
    try:
        run()
    finally:
        jax.profiler.stop_trace()
    try:
        return load_trace_dir(d)
    finally:
        if trace_dir is None:
            import shutil

            shutil.rmtree(d, ignore_errors=True)


# -- phase folding ----------------------------------------------------------


def phase_of_op_name(op_name) -> Optional[str]:
    """First path component of a named_scope path that names a known
    phase (``jit(step)/jit(main)/backward/mul_grad/...`` → "backward");
    None for unknown scopes — the caller tolerates them."""
    if not op_name:
        return None
    for part in str(op_name).split("/"):
        if part in PHASES:
            return part
    return None


def fold_device_phases(space: Dict, steps: int = 1) -> Optional[Dict]:
    """Fold a parsed XSpace's device op intervals back into per-phase
    timings.

    Resolution per event: its name (or ``hlo_op`` stat) looked up in
    the capture's HLO instruction → op_name map, then the op_name's
    named_scope path; an event whose name itself carries a phase path
    (TraceMe-style) resolves directly. Per-phase time is the interval
    UNION across all lines (concurrent thunks counted once);
    collective-vs-compute overlap and the busy critical path come from
    ``analyze_timeline`` — the same math as the host report, different
    evidence. Returns None when NO event resolves to a phase (empty or
    annotation-less trace) — the caller falls back to host numbers.
    """
    from .profiler import _union_length, analyze_timeline

    steps = max(1, int(steps))
    hlo: Dict[str, str] = {}
    for plane in space.get("planes") or []:
        hlo.update(plane.get("hlo_op_names") or {})
    spans: List[Tuple[str, float, float]] = []   # (phase, ts_ms, dur_ms)
    n_events = 0
    n_attr = 0
    unattributed_ps = 0
    for plane in space.get("planes") or []:
        for line in plane.get("lines") or []:
            for ev in line.get("events") or []:
                n_events += 1
                name = ev.get("name") or ""
                op_name = hlo.get(name)
                resolved = op_name is not None
                if op_name is None:
                    h = (ev.get("stats") or {}).get("hlo_op")
                    if isinstance(h, str):
                        op_name = hlo.get(h)
                        resolved = resolved or op_name is not None
                phase = phase_of_op_name(op_name) or phase_of_op_name(name)
                if phase is None:
                    if resolved:
                        # a genuine XLA op whose scope names no known
                        # phase — tolerated, but accounted
                        unattributed_ps += int(ev.get("dur_ps") or 0)
                    continue
                n_attr += 1
                spans.append((phase, ev.get("ts_ps", 0) / 1e9,
                              ev.get("dur_ps", 0) / 1e9))
    if not spans:
        return None
    tl = analyze_timeline(spans)
    phase_ms: Dict[str, float] = {}
    for ph in sorted({s[0] for s in spans}):
        phase_ms[ph] = _union_length(
            [(ts, ts + dur) for p, ts, dur in spans if p == ph]) / steps
    return {
        "device_phase_ms": phase_ms,
        "overlap_frac": tl["overlap_frac"],
        "critical_path_ms": tl["critical_path_ms"] / steps,
        "compute_ms": tl["compute_ms"] / steps,
        "collective_ms": tl["collective_ms"] / steps,
        "exposed_collective_ms": tl["exposed_collective_ms"] / steps,
        "unattributed_ms": unattributed_ps / 1e9 / steps,
        "n_events": n_events,
        "n_attributed": n_attr,
        "steps": steps,
        "source": "xplane",
    }


# -- host cross-check -------------------------------------------------------


def cross_check(host_phase_ms: Dict, device_phase_ms: Dict) -> Dict:
    """Per-phase agreement between the host-measured re-execution
    breakdown and the device-folded one: ``min/max`` ratio per phase
    (1.0 = perfect agreement, 0 = one side missing entirely) plus a
    duration-weighted overall ``agreement``. Host "collective" is the
    SERIAL microbench cost while the device side measures actual (often
    overlapped) collective intervals — disagreement there is signal,
    not error; the weighted overall number is what the perf gate
    watches for drift."""
    per: Dict[str, Dict] = {}
    num = den = 0.0
    for ph in sorted(set(host_phase_ms or {}) | set(device_phase_ms or {})):
        h = float((host_phase_ms or {}).get(ph) or 0.0)
        d = float((device_phase_ms or {}).get(ph) or 0.0)
        hi = max(h, d)
        ratio = (min(h, d) / hi) if hi > 0 else 1.0
        per[ph] = {"host_ms": h, "device_ms": d, "agreement": ratio}
        num += ratio * hi
        den += hi
    return {"per_phase": per,
            "agreement": (num / den) if den else None}


def _emit_device_profile(dev: Dict, agreement=None) -> None:
    from .. import observability as _obs

    if not _obs.enabled():
        return
    for phase, ms in dev["device_phase_ms"].items():
        _obs.observe("profile.device_phase_ms", ms, phase=phase)
    if dev["overlap_frac"] is not None:
        _obs.set_gauge("profile.device_overlap_frac", dev["overlap_frac"])
    _obs.set_gauge("profile.device_critical_path_ms",
                   dev["critical_path_ms"])
    if agreement is not None:
        _obs.set_gauge("profile.host_device_agreement", agreement)


# -- one-call device profile of a static program ----------------------------


def device_profile_step(program, scope, feed, mesh=None,
                        axis_name: str = "dp", steps: int = 3,
                        trace_dir: Optional[str] = None,
                        seed: int = 0) -> Optional[Dict]:
    """Capture + fold a device-phase report for one runnable static
    program (same contract as ``profiler.profile_step``: startup run,
    rewrites applied; state is read, never written back).

    The step is re-jitted with phase annotation armed — prior
    annotation state is restored afterwards, so a default-off process
    stays default-off — compiled before the capture window, then run
    ``steps`` times under the XPlane trace. Returns the folded report,
    or None when the trace carried no phase-attributed device events
    (the caller keeps the host-measured numbers)."""
    import jax
    import jax.numpy as jnp

    from . import profiler

    ctx = profiler._exec_inputs(program, scope, feed, mesh=mesh,
                                axis_name=axis_name)
    args = (ctx["state"], ctx["feed_vals"], jnp.uint32(seed))
    sync = profiler._whole_sync(ctx["ops"], ctx["persist_written"])
    was_on = profiler.annotating()
    profiler.enable_annotation()
    # the persistent XLA compile cache keys on the computation, NOT its
    # metadata — an executable cached from an UNANNOTATED compile of
    # the same step (bench warmup, a previous run) would be served for
    # the annotated trace and its XPlane would carry no phase scopes.
    # Bypass the cache for this one compile; restore after.
    cache_dir = getattr(jax.config, "jax_compilation_cache_dir", None)
    try:
        if cache_dir:
            jax.config.update("jax_compilation_cache_dir", None)
        fn = ctx["make_fn"](ctx["ops"], sync)
        jax.block_until_ready(fn(*args))   # compile OUTSIDE the capture

        def run():
            for _ in range(max(1, steps)):
                jax.block_until_ready(fn(*args))

        space = capture_xspace(run, trace_dir)
    finally:
        if cache_dir:
            jax.config.update("jax_compilation_cache_dir", cache_dir)
        if not was_on:
            profiler.disable_annotation()
    dev = fold_device_phases(space, steps=steps)
    if dev is not None:
        _emit_device_profile(dev)
    return dev
