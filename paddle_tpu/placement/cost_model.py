"""Profile-fitted cost model for the placement search.

Scores a candidate collective schedule in milliseconds from two kinds
of evidence, with the provenance of every number recorded:

- **fitted** — the saved step-profile report
  (``profiler.profile_step``): measured per-collective cost points
  (``per_bucket``: bytes vs collective_ms, labeled by kind) fit a
  per-kind ``a + b*bytes`` line; measured ``backward_segments`` give
  the hide budget after each availability point; measured ``phase_ms``
  gives the compute floor. Strategy transfer uses launch/bandwidth
  factors describing what ``strategy_psum`` actually EXECUTES: the
  fitted (a, b) of the measured spelling back out a per-launch cost
  ``alpha`` and a per-byte unit ``beta_unit``, and the other
  spellings re-scale by their launch count and busiest-link factor
  (see ``strategy_factors``).

- **analytic** — hand estimates (``DEFAULT_ALPHA_MS`` /
  ``DEFAULT_BW_GBPS``) when no usable report exists. The search still
  runs; every score carries ``provenance="analytic"`` so a consumer
  (bench placement block, placement_smoke) can see it was not
  measurement-driven.

The model deliberately charges EXECUTED wire widths
(``QUANT_PSUM_ITEMSIZE``: emulated int8 psums int32 codes — no byte
win on a CPU host mesh, matching the MULTICHIP_BENCH_r01 finding that
int8 measured slower than bf16 there); ``native_wire=True`` prices the
native-hardware projection instead, which is where error-feedback int8
starts winning wire-bound buckets.
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["CostModel", "fit_cost_model", "analytic_cost_model",
           "strategy_factors"]

# analytic fallbacks (CPU-host-mesh magnitudes; a real-hardware fitting
# run replaces them through the fitted path, never by editing these)
DEFAULT_ALPHA_MS = 0.05     # per-hop launch/latency cost
DEFAULT_BW_GBPS = 2.0       # effective per-link bandwidth
# fraction of a bucket's in-budget cost the scheduler actually hides;
# fitted from the report's measured overlap_frac when present
DEFAULT_OVERLAP_EFF = 0.6
ASYNC_OVERLAP_BONUS = 0.2   # scheduled start/await > hoped-for hoisting

# analytic compute cost of the EMULATED quantized wire (the cast /
# scale+round+clip passes over the payload that bracket the psum) —
# charged when pricing a quant mode the report did NOT measure, so the
# model never calls quantization free just because it shrinks bytes
# (verified the hard way: an unpenalized model picked bf16 on the CPU
# host mesh and measured 40% slower; the magnitudes below back out of
# that measured gap, ~2e-5 ms/B of payload on this class of host). A
# report whose points WERE measured under a mode — e.g. the multichip
# bench's `_int8` variant config — carries the cost inside its fitted
# line and pays no penalty, which is how a real-hardware fitting run
# (where the VPU makes the cast ~free) legalizes quantization without
# editing these hand numbers.
QUANT_COMPUTE_MS_PER_BYTE = {"none": 0.0, "bf16": 2e-5, "int8": 4e-5}


def strategy_factors(strategy: str, nranks: int,
                     stage_sizes: Optional[Sequence[int]] = None
                     ) -> Tuple[float, float]:
    """(launches, bw_factor) of a reduction spelling at fan-in
    ``nranks`` — a price of what ``strategy_psum`` actually EXECUTES,
    not of textbook algorithms the lowering doesn't use:

    - ``ring``: ONE fused XLA psum; busiest-link bytes 2(n-1)/n of the
      payload (the bandwidth floor).
    - ``tree``: TWO collectives (reduce_scatter + all_gather), same
      total bytes as ring — it pays an extra launch/sync for exposing
      the decomposition to the scheduler. (The binomial-tree /
      latency-optimal variant is a real-hardware concern the fitted
      terms of a real run would capture; pricing it here would mis-rank
      the spelling that actually executes.)
    - ``two_stage``: one FULL-payload psum per mesh axis
      (``stage_sizes``; defaults to a balanced 2-way split) — each
      stage moves 2(s-1)/s of the payload on its axis. Wins only where
      per-axis wire speeds genuinely differ (hierarchical topologies),
      which per-axis fitted terms are the future hook for.
    """
    n = max(1, int(nranks))
    if n == 1:
        return 0.0, 0.0
    if strategy == "tree":
        return 2.0, 2.0 * (n - 1) / n
    if strategy == "two_stage":
        sizes = [s for s in (stage_sizes or ()) if s and s > 1]
        if not sizes:
            a = 2 ** (math.ceil(math.log2(n)) // 2)
            sizes = [max(2, int(a)), max(1, n // max(2, int(a)))]
        bw = sum(2.0 * (s - 1) / s for s in sizes)
        return float(len(sizes)), bw
    # ring (the single fused psum XLA emits)
    return 1.0, 2.0 * (n - 1) / n


class CostModel:
    """Per-kind ``a + b*bytes`` collective terms + compute terms, each
    tagged ``fitted`` or ``analytic``. ``provenance`` is the weakest
    tag any consumed term carries — a score is only "fitted" when
    every number behind it was measured."""

    def __init__(self, nranks: int, terms: Dict[str, Tuple[float, float]],
                 compute_ms: float, backward_segments: List,
                 fitted_kinds: frozenset, base_strategy: str = "ring",
                 overlap_eff: float = DEFAULT_OVERLAP_EFF,
                 compute_fitted: bool = False,
                 overhead_ms: float = 0.0, base_quant: str = "none"):
        self.nranks = max(1, int(nranks))
        self.terms = dict(terms)          # kind -> (a_ms, b_ms_per_byte)
        self.compute_ms = float(compute_ms)
        self.backward_segments = [tuple(s) for s in backward_segments]
        self.fitted_kinds = frozenset(fitted_kinds)
        self.base_strategy = base_strategy
        self.overlap_eff = float(overlap_eff)
        self.compute_fitted = bool(compute_fitted)
        # fixed per-step cost outside compute+collectives (dispatch,
        # fetch, host glue) — measured as the report's whole-step time
        # minus its attributed phases. Constant across candidates, so
        # it never changes a ranking; it anchors predicted_step_ms to
        # the same clock the bench measures, which is what makes the
        # placement_agreement drift metric readable.
        self.overhead_ms = max(0.0, float(overhead_ms))
        # the wire mode the fitted points were measured under — that
        # mode's quantize compute is already inside the fitted line
        self.base_quant = base_quant

    # -- provenance ---------------------------------------------------------

    def term_provenance(self, kind: str) -> str:
        return "fitted" if kind in self.fitted_kinds else "analytic"

    @property
    def provenance(self) -> str:
        """Whole-model tag: fitted only when the compute floor AND at
        least one collective term came from measurement."""
        return ("fitted" if self.compute_fitted and self.fitted_kinds
                else "analytic")

    # -- collective pricing -------------------------------------------------

    def quant_penalty_ms(self, quant: str, nbytes: float) -> float:
        """Analytic quantize-compute charge for a wire mode the report
        did not measure (0 for exact wire or the fitted base mode)."""
        if quant in (None, "", "none") or quant == self.base_quant:
            return 0.0
        return QUANT_COMPUTE_MS_PER_BYTE.get(quant, 0.0) * nbytes

    def collective_ms(self, kind: str, nbytes: float,
                      strategy: str = "ring",
                      stage_sizes: Optional[Sequence[int]] = None,
                      quant: str = "none") -> float:
        """Serial cost of one collective of ``kind`` moving ``nbytes``
        under ``strategy`` and wire mode ``quant``. The per-kind
        (a, b) describe the model's BASE strategy; other spellings
        re-scale through the alpha-beta factors; unmeasured quant
        modes add the analytic quantize-compute penalty."""
        a, b = self.terms.get(kind, self.terms.get("allreduce",
                                                   (DEFAULT_ALPHA_MS, 0.0)))
        pen = self.quant_penalty_ms(quant, nbytes)
        base_ln, base_bw = strategy_factors(self.base_strategy,
                                            self.nranks, stage_sizes)
        launches, bw = strategy_factors(strategy, self.nranks,
                                        stage_sizes)
        if base_ln <= 0 or base_bw <= 0:
            return a + b * nbytes + pen
        # the fitted intercept is the per-launch cost of the BASE
        # spelling; the fitted slope is its per-byte cost at the base
        # busiest-link factor
        alpha = a / base_ln
        beta_unit = b / base_bw
        return alpha * launches + beta_unit * bw * nbytes + pen

    def hide_budget_ms(self, pos: int) -> float:
        """Measured backward compute remaining after compute position
        ``pos`` — the same budget rule the PR-10 bucket planner uses."""
        return sum(float(ms) for _s, e, ms in self.backward_segments
                   if e > pos)

    # -- whole-schedule scoring ---------------------------------------------

    def predict(self, schedule: Sequence[Dict],
                async_scheduled: bool = False) -> Dict:
        """Predicted step time for a candidate collective schedule.

        ``schedule``: one dict per collective —
        ``{"kind", "bytes", "avail_pos", "strategy"[, "stage_sizes"]}``
        (``avail_pos`` None = nothing to hide behind, e.g. the
        optimizer-phase allgather of a sharded update). Returns
        ``{"step_ms", "compute_ms", "collective_ms", "exposed_ms",
        "overlap_eff", "provenance", "per_collective"}``.

        Exposure rule: a collective overlaps ``overlap_eff`` of
        ``min(cost, hide_budget(avail_pos))`` — the efficiency is the
        report's measured overlap_frac (fitted) or the analytic
        default, plus a bounded bonus when the start/await pass
        schedules the overlap explicitly instead of leaving hoisted
        psums to XLA.
        """
        eff = min(1.0, self.overlap_eff
                  + (ASYNC_OVERLAP_BONUS if async_scheduled else 0.0))
        per = []
        coll_total = 0.0
        exposed_total = 0.0
        prov = "fitted" if self.compute_fitted else "analytic"
        for c in schedule:
            quant = c.get("quant", "none")
            nbytes = float(c.get("bytes", 0))
            cost = self.collective_ms(c["kind"], nbytes,
                                      c.get("strategy", "ring"),
                                      c.get("stage_sizes"), quant=quant)
            pos = c.get("avail_pos")
            budget = 0.0 if pos is None else self.hide_budget_ms(pos)
            hidden = eff * min(cost, budget)
            exposed = max(0.0, cost - hidden)
            if self.term_provenance(c["kind"]) == "analytic" \
                    or self.quant_penalty_ms(quant, nbytes) > 0:
                prov = "analytic"
            coll_total += cost
            exposed_total += exposed
            per.append({"kind": c["kind"], "bytes": c.get("bytes", 0),
                        "strategy": c.get("strategy", "ring"),
                        "cost_ms": cost, "hidden_ms": hidden,
                        "exposed_ms": exposed,
                        "provenance": self.term_provenance(c["kind"])})
        return {
            "step_ms": self.compute_ms + self.overhead_ms
            + exposed_total,
            "compute_ms": self.compute_ms,
            "overhead_ms": self.overhead_ms,
            "collective_ms": coll_total,
            "exposed_ms": exposed_total,
            "overlap_eff": eff,
            "provenance": prov,
            "per_collective": per,
        }


# ---------------------------------------------------------------------------
# fitting
# ---------------------------------------------------------------------------


def _fit_line(points: List[Tuple[float, float]]
              ) -> Optional[Tuple[float, float]]:
    """Least-squares ``a + b*x`` with the PR-10 single-point rule: one
    measured point cannot separate latency from bandwidth, so a 10%%
    floor stands in for the intercept (splitting is never free)."""
    pts = [(float(x), float(y)) for x, y in points if x > 0 and y > 0]
    if not pts:
        return None
    xs = [p[0] for p in pts]
    ys = [p[1] for p in pts]
    if len(set(xs)) >= 2:
        n = float(len(pts))
        mx = sum(xs) / n
        my = sum(ys) / n
        var = sum((x - mx) ** 2 for x in xs)
        slope = sum((x - mx) * (y - my) for x, y in pts) / var
        icept = my - slope * mx
        if slope <= 0:  # degenerate (noise-dominated) fit
            slope = my / mx if mx else 0.0
            icept = 0.0
        return max(0.0, icept), max(0.0, slope)
    icept = 0.1 * ys[0]
    slope = max(0.0, ys[0] - icept) / xs[0] if xs[0] else 0.0
    return icept, slope


def analytic_cost_model(nranks: int,
                        compute_ms: float = 0.0) -> CostModel:
    """Hand-estimate fallback: ring alpha-beta terms from
    ``DEFAULT_ALPHA_MS`` / ``DEFAULT_BW_GBPS`` for every kind. Every
    score it produces carries ``provenance="analytic"``."""
    n = max(1, int(nranks))
    hops, bw = strategy_factors("ring", n)
    a = DEFAULT_ALPHA_MS * hops
    b = bw / (DEFAULT_BW_GBPS * 1e6)  # ms per byte
    terms = {k: (a, b) for k in ("allreduce", "allgather",
                                 "reducescatter", "ppermute",
                                 "alltoall", "sharded_update")}
    return CostModel(nranks=n, terms=terms, compute_ms=compute_ms,
                     backward_segments=[], fitted_kinds=frozenset(),
                     overlap_eff=DEFAULT_OVERLAP_EFF,
                     compute_fitted=False)


def fit_cost_model(report: Optional[Dict],
                   nranks: Optional[int] = None) -> CostModel:
    """Fit a :class:`CostModel` to a step-profile report; falls back to
    :func:`analytic_cost_model` terms for anything the report cannot
    pin (missing kinds, no compute phases), recording exactly which
    terms were measured. A None/unusable report returns the pure
    analytic model."""
    from ..observability.steering import coerce_report

    report = coerce_report(report) if report is not None else None
    n = int(nranks or (report or {}).get("nranks") or 1)
    base = analytic_cost_model(n)
    if report is None:
        return base

    by_kind: Dict[str, List[Tuple[float, float]]] = {}
    strategies = set()
    quants = set()
    for b in report.get("per_bucket") or []:
        x = float(b.get("bytes") or 0)
        y = float(b.get("collective_ms") or 0)
        if x <= 0 or y <= 0:
            continue
        by_kind.setdefault(b.get("kind") or "allreduce", []).append((x, y))
        strategies.add(b.get("strategy", "ring"))
        quants.add(b.get("quant", "none"))
    terms = dict(base.terms)
    fitted = set()
    for kind, pts in by_kind.items():
        line = _fit_line(pts)
        if line is not None:
            terms[kind] = line
            fitted.add(kind)

    phase_ms = report.get("phase_ms") or {}
    compute_ms = sum(float(v) for k, v in phase_ms.items()
                     if k != "collective" and isinstance(v, (int, float)))
    compute_fitted = compute_ms > 0

    overlap = report.get("overlap_frac")
    eff = (float(overlap) if isinstance(overlap, (int, float))
           and 0.0 < float(overlap) <= 1.0 else DEFAULT_OVERLAP_EFF)
    # fixed per-step overhead: whole-step time minus attributed phases
    # (collective exposure counted at the measured overlap). The raw
    # profiler report names the whole-step time "step_ms"; a bench
    # record's profile block renames it "profiled_step_ms" (bench.py
    # _profile_record) — accept both, since the bench block is the
    # documented report source.
    overhead = 0.0
    step_ms = report.get("step_ms")
    if not isinstance(step_ms, (int, float)):
        step_ms = report.get("profiled_step_ms")
    if isinstance(step_ms, (int, float)) and compute_fitted:
        exp = report.get("exposed_collective_ms")
        exp = float(exp) if isinstance(exp, (int, float)) else 0.0
        overhead = max(0.0, float(step_ms) - compute_ms - exp)
    # the report measured ONE strategy; record it so transfers re-scale
    base_strategy = strategies.pop() if len(strategies) == 1 else "ring"
    return CostModel(
        nranks=n, terms=terms, compute_ms=compute_ms,
        backward_segments=[s for s in
                           (report.get("backward_segments") or [])
                           if isinstance(s, (list, tuple))
                           and len(s) == 3],
        fitted_kinds=frozenset(fitted),
        base_strategy=base_strategy if base_strategy in
        ("ring", "tree", "two_stage") else "ring",
        overlap_eff=eff, compute_fitted=compute_fitted,
        overhead_ms=overhead,
        base_quant=(quants.pop() if len(quants) == 1 else "none"))
