"""Typed errors + enforce helpers.

Parity: /root/reference/paddle/fluid/platform/enforce.h:261
(PADDLE_ENFORCE / EnforceNotMet) and errors.h's typed error taxonomy.
Framework raise sites funnel through these so users get op/var context
instead of bare KeyErrors from deep in the registry.
"""
from __future__ import annotations

__all__ = [
    "EnforceNotMet",
    "InvalidArgumentError",
    "NotFoundError",
    "OutOfRangeError",
    "AlreadyExistsError",
    "PermissionDeniedError",
    "UnimplementedError",
    "PreconditionNotMetError",
    "ExecutionTimeoutError",
    "enforce",
    "enforce_not_none",
]


class EnforceNotMet(RuntimeError):
    """Base framework error (reference EnforceNotMet)."""


class InvalidArgumentError(EnforceNotMet, ValueError):
    pass


class NotFoundError(EnforceNotMet, KeyError):
    def __str__(self):  # KeyError quotes its arg; keep it readable
        return RuntimeError.__str__(self)


class OutOfRangeError(EnforceNotMet, IndexError):
    pass


class AlreadyExistsError(EnforceNotMet):
    pass


class PermissionDeniedError(EnforceNotMet):
    pass


class UnimplementedError(EnforceNotMet, NotImplementedError):
    pass


class PreconditionNotMetError(EnforceNotMet):
    pass


class ExecutionTimeoutError(EnforceNotMet, TimeoutError):
    pass


def enforce(cond, message, error_cls=EnforceNotMet):
    if not cond:
        raise error_cls(message)


def enforce_not_none(value, message, error_cls=NotFoundError):
    if value is None:
        raise error_cls(message)
    return value


def _cmp_enforce(name, ok):
    def check(a, b, message="", error_cls=InvalidArgumentError):
        if not ok(a, b):
            raise error_cls(
                "%sExpected %r %s %r." % (message + " " if message
                                          else "", a, name, b))
    return check


# PADDLE_ENFORCE_EQ family (enforce.h:300+): failures show both sides
enforce_eq = _cmp_enforce("==", lambda a, b: a == b)
enforce_ne = _cmp_enforce("!=", lambda a, b: a != b)
enforce_gt = _cmp_enforce(">", lambda a, b: a > b)
enforce_ge = _cmp_enforce(">=", lambda a, b: a >= b)
enforce_lt = _cmp_enforce("<", lambda a, b: a < b)
enforce_le = _cmp_enforce("<=", lambda a, b: a <= b)


def annotate_op_error(exc: BaseException, op, phase: str) -> None:
    """Append operator context to an in-flight exception, preserving
    its type and traceback — the reference wraps every kernel failure
    in EnforceNotMet carrying the op's signature (operator.cc:157
    catch + exception_holder). Mutating args keeps pytest.raises and
    user except-clauses working on the original type."""
    ctx = "\n  [operator %r error during %s; inputs: %s; outputs: %s]" % (
        getattr(op, "type", "?"), phase,
        {k: v for k, v in getattr(op, "inputs", {}).items()},
        {k: v for k, v in getattr(op, "outputs", {}).items()})
    if exc.args and isinstance(exc.args[0], str):
        if ctx in exc.args[0]:
            return  # nested run_op frames annotate once
        exc.args = (exc.args[0] + ctx,) + exc.args[1:]
    else:
        exc.args = exc.args + (ctx,)
