"""Benchmark driver — prints ONE JSON line on stdout.

Protocol (BASELINE.md): synthetic data staged ON DEVICE (a real input
pipeline overlaps host->device transfer — DataLoader's double-buffer
prefetch provides that; this host's tunnel uploads are also anomalously
slow under load, which would otherwise dominate), warm-up excluded,
each timed window hard-synced by a device->host fetch of the loss.

Headline metric: ResNet-50 ImageNet images/sec on the one available chip
(BASELINE.json north-star config 2). The reference publishes no in-repo
numbers; ``vs_baseline`` is computed against the fluid-era CUDA per-chip
anchor of 360 images/sec (ResNet-50 fp32 on the V100 generation the
reference targets) — the north star asks for >=90% of CUDA per-chip.
Secondary metrics (MNIST MLP steps/sec, MFU estimate) ride in "extras".
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

CUDA_PER_CHIP_ANCHOR_IMG_S = 360.0  # ResNet-50 fp32 per-chip, V100 era


def _device_feed(arrays):
    """Stage the synthetic batch on device once (input-pipeline overlap
    assumed; see module docstring)."""
    import jax.numpy as jnp

    from paddle_tpu.core.tensor import LoDTensor

    return {k: LoDTensor(jnp.asarray(v)) for k, v in arrays.items()}


def _resnet_img_shape(batch, data_format):
    return ((batch, 3, 224, 224) if data_format == "NCHW"
            else (batch, 224, 224, 3))


def _build_resnet50(batch, use_bf16=False, data_format="NCHW"):
    import paddle_tpu as fluid
    from paddle_tpu import models

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.data(name="img",
                         shape=list(_resnet_img_shape(batch, data_format)),
                         dtype="float32")
        label = fluid.data(name="label", shape=[batch, 1], dtype="int64")
        pred = models.resnet50(img, data_format=data_format)
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
        opt = fluid.optimizer.MomentumOptimizer(learning_rate=0.1,
                                                momentum=0.9)
        if use_bf16:
            try:
                from paddle_tpu.contrib import mixed_precision as mp
            except ImportError:
                use_bf16 = False  # AMP not built yet — measure f32
            else:
                opt = mp.decorate(opt)  # bf16 defaults: no loss scaling
        opt.minimize(loss)
    return main, startup, loss, use_bf16


def _build_mnist_mlp(batch):
    import paddle_tpu as fluid
    from paddle_tpu import models

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data(name="x", shape=[batch, 784], dtype="float32")
        label = fluid.data(name="label", shape=[batch, 1], dtype="int64")
        pred = models.mlp(x)
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
        fluid.optimizer.AdamOptimizer(1e-3).minimize(loss)
    return main, startup, loss


def _measure_feed(feed, reps=5):
    """Per-step feed staging cost for this batch, both ways: SYNC =
    hard-synced H2D from host memory (what a naive per-step input
    pipeline pays on the critical path), ASYNC = the consumer-side
    stall with the double-buffered AsyncDeviceFeeder staging ahead
    (what remains under PADDLE_TPU_ASYNC_FEED). Returns
    (feed_ms_async, feed_ms_sync)."""
    import jax

    from paddle_tpu.core.native_feed import AsyncDeviceFeeder
    from paddle_tpu.core.tensor import LoDTensor

    host = {k: np.asarray(v.array if isinstance(v, LoDTensor) else v)
            for k, v in feed.items()}
    sync = float("inf")
    for _ in range(max(1, reps)):
        t0 = time.perf_counter()
        jax.block_until_ready([jax.device_put(v) for v in host.values()])
        sync = min(sync, time.perf_counter() - t0)
    waits = []
    with AsyncDeviceFeeder((host for _ in range(reps + 2))) as fdr:
        next(fdr)  # cold pop: nothing was staged ahead of it yet
        while True:
            # "compute" the staging should hide behind, then measure
            # what fetching the NEXT (pre-staged) batch still costs
            # on the critical path
            time.sleep(sync * 2)
            t0 = time.perf_counter()
            try:
                batch = next(fdr)
            except StopIteration:
                break
            jax.block_until_ready(list(batch.values()))
            waits.append(time.perf_counter() - t0)
    return (min(waits) * 1e3 if waits else 0.0, sync * 1e3)


def _time_steps(exe, main, feed, loss, warmup=3, iters=20, windows=2,
                window_gap_s=0.0):
    """Timed windows, each HARD-synced by a numpy loss fetch.

    Protocol: `windows` windows of `iters` steps; in a window the first
    iters-1 steps keep results on device and the last step fetches the
    loss to numpy — the d2h is the only sync this remote runtime honors,
    so it is part of the timed window (a ~d2h/iters overestimate of step
    time, i.e. conservative). The faster window is used: d2h cost is
    variable and only ever inflates a window. ``window_gap_s`` sleeps
    between windows so a transient tunnel-pool degradation doesn't hit
    every window (round-3 diagnosis aid).

    Returns (dt, final_loss, diag) where diag records per-window wall
    times and whether the program took the whole-compile path — the
    round-3 BERT collapse was a silent interpreter fallback, and this
    makes any recurrence legible in BENCH json. Step/compile/recompile
    counts come from the observability registry (the same counters a
    production deployment would scrape), not hand-rolled probes.
    """
    from paddle_tpu import observability as obs

    obs.enable()

    def _counts():
        return {
            "steps_compiled": obs.counter_value("executor.steps",
                                                path="compiled"),
            "steps_interpreter": obs.counter_value("executor.steps",
                                                   path="interpreter"),
            "compiles": obs.counter_value("executor.compiles"),
            "compile_fallbacks": obs.counter_value(
                "executor.compile_fallbacks"),
        }

    from paddle_tpu.core.native_feed import async_feed_enabled

    use_async = async_feed_enabled()
    host_feed = None
    if use_async:
        from paddle_tpu.core.tensor import LoDTensor as _LT

        # PADDLE_TPU_ASYNC_FEED: the timed loop feeds from HOST
        # memory through the double-buffered feeder (the realistic
        # input pipeline), not the pre-staged device dict — H2D of
        # step N+1 overlaps compute of step N
        host_feed = {k: np.asarray(v.array if isinstance(v, _LT)
                                   else v) for k, v in feed.items()}

    def run_n(n):
        """n-1 device-resident steps + one numpy-fetch step: the final
        d2h is the only HARD sync this remote runtime honors
        (block_until_ready returns early through the tunnel), so every
        window ends with one."""
        t0 = time.time()
        if use_async:
            from paddle_tpu.core.native_feed import AsyncDeviceFeeder

            with AsyncDeviceFeeder(
                    (host_feed for _ in range(n))) as fdr:
                o = None
                for i, fb in enumerate(fdr):
                    if i < n - 1:
                        exe.run(main, feed=fb, fetch_list=[loss],
                                return_numpy=False)
                    else:
                        (o,) = exe.run(main, feed=fb,
                                       fetch_list=[loss])
            return time.time() - t0, float(np.asarray(o).ravel()[0])
        for _ in range(n - 1):
            exe.run(main, feed=feed, fetch_list=[loss],
                    return_numpy=False)
        (o,) = exe.run(main, feed=feed, fetch_list=[loss])
        return time.time() - t0, float(np.asarray(o).ravel()[0])

    t_compile = time.time()
    for _ in range(warmup):
        exe.run(main, feed=feed, fetch_list=[loss], return_numpy=False)
    run_n(1)  # sync point + first (expensive) d2h out of the way
    t_compile = time.time() - t_compile
    c_warm = _counts()
    times = []
    final_loss = float("nan")
    for w in range(windows):
        if w and window_gap_s:
            time.sleep(window_gap_s)
        t, final_loss = run_n(iters)
        times.append(t)
    dt = min(times) / iters
    c_end = _counts()
    timed = {k: c_end[k] - c_warm[k] for k in c_end}
    # whole_compile reflects what the TIMED windows actually executed:
    # any interpreter step during them IS the round-3 silent collapse
    # (counter covers both the fallback path and the never-attempted
    # untraceable path — both land in executor.steps{path=interpreter})
    whole = timed["steps_interpreter"] == 0 and timed["steps_compiled"] > 0
    try:
        feed_ms, feed_ms_sync = _measure_feed(feed)
    except Exception:   # feed measurement must never kill a bench
        feed_ms, feed_ms_sync = None, None
    diag = {
        "windows_s": [round(t, 3) for t in times],
        "warmup_s": round(t_compile, 1),
        # per-step feed staging: critical-path cost with the async
        # double buffer (feed_ms — what the timed loop pays when
        # PADDLE_TPU_ASYNC_FEED=1) vs the sync H2D a naive per-step
        # pipeline would pay (feed_ms_sync); bench_diff watches
        # feed_ms so the overlap win is gated, not hoped for
        "feed_ms": feed_ms,
        "feed_ms_sync": feed_ms_sync,
        "async_feed": use_async,
        "whole_compile": whole,
        # single-chip runs move zero collective bytes — recorded
        # explicitly so bench_diff.py can diff single- and multi-chip
        # records under one schema
        "collective_bytes": 0,
        # recompiles during the timed windows: nonzero means signature
        # churn is recompiling the program mid-measurement
        "recompiles": timed["compiles"],
        "steps": {"compiled": timed["steps_compiled"],
                  "interpreter": timed["steps_interpreter"]},
        "warmup_compiles": c_warm["compiles"],
    }
    if not whole:
        from paddle_tpu.core.compiler_engine import (_program_version,
                                                     untraceable_reasons)

        fb = exe._compile_fallbacks.get(_program_version(main))
        diag["fallback"] = (str(fb)[:200] if fb is not None else
                            "untraceable: %s" % ", ".join(
                                untraceable_reasons(
                                    main.global_block()))[:200])
    return dt, final_loss, diag


def _profile_phases_enabled(default: bool) -> bool:
    """Measured phase breakdown on/off: ``PADDLE_TPU_PROFILE_BENCH``
    overrides either way; unset keeps the caller's default (ON for
    multichip configs — cheap CPU-mesh shapes, and the overlap number
    is the point — OFF for single-chip runs where phase-sliced
    re-execution means extra whole-program compiles through the
    tunnel)."""
    raw = os.environ.get("PADDLE_TPU_PROFILE_BENCH", "").strip().lower()
    if not raw:
        return default
    return raw in ("1", "true", "yes", "on")


def _device_trace_enabled(default: bool) -> bool:
    """XPlane device-trace capture on/off: ``PADDLE_TPU_DEVICE_TRACE``
    overrides either way; unset keeps the caller's default (ON for
    multichip configs — the host-vs-device cross-check is this bench's
    trust anchor — OFF for single-chip runs, same convention as the
    phase breakdown)."""
    from paddle_tpu.observability import device_trace as dtr

    return dtr.capture_enabled(default)


def _profile_record(step_s, flops_total, by_category=None, bf16=False,
                    n_devices=1, program=None, scope=None, feed=None,
                    mesh=None, phases_default=False,
                    device_default=False):
    """The ``profile`` block every bench record carries — ONE schema
    for single-chip and multichip runs: analytic FLOPs + registry-
    derived ``mfu_est`` always; measured phase breakdown / overlap /
    critical path when phase profiling is enabled and a static program
    is available; DEVICE-folded phase breakdown + host-vs-device
    agreement when XPlane capture is enabled
    (``tools/bench_diff.py`` diffs these fields)."""
    from paddle_tpu.observability import profiler as prof

    rec = {
        "flops_per_step": int(flops_total),
        "mfu_est": prof.mfu_est(flops_total, step_s, bf16=bf16,
                                n_devices=n_devices),
        "peak_flops": prof.peak_flops(bf16, n_devices),
        "n_devices": int(n_devices),
    }
    if by_category:
        rec["flops_by_category"] = {k: int(v)
                                    for k, v in by_category.items()}
    if program is not None and _profile_phases_enabled(phases_default):
        try:
            rep = prof.profile_step(program, scope, feed, mesh=mesh)
            rec.update({
                "phase_ms": rep["phase_ms"],
                "feed_ms": rep.get("feed_ms"),
                "optimizer_ms": rep.get("optimizer_ms"),
                "overlap_frac": rep["overlap_frac"],
                "critical_path_ms": rep["critical_path_ms"],
                "exposed_collective_ms": rep["exposed_collective_ms"],
                "serialized_ms": rep["serialized_ms"],
                "per_bucket": rep["per_bucket"],
                "backward_segments": rep["backward_segments"],
                "n_compute": rep["n_compute"],
                "nranks": rep.get("nranks"),
                "profiled_step_ms": rep["step_ms"],
                "exposed_includes_fused_update":
                    rep["exposed_includes_fused_update"],
            })
        except Exception as e:  # the bench number survives a broken
            rec["phase_error"] = repr(e)  # profile, never vice versa
    if program is not None and _device_trace_enabled(device_default):
        try:
            from paddle_tpu.observability import device_trace as dtr

            dev = dtr.device_profile_step(program, scope, feed,
                                          mesh=mesh)
            if dev is None:
                # annotation-less / empty capture: the host numbers
                # stand alone, flagged so readers know why
                rec["device_trace"] = {"status": "empty",
                                       "fallback": "host"}
            else:
                rec["device_phase_ms"] = dev["device_phase_ms"]
                rec["device_overlap_frac"] = dev["overlap_frac"]
                rec["device_critical_path_ms"] = dev["critical_path_ms"]
                rec["device_exposed_collective_ms"] = \
                    dev["exposed_collective_ms"]
                rec["device_trace"] = {
                    k: dev[k] for k in ("n_events", "n_attributed",
                                        "unattributed_ms", "steps",
                                        "source")}
                if isinstance(rec.get("phase_ms"), dict):
                    cc = dtr.cross_check(rec["phase_ms"],
                                         dev["device_phase_ms"])
                    rec["host_device_agreement"] = cc["agreement"]
                    rec["agreement_per_phase"] = cc["per_phase"]
                    from paddle_tpu import observability as _obs

                    if _obs.enabled() and cc["agreement"] is not None:
                        _obs.set_gauge("profile.host_device_agreement",
                                       cc["agreement"])
        except Exception as e:  # same contract as the host phases
            rec["device_trace_error"] = repr(e)
    return rec


def _program_profile(main, scope, feed, step_s, bf16=False, mesh=None,
                     n_devices=1, phases_default=False, flops_scale=1,
                     device_default=False):
    """``flops_scale`` converts the PROGRAM's analytic FLOPs into the
    job step's: per-replica-built multichip models (bert/gpt built at
    batch/n, every replica runs one) scale by n_devices so mfu_est is
    consistent with the global-throughput numbers beside it."""
    from paddle_tpu.observability import profiler as prof

    fl = prof.program_flops(main, scope)
    return _profile_record(step_s, fl["total"] * flops_scale,
                           {k: v * flops_scale
                            for k, v in fl["by_category"].items()},
                           bf16=bf16, n_devices=n_devices, program=main,
                           scope=scope, feed=feed, mesh=mesh,
                           phases_default=phases_default,
                           device_default=device_default)


def bench_resnet50(batch=128, iters=12, use_bf16=False,
                   data_format="NCHW"):
    import paddle_tpu as fluid

    main, startup, loss, use_bf16 = _build_resnet50(
        batch, use_bf16=use_bf16, data_format=data_format)
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    feed = _device_feed({
        "img": rng.rand(*_resnet_img_shape(batch,
                                           data_format)).astype("float32"),
        "label": rng.randint(0, 1000, (batch, 1)).astype("int64"),
    })
    dt, final_loss, diag = _time_steps(exe, main, feed, loss, iters=iters)
    if not np.isfinite(final_loss):
        raise RuntimeError("resnet50 diverged: loss=%r" % final_loss)
    return {"images_per_sec": batch / dt, "step_ms": dt * 1e3,
            "batch": batch, "loss": final_loss, "bf16": use_bf16,
            "data_format": data_format, "diag": diag,
            "profile": _program_profile(main, fluid.global_scope(),
                                        feed, dt, bf16=use_bf16)}


def bench_mnist_mlp(batch=512, iters=100):
    import paddle_tpu as fluid

    main, startup, loss = _build_mnist_mlp(batch)
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    feed = _device_feed({
        "x": rng.rand(batch, 784).astype("float32"),
        "label": rng.randint(0, 10, (batch, 1)).astype("int64"),
    })
    dt, final_loss, diag = _time_steps(exe, main, feed, loss, iters=iters)
    if not np.isfinite(final_loss):
        raise RuntimeError("mnist mlp diverged: loss=%r" % final_loss)
    return {"steps_per_sec": 1.0 / dt, "examples_per_sec": batch / dt,
            "step_ms": dt * 1e3, "batch": batch, "loss": final_loss,
            "diag": diag,
            "profile": _program_profile(main, fluid.global_scope(),
                                        feed, dt)}


def _build_bert_base(batch, seq_len, use_bf16=False):
    import paddle_tpu as fluid
    from paddle_tpu import models

    M = 20  # masked positions per sample
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        src = fluid.data(name="src", shape=[batch, seq_len], dtype="int64")
        pos = fluid.data(name="pos", shape=[batch, seq_len], dtype="int64")
        mpos = fluid.data(name="mpos", shape=[batch, M], dtype="int64")
        labels = fluid.data(name="labels", shape=[batch, M, 1],
                            dtype="int64")
        logits = models.bert_base_pretrain(src, pos, mpos,
                                           vocab_size=30522,
                                           max_len=seq_len)
        loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(
            fluid.layers.reshape(logits, [batch * M, 30522]),
            fluid.layers.reshape(labels, [batch * M, 1])))
        opt = fluid.optimizer.AdamOptimizer(1e-4)
        if use_bf16:
            try:
                from paddle_tpu.contrib import mixed_precision as mp
            except ImportError:
                use_bf16 = False
            else:
                opt = mp.decorate(opt)
        opt.minimize(loss)
    return main, startup, loss, M, use_bf16


def bench_bert_base(batch=32, seq_len=128, iters=30, use_bf16=True):
    import paddle_tpu as fluid

    main, startup, loss, M, use_bf16 = _build_bert_base(batch, seq_len,
                                                        use_bf16)
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    feed = _device_feed({
        "src": rng.randint(0, 30522, (batch, seq_len)).astype("int64"),
        "pos": np.tile(np.arange(seq_len), (batch, 1)).astype("int64"),
        "mpos": rng.randint(0, seq_len, (batch, M)).astype("int64"),
        "labels": rng.randint(0, 30522, (batch, M, 1)).astype("int64"),
    })
    from paddle_tpu.core.compiler_engine import (block_is_traceable,
                                                 untraceable_reasons)

    if not block_is_traceable(main.global_block()):
        # round-3 collapse guard: a single host op (then: `range`) drops
        # the 1440-op program to op-by-op interpretation, ~30x slow.
        # Fail loudly rather than record a meaningless number.
        raise RuntimeError(
            "bert program not whole-compilable; blockers: %s"
            % untraceable_reasons(main.global_block()))
    # three windows, the later ones separated in time — distinguishes a
    # transient degraded tunnel window from a persistent regression
    dt, final_loss, diag = _time_steps(exe, main, feed, loss, warmup=2,
                                       iters=iters, windows=3,
                                       window_gap_s=5.0)
    if not np.isfinite(final_loss):
        raise RuntimeError("bert diverged: loss=%r" % final_loss)
    return {"tokens_per_sec": batch * seq_len / dt, "step_ms": dt * 1e3,
            "batch": batch, "seq_len": seq_len, "loss": final_loss,
            "bf16": use_bf16, "diag": diag,
            "profile": _program_profile(main, fluid.global_scope(),
                                        feed, dt, bf16=use_bf16)}


def _build_transformer_wmt(batch, seq_len, use_bf16=False,
                           use_lengths=False):
    import paddle_tpu as fluid
    from paddle_tpu import models

    V = 32000
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        src = fluid.data(name="src", shape=[batch, seq_len], dtype="int64")
        spos = fluid.data(name="spos", shape=[batch, seq_len],
                          dtype="int64")
        tgt = fluid.data(name="tgt", shape=[batch, seq_len], dtype="int64")
        tpos = fluid.data(name="tpos", shape=[batch, seq_len],
                          dtype="int64")
        lbl = fluid.data(name="lbl", shape=[batch, seq_len, 1],
                         dtype="int64")
        slen = tlen = None
        if use_lengths:
            slen = fluid.data(name="slen", shape=[batch], dtype="int32")
            tlen = fluid.data(name="tlen", shape=[batch], dtype="int32")
        logits = models.transformer_wmt(src, spos, tgt, tpos,
                                        vocab_size=V, max_len=seq_len,
                                        src_lengths=slen,
                                        tgt_lengths=tlen)
        ce = fluid.layers.softmax_with_cross_entropy(
            fluid.layers.reshape(logits, [batch * seq_len, V]),
            fluid.layers.reshape(lbl, [batch * seq_len, 1]))
        if use_lengths:
            # padded target rows are masked out of the loss (the
            # realistic seq2seq objective — dist_transformer.py weights
            # by non-pad tokens)
            w = fluid.layers.cast(fluid.layers.sequence_mask(
                tlen, maxlen=seq_len), "float32")
            w = fluid.layers.reshape(w, [batch * seq_len, 1])
            loss = fluid.layers.reduce_sum(
                fluid.layers.elementwise_mul(ce, w)) / (
                fluid.layers.reduce_sum(w) + 1e-6)
        else:
            loss = fluid.layers.mean(ce)
        opt = fluid.optimizer.AdamOptimizer(1e-4)
        if use_bf16:
            try:
                from paddle_tpu.contrib import mixed_precision as mp
            except ImportError:
                use_bf16 = False
            else:
                opt = mp.decorate(opt)
        opt.minimize(loss)
    return main, startup, loss, V, use_bf16


def bench_transformer_wmt(batch=64, seq_len=256, iters=10, use_bf16=True,
                          use_lengths=True):
    """North-star config 4 (Transformer-base WMT seq2seq — reference
    tests/unittests/dist_transformer.py) at a REALISTIC shape: seq 256
    with per-example padding lengths; encoder and decoder
    self-attention route the masked pallas flash kernels (verified
    in-bench), the loss is masked to non-pad tokens, and convergence
    (loss drop on the fixed batch) is asserted — not just isfinite.
    Metric: non-pad target tokens/sec."""
    import paddle_tpu as fluid

    main, startup, loss, V, use_bf16 = _build_transformer_wmt(
        batch, seq_len, use_bf16, use_lengths=use_lengths)
    flash_ops = sum(1 for op in main.global_block().ops
                    if op.type == "flash_attention")
    if use_lengths and flash_ops < 12:  # 6 enc + 6 dec layers
        raise RuntimeError(
            "masked flash routing regressed: %d flash ops" % flash_ops)
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    pos = np.tile(np.arange(seq_len), (batch, 1)).astype("int64")
    feed_np = {
        "src": rng.randint(0, V, (batch, seq_len)).astype("int64"),
        "spos": pos, "tpos": pos,
        "tgt": rng.randint(0, V, (batch, seq_len)).astype("int64"),
        "lbl": rng.randint(0, V, (batch, seq_len, 1)).astype("int64"),
    }
    tok_per_step = batch * seq_len
    if use_lengths:
        # realistic padding mix: 50-100% fill, mean ~0.75
        slen = rng.randint(seq_len // 2, seq_len + 1,
                           (batch,)).astype("int32")
        tlen = rng.randint(seq_len // 2, seq_len + 1,
                           (batch,)).astype("int32")
        feed_np["slen"], feed_np["tlen"] = slen, tlen
        tok_per_step = int(tlen.sum())
    feed = _device_feed(feed_np)
    l0 = float(np.asarray(exe.run(main, feed=feed,
                                  fetch_list=[loss])[0]))
    dt, final_loss, diag = _time_steps(exe, main, feed, loss, warmup=2,
                                       iters=iters)
    if not np.isfinite(final_loss):
        raise RuntimeError("transformer diverged: loss=%r" % final_loss)
    if not final_loss < l0:
        raise RuntimeError("transformer did not train: %r -> %r"
                           % (l0, final_loss))
    return {"tokens_per_sec": tok_per_step / dt, "step_ms": dt * 1e3,
            "batch": batch, "seq_len": seq_len, "loss": final_loss,
            "loss0": l0, "bf16": use_bf16, "masked_flash": use_lengths,
            "flash_ops": flash_ops, "diag": diag,
            "profile": _program_profile(main, fluid.global_scope(),
                                        feed, dt, bf16=use_bf16)}


def _build_wide_deep(batch):
    import paddle_tpu as fluid
    from paddle_tpu import models

    V, S, DD = 100000, 26, 13  # criteo-ish: 26 sparse slots, 13 dense
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        dense = fluid.data(name="dense", shape=[batch, DD],
                           dtype="float32")
        sparse = fluid.data(name="sparse", shape=[batch, S],
                            dtype="int64")
        label = fluid.data(name="label", shape=[batch, 1], dtype="int64")
        pred = models.wide_deep(dense, sparse, vocab_size=V)
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
        fluid.optimizer.AdamOptimizer(1e-3).minimize(loss)
    return main, startup, loss, V, S, DD


def bench_wide_deep(batch=2048, iters=40):
    """North-star config 5 (Wide&Deep CTR — reference dist_ctr.py).
    Metric: examples/sec."""
    import paddle_tpu as fluid

    main, startup, loss, V, S, DD = _build_wide_deep(batch)
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    feed = _device_feed({
        "dense": rng.rand(batch, DD).astype("float32"),
        "sparse": rng.randint(0, V, (batch, S)).astype("int64"),
        "label": rng.randint(0, 2, (batch, 1)).astype("int64"),
    })
    dt, final_loss, diag = _time_steps(exe, main, feed, loss, iters=iters)
    if not np.isfinite(final_loss):
        raise RuntimeError("wide_deep diverged: loss=%r" % final_loss)
    return {"examples_per_sec": batch / dt, "step_ms": dt * 1e3,
            "batch": batch, "loss": final_loss, "diag": diag,
            "profile": _program_profile(main, fluid.global_scope(),
                                        feed, dt)}


def bench_dygraph_mlp(batch=256, iters=30, lazy=False):
    """Eager-mode bench through dygraph/tracer.py (the reference's
    imperative Tracer::TraceOp hot path, imperative/tracer.cc:45) —
    records per-op eager dispatch cost, which whole-program numbers
    hide. Metric: steps/sec (an MLP is ~10 traced ops + backward +
    optimizer per step). ``lazy=True`` measures the queued-dispatch
    mode (dygraph/lazy.py): ops flush as ONE cached compiled call per
    step instead of ~40 tunnel round-trips."""
    import paddle_tpu as fluid
    from paddle_tpu.dygraph import Linear, to_variable

    with fluid.dygraph.guard(lazy=lazy):
        l1 = Linear(784, 256, act="relu")
        l2 = Linear(256, 256, act="relu")
        l3 = Linear(256, 10)
        params = l1.parameters() + l2.parameters() + l3.parameters()
        opt = fluid.optimizer.AdamOptimizer(1e-3, parameter_list=params)
        rng = np.random.RandomState(0)
        x = rng.rand(batch, 784).astype("float32")
        y = rng.randint(0, 10, (batch, 1)).astype("int64")

        def step():
            logits = l3(l2(l1(to_variable(x))))
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(
                    logits, to_variable(y)))
            loss.backward()
            opt.minimize(loss, parameter_list=params)
            for p in params:
                p.clear_gradient()
            return loss

        for _ in range(3):
            loss = step()
        float(np.asarray(loss.numpy()).ravel()[0])  # sync
        t0 = time.time()
        for _ in range(iters):
            loss = step()
        final_loss = float(np.asarray(loss.numpy()).ravel()[0])  # sync
        dt = (time.time() - t0) / iters
    if not np.isfinite(final_loss):
        raise RuntimeError("dygraph mlp diverged: loss=%r" % final_loss)
    from paddle_tpu.observability import profiler as prof

    return {"steps_per_sec": 1.0 / dt, "examples_per_sec": batch / dt,
            "step_ms": dt * 1e3, "batch": batch, "loss": final_loss,
            "dispatch": "lazy" if lazy else "eager",
            # no static program in dygraph — the analytic formula IS
            # the registry entry for this shape
            "profile": _profile_record(
                dt, prof.flops_mlp(batch, (784, 256, 256, 10)))}


def bench_dygraph_bert(batch=32, seq_len=128, iters=8, n_layers=12,
                       d_model=768, n_heads=12, vocab=30522, lazy=True):
    """Dygraph BERT-base masked-LM step — north-star config 3 measured
    on the path its label names (BASELINE.md: the reference benches
    BERT through the imperative Tracer). Eager per-op dispatch through
    the tunnel is ~10ms/op x ~2000 ops; the lazy queue (dygraph/
    lazy.py) makes the eager API viable, so that is the recorded
    number. Metric: tokens/sec."""
    import paddle_tpu as fluid
    from paddle_tpu.dygraph import Embedding, LayerNorm, Linear, \
        to_variable

    head = d_model // n_heads
    with fluid.dygraph.guard(lazy=lazy):
        L = fluid.layers
        emb = Embedding(size=[vocab, d_model])
        pos = Embedding(size=[seq_len, d_model])
        blocks = []
        for _ in range(n_layers):
            blocks.append({
                "q": Linear(d_model, d_model),
                "k": Linear(d_model, d_model),
                "v": Linear(d_model, d_model),
                "o": Linear(d_model, d_model),
                "ln1": LayerNorm(d_model),
                "f1": Linear(d_model, d_model * 4, act="gelu"),
                "f2": Linear(d_model * 4, d_model),
                "ln2": LayerNorm(d_model),
            })
        out_proj = Linear(d_model, vocab)
        params = [p for b in blocks for lyr in b.values()
                  for p in lyr.parameters()]
        params += emb.parameters() + pos.parameters() + \
            out_proj.parameters()
        opt = fluid.optimizer.AdamOptimizer(1e-4, parameter_list=params)
        rng = np.random.RandomState(0)
        ids = rng.randint(0, vocab, (batch, seq_len)).astype("int64")
        pids = np.tile(np.arange(seq_len), (batch, 1)).astype("int64")
        lbl = rng.randint(0, vocab,
                          (batch * seq_len, 1)).astype("int64")

        def heads_of(t):
            t = L.reshape(t, [batch, seq_len, n_heads, head])
            return L.transpose(t, [0, 2, 1, 3])

        def step():
            x = emb(to_variable(ids)) + pos(to_variable(pids))
            for b in blocks:
                q, k, v = heads_of(b["q"](x)), heads_of(b["k"](x)), \
                    heads_of(b["v"](x))
                s = L.matmul(q, k, transpose_y=True,
                             alpha=float(head) ** -0.5)
                ctx = L.matmul(L.softmax(s), v)
                ctx = L.reshape(L.transpose(ctx, [0, 2, 1, 3]),
                                [batch, seq_len, d_model])
                x = b["ln1"](x + b["o"](ctx))
                x = b["ln2"](x + b["f2"](b["f1"](x)))
            logits = L.reshape(out_proj(x), [batch * seq_len, vocab])
            loss = L.mean(L.softmax_with_cross_entropy(
                logits, to_variable(lbl)))
            loss.backward()
            opt.minimize(loss, parameter_list=params)
            for p in params:
                p.clear_gradient()
            return loss

        for _ in range(2):
            loss = step()
        float(np.asarray(loss.numpy()).ravel()[0])  # sync
        t0 = time.time()
        for _ in range(iters):
            loss = step()
        final_loss = float(np.asarray(loss.numpy()).ravel()[0])
        dt = (time.time() - t0) / iters
    if not np.isfinite(final_loss):
        raise RuntimeError("dygraph bert diverged: loss=%r" % final_loss)
    from paddle_tpu.observability import profiler as prof

    return {"tokens_per_sec": batch * seq_len / dt, "step_ms": dt * 1e3,
            "batch": batch, "seq_len": seq_len, "loss": final_loss,
            "dispatch": "lazy" if lazy else "eager",
            "profile": _profile_record(
                dt, prof.flops_transformer_lm(batch, seq_len, d_model,
                                              n_layers, vocab))}


def _enable_compile_cache():
    """Persistent on-disk XLA compilation cache: the BERT program's
    compile (~minutes through the tunnel) dominated round-2's subprocess
    budget; caching makes re-runs (two timed windows, later driver runs
    on the same host) compile in seconds."""
    try:
        import jax

        cache_dir = os.environ.get(
            "PADDLE_TPU_COMPILE_CACHE",
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         ".jax_compile_cache"))
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:  # cache is an optimization, never fatal
        print("compile cache unavailable: %r" % e, file=sys.stderr)


def _build_gpt_long(batch, seq_len, d_model=1024, n_heads=16,
                    n_layers=2, vocab=8192, use_bf16=True):
    """Small causal LM at LONG sequence — the config that exists to
    exercise the pallas flash-attention training kernels (BASELINE.md
    round-4 table: at seq 4096 flash fwd+bwd measures 2.3x XLA's dense
    lowering, and beyond 8k dense does not compile at all)."""
    import paddle_tpu as fluid
    from paddle_tpu import layers

    head = d_model // n_heads
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.data(name="ids", shape=[batch, seq_len],
                         dtype="int64")
        lbl = fluid.data(name="lbl", shape=[batch * seq_len, 1],
                         dtype="int64")
        x = layers.embedding(ids, size=(vocab, d_model))
        for _ in range(n_layers):
            h = layers.layer_norm(x)
            q = layers.fc(h, d_model, num_flatten_dims=2)
            k = layers.fc(h, d_model, num_flatten_dims=2)
            v = layers.fc(h, d_model, num_flatten_dims=2)

            def heads(t):
                t = layers.reshape(t, [batch, seq_len, n_heads, head])
                return layers.transpose(t, [0, 2, 1, 3])

            ctx = layers.flash_attention(heads(q), heads(k), heads(v),
                                         causal=True)
            ctx = layers.transpose(ctx, [0, 2, 1, 3])
            ctx = layers.reshape(ctx, [batch, seq_len, d_model])
            x = x + layers.fc(ctx, d_model, num_flatten_dims=2)
            m = layers.layer_norm(x)
            m = layers.fc(m, d_model * 4, num_flatten_dims=2, act="gelu")
            x = x + layers.fc(m, d_model, num_flatten_dims=2)
        logits = layers.fc(layers.layer_norm(x), vocab,
                           num_flatten_dims=2)
        loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(
            fluid.layers.reshape(logits, [batch * seq_len, vocab]), lbl))
        opt = fluid.optimizer.AdamOptimizer(1e-4)
        if use_bf16:
            from paddle_tpu.contrib import mixed_precision as mp

            opt = mp.decorate(opt)
        opt.minimize(loss)
    return main, startup, loss


def bench_gpt_long(batch=2, seq_len=4096, iters=6, use_bf16=True):
    import paddle_tpu as fluid

    main, startup, loss = _build_gpt_long(batch, seq_len,
                                          use_bf16=use_bf16)
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    feed = _device_feed({
        "ids": rng.randint(0, 8192, (batch, seq_len)).astype("int64"),
        "lbl": rng.randint(0, 8192,
                           (batch * seq_len, 1)).astype("int64"),
    })
    dt, final_loss, diag = _time_steps(exe, main, feed, loss, warmup=2,
                                       iters=iters, windows=2,
                                       window_gap_s=3.0)
    if not np.isfinite(final_loss):
        raise RuntimeError("gpt_long diverged: loss=%r" % final_loss)
    return {"tokens_per_sec": batch * seq_len / dt, "step_ms": dt * 1e3,
            "batch": batch, "seq_len": seq_len, "loss": final_loss,
            "bf16": use_bf16, "attention": "pallas_flash_causal",
            "diag": diag,
            "profile": _program_profile(main, fluid.global_scope(),
                                        feed, dt, bf16=use_bf16)}


# -- multi-chip bench (ISSUE 6) ---------------------------------------------
#
# Promotes the MULTICHIP dryruns into *measured* runs: dp=8 data
# parallelism for resnet50 / bert_base / gpt_long plus one 3D config
# (dp2 x pp2 x mp2), on a virtual 8-device CPU mesh (the same
# xla_force_host_platform_device_count recipe the dryruns and tests
# use — on real multi-chip hardware the pin is a no-op and the same
# code measures ICI). Shapes are CPU-sized (recorded in the output);
# the numbers that matter are the per-step collective counters, which
# are shape-exact and hardware-independent:
#   collective.ops / bytes        what the step actually moves
#   collective.pergrad_baseline_* the same program WITHOUT bucketing /
#                                 sharded update (the before)
#   collective.quant_int8_saving  bytes int8 quantization would shave
# Per-process metric dumps land in $PADDLE_TPU_METRICS_DIR and the
# parent merges them into job-level metrics.json (PR-5 pipeline), so
# every win is provable from counters, not prints.

MC_DEVICES = 8


def _pin_host_mesh(n_devices):
    """Pin a CPU platform with n virtual devices BEFORE the first jax
    backend touch (same self-bootstrapping recipe as
    __graft_entry__.dryrun_multichip)."""
    import re

    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    if m is None or int(m.group(1)) < n_devices:
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+", "", flags)
        os.environ["XLA_FLAGS"] = (
            flags.strip()
            + " --xla_force_host_platform_device_count=%d" % n_devices
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    if len(jax.devices()) < n_devices:
        raise RuntimeError(
            "need %d devices, jax exposes %d — run each multichip "
            "config in a fresh process" % (n_devices, len(jax.devices())))


def _mc_build_mlp(batch):
    main, startup, loss = _build_mnist_mlp(batch)
    return main, startup, loss, batch  # unit: examples


def _mc_build_resnet50(batch, img):
    import paddle_tpu as fluid
    from paddle_tpu import models

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data(name="img", shape=[batch, 3, img, img],
                       dtype="float32")
        label = fluid.data(name="label", shape=[batch, 1], dtype="int64")
        pred = models.resnet50(x)
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
        fluid.optimizer.MomentumOptimizer(0.1, 0.9).minimize(loss)
    return main, startup, loss, batch


def _mc_build_bert(batch, seq_len):
    main, startup, loss, _M, _ = _build_bert_base(batch, seq_len,
                                                  use_bf16=False)
    return main, startup, loss, batch * seq_len  # unit: tokens


def _mc_build_gpt(batch, seq_len):
    main, startup, loss = _build_gpt_long(batch, seq_len, use_bf16=False)
    return main, startup, loss, batch * seq_len


def _mc_feeds(name, batch, img=96, seq_len=128):
    rng = np.random.RandomState(0)
    if name == "mlp":
        return {"x": rng.rand(batch, 784).astype("float32"),
                "label": rng.randint(0, 10, (batch, 1)).astype("int64")}
    if name == "resnet50":
        return {"img": rng.rand(batch, 3, img, img).astype("float32"),
                "label": rng.randint(0, 1000, (batch, 1)).astype("int64")}
    if name == "bert_base":
        return {
            "src": rng.randint(0, 30522, (batch, seq_len)).astype("int64"),
            "pos": np.tile(np.arange(seq_len), (batch, 1)).astype("int64"),
            "mpos": rng.randint(0, seq_len, (batch, 20)).astype("int64"),
            "labels": rng.randint(0, 30522,
                                  (batch, 20, 1)).astype("int64"),
        }
    if name == "gpt_long":
        return {
            "ids": rng.randint(0, 8192, (batch, seq_len)).astype("int64"),
            "lbl": rng.randint(0, 8192,
                               (batch * seq_len, 1)).astype("int64"),
        }
    raise ValueError(name)


# per-config CPU-mesh shapes. ``batch`` is the GLOBAL batch; models
# with batch-dependent reshapes (bert/gpt) are built at the
# per-replica batch and fed the global one (shard_map slices the feed
# — the same recipe as the dp x pp x mp dryrun), models without
# (mlp/resnet) build at the global batch.
MC_CONFIGS = {
    "mlp": {"batch": 512, "unit": "examples_per_sec", "iters": 8},
    "resnet50": {"batch": 16, "img": 96, "unit": "images_per_sec",
                 "iters": 2},
    "bert_base": {"batch": 8, "seq_len": 128, "unit": "tokens_per_sec",
                  "iters": 2, "per_replica_build": True},
    "gpt_long": {"batch": 8, "seq_len": 512, "unit": "tokens_per_sec",
                 "iters": 2, "per_replica_build": True},
    "dp2_pp2_mp2": {"unit": "examples_per_sec", "iters": 4},
}


def _pergrad_baseline(build, scope_state):
    """Static collective estimate of the SAME model on the per-grad
    path (no bucketing, no sharded update): one c_allreduce_sum per
    grad. Shape-exact, nothing executed."""
    from paddle_tpu.parallel.engine import _estimate_collective_bytes
    from paddle_tpu.parallel.transpiler import insert_allreduce_ops

    main, _startup, _loss, _units = build()
    insert_allreduce_ops(main, MC_DEVICES)
    est = _estimate_collective_bytes(main, scope_state)
    return est["ops_total"], est["bytes_total"]


def _quant_saving(program, scope_state):
    """PROJECTED bytes/step a NATIVE int8 collective would shave off
    this (already rewritten) program — computed by re-estimating with
    the bucket / sharded ops' quant attr forced to int8 at native wire
    width, then restored. The emulated int8 lowering psums int32
    codes, so the executed-traffic counters do NOT shrink by this."""
    from paddle_tpu.parallel.engine import _estimate_collective_bytes

    touched = []
    for op in program.global_block().ops:
        if op.type in ("c_bucket_allreduce", "c_sharded_update"):
            touched.append((op, op.attrs.get("quant", "none")))
            op.attrs["quant"] = "int8"
    est = _estimate_collective_bytes(program, scope_state,
                                     native_wire=True)
    for op, prev in touched:
        op.attrs["quant"] = prev
    return est["bytes_exact"] - est["bytes_total"]


def _mc_counters():
    from paddle_tpu import observability as obs

    d = obs.dump()["counters"]
    return {k: v for k, v in d.items() if k.startswith("parallel.")}


def _mc_measure(exe, cp, feed, loss, iters, name):
    """Shared timing/counter protocol for every multichip config: one
    compile+sync run, then `iters` timed steps with results kept on
    device until a final hard-syncing fetch, counter deltas divided
    per step. Returns (dt_s, t_compile_s, final_loss, per_step)."""
    t_compile = time.time()
    exe.run(cp, feed=feed, fetch_list=[loss])  # compile + sync
    t_compile = time.time() - t_compile
    c0 = _mc_counters()
    t0 = time.time()
    for _ in range(iters - 1):
        exe.run(cp, feed=feed, fetch_list=[loss], return_numpy=False)
    (out,) = exe.run(cp, feed=feed, fetch_list=[loss])  # hard sync
    dt = (time.time() - t0) / iters
    c1 = _mc_counters()
    final_loss = float(np.mean(np.asarray(out)))
    if not np.isfinite(final_loss):
        raise RuntimeError("%s diverged: loss=%r" % (name, final_loss))
    delta = {k: c1.get(k, 0) - c0.get(k, 0) for k in c1}
    steps = max(1, delta.get("parallel.steps", iters))
    per_step = {k: v // steps for k, v in delta.items()
                if k.startswith("parallel.collective")}
    return dt, t_compile, final_loss, per_step


def bench_multichip_config(name, iters=None, quant=None, sharded=True):
    """Child-process entry: one multichip config on an 8-device CPU
    mesh, JSON on stdout."""
    _pin_host_mesh(MC_DEVICES)
    import paddle_tpu as fluid
    from paddle_tpu import observability as obs
    from paddle_tpu.parallel.mesh_utils import make_mesh

    obs.enable()
    cfg = dict(MC_CONFIGS[name])
    unit = cfg.pop("unit")
    iters = iters or cfg.pop("iters")
    cfg.pop("iters", None)
    per_replica = cfg.pop("per_replica_build", False)
    if quant:
        os.environ["PADDLE_TPU_QUANT_ALLREDUCE"] = quant
    if sharded and name != "dp2_pp2_mp2":
        os.environ.setdefault("PADDLE_TPU_SHARDED_UPDATE", "1")

    if name == "dp2_pp2_mp2":
        return _mc_3d_config(iters, unit)

    bcfg = dict(cfg)
    if per_replica:
        if bcfg["batch"] % MC_DEVICES:
            raise ValueError("global batch %d not divisible by dp=%d"
                             % (bcfg["batch"], MC_DEVICES))
        bcfg["batch"] //= MC_DEVICES
    builders = {"mlp": lambda: _mc_build_mlp(bcfg["batch"]),
                "resnet50": lambda: _mc_build_resnet50(**bcfg),
                "bert_base": lambda: _mc_build_bert(**bcfg),
                "gpt_long": lambda: _mc_build_gpt(**bcfg)}
    with fluid.unique_name.guard():
        main, startup, loss, units_per_step = builders[name]()
    if per_replica:
        units_per_step *= MC_DEVICES  # builder counted one replica
    feed = _device_feed(_mc_feeds(name, **cfg))
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        state = {}
        for vname in main.global_block().vars:
            var = scope.find_var(vname)
            if var is not None and var.is_initialized():
                state[vname] = np.asarray(var.raw().array)
        with fluid.unique_name.guard():
            base_ops, base_bytes = _pergrad_baseline(
                builders[name], state)
        mesh = make_mesh([MC_DEVICES], ["dp"])
        cp = fluid.CompiledProgram(main).with_data_parallel(
            loss_name=loss.name, places=mesh)
        dt, t_compile, final_loss, per_step = _mc_measure(
            exe, cp, feed, loss, iters, name)
        quant_save = _quant_saving(main, state)
        # phase breakdown + per-bucket overlap report over the
        # REWRITTEN program (bucketed/sharded collectives in place) —
        # the measured answer to "do the collectives overlap backward
        # compute" — plus the XPlane device-folded counterpart and its
        # host-vs-device agreement ratio. Default-on here: CPU-mesh
        # shapes are small and the overlap number is this bench's
        # point.
        profile = _program_profile(main, scope, feed, dt,
                                   mesh=mesh, n_devices=MC_DEVICES,
                                   phases_default=True,
                                   device_default=True,
                                   flops_scale=(MC_DEVICES
                                                if per_replica else 1))
    from paddle_tpu.parallel.collectives import (bucket_mb,
                                                 bucket_plan_mode,
                                                 quant_mode,
                                                 sharded_update_enabled)

    from paddle_tpu.analysis import schedule_record

    # placement block (ISSUE 15): when a searched plan drove this run
    # (PADDLE_TPU_PLACEMENT_PLAN), record its digest + predicted vs
    # measured step time so bench_diff can watch predicted-vs-measured
    # drift and flag a silent plan change between runs
    placement = None
    pl = getattr(main, "_placement_plan", None)
    if pl is not None:
        pred_ms = pl.get("predicted_step_ms")
        placement = dict(pl)
        placement["measured_step_ms"] = dt * 1e3
        # agreement compares on the PROFILE clock (the tight re-jitted
        # step measurement the cost model was fitted to); the bench
        # wall-clock dt above carries harness overhead the model never
        # saw and rides separately
        prof_ms = (profile or {}).get("profiled_step_ms") or dt * 1e3
        placement["profile_step_ms"] = prof_ms
        placement["placement_agreement"] = (
            min(pred_ms, prof_ms) / max(pred_ms, prof_ms)
            if pred_ms and prof_ms else None)

    collective_rec = {
        "per_step": per_step,
        "pergrad_baseline_ops": base_ops,
        "pergrad_baseline_bytes": base_bytes,
        # static collective-consistency verdict over the REWRITTEN
        # program (ISSUE 12): ok + schedule digest — two ranks/processes
        # running the same plan must agree on the digest, and a
        # conditional/double-reduce hazard flips ok to False with the
        # op named in "error"
        "schedule": schedule_record(main, nranks=MC_DEVICES,
                                    scope=scope),
        "quant_int8_bytes_saved": int(quant_save),
        # executed bucket layout + which planner produced it —
        # "demonstrably changes the bucket plan" is assertable from
        # this block (mc_smoke's profile-guided replan cycle does)
        "bucket_ops": sum(1 for op in main.global_block().ops
                          if op.type in ("c_bucket_allreduce",
                                         "c_bucket_allreduce_start",
                                         "c_sharded_update")),
        "bucket_plan": getattr(main, "_bucket_plan", None),
    }
    return {
        "config": name, "mesh": {"dp": MC_DEVICES}, "unit": unit,
        "step_ms": dt * 1e3,
        "tokens_or_images_per_sec": units_per_step / dt,
        unit: units_per_step / dt,
        "loss": final_loss, "shapes": cfg, "iters": iters,
        "warmup_s": round(t_compile, 1),
        "collective_bytes": per_step.get("parallel.collective_bytes", 0),
        "collective": collective_rec,
        "profile": profile,
        "placement": placement,
        "knobs": {"bucket_mb": bucket_mb(), "quant": quant_mode(),
                  "sharded_update": sharded_update_enabled(),
                  "bucket_plan": bucket_plan_mode(),
                  "placement_plan": os.environ.get(
                      "PADDLE_TPU_PLACEMENT_PLAN", "") or None},
    }


def _mc_3d_config(iters, unit):
    """dp2 x pp2 x mp2: dp replicas of a 2-stage pipeline whose first
    stage holds an mp-row-sharded embedding (the MULTICHIP_r05 3D
    parity config, grown to measurable size)."""
    import paddle_tpu as fluid
    from paddle_tpu.incubate.fleet.collective import (CollectiveOptimizer,
                                                      DistributedStrategy)
    from paddle_tpu.parallel.mesh_utils import make_mesh

    dp, pp, mp = 2, 2, 2
    n_micro, mb = 2, 32
    B = dp * n_micro * mb
    V, D, H = 2048, 64, 256

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        ids = fluid.data(name="ids", shape=[mb, 1], dtype="int64")
        tgt = fluid.data(name="tgt", shape=[mb, 16], dtype="float32")
        emb = fluid.layers.embedding(
            ids, size=[V, D], param_attr=fluid.ParamAttr(name="emb_w"))
        h1 = fluid.layers.fc(emb, size=H, act="relu")
        h2 = fluid.layers.fc(h1, size=H, act="relu")
        pred = fluid.layers.fc(h2, size=16)
        loss = fluid.layers.reduce_mean(
            fluid.layers.square(fluid.layers.elementwise_sub(pred, tgt)))
        strat = DistributedStrategy()
        strat.sharded_embedding = True
        strat.mp_degree = mp
        strat.pipeline = True
        strat.pipeline_cut_list = [[h1]]
        strat.pipeline_num_microbatches = n_micro
        CollectiveOptimizer(fluid.optimizer.MomentumOptimizer(0.1, 0.9),
                            strat).minimize(loss,
                                            startup_program=startup)

    rng = np.random.RandomState(41)
    feed = _device_feed({
        "ids": rng.randint(0, V, (B, 1)).astype("int64"),
        "tgt": rng.randn(B, 16).astype("float32"),
    })
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        mesh = make_mesh([dp, pp, mp], ["dp", "pp", "mp"])
        cp = fluid.CompiledProgram(main).with_data_parallel(
            loss_name=loss.name, places=mesh)
        dt, t_compile, final_loss, per_step = _mc_measure(
            exe, cp, feed, loss, iters, "dp2_pp2_mp2")
        # FLOPs/mfu only: phase-sliced re-execution assumes the dp
        # engine's one-shard_map step shape, which a pipeline program
        # (scan over ticks + separate update trace) is not. The
        # program is ONE microbatch of ONE pipeline replica; the job
        # step runs n_micro microbatches on each of dp replicas
        # (mp/pp shard that same work, they don't duplicate it)
        from paddle_tpu.observability import profiler as prof

        fl = prof.program_flops(main)
        scale = dp * n_micro
        profile = _profile_record(
            dt, fl["total"] * scale,
            {k: v * scale for k, v in fl["by_category"].items()},
            n_devices=dp * pp * mp)
    return {
        "config": "dp2_pp2_mp2", "unit": unit,
        "mesh": {"dp": dp, "pp": pp, "mp": mp},
        "step_ms": dt * 1e3,
        "tokens_or_images_per_sec": B / dt,
        unit: B / dt, "loss": final_loss,
        "shapes": {"batch": B, "vocab": V, "d": D, "hidden": H,
                   "n_micro": n_micro},
        "iters": iters, "warmup_s": round(t_compile, 1),
        "collective_bytes": per_step.get("parallel.collective_bytes", 0),
        "collective": {"per_step": per_step},
        "profile": profile,
        "knobs": {},
    }


def _mc_subprocess(name, jobdir, rank, quant=None, timeout=900):
    import subprocess

    args = [sys.executable, __file__, "--mc-config=" + name]
    if quant:
        args.append("--mc-quant=" + quant)
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": (env.get("XLA_FLAGS", "").strip()
                      + " --xla_force_host_platform_device_count=%d"
                      % MC_DEVICES).strip(),
        "PADDLE_TPU_METRICS": "1",
        "PADDLE_TPU_METRICS_DIR": jobdir,
        "PADDLE_ROLE": "bench",
        "PADDLE_TRAINER_ID": str(rank),
    })
    proc = subprocess.run(args, capture_output=True, text=True,
                          timeout=timeout, env=env)
    if proc.returncode != 0:
        raise RuntimeError("multichip bench %s failed: %s"
                           % (name, proc.stderr[-2000:]))
    return json.loads(proc.stdout.strip().splitlines()[-1])


def bench_multichip(out_path=None, configs=None, quant_config="bert_base"):
    """Parent: run every multichip config in its own process (fresh
    device-count pin per child), merge the children's metric dumps
    into job-level metrics.json, write MULTICHIP_BENCH json."""
    import tempfile

    out_path = out_path or "MULTICHIP_BENCH_r01.json"
    configs = configs or ["resnet50", "bert_base", "gpt_long",
                          "dp2_pp2_mp2"]
    jobdir = tempfile.mkdtemp(prefix="mc_bench_metrics_")
    # one job trace id for every config child (the launch-supervisor
    # contract): the merged trace.json reads as one timeline
    from paddle_tpu.observability.distributed import JOB_TRACE_ENV

    os.environ.setdefault(JOB_TRACE_ENV, os.urandom(8).hex())
    t_start = time.time()
    results, errors = {}, {}
    rank = 0
    for name in configs:
        try:
            results[name] = _mc_subprocess(name, jobdir, rank)
        except Exception as e:
            errors[name] = repr(e)
            print("multichip %s failed: %r" % (name, e), file=sys.stderr)
        rank += 1
    # one opt-in quantized variant: the measured (not just estimated)
    # bytes saved + its throughput delta
    if quant_config in results:
        try:
            results[quant_config + "_int8"] = _mc_subprocess(
                quant_config, jobdir, rank, quant="int8")
        except Exception as e:
            errors[quant_config + "_int8"] = repr(e)
            print("multichip %s int8 failed: %r" % (quant_config, e),
                  file=sys.stderr)

    from paddle_tpu.observability.distributed import merge_job_dir

    metrics_path, _trace = merge_job_dir(jobdir)
    merged = None
    if metrics_path:
        with open(metrics_path) as f:
            merged = json.load(f)

    doc = {
        "schema": "multichip_bench_v1",
        "n_devices": MC_DEVICES,
        "platform": "cpu_host_mesh",
        "configs": results,
        "errors": errors,
        "wall_s": round(time.time() - t_start, 1),
        # job-level merged counter totals (PR-5 pipeline): the
        # provable-win surface — collective ops/bytes by kind across
        # every config in this run
        "metrics_totals": (merged or {}).get("counters_total"),
    }
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    if merged is not None:
        mpath = os.path.splitext(out_path)[0] + ".metrics.json"
        with open(mpath, "w") as f:
            json.dump(merged, f, indent=2, sort_keys=True)
            f.write("\n")
    print(json.dumps(doc))
    return doc


def _enable_fast_paths():
    """Single-chip fast paths bench.py runs WITH (ISSUE 14): fused
    optimizer update, fused epilogues, async host feed. Default-off in
    the runtime; flipped on here because the bit-parity suite
    (tests/test_single_chip_fusion.py) licenses it — an explicit
    ``=0`` in the caller's environment still wins (setdefault)."""
    for knob in ("PADDLE_TPU_FUSED_OPTIMIZER", "PADDLE_TPU_FUSED_EPILOGUE",
                 "PADDLE_TPU_ASYNC_FEED"):
        os.environ.setdefault(knob, "1")


def _emit(rec):
    """Print one bench record, with the profile-derived ``mfu_est``
    surfaced at top level for EVERY model (bench_diff and BENCH_r
    readers key on it; wide_deep / transformer_wmt used to omit it)."""
    prof = rec.get("profile") or {}
    if "mfu_est" not in rec and prof.get("mfu_est") is not None:
        rec["mfu_est"] = prof["mfu_est"]
    print(json.dumps(rec))


def _run_one(name, use_bf16):
    """Child-process entry: bench one model, print its JSON."""
    _enable_compile_cache()
    _enable_fast_paths()
    if name == "mnist_mlp":
        _emit(bench_mnist_mlp())
    elif name == "bert_base":
        _emit(bench_bert_base(use_bf16=use_bf16))
    elif name == "transformer_wmt":
        _emit(bench_transformer_wmt(use_bf16=use_bf16))
    elif name == "wide_deep":
        _emit(bench_wide_deep())
    elif name == "dygraph_mlp":
        _emit(bench_dygraph_mlp())
    elif name == "dygraph_mlp_lazy":
        _emit(bench_dygraph_mlp(lazy=True))
    elif name == "dygraph_bert":
        _emit(bench_dygraph_bert())
    elif name == "gpt_long":
        _emit(bench_gpt_long(use_bf16=use_bf16))
    elif name == "resnet50":
        rn = bench_resnet50(use_bf16=use_bf16)
        # mfu from the analytic FLOP registry (profiler.program_flops
        # over the actual program) — the hardcoded 4.1 GFLOP/img
        # estimate this replaced lives on only as a sanity cross-check
        # in tests/test_profiler.py
        _emit(rn)
    else:
        raise SystemExit("unknown model %r" % name)


def _bench_subprocess(name, use_bf16):
    """Each model benches in its own process: the remote device runtime
    degrades badly when multiple compiled programs share a process (its
    executable cache thrashes), which would corrupt the measurement."""
    import subprocess

    args = [sys.executable, __file__, "--model=" + name]
    if not use_bf16:
        args.append("--no-bf16")
    timeout = {"resnet50": 360, "bert_base": 600, "mnist_mlp": 120,
               "transformer_wmt": 480, "wide_deep": 240,
               "dygraph_mlp": 240, "dygraph_mlp_lazy": 240,
               "dygraph_bert": 600, "gpt_long": 480}.get(name, 60)
    proc = subprocess.run(args, capture_output=True, text=True,
                          timeout=timeout)
    if proc.returncode != 0:
        raise RuntimeError("bench %s failed: %s" % (name,
                                                    proc.stderr[-2000:]))
    return json.loads(proc.stdout.strip().splitlines()[-1])


def main():
    use_bf16 = "--no-bf16" not in sys.argv
    mc_quant = None
    mc_iters = None
    out_path = None
    for a in sys.argv[1:]:
        if a.startswith("--mc-quant="):
            mc_quant = a.split("=", 1)[1]
        elif a.startswith("--mc-iters="):
            mc_iters = int(a.split("=", 1)[1])
        elif a.startswith("--out="):
            out_path = a.split("=", 1)[1]
    for a in sys.argv[1:]:
        if a.startswith("--mc-config="):
            _enable_compile_cache()
            print(json.dumps(bench_multichip_config(
                a.split("=", 1)[1], iters=mc_iters, quant=mc_quant)))
            return
    if "--multichip" in sys.argv:
        configs = [a.split("=", 1)[1].split(",")
                   for a in sys.argv[1:]
                   if a.startswith("--mc-only=")]
        doc = bench_multichip(out_path=out_path,
                              configs=configs[0] if configs else None)
        if doc["errors"]:
            # the artifact (with whatever was measured) is written, but
            # a run that failed configs must not look like a clean pass
            raise SystemExit(1)
        return
    for a in sys.argv[1:]:
        if a.startswith("--model="):
            _run_one(a.split("=", 1)[1], use_bf16)
            return

    extras = {}
    t_start = time.time()
    budget_s = float(os.environ.get("BENCH_BUDGET_S", "780"))
    # cheapest first (round-2 lesson: heaviest-first starved the other
    # configs of budget and BENCH_r02 recorded only one number) — mnist
    # is seconds, resnet is the headline, bert rides the compile cache
    try:
        extras["mnist_mlp"] = _bench_subprocess("mnist_mlp", use_bf16)
    except Exception as e:
        extras["mnist_mlp_error"] = repr(e)
        print("mnist bench failed: %r" % e, file=sys.stderr)
    rn = None
    try:
        rn = _bench_subprocess("resnet50", use_bf16)
    except Exception as e:
        print("bf16 resnet bench failed (%r); retrying f32" % e,
              file=sys.stderr)
        try:
            rn = _bench_subprocess("resnet50", False)
        except Exception as e2:
            # never lose the whole run to the headline model: fall back
            # to whatever secondary number exists (round-2 lesson)
            extras["resnet50_error"] = repr(e2)
            print("resnet bench failed twice: %r" % e2, file=sys.stderr)
    if time.time() - t_start > budget_s:
        extras["bert_base_skipped"] = "time budget exhausted"
    else:
        try:
            extras["bert_base"] = _bench_subprocess("bert_base", use_bf16)
            # the shared tunnel's d2h cost varies 10-100x between pool
            # windows (identical code measures 6k-127k tok/s); when a
            # clearly degraded window hits AND budget remains, one
            # retry usually lands a clean window — keep the better
            if (extras["bert_base"]["tokens_per_sec"] < 2e4
                    and time.time() - t_start < budget_s):
                retry = _bench_subprocess("bert_base", use_bf16)
                if retry["tokens_per_sec"] > \
                        extras["bert_base"]["tokens_per_sec"]:
                    extras["bert_base_degraded_window"] = \
                        extras["bert_base"]
                    extras["bert_base"] = retry
        except Exception as e:  # keep the headline alive
            extras["bert_base_error"] = repr(e)
            print("bert bench failed: %r" % e, file=sys.stderr)
    if rn is not None:
        extras["resnet50"] = rn
    # north-star configs 4/5 + the eager path — budget-gated so the
    # headline models always record first
    for extra_model in ("wide_deep", "dygraph_mlp", "dygraph_mlp_lazy",
                        "transformer_wmt", "gpt_long", "dygraph_bert"):
        if time.time() - t_start > budget_s:
            extras[extra_model + "_skipped"] = "time budget exhausted"
            continue
        try:
            extras[extra_model] = _bench_subprocess(extra_model, use_bf16)
        except Exception as e:
            extras[extra_model + "_error"] = repr(e)
            print("%s bench failed: %r" % (extra_model, e),
                  file=sys.stderr)
    extras["wall_s"] = time.time() - t_start
    try:
        import jax

        extras["device"] = str(jax.devices()[0])
    except Exception:
        pass
    if rn is not None:
        result = {
            "metric": "resnet50_images_per_sec_per_chip",
            "value": round(rn["images_per_sec"], 2),
            "unit": "images/sec",
            "vs_baseline": round(
                rn["images_per_sec"] / CUDA_PER_CHIP_ANCHOR_IMG_S, 4),
            "extras": extras,
        }
    elif "mnist_mlp" in extras:
        result = {
            "metric": "mnist_mlp_steps_per_sec",
            "value": round(extras["mnist_mlp"]["steps_per_sec"], 2),
            "unit": "steps/sec",
            "vs_baseline": 0.0,
            "extras": extras,
        }
    else:
        result = {"metric": "bench_failed", "value": 0, "unit": "",
                  "vs_baseline": 0.0, "extras": extras}
    print(json.dumps(result))


if __name__ == "__main__":
    main()
