"""Dense param block-slicing in the PS dataplane (VERDICT r4 #4).

Reference contract: distribute_transpiler.py:95 (slice_variable), :540
(split send), :1146 (per-block server optimize blocks). One fc weight
is split into row blocks across TWO pservers; the trainer splits its
grad, each server runs the optimizer on its block, the trainer concats
recv'd blocks — and training matches the single-process oracle."""
import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as fluid

WORKER = os.path.join(os.path.dirname(__file__),
                      "dist_worker_sliced_ps.py")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _sliced_net():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        x = fluid.data(name="x", shape=[16, 16], dtype="float32")
        y = fluid.data(name="y", shape=[16, 1], dtype="float32")
        h = fluid.layers.fc(
            x, 8, act="relu",
            param_attr=fluid.ParamAttr(
                name="w",
                initializer=fluid.initializer.ConstantInitializer(0.12)),
            bias_attr=fluid.ParamAttr(
                name="b",
                initializer=fluid.initializer.ConstantInitializer(0.0)))
        pred = fluid.layers.fc(
            h, 1,
            param_attr=fluid.ParamAttr(
                name="w2",
                initializer=fluid.initializer.ConstantInitializer(0.2)),
            bias_attr=fluid.ParamAttr(
                name="b2",
                initializer=fluid.initializer.ConstantInitializer(0.0)))
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.MomentumOptimizer(0.05, 0.9).minimize(loss)
    return main, startup, loss


def _cfg():
    cfg = fluid.DistributeTranspilerConfig()
    cfg.min_block_size = 64   # w [16, 8] = 128 elements -> 2 blocks
    return cfg


def test_transpiled_block_contract():
    main, startup, loss = _sliced_net()
    t = fluid.DistributeTranspiler(config=_cfg())
    t.transpile(trainer_id=0, program=main, startup_program=startup,
                pservers="ps0:7164,ps1:7164", trainers=1)
    assert "w" in t.dense_blocks
    rows = [e["rows"] for e in t.dense_blocks["w"]]
    assert sum(rows) == 16 and len(rows) == 2
    types = [op.type for op in main.global_block().ops]
    assert "split" in types and "concat" in types
    sends = [op for op in main.global_block().ops if op.type == "send"]
    block_sends = [op for op in sends
                   if ".block" in op.attrs["table_name"]]
    assert len(block_sends) == 2
    assert {op.attrs["epmap"][0] for op in block_sends} == \
        {"ps0:7164", "ps1:7164"}

    # each server hosts exactly one w-block (param + momentum velocity
    # block-shaped), and its optimize sub-block updates the BLOCK
    for ep in ("ps0:7164", "ps1:7164"):
        ps = t.get_pserver_program(ep)
        pb = ps.global_block()
        wblocks = [n for n in pb.vars if n.startswith("w.block")]
        assert len(wblocks) == 1
        bvar = pb.vars[wblocks[0]]
        assert tuple(bvar.shape)[0] in (8,)    # 8 rows each
        serv = pb.ops[-1]
        assert serv.type == "listen_and_serv"
        momentum_params = []
        for sub in serv.attrs["optimize_blocks"]:
            for op in sub.ops:
                if op.type == "momentum":
                    momentum_params.append(op.input("Param")[0])
        assert any(p.startswith("w.block") for p in momentum_params)
        # startup initializes the block at BLOCK shape
        sp = t.get_startup_program(ep, ps)
        inits = {o: op for op in sp.global_block().ops
                 for o in op.output_arg_names}
        assert wblocks[0] in inits
        assert list(inits[wblocks[0]].attrs["shape"]) == [8, 8]


def test_emulated_sliced_ps_matches_single_process():
    from paddle_tpu.ops.distributed_ops import reset_emulated_servers

    rng = np.random.RandomState(5)
    W = rng.randn(16, 1).astype("float32")
    batches = [rng.randn(16, 16).astype("float32") for _ in range(20)]

    # oracle: plain single-process training of the same net
    main_o, startup_o, loss_o = _sliced_net()
    scope_o = fluid.Scope()
    with fluid.scope_guard(scope_o):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup_o)
        oracle_losses = []
        for xb in batches:
            (l,) = exe.run(main_o, feed={"x": xb, "y": xb @ W},
                           fetch_list=[loss_o])
            oracle_losses.append(float(np.asarray(l).ravel()[0]))
        w_oracle = np.asarray(scope_o.find_var("w").raw().array)

    # transpiled: 2 emulated pservers, w sliced across them
    reset_emulated_servers()
    main, startup, loss = _sliced_net()
    t = fluid.DistributeTranspiler(config=_cfg())
    t.transpile(trainer_id=0, program=main, startup_program=startup,
                pservers="ps0:7164,ps1:7164", trainers=1)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        for ep in ("ps0:7164", "ps1:7164"):
            psprog = t.get_pserver_program(ep)
            exe.run(t.get_startup_program(ep, psprog))
            exe.run(psprog)
        exe.run(startup)
        losses = []
        for xb in batches:
            (l,) = exe.run(t.get_trainer_program(),
                           feed={"x": xb, "y": xb @ W},
                           fetch_list=[loss])
            losses.append(float(np.asarray(l).ravel()[0]))
        w_sliced = np.asarray(scope.find_var("w").raw().array)

    np.testing.assert_allclose(losses, oracle_losses, rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(w_sliced, w_oracle, rtol=1e-5,
                               atol=1e-6)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def test_multiprocess_sliced_ps(tmp_path):
    """TWO real pserver processes, one block of the same fc weight
    each; parity with the single-process oracle across real process
    boundaries (the VERDICT r4 #4 'done' bar)."""
    eps = ["127.0.0.1:%d" % _free_port(), "127.0.0.1:%d" % _free_port()]
    out = tmp_path / "trainer.json"

    def env(role, ep=""):
        e = dict(os.environ)
        e.update({"PADDLE_TRAINING_ROLE": role,
                  "PSERVER_ENDPOINTS": ",".join(eps),
                  "PSERVER_ENDPOINT": ep,
                  "JAX_PLATFORMS": "cpu",
                  "PYTHONPATH": REPO + os.pathsep
                  + e.get("PYTHONPATH", "")})
        return e

    servers = [subprocess.Popen(
        [sys.executable, WORKER, str(tmp_path / ("ps%d" % i))],
        env=env("PSERVER", ep), stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
        for i, ep in enumerate(eps)]
    try:
        tr = subprocess.run([sys.executable, WORKER, str(out)],
                            env=env("TRAINER"), capture_output=True,
                            text=True, timeout=240)
        assert tr.returncode == 0, tr.stderr[-3000:]
        for ps in servers:
            ps.wait(timeout=60)
    finally:
        for ps in servers:
            if ps.poll() is None:
                ps.kill()
    result = json.loads(out.read_text())
    assert len(set(result["block_eps"])) == 2

    # oracle in-process
    rng = np.random.RandomState(5)
    W = rng.randn(16, 1).astype("float32")
    main_o, startup_o, loss_o = _sliced_net()
    scope_o = fluid.Scope()
    with fluid.scope_guard(scope_o):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup_o)
        oracle = []
        for _ in range(5):
            xb = rng.randn(16, 16).astype("float32")
            (l,) = exe.run(main_o, feed={"x": xb, "y": xb @ W},
                           fetch_list=[loss_o])
            oracle.append(float(np.asarray(l).ravel()[0]))
        w_oracle = np.asarray(scope_o.find_var("w").raw().array)
    np.testing.assert_allclose(result["losses"], oracle, rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(result["w_final"]), w_oracle,
                               rtol=1e-5, atol=1e-6)
