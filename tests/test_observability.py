"""Unified runtime observability (paddle_tpu/observability): registry
semantics, span tracing, exporters, and the counters threaded through
every execution path — static executor (compiled + interpreter), lazy
dygraph engine, mesh data-parallel engine — plus the profiler
compatibility shim and the default-off no-op contract.

Reference contract being generalized: platform/profiler.cc RecordEvent
+ device_tracer + tools/timeline.py chrome-trace export."""
import json
import threading
import time
import warnings

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import observability as obs
from paddle_tpu.observability.registry import MetricsRegistry


@pytest.fixture(autouse=True)
def _clean_registry():
    """Each test starts from an armed, empty registry and leaves the
    layer disabled (other test files assume default-off)."""
    obs.reset()
    obs.enable()
    yield
    obs.reset()
    obs.disable()


# -- registry semantics ----------------------------------------------------

def test_counter_inc_and_labels():
    r = MetricsRegistry()
    c = r.counter("steps", path="compiled")
    c.inc()
    c.inc(4)
    assert c.value == 5
    # same (name, labels) -> same metric; different labels -> distinct
    assert r.counter("steps", path="compiled") is c
    assert r.counter("steps", path="interp").value == 0
    assert r.counter_value("steps", path="compiled") == 5
    assert r.counter_value("never_touched") == 0
    with pytest.raises(ValueError):
        c.inc(-1)


def test_kind_mismatch_raises():
    r = MetricsRegistry()
    r.counter("m")
    with pytest.raises(TypeError):
        r.gauge("m")


def test_gauge_set_inc_dec():
    r = MetricsRegistry()
    g = r.gauge("live_bytes")
    g.set(100)
    g.inc(50)
    g.dec(25)
    assert g.value == 125


def test_histogram_stats_and_reservoir_bound():
    r = MetricsRegistry()
    h = r.histogram("lat_ms")
    for v in range(1, 101):
        h.observe(v)
    s = h.snapshot()
    assert s["count"] == 100 and s["sum"] == 5050.0
    assert s["min"] == 1.0 and s["max"] == 100.0
    assert abs(s["mean"] - 50.5) < 1e-9
    assert 30 <= s["p50"] <= 70    # reservoir estimate
    # bounded memory no matter how many observations
    for v in range(10000):
        h.observe(v)
    assert len(h._reservoir) <= h.RESERVOIR


def test_registry_thread_safety_smoke():
    r = MetricsRegistry()
    c = r.counter("hits")

    def worker():
        for _ in range(1000):
            c.inc()
            r.histogram("h").observe(1.0)
            r.counter("per_thread", t=threading.get_ident()).inc()

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 8000
    assert r.histogram("h").count == 8000


def test_snapshot_and_prometheus_format():
    r = MetricsRegistry()
    r.counter("steps", path="compiled").inc(3)
    r.gauge("bubble").set(0.25)
    r.histogram("lat_ms").observe(2.0)
    snap = r.snapshot()
    assert snap["counters"]["steps{path=compiled}"] == 3
    assert snap["gauges"]["bubble"] == 0.25
    assert snap["histograms"]["lat_ms"]["count"] == 1
    text = r.to_prometheus()
    assert "# TYPE paddle_tpu_steps counter" in text
    assert 'paddle_tpu_steps{path="compiled"} 3' in text
    assert "# TYPE paddle_tpu_lat_ms summary" in text
    assert "paddle_tpu_lat_ms_count 1" in text
    assert "paddle_tpu_bubble 0.25" in text


def test_prometheus_label_value_escaping():
    """Exposition format 0.0.4: backslash, double quote, and newline in
    label VALUES must be escaped — an unescaped newline would split the
    sample line and corrupt the whole scrape."""
    r = MetricsRegistry()
    r.counter("evil", path='say "hi"\\there\nbye').inc()
    text = r.to_prometheus()
    line = [ln for ln in text.splitlines()
            if ln.startswith("paddle_tpu_evil")][0]
    assert line == ('paddle_tpu_evil{path="say \\"hi\\"\\\\there'
                    '\\nbye"} 1')
    # one sample line, not two: the newline never reached the wire raw
    assert sum(ln.startswith("paddle_tpu_evil")
               for ln in text.splitlines()) == 1


def test_prometheus_summary_series_shape():
    """A histogram exports as a summary: one quantile series per
    (labels, quantile) plus _sum and _count — the shape Prometheus
    clients parse, including labeled families like
    rpc.latency_ms{method=}."""
    r = MetricsRegistry()
    for v in range(1, 11):
        r.histogram("rpc.latency_ms", method="send_grad").observe(v)
    r.histogram("rpc.latency_ms", method="get_param").observe(7.0)
    text = r.to_prometheus()
    lines = text.splitlines()
    assert "# TYPE paddle_tpu_rpc_latency_ms summary" in lines
    for q in ("0.5", "0.9", "0.99"):
        assert any(ln.startswith(
            'paddle_tpu_rpc_latency_ms{method="send_grad",'
            'quantile="%s"}' % q) for ln in lines), q
    assert 'paddle_tpu_rpc_latency_ms_sum{method="send_grad"} 55.0' \
        in lines
    assert 'paddle_tpu_rpc_latency_ms_count{method="send_grad"} 10' \
        in lines
    assert 'paddle_tpu_rpc_latency_ms_count{method="get_param"} 1' \
        in lines
    # exactly one TYPE header for the family, not one per label set
    assert sum("TYPE paddle_tpu_rpc_latency_ms" in ln
               for ln in lines) == 1


# -- span tracing ----------------------------------------------------------

def test_span_nesting_records_contained_intervals():
    with obs.span("outer", cat="step"):
        time.sleep(0.002)
        with obs.span("inner"):
            time.sleep(0.001)
    evs = {e[0]: e for e in obs.tracing.trace_events()}
    assert "outer" in evs and "inner" in evs
    (_, o_ts, o_dur, o_tid, o_cat, _) = evs["outer"]
    (_, i_ts, i_dur, i_tid, _, _) = evs["inner"]
    assert o_cat == "step"
    assert o_tid == i_tid == threading.get_ident()
    # containment: inner starts after outer and ends before it
    assert i_ts >= o_ts
    assert i_ts + i_dur <= o_ts + o_dur + 1.0  # 1us slack
    assert o_dur >= i_dur


def test_span_disabled_is_noop_singleton():
    obs.disable()
    s1 = obs.tracing.span("a")
    s2 = obs.tracing.span("b", cat="step", foo=1)
    assert s1 is s2            # shared null object: no allocation
    with s1:
        pass
    assert obs.tracing.trace_events() == []


def test_chrome_trace_export_roundtrip(tmp_path):
    with obs.span("step_one", cat="step", idx=7):
        pass
    path = str(tmp_path / "trace.json")
    obs.write_chrome_trace(path)
    with open(path) as f:
        doc = json.load(f)   # valid JSON == loads in Perfetto/chrome
    assert isinstance(doc["traceEvents"], list) and doc["traceEvents"]
    ev = [e for e in doc["traceEvents"] if e["name"] == "step_one"][0]
    assert ev["ph"] == "X" and ev["cat"] == "step"
    assert isinstance(ev["ts"], float) and isinstance(ev["dur"], float)
    assert ev["args"] == {"idx": 7}
    # ts-sorted, required for sane timeline rendering
    ts = [e["ts"] for e in doc["traceEvents"]]
    assert ts == sorted(ts)


def test_chrome_trace_merges_legacy_profiler_timeline():
    from paddle_tpu import profiler

    with profiler.profiler():
        with profiler.RecordEvent("legacy_op"):
            pass
    # session is OVER (snapshot only) — the unified export must still
    # carry it
    assert any(e["name"] == "legacy_op"
               for e in obs.chrome_trace()["traceEvents"])
    # and reset() clears the snapshot too: a post-reset export is empty
    obs.reset()
    assert obs.chrome_trace()["traceEvents"] == []


# -- executor counters on a real 2-op program ------------------------------

def _two_op_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data(name="x", shape=[4, 8], dtype="float32")
        y = fluid.layers.scale(x, scale=2.0)
        out = fluid.layers.mean(y)
    return main, startup, out


def test_compiled_executor_counters_and_dump():
    main, startup, out = _two_op_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    # the startup program counts as a step too — measure the delta
    base = obs.counter_value("executor.steps", path="compiled")
    feed = {"x": np.ones((4, 8), "float32")}
    for _ in range(3):
        exe.run(main, feed=feed, fetch_list=[out])
    d = obs.dump()
    steps = obs.counter_value("executor.steps", path="compiled")
    assert steps - base == 3
    assert d["counters"]["executor.compiles"] >= 1
    assert d["histograms"]["executor.step_ms{path=compiled}"]["count"] \
        == steps
    # memory gauges ride every dump
    assert "memory.allocated_bytes" in d["gauges"]
    assert "memory.peak_bytes" in d["gauges"]
    # prometheus export of the same state
    text = obs.dump(fmt="prometheus")
    assert 'paddle_tpu_executor_steps{path="compiled"} %d' % steps in text


def test_interpreter_executor_per_op_counters():
    main, startup, out = _two_op_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = {"x": np.ones((4, 8), "float32")}
    # FLAGS_check_nan_inf forces the op-by-op interpreter
    fluid.set_flags({"FLAGS_check_nan_inf": True})
    try:
        exe.run(main, feed=feed, fetch_list=[out])
    finally:
        fluid.set_flags({"FLAGS_check_nan_inf": False})
    d = obs.dump()
    assert d["counters"]["executor.steps{path=interpreter}"] == 1
    assert d["counters"]["executor.ops{type=scale}"] == 1
    assert d["counters"]["executor.ops{type=mean}"] == 1


def test_interpreter_step_emits_spans_under_metrics_mode():
    main, startup, out = _two_op_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    fluid.set_flags({"FLAGS_check_nan_inf": True})
    try:
        exe.run(main, feed={"x": np.ones((4, 8), "float32")},
                fetch_list=[out])
    finally:
        fluid.set_flags({"FLAGS_check_nan_inf": False})
    names = [e[0] for e in obs.tracing.trace_events()]
    assert "executor/step" in names
    assert "scale" in names and "mean" in names


# -- lazy dygraph engine counters ------------------------------------------

def test_lazy_engine_flush_and_recompile_counters():
    from paddle_tpu.dygraph import Linear, to_variable

    with fluid.dygraph.guard(lazy=True):
        lin = Linear(8, 4)
        x = np.ones((2, 8), "float32")

        def step():
            loss = fluid.layers.mean(lin(to_variable(x)))
            loss.backward()
            return float(np.asarray(loss.numpy()).ravel()[0])

        step()
        d1 = obs.dump()["counters"]
        assert d1["lazy.flushes"] == 1
        assert d1["lazy.recompiles"] == 1     # first structure: a miss
        assert d1["dygraph.ops{dispatch=lazy}"] >= 2
        # steps 2 and 3: param-init nodes are gone after step 1, so at
        # most one more structure compiles — then the cache must hit
        step()
        step()
        d2 = obs.dump()["counters"]
        assert d2["lazy.flushes"] == 3
        assert d2["lazy.recompiles"] <= 2
        assert d2.get("lazy.cache_hits", 0) >= 1
    h = obs.dump()["histograms"]["lazy.graph_nodes"]
    assert h["count"] == 3 and h["min"] >= 1


def test_force_pins_value_held_only_by_locals():
    """Satellite dygraph/lazy.py:119 — forcing a PendingValue whose
    only reference is a local variable (no VarBase owner) must
    materialize it instead of raising 'dead at flush time'."""
    import jax
    import jax.numpy as jnp

    with fluid.dygraph.guard(lazy=True):
        from paddle_tpu.dygraph.tracer import current_tracer

        eng = current_tracer().lazy_engine
        p = eng.constant_node(
            lambda: jnp.full((3,), 7.0, jnp.float32),
            jax.ShapeDtypeStruct((3,), jnp.float32),
            ("t_const", (3,), "float32"))
        assert not p._resolved and not p.is_needed()
        np.testing.assert_allclose(np.asarray(p.force()),
                                   np.full((3,), 7.0))


def test_attrs_sig_hashes_array_content():
    """Satellite dygraph/tracer.py:435 — array-valued attrs must be
    cache-keyed by content, not repr (repr elides interior elements of
    large arrays, aliasing distinct ops onto one compiled graph)."""
    from paddle_tpu.dygraph.tracer import attrs_signature

    a = np.zeros(2000, dtype=np.float32)
    b = a.copy()
    b[1000] = 5.0   # elided by repr's summarization
    assert repr(a) == repr(b)   # the old key COULD NOT tell them apart
    assert attrs_signature({"v": a}) != attrs_signature({"v": b})
    assert attrs_signature({"v": a}) == attrs_signature({"v": a.copy()})
    # nested containers canonicalize too
    assert attrs_signature({"v": [a, 1]}) != attrs_signature({"v": [b, 1]})


# -- parallel engine counters ----------------------------------------------

def test_parallel_engine_counters():
    from paddle_tpu.parallel.mesh_utils import make_mesh

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data(name="x", shape=[8, 4], dtype="float32")
        y = fluid.layers.fc(x, size=2)
        loss = fluid.layers.mean(y)
        fluid.optimizer.SGDOptimizer(0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    mesh = make_mesh([2], ["dp"])
    cp = fluid.CompiledProgram(main).with_data_parallel(
        loss_name=loss.name, places=mesh)
    feed = {"x": np.ones((8, 4), "float32")}
    exe.run(cp, feed=feed, fetch_list=[loss])
    exe.run(cp, feed=feed, fetch_list=[loss])
    d = obs.dump()["counters"]
    assert d["parallel.steps"] == 2
    assert d["parallel.compiles"] == 1
    # grad allreduces moved bytes both steps
    assert d["parallel.collective_ops"] >= 2
    assert d["parallel.collective_bytes"] > 0
    assert obs.dump()["histograms"]["parallel.step_ms"]["count"] == 2


# -- lod lowering decline surface ------------------------------------------

def test_lowering_decline_returned_and_counted():
    """Satellite core/lod_lowering.py:68 — the decline reason is a
    return value (no mutable module global), and the executor surfaces
    it as a labeled counter."""
    from paddle_tpu.core.lod_lowering import Decline, plan_lowering
    from paddle_tpu.core.tensor import LoDTensor

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.data(name="ids", shape=[-1, 1], dtype="int64",
                         lod_level=1)
        emb = fluid.layers.embedding(ids, size=[10, 4])
        fluid.layers.fc(emb, size=2)      # fc over ragged: unsupported
        pooled = fluid.layers.sequence_pool(emb, pool_type="SUM")
        loss = fluid.layers.mean(pooled)

    plan = plan_lowering(main, ["ids"])
    assert isinstance(plan, Decline) and not plan   # falsy
    assert plan.op_type == "mul"
    assert "unsupported" in plan.reason
    # module has no mutable decline global anymore
    from paddle_tpu.core import lod_lowering

    assert not hasattr(lod_lowering, "LAST_DECLINE")

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    t = LoDTensor(np.array([[1], [2], [3]], dtype="int64"))
    t.set_lod([[0, 1, 3]])
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        exe.run(main, feed={"ids": t}, fetch_list=[loss])
    d = obs.dump()["counters"]
    key = [k for k in d if k.startswith("lod_lowering.declines")]
    assert key and "op_type=mul" in key[0]


# -- profiler shim backward compatibility ----------------------------------

def test_profiler_shim_session_contract(capsys):
    from paddle_tpu import profiler

    main, startup, out = _two_op_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    fluid.set_flags({"FLAGS_check_nan_inf": True})  # per-op events
    try:
        assert not profiler.is_profiler_enabled()
        with profiler.profiler():
            assert profiler.is_profiler_enabled()
            exe.run(main, feed={"x": np.ones((4, 8), "float32")},
                    fetch_list=[out])
            live = profiler.get_trace_events()
            assert any(n == "scale" for (n, _, _) in live)
    finally:
        fluid.set_flags({"FLAGS_check_nan_inf": False})
    # stop printed the host summary table
    assert "Event" in capsys.readouterr().out
    # snapshot survives after stop; live state drained
    assert not profiler.is_profiler_enabled()
    snap = profiler.get_trace_events()
    assert any(n == "scale" for (n, _, _) in snap)
    assert all(len(ev) == 3 for ev in snap)
    # timeline converter keeps working on the shim
    from paddle_tpu.tools.timeline import chrome_trace_events

    evs = chrome_trace_events()
    assert any(e["name"] == "scale" and e["ph"] == "X" for e in evs)


def test_profiler_sessions_do_not_bleed(capsys):
    from paddle_tpu import profiler

    with profiler.profiler():
        with profiler.RecordEvent("first_session_op"):
            pass
    capsys.readouterr()
    with profiler.profiler():
        pass
    # second (empty) session replaced the snapshot
    assert profiler.get_trace_events() == []


def test_reset_profiler_scoped_to_session():
    """reset_profiler drops only the live session's events — spans
    recorded by the metrics layer before the session are not the
    legacy API's to destroy."""
    from paddle_tpu import profiler

    with obs.span("metrics_mode_span"):
        pass
    profiler.start_profiler()
    with profiler.RecordEvent("sess_op"):
        pass
    profiler.reset_profiler()
    assert profiler.get_trace_events() == []   # session emptied
    profiler.stop_profiler()
    names = [e[0] for e in obs.tracing.trace_events()]
    assert "metrics_mode_span" in names        # survived the reset


def test_profiler_summary_exact_under_buffer_pressure(capsys):
    """The session summary table aggregates exactly even when buffer
    pressure drops old span tuples mid-session."""
    from paddle_tpu import profiler
    from paddle_tpu.observability import tracing

    old_cap, tracing._MAX_EVENTS = tracing._MAX_EVENTS, 64
    try:
        with profiler.profiler():
            for _ in range(200):   # >> capped buffer
                with profiler.RecordEvent("hot_op"):
                    pass
    finally:
        tracing._MAX_EVENTS = old_cap
    out = capsys.readouterr().out
    row = [ln for ln in out.splitlines() if ln.startswith("hot_op")]
    assert row and row[0].split()[1] == "200"   # exact Calls column


# -- default-off contract --------------------------------------------------

def test_disabled_records_nothing_and_is_cheap():
    obs.disable()
    main, startup, out = _two_op_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    exe.run(main, feed={"x": np.ones((4, 8), "float32")},
            fetch_list=[out])
    d = obs.dump()
    assert d["enabled"] is False
    # a disabled dump is a pure observation: it creates NOTHING (not
    # even the dump-time memory gauges)
    assert d["counters"] == {}
    assert d["gauges"] == {}
    assert d["histograms"] == {}
    assert d["spans"]["recorded"] == 0
    # disabled primitives are sub-microsecond-ish (generous CI bound)
    t0 = time.perf_counter()
    for _ in range(100000):
        obs.tracing.span("x")
        obs.inc("y")
    per_call_us = (time.perf_counter() - t0) / 200000 * 1e6
    assert per_call_us < 5.0, per_call_us


def test_flag_arms_the_layer():
    obs.disable()
    fluid.set_flags({"FLAGS_tpu_metrics": True})
    try:
        assert obs.enabled()
    finally:
        fluid.set_flags({"FLAGS_tpu_metrics": False})
    assert not obs.enabled()
    # and the sync is two-way: direct enable() keeps get_flags truthful
    obs.enable()
    assert fluid.get_flags("FLAGS_tpu_metrics")["FLAGS_tpu_metrics"]
    obs.disable()
    assert not fluid.get_flags("FLAGS_tpu_metrics")["FLAGS_tpu_metrics"]


def test_stop_profiler_without_start_keeps_metrics_spans(capsys):
    from paddle_tpu import profiler

    with obs.span("precious_metrics_span"):
        pass
    profiler.stop_profiler()   # no session live: harmless no-op
    capsys.readouterr()
    names = [e[0] for e in obs.tracing.trace_events()]
    assert "precious_metrics_span" in names


# -- conv stride guard (satellite ops/pallas/conv.py) ----------------------

def test_conv2d_bn_act_rejects_unsupported_stride():
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas.conv import conv2d_bn_act

    x = jnp.zeros((1, 9, 9, 128), jnp.float32)
    w = jnp.zeros((3, 3, 128, 128), jnp.float32)
    with pytest.raises(ValueError, match="stride 1 or 2"):
        conv2d_bn_act(x, w, stride=3)
    with pytest.raises(ValueError, match="stride 1 or 2"):
        conv2d_bn_act(x, w, stride=0)
