"""Replica-fleet front end: one router over N serving replicas.

One ``ServingEngine`` behind one HTTP server is a single point of
failure: a SIGKILL takes the whole serving tier down, a straggling
dispatch stalls every caller behind it, and overload is all-or-nothing.
This module is the tier that survives — a ``FleetRouter`` in front of N
replica processes (each a ``ServingEngine`` + HTTP front, spawned per
device or per process via ``launch.py --serving_replicas=N``) that owns
the four behaviors a fleet needs and a single engine cannot have:

- **Shared admission control.** ONE bounded queue for the whole fleet;
  past ``max_queue`` a submit is rejected with the typed
  ``ServerOverloaded`` — backpressure at the front door, not N private
  queues each discovering overload separately.

- **Cost-class load shedding with priority lanes.** Every request
  carries a cost class; each class has an admission watermark (a
  fraction of ``max_queue``). As the shared queue fills, the cheapest
  watermark trips first: low-priority/expensive requests are shed
  (typed ``RequestShed``, ``serving.shed{class=}``) while
  high-priority traffic still admits, and the dispatch order is a
  priority heap so admitted high-priority work also LEAVES the queue
  first. Deadline-expired requests are dropped before any dispatch is
  wasted on them and fail with the typed ``DeadlineExpired`` (HTTP
  504).

- **Health-checked routing.** A background prober polls each replica's
  ``/healthz`` (machine-readable lifecycle); a replica reporting
  ``draining``/``stopped`` stops receiving traffic IMMEDIATELY — not
  when its socket starts refusing — and a replica that stops answering
  (or fails dispatches) ``eject_after`` consecutive times is ejected
  from rotation in bounded time (``serving.replica_ejections{cause=}``
  + a ``serving.replica_ejected`` flight event). A relaunched replica
  that answers ``serving`` again rejoins automatically
  (``serving.replica_rejoins`` + ``serving.replica_rejoined``).

- **Bounded hedged retries, exactly-once.** An attempt that FAILS
  (replica died mid-flight) is re-dispatched to another live replica
  with the REMAINING deadline (never the original); an attempt that
  STRAGGLES past ``hedge_after_ms`` gets a racing hedge on a second
  replica (``serving.hedges``, at most ``max_hedges``). Results are
  exactly-once by construction: every request has an idempotent
  request id (replica engines dedup duplicate deliveries against it),
  a per-request latch surfaces the FIRST completion and discards the
  loser (``serving.hedge_wasted``), and the loser's socket is closed
  so it stops consuming a replica slot.

- **Token-level stream failover.** ``generate()`` proxies a decode
  replica's chunked ``/generate`` stream; when the replica dies
  mid-stream the router re-dispatches to a survivor with
  ``resume_from`` set to the next undelivered index and suppresses
  anything already yielded, so the caller sees every token index
  exactly once, in order, with no gaps
  (``serving.stream_resumes`` + ``serving.stream_resume`` flight
  events). Streams are admission-priced in COST UNITS scaled by
  ``max_tokens`` (``FleetConfig.cost_unit_tokens``), so an expensive
  low-priority stream sheds before a cheap high-priority one.

The router speaks plain HTTP/1.1 to the replicas over raw sockets and
routes every frame through ``distributed.fault.get_injector()`` — the
same injector that drills the PS dataplane — so ``tools/
serving_chaos.py`` can drop/delay/sever fleet RPCs deterministically
and CI can assert the SLO holds while it happens.

Trace story: a request's attempts ride the submitter's trace context
(or, under a launcher, the job trace id), and every attempt sends
``X-Trace-Id``/``X-Parent-Span`` headers, so one fleet request — queue
wait, every attempt, the winning replica's batch dispatch — is ONE
cross-process trace in the merged job ``trace.json``.

``FleetRouter`` implements the same ``predict`` / ``health`` /
``stats`` surface as ``ServingEngine``, so ``serving.
start_http_server(router)`` puts an HTTP front on the FLEET unchanged.
"""
from __future__ import annotations

import heapq
import itertools
import json
import socket
import threading
import time
import uuid
from collections import OrderedDict
from concurrent.futures import Future
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..distributed import fault as _fault
from ..observability import distributed as _dtrace
from ..observability import flight as _flight
from . import metrics as _m
from .engine import (DeadlineExpired, EngineStopped, ServerOverloaded,
                     ServingError)

__all__ = ["FleetConfig", "FleetRouter", "Replica", "RequestShed",
           "ReplicaUnavailable", "DEFAULT_COST_CLASSES"]


class RequestShed(ServerOverloaded):
    """Load shedding by cost class: the shared queue crossed THIS
    class's admission watermark. A cheaper/higher-priority class may
    still be admitted right now — retry later or downgrade the work,
    don't hammer the same lane."""


class ReplicaUnavailable(ServingError):
    """Every dispatch attempt failed and the retry budget (or the
    deadline) is exhausted — no replica produced a result."""


# priority lanes, highest first. The float is the class's admission
# watermark as a fraction of max_queue: class requests are SHED once
# queue depth reaches it. "high" admits up to the hard bound (only
# ServerOverloaded proper rejects it); cheaper lanes trip earlier, so
# under overload the low-priority shed rate is strictly above the
# high-priority one — the property the chaos drill asserts.
DEFAULT_COST_CLASSES: Tuple[Tuple[str, float], ...] = (
    ("high", 1.0), ("normal", 0.75), ("low", 0.5))


class FleetConfig:
    """Router knobs.

    ``cost_classes`` — ordered (name, admit_frac) pairs, highest
    priority first; ``admit_frac * max_queue`` is the queue depth — in
    COST UNITS — at which that class starts shedding.
    ``hedge_after_ms=None`` disables straggler hedging (failure
    retries still run). ``request_timeout_s`` bounds a request WITHOUT
    an explicit deadline. ``eject_after`` is consecutive
    probe/dispatch failures before a replica leaves rotation; with
    ``health_interval_ms`` it bounds how long a dead replica can keep
    eating traffic.

    Cost units price admission by EXPECTED WORK, not request count: a
    one-shot predict is 1 unit, a decode stream is
    ``ceil(max_tokens / cost_unit_tokens)`` units
    (``default_stream_tokens`` when the caller names no budget) — so
    one 512-token stream weighs what 32 one-shot requests weigh, and
    under pressure a long low-priority stream sheds BEFORE a short
    high-priority one rather than both being "one request"."""

    def __init__(self,
                 max_queue: int = 128,
                 num_dispatchers: int = 8,
                 cost_classes: Optional[Sequence[Tuple[str, float]]] = None,
                 default_class: Optional[str] = None,
                 default_deadline_ms: Optional[float] = None,
                 request_timeout_s: float = 30.0,
                 max_attempts: int = 3,
                 hedge_after_ms: Optional[float] = 200.0,
                 max_hedges: int = 1,
                 health_interval_ms: float = 100.0,
                 eject_after: int = 2,
                 connect_timeout_s: float = 2.0,
                 backoff_ms: float = 25.0,
                 cost_unit_tokens: int = 16,
                 default_stream_tokens: int = 16,
                 stream_stall_s: float = 5.0):
        self.max_queue = int(max_queue)
        if self.max_queue < 1:
            raise ValueError("max_queue must be >= 1")
        self.num_dispatchers = int(num_dispatchers)
        if self.num_dispatchers < 1:
            raise ValueError("num_dispatchers must be >= 1")
        classes = list(cost_classes if cost_classes is not None
                       else DEFAULT_COST_CLASSES)
        if not classes:
            raise ValueError("need at least one cost class")
        self.cost_classes: List[Tuple[str, float]] = []
        seen = set()
        for name, frac in classes:
            name = str(name)
            frac = float(frac)
            if name in seen:
                raise ValueError("duplicate cost class %r" % name)
            if not 0.0 < frac <= 1.0:
                raise ValueError(
                    "admit fraction for %r must be in (0, 1], got %g"
                    % (name, frac))
            seen.add(name)
            self.cost_classes.append((name, frac))
        self.default_class = (str(default_class) if default_class
                              else self.cost_classes[0][0])
        if self.default_class not in seen:
            raise ValueError("default_class %r not among cost classes %s"
                             % (self.default_class, sorted(seen)))
        self.default_deadline_ms = default_deadline_ms
        self.request_timeout_s = float(request_timeout_s)
        self.max_attempts = max(1, int(max_attempts))
        self.hedge_after_ms = (None if hedge_after_ms is None
                               else float(hedge_after_ms))
        self.max_hedges = max(0, int(max_hedges))
        self.health_interval_ms = float(health_interval_ms)
        self.eject_after = max(1, int(eject_after))
        self.connect_timeout_s = float(connect_timeout_s)
        self.backoff_ms = float(backoff_ms)
        self.cost_unit_tokens = max(1, int(cost_unit_tokens))
        self.default_stream_tokens = max(1, int(default_stream_tokens))
        # the streaming analogue of hedging: a stream attempt that
        # goes THIS long with no bytes (no token, no finish) is
        # declared stalled and failed over — without it a replica that
        # accepts the connection and then wedges burns the caller's
        # whole deadline on one attempt
        self.stream_stall_s = float(stream_stall_s)

    def stream_units(self, max_tokens: Optional[int]) -> int:
        """Admission weight of a decode stream: its expected decode
        cost in one-shot-request equivalents."""
        toks = (int(max_tokens) if max_tokens is not None
                else self.default_stream_tokens)
        return max(1, -(-toks // self.cost_unit_tokens))

    def class_rank(self, name: str) -> int:
        for i, (n, _) in enumerate(self.cost_classes):
            if n == name:
                return i
        raise ValueError("unknown cost class %r (have %s)"
                         % (name, [n for n, _ in self.cost_classes]))

    def admit_depth(self, name: str) -> int:
        """Queue depth at which ``name`` starts shedding."""
        for n, frac in self.cost_classes:
            if n == name:
                return max(1, int(round(frac * self.max_queue)))
        raise ValueError("unknown cost class %r" % name)


# -- replica state -----------------------------------------------------------

class Replica:
    """One replica endpoint and everything the router knows about it.
    ``state`` is the last OBSERVED lifecycle ("unknown" until the first
    probe — optimistically routable so a fresh fleet doesn't stall on
    its first health interval)."""

    ROUTABLE = ("serving", "unknown")

    def __init__(self, endpoint: str):
        self.endpoint = str(endpoint)
        self.state = "unknown"
        self.failures = 0          # consecutive probe/dispatch failures
        self.inflight = 0
        self.served = 0            # results actually surfaced from here
        self.ejections = 0
        self.was_ejected = False   # a rejoin is only a rejoin after one
        self.kind = "unknown"      # healthz engine_kind: oneshot|decode
        self.kv_occupancy: Optional[float] = None

    @property
    def routable(self) -> bool:
        return self.state in self.ROUTABLE

    def snapshot(self) -> Dict:
        return {"endpoint": self.endpoint, "state": self.state,
                "failures": self.failures, "inflight": self.inflight,
                "served": self.served, "ejections": self.ejections,
                "kind": self.kind, "kv_occupancy": self.kv_occupancy}


class _FleetRequest:
    """One admitted request: payload, lane, deadline, the exactly-once
    completion latch, and the live-attempt bookkeeping the dispatcher's
    hedge/retry loop runs on."""

    __slots__ = ("inputs", "cost_class", "rank", "deadline", "rid",
                 "future", "t_enqueue", "trace_ctx", "cond", "done",
                 "live", "last_launch", "last_error", "attempt_socks",
                 "tried", "units")

    def __init__(self, inputs, cost_class, rank, deadline, rid,
                 trace_ctx, units=1):
        self.inputs = inputs          # {name: nested list} (json-ready)
        self.cost_class = cost_class
        self.rank = rank
        self.deadline = deadline      # monotonic ts or None
        self.rid = rid
        self.future: Future = Future()
        self.t_enqueue = time.monotonic()
        self.trace_ctx = trace_ctx
        self.cond = threading.Condition()
        self.done = False
        self.live = 0                 # attempts in flight
        self.last_launch = 0.0
        self.last_error: Optional[BaseException] = None
        self.attempt_socks: List[socket.socket] = []
        self.tried: set = set()       # endpoints with a LIVE attempt
        self.units = int(units)       # admission cost units held


class _FleetStream:
    """Iterator over a fleet decode stream. Exists so the admission
    cost units release EXACTLY once on every exit path — exhaustion,
    ``close()``/``cancel()``, caller error, or a stream that is never
    iterated at all (a bare generator's ``finally`` never runs if its
    body never starts). ``cancel`` is the duck-typed hook the HTTP
    front calls when the downstream client disconnects."""

    __slots__ = ("_gen", "_release")

    def __init__(self, gen, release):
        self._gen = gen
        self._release = release

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self._gen)
        except BaseException:
            # StopIteration included: the stream is over either way
            self._release()
            raise

    def close(self) -> None:
        self._gen.close()
        self._release()

    def cancel(self) -> None:
        self.close()

    def __del__(self):
        try:
            self.close()
        except (RuntimeError, AttributeError):
            # finalizer during interpreter teardown: the generator may
            # be mid-run (RuntimeError) or the module half-cleared
            # (AttributeError) — neither may raise out of __del__
            pass


# -- minimal fault-injectable HTTP client ------------------------------------

class _Transport(OSError):
    """A fleet RPC attempt died in transit (connect/send/recv failure,
    injected fault, replica-side 503). Retryable on another replica."""


def _http_call(endpoint: str, method: str, path: str,
               body: Optional[bytes], timeout_s: float,
               connect_timeout_s: float,
               headers: Sequence[Tuple[str, str]] = (),
               sock_sink=None) -> Tuple[int, bytes]:
    """One HTTP/1.1 exchange over a raw socket, every frame routed
    through the process fault injector (the drillable fleet RPC path).
    Returns (status, body). ``sock_sink(sock)`` exposes the live socket
    to the caller for hedged-loser cancellation."""
    host, _, port = endpoint.rpartition(":")
    try:
        sock = socket.create_connection((host or "127.0.0.1", int(port)),
                                        timeout=connect_timeout_s)
    except OSError as e:
        raise _Transport("connect %s: %s" % (endpoint, e)) from e
    try:
        sock.settimeout(max(0.05, timeout_s))
        if sock_sink is not None:
            sock_sink(sock)
        lines = ["%s %s HTTP/1.1" % (method, path),
                 "Host: %s" % endpoint,
                 "Connection: close",
                 "Content-Length: %d" % (len(body) if body else 0),
                 "Content-Type: application/json"]
        for k, v in headers:
            lines.append("%s: %s" % (k, v))
        frame = ("\r\n".join(lines) + "\r\n\r\n").encode() + (body or b"")
        inj = _fault.get_injector()
        try:
            if inj is not None:
                if not inj.on_send(sock, frame):
                    # injected send-drop: the replica never sees the
                    # request; the peer's silence surfaces as a recv
                    # timeout below, exactly like a real lost frame
                    pass
            else:
                sock.sendall(frame)
            if inj is not None:
                verdict = inj.on_recv(sock)
                if verdict == "drop":
                    # injected recv-drop: the reply dies on the wire —
                    # surface a silence-shaped failure so the retry
                    # path engages exactly as for a real lost response
                    raise socket.timeout("injected: response dropped")
            return _read_http_response(sock)
        except _fault.FaultInjected as e:
            raise _Transport("injected: %s" % e) from e
        except (socket.timeout, OSError, ValueError) as e:
            raise _Transport("%s %s: %s: %s"
                             % (method, endpoint, type(e).__name__,
                                e)) from e
    finally:
        try:
            sock.close()
        except OSError:
            pass


def _read_http_response(sock: socket.socket) -> Tuple[int, bytes]:
    buf = b""
    while b"\r\n\r\n" not in buf:
        chunk = sock.recv(65536)
        if not chunk:
            raise ValueError("EOF before response headers")
        buf += chunk
        if len(buf) > 1 << 20:
            raise ValueError("oversized response headers")
    head, _, rest = buf.partition(b"\r\n\r\n")
    lines = head.decode("latin-1").split("\r\n")
    parts = lines[0].split(None, 2)
    if len(parts) < 2 or not parts[1].isdigit():
        raise ValueError("bad status line %r" % lines[0])
    status = int(parts[1])
    clen = None
    for ln in lines[1:]:
        k, _, v = ln.partition(":")
        if k.strip().lower() == "content-length":
            clen = int(v.strip())
    if clen is None:
        # Connection: close — read to EOF
        while True:
            chunk = sock.recv(65536)
            if not chunk:
                break
            rest += chunk
        return status, rest
    while len(rest) < clen:
        chunk = sock.recv(65536)
        if not chunk:
            raise ValueError("EOF mid-body (%d/%d bytes)"
                             % (len(rest), clen))
        rest += chunk
    return status, rest[:clen]


class _StreamHTTP(Exception):
    """A /generate attempt got a complete NON-200 reply: the replica is
    alive and said no. Carries status + error body so the caller can
    route (503 retry elsewhere, 4xx/5xx surface typed)."""

    def __init__(self, status: int, raw: bytes):
        super().__init__("HTTP %d: %s" % (status, _err_of(raw)))
        self.status = int(status)
        self.raw = raw


def _http_stream(endpoint: str, method: str, path: str,
                 body: Optional[bytes], timeout_s: float,
                 connect_timeout_s: float,
                 headers: Sequence[Tuple[str, str]] = (),
                 sock_sink=None,
                 stall_timeout_s: Optional[float] = None):
    """One chunked-transfer HTTP/1.1 exchange: generator yielding each
    ndjson event object as its bytes arrive, so tokens surface with
    decode-step latency instead of stream-end latency. Same raw-socket
    + fault-injector discipline as ``_http_call`` (the chaos drill
    kills replicas mid-chunk and this path must die honestly: any
    transport failure — EOF mid-chunk, reset, timeout, injected drop —
    raises ``_Transport`` so the router can fail over and resume)."""
    host, _, port = endpoint.rpartition(":")
    try:
        sock = socket.create_connection((host or "127.0.0.1", int(port)),
                                        timeout=connect_timeout_s)
    except OSError as e:
        raise _Transport("connect %s: %s" % (endpoint, e)) from e
    try:
        # the socket timeout bounds each recv(), i.e. the silence
        # BETWEEN events — the overall deadline is the caller's loop
        sock.settimeout(max(0.05, min(timeout_s, stall_timeout_s)
                            if stall_timeout_s is not None
                            else timeout_s))
        if sock_sink is not None:
            sock_sink(sock)
        lines = ["%s %s HTTP/1.1" % (method, path),
                 "Host: %s" % endpoint,
                 "Connection: close",
                 "Content-Length: %d" % (len(body) if body else 0),
                 "Content-Type: application/json"]
        for k, v in headers:
            lines.append("%s: %s" % (k, v))
        frame = ("\r\n".join(lines) + "\r\n\r\n").encode() + (body or b"")
        inj = _fault.get_injector()
        try:
            if inj is not None:
                if not inj.on_send(sock, frame):
                    pass  # injected send-drop -> recv timeout below
            else:
                sock.sendall(frame)
            if inj is not None and inj.on_recv(sock) == "drop":
                raise socket.timeout("injected: response dropped")
            buf = b""
            while b"\r\n\r\n" not in buf:
                chunk = sock.recv(65536)
                if not chunk:
                    raise ValueError("EOF before response headers")
                buf += chunk
                if len(buf) > 1 << 20:
                    raise ValueError("oversized response headers")
            head, _, buf = buf.partition(b"\r\n\r\n")
            hlines = head.decode("latin-1").split("\r\n")
            parts = hlines[0].split(None, 2)
            if len(parts) < 2 or not parts[1].isdigit():
                raise ValueError("bad status line %r" % hlines[0])
            status = int(parts[1])
            hdrs = {}
            for ln in hlines[1:]:
                k, _, v = ln.partition(":")
                hdrs[k.strip().lower()] = v.strip()
            if status != 200:
                # complete (small) error doc, then the typed refusal
                clen = int(hdrs.get("content-length") or 0)
                while len(buf) < clen:
                    chunk = sock.recv(65536)
                    if not chunk:
                        break
                    buf += chunk
                raise _StreamHTTP(status, buf[:clen])
            if hdrs.get("transfer-encoding", "").lower() != "chunked":
                raise ValueError("stream reply is not chunked")
            pending = b""  # decoded bytes not yet forming a full line
            while True:
                while b"\r\n" not in buf:
                    chunk = sock.recv(65536)
                    if not chunk:
                        raise ValueError("EOF mid-chunk header")
                    buf += chunk
                size_line, _, buf = buf.partition(b"\r\n")
                size = int(size_line.strip().split(b";")[0] or b"0", 16)
                if size == 0:
                    return  # terminal chunk — clean stream end
                while len(buf) < size + 2:  # data + trailing CRLF
                    chunk = sock.recv(65536)
                    if not chunk:
                        raise ValueError("EOF mid-chunk (%d/%d bytes)"
                                         % (len(buf), size))
                    buf += chunk
                pending += buf[:size]
                buf = buf[size + 2:]
                while b"\n" in pending:
                    line, _, pending = pending.partition(b"\n")
                    if line.strip():
                        yield json.loads(line.decode())
        except _StreamHTTP:
            raise
        except _fault.FaultInjected as e:
            raise _Transport("injected: %s" % e) from e
        except (socket.timeout, OSError, ValueError) as e:
            # json.JSONDecodeError is a ValueError: a half-written line
            # from a dying replica is a transport failure, not a
            # protocol error
            raise _Transport("%s %s: %s: %s"
                             % (method, endpoint, type(e).__name__,
                                e)) from e
    finally:
        try:
            sock.close()
        except OSError:
            pass


# -- the router --------------------------------------------------------------

class FleetRouter:
    """The fleet front end. Construct over the replica endpoints, then
    ``start()``; ``submit``/``predict`` mirror ``ServingEngine`` (plus
    ``cost_class``), so the HTTP front (``serving.start_http_server``)
    works on a fleet unchanged."""

    def __init__(self, endpoints: Sequence[str],
                 config: Optional[FleetConfig] = None):
        eps = [str(e).strip() for e in endpoints if str(e).strip()]
        if not eps:
            raise ValueError("FleetRouter needs at least one endpoint")
        self.config = config or FleetConfig()
        self.replicas = [Replica(e) for e in eps]
        self._rep_lock = threading.Lock()
        self._rr = itertools.count()
        self._heap: List[Tuple[int, int, _FleetRequest]] = []
        self._seq = itertools.count()
        self._cond = threading.Condition()
        # admission depth in COST UNITS (see FleetConfig): queued
        # one-shot requests + live decode streams, both under _cond
        self._queued_units = 0
        self._stream_units = 0
        # request-id -> Future, LRU-bounded (same contract as the
        # engine's cache: completed ids stay joinable until evicted)
        self._ids: "OrderedDict[str, Future]" = OrderedDict()
        self._ids_lock = threading.Lock()
        self._dispatchers: List[threading.Thread] = []
        self._health_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._started = False
        self._stopped = False

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "FleetRouter":
        if self._stopped:
            raise EngineStopped("fleet router cannot be restarted")
        if self._started:
            return self
        self._started = True
        for i in range(self.config.num_dispatchers):
            t = threading.Thread(target=self._dispatch_loop,
                                 name="fleet-dispatch-%d" % i,
                                 daemon=True)
            t.start()
            self._dispatchers.append(t)
        self._health_thread = threading.Thread(
            target=self._health_loop, name="fleet-health", daemon=True)
        self._health_thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        """Refuse new submits, fail everything still queued (typed),
        join the dispatchers."""
        if self._stopped:
            return
        self._stopped = True
        self._stop.set()
        with self._cond:
            leftovers = [req for _, _, req in self._heap]
            self._heap = []
            self._queued_units = 0
            self._cond.notify_all()
        for req in leftovers:
            self._finish_error(req, EngineStopped("fleet stopped"))
        end = time.monotonic() + timeout
        for t in self._dispatchers:
            t.join(max(0.0, end - time.monotonic()))
        if self._health_thread is not None:
            self._health_thread.join(max(0.0, end - time.monotonic()))

    def __enter__(self) -> "FleetRouter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def health(self) -> str:
        if self._stopped:
            return "stopped"
        if not self._started:
            return "starting"
        return "serving"

    @property
    def running(self) -> bool:
        return self._started and not self._stopped

    def stats(self) -> Dict:
        out = _m.snapshot()
        with self._cond:
            out["queue_depth"] = len(self._heap)
            out["queue_units"] = self._queued_units + self._stream_units
        out["running"] = self.running
        out["state"] = self.health()
        with self._rep_lock:
            out["replicas"] = [r.snapshot() for r in self.replicas]
        return out

    def healthy_count(self) -> int:
        """Replicas the prober has actually SEEN serving. Stricter than
        routable (which optimistically includes never-probed replicas so
        a fresh fleet doesn't stall): this is the "wait until the fleet
        is up" primitive, and an unprobed replica isn't up yet."""
        with self._rep_lock:
            return sum(1 for r in self.replicas if r.state == "serving")

    # -- request path --------------------------------------------------------

    def submit(self, feed: Dict[str, np.ndarray],
               deadline_ms: Optional[float] = None,
               request_id: Optional[str] = None,
               cost_class: Optional[str] = None) -> Future:
        """Admit one request into the fleet queue. Typed failures:
        ``ServerOverloaded`` (hard queue bound), ``RequestShed`` (this
        class's watermark tripped), ``EngineStopped``. The returned
        future resolves to the winning replica's outputs (name ->
        ndarray) or the typed error. Duplicate ``request_id`` submits
        join the original future (idempotent, like the engine)."""
        if not self.running:
            raise EngineStopped("fleet router is not accepting requests")
        cls = cost_class or self.config.default_class
        rank = self.config.class_rank(cls)  # raises on unknown class
        if not isinstance(feed, dict) or not feed:
            raise ValueError("feed must be a non-empty dict name -> array")
        if request_id is not None:
            with self._ids_lock:
                f = self._ids.get(str(request_id))
                if f is not None:
                    self._ids.move_to_end(str(request_id))
            if f is not None:
                _m.inc(_m.DEDUP_HITS)
                return f
        if deadline_ms is None:
            deadline_ms = self.config.default_deadline_ms
        deadline = (time.monotonic() + float(deadline_ms) / 1e3
                    if deadline_ms is not None else None)
        inputs = {str(n): np.asarray(v).tolist() for n, v in feed.items()}
        rid = str(request_id) if request_id is not None else uuid.uuid4().hex
        ctx = _dtrace.current()
        if ctx is None and _dtrace.job_trace_id() is not None:
            # under a launcher every fleet request joins the ONE job
            # trace, so per-replica serving spans merge into a single
            # cross-process timeline
            ctx = _dtrace.TraceContext(_dtrace.job_trace_id(),
                                       "fleetreq-" + rid[:12])
        req = _FleetRequest(inputs, cls, rank, deadline, rid, ctx)
        if request_id is not None:
            # register BEFORE admission, re-checking under the lock:
            # two concurrent duplicates race here, and the loser must
            # join the winner's future, never enqueue a second copy
            with self._ids_lock:
                f = self._ids.get(rid)
                if f is not None:
                    self._ids.move_to_end(rid)
                    _m.inc(_m.DEDUP_HITS)
                    return f
                self._ids[rid] = req.future
                while len(self._ids) > 4096:
                    self._ids.popitem(last=False)
        try:
            with self._cond:
                # depth is measured in COST UNITS: a queued decode
                # stream holding 32 units pressures the watermarks as
                # hard as 32 queued one-shot requests would
                depth = self._queued_units + self._stream_units
                admit = self.config.admit_depth(cls)
                if depth + req.units - 1 >= admit:
                    # the class's watermark tripped. For the TOP lane
                    # the watermark IS the hard bound
                    # (ServerOverloaded); any cheaper lane is SHED —
                    # typed per class, even when the queue is also
                    # full, so shed accounting reads "this class was
                    # turned away under overload"
                    if admit >= self.config.max_queue:
                        _m.inc(_m.REJECTED)
                        raise ServerOverloaded(
                            "fleet queue full (%d requests); retry "
                            "later" % self.config.max_queue)
                    _m.inc(_m.SHED, **{"class": cls})
                    raise RequestShed(
                        "queue depth %d at/over class %r watermark %d "
                        "— shed; retry later or use a higher-priority "
                        "class" % (depth, cls, admit))
                heapq.heappush(self._heap, (rank, next(self._seq), req))
                self._queued_units += req.units
                _m.inc(_m.REQUESTS)
                self._set_depth(len(self._heap))
                self._cond.notify()
        except ServerOverloaded as exc:
            if request_id is not None:
                # a concurrent duplicate may already hold this future:
                # resolve it with the same rejection so the holder is
                # never left waiting on a request that was never
                # admitted, then forget the id (a RETRY of it is a
                # fresh admission attempt, not a join of the failure)
                with self._ids_lock:
                    self._ids.pop(rid, None)
                try:
                    req.future.set_exception(exc)
                except Exception:
                    pass
            raise
        return req.future

    def predict(self, feed: Dict[str, np.ndarray],
                deadline_ms: Optional[float] = None,
                timeout: Optional[float] = None,
                request_id: Optional[str] = None,
                cost_class: Optional[str] = None) -> Dict[str, np.ndarray]:
        """Blocking submit().result() convenience."""
        return self.submit(feed, deadline_ms, request_id=request_id,
                           cost_class=cost_class).result(timeout)

    # -- streaming decode across the fleet -----------------------------------

    def generate(self, prompt, *, max_tokens: Optional[int] = None,
                 request_id: Optional[str] = None,
                 cost_class: Optional[str] = None,
                 deadline_s: Optional[float] = None,
                 resume_from: int = 0):
        """Stream one decode request through the fleet: pick a decode
        replica, proxy its ``/generate`` chunked stream, and on replica
        death RESUME on a survivor from the next undelivered token.
        The ``(request_id, token_index)`` contract makes failover
        exactly-once at the token level: each index is yielded at most
        once, in order, with no gaps, however many replicas die
        mid-stream (the survivor regenerates deterministically and the
        router suppresses anything already delivered).

        Admission is cost-priced: the stream holds
        ``ceil(max_tokens / cost_unit_tokens)`` queue units for its
        lifetime, so a long low-priority stream trips its shed
        watermark before a short high-priority one. Pre-stream
        failures are typed like ``submit`` (``RequestShed`` /
        ``ServerOverloaded`` / ``EngineStopped`` / ``ValueError``);
        once streaming, terminal failures arrive in-band as a finish
        event (reason ``deadline_expired`` / ``replica_unavailable`` /
        ``error``) — the engine's own contract, since the HTTP front
        cannot retract a 200 mid-stream."""
        if not self.running:
            raise EngineStopped("fleet router is not accepting requests")
        cls = cost_class or self.config.default_class
        self.config.class_rank(cls)  # raises on unknown class
        if not isinstance(prompt, (list, tuple)) or not prompt:
            raise ValueError("prompt must be a non-empty token list")
        prompt = [int(t) for t in prompt]
        if max_tokens is not None:
            max_tokens = int(max_tokens)
            if max_tokens < 1:
                raise ValueError("max_tokens must be >= 1")
        units = self.config.stream_units(max_tokens)
        admit = self.config.admit_depth(cls)
        with self._cond:
            depth = self._queued_units + self._stream_units
            if depth + units - 1 >= admit:
                if admit >= self.config.max_queue:
                    _m.inc(_m.REJECTED)
                    raise ServerOverloaded(
                        "fleet queue full (%d + %d units over %d); "
                        "retry later"
                        % (depth, units, self.config.max_queue))
                _m.inc(_m.SHED, **{"class": cls})
                raise RequestShed(
                    "stream of %d cost unit(s) at depth %d would cross "
                    "class %r watermark %d — shed; retry later, lower "
                    "max_tokens, or use a higher-priority class"
                    % (units, depth, cls, admit))
            self._stream_units += units
        rid = (str(request_id) if request_id is not None
               else uuid.uuid4().hex)
        deadline = time.monotonic() + (
            float(deadline_s) if deadline_s is not None
            else self.config.request_timeout_s)
        _m.inc(_m.STREAMS)
        released = []

        def release():
            # exactly-once: both the generator's finally and the
            # wrapper call this; a stream the caller never iterates
            # (generator body never entered) still releases on close
            if released:
                return
            released.append(True)
            with self._cond:
                self._stream_units = max(0, self._stream_units - units)

        return _FleetStream(
            self._generate_stream(prompt, max_tokens, rid, cls,
                                  deadline, int(resume_from), release),
            release)

    def _generate_stream(self, prompt, max_tokens, rid, cls, deadline,
                         resume_from, release):
        """The post-admission attempt loop (a generator: admission
        already happened eagerly in ``generate`` so callers get typed
        refusals at call time, not at first ``next()``)."""
        cfg = self.config
        next_index = int(resume_from)  # next token index owed caller
        emitted = 0
        failures = 0          # consecutive attempts with NO progress
        tried: set = set()    # endpoints failed since last progress
        last_error: Optional[BaseException] = None
        try:
            while True:
                rem = deadline - time.monotonic()
                if rem <= 0:
                    _m.inc(_m.DEADLINE_EXPIRED)
                    yield {"type": "finish",
                           "reason": "deadline_expired",
                           "error": "stream deadline expired after %d "
                                    "delivered token(s)" % emitted,
                           "tokens": emitted}
                    return
                if failures >= cfg.max_attempts:
                    _m.inc(_m.STREAM_ERRORS)
                    yield {"type": "finish",
                           "reason": "replica_unavailable",
                           "error": "no replica could continue the "
                                    "stream after %d attempt(s)%s"
                                    % (failures,
                                       (": last error %s" % last_error)
                                       if last_error else ""),
                           "tokens": emitted}
                    return
                rep = self._pick(exclude=tried, kind="decode")
                if rep is None:
                    # nothing routable right now: bounded nap — a
                    # relaunching replica may rejoin within deadline
                    failures += 1
                    time.sleep(max(0.0, min(cfg.backoff_ms / 1e3, rem)))
                    continue
                if failures > 0 or next_index > int(resume_from):
                    _m.inc(_m.FLEET_RETRIES)
                if next_index > int(resume_from):
                    # a true mid-stream failover: the stream resumes
                    # token-exact on another replica
                    _m.inc(_m.STREAM_RESUMES)
                    _flight.record("serving.stream_resume",
                                   rid=rid[:12], endpoint=rep.endpoint,
                                   from_index=next_index)
                body = json.dumps({"prompt": prompt,
                                   "max_tokens": max_tokens,
                                   "cost_class": cls,
                                   "deadline_ms": rem * 1e3,
                                   "resume_from": next_index}).encode()
                with self._rep_lock:
                    rep.inflight += 1
                try:
                    for ev in _http_stream(
                            rep.endpoint, "POST", "/generate", body,
                            timeout_s=rem,
                            connect_timeout_s=min(cfg.connect_timeout_s,
                                                  max(rem, 0.05)),
                            headers=[("X-Request-Id", rid)],
                            stall_timeout_s=cfg.stream_stall_s):
                        kind = ev.get("type")
                        if kind == "token":
                            idx = int(ev.get("index", -1))
                            if idx < next_index:
                                continue  # replayed duplicate — drop
                            if idx > next_index:
                                # a hole means the replica's replay
                                # contract broke; treat as transport
                                # and resume cleanly elsewhere
                                raise _Transport(
                                    "token index gap from %s: got %d, "
                                    "expected %d"
                                    % (rep.endpoint, idx, next_index))
                            next_index += 1
                            emitted += 1
                            failures = 0
                            tried = set()
                            yield ev
                        elif kind == "finish":
                            reason = str(ev.get("reason") or "")
                            if reason in ("engine_stopped", "cancelled"):
                                # the REPLICA is going away (drain /
                                # replica-local cancel), not our
                                # caller: fail over and resume
                                raise _Transport(
                                    "replica %s ended stream early: %s"
                                    % (rep.endpoint, reason))
                            if reason == "deadline_expired":
                                _m.inc(_m.DEADLINE_EXPIRED)
                            with self._rep_lock:
                                rep.served += 1
                            yield ev
                            return
                        else:
                            yield ev  # forward-compat passthrough
                    raise _Transport(
                        "stream from %s ended without a finish event"
                        % rep.endpoint)
                except _StreamHTTP as e:
                    last_error = e
                    if e.status == 503:
                        # alive-but-refusing (overload/drain): proof of
                        # life, never an ejection signal
                        with self._rep_lock:
                            rep.failures = 0
                        tried.add(rep.endpoint)
                        failures += 1
                    elif e.status == 501:
                        # a one-shot replica in a mixed fleet: remember
                        # its kind so streams stop landing on it
                        with self._rep_lock:
                            rep.kind = "oneshot"
                        tried.add(rep.endpoint)
                        failures += 1
                    else:
                        # 4xx/5xx: deterministic — a retry would fail
                        # identically; surface in-band
                        _m.inc(_m.STREAM_ERRORS)
                        yield {"type": "finish", "reason": "error",
                               "error": str(e), "tokens": emitted}
                        return
                except _Transport as e:
                    last_error = e
                    self._note_failure(rep, str(e))
                    tried.add(rep.endpoint)
                    failures += 1
                finally:
                    with self._rep_lock:
                        rep.inflight -= 1
        finally:
            release()

    def _set_depth(self, n: int) -> None:
        _m.set_queue_depth(n)

    # -- dispatch: retry + hedge state machine -------------------------------

    def _dispatch_loop(self) -> None:
        while not self._stop.is_set():
            with self._cond:
                if not self._heap:
                    self._cond.wait(0.05)
                if not self._heap:
                    continue
                _, _, req = heapq.heappop(self._heap)
                self._queued_units = max(0, self._queued_units
                                         - req.units)
                self._set_depth(len(self._heap))
            self._serve(req)

    def _remaining_s(self, req: _FleetRequest) -> float:
        if req.deadline is not None:
            return req.deadline - time.monotonic()
        # no explicit deadline: the router still bounds the request
        return (req.t_enqueue + self.config.request_timeout_s
                - time.monotonic())

    def _serve(self, req: _FleetRequest) -> None:
        cfg = self.config
        now = time.monotonic()
        if req.deadline is not None and now > req.deadline:
            # dropped BEFORE any dispatch is wasted — and the caller
            # gets the typed 504, never silence
            _m.inc(_m.DEADLINE_EXPIRED)
            self._finish_error(req, DeadlineExpired(
                "deadline passed %.1f ms ago while queued in the fleet"
                % ((now - req.deadline) * 1e3)))
            return
        _m.observe(_m.QUEUE_MS, (now - req.t_enqueue) * 1e3)
        attempts = 0
        hedges = 0
        with req.cond:
            while not req.done:
                rem = self._remaining_s(req)
                if rem <= 0:
                    break
                hedge_due = (
                    req.live > 0 and hedges < cfg.max_hedges
                    and cfg.hedge_after_ms is not None
                    and (time.monotonic() - req.last_launch) * 1e3
                    >= cfg.hedge_after_ms)
                want_launch = (req.live == 0) or hedge_due
                if want_launch and req.live == 0 \
                        and attempts >= cfg.max_attempts:
                    break  # retry budget exhausted, nothing in flight
                if want_launch and (req.live > 0
                                    or attempts < cfg.max_attempts):
                    rep = self._pick(exclude=req.tried)
                    if rep is None and req.live == 0:
                        # nowhere to send and nothing in flight: a
                        # short bounded nap — a relaunching replica
                        # may rejoin within the deadline
                        req.cond.wait(min(cfg.backoff_ms / 1e3, rem))
                        continue
                    if rep is not None:
                        if req.live > 0:
                            hedges += 1
                            _m.inc(_m.HEDGES)
                            _flight.record("serving.hedge",
                                           rid=req.rid[:12],
                                           endpoint=rep.endpoint)
                        elif attempts > 0:
                            _m.inc(_m.FLEET_RETRIES)
                        attempts += 1
                        self._launch_attempt(req, rep)
                        continue
                # wait for an attempt to finish, the hedge window to
                # open, or the deadline — whichever is first
                timeout = rem
                if req.live > 0 and hedges < cfg.max_hedges \
                        and cfg.hedge_after_ms is not None:
                    window = (cfg.hedge_after_ms / 1e3
                              - (time.monotonic() - req.last_launch))
                    timeout = min(timeout, max(window, 0.005))
                req.cond.wait(max(0.005, min(timeout, 0.25)))
        if req.done:
            return
        # loop exited without a winner: deadline or budget exhausted
        self._cancel_attempts(req)
        if self._remaining_s(req) <= 0 and (req.deadline is not None):
            _m.inc(_m.DEADLINE_EXPIRED)
            self._finish_error(req, DeadlineExpired(
                "deadline expired after %d attempt(s)%s" % (
                    attempts,
                    (": last error %s" % req.last_error)
                    if req.last_error else "")))
        else:
            self._finish_error(req, ReplicaUnavailable(
                "no replica answered after %d attempt(s)%s" % (
                    attempts,
                    (": last error %s" % req.last_error)
                    if req.last_error else "")))

    def _launch_attempt(self, req: _FleetRequest, rep: Replica) -> None:
        """Called with ``req.cond`` held."""
        req.live += 1
        req.last_launch = time.monotonic()
        req.tried.add(rep.endpoint)
        t = threading.Thread(target=self._run_attempt, args=(req, rep),
                             name="fleet-attempt", daemon=True)
        t.start()

    def _run_attempt(self, req: _FleetRequest, rep: Replica) -> None:
        t0 = time.perf_counter()
        with self._rep_lock:
            rep.inflight += 1
        err: Optional[BaseException] = None
        outcome = "error"
        try:
            rem = self._remaining_s(req)
            if rem <= 0:
                raise _Transport("deadline expired before attempt")
            # the attempt inherits the REMAINING deadline — a hedge or
            # retry must never hand the replica the original budget
            body = json.dumps({"inputs": req.inputs,
                               "deadline_ms": rem * 1e3,
                               "cost_class": req.cost_class}).encode()
            headers = [("X-Request-Id", req.rid)]
            if req.trace_ctx is not None:
                headers += [("X-Trace-Id", req.trace_ctx.trace_id),
                            ("X-Parent-Span", req.trace_ctx.span_id)]
            socks: List[socket.socket] = []

            def sink(s):
                socks.append(s)
                with req.cond:
                    req.attempt_socks.append(s)

            status, raw = _http_call(
                rep.endpoint, "POST", "/predict", body,
                timeout_s=rem, connect_timeout_s=min(
                    self.config.connect_timeout_s, max(rem, 0.05)),
                headers=headers, sock_sink=sink)
            if status == 200:
                doc = json.loads(raw.decode() or "{}")
                outputs = {str(n): np.asarray(v)
                           for n, v in (doc.get("outputs") or {}).items()}
                if self._complete(req, rep, outputs):
                    outcome = "won"
                else:
                    outcome = "wasted"
            elif status == 503:
                # replica-side overload/draining: retryable elsewhere.
                # The reply PROVES the replica process is alive, so
                # this must not count toward dead-replica ejection —
                # ejecting a busy replica under a burst would cascade
                # the overload onto the survivors (the prober handles
                # a genuinely draining one via its lifecycle state)
                e = _Transport("replica %s answered 503"
                               % rep.endpoint)
                e.replica_alive = True
                raise e
            elif status == 504:
                # the REPLICA's queue expired the deadline — it is
                # global, so the request is over everywhere
                _m.inc(_m.DEADLINE_EXPIRED)
                self._finish_error(req, DeadlineExpired(
                    "replica %s: %s" % (rep.endpoint,
                                        _err_of(raw))))
                outcome = "expired"
            else:
                # 400/500: deterministic request/model failure — a
                # retry would fail identically, surface it typed
                self._finish_error(req, ServingError(
                    "replica %s answered %d: %s"
                    % (rep.endpoint, status, _err_of(raw))))
                outcome = "failed"
        except _Transport as e:
            err = e
            if getattr(e, "replica_alive", False):
                with self._rep_lock:
                    rep.failures = 0
            elif not self._was_cancelled(req):
                self._note_failure(rep, str(e))
        except Exception as e:  # noqa: BLE001 — malformed reply etc.
            err = e
            if not self._was_cancelled(req):
                self._note_failure(rep, repr(e))
        finally:
            with self._rep_lock:
                rep.inflight -= 1
                if outcome in ("won", "wasted"):
                    # any completed exchange proves the replica alive
                    rep.failures = 0
            if req.trace_ctx is not None:
                _dtrace.record_span("serving.fleet_attempt", t0,
                                    cat="serving", ctx=req.trace_ctx,
                                    endpoint=rep.endpoint,
                                    outcome=outcome)
            with req.cond:
                req.live -= 1
                req.tried.discard(rep.endpoint)
                if err is not None:
                    req.last_error = err
                req.cond.notify_all()

    @staticmethod
    def _was_cancelled(req: _FleetRequest) -> bool:
        """True when the request already completed — this attempt's
        socket was closed by the winner's cancellation, so its error
        is OUR doing and must not mark the replica unhealthy."""
        with req.cond:
            return req.done

    def _complete(self, req: _FleetRequest, rep: Replica,
                  outputs: Dict[str, np.ndarray]) -> bool:
        """Exactly-once latch: the first completion wins; later ones
        are discarded (and counted) — a hedge can never surface two
        results for one request."""
        with req.cond:
            if req.done:
                _m.inc(_m.HEDGE_WASTED)
                return False
            req.done = True
            req.cond.notify_all()
        with self._rep_lock:
            rep.served += 1
        _m.observe(_m.TOTAL_MS,
                   (time.monotonic() - req.t_enqueue) * 1e3)
        try:
            req.future.set_result(outputs)
        except Exception:
            pass  # caller cancelled
        self._cancel_attempts(req)
        return True

    def _finish_error(self, req: _FleetRequest, exc: Exception) -> None:
        with req.cond:
            if req.done:
                return
            req.done = True
            req.cond.notify_all()
        _m.inc(_m.ERRORS)
        try:
            req.future.set_exception(exc)
        except Exception:
            pass
        self._cancel_attempts(req)

    @staticmethod
    def _cancel_attempts(req: _FleetRequest) -> None:
        """Close every attempt socket still open: the hedge loser (or
        an attempt outliving the deadline) stops consuming a replica
        slot NOW instead of running to completion for a discarded
        result."""
        with req.cond:
            socks, req.attempt_socks = req.attempt_socks, []
        for s in socks:
            try:
                s.close()
            except OSError:
                pass

    # -- routing + health ----------------------------------------------------

    def _pick(self, exclude=(), kind: Optional[str] = None
              ) -> Optional[Replica]:
        """Least-inflight routable replica, round-robin on ties;
        ``exclude`` keeps a hedge off the endpoint its original is
        already waiting on (falls back to it when there is nothing
        else — a straggler beats nothing). ``kind`` restricts to
        replicas whose probed ``engine_kind`` matches (unknown is
        optimistically allowed, like unprobed state)."""
        with self._rep_lock:
            routable = [r for r in self.replicas if r.routable]
            if kind is not None:
                routable = [r for r in routable
                            if r.kind in (kind, "unknown")]
            cands = [r for r in routable if r.endpoint not in exclude] \
                or routable
            if not cands:
                return None
            start = next(self._rr) % len(cands)
            order = cands[start:] + cands[:start]
            return min(order, key=lambda r: r.inflight)

    def _note_failure(self, rep: Replica, why: str) -> None:
        with self._rep_lock:
            rep.failures += 1
            should_eject = (rep.failures >= self.config.eject_after
                            and rep.routable)
        if should_eject:
            self._eject(rep, cause="dead", why=why)

    def _eject(self, rep: Replica, cause: str, why: str = "") -> None:
        with self._rep_lock:
            if not rep.routable:
                return
            rep.state = "draining" if cause == "draining" else "dead"
            rep.ejections += 1
            rep.was_ejected = True
        _m.inc(_m.REPLICA_EJECTIONS, cause=cause)
        _flight.record("serving.replica_ejected", endpoint=rep.endpoint,
                       cause=cause, why=why[:120])

    def _mark_up(self, rep: Replica) -> None:
        with self._rep_lock:
            rep.failures = 0
            if rep.routable:
                if rep.state == "unknown":
                    rep.state = "serving"
                return
            rep.state = "serving"
            rejoin = rep.was_ejected
        if rejoin:
            _m.inc(_m.REPLICA_REJOINS)
            _flight.record("serving.replica_rejoined",
                           endpoint=rep.endpoint)

    def _health_loop(self) -> None:
        interval = max(0.01, self.config.health_interval_ms / 1e3)
        while not self._stop.wait(interval):
            for rep in list(self.replicas):
                if self._stop.is_set():
                    return
                self._probe(rep)

    def _probe(self, rep: Replica) -> None:
        try:
            status, raw = _http_call(
                rep.endpoint, "GET", "/healthz", None,
                timeout_s=max(0.25,
                              self.config.health_interval_ms / 1e3 * 4),
                connect_timeout_s=self.config.connect_timeout_s)
            doc = {}
            try:
                doc = json.loads(raw.decode() or "{}")
            except ValueError:
                pass
            state = str(doc.get("status") or "")
            if status == 200 and state in ("serving", "ok"):
                ekind = str(doc.get("engine_kind") or "")
                occ = doc.get("kv_occupancy")
                with self._rep_lock:
                    if ekind:
                        rep.kind = ekind
                    rep.kv_occupancy = (float(occ) if isinstance(
                        occ, (int, float)) else None)
                self._mark_up(rep)
            elif state in ("draining", "stopped"):
                # the replica SAID it is leaving: stop routing NOW —
                # this is the proactive half the connection-refusal
                # path cannot give
                self._eject(rep, cause="draining", why=state)
            else:
                self._note_failure(rep, "healthz %d %s" % (status, state))
        except (_Transport, OSError, ValueError) as e:
            self._note_failure(rep, str(e))


def _err_of(raw: bytes) -> str:
    try:
        doc = json.loads(raw.decode() or "{}")
        return str(doc.get("error") or doc)[:200]
    except ValueError:
        return raw[:200].decode("latin-1", "replace")
