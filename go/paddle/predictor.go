// Go inference client over the paddle_tpu C ABI.
//
// Parity: /root/reference/go/paddle/predictor.go (cgo binding over the
// reference's paddle_fluid_c library). This binds csrc/libptcapi.so —
// the same four-entry ABI (PD_NewPredictor / PD_PredictorRun /
// PD_DeletePredictor / PD_GetLastError) in front of the XLA-compiled
// predictor.
//
// Build (from repo root, after csrc/build.sh):
//
//	CGO_CFLAGS="-I$PWD/csrc" CGO_LDFLAGS="-L$PWD/csrc -lptcapi" \
//	    go build ./go/paddle
package paddle

// #cgo LDFLAGS: -lptcapi
// #include <stdint.h>
// #include <stdlib.h>
// typedef struct PD_Predictor PD_Predictor;
// PD_Predictor* PD_NewPredictor(const char* model_dir);
// int PD_PredictorRun(PD_Predictor*, const char* input_name,
//                     const float* data, const int64_t* shape,
//                     int ndims, float* out, int64_t out_capacity,
//                     int64_t* out_size);
// void PD_DeletePredictor(PD_Predictor*);
// const char* PD_GetLastError();
import "C"

import (
	"fmt"
	"runtime"
	"unsafe"
)

// Predictor wraps a loaded inference model (a saved
// save_inference_model directory — JSON or reference __model__ format).
type Predictor struct {
	c *C.PD_Predictor
}

// NewPredictor loads the model saved at modelDir.
func NewPredictor(modelDir string) (*Predictor, error) {
	cdir := C.CString(modelDir)
	defer C.free(unsafe.Pointer(cdir))
	cp := C.PD_NewPredictor(cdir)
	if cp == nil {
		return nil, fmt.Errorf("paddle: %s", lastError())
	}
	p := &Predictor{c: cp}
	runtime.SetFinalizer(p, (*Predictor).finalize)
	return p, nil
}

// keepAlive pins p past its last cgo use so the GC finalizer can't
// free the C predictor mid-call (use-after-free hazard).
func (p *Predictor) keepAlive() { runtime.KeepAlive(p) }

func (p *Predictor) finalize() {
	if p.c != nil {
		C.PD_DeletePredictor(p.c)
		p.c = nil
	}
}

// Close releases the predictor eagerly (the finalizer also covers it).
func (p *Predictor) Close() { p.finalize() }

func lastError() string {
	return C.GoString(C.PD_GetLastError())
}

// Run feeds one float32 input (name + row-major data + shape) and
// returns the first fetch target's flattened float32 values.
func (p *Predictor) Run(inputName string, data []float32,
	shape []int64) ([]float32, error) {
	if p.c == nil {
		return nil, fmt.Errorf("paddle: predictor closed")
	}
	if len(data) == 0 || len(shape) == 0 {
		return nil, fmt.Errorf("paddle: empty input data/shape")
	}
	defer p.keepAlive()
	cname := C.CString(inputName)
	defer C.free(unsafe.Pointer(cname))

	// first call discovers the output size; grow and retry once
	capHint := int64(len(data)) * 4
	if capHint < 1024 {
		capHint = 1024
	}
	for attempt := 0; attempt < 2; attempt++ {
		out := make([]float32, capHint)
		var outSize C.int64_t
		rc := C.PD_PredictorRun(p.c, cname,
			(*C.float)(unsafe.Pointer(&data[0])),
			(*C.int64_t)(unsafe.Pointer(&shape[0])),
			C.int(len(shape)),
			(*C.float)(unsafe.Pointer(&out[0])),
			C.int64_t(capHint), &outSize)
		if rc == 0 {
			return out[:outSize], nil
		}
		if int64(outSize) > capHint { // buffer too small: resize, retry
			capHint = int64(outSize)
			continue
		}
		return nil, fmt.Errorf("paddle: run failed: %s", lastError())
	}
	return nil, fmt.Errorf("paddle: run failed after resize: %s",
		lastError())
}
