"""dygraph.nn layers.

Parity: /root/reference/python/paddle/fluid/dygraph/nn.py (Conv2D, Linear,
Pool2D, BatchNorm, Embedding, LayerNorm, Dropout, GRUUnit, NCE, PRelu,
BilinearTensorProduct, Conv2DTranspose, GroupNorm, SpectralNorm,
TreeConv subset).
"""
from __future__ import annotations

import numpy as np

from .. import framework
from ..initializer import ConstantInitializer, NormalInitializer, XavierInitializer
from ..param_attr import ParamAttr
from .layers import Layer
from .varbase import ParamBase, VarBase

__all__ = ["Conv2D", "Conv2DTranspose", "Pool2D", "Linear", "BatchNorm",
           "Embedding", "LayerNorm", "Dropout", "GRUUnit", "PRelu",
           "GroupNorm", "InstanceNorm"]


def _tracer():
    t = framework._dygraph_tracer()
    if t is None:
        raise RuntimeError("dygraph layers require dygraph.guard()")
    return t


def _create_param(shape, dtype, attr, is_bias=False, default_init=None):
    attr = ParamAttr._to_attr(attr)
    if attr is False:
        return None
    if default_init is None:
        default_init = ConstantInitializer(0.0) if is_bias else XavierInitializer()
    attr._with_initializer(default_init)
    from ..utils import unique_name

    name = attr.name or unique_name.generate("param")
    p = ParamBase.create(name, shape, dtype, attr.initializer,
                         trainable=attr.trainable)
    _tracer().register_parameter(p)
    return p


def _pair(x, n=2):
    return list(x) if isinstance(x, (list, tuple)) else [x] * n


class Conv2D(Layer):
    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, dilation=1, groups=None, param_attr=None,
                 bias_attr=None, use_cudnn=True, act=None, dtype="float32"):
        super().__init__()
        groups = groups or 1
        fs = _pair(filter_size)
        self._attrs = {
            "strides": _pair(stride),
            "paddings": _pair(padding),
            "dilations": _pair(dilation),
            "groups": groups,
        }
        self._act = act
        fan_in = num_channels * fs[0] * fs[1] // groups
        self.weight = _create_param(
            [num_filters, num_channels // groups] + fs, dtype, param_attr,
            default_init=NormalInitializer(0.0, (2.0 / fan_in) ** 0.5))
        self.bias = _create_param([num_filters], dtype, bias_attr, is_bias=True)

    def forward(self, input):
        out = _tracer().trace_op(
            "conv2d", {"Input": input, "Filter": self.weight}, {},
            self._attrs)["Output"][0]
        if self.bias is not None:
            out = _tracer().trace_op(
                "elementwise_add", {"X": out, "Y": self.bias}, {},
                {"axis": 1})["Out"][0]
        if self._act:
            out = _tracer().trace_op(self._act, {"X": out}, {}, {})["Out"][0]
        return out


class Conv2DTranspose(Layer):
    def __init__(self, num_channels, num_filters, filter_size, output_size=None,
                 padding=0, stride=1, dilation=1, groups=None, param_attr=None,
                 bias_attr=None, use_cudnn=True, act=None, dtype="float32"):
        super().__init__()
        groups = groups or 1
        fs = _pair(filter_size)
        self._attrs = {
            "strides": _pair(stride),
            "paddings": _pair(padding),
            "dilations": _pair(dilation),
            "groups": groups,
        }
        self._act = act
        self.weight = _create_param(
            [num_channels, num_filters // groups] + fs, dtype, param_attr)
        self.bias = _create_param([num_filters], dtype, bias_attr, is_bias=True)

    def forward(self, input):
        out = _tracer().trace_op(
            "conv2d_transpose", {"Input": input, "Filter": self.weight}, {},
            self._attrs)["Output"][0]
        if self.bias is not None:
            out = _tracer().trace_op("elementwise_add",
                                     {"X": out, "Y": self.bias}, {},
                                     {"axis": 1})["Out"][0]
        if self._act:
            out = _tracer().trace_op(self._act, {"X": out}, {}, {})["Out"][0]
        return out


class Pool2D(Layer):
    def __init__(self, pool_size=-1, pool_type="max", pool_stride=1,
                 pool_padding=0, global_pooling=False, use_cudnn=True,
                 ceil_mode=False, exclusive=True):
        super().__init__()
        self._attrs = {
            "pooling_type": pool_type,
            "ksize": _pair(pool_size),
            "strides": _pair(pool_stride),
            "paddings": _pair(pool_padding),
            "global_pooling": global_pooling,
            "ceil_mode": ceil_mode,
            "exclusive": exclusive,
        }

    def forward(self, input):
        return _tracer().trace_op("pool2d", {"X": input}, {},
                                  self._attrs)["Out"][0]


class Linear(Layer):
    def __init__(self, input_dim, output_dim, param_attr=None, bias_attr=None,
                 act=None, dtype="float32"):
        super().__init__()
        self.weight = _create_param([input_dim, output_dim], dtype, param_attr)
        self.bias = _create_param([output_dim], dtype, bias_attr, is_bias=True)
        self._act = act

    def forward(self, input):
        out = _tracer().trace_op(
            "matmul", {"X": input, "Y": self.weight}, {},
            {"transpose_X": False, "transpose_Y": False, "alpha": 1.0})["Out"][0]
        if self.bias is not None:
            out = _tracer().trace_op("elementwise_add",
                                     {"X": out, "Y": self.bias}, {},
                                     {"axis": len(out.shape) - 1})["Out"][0]
        if self._act:
            out = _tracer().trace_op(self._act, {"X": out}, {}, {})["Out"][0]
        return out


FC = Linear


class BatchNorm(Layer):
    def __init__(self, num_channels, act=None, is_test=False, momentum=0.9,
                 epsilon=1e-5, param_attr=None, bias_attr=None,
                 dtype="float32", data_layout="NCHW", in_place=False,
                 moving_mean_name=None, moving_variance_name=None,
                 do_model_average_for_mean_and_var=True,
                 use_global_stats=False, trainable_statistics=False):
        super().__init__()
        self.weight = _create_param([num_channels], dtype, param_attr,
                                    default_init=ConstantInitializer(1.0))
        self.bias = _create_param([num_channels], dtype, bias_attr,
                                  is_bias=True)
        self._mean = ParamBase.create(
            moving_mean_name or framework.unique_name.generate("bn_mean"),
            [num_channels], dtype, ConstantInitializer(0.0), trainable=False)
        self._variance = ParamBase.create(
            moving_variance_name or framework.unique_name.generate("bn_var"),
            [num_channels], dtype, ConstantInitializer(1.0), trainable=False)
        self.register_buffer("_mean_buf", self._mean)
        self.register_buffer("_variance_buf", self._variance)
        self._attrs = {"momentum": momentum, "epsilon": epsilon,
                       "data_layout": data_layout,
                       "use_global_stats": use_global_stats}
        self._act = act

    def forward(self, input):
        attrs = dict(self._attrs)
        attrs["is_test"] = not self.training
        res = _tracer().trace_op(
            "batch_norm",
            {"X": input, "Scale": self.weight, "Bias": self.bias,
             "Mean": self._mean, "Variance": self._variance},
            {},
            attrs,
        )
        # update running stats in place (reference MeanOut/VarianceOut refs)
        self._mean._array = res["MeanOut"][0]._array
        self._variance._array = res["VarianceOut"][0]._array
        out = res["Y"][0]
        if self._act:
            out = _tracer().trace_op(self._act, {"X": out}, {}, {})["Out"][0]
        return out


class Embedding(Layer):
    def __init__(self, size, is_sparse=False, is_distributed=False,
                 padding_idx=None, param_attr=None, dtype="float32"):
        super().__init__()
        self.weight = _create_param(list(size), dtype, param_attr,
                                    default_init=XavierInitializer())
        self._padding_idx = -1 if padding_idx is None else padding_idx

    def forward(self, input):
        return _tracer().trace_op(
            "lookup_table_v2", {"W": self.weight, "Ids": input}, {},
            {"padding_idx": self._padding_idx})["Out"][0]


class LayerNorm(Layer):
    def __init__(self, normalized_shape, scale=True, shift=True,
                 epsilon=1e-5, param_attr=None, bias_attr=None,
                 act=None, dtype="float32"):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        n = int(np.prod(normalized_shape))
        self.weight = _create_param([n], dtype, param_attr,
                                    default_init=ConstantInitializer(1.0)) \
            if scale else None
        self.bias = _create_param([n], dtype, bias_attr, is_bias=True) \
            if shift else None
        self._epsilon = epsilon
        self._act = act
        self._normalized_ndim = len(normalized_shape)

    def forward(self, input):
        begin = len(input.shape) - self._normalized_ndim
        ins = {"X": input}
        if self.weight is not None:
            ins["Scale"] = self.weight
        if self.bias is not None:
            ins["Bias"] = self.bias
        out = _tracer().trace_op(
            "layer_norm", ins, {},
            {"epsilon": self._epsilon, "begin_norm_axis": begin})["Y"][0]
        if self._act:
            out = _tracer().trace_op(self._act, {"X": out}, {}, {})["Out"][0]
        return out


class Dropout(Layer):
    def __init__(self, p=0.5, seed=None,
                 dropout_implementation="downgrade_in_infer",
                 is_test=False):
        super().__init__()
        self._attrs = {"dropout_prob": p, "seed": seed or 0,
                       "fix_seed": seed is not None,
                       "dropout_implementation": dropout_implementation}

    def forward(self, input):
        attrs = dict(self._attrs)
        attrs["is_test"] = not self.training
        return _tracer().trace_op("dropout", {"X": input}, {}, attrs)["Out"][0]


class GRUUnit(Layer):
    def __init__(self, size, param_attr=None, bias_attr=None,
                 activation="tanh", gate_activation="sigmoid",
                 origin_mode=False, dtype="float32"):
        super().__init__()
        d = size // 3
        self.weight = _create_param([d, d * 3], dtype, param_attr)
        self.bias = _create_param([1, d * 3], dtype, bias_attr, is_bias=True)
        self._attrs = {"origin_mode": origin_mode}

    def forward(self, input, hidden):
        ins = {"Input": input, "HiddenPrev": hidden, "Weight": self.weight}
        if self.bias is not None:
            ins["Bias"] = self.bias
        res = _tracer().trace_op("gru_unit", ins, {}, self._attrs)
        return res["Hidden"][0], res["ResetHiddenPrev"][0], res["Gate"][0]


class PRelu(Layer):
    def __init__(self, mode="all", channel=None, input_shape=None,
                 param_attr=None, dtype="float32"):
        super().__init__()
        if mode == "all":
            shape = [1]
        elif mode == "channel":
            shape = [channel]
        else:
            shape = list(input_shape[1:])
        self.weight = _create_param(shape, dtype, param_attr,
                                    default_init=ConstantInitializer(0.25))
        self._mode = mode

    def forward(self, input):
        return _tracer().trace_op(
            "prelu", {"X": input, "Alpha": self.weight}, {},
            {"mode": self._mode})["Out"][0]


class GroupNorm(Layer):
    def __init__(self, channels, groups, epsilon=1e-5, param_attr=None,
                 bias_attr=None, act=None, dtype="float32"):
        super().__init__()
        self.weight = _create_param([channels], dtype, param_attr,
                                    default_init=ConstantInitializer(1.0))
        self.bias = _create_param([channels], dtype, bias_attr, is_bias=True)
        self._attrs = {"groups": groups, "epsilon": epsilon}
        self._act = act

    def forward(self, input):
        out = _tracer().trace_op(
            "group_norm",
            {"X": input, "Scale": self.weight, "Bias": self.bias}, {},
            self._attrs)["Y"][0]
        if self._act:
            out = _tracer().trace_op(self._act, {"X": out}, {}, {})["Out"][0]
        return out


class InstanceNorm(Layer):
    def __init__(self, num_channels, epsilon=1e-5, param_attr=None,
                 bias_attr=None, dtype="float32"):
        super().__init__()
        self.weight = _create_param([num_channels], dtype, param_attr,
                                    default_init=ConstantInitializer(1.0))
        self.bias = _create_param([num_channels], dtype, bias_attr,
                                  is_bias=True)
        self._epsilon = epsilon

    def forward(self, input):
        return _tracer().trace_op(
            "instance_norm",
            {"X": input, "Scale": self.weight, "Bias": self.bias}, {},
            {"epsilon": self._epsilon})["Y"][0]
