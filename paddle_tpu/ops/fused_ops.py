"""Fused ops (reference operators/fused/) + Pallas fast paths.

The reference ships hand-fused CUDA kernels (fused_elemwise_activation,
multihead_matmul, fused_embedding_eltwise_layernorm...). On TPU, XLA does
most elementwise fusion automatically; these ops exist for program parity
and as the hook points where Pallas kernels (paddle_tpu/ops/pallas/) plug
in for the truly hot paths (flash attention).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import In, Out, register_op


@register_op(
    "fused_elemwise_activation",
    inputs=[In("X"), In("Y")],
    outputs=[Out("Out"), Out("IntermediateOut", no_grad=True)],
    attrs={"functor_list": [], "axis": -1, "scale": 0.0,
           "save_intermediate_out": False},
)
def _fused_elemwise_activation(ins, attrs):
    from .elementwise_ops import _align

    funcs = list(attrs.get("functor_list", []))
    x, y = ins["X"], ins["Y"]

    def apply_unary(name, v):
        return {
            "relu": jax.nn.relu,
            "scale": lambda a: a * attrs.get("scale", 1.0),
            "tanh": jnp.tanh,
            "sigmoid": jax.nn.sigmoid,
        }[name](v)

    inter = None
    if funcs and funcs[0].startswith("elementwise_"):
        bin_name, un_name = funcs[0], funcs[1] if len(funcs) > 1 else None
        xa, ya = _align(x, y, attrs.get("axis", -1))
        binf = {"elementwise_add": jnp.add, "elementwise_mul": jnp.multiply}[bin_name]
        inter = binf(xa, ya)
        out = apply_unary(un_name.replace("_grad", ""), inter) if un_name else inter
    else:
        un_name, bin_name = funcs[0], funcs[1]
        inter = apply_unary(un_name, y)
        xa, ia = _align(x, inter, attrs.get("axis", -1))
        binf = {"elementwise_add": jnp.add, "elementwise_mul": jnp.multiply}[bin_name]
        out = binf(xa, ia)
    return {"Out": out, "IntermediateOut": inter}


@register_op(
    "multihead_matmul",
    inputs=[In("Input"), In("W"), In("Bias"), In("BiasQK", dispensable=True)],
    outputs=[Out("Out")],
    attrs={"transpose_Q": False, "transpose_K": True, "transpose_V": False,
           "alpha": 1.0, "head_number": 1},
)
def _multihead_matmul(ins, attrs):
    # Fused QKV attention (reference fused/multihead_matmul_op.cu): Input
    # [B, S, 3H], W [3H? ...] — inference-era fused layout. Simplified:
    # Input already projected [B, S, 3, N, H/N] via W/Bias application.
    x, w, b = ins["Input"], ins["W"], ins["Bias"]
    nheads = attrs.get("head_number", 1)
    B, S, D = x.shape
    qkv = jnp.matmul(x, w.reshape(D, -1)) + b.reshape(1, 1, -1)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    hd = q.shape[-1] // nheads

    def split_heads(t):
        return t.reshape(B, S, nheads, hd).transpose(0, 2, 1, 3)

    q, k, v = split_heads(q), split_heads(k), split_heads(v)
    scores = jnp.matmul(q, k.transpose(0, 1, 3, 2)) * attrs.get("alpha", 1.0)
    if ins.get("BiasQK") is not None:
        scores = scores + ins["BiasQK"]
    probs = jax.nn.softmax(scores, axis=-1)
    ctx = jnp.matmul(probs, v)
    return {"Out": ctx.transpose(0, 2, 1, 3).reshape(B, S, -1)}


@register_op(
    "fc",
    inputs=[In("Input"), In("W"), In("Bias", dispensable=True)],
    outputs=[Out("Out")],
    attrs={"in_num_col_dims": 1, "activation_type": ""},
)
def _fc(ins, attrs):
    """Fused fully-connected (reference operators/fc_op.cc — the target
    of ir/fc_fuse_pass.cc). XLA fuses dot+add+act on its own; this op
    exists so fused inference graphs execute 1:1."""
    x = ins["Input"]
    w = ins["W"]
    k = int(attrs.get("in_num_col_dims", 1))
    lead = 1
    for s in x.shape[:k]:
        lead *= s
    out = x.reshape(lead, -1) @ w
    if ins.get("Bias") is not None:
        out = out + ins["Bias"].reshape(1, -1)
    act = attrs.get("activation_type", "")
    if act == "relu":
        out = jnp.maximum(out, 0)
    elif act:
        raise NotImplementedError("fc activation %r" % act)
    return {"Out": out.reshape(tuple(x.shape[:k]) + (w.shape[1],))}


def _registry_fn(op_type):
    from ..core.registry import OpInfoMap

    return OpInfoMap.instance().get(op_type).fn


@register_op(
    "fused_bias_act",
    inputs=[In("X"), In("Y")],
    outputs=[Out("Out"), Out("AddOut", dispensable=True),
             Out("ActOut", dispensable=True),
             Out("Mask", dispensable=True, no_grad=True)],
    attrs={"act": "relu", "axis": -1, "approximate": False,
           "alpha": 0.02, "dropout_prob": -1.0, "is_test": False,
           "fix_seed": False, "seed": 0,
           "dropout_implementation": "downgrade_in_infer"},
    grad=None,
    needs_rng=True,
)
def _fused_bias_act(ins, attrs):
    """bias/residual-add + activation (+ optional dropout) epilogue —
    the chain the core/fusion.py epilogue rewrite collapses
    (elementwise_add -> relu/gelu/... [-> dropout]). Each stage calls
    the SAME registered kernel fn the standalone ops run, in the same
    order, so the fused op is bit-for-bit with the chain it replaces —
    including the dropout mask, which draws from the original dropout
    op's RNG stream (the rewrite carries its ``_fwd_op_id`` so the
    pre-built ``dropout_grad`` op sees matching masks). Intermediate
    outputs (AddOut/ActOut/Mask) are emitted only when the program
    still reads them (pre-built grad ops recompute through forward
    INPUTS, so AddOut usually stays live); ``dropout_prob < 0`` means
    no dropout stage. XLA fuses the whole epilogue into one loop —
    the win is one traced/launched op instead of three."""
    from ..core.registry import RNG_SEED_ATTR

    inter = _registry_fn("elementwise_add")(
        {"X": ins["X"], "Y": ins["Y"]},
        {"axis": attrs.get("axis", -1)})["Out"]
    act = attrs.get("act", "relu")
    out = _registry_fn(act)({"X": inter}, dict(attrs))["Out"]
    act_out = out
    mask = None
    if float(attrs.get("dropout_prob", -1.0)) >= 0.0:
        d = _registry_fn("dropout")(
            {"X": out, "Seed": None, RNG_SEED_ATTR: ins.get(RNG_SEED_ATTR)},
            {"dropout_prob": attrs.get("dropout_prob"),
             "is_test": attrs.get("is_test", False),
             "dropout_implementation": attrs.get(
                 "dropout_implementation", "downgrade_in_infer")})
        out, mask = d["Out"], d.get("Mask")
    return {"Out": out, "AddOut": inter, "ActOut": act_out,
            "Mask": mask}


@register_op(
    "fused_residual_layer_norm",
    inputs=[In("X"), In("Y"), In("Scale", dispensable=True),
            In("Bias", dispensable=True)],
    outputs=[Out("Out"), Out("AddOut", dispensable=True),
             Out("Mean", dispensable=True, no_grad=True),
             Out("Variance", dispensable=True, no_grad=True)],
    attrs={"axis": -1, "epsilon": 1e-5, "begin_norm_axis": 1},
    grad=None,
)
def _fused_residual_layer_norm(ins, attrs):
    """residual-add + layer_norm epilogue (elementwise_add ->
    layer_norm), fused by the core/fusion.py rewrite under the same
    contract as fused_bias_act: identical registered kernels composed
    in program order, intermediates re-emitted for the pre-built
    backward."""
    inter = _registry_fn("elementwise_add")(
        {"X": ins["X"], "Y": ins["Y"]},
        {"axis": attrs.get("axis", -1)})["Out"]
    ln = _registry_fn("layer_norm")(
        {"X": inter, "Scale": ins.get("Scale"), "Bias": ins.get("Bias")},
        {"epsilon": attrs.get("epsilon", 1e-5),
         "begin_norm_axis": attrs.get("begin_norm_axis", 1)})
    return {"Out": ln["Y"], "AddOut": inter, "Mean": ln["Mean"],
            "Variance": ln["Variance"]}


@register_op(
    "flash_attention",
    inputs=[In("Q"), In("K"), In("V"),
            In("Lengths", dispensable=True, no_grad=True)],
    outputs=[Out("Out")],
    attrs={"causal": False, "scale": 0.0},
)
def _flash_attention(ins, attrs):
    """Flash attention over [B, H, S, D] (pallas kernel on TPU, exact
    dense math elsewhere; see ops/pallas/flash_attention.py).
    ``Lengths`` [B] int: per-row valid-KV count — the kernel-side
    padding mask (reference's additive src_slf_attn_bias)."""
    from .pallas import flash_attention

    q, k, v = ins["Q"], ins["K"], ins["V"]
    scale = attrs.get("scale", 0.0) or None
    return {"Out": flash_attention(q, k, v,
                                   causal=bool(attrs.get("causal")),
                                   scale=scale,
                                   lengths=ins.get("Lengths"))}
