#!/usr/bin/env python
"""Mechanical perf gate: diff two bench / multichip / metrics JSON files.

Thin CLI over ``paddle_tpu.observability.comparator`` — the watched
metrics, noise floors, and threshold logic live THERE now, shared with
the canary protocol (``observability/canary.py``), so CI and the
self-driving runtime can never disagree about what counts as a
regression.

Compares per-workload numbers between a BASE and a HEAD run and exits
nonzero when any watched higher-is-better metric regresses by more than
the threshold (or a lower-is-better one grows by more than it). This is
the regression gate the ROADMAP observability item asks for: CI diffs
the merged counters instead of a human eyeballing two JSON blobs.

Usage:
  tools/bench_diff.py BASE.json HEAD.json [--threshold 0.10]
      [--counters-threshold 0.25] [--json]

``--json`` prints the full machine-readable comparison (the same
``Comparison.to_dict()`` document the canary writes into
``steering_audit.json``) instead of the human table.

Exit codes: 0 = within threshold, 1 = regression past threshold,
2 = usage/load error.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from paddle_tpu.observability.comparator import (  # noqa: E402
    ABS_NOISE_FLOOR, COUNTER_WATCH_GROWS_BAD, WATCHED, Objective,
    compare, counter_totals, diff_counters, diff_records, load,
    workloads,
)

__all__ = ["WATCHED", "ABS_NOISE_FLOOR", "COUNTER_WATCH_GROWS_BAD",
           "Objective", "load", "workloads", "counter_totals",
           "diff_records", "diff_counters", "main"]


def _fmt(v):
    if isinstance(v, float):
        return "%.4g" % v
    return str(v)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        epilog="Exit codes: 0 ok, 1 regression, 2 load error.")
    ap.add_argument("base", nargs="?", help="BASE json (bench / "
                    "multichip / merged metrics.json)")
    ap.add_argument("head", nargs="?",
                    help="HEAD json to compare against BASE")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="max relative regression per workload metric "
                         "(default 0.10 = 10%%)")
    ap.add_argument("--counters-threshold", type=float, default=0.25,
                    help="max relative growth for watched counter "
                         "totals (default 0.25)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the machine-readable comparison "
                         "(Comparison.to_dict()) instead of the table")
    ap.add_argument("--self-test", action="store_true",
                    help="run the built-in self test and exit")
    args = ap.parse_args(argv)

    if args.self_test:
        return _self_test()
    if not args.base or not args.head:
        ap.error("base and head are required (unless --self-test)")

    try:
        base, head = load(args.base), load(args.head)
    except (OSError, ValueError) as e:
        print("bench_diff: cannot load inputs: %s" % e, file=sys.stderr)
        return 2

    cmp = compare(base, head, args.threshold, args.counters_threshold)

    if args.as_json:
        print(json.dumps(cmp.to_dict(), indent=2, sort_keys=True))
        if cmp.verdict == "no_overlap":
            return 2
        return 1 if cmp.regressions else 0

    for name, metric, bv, hv, rel, bad in cmp.rows:
        mark = " REGRESSION" if bad else ""
        print("%-24s %-26s %12s -> %-12s %+7.2f%%%s"
              % (name, metric, _fmt(bv), _fmt(hv), rel * 100, mark))
    for key, bv, hv, rel, bad in cmp.counter_rows:
        mark = " REGRESSION" if bad else ""
        print("%-51s %12s -> %-12s %+7.2f%%%s"
              % (key, _fmt(bv), _fmt(hv), rel * 100, mark))
    if not cmp.compared:
        print("bench_diff: no common workloads or counters between "
              "inputs", file=sys.stderr)
        return 2
    if cmp.regressions:
        print("bench_diff: %d metric(s) regressed past threshold"
              % cmp.regressions, file=sys.stderr)
        return 1
    print("bench_diff: ok (%d metrics compared)" % cmp.compared)
    return 0


def _self_test():
    """In-process sanity: detects a planted regression, passes a clean
    diff, and diffs a single-chip record against a multichip one."""
    single = {"extras": {"w": {"tokens_per_sec": 100.0, "step_ms": 10.0,
                               "diag": {"collective_bytes": 0}}}}
    multi = {"configs": {"w": {"tokens_per_sec": 100.0, "step_ms": 10.0,
                               "collective_bytes": 0}}}
    ok = list(diff_records(single, multi, 0.10))
    assert ok and not any(r[-1] for r in ok), ok
    # single-chip base (0 collective bytes) vs a multichip head: the
    # 0 -> N growth row shows but must not hard-fail the diff
    went_multi = {"configs": {"w": {"tokens_per_sec": 100.0,
                                    "step_ms": 10.0,
                                    "collective_bytes": 4096}}}
    rows = list(diff_records(single, went_multi, 0.10))
    zrow = [r for r in rows if r[1] == "collective_bytes"]
    assert zrow and not zrow[0][-1], rows
    slow = {"configs": {"w": {"tokens_per_sec": 50.0, "step_ms": 20.0,
                              "collective_bytes": 4096}}}
    bad = list(diff_records(single, slow, 0.10))
    assert any(r[-1] for r in bad), bad
    m0 = {"totals": {"parallel.collective_bytes": 1000,
                     "parallel.steps": 2}}
    m1 = {"totals": {"parallel.collective_bytes": 2000,
                     "parallel.steps": 2}}
    cbad = list(diff_counters(m0, m1, 0.25))
    assert any(r[-1] for r in cbad), cbad
    assert not any(r[-1] for r in diff_counters(m0, m0, 0.25))
    # growth from a ZERO base must still flag (no relative delta exists)
    z0 = {"totals": {"executor.compile_fallbacks": 0}}
    z1 = {"totals": {"executor.compile_fallbacks": 5}}
    zbad = list(diff_counters(z0, z1, 0.25))
    assert zbad and zbad[0][-1], zbad
    assert not list(diff_counters(z0, z0, 0.25))
    # a regression back to full-blob PS replication (delta bytes
    # ballooning for the same drilled workload) must flag
    r0 = {"totals": {"ps.replication_bytes{mode=delta}": 160,
                     "ps.replication_bytes{mode=full}": 16416}}
    r1 = {"totals": {"ps.replication_bytes{mode=delta}": 16416,
                     "ps.replication_bytes{mode=full}": 16416}}
    rbad = [r for r in diff_counters(r0, r1, 0.25) if r[-1]]
    assert rbad and rbad[0][0].startswith("ps.replication_bytes"), rbad
    assert not any(r[-1] for r in diff_counters(r0, r0, 0.25))
    # a regression from row-range moves back to whole-var moves (the
    # cold 99% of the table riding a migration again) must flag via
    # the kind=var series — the kind=range series holding steady for
    # the same drilled workload must not
    v0 = {"totals": {"ps.migration_bytes{kind=range}": 2048,
                     "ps.migration_bytes{kind=var}": 0}}
    v1 = {"totals": {"ps.migration_bytes{kind=range}": 2048,
                     "ps.migration_bytes{kind=var}": 262144}}
    vbad = [r for r in diff_counters(v0, v1, 0.25) if r[-1]]
    assert vbad and vbad[0][0] == "ps.migration_bytes{kind=var}", vbad
    assert not any(r[-1] for r in diff_counters(v0, v0, 0.25))
    # profile-block metrics: an overlap_frac / mfu_est drop past the
    # threshold is a regression even when raw throughput held
    p0 = {"configs": {"w": {"tokens_per_sec": 100.0, "profile": {
        "mfu_est": 0.40, "overlap_frac": 0.90,
        "critical_path_ms": 10.0}}}}
    p1 = {"configs": {"w": {"tokens_per_sec": 100.0, "profile": {
        "mfu_est": 0.40, "overlap_frac": 0.30,
        "critical_path_ms": 10.0}}}}
    pbad = [r for r in diff_records(p0, p1, 0.10)
            if r[1] == "overlap_frac"]
    assert pbad and pbad[0][-1], pbad
    assert not any(r[-1] for r in diff_records(p0, p0, 0.10))
    # single-chip phase attribution (ISSUE 14): an optimizer_ms /
    # feed_ms blowup past threshold+floor (fused update or async feed
    # silently off) must flag; sub-floor feed jitter must not
    f0 = {"extras": {"resnet50": {"images_per_sec": 100.0, "profile": {
        "mfu_est": 0.2, "optimizer_ms": 5.0, "feed_ms": 0.5}}}}
    f1 = {"extras": {"resnet50": {"images_per_sec": 100.0, "profile": {
        "mfu_est": 0.2, "optimizer_ms": 40.0, "feed_ms": 9.5}}}}
    fbad = {r[1] for r in diff_records(f0, f1, 0.5) if r[-1]}
    assert {"optimizer_ms", "feed_ms"} <= fbad, fbad
    f2 = {"extras": {"resnet50": {"images_per_sec": 100.0, "profile": {
        "mfu_est": 0.2, "optimizer_ms": 5.5, "feed_ms": 0.9}}}}
    assert not any(r[-1] for r in diff_records(f0, f2, 0.5)), \
        list(diff_records(f0, f2, 0.5))
    # a diag-level feed_ms (single-chip timed-loop measurement) also
    # resolves through _lookup
    g0d = {"extras": {"w": {"diag": {"feed_ms": 1.0}}}}
    g1d = {"extras": {"w": {"diag": {"feed_ms": 30.0}}}}
    gdbad = [r for r in diff_records(g0d, g1d, 0.5) if r[-1]]
    assert gdbad and gdbad[0][1] == "feed_ms", gdbad
    # sub-floor jitter on a near-zero timing base must NOT flag
    # (0.2ms -> 0.5ms exposed time is scheduler noise, not a 150%
    # regression), while the same relative delta at real magnitude
    # still does
    n0 = {"configs": {"w": {"profile": {"exposed_collective_ms": 0.2}}}}
    n1 = {"configs": {"w": {"profile": {"exposed_collective_ms": 0.5}}}}
    assert not any(r[-1] for r in diff_records(n0, n1, 0.5))
    n2 = {"configs": {"w": {"profile": {"exposed_collective_ms": 20.0}}}}
    n3 = {"configs": {"w": {"profile": {"exposed_collective_ms": 50.0}}}}
    nbad = list(diff_records(n2, n3, 0.5))
    assert any(r[-1] for r in nbad), nbad
    # device-truth metrics: a host-vs-device agreement collapse (the
    # host estimate silently diverging from the XPlane-folded truth)
    # must flag even when every host-side number held; sub-floor
    # agreement jitter must not
    d0 = {"configs": {"w": {"profile": {
        "overlap_frac": 0.60, "device_overlap_frac": 0.55,
        "host_device_agreement": 0.90}}}}
    d1 = {"configs": {"w": {"profile": {
        "overlap_frac": 0.60, "device_overlap_frac": 0.55,
        "host_device_agreement": 0.40}}}}
    dbad = [r for r in diff_records(d0, d1, 0.10)
            if r[1] == "host_device_agreement"]
    assert dbad and dbad[0][-1], dbad
    d2 = {"configs": {"w": {"profile": {
        "overlap_frac": 0.60, "device_overlap_frac": 0.55,
        "host_device_agreement": 0.85}}}}
    assert not any(r[-1] for r in diff_records(d0, d2, 0.10))
    dov = {"configs": {"w": {"profile": {
        "overlap_frac": 0.60, "device_overlap_frac": 0.10,
        "host_device_agreement": 0.90}}}}
    dovbad = [r for r in diff_records(d0, dov, 0.10)
              if r[1] == "device_overlap_frac"]
    assert dovbad and dovbad[0][-1], dovbad
    assert not any(r[-1] for r in diff_records(d0, d0, 0.10))
    # serving records: a queue-wait blowup or a compile-count leak
    # (the ladder property breaking) must flag; sub-floor latency
    # jitter must not; serving.errors growth from zero must flag
    s0 = {"configs": {"serving_smoke": {
        "rows_per_s": 5000.0, "p99_ms": 40.0,
        "serving_queue_ms_p99": 20.0, "serving_batch_size_mean": 3.0,
        "serving_padding_waste_frac": 0.3, "jit_traces": 4}},
        "counters_total": {"serving.errors": 0}}
    s1 = {"configs": {"serving_smoke": {
        "rows_per_s": 5000.0, "p99_ms": 44.0,
        "serving_queue_ms_p99": 24.0, "serving_batch_size_mean": 3.0,
        "serving_padding_waste_frac": 0.32, "jit_traces": 4}},
        "counters_total": {"serving.errors": 0}}
    assert not any(r[-1] for r in diff_records(s0, s1, 0.5)), \
        list(diff_records(s0, s1, 0.5))
    s2 = {"configs": {"serving_smoke": {
        "rows_per_s": 5000.0, "p99_ms": 40.0,
        "serving_queue_ms_p99": 200.0, "serving_batch_size_mean": 3.0,
        "serving_padding_waste_frac": 0.3, "jit_traces": 12}},
        "counters_total": {"serving.errors": 3}}
    sbad = {r[1] for r in diff_records(s0, s2, 0.5) if r[-1]}
    assert {"serving_queue_ms_p99", "jit_traces"} <= sbad, sbad
    scbad = [r for r in diff_counters(s0, s2, 0.25) if r[-1]]
    assert scbad and scbad[0][0] == "serving.errors", scbad
    # decode records (--decode smoke): a TTFT/ITL blowup past
    # threshold+floor must flag, as must the continuous-vs-static
    # speedup evaporating or stream errors growing from zero;
    # sub-floor SLO jitter and arena-pressure preemption noise must not
    dk0 = {"configs": {"decode_smoke": {
        "tokens_per_s": 900.0, "static_tokens_per_s": 500.0,
        "decode_speedup_vs_static": 1.8, "ttft_p50_ms": 20.0,
        "ttft_p99_ms": 60.0, "itl_p50_ms": 4.0, "itl_p99_ms": 12.0,
        "kv_occupancy_frac": 0.5, "preemptions": 1}},
        "counters_total": {"serving.stream_errors": 0}}
    dk1 = {"configs": {"decode_smoke": {
        "tokens_per_s": 880.0, "static_tokens_per_s": 500.0,
        "decode_speedup_vs_static": 1.7, "ttft_p50_ms": 26.0,
        "ttft_p99_ms": 75.0, "itl_p50_ms": 5.5, "itl_p99_ms": 17.0,
        "kv_occupancy_frac": 0.45, "preemptions": 3}},
        "counters_total": {"serving.stream_errors": 0}}
    assert not any(r[-1] for r in diff_records(dk0, dk1, 0.5)), \
        list(diff_records(dk0, dk1, 0.5))
    dk2 = {"configs": {"decode_smoke": {
        "tokens_per_s": 300.0, "static_tokens_per_s": 500.0,
        "decode_speedup_vs_static": 0.6, "ttft_p50_ms": 200.0,
        "ttft_p99_ms": 600.0, "itl_p50_ms": 40.0, "itl_p99_ms": 120.0,
        "kv_occupancy_frac": 0.5, "preemptions": 40}},
        "counters_total": {"serving.stream_errors": 2}}
    dkbad = {r[1] for r in diff_records(dk0, dk2, 0.5) if r[-1]}
    assert {"decode_speedup_vs_static", "ttft_p99_ms",
            "itl_p99_ms", "preemptions"} <= dkbad, dkbad
    assert "tokens_per_s" not in dkbad, dkbad  # load-bound, unwatched
    dkcbad = [r for r in diff_counters(dk0, dk2, 0.25) if r[-1]]
    assert dkcbad and dkcbad[0][0] == "serving.stream_errors", dkcbad
    # ps_scale records: a digest-cost regression past threshold+floor
    # (incremental digesting broken back toward full re-hash) must
    # flag; sub-floor hashing jitter must not; a delta-bytes blowup
    # (row slices regressing to whole-table ships) must flag
    g0 = {"configs": {"ps_scale": {
        "ps_digest_ms": 8.0, "rounds_per_s": 50.0,
        "repl_delta_bytes_per_round": 4096}}}
    g1 = {"configs": {"ps_scale": {
        "ps_digest_ms": 40.0, "rounds_per_s": 50.0,
        "repl_delta_bytes_per_round": 4096}}}
    gbad = [r for r in diff_records(g0, g1, 0.5)
            if r[1] == "ps_digest_ms"]
    assert gbad and gbad[0][-1], gbad
    g2 = {"configs": {"ps_scale": {
        "ps_digest_ms": 10.0, "rounds_per_s": 50.0,
        "repl_delta_bytes_per_round": 4096}}}
    assert not any(r[-1] for r in diff_records(g0, g2, 0.5))
    g3 = {"configs": {"ps_scale": {
        "ps_digest_ms": 8.0, "rounds_per_s": 50.0,
        "repl_delta_bytes_per_round": 16777216}}}
    g3bad = [r for r in diff_records(g0, g3, 0.5)
             if r[1] == "repl_delta_bytes_per_round"]
    assert g3bad and g3bad[0][-1], g3bad
    # durable-checkpoint records (ISSUE 19): a per-round durable-frame
    # blowup (incremental snapshots regressing to full-blob dumps) or a
    # cold-restore-latency regression past threshold+floor must flag;
    # fs-cache jitter under the restore_ms noise floor must not
    k0 = {"configs": {"ps_scale": {
        "ps_digest_ms": 8.0, "rounds_per_s": 50.0,
        "ckpt_delta_bytes_per_round": 4096.0,
        "ckpt_restore_ms": 60.0}}}
    k1 = {"configs": {"ps_scale": {
        "ps_digest_ms": 8.0, "rounds_per_s": 50.0,
        "ckpt_delta_bytes_per_round": 16777216.0,
        "ckpt_restore_ms": 60.0}}}
    kbad = [r for r in diff_records(k0, k1, 0.5)
            if r[1] == "ckpt_delta_bytes_per_round"]
    assert kbad and kbad[0][-1], kbad
    k2 = {"configs": {"ps_scale": {
        "ps_digest_ms": 8.0, "rounds_per_s": 50.0,
        "ckpt_delta_bytes_per_round": 4096.0,
        "ckpt_restore_ms": 75.0}}}
    assert not any(r[-1] for r in diff_records(k0, k2, 0.10)), \
        list(diff_records(k0, k2, 0.10))
    k3 = {"configs": {"ps_scale": {
        "ps_digest_ms": 8.0, "rounds_per_s": 50.0,
        "ckpt_delta_bytes_per_round": 4096.0,
        "ckpt_restore_ms": 600.0}}}
    k3bad = [r for r in diff_records(k0, k3, 0.5)
             if r[1] == "ckpt_restore_ms"]
    assert k3bad and k3bad[0][-1], k3bad
    # the checkpoint.round_bytes counter family (labeled by mode) is
    # watched: durable bytes ballooning for the same workload flags
    c0 = {"totals": {"checkpoint.round_bytes{mode=delta}": 4096,
                     "checkpoint.round_bytes{mode=full}": 16777216}}
    c1 = {"totals": {"checkpoint.round_bytes{mode=delta}": 16777216,
                     "checkpoint.round_bytes{mode=full}": 16777216}}
    ckbad = [r for r in diff_counters(c0, c1, 0.25) if r[-1]]
    assert ckbad and ckbad[0][0].startswith("checkpoint.round_bytes"), \
        ckbad
    assert not any(r[-1] for r in diff_counters(c0, c0, 0.25))
    # placement records (ISSUE 15): a predicted-vs-measured agreement
    # collapse past threshold+floor must flag; sub-floor drift must
    # not; and a SILENT plan-digest change between runs always flags
    # while an unchanged plan never does
    pl0 = {"configs": {"mlp": {"step_ms": 300.0, "placement": {
        "plan_digest": "aaaa1111", "predicted_step_ms": 290.0,
        "placement_agreement": 0.95}}}}
    pl1 = {"configs": {"mlp": {"step_ms": 300.0, "placement": {
        "plan_digest": "aaaa1111", "predicted_step_ms": 120.0,
        "placement_agreement": 0.40}}}}
    plbad = [r for r in diff_records(pl0, pl1, 0.10)
             if r[1] == "placement_agreement"]
    assert plbad and plbad[0][-1], plbad
    pl2 = {"configs": {"mlp": {"step_ms": 300.0, "placement": {
        "plan_digest": "aaaa1111", "predicted_step_ms": 280.0,
        "placement_agreement": 0.88}}}}
    assert not any(r[-1] for r in diff_records(pl0, pl2, 0.10)), \
        list(diff_records(pl0, pl2, 0.10))
    pl3 = {"configs": {"mlp": {"step_ms": 300.0, "placement": {
        "plan_digest": "bbbb2222", "predicted_step_ms": 290.0,
        "placement_agreement": 0.95}}}}
    digrow = [r for r in diff_records(pl0, pl3, 0.10)
              if r[1] == "placement.plan_digest"]
    assert digrow and digrow[0][-1], digrow
    assert not any(r[1] == "placement.plan_digest"
                   for r in diff_records(pl0, pl0, 0.10))
    # a run WITHOUT a placement block diffs cleanly against one with
    assert not any(r[-1] for r in diff_records(
        {"configs": {"mlp": {"step_ms": 300.0}}}, pl0, 0.10))
    # the structured layer the canary audits: verdicts + JSON safety
    c = compare(single, slow, 0.10)
    assert c.verdict == "regression" and not c.ok and c.regressions
    assert "step_ms" in c.regressed_metrics, c.regressed_metrics
    c_ok = compare(single, multi, 0.10)
    assert c_ok.verdict == "ok" and c_ok.ok
    assert compare({}, {}).verdict == "no_overlap"
    assert not compare({}, {}).ok
    d = compare(single, went_multi, 0.10).to_dict()
    json.dumps(d)  # inf rows must serialize
    zr = [r for r in d["rows"] if r["metric"] == "collective_bytes"]
    assert zr and zr[0]["rel"] == "inf" and not zr[0]["regressed"], d
    gain = compare(single, {"extras": {"w": {
        "tokens_per_sec": 150.0, "step_ms": 10.0,
        "diag": {"collective_bytes": 0}}}}, 0.10)
    imp = gain.improvement("tokens_per_sec")
    assert imp is not None and imp > 0.4, imp
    # -- objective scoring (ISSUE 20) --------------------------------
    # a plan trading a bounded latency regression for a big
    # throughput win: the flat bar rejects it, a weighted objective
    # promotes it — and the default (no objective) dict stays
    # bit-compatible (no "objective" key)
    ob0 = {"extras": {"srv": {"rows_per_s": 1000.0, "p50_ms": 10.0}}}
    ob1 = {"extras": {"srv": {"rows_per_s": 1300.0, "p50_ms": 16.0}}}
    flat_c = compare(ob0, ob1, 0.10)
    assert not flat_c.ok and "p50_ms" in flat_c.regressed_metrics
    assert "objective" not in flat_c.to_dict()
    obj = Objective({"rows_per_s": 3.0, "p50_ms": 1.0})
    obj_c = compare(ob0, ob1, 0.10, objective=obj)
    assert obj_c.ok and obj_c.verdict == "objective_improved", \
        obj_c.verdict
    assert obj_c.objective_score is not None \
        and obj_c.objective_score > 0
    json.dumps(obj_c.to_dict())
    assert "objective" in obj_c.to_dict()
    # weight normalization: weights express only RELATIVE importance
    rows = obj_c.rows
    s_a = Objective({"rows_per_s": 2.0, "p50_ms": 2.0}).score_rows(
        rows)[0]
    s_b = Objective({"rows_per_s": 1.0, "p50_ms": 1.0}).score_rows(
        rows)[0]
    assert abs(s_a - s_b) < 1e-12, (s_a, s_b)
    # missing-metric term: contributes 0 but keeps its weight in the
    # normalization and is flagged in the provenance
    miss = Objective({"rows_per_s": 1.0, "mfu_est": 1.0})
    ms, mterms = miss.score_rows(rows)
    mrow = [t for t in mterms if t["metric"] == "mfu_est"]
    assert mrow and mrow[0]["missing"] and \
        mrow[0]["contribution"] == 0.0, mterms
    only = Objective({"rows_per_s": 1.0}).score_rows(rows)[0]
    assert abs(ms - only / 2.0) < 1e-12, (ms, only)
    # hard-floor veto: SLO bound on the HEAD value trumps any score
    slo = Objective({"rows_per_s": 3.0, "p50_ms": 1.0},
                    hard_floors={"p50_ms": 15.0})
    slo_c = compare(ob0, ob1, 0.10, objective=slo)
    assert not slo_c.ok and slo_c.verdict == "hard_floor", \
        slo_c.verdict
    viol = slo_c.objective_result()["hard_floor_violations"]
    assert viol and viol[0]["metric"] == "p50_ms" \
        and viol[0]["head"] == 16.0, viol
    # direction conflict with WATCHED is a configuration bug;
    # an unwatched metric demands an explicit direction
    try:
        Objective({"step_ms": 1.0}, directions={"step_ms": +1})
        raise AssertionError("direction conflict not caught")
    except ValueError:
        pass
    try:
        Objective({"custom_metric": 1.0})
        raise AssertionError("unwatched metric without direction "
                             "not caught")
    except ValueError:
        pass
    Objective({"custom_metric": 1.0},
              directions={"custom_metric": -1})  # explicit is fine
    # the new watched surfaces: an objective_score drop in a record
    # flags like any watched metric, and canary.windows{phase=}
    # counters surface (non-fatally) through the counter diff
    os0 = {"extras": {"ab": {"objective_score": 0.5}}}
    os1 = {"extras": {"ab": {"objective_score": 0.3}}}
    osbad = [r for r in diff_records(os0, os1, 0.10)
             if r[1] == "objective_score"]
    assert osbad and osbad[0][-1], osbad
    w0 = {"totals": {"canary.windows{phase=incumbent}": 3,
                     "canary.windows{phase=candidate}": 3}}
    w1 = {"totals": {"canary.windows{phase=incumbent}": 9,
                     "canary.windows{phase=candidate}": 9}}
    wrows = list(diff_counters(w0, w1, 0.25))
    assert len(wrows) == 2 and not any(r[-1] for r in wrows), wrows
    print("bench_diff self-test ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
