"""Gradient clipping.

Parity: /root/reference/python/paddle/fluid/clip.py (GradientClipByValue,
GradientClipByNorm, GradientClipByGlobalNorm, set_gradient_clip,
append_gradient_clip_ops).
"""
from __future__ import annotations

from . import framework
from .layer_helper import LayerHelper


class BaseGradientClipAttr:
    def _append_clip_op(self, block, param, grad):
        raise NotImplementedError

    def _process_context(self, context, param, grad):
        pass

    def _create_operators(self, param, grad):
        return param, grad


class NullGradientClipAttr(BaseGradientClipAttr):
    def _append_clip_op(self, block, param, grad):
        return grad


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def _create_operators(self, param, grad):
        block = grad.block
        out = block.create_var(dtype=grad.dtype, shape=grad.shape)
        block.append_op("clip", inputs={"X": [grad]}, outputs={"Out": [out]},
                        attrs={"min": self.min, "max": self.max})
        return param, out


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _create_operators(self, param, grad):
        block = grad.block
        out = block.create_var(dtype=grad.dtype, shape=grad.shape)
        block.append_op("clip_by_norm", inputs={"X": [grad]},
                        outputs={"Out": [out]},
                        attrs={"max_norm": self.clip_norm})
        return param, out


class GradientClipByGlobalNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def _process_context(self, context, param, grad):
        ctx = context.setdefault(self.group_name,
                                 {"clip": self.clip_norm, "sq": []})
        block = grad.block
        sq = block.create_var(dtype=grad.dtype, shape=(1,))
        block.append_op("squared_l2_norm", inputs={"X": [grad]},
                        outputs={"Out": [sq]})
        ctx["sq"].append(sq)

    def _create_operators_group(self, context, params_grads):
        from .layers import ops as _ops
        from .layers import tensor as _t
        from .layers import nn as _nn

        ctx = context[self.group_name]
        block = params_grads[0][1].block
        total = block.create_var(dtype="float32", shape=(1,))
        block.append_op("sum", inputs={"X": ctx["sq"]},
                        outputs={"Out": [total]})
        gnorm = block.create_var(dtype="float32", shape=(1,))
        block.append_op("sqrt", inputs={"X": [total]}, outputs={"Out": [gnorm]})
        clip_v = block.create_var(dtype="float32", shape=(1,))
        block.append_op("fill_constant", outputs={"Out": [clip_v]},
                        attrs={"shape": [1], "value": self.clip_norm,
                               "dtype": 5}, infer_shape=False)
        denom = block.create_var(dtype="float32", shape=(1,))
        block.append_op("elementwise_max", inputs={"X": [gnorm], "Y": [clip_v]},
                        outputs={"Out": [denom]})
        scale = block.create_var(dtype="float32", shape=(1,))
        block.append_op("elementwise_div", inputs={"X": [clip_v], "Y": [denom]},
                        outputs={"Out": [scale]})
        outs = []
        for p, g in params_grads:
            ng = g.block.create_var(dtype=g.dtype, shape=g.shape)
            g.block.append_op("elementwise_mul", inputs={"X": [g], "Y": [scale]},
                              outputs={"Out": [ng]}, attrs={"axis": -1})
            outs.append((p, ng))
        return outs


_clip_attr_holder = {}


def set_gradient_clip(clip, param_list=None, program=None):
    program = program or framework.default_main_program()
    if param_list is None:
        param_list = program.all_parameters()
    for p in param_list:
        name = p if isinstance(p, str) else p.name
        _clip_attr_holder[(id(program), name)] = clip


def append_gradient_clip_ops(params_grads):
    if not params_grads:
        return params_grads
    program = params_grads[0][0].block.program
    context = {}
    global_clips = []
    res = []
    for p, g in params_grads:
        clip = _clip_attr_holder.get((id(program), p.name)) or \
            getattr(p, "gradient_clip_attr", None)
        if clip is None:
            res.append((p, g))
        elif isinstance(clip, GradientClipByGlobalNorm):
            clip._process_context(context, p, g)
            global_clips.append((clip, p, g))
        else:
            res.append(clip._create_operators(p, g))
    if global_clips:
        clip = global_clips[0][0]
        res.extend(clip._create_operators_group(
            context, [(p, g) for _, p, g in global_clips]))
    return res


class ErrorClipByValue:
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max
