"""Worker for the dense block-slicing PS test: TWO real pserver
processes each host ONE row block of the same fc weight
(slice_variable wired into the dataplane — reference
distribute_transpiler.py:95,540,1146); the trainer splits grads,
sends per-block, and concats recv'd blocks. Parity with the
single-process oracle is asserted by the pytest harness."""
import json
import os
import sys

import numpy as np

import paddle_tpu as fluid

STEPS = 5
BS = 16


def _net():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data(name="x", shape=[BS, 16], dtype="float32")
        y = fluid.data(name="y", shape=[BS, 1], dtype="float32")
        h = fluid.layers.fc(
            x, 8, act="relu",
            param_attr=fluid.ParamAttr(
                name="w",
                initializer=fluid.initializer.ConstantInitializer(0.12)),
            bias_attr=fluid.ParamAttr(
                name="b",
                initializer=fluid.initializer.ConstantInitializer(0.0)))
        pred = fluid.layers.fc(
            h, 1,
            param_attr=fluid.ParamAttr(
                name="w2",
                initializer=fluid.initializer.ConstantInitializer(0.2)),
            bias_attr=fluid.ParamAttr(
                name="b2",
                initializer=fluid.initializer.ConstantInitializer(0.0)))
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.MomentumOptimizer(0.05, 0.9).minimize(loss)
    return main, startup, loss


def _transpiler(endpoints):
    cfg = fluid.DistributeTranspilerConfig()
    cfg.min_block_size = 64   # w is [16, 8] = 128 elems -> 2 blocks
    return fluid.DistributeTranspiler(config=cfg), endpoints


def main():
    role = os.environ["PADDLE_TRAINING_ROLE"]
    endpoints = os.environ["PSERVER_ENDPOINTS"].split(",")
    out_path = sys.argv[1]

    main_prog, startup, loss = _net()
    t, eps = _transpiler(endpoints)
    t.transpile(trainer_id=0, program=main_prog, startup_program=startup,
                pservers=",".join(eps), trainers=1, sync_mode=True)

    if role == "PSERVER":
        endpoint = os.environ["PSERVER_ENDPOINT"]
        os.environ["PADDLE_PSERVER_RPC"] = "1"
        ps_prog = t.get_pserver_program(endpoint)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(t.get_startup_program(endpoint, ps_prog))
        exe.run(ps_prog)  # serve until shutdown
        return

    # trainer
    assert "w" in t.dense_blocks, "w must be block-sliced"
    blocks = t.dense_blocks["w"]
    assert len(blocks) == 2
    assert len({e["ep"] for e in blocks}) == 2, \
        "the two blocks must land on DIFFERENT servers"
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(5)
    W = rng.randn(16, 1).astype("float32")
    losses = []
    for _ in range(STEPS):
        xb = rng.randn(BS, 16).astype("float32")
        (l,) = exe.run(main_prog, feed={"x": xb, "y": xb @ W},
                       fetch_list=[loss])
        losses.append(float(np.asarray(l).ravel()[0]))
    scope = fluid.global_scope()
    w_final = np.asarray(scope.find_var("w").raw().array)

    from paddle_tpu.distributed.ps_rpc import PSClient

    for ep in endpoints:
        PSClient.for_endpoint(ep).shutdown_server()
    with open(out_path, "w") as f:
        f.write(json.dumps({"losses": losses,
                            "w_final": w_final.tolist(),
                            "block_eps": [e["ep"] for e in blocks]}))


if __name__ == "__main__":
    main()
