#!/usr/bin/env python
"""Emit a per-model placement plan: search the parallelism space
against a saved profile report and write the winning configuration as
a ``PADDLE_TPU_PLACEMENT_PLAN`` artifact.

The search is SYMBOLIC — no device, no tracing: every candidate plan
is rewritten on a fresh program and gated through the static verifier
(``verify_program`` + ``check_collective_schedule`` +
``check_cross_rank``) before it is scored by the profile-fitted cost
model. The audit (``--audit``) records every enumerated candidate with
its verdict, predicted step time, and cost provenance
(fitted | analytic) — the CI gate (tools/placement_smoke.py) asserts
zero candidates were ever traced before passing the verifier.

Usage:
  tools/placement_search.py --model mlp --report profile.json \
      --out plan.json [--devices 8] [--beam 4] [--seed 0]
      [--audit audit.json] [--no-quant]

``--report`` accepts a raw ``profiler.profile_step`` dict, a bench
record (its ``profile`` block unwraps), or may be omitted — the search
then runs on the analytic hand-estimate model and says so in every
provenance tag.

Run the emitted plan:
  PADDLE_TPU_PLACEMENT_PLAN=plan.json python bench.py --mc-config=mlp
"""
from __future__ import annotations

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def _builder(model: str, devices: int):
    """A fresh-program builder per supported model, reusing bench.py's
    model zoo (built at per-replica batch where the model reshapes by
    batch — the same contract as ``bench.py --mc-config``)."""
    import bench

    def build_mlp():
        import paddle_tpu as fluid

        with fluid.unique_name.guard():
            main, _startup, loss = bench._build_mnist_mlp(512)
        return main, loss.name

    def build_resnet50():
        import paddle_tpu as fluid

        with fluid.unique_name.guard():
            main, _s, loss, _b = bench._mc_build_resnet50(16, 96)
        return main, loss.name

    def build_bert():
        import paddle_tpu as fluid

        with fluid.unique_name.guard():
            main, _s, loss, _u = bench._mc_build_bert(
                max(1, 8 // devices), 128)
        return main, loss.name

    def build_gpt():
        import paddle_tpu as fluid

        with fluid.unique_name.guard():
            main, _s, loss, _u = bench._mc_build_gpt(
                max(1, 8 // devices), 512)
        return main, loss.name

    builders = {"mlp": build_mlp, "resnet50": build_resnet50,
                "bert_base": build_bert, "gpt_long": build_gpt}
    if model not in builders:
        raise SystemExit("placement_search: unknown model %r (have: %s)"
                         % (model, ", ".join(sorted(builders))))
    return builders[model]


def main(argv=None):
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--model", default="mlp",
                    help="model to plan for (mlp | resnet50 | "
                         "bert_base | gpt_long)")
    ap.add_argument("--report", default=None,
                    help="saved profile report (profile_step dict or "
                         "bench record); omit for the analytic model")
    ap.add_argument("--out", required=True,
                    help="plan artifact path (PADDLE_TPU_PLACEMENT_PLAN)")
    ap.add_argument("--audit", default=None,
                    help="also write the full candidate audit here")
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--beam", type=int, default=4)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--no-quant", action="store_true",
                    help="exclude quantized-wire candidates")
    args = ap.parse_args(argv)

    from paddle_tpu.observability import steering
    from paddle_tpu.placement import save_plan

    report = None
    if args.report:
        report = steering.load_report(args.report)
        if report is None:
            raise SystemExit(
                "placement_search: %r is not a usable profile report "
                "(need per_bucket + backward_segments; pass nothing to "
                "search on the analytic model instead)" % args.report)

    builder = _builder(args.model, args.devices)
    # dispatch through the steering registry — the one report->plan
    # interface every subsystem registers against
    plan, audit = steering.steer(
        "placement", report, builder=builder, n_devices=args.devices,
        beam_width=args.beam, seed=args.seed, model=args.model,
        include_quant=not args.no_quant)

    if args.audit:
        with open(args.audit, "w") as f:
            json.dump(audit, f, indent=2, sort_keys=True)
            f.write("\n")
    print("placement_search: %s: enumerated %d candidate(s) "
          "(%d verified, %d rejected, %d deduped, %d pruned, "
          "%d unsupported mesh(es)); cost model: %s"
          % (args.model, audit["enumerated"], audit["verified"],
             audit["rejected"], audit["deduped"], audit["pruned"],
             len(audit["unsupported"]), audit["cost_provenance"]))
    if plan is None:
        print("placement_search: NO candidate survived the static "
              "gate — not writing a plan", file=sys.stderr)
        return 1
    digest = save_plan(plan, args.out)
    w = audit["winner"]
    print("placement_search: winner mesh=%s sharded_update=%s "
          "bucket=%s strategy=%s quant=%s ef=%s async=%s"
          % (w["mesh"], w["sharded_update"], w["bucket"],
             w["strategy"], w["quant"]["mode"],
             w["quant"]["error_feedback"], w["async_collectives"]))
    print("placement_search: predicted step %.3f ms (%s); plan %s "
          "-> %s" % (plan.predicted_step_ms or 0.0,
                     plan.cost_provenance, digest[:12], args.out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
