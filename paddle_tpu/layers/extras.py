"""Wave-3 layer APIs.

Parity: the remaining single-op wrappers and small compositions from
/root/reference/python/paddle/fluid/layers/ (nn.py, loss.py, tensor.py,
control_flow.py, detection.py) — each docstring names its op/source.
"""
from __future__ import annotations

import numpy as np

from .. import framework
from ..layer_helper import LayerHelper

__all__ = [
    "reverse", "pixel_shuffle", "shuffle_channel", "space_to_depth",
    "temporal_shift", "shard_index", "multiplex", "crop", "crop_tensor",
    "affine_channel", "unfold", "affine_grid", "selu", "mean_iou",
    "bilinear_tensor_product", "cos_sim", "bpr_loss",
    "teacher_student_sigmoid_loss", "sigmoid_focal_loss", "row_conv",
    "fsp_matrix", "hash", "unique", "edit_distance", "warpctc",
    "ctc_greedy_decoder", "rank", "size", "is_empty", "sum",
    "scatter_nd", "pad_constant_like", "add_position_encoding",
    "dice_loss", "npair_loss", "while_loop", "case", "switch_case",
    "gru_unit", "lstm_unit", "py_func", "double_buffer",
    "image_resize_short", "gaussian_random_batch_size_like",
    "sequence_reverse", "get_tensor_from_selected_rows",
    "merge_selected_rows", "lod_reset",
]


def _simple(op_type, x, attrs=None, dtype=None, out_slot="Out"):
    helper = LayerHelper(op_type, input=x)
    out = helper.create_variable_for_type_inference(dtype or x.dtype)
    helper.append_op(op_type, inputs={"X": [x]},
                     outputs={out_slot: [out]}, attrs=attrs or {},
                     infer_shape=False)
    return out


def reverse(x, axis):
    return _simple("reverse", x, {"axis": axis if isinstance(
        axis, (list, tuple)) else [axis]})


def pixel_shuffle(x, upscale_factor):
    return _simple("pixel_shuffle", x, {"upscale_factor": upscale_factor})


def shuffle_channel(x, group, name=None):
    return _simple("shuffle_channel", x, {"group": group})


def space_to_depth(x, blocksize, name=None):
    return _simple("space_to_depth", x, {"blocksize": blocksize})


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None):
    return _simple("temporal_shift", x, {"seg_num": seg_num,
                                         "shift_ratio": shift_ratio})


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    return _simple("shard_index", input,
                   {"index_num": index_num, "nshards": nshards,
                    "shard_id": shard_id, "ignore_value": ignore_value})


def multiplex(inputs, index):
    helper = LayerHelper("multiplex", input=inputs[0])
    out = helper.create_variable_for_type_inference(inputs[0].dtype)
    helper.append_op("multiplex",
                     inputs={"X": list(inputs), "Ids": [index]},
                     outputs={"Out": [out]}, infer_shape=False)
    return out


def crop(x, shape=None, offsets=None, name=None):
    helper = LayerHelper("crop", input=x)
    out = helper.create_variable_for_type_inference(x.dtype)
    inputs = {"X": [x]}
    attrs = {}
    if isinstance(shape, framework.Variable):
        inputs["Y"] = [shape]
    else:
        attrs["shape"] = list(shape or [])
    attrs["offsets"] = list(offsets or [])
    helper.append_op("crop", inputs=inputs, outputs={"Out": [out]},
                     attrs=attrs, infer_shape=False)
    return out


def crop_tensor(x, shape=None, offsets=None, name=None):
    return crop(x, shape, offsets, name)


def affine_channel(x, scale=None, bias=None, data_layout="NCHW", name=None,
                   act=None):
    from .tensor import fill_constant

    helper = LayerHelper("affine_channel", input=x)
    c = int(x.shape[1 if data_layout == "NCHW" else -1])
    if scale is None:
        scale = fill_constant([c], x.dtype, 1.0)
    if bias is None:
        bias = fill_constant([c], x.dtype, 0.0)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("affine_channel",
                     inputs={"X": [x], "Scale": [scale], "Bias": [bias]},
                     outputs={"Out": [out]},
                     attrs={"data_layout": data_layout},
                     infer_shape=False)
    return out


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    def _pair(v):
        return list(v) if isinstance(v, (list, tuple)) else [v, v]

    pads = paddings if isinstance(paddings, (list, tuple)) and \
        len(paddings) == 4 else _pair(paddings) * 2
    return _simple("unfold", x,
                   {"kernel_sizes": _pair(kernel_sizes),
                    "strides": _pair(strides),
                    "paddings": list(pads),
                    "dilations": _pair(dilations)}, out_slot="Y")


def affine_grid(theta, out_shape, name=None):
    helper = LayerHelper("affine_grid", input=theta)
    out = helper.create_variable_for_type_inference(theta.dtype)
    inputs = {"Theta": [theta]}
    attrs = {}
    if isinstance(out_shape, framework.Variable):
        inputs["OutputShape"] = [out_shape]
    else:
        attrs["output_shape"] = [int(v) for v in out_shape]
    helper.append_op("affine_grid", inputs=inputs,
                     outputs={"Output": [out]}, attrs=attrs,
                     infer_shape=False)
    return out


def selu(x, scale=None, alpha=None, name=None):
    attrs = {}
    if scale is not None:
        attrs["scale"] = scale
    if alpha is not None:
        attrs["alpha"] = alpha
    return _simple("selu", x, attrs)


def mean_iou(input, label, num_classes):
    helper = LayerHelper("mean_iou", input=input)
    miou = helper.create_variable_for_type_inference("float32")
    wrong = helper.create_variable_for_type_inference("int32")
    correct = helper.create_variable_for_type_inference("int32")
    helper.append_op("mean_iou",
                     inputs={"Predictions": [input], "Labels": [label]},
                     outputs={"OutMeanIou": [miou], "OutWrong": [wrong],
                              "OutCorrect": [correct]},
                     attrs={"num_classes": num_classes},
                     infer_shape=False)
    return miou, wrong, correct


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    helper = LayerHelper("bilinear_tensor_product", input=x,
                         param_attr=param_attr, bias_attr=bias_attr)
    dtype = helper.input_dtype()
    w = helper.create_parameter(
        attr=helper.param_attr,
        shape=[size, int(x.shape[1]), int(y.shape[1])], dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    inputs = {"X": [x], "Y": [y], "Weight": [w]}
    if bias_attr is not False:
        b = helper.create_parameter(attr=helper.bias_attr,
                                    shape=[1, size], dtype=dtype,
                                    is_bias=True)
        inputs["Bias"] = [b]
    helper.append_op("bilinear_tensor_product", inputs=inputs,
                     outputs={"Out": [out]}, infer_shape=False)
    return out


def cos_sim(X, Y):
    helper = LayerHelper("cos_sim", input=X)
    out = helper.create_variable_for_type_inference(X.dtype)
    xn = helper.create_variable_for_type_inference(X.dtype)
    yn = helper.create_variable_for_type_inference(X.dtype)
    helper.append_op("cos_sim", inputs={"X": [X], "Y": [Y]},
                     outputs={"Out": [out], "XNorm": [xn], "YNorm": [yn]},
                     infer_shape=False)
    if X.shape is not None:
        out.shape = (int(X.shape[0]), 1)
    return out


def bpr_loss(input, label, name=None):
    helper = LayerHelper("bpr_loss", input=input)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("bpr_loss",
                     inputs={"X": [input], "Label": [label]},
                     outputs={"Y": [out]}, infer_shape=False)
    return out


def teacher_student_sigmoid_loss(input, label, soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0):
    helper = LayerHelper("teacher_student_sigmoid_loss", input=input)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("teacher_student_sigmoid_loss",
                     inputs={"X": [input], "Label": [label]},
                     outputs={"Y": [out]},
                     attrs={"soft_max_up_bound": soft_max_up_bound,
                            "soft_max_lower_bound": soft_max_lower_bound},
                     infer_shape=False)
    return out


def sigmoid_focal_loss(x, label, fg_num, gamma=2.0, alpha=0.25):
    helper = LayerHelper("sigmoid_focal_loss", input=x)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("sigmoid_focal_loss",
                     inputs={"X": [x], "Label": [label],
                             "FgNum": [fg_num]},
                     outputs={"Out": [out]},
                     attrs={"gamma": gamma, "alpha": alpha},
                     infer_shape=False)
    return out


def row_conv(input, future_context_size, param_attr=None, act=None):
    helper = LayerHelper("row_conv", input=input,
                         param_attr=param_attr)
    dtype = helper.input_dtype()
    w = helper.create_parameter(
        attr=helper.param_attr,
        shape=[future_context_size + 1, int(input.shape[-1])],
        dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("row_conv",
                     inputs={"X": [input], "Filter": [w]},
                     outputs={"Out": [out]}, infer_shape=False)
    return out


def fsp_matrix(x, y):
    helper = LayerHelper("fsp", input=x)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("fsp", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]}, infer_shape=False)
    return out


def hash(input, hash_size, num_hash=1, name=None):
    return _simple("hash", input, {"mod_by": hash_size,
                                   "num_hash": num_hash}, dtype="int64")


def unique(x, dtype="int32"):
    helper = LayerHelper("unique", input=x)
    out = helper.create_variable_for_type_inference(x.dtype)
    index = helper.create_variable_for_type_inference(dtype)
    helper.append_op("unique", inputs={"X": [x]},
                     outputs={"Out": [out], "Index": [index]},
                     infer_shape=False)
    return out, index


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None):
    helper = LayerHelper("edit_distance", input=input)
    out = helper.create_variable_for_type_inference("float32")
    seq_num = helper.create_variable_for_type_inference("int64")
    helper.append_op("edit_distance",
                     inputs={"Hyps": [input], "Refs": [label]},
                     outputs={"Out": [out], "SequenceNum": [seq_num]},
                     attrs={"normalized": normalized},
                     infer_shape=False)
    return out, seq_num


def warpctc(input, label, blank=0, norm_by_times=False,
            input_length=None, label_length=None):
    helper = LayerHelper("warpctc", input=input)
    loss = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"Logits": [input], "Label": [label]}
    if input_length is not None:
        inputs["LogitsLength"] = [input_length]
    helper.append_op("warpctc",
                     inputs=inputs,
                     outputs={"Loss": [loss]},
                     attrs={"blank": blank,
                            "norm_by_times": norm_by_times},
                     infer_shape=False)
    loss.shape = (int(input.shape[0]) if len(input.shape) == 3 else 1, 1)
    return loss


def ctc_greedy_decoder(input, blank, name=None):
    """argmax over classes then CTC alignment (reference
    ctc_greedy_decoder = top_k + ctc_align)."""
    from .nn import argmax

    ids = argmax(input, axis=-1)
    helper = LayerHelper("ctc_align", input=input)
    out = helper.create_variable_for_type_inference("int64")
    out.lod_level = 1
    helper.append_op("ctc_align", inputs={"Input": [ids]},
                     outputs={"Output": [out]},
                     attrs={"blank": blank, "merge_repeated": True},
                     infer_shape=False)
    return out


def rank(input):
    """Static rank as a constant tensor (reference layers/nn.py rank)."""
    from .tensor import fill_constant

    return fill_constant([1], "int32", len(input.shape))


def size(input):
    """Runtime element count (handles dynamic -1 dims via the shape op,
    unlike a compile-time constant which would go negative)."""
    from .nn import reduce_prod, shape
    from .tensor import cast

    return cast(reduce_prod(cast(shape(input), "int64")), "int64")


def is_empty(x, cond=None):
    from .control_flow import equal
    from .tensor import assign, cast, fill_constant

    zero = fill_constant([1], "int64", 0)
    out = equal(cast(size(x), "int64"), zero)
    if cond is not None:
        assign(out, output=cond)
        return cond
    return out


def sum(x):
    """Elementwise sum of a LIST of tensors (reference layers.sum ->
    sum op; distinct from reduce_sum)."""
    xs = x if isinstance(x, (list, tuple)) else [x]
    helper = LayerHelper("sum", input=xs[0])
    out = helper.create_variable_for_type_inference(xs[0].dtype)
    helper.append_op("sum", inputs={"X": list(xs)},
                     outputs={"Out": [out]}, infer_shape=False)
    return out


def scatter_nd(index, updates, shape, name=None):
    """zeros(shape) with updates scattered (reference scatter_nd =
    scatter_nd_add onto zeros)."""
    from .nn import scatter_nd_add
    from .tensor import fill_constant

    zero = fill_constant(list(shape), updates.dtype, 0.0)
    return scatter_nd_add(zero, index, updates)


def pad_constant_like(x, y, pad_value=0.0, name=None):
    """Pad y to x's shape (reference pad_constant_like_op)."""
    from .nn import pad

    paddings = []
    for xs, ys in zip(x.shape, y.shape):
        if int(xs) < 0 or int(ys) < 0:
            raise ValueError(
                "pad_constant_like requires static shapes; got %s vs %s"
                % (x.shape, y.shape))
        paddings.extend([0, int(xs) - int(ys)])
    return pad(y, paddings, pad_value)


def add_position_encoding(input, alpha, beta, name=None):
    """Sinusoidal position encoding added in-graph (reference
    add_position_encoding_op)."""
    from . import tensor as lt
    from .nn import elementwise_add
    from .ops import scale

    T, D = int(input.shape[1]), int(input.shape[2])
    pos = np.arange(T)[:, None]
    dim = np.arange(D // 2)[None, :]
    inv = 1.0 / np.power(10000.0, 2 * dim / D)
    enc = np.zeros((T, D), np.float32)
    enc[:, 0::2] = np.sin(pos * inv)
    enc[:, 1::2] = np.cos(pos * inv)
    # [1, T, D]: broadcast over the (possibly dynamic) batch dim
    enc_var = lt.assign(enc[None])
    return elementwise_add(scale(input, scale=alpha),
                           scale(enc_var, scale=beta))


def dice_loss(input, label, epsilon=1e-5):
    """(reference layers/nn.py dice_loss): one-hot the class labels,
    per-sample dice, then mean."""
    from .nn import one_hot, reduce_mean, reduce_sum
    from .ops import scale
    from .tensor import cast

    depth = int(input.shape[-1])
    # one_hot squeezes label's trailing 1-dim: [..., 1] -> [..., depth]
    label_oh = cast(one_hot(label, depth), input.dtype)
    reduce_dim = list(range(1, len(input.shape)))
    inse = reduce_sum(input * label_oh, dim=reduce_dim)
    denom = reduce_sum(input, dim=reduce_dim) + \
        reduce_sum(label_oh, dim=reduce_dim)
    dice = scale(inse, 2.0) / (denom + epsilon)
    return reduce_mean(scale(dice, -1.0, bias=1.0))


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """(reference layers/loss.py npair_loss composition)."""
    from .loss import softmax_with_cross_entropy
    from .nn import matmul, reduce_mean, reduce_sum, transpose
    from .ops import scale
    from .tensor import cast

    reg = reduce_mean(reduce_sum(anchor * anchor, dim=1)) + \
        reduce_mean(reduce_sum(positive * positive, dim=1))
    sim = matmul(anchor, transpose(positive, [1, 0]))
    n = int(anchor.shape[0])
    lab = cast(labels, "int64")
    from .nn import reshape

    ce = softmax_with_cross_entropy(sim, reshape(lab, [n, 1]))
    return reduce_mean(ce) + scale(reg, l2_reg / 2.0)


def while_loop(cond, body, loop_vars, is_test=False, name=None):
    """Functional while (reference layers/control_flow.py while_loop)
    built on the While op: loop vars thread through assigns."""
    from .control_flow import While
    from .tensor import assign

    c = cond(*loop_vars)
    w = While(c)
    with w.block():
        new_vars = body(*loop_vars)
        if not isinstance(new_vars, (list, tuple)):
            new_vars = [new_vars]
        for old, new in zip(loop_vars, new_vars):
            assign(new, output=old)
        assign(cond(*loop_vars), output=c)
    return list(loop_vars)


def case(pred_fn_pairs, default=None, name=None):
    """First-true-wins select chain (reference layers/control_flow.py
    case; both branches evaluate — XLA select semantics)."""
    helper = LayerHelper("case")
    if default is None:
        raise ValueError("case requires a default fn here")
    result = default()
    for pred, fn in reversed(pred_fn_pairs):
        val = fn()
        out = helper.create_variable_for_type_inference(val.dtype)
        helper.append_op("where",
                         inputs={"Condition": [pred], "X": [val],
                                 "Y": [result]},
                         outputs={"Out": [out]}, infer_shape=False)
        result = out
    return result


def switch_case(branch_index, branch_fns, default=None, name=None):
    from .control_flow import equal  # noqa: F401
    from .tensor import fill_constant

    pairs = []
    helper = LayerHelper("switch_case")
    for idx, fn in (branch_fns.items() if isinstance(branch_fns, dict)
                    else enumerate(branch_fns)):
        iconst = fill_constant([1], branch_index.dtype, int(idx))
        eq = helper.create_variable_for_type_inference("bool")
        helper.append_op("equal",
                         inputs={"X": [branch_index], "Y": [iconst]},
                         outputs={"Out": [eq]}, infer_shape=False)
        pairs.append((eq, fn))
    return case(pairs, default=default or pairs[-1][1])


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid",
             origin_mode=False):
    """(reference layers/rnn.py gru_unit over the gru_unit op)."""
    helper = LayerHelper("gru_unit", input=input, param_attr=param_attr,
                         bias_attr=bias_attr)
    dtype = helper.input_dtype()
    d = size // 3
    w = helper.create_parameter(attr=helper.param_attr, shape=[d, 3 * d],
                                dtype=dtype)
    inputs = {"Input": [input], "HiddenPrev": [hidden], "Weight": [w]}
    if bias_attr is not False:
        b = helper.create_parameter(attr=helper.bias_attr,
                                    shape=[1, 3 * d], dtype=dtype,
                                    is_bias=True)
        inputs["Bias"] = [b]
    gate = helper.create_variable_for_type_inference(dtype)
    rhp = helper.create_variable_for_type_inference(dtype)
    hid = helper.create_variable_for_type_inference(dtype)
    helper.append_op("gru_unit", inputs=inputs,
                     outputs={"Gate": [gate], "ResetHiddenPrev": [rhp],
                              "Hidden": [hid]},
                     attrs={"origin_mode": origin_mode},
                     infer_shape=False)
    b = int(hidden.shape[0])
    hid.shape = (b, d)
    rhp.shape = (b, d)
    gate.shape = (b, 3 * d)
    return hid, rhp, gate


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """(reference layers/rnn.py lstm_unit: fc + lstm_unit op)."""
    from .nn import fc
    from .tensor import concat

    helper = LayerHelper("lstm_unit", input=x_t)
    d = int(cell_t_prev.shape[-1])
    merged = concat([x_t, hidden_t_prev], axis=1)
    gates = fc(merged, size=4 * d, param_attr=param_attr,
               bias_attr=bias_attr)
    c = helper.create_variable_for_type_inference(x_t.dtype)
    h = helper.create_variable_for_type_inference(x_t.dtype)
    helper.append_op("lstm_unit",
                     inputs={"X": [gates], "C_prev": [cell_t_prev]},
                     outputs={"C": [c], "H": [h]},
                     attrs={"forget_bias": forget_bias},
                     infer_shape=False)
    c.shape = tuple(cell_t_prev.shape)
    h.shape = tuple(cell_t_prev.shape)
    return h, c


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Delegates to the full py_func layer (nn.py) backed by the real
    py_func op with backward-callable support (py_func_op.cc); this
    round-2 forward-only shim kept its export slot here."""
    from .nn import py_func as _py_func_full

    return _py_func_full(func, x, out, backward_func=backward_func,
                         skip_vars_in_backward_input=
                         skip_vars_in_backward_input)


def double_buffer(reader, place=None, name=None):
    """Device double-buffering is built into DataLoader
    (use_double_buffer=True); graph-side this is identity."""
    return reader


def image_resize_short(input, out_short_len, resample="BILINEAR"):
    from .nn import image_resize

    h, w = int(input.shape[2]), int(input.shape[3])
    short = min(h, w)
    scale = out_short_len / float(short)
    return image_resize(input, out_shape=[int(round(h * scale)),
                                          int(round(w * scale))],
                        resample=resample)


def gaussian_random_batch_size_like(input, shape, mean=0.0, std=1.0,
                                    seed=0, dtype="float32"):
    from .nn import gaussian_random

    shape = list(shape)
    shape[0] = int(input.shape[0])
    return gaussian_random(shape, mean=mean, std=std, seed=seed,
                           dtype=dtype)


def sequence_reverse(x, name=None):
    """Reverse each sequence (LoD) — needs_lod op composition via the
    reverse op on equal-length, else host path."""
    helper = LayerHelper("sequence_reverse", input=x)
    out = helper.create_variable_for_type_inference(x.dtype)
    out.lod_level = getattr(x, "lod_level", 0)
    helper.append_op("sequence_reverse", inputs={"X": [x]},
                     outputs={"Y": [out]}, infer_shape=False)
    return out


def get_tensor_from_selected_rows(x, name=None):
    return _simple("get_tensor_from_selected_rows", x)


def merge_selected_rows(x, name=None):
    helper = LayerHelper("merge_selected_rows", input=x)
    out = helper.main_program.current_block().create_var(
        name=framework.unique_name.generate("merged_sr"),
        type="selected_rows", dtype=x.dtype)
    helper.append_op("merge_selected_rows", inputs={"X": [x]},
                     outputs={"Out": [out]}, infer_shape=False)
    return out


def lod_reset(x, y=None, target_lod=None):
    """Re-stamp a tensor's LoD (reference lod_reset_op)."""
    helper = LayerHelper("lod_reset", input=x)
    out = helper.create_variable_for_type_inference(x.dtype)
    out.lod_level = 1
    inputs = {"X": [x]}
    attrs = {}
    if y is not None:
        inputs["Y"] = [y]
    else:
        attrs["target_lod"] = [int(v) for v in (target_lod or [])]
    helper.append_op("lod_reset", inputs=inputs, outputs={"Out": [out]},
                     attrs=attrs, infer_shape=False)
    out.shape = tuple(x.shape)
    return out


def linear_chain_crf(input, label, param_attr=None, length=None):
    """CRF negative log-likelihood (reference layers/nn.py
    linear_chain_crf over linear_chain_crf_op). Dense [B, T, K] input;
    creates the [K+2, K] transition parameter. `length` is not yet
    honored — pad with the repeated last label (the NLL of the padded
    tail is then constant wrt the emissions)."""
    if length is not None:
        raise NotImplementedError(
            "linear_chain_crf(length=...) is not supported yet; pad "
            "labels with the repeated final label instead")
    helper = LayerHelper("linear_chain_crf", input=input,
                         param_attr=param_attr)
    dtype = helper.input_dtype()
    k = int(input.shape[-1])
    trans = helper.create_parameter(attr=helper.param_attr,
                                    shape=[k + 2, k], dtype=dtype)
    alpha = helper.create_variable_for_type_inference(dtype)
    em_exps = helper.create_variable_for_type_inference(dtype)
    tr_exps = helper.create_variable_for_type_inference(dtype)
    ll = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "linear_chain_crf",
        inputs={"Emission": [input], "Transition": [trans],
                "Label": [label]},
        outputs={"Alpha": [alpha], "EmissionExps": [em_exps],
                 "TransitionExps": [tr_exps], "LogLikelihood": [ll]},
        infer_shape=False)
    ll.shape = (int(input.shape[0]) if len(input.shape) == 3 else 1, 1)
    return ll


def crf_decoding(input, param_attr, label=None, length=None):
    """Viterbi decode using the transition learned by
    linear_chain_crf (reference layers/nn.py crf_decoding)."""
    helper = LayerHelper("crf_decoding", input=input)
    # reuse the transition parameter created by linear_chain_crf
    from ..param_attr import ParamAttr

    name = param_attr.name if isinstance(param_attr, ParamAttr) else None
    blk = helper.main_program.global_block()
    trans = None
    if name:
        trans = blk._find_var_recursive(name)
    if trans is None:
        k = int(input.shape[-1])
        matches = [p for p in blk.all_parameters
                   if p.shape and len(p.shape) == 2
                   and p.shape[0] == k + 2 and p.shape[1] == k]
        # most recently created wins (the CRF layer built just before);
        # pass a NAMED param_attr to disambiguate multiple CRFs
        trans = matches[-1] if matches else None
    if trans is None:
        raise ValueError("crf_decoding: no transition parameter found; "
                         "run linear_chain_crf first or name the param")
    out = helper.create_variable_for_type_inference("int64")
    inputs = {"Emission": [input], "Transition": [trans]}
    if label is not None:
        inputs["Label"] = [label]
    helper.append_op("crf_decoding", inputs=inputs,
                     outputs={"ViterbiPath": [out]}, infer_shape=False)
    out.shape = tuple(input.shape[:-1])
    return out


__all__ += ["linear_chain_crf", "crf_decoding"]


def sequence_slice(input, offset, length, name=None):
    """(reference sequence_ops sequence_slice layer over the host op)."""
    helper = LayerHelper("sequence_slice", input=input)
    out = helper.create_variable_for_type_inference(input.dtype)
    out.lod_level = 1
    out.shape = (-1,) + tuple(input.shape[1:])
    helper.append_op("sequence_slice",
                     inputs={"X": [input], "Offset": [offset],
                             "Length": [length]},
                     outputs={"Out": [out]}, infer_shape=False)
    return out


def sequence_unpad(x, length, name=None):
    helper = LayerHelper("sequence_unpad", input=x)
    out = helper.create_variable_for_type_inference(x.dtype)
    out.lod_level = 1
    out.shape = (-1,) + tuple(x.shape[2:])
    helper.append_op("sequence_unpad",
                     inputs={"X": [x], "Length": [length]},
                     outputs={"Out": [out]}, infer_shape=False)
    return out


def im2sequence(input, filter_size=1, stride=1, padding=0,
                input_image_size=None, out_stride=1, name=None):
    if input_image_size is not None:
        raise NotImplementedError(
            "im2sequence with per-sample input_image_size is not "
            "supported yet; crop/pad to a uniform size upstream")

    def _pair(v):
        return list(v) if isinstance(v, (list, tuple)) else [v, v]

    helper = LayerHelper("im2sequence", input=input)
    out = helper.create_variable_for_type_inference(input.dtype)
    pads = _pair(padding)
    helper.append_op(
        "im2sequence", inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"kernels": _pair(filter_size), "strides": _pair(stride),
               "paddings": pads * 2 if len(pads) == 2 else pads,
               "out_stride": _pair(out_stride)},
        infer_shape=False)
    return out


def grid_sampler(x, grid, name=None):
    helper = LayerHelper("grid_sampler", input=x)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("grid_sampler",
                     inputs={"X": [x], "Grid": [grid]},
                     outputs={"Output": [out]}, infer_shape=False)
    out.shape = (int(x.shape[0]), int(x.shape[1]),
                 int(grid.shape[1]), int(grid.shape[2]))
    return out


def soft_relu(x, threshold=40.0, name=None):
    """log(1 + exp(min(x, threshold))) (reference soft_relu)."""
    from .nn import elementwise_max, elementwise_min
    from .ops import exp, log, scale
    from .tensor import fill_constant

    capped = elementwise_min(
        x, fill_constant([1], x.dtype, float(threshold)))
    capped = elementwise_max(
        capped, fill_constant([1], x.dtype, -float(threshold)))
    return log(scale(exp(capped), scale=1.0, bias=1.0))


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=True,
          print_phase="both"):
    """(reference layers/control_flow.py Print over the print host op)."""
    helper = LayerHelper("print", input=input)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "print", inputs={"In": [input]}, outputs={"Out": [out]},
        attrs={"first_n": first_n, "message": message or "",
               "summarize": summarize,
               "print_tensor_name": print_tensor_name,
               "print_tensor_type": print_tensor_type,
               "print_tensor_shape": print_tensor_shape,
               "print_tensor_lod": print_tensor_lod,
               "print_phase": print_phase.upper()},
        infer_shape=False)
    out.shape = tuple(input.shape or ())
    return out


def gather_tree(ids, parents):
    helper = LayerHelper("gather_tree", input=ids)
    out = helper.create_variable_for_type_inference(ids.dtype)
    helper.append_op("gather_tree",
                     inputs={"Ids": [ids], "Parents": [parents]},
                     outputs={"Out": [out]}, infer_shape=False)
    out.shape = tuple(ids.shape)
    return out


def random_crop(x, shape, seed=None):
    helper = LayerHelper("random_crop", input=x)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("random_crop", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"shape": [int(s) for s in shape],
                            "seed": int(seed or 0)},
                     infer_shape=False)
    out.shape = tuple(x.shape[:len(x.shape) - len(shape)]) + \
        tuple(int(s) for s in shape)
    return out


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    helper = LayerHelper("spectral_norm", input=weight)
    h = int(weight.shape[dim])
    w = 1
    for i, s in enumerate(weight.shape):
        if i != dim:
            w *= int(s)
    # random init (reference uses Normal(0,1)): a CONSTANT init would
    # zero out against weights orthogonal to the all-ones vector and
    # divide by sigma=0
    from ..initializer import NormalInitializer

    u = helper.main_program.global_block().create_var(
        name=framework.unique_name.generate("spectral_norm_u"),
        shape=(h,), dtype="float32", persistable=True)
    u.stop_gradient = True
    helper.set_variable_initializer(u, NormalInitializer(0.0, 1.0))
    v = helper.main_program.global_block().create_var(
        name=framework.unique_name.generate("spectral_norm_v"),
        shape=(w,), dtype="float32", persistable=True)
    v.stop_gradient = True
    helper.set_variable_initializer(v, NormalInitializer(0.0, 1.0))
    out = helper.create_variable_for_type_inference(weight.dtype)
    helper.append_op("spectral_norm",
                     inputs={"Weight": [weight], "U": [u], "V": [v]},
                     outputs={"Out": [out], "UOut": [u], "VOut": [v]},
                     attrs={"dim": dim, "power_iters": power_iters,
                            "eps": eps},
                     infer_shape=False)
    out.shape = tuple(weight.shape)
    return out


def data_norm(input, act=None, epsilon=1e-4, param_attr=None,
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=False):
    helper = LayerHelper("data_norm", input=input, act=act)
    d = int(input.shape[-1])
    # reference nn.py data_norm defaults (batch_size=1e4, batch_sum=0,
    # batch_square=1e4), overridable via a param_attr dict; the stats are
    # persistable and UPDATED BY THE GRAD OP each backward pass (see
    # ops/misc_ops.py _data_norm_grad_maker) — test-mode programs never
    # run backward, so stats stay frozen, matching the reference.
    size_default, sum_default, sq_default = 1e4, 0.0, 1e4
    if param_attr and isinstance(param_attr, dict):
        size_default = param_attr.get("batch_size", 1e4)
        sum_default = param_attr.get("batch_sum", 0.0)
        sq_default = param_attr.get("batch_square", 1e4)
    # trainable=True parameters like the reference (their presence on the
    # grad path is what triggers the stat-updating grad op; no optimizer
    # update ever applies to them because the grad op rebinds the vars
    # in-place instead of emitting @GRAD outputs)
    from ..initializer import ConstantInitializer
    from ..param_attr import ParamAttr

    def stat_param(tag, value):
        return helper.create_parameter(
            attr=ParamAttr(
                name=framework.unique_name.generate("dn_%s" % tag),
                initializer=ConstantInitializer(float(value))),
            shape=[d], dtype="float32")

    size = stat_param("size", size_default)
    ssum = stat_param("sum", sum_default)
    sqsum = stat_param("sqsum", sq_default)
    out = helper.create_variable_for_type_inference(input.dtype)
    means = helper.create_variable_for_type_inference("float32")
    scales = helper.create_variable_for_type_inference("float32")
    helper.append_op("data_norm",
                     inputs={"X": [input], "BatchSize": [size],
                             "BatchSum": [ssum],
                             "BatchSquareSum": [sqsum]},
                     outputs={"Y": [out], "Means": [means],
                              "Scales": [scales]},
                     attrs={"epsilon": epsilon}, infer_shape=False)
    out.shape = tuple(input.shape)
    return helper.append_activation(out)


def center_loss(input, label, num_classes, alpha, param_attr=None,
                update_center=True):
    from .tensor import fill_constant
    from ..initializer import ConstantInitializer
    from ..param_attr import ParamAttr

    helper = LayerHelper("center_loss", input=input)
    d = int(input.shape[-1])
    # reference loss.py center_loss: centers via create_parameter with
    # the caller's param_attr, zero-filled by default
    centers = helper.create_parameter(
        attr=param_attr if param_attr is not None else ParamAttr(
            name=framework.unique_name.generate("centers")),
        shape=[num_classes, d], dtype="float32",
        default_initializer=ConstantInitializer(0.0), stop_gradient=True)
    rate = alpha if isinstance(alpha, framework.Variable) else \
        fill_constant([1], "float32", float(alpha))
    diff = helper.create_variable_for_type_inference(input.dtype)
    loss = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "center_loss",
        inputs={"X": [input], "Label": [label], "Centers": [centers],
                "CenterUpdateRate": [rate]},
        outputs={"CentersOut": [centers], "SampleCenterDiff": [diff],
                 "Loss": [loss]},
        attrs={"cluster_num": num_classes,
               "need_update": update_center},
        infer_shape=False)
    loss.shape = (int(input.shape[0]), 1)
    return loss


def tensor_array_to_tensor(input, axis=0, name=None, use_stack=False,
                           dtype="float32"):
    """NOTE: the array's element shapes are runtime information, so the
    returned Variable has no static shape — set `out.shape` manually
    before feeding it to shape-inferring layers."""
    helper = LayerHelper("tensor_array_to_tensor", input=None)
    out = helper.main_program.current_block().create_var(
        name=framework.unique_name.generate("ta2t"), dtype=dtype)
    idx = helper.main_program.current_block().create_var(
        name=framework.unique_name.generate("ta2t_idx"), dtype="int32")
    helper.append_op("tensor_array_to_tensor",
                     inputs={"X": [input]},
                     outputs={"Out": [out], "OutIndex": [idx]},
                     attrs={"axis": axis, "use_stack": use_stack},
                     infer_shape=False)
    return out, idx


def adaptive_pool3d(input, pool_size, pool_type="max",
                    require_index=False, name=None):
    if require_index:
        raise NotImplementedError(
            "adaptive_pool3d(require_index=True) (mask output) is not "
            "supported yet")

    def _triple(v):
        return list(v) if isinstance(v, (list, tuple)) else [v, v, v]

    helper = LayerHelper("adaptive_pool3d", input=input)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "pool3d", inputs={"X": [input]}, outputs={"Out": [out]},
        attrs={"pooling_type": pool_type, "ksize": _triple(pool_size),
               "adaptive": True})
    return out


__all__ += ["sequence_slice", "sequence_unpad", "im2sequence",
            "grid_sampler", "soft_relu", "Print", "gather_tree",
            "random_crop", "spectral_norm", "data_norm", "center_loss",
            "tensor_array_to_tensor", "adaptive_pool3d"]


def flash_attention(q, k, v, causal=False, scale=0.0, lengths=None):
    """Fused attention over [B, H, S, D] (the multihead hot path —
    reference fused/multihead_matmul_op.cu). Lowers to the Pallas flash
    kernel on TPU; ``apply_sequence_parallel`` rewrites it to ring
    attention over an 'sp' mesh axis for long-context training.
    ``lengths`` ([B] int) masks padded keys inside the kernel."""
    helper = LayerHelper("flash_attention", input=q)
    out = helper.create_variable_for_type_inference(q.dtype)
    ins = {"Q": [q], "K": [k], "V": [v]}
    if lengths is not None:
        ins["Lengths"] = [lengths]
    helper.append_op(
        "flash_attention", inputs=ins,
        outputs={"Out": [out]},
        attrs={"causal": bool(causal), "scale": float(scale)})
    return out


def switch_moe(input, num_experts, hidden_dim, capacity_factor=1.0,
               num_groups=1, param_attr=None, name=None):
    """Switch-routed mixture-of-experts FFN over [T, D] tokens: top-1
    gating with fixed per-expert capacity (overflow dropped, GShard /
    Switch-Transformer semantics). The reference snapshot has no MoE;
    this is the Program surface that ``apply_expert_parallel`` shards
    over an 'ep' mesh axis (experts device-local, two all_to_alls route
    token slots — parallel/moe.py)."""
    helper = LayerHelper("moe", input=input, param_attr=param_attr,
                         name=name)
    dtype = helper.input_dtype()
    d = int(input.shape[-1])
    gate_w = helper.create_parameter(
        attr=helper.param_attr, shape=[d, num_experts], dtype=dtype)
    w_in = helper.create_parameter(
        attr=helper.param_attr, shape=[num_experts, d, hidden_dim],
        dtype=dtype)
    w_out = helper.create_parameter(
        attr=helper.param_attr, shape=[num_experts, hidden_dim, d],
        dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "moe",
        inputs={"X": [input], "GateW": [gate_w], "WIn": [w_in],
                "WOut": [w_out]},
        outputs={"Out": [out]},
        attrs={"shard_axis": "", "num_groups": int(num_groups),
               "capacity_factor": float(capacity_factor)})
    return out


__all__ += ["flash_attention", "switch_moe"]


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None, seq_length=None):
    """Chunk-detection precision/recall/F1 (reference layers/nn.py:866
    -> chunk_eval_op; the NER evaluation layer)."""
    helper = LayerHelper("chunk_eval", input=input)

    def mk(dtype):
        return helper.create_variable_for_type_inference(
            dtype, stop_gradient=True)

    precision, recall, f1 = mk("float32"), mk("float32"), mk("float32")
    n_infer, n_label, n_correct = mk("int64"), mk("int64"), mk("int64")
    inputs = {"Inference": [input], "Label": [label]}
    if seq_length is not None:
        inputs["SeqLength"] = [seq_length]
    helper.append_op(
        "chunk_eval", inputs=inputs,
        outputs={"Precision": [precision], "Recall": [recall],
                 "F1-Score": [f1], "NumInferChunks": [n_infer],
                 "NumLabelChunks": [n_label],
                 "NumCorrectChunks": [n_correct]},
        attrs={"num_chunk_types": int(num_chunk_types),
               "chunk_scheme": chunk_scheme,
               "excluded_chunk_types": list(excluded_chunk_types or [])},
        infer_shape=False)
    for v in (precision, recall, f1):
        v.shape, v.dtype = (1,), "float32"
    for v in (n_infer, n_label, n_correct):
        v.shape, v.dtype = (1,), "int64"
    return precision, recall, f1, n_infer, n_label, n_correct


__all__ += ["chunk_eval"]
