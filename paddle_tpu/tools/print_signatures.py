"""API-signature fingerprint dump.

Parity: /root/reference/tools/print_signatures.py — walks the public
API and prints ``module.name (args) -> hash`` lines so CI can diff the
frozen surface against an approved snapshot.

Usage: python -m paddle_tpu.tools.print_signatures [module ...]
"""
from __future__ import annotations

import hashlib
import importlib
import inspect
import sys

DEFAULT_MODULES = ["paddle_tpu", "paddle_tpu.layers",
                   "paddle_tpu.optimizer", "paddle_tpu.nn",
                   "paddle_tpu.io", "paddle_tpu.dygraph"]


def _signature_of(obj):
    try:
        return str(inspect.signature(obj))
    except (TypeError, ValueError):
        return "(..)"


def iter_api(module_name):
    mod = importlib.import_module(module_name)
    names = getattr(mod, "__all__", None) or [
        n for n in dir(mod) if not n.startswith("_")]
    for name in sorted(set(names)):
        obj = getattr(mod, name, None)
        if obj is None or inspect.ismodule(obj):
            continue
        if callable(obj):
            sig = _signature_of(obj)
            digest = hashlib.md5(
                ("%s.%s%s" % (module_name, name, sig)).encode()
            ).hexdigest()[:12]
            yield "%s.%s %s -> %s" % (module_name, name, sig, digest)


def main(argv=None):
    mods = (argv or sys.argv[1:]) or DEFAULT_MODULES
    for m in mods:
        for line in iter_api(m):
            print(line)


if __name__ == "__main__":
    main()
