"""Normalization + dropout ops.

Parity: /root/reference/paddle/fluid/operators/{batch_norm_op.cc,
layer_norm_op.cc, instance_norm_op.cc, group_norm_op.cc, dropout_op.cc,
lrn_op.cc}. batch_norm keeps the reference's five-output contract
(Y, MeanOut/VarianceOut in-place running stats, SavedMean/SavedVariance);
running-stat updates are data outputs rather than buffer mutation — the
executor rebinds them, which is the functional XLA-native way.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import RNG_SEED_ATTR, In, Out, register_op


@register_op(
    "batch_norm",
    inputs=[
        In("X"),
        In("Scale"),
        In("Bias"),
        In("Mean", no_grad=True),
        In("Variance", no_grad=True),
        In("MomentumTensor", dispensable=True, no_grad=True),
    ],
    outputs=[
        Out("Y"),
        Out("MeanOut", is_ref=True, no_grad=True),
        Out("VarianceOut", is_ref=True, no_grad=True),
        Out("SavedMean", no_grad=True),
        Out("SavedVariance", no_grad=True),
        # cuDNN-only scratch in the reference (dispensable there too);
        # the kernel returns None for it and inference-pruned programs
        # never bind it — surfaced by the ISSUE-12 verifier
        Out("ReserveSpace", dispensable=True, no_grad=True),
    ],
    attrs={
        "momentum": 0.9,
        "epsilon": 1e-5,
        "is_test": False,
        "data_layout": "NCHW",
        "use_global_stats": False,
        "trainable_statistics": False,
        "fuse_with_relu": False,
        "use_mkldnn": False,
    },
)
def _batch_norm(ins, attrs):
    x = ins["X"]
    scale, bias = ins["Scale"], ins["Bias"]
    mean, var = ins["Mean"], ins["Variance"]
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    layout = attrs.get("data_layout", "NCHW")
    use_global = attrs.get("is_test", False) or attrs.get("use_global_stats", False)

    c_axis = 1 if layout == "NCHW" else x.ndim - 1
    red_axes = tuple(i for i in range(x.ndim) if i != c_axis)
    bshape = [1] * x.ndim
    bshape[c_axis] = x.shape[c_axis]

    if use_global:
        use_mean, use_var = mean, var
        saved_mean = mean
        saved_inv_std = jax.lax.rsqrt(var + eps)
        mean_out, var_out = mean, var
    else:
        # sync-BN (reference sync_batch_norm_op.cu / sync_batch_norm_pass):
        # when marked and running inside a mapped mesh axis, batch
        # statistics average across the axis before normalization
        axis_name = None
        if attrs.get("_sync_stats"):
            from .collective_ops import axis_for_ring

            axis_name = axis_for_ring(attrs.get("_sync_ring_id", 0))
        if axis_name is not None:
            local_mean = jnp.mean(x, axis=red_axes)
            local_sq = jnp.mean(jnp.square(x), axis=red_axes)
            use_mean = jax.lax.pmean(local_mean, axis_name)
            use_var = jax.lax.pmean(local_sq, axis_name) -                 jnp.square(use_mean)
        else:
            use_mean = jnp.mean(x, axis=red_axes)
            use_var = jnp.mean(jnp.square(x - use_mean.reshape(bshape)),
                               axis=red_axes)
        saved_mean = use_mean
        saved_inv_std = jax.lax.rsqrt(use_var + eps)
        mean_out = mean * momentum + use_mean * (1 - momentum)
        var_out = var * momentum + use_var * (1 - momentum)

    inv_std = jax.lax.rsqrt(use_var + eps)
    y = (x - use_mean.reshape(bshape)) * (scale * inv_std).reshape(bshape) + bias.reshape(bshape)
    return {
        "Y": y,
        "MeanOut": mean_out,
        "VarianceOut": var_out,
        "SavedMean": saved_mean,
        "SavedVariance": saved_inv_std,  # reference saves inverse std
        "ReserveSpace": None,
    }


@register_op(
    "layer_norm",
    inputs=[In("X"), In("Scale", dispensable=True), In("Bias", dispensable=True)],
    outputs=[Out("Y"), Out("Mean", no_grad=True), Out("Variance", no_grad=True)],
    attrs={"epsilon": 1e-5, "begin_norm_axis": 1},
)
def _layer_norm(ins, attrs):
    x = ins["X"]
    eps = attrs.get("epsilon", 1e-5)
    axis = attrs.get("begin_norm_axis", 1)
    red = tuple(range(axis, x.ndim))
    mean = jnp.mean(x, axis=red, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=red, keepdims=True)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    if ins.get("Scale") is not None:
        y = y * ins["Scale"].reshape((1,) * axis + x.shape[axis:])
    if ins.get("Bias") is not None:
        y = y + ins["Bias"].reshape((1,) * axis + x.shape[axis:])
    lead = 1
    for d in x.shape[:axis]:
        lead *= d
    return {
        "Y": y,
        "Mean": mean.reshape(lead),
        "Variance": var.reshape(lead),
    }


@register_op(
    "instance_norm",
    inputs=[In("X"), In("Scale", dispensable=True), In("Bias", dispensable=True)],
    outputs=[Out("Y"), Out("SavedMean", no_grad=True),
             Out("SavedVariance", no_grad=True)],
    attrs={"epsilon": 1e-5},
)
def _instance_norm(ins, attrs):
    x = ins["X"]
    eps = attrs.get("epsilon", 1e-5)
    red = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=red, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=red, keepdims=True)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    bshape = (1, -1) + (1,) * (x.ndim - 2)
    if ins.get("Scale") is not None:
        y = y * ins["Scale"].reshape(bshape)
    if ins.get("Bias") is not None:
        y = y + ins["Bias"].reshape(bshape)
    n, c = x.shape[0], x.shape[1]
    return {
        "Y": y,
        "SavedMean": mean.reshape(n * c),
        "SavedVariance": jax.lax.rsqrt(var + eps).reshape(n * c),
    }


@register_op(
    "group_norm",
    inputs=[In("X"), In("Scale", dispensable=True), In("Bias", dispensable=True)],
    outputs=[Out("Y"), Out("Mean", no_grad=True), Out("Variance", no_grad=True)],
    attrs={"epsilon": 1e-5, "groups": 1, "data_layout": "NCHW"},
)
def _group_norm(ins, attrs):
    x = ins["X"]
    g = attrs.get("groups", 1)
    eps = attrs.get("epsilon", 1e-5)
    n, c = x.shape[0], x.shape[1]
    xg = x.reshape((n, g, c // g) + x.shape[2:])
    red = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=red, keepdims=True)
    var = jnp.mean(jnp.square(xg - mean), axis=red, keepdims=True)
    y = ((xg - mean) * jax.lax.rsqrt(var + eps)).reshape(x.shape)
    bshape = (1, c) + (1,) * (x.ndim - 2)
    if ins.get("Scale") is not None:
        y = y * ins["Scale"].reshape(bshape)
    if ins.get("Bias") is not None:
        y = y + ins["Bias"].reshape(bshape)
    return {"Y": y, "Mean": mean.reshape(n, g), "Variance": var.reshape(n, g)}


@register_op(
    "dropout",
    inputs=[In("X"), In("Seed", dispensable=True, no_grad=True)],
    outputs=[Out("Out"), Out("Mask", no_grad=True)],
    attrs={
        "dropout_prob": 0.5,
        "is_test": False,
        "fix_seed": False,
        "seed": 0,
        "dropout_implementation": "downgrade_in_infer",
    },
    needs_rng=True,
)
def _dropout(ins, attrs):
    x = ins["X"]
    p = attrs.get("dropout_prob", 0.5)
    impl = attrs.get("dropout_implementation", "downgrade_in_infer")
    if attrs.get("is_test", False):
        out = x if impl == "upscale_in_train" else x * (1.0 - p)
        return {"Out": out, "Mask": None}
    key = jax.random.PRNGKey(ins[RNG_SEED_ATTR])
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
    mask = keep.astype(x.dtype)
    if impl == "upscale_in_train":
        out = jnp.where(p >= 1.0, jnp.zeros_like(x), x * mask / (1.0 - p))
    else:
        out = x * mask
    return {"Out": out, "Mask": mask}


@register_op(
    "lrn",
    inputs=[In("X")],
    outputs=[Out("Out"), Out("MidOut", no_grad=True)],
    attrs={"n": 5, "alpha": 1e-4, "beta": 0.75, "k": 1.0, "data_format": "NCHW"},
)
def _lrn(ins, attrs):
    x = ins["X"]
    n = attrs.get("n", 5)
    alpha, beta, k = attrs.get("alpha", 1e-4), attrs.get("beta", 0.75), attrs.get("k", 1.0)
    half = n // 2
    sq = jnp.square(x)
    padded = jnp.pad(sq, [(0, 0), (half, half), (0, 0), (0, 0)])
    mid = k + alpha * sum(
        padded[:, i : i + x.shape[1]] for i in range(n)
    )
    return {"Out": x / jnp.power(mid, beta), "MidOut": mid}


@register_op(
    "l2_normalize",
    inputs=[In("X")],
    outputs=[Out("Out")],
    attrs={"axis": -1, "epsilon": 1e-10},
)
def _l2_normalize(ins, attrs):
    x = ins["X"]
    sq = jnp.sum(jnp.square(x), axis=attrs.get("axis", -1), keepdims=True)
    return {"Out": x * jax.lax.rsqrt(jnp.maximum(sq, attrs.get("epsilon", 1e-10)))}
