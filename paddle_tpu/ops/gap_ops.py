"""Round-3 op-gap wave: deformable conv, precise ROI pooling, 3-D
max-pool-with-index, int8 (de/re)quantize, py_func, and the LoD
rank-table op family that backs dynamic RNNs.

Parity targets (/root/reference/paddle/fluid/operators/):
deformable_conv_op.cc (+_v1), prroi_pool_op.cc/.h, pool_with_index_op.cc
(3-D), quantize_op.cc / dequantize_op.cc / requantize_op.cc,
py_func_op.cc, lod_rank_table_op.cc, lod_tensor_to_array_op.cc,
array_to_lod_tensor_op.cc, shrink_rnn_memory_op.cc,
max_sequence_len_op.cc.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import In, Out, register_host_op, register_op

# ---------------------------------------------------------------------------
# deformable convolution (v2 with modulation mask; v1 without)
# ---------------------------------------------------------------------------


def _dcn_sample(x, off, mask, kh, kw, strides, pads, dils, dg):
    """Sample input taps at offset positions with bilinear interpolation.

    Layout (deformable_conv_op.cu:88-111): Offset is [N, dg*2*kh*kw,
    Ho, Wo] — per deformable group, (y, x) interleaved per tap; Mask is
    [N, dg*kh*kw, Ho, Wo]. Returns [N, Cin, kh, kw, Ho, Wo].
    """
    n, cin, h, w = x.shape
    ho, wo = off.shape[2], off.shape[3]
    sh, sw = strides
    ph, pw = pads
    dh, dw = dils
    cpg = cin // dg

    off = off.reshape(n, dg, kh, kw, 2, ho, wo)
    off_y, off_x = off[:, :, :, :, 0], off[:, :, :, :, 1]  # [N,dg,kh,kw,Ho,Wo]
    base_y = (jnp.arange(ho) * sh - ph)[:, None] + jnp.zeros((ho, wo))
    base_x = (jnp.arange(wo) * sw - pw)[None, :] + jnp.zeros((ho, wo))
    tap_y = (jnp.arange(kh) * dh)[:, None, None, None]
    tap_x = (jnp.arange(kw) * dw)[None, :, None, None]
    py = base_y[None, None, None, None] + tap_y[None, None] + off_y
    px = base_x[None, None, None, None] + tap_x[None, None] + off_x

    y0 = jnp.floor(py)
    x0 = jnp.floor(px)
    wy1 = py - y0
    wx1 = px - x0

    xr = x.reshape(n, dg, cpg, h * w)

    def corner(yc, xc):
        valid = ((yc >= 0) & (yc < h) & (xc >= 0) & (xc < w))
        idx = (jnp.clip(yc, 0, h - 1) * w
               + jnp.clip(xc, 0, w - 1)).astype(jnp.int32)
        flat = idx.reshape(n, dg, -1)
        g = jnp.take_along_axis(xr, flat[:, :, None, :], axis=3)
        g = g.reshape(n, dg, cpg, kh, kw, ho, wo)
        return g * valid[:, :, None].astype(x.dtype)

    v00 = corner(y0, x0)
    v01 = corner(y0, x0 + 1)
    v10 = corner(y0 + 1, x0)
    v11 = corner(y0 + 1, x0 + 1)
    wy1e = wy1[:, :, None]
    wx1e = wx1[:, :, None]
    sampled = (v00 * (1 - wy1e) * (1 - wx1e) + v01 * (1 - wy1e) * wx1e
               + v10 * wy1e * (1 - wx1e) + v11 * wy1e * wx1e)
    if mask is not None:
        sampled = sampled * mask.reshape(
            n, dg, 1, kh, kw, ho, wo).astype(x.dtype)
    return sampled.reshape(n, cin, kh, kw, ho, wo)


def _deformable_conv_impl(ins, attrs, with_mask):
    x, offset, filt = ins["Input"], ins["Offset"], ins["Filter"]
    mask = ins.get("Mask") if with_mask else None
    groups = int(attrs.get("groups", 1))
    dg = int(attrs.get("deformable_groups", 1))
    cout, cpg_f, kh, kw = filt.shape
    sampled = _dcn_sample(
        x, offset, mask, kh, kw,
        [int(s) for s in attrs.get("strides", [1, 1])],
        [int(p) for p in attrs.get("paddings", [0, 0])],
        [int(d) for d in attrs.get("dilations", [1, 1])], dg)
    n, cin = x.shape[:2]
    ho, wo = sampled.shape[-2:]
    sg = sampled.reshape(n, groups, cin // groups, kh, kw, ho, wo)
    fg = filt.reshape(groups, cout // groups, cpg_f, kh, kw)
    out = jnp.einsum("ngcijhw,gocij->ngohw", sg, fg)
    return {"Output": out.reshape(n, cout, ho, wo)}


@register_op(
    "deformable_conv",
    inputs=[In("Input"), In("Offset"), In("Mask"), In("Filter")],
    outputs=[Out("Output")],
    attrs={"strides": [1, 1], "paddings": [0, 0], "dilations": [1, 1],
           "groups": 1, "deformable_groups": 1, "im2col_step": 64},
)
def _deformable_conv(ins, attrs):
    return _deformable_conv_impl(ins, attrs, with_mask=True)


@register_op(
    "deformable_conv_v1",
    inputs=[In("Input"), In("Offset"), In("Filter")],
    outputs=[Out("Output")],
    attrs={"strides": [1, 1], "paddings": [0, 0], "dilations": [1, 1],
           "groups": 1, "deformable_groups": 1, "im2col_step": 64},
)
def _deformable_conv_v1(ins, attrs):
    return _deformable_conv_impl(ins, attrs, with_mask=False)


# ---------------------------------------------------------------------------
# precise ROI pooling (PrRoIPool) — exact integral of the bilinear
# surface over each bin (prroi_pool_op.cu:68-95 window math)
# ---------------------------------------------------------------------------


@register_op(
    "prroi_pool",
    inputs=[In("X"), In("ROIs", no_grad=True),
            In("BatchRoINums", dispensable=True, no_grad=True)],
    outputs=[Out("Out")],
    attrs={"spatial_scale": 1.0, "pooled_height": 1, "pooled_width": 1},
    needs_lod=True,
)
def _prroi_pool(ins, attrs):
    x, rois = ins["X"], ins["ROIs"]
    scale = float(attrs.get("spatial_scale", 1.0))
    ph_n = int(attrs.get("pooled_height", 1))
    pw_n = int(attrs.get("pooled_width", 1))
    n, c, h, w = x.shape
    nroi = rois.shape[0]
    # batch assignment: ROI LoD when present (single source of truth:
    # lod_utils.batch_ids_for, as roi_align/roi_pool use), else a dense
    # BatchRoINums tensor (reference prroi_pool non-LoD API), else
    # image 0
    from .lod_utils import batch_ids_for, lod_offsets

    brn = ins.get("BatchRoINums")
    if lod_offsets(attrs, "ROIs") is not None:
        batch_ids = batch_ids_for(attrs, "ROIs", nroi)
    elif brn is not None:
        bounds = jnp.cumsum(brn.astype(jnp.int32))
        batch_ids = jnp.searchsorted(bounds, jnp.arange(nroi),
                                     side="right").astype(jnp.int32)
    else:
        batch_ids = jnp.zeros((nroi,), jnp.int32)

    sw = rois[:, 0] * scale
    sh = rois[:, 1] * scale
    ew = rois[:, 2] * scale
    eh = rois[:, 3] * scale
    roi_w = jnp.maximum(ew - sw, 0.0)
    roi_h = jnp.maximum(eh - sh, 0.0)
    bin_w = roi_w / pw_n
    bin_h = roi_h / ph_n

    # per-bin windows [R, ph, pw]
    wy0 = sh[:, None, None] + bin_h[:, None, None] * \
        jnp.arange(ph_n)[None, :, None]
    wx0 = sw[:, None, None] + bin_w[:, None, None] * \
        jnp.arange(pw_n)[None, None, :]
    wy1 = wy0 + bin_h[:, None, None]
    wx1 = wx0 + bin_w[:, None, None]

    # integral weights per grid line: cell [i, i+1] contributes
    # A0 = ∫(1-u)du and A1 = ∫u du over u ∈ [clip(y0-i), clip(y1-i)].
    # Cells run from -1 to size-1: the reference zero-pads DATA outside
    # the image but still integrates boundary cells, so cell [-1, 0]
    # contributes its ∫u weight to grid line 0 (windows past the
    # top/left border are not clipped by PrRoIPool).
    def line_weights(a0, a1, size):
        i = jnp.arange(-1, size)[None, None, None, :]
        u0 = jnp.clip(a0[..., None] - i, 0.0, 1.0)
        u1 = jnp.clip(a1[..., None] - i, 0.0, 1.0)
        w1 = 0.5 * (u1 * u1 - u0 * u0)     # ∫ u
        w0 = (u1 - u0) - w1                # ∫ (1-u)
        return w0, w1

    ay0, ay1 = line_weights(wy0, wy1, h)   # [R, ph, pw, H+1] cells
    bx0, bx1 = line_weights(wx0, wx1, w)   # [R, ph, pw, W+1] cells
    # grid value j collects A0 from cell j (index j+1 in the padded
    # cell axis) and A1 from cell j-1 (index j)
    ay = ay0[..., 1:] + ay1[..., :-1]
    bx = bx0[..., 1:] + bx1[..., :-1]

    xg = x[batch_ids]                      # [R, C, H, W]
    integral = jnp.einsum("rchw,rpqh,rpqw->rcpq", xg, ay, bx)
    area = jnp.maximum(bin_w * bin_h, 0.0)[:, None, None, None]
    out = jnp.where(area > 0, integral / jnp.maximum(area, 1e-12), 0.0)
    return {"Out": out.astype(x.dtype)}


# ---------------------------------------------------------------------------
# max_pool3d_with_index (pool_with_index_op.cc, NCDHW)
# ---------------------------------------------------------------------------


@register_op("max_pool3d_with_index", inputs=[In("X")],
             outputs=[Out("Out"), Out("Mask", no_grad=True)],
             attrs={"ksize": [1, 1, 1], "strides": [1, 1, 1],
                    "paddings": [0, 0, 0], "global_pooling": False,
                    "adaptive": False})
def _max_pool3d_with_index(ins, attrs):
    x = ins["X"]
    n, c, d, h, w = x.shape
    kd, kh, kw = attrs["ksize"]
    sd, sh, sw = attrs.get("strides", [1, 1, 1])
    pd, ph, pw = attrs.get("paddings", [0, 0, 0])
    if attrs.get("global_pooling"):
        kd, kh, kw, pd, ph, pw = d, h, w, 0, 0, 0
    if attrs.get("adaptive"):
        return _adaptive_max_pool3d_with_index(x, kd, kh, kw)
    neg = jnp.finfo(x.dtype).min
    xp = jnp.pad(x, ((0, 0), (0, 0), (pd, pd), (ph, ph), (pw, pw)),
                 constant_values=neg)
    dp, hp, wp = xp.shape[2:]
    flat_idx = jnp.arange(dp * hp * wp).reshape(dp, hp, wp)
    od = (d + 2 * pd - kd) // sd + 1
    oh = (h + 2 * ph - kh) // sh + 1
    ow = (w + 2 * pw - kw) // sw + 1
    outs, idxs = [], []
    for a in range(kd):
        for i in range(kh):
            for j in range(kw):
                outs.append(xp[:, :, a:a + od * sd:sd, i:i + oh * sh:sh,
                               j:j + ow * sw:sw])
                idxs.append(jnp.broadcast_to(
                    flat_idx[a:a + od * sd:sd, i:i + oh * sh:sh,
                             j:j + ow * sw:sw], (n, c, od, oh, ow)))
    stack = jnp.stack(outs, axis=0)
    which = jnp.argmax(stack, axis=0)
    out = jnp.max(stack, axis=0)
    picked = jnp.take_along_axis(jnp.stack(idxs, axis=0), which[None],
                                 axis=0)[0]
    prow = picked // (hp * wp) - pd
    rem = picked % (hp * wp)
    pr = rem // wp - ph
    pc = rem % wp - pw
    mask = (prow * h + pr) * w + pc
    return {"Out": out, "Mask": mask.astype(jnp.int32)}


def _adaptive_max_pool3d_with_index(x, od, oh, ow):
    n, c, d, h, w = x.shape
    out = jnp.zeros((n, c, od, oh, ow), x.dtype)
    mask = jnp.zeros((n, c, od, oh, ow), jnp.int32)
    for a in range(od):
        d0, d1 = (a * d) // od, -(-((a + 1) * d) // od)
        for i in range(oh):
            h0, h1 = (i * h) // oh, -(-((i + 1) * h) // oh)
            for j in range(ow):
                w0, w1 = (j * w) // ow, -(-((j + 1) * w) // ow)
                win = x[:, :, d0:d1, h0:h1, w0:w1].reshape(n, c, -1)
                am = jnp.argmax(win, axis=2)
                dd = (d1 - d0)
                hh = (h1 - h0)
                ww = (w1 - w0)
                az = am // (hh * ww) + d0
                rr = am % (hh * ww)
                ai = rr // ww + h0
                aj = rr % ww + w0
                flat = (az * h + ai) * w + aj
                out = out.at[:, :, a, i, j].set(jnp.max(win, axis=2))
                mask = mask.at[:, :, a, i, j].set(flat.astype(jnp.int32))
    return {"Out": out, "Mask": mask}


# ---------------------------------------------------------------------------
# int8 quantize / dequantize / requantize (quantize_op.cc family)
# ---------------------------------------------------------------------------


@register_op("quantize", inputs=[In("Input", no_grad=True)],
             outputs=[Out("Output")],
             attrs={"Scale": 1.0, "is_negative_input": False,
                    "output_format": "NCHW"}, grad=None)
def _quantize(ins, attrs):
    """Out = round(X * Scale) saturated to int8 (signed) or uint8."""
    x = ins["Input"]
    s = float(attrs.get("Scale", 1.0))
    q = jnp.round(x * s)
    if attrs.get("is_negative_input", False):
        return {"Output": jnp.clip(q, -128, 127).astype(jnp.int8)}
    return {"Output": jnp.clip(q, 0, 255).astype(jnp.uint8)}


@register_op("dequantize", inputs=[In("Input", no_grad=True)],
             outputs=[Out("Output")],
             attrs={"Scale": 1.0}, grad=None)
def _dequantize(ins, attrs):
    s = float(attrs.get("Scale", 1.0))
    return {"Output": ins["Input"].astype(jnp.float32) / s}


@register_op("requantize", inputs=[In("Input", no_grad=True)],
             outputs=[Out("Output")],
             attrs={"Scale_in": 1.0, "Scale_out": 1.0}, grad=None)
def _requantize(ins, attrs):
    s_in = float(attrs.get("Scale_in", 1.0))
    s_out = float(attrs.get("Scale_out", 1.0))
    x = ins["Input"].astype(jnp.float32)
    q = jnp.round(x * (s_out / s_in))
    return {"Output": jnp.clip(q, -128, 127).astype(jnp.int8)}


# ---------------------------------------------------------------------------
# py_func (py_func_op.cc): user python callables as graph ops
# ---------------------------------------------------------------------------

_PY_FUNC_REGISTRY = []


def register_py_func(fn) -> int:
    _PY_FUNC_REGISTRY.append(fn)
    return len(_PY_FUNC_REGISTRY) - 1


def _py_func_grad_maker(block, op, pending, finalize):
    """Emit a backward py_func op when a backward callable was
    registered (py_func_op.cc grad maker): the backward fn receives
    (forward inputs..., forward outputs..., out grads...) minus any
    backward_skip_vars, and returns one grad per (unskipped) forward
    input (None allowed → zero grad)."""
    bwd_id = int(op.attrs.get("backward_callable_id", -1))
    if bwd_id < 0:
        return
    ogs = []
    for n in op.output("Out"):
        g = finalize(n)
        ogs.append(g if g is not None else "@EMPTY@")
    if all(g == "@EMPTY@" for g in ogs):
        return
    from .control_flow_ops import _bind_partial_grad

    # backward INPUTS drop skipped vars; backward OUTPUTS cover every
    # forward input — "Backward IG cannot be skipped"
    # (py_func_op.cc:239-247), the callable returns one grad per
    # forward input in order (None allowed)
    skip = set(op.attrs.get("backward_skip_vars") or [])
    grad_for = list(op.input("X"))
    gnames = [_bind_partial_grad(block, pending, n) for n in grad_for]
    bwd_x = ([n for n in op.input("X") if n not in skip]
             + [n for n in op.output("Out") if n not in skip] + ogs)
    block.append_op(
        "py_func",
        {"X": bwd_x},
        {"Out": gnames},
        {"forward_callable_id": bwd_id, "backward_callable_id": -1,
         "_grad_for": grad_for},
        infer_shape=False)


@register_host_op(
    "py_func",
    inputs=[In("X", duplicable=True, no_grad=True)],
    outputs=[Out("Out", duplicable=True)],
    attrs={"forward_callable_id": -1, "backward_callable_id": -1,
           "backward_skip_vars": []},
    grad=_py_func_grad_maker,
)
def _py_func(executor, op, scope):
    fn = _PY_FUNC_REGISTRY[int(op.attrs["forward_callable_id"])]
    args = []
    for n in op.input("X"):
        v = executor._read_var(scope, n)
        args.append(None if v is None else np.asarray(v))
    outs = fn(*args)
    if outs is None:
        outs = ()
    if not isinstance(outs, (tuple, list)):
        outs = (outs,)
    names = op.output("Out")
    grad_for = op.attrs.get("_grad_for")  # set only on backward ops
    if len(outs) > len(names):
        raise ValueError(
            "py_func callable produced %d outputs but the op declares "
            "%d (py_func_op.cc enforces the output arity)"
            % (len(outs), len(names)))
    outs = list(outs) + [None] * (len(names) - len(outs))
    for i, (name, val) in enumerate(zip(names, outs)):
        if val is None:
            if grad_for is not None:
                # backward callable returned None for this input: zero
                # grad, shaped like the forward var (its grad slot was
                # already bound into the pending sum)
                ref = executor._read_var(scope, grad_for[i])
                val = np.zeros_like(np.asarray(ref))
            else:
                raise ValueError(
                    "py_func forward callable produced %d output(s) "
                    "but the op declares %d (py_func_op.cc enforces "
                    "the output arity)" % (len([o for o in outs
                                                if o is not None]),
                                           len(names)))
        executor._write_var(scope, name, np.asarray(val))


# ---------------------------------------------------------------------------
# LoD rank-table family (dynamic_rnn substrate)
# ---------------------------------------------------------------------------


class LoDRankTable:
    """(index, length) items sorted by length desc, stable
    (lod_rank_table.h): the execution order for time-major RNN steps.
    ``level`` records which LoD level the lengths came from —
    lod_tensor_to_array slices sub-sequences at level+1 against it
    (lod_tensor_to_array_op.cc:107)."""

    def __init__(self, items, level: int = 0):
        self.items = list(items)  # [(original_seq_idx, seq_len), ...]
        self.level = int(level)

    def active_at(self, t: int) -> int:
        return sum(1 for _, ln in self.items if ln > t)

    def max_len(self) -> int:
        return self.items[0][1] if self.items else 0


def _seq_lengths_from_lod(lod, level):
    offsets = lod[level]
    return [offsets[i + 1] - offsets[i] for i in range(len(offsets) - 1)]


@register_host_op("lod_rank_table", inputs=[In("X", no_grad=True)],
                  outputs=[Out("Out")], attrs={"level": 0})
def _lod_rank_table(executor, op, scope):
    var = scope.find_var(op.input("X")[0])
    t = var.raw()
    lod = t.lod()
    level = int(op.attrs.get("level", 0))
    if not lod:
        n = t.array.shape[0]
        lengths = [1] * n
    else:
        lengths = _seq_lengths_from_lod(lod, level)
    items = sorted(enumerate(lengths), key=lambda kv: -kv[1])
    scope.var(op.output("Out")[0]).set(LoDRankTable(items, level))


@register_host_op("max_sequence_len",
                  inputs=[In("RankTable", no_grad=True)],
                  outputs=[Out("Out")])
def _max_sequence_len(executor, op, scope):
    table = scope.find_var(op.input("RankTable")[0]).raw()
    executor._write_var(scope, op.output("Out")[0],
                        np.asarray([table.max_len()], dtype="int64"))


def _lod_tensor_to_array_grad_maker(block, op, pending, finalize):
    """Adjoint pair: d(lod_tensor_to_array)/dX = array_to_lod_tensor of
    the out-grad array with the same rank table (and vice versa)."""
    g_out = finalize(op.output("Out")[0])
    if g_out is None:
        return
    from .control_flow_ops import _bind_partial_grad

    gname = _bind_partial_grad(block, pending, op.input("X")[0])
    block.append_op(
        "array_to_lod_tensor",
        {"X": [g_out], "RankTable": [op.input("RankTable")[0]]},
        {"Out": [gname]}, {}, infer_shape=False)


def _array_to_lod_tensor_grad_maker(block, op, pending, finalize):
    g_out = finalize(op.output("Out")[0])
    if g_out is None:
        return
    from .control_flow_ops import _bind_partial_grad

    gname = _bind_partial_grad(block, pending, op.input("X")[0])
    block.append_op(
        "lod_tensor_to_array",
        {"X": [g_out], "RankTable": [op.input("RankTable")[0]]},
        {"Out": [gname]}, {}, infer_shape=False)



@register_host_op("lod_tensor_to_array",
                  inputs=[In("X"), In("RankTable", no_grad=True)],
                  outputs=[Out("Out")],
                  grad=_lod_tensor_to_array_grad_maker)
def _lod_tensor_to_array(executor, op, scope):
    """Split X into a time-major TensorArray by the rank table
    (lod_tensor_to_array_op.cc): array[t] stacks row t of every
    sequence still active at step t, in rank order."""
    from ..core.tensor import LoDTensor, LoDTensorArray

    xvar = scope.find_var(op.input("X")[0]).raw()
    table = scope.find_var(op.input("RankTable")[0]).raw()
    x = np.asarray(xvar.array)
    lod = xvar.lod()
    level = getattr(table, "level", 0)
    offsets = (lod[level] if lod
               else list(range(x.shape[0] + 1)))
    # with a deeper LoD level, each step item is a whole sub-sequence
    # (lod_tensor_to_array_op.cc:124 copies [start, start+1) at
    # rank_level+1); with a flat LoD it is one row
    deeper = lod[level + 1] if lod and len(lod) > level + 1 else None
    arr = LoDTensorArray()
    for t in range(table.max_len()):
        row_idx = []
        sub_lens = []
        for idx, ln in table.items:
            if ln <= t:
                continue
            s = offsets[idx] + t
            r0, r1 = (deeper[s], deeper[s + 1]) if deeper is not None \
                else (s, s + 1)
            row_idx.extend(range(r0, r1))
            sub_lens.append(r1 - r0)
        step = LoDTensor()
        step.set(jnp.asarray(x[np.asarray(row_idx, dtype=np.int64)]))
        if deeper is not None:
            offs = [0]
            for ln in sub_lens:
                offs.append(offs[-1] + ln)
            step._lod = [offs]
        arr.append(step)
    scope.var(op.output("Out")[0]).set(arr)


@register_host_op("array_to_lod_tensor",
                  inputs=[In("X"), In("RankTable", no_grad=True)],
                  outputs=[Out("Out")],
                  grad=_array_to_lod_tensor_grad_maker)
def _array_to_lod_tensor(executor, op, scope):
    """Inverse of lod_tensor_to_array: reassemble original sequence
    order + LoD (array_to_lod_tensor_op.cc)."""
    from ..core.tensor import LoDTensor

    arr = scope.find_var(op.input("X")[0]).raw()
    table = scope.find_var(op.input("RankTable")[0]).raw()
    steps = [np.asarray(t.array) for t in arr]
    step_lods = [t.lod() for t in arr]
    n_seq = len(table.items)
    lengths_by_orig = {idx: ln for idx, ln in table.items}
    rank_of = {idx: r for r, (idx, _) in enumerate(table.items)}
    if steps:
        feature_shape = steps[0].shape[1:]
        dtype = steps[0].dtype
    else:
        feature_shape, dtype = (0,), np.float32
    has_sub = any(sl for sl in step_lods)
    seqs = []
    for orig in range(n_seq):
        ln = lengths_by_orig[orig]
        r = rank_of[orig]
        rows = []
        for t in range(ln):
            # rank r is always within step t's active prefix: ranks are
            # length-sorted, so ln > t implies every rank <= r is live
            if has_sub and step_lods[t]:
                offs = step_lods[t][0]
                rows.append(steps[t][offs[r]:offs[r + 1]])
            else:
                rows.append(steps[t][r:r + 1])
        seqs.append(np.concatenate(rows) if rows
                    else np.zeros((0,) + feature_shape, dtype))
    full = (np.concatenate(seqs) if seqs
            else np.zeros((0,) + feature_shape, dtype))
    out = LoDTensor()
    out.set(jnp.asarray(full))
    offs = [0]
    for orig in range(n_seq):
        offs.append(offs[-1] + lengths_by_orig[orig])
    if has_sub:
        # 2-level reconstruction: level-0 counts sub-sequences, level-1
        # holds each sub-sequence's row offsets in original order
        sub_offs = [0]
        for orig in range(n_seq):
            ln = lengths_by_orig[orig]
            r = rank_of[orig]
            for t in range(ln):
                o = step_lods[t][0]
                sub_offs.append(sub_offs[-1] + (o[r + 1] - o[r]))
        out._lod = [offs, sub_offs]
    else:
        out._lod = [offs]
    scope.var(op.output("Out")[0]).set(out)


@register_host_op("shrink_rnn_memory",
                  inputs=[In("X"), In("RankTable", no_grad=True),
                          In("I", no_grad=True)],
                  outputs=[Out("Out")])
def _shrink_rnn_memory(executor, op, scope):
    """Keep the first k rows of X where k = #sequences active at step I
    (shrink_rnn_memory_op.cc); the grad pads dropped rows with zeros."""
    x = executor._read_var(scope, op.input("X")[0])
    table = scope.find_var(op.input("RankTable")[0]).raw()
    i = int(np.asarray(
        executor._read_var(scope, op.input("I")[0])).ravel()[0])
    k = table.active_at(i)
    executor._write_var(scope, op.output("Out")[0], x[:k])


@register_host_op("reorder_lod_tensor_by_rank",
                  inputs=[In("X"), In("RankTable", no_grad=True)],
                  outputs=[Out("Out")])
def _reorder_lod_tensor_by_rank(executor, op, scope):
    """Reorder X's sequences into rank-table order (reorder_lod_tensor_
    by_rank_op.cc) — DynamicRNN's static_input / memory(init=) uses it
    so row r always belongs to the rank-r sequence."""
    from ..core.tensor import LoDTensor

    xvar = scope.find_var(op.input("X")[0]).raw()
    table = scope.find_var(op.input("RankTable")[0]).raw()
    x = np.asarray(xvar.array)
    lod = xvar.lod()
    offsets = lod[0] if lod else list(range(x.shape[0] + 1))
    rows = []
    new_offs = [0]
    for idx, _ in table.items:
        seg = range(offsets[idx], offsets[idx + 1])
        rows.extend(seg)
        new_offs.append(new_offs[-1] + len(seg))
    out = LoDTensor()
    out.set(jnp.asarray(x[np.asarray(rows, dtype=np.int64)]))
    if lod:
        out._lod = [new_offs]
    scope.var(op.output("Out")[0]).set(out)


@register_host_op("rank_table_boot_memory",
                  inputs=[In("RankTable", no_grad=True)],
                  outputs=[Out("Out")],
                  attrs={"shape": [], "value": 0.0, "dtype": 5})
def _rank_table_boot_memory(executor, op, scope):
    """Initial RNN memory: [n_sequences, *shape] filled with value —
    the boot the reference DynamicRNN.memory() builds from the rank
    table's batch size."""
    from ..core import dtypes as _dt

    table = scope.find_var(op.input("RankTable")[0]).raw()
    shape = [len(table.items)] + [int(s) for s in
                                  op.attrs.get("shape", [])]
    executor._write_var(
        scope, op.output("Out")[0],
        np.full(shape, float(op.attrs.get("value", 0.0)),
                _dt.to_numpy_dtype(op.attrs.get("dtype", 5))))


@register_host_op("shrink_rnn_memory_grad",
                  inputs=[In("X", no_grad=True),
                          In("Out@GRAD", no_grad=True)],
                  outputs=[Out("X@GRAD")])
def _shrink_rnn_memory_grad(executor, op, scope):
    """Zero-pad the shrunk grad back to X's row count
    (shrink_rnn_memory_op.cc grad: dropped rows get zero grad)."""
    x = executor._read_var(scope, op.input("X")[0])
    og = executor._read_var(scope, op.input("Out@GRAD")[0])
    g = jnp.zeros_like(x).at[:og.shape[0]].set(og)
    executor._write_var(scope, op.output("X@GRAD")[0], g)
