"""ISSUE 20: windowed time-series telemetry — arming/force-off knobs,
the bounded ring, counter-reset clamping, job-aligned windows under
clock skew, and the dump/merge integration (``doc["series"]`` →
``series_windows``)."""
import json
import os

import pytest

from paddle_tpu import observability as obs
from paddle_tpu.observability import distributed as dist
from paddle_tpu.observability import timeseries as ts


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.delenv("PADDLE_TPU_METRICS_DIR", raising=False)
    monkeypatch.delenv("PADDLE_TPU_TIMESERIES", raising=False)
    monkeypatch.delenv("PADDLE_TPU_TIMESERIES_WINDOWS", raising=False)
    obs.reset()
    obs.enable()
    ts._reset_for_tests()
    yield
    obs.reset()
    obs.disable()
    ts._reset_for_tests()


# -- knobs ------------------------------------------------------------------


def test_disabled_without_metrics_dir():
    assert not ts.series_enabled()
    ts.record_point("a.b", 1.0)
    assert ts.record_samples({"counters": {"x": 1}}) == 0
    assert ts.process_series() == {}


def test_armed_by_metrics_dir(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_METRICS_DIR", str(tmp_path))
    ts._reset_for_tests()
    assert ts.series_enabled()
    ts.record_point("a.b", 1.0, wall_ts=10.0)
    assert ts.process_series() == {
        "a.b": {"kind": "gauge", "points": [[10.0, 1.0]]}}
    # non-numeric values are ignored, not stored
    ts.record_point("a.b", "nope")
    ts.record_point("a.b", True)
    assert len(ts.process_series()["a.b"]["points"]) == 1


def test_force_off_beats_the_arm(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_METRICS_DIR", str(tmp_path))
    monkeypatch.setenv("PADDLE_TPU_TIMESERIES", "0")
    ts._reset_for_tests()
    assert not ts.series_enabled()
    assert ts.record_samples({"counters": {"x": 1}}) == 0


def test_window_cap_parsing(monkeypatch):
    assert ts.window_cap() == ts.DEFAULT_WINDOWS
    ts._reset_for_tests()
    monkeypatch.setenv("PADDLE_TPU_TIMESERIES_WINDOWS", "bogus")
    assert ts.window_cap() == ts.DEFAULT_WINDOWS
    ts._reset_for_tests()
    # a delta needs two samples: the floor is 2
    monkeypatch.setenv("PADDLE_TPU_TIMESERIES_WINDOWS", "1")
    assert ts.window_cap() == 2


# -- the bounded ring -------------------------------------------------------


def test_ring_evicts_oldest_at_the_bound(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_METRICS_DIR", str(tmp_path))
    monkeypatch.setenv("PADDLE_TPU_TIMESERIES_WINDOWS", "4")
    ts._reset_for_tests()
    for i in range(10):
        ts.record_point("c", float(i), wall_ts=float(i),
                        kind="counter")
    pts = ts.process_series()["c"]["points"]
    assert pts == [[6.0, 6.0], [7.0, 7.0], [8.0, 8.0], [9.0, 9.0]]


# -- pure window queries ----------------------------------------------------


def test_counter_reset_clamps_at_zero():
    # a relaunch resets the counter between t=2 and t=3: that hop
    # contributes 0, never a negative delta
    pts = [[1.0, 100.0], [2.0, 150.0], [3.0, 10.0], [4.0, 30.0]]
    assert ts.counter_delta(pts) == pytest.approx(70.0)
    assert ts.counter_delta([[1.0, 100.0], [2.0, 40.0]]) == 0.0
    assert ts.counter_delta([[1.0, 100.0]]) is None
    assert ts.counter_delta([]) is None


def test_rate_and_trailing_window():
    pts = [[0.0, 0.0], [10.0, 100.0], [20.0, 400.0]]
    assert ts.window_span(pts) == pytest.approx(20.0)
    assert ts.counter_rate(pts) == pytest.approx(20.0)
    # trailing 10s window keeps only the last hop
    assert ts.counter_delta(pts, window_s=10.0) == pytest.approx(300.0)
    assert ts.counter_rate(pts, window_s=10.0) == pytest.approx(30.0)
    # span 0 (one point in window after filtering): no rate
    assert ts.counter_rate(pts, window_s=0.0) is None
    assert ts.last_value(pts) == 400.0
    assert ts.last_value([]) is None
    # unordered input is sorted before the hops are walked
    assert ts.counter_delta([[2.0, 5.0], [1.0, 3.0]]) == \
        pytest.approx(2.0)


def test_record_samples_ships_histograms_as_counter_pairs(
        tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_METRICS_DIR", str(tmp_path))
    ts._reset_for_tests()
    snap = {"counters": {"c{x=1}": 5.0}, "gauges": {"g": 2.5},
            "histograms": {"h{s=0}": {"count": 4, "sum": 40.0}}}
    assert ts.record_samples(snap, wall_ts=1.0) == 3
    snap2 = {"counters": {"c{x=1}": 9.0}, "gauges": {"g": 3.5},
             "histograms": {"h{s=0}": {"count": 6, "sum": 100.0}}}
    assert ts.record_samples(snap2, wall_ts=2.0) == 3
    ser = ts.process_series()
    assert ser["c{x=1}"]["kind"] == "counter"
    assert ser["g"]["kind"] == "gauge"
    assert ts.counter_delta(ser["h{s=0}#sum"]["points"]) == 60.0
    assert ts.counter_delta(ser["h{s=0}#count"]["points"]) == 2.0
    # windowed mean = delta(sum)/delta(count) = 30ms


# -- job-aligned windows ----------------------------------------------------


def _series(points, kind="counter"):
    return {"m": {"kind": kind, "points": points}}


def test_job_windows_rebase_skewed_rank():
    # both ranks saw the same physical 10s interval; rank b's wall
    # clock runs 5s ahead and its applied skew says so
    per = {"a": _series([[100.0, 0.0], [110.0, 50.0]]),
           "b": _series([[105.0, 0.0], [115.0, 100.0]])}
    win = ts.job_windows(per, skews_us={"b": 5_000_000.0})["m"]
    assert win["kind"] == "counter"
    assert win["delta"] == pytest.approx(150.0)
    assert win["t0"] == pytest.approx(100.0)
    assert win["t1"] == pytest.approx(110.0)
    assert win["rate"] == pytest.approx(15.0)
    assert win["per_rank"]["b"]["t0"] == pytest.approx(100.0)
    assert win["per_rank"]["b"]["delta"] == pytest.approx(100.0)
    # without the correction the merged window smears over 15s
    smeared = ts.job_windows(per)["m"]
    assert smeared["t1"] == pytest.approx(115.0)


def test_job_windows_rank_without_usable_series():
    # one-point rank: no delta, no per_rank entry; the other rank
    # still folds. A rank entirely absent from per_series never shows.
    per = {"a": _series([[0.0, 0.0], [10.0, 40.0]]),
           "b": _series([[3.0, 7.0]])}
    win = ts.job_windows(per)["m"]
    assert set(win["per_rank"]) == {"a"}
    assert win["delta"] == pytest.approx(40.0)
    # all ranks unusable: the metric is dropped, not emitted empty
    assert ts.job_windows({"b": _series([[3.0, 7.0]])}) == {}
    assert ts.job_windows({}) == {}


def test_job_windows_gauges_fold_to_last_values():
    per = {"a": _series([[0.0, 1.0], [5.0, 2.0]], kind="gauge"),
           "b": _series([[1.0, 9.0]], kind="gauge")}
    win = ts.job_windows(per)["m"]
    assert win["kind"] == "gauge"
    assert win["per_rank"] == {"a": 2.0, "b": 9.0}


# -- dump/merge integration -------------------------------------------------


def _dump(d, role, rank, monkeypatch):
    monkeypatch.setenv("PADDLE_ROLE", role)
    monkeypatch.setenv("PADDLE_TRAINER_ID", str(rank))
    monkeypatch.setenv("PADDLE_PSERVER_INDEX", str(rank))
    monkeypatch.setenv("PADDLE_RESTART_COUNT", "0")
    dist._identity = None
    return dist.dump_process(os.path.join(d, "%s-%d.json"
                                          % (role, rank)))


def test_dump_attaches_series_and_merge_folds(tmp_path, monkeypatch):
    d = str(tmp_path)
    monkeypatch.setenv("PADDLE_TPU_METRICS_DIR", d)
    ts._reset_for_tests()
    # two dump ticks = two ring points per metric
    obs.counter("rpc.retries", method="send").inc(3)
    _dump(d, "trainer", 0, monkeypatch)
    obs.counter("rpc.retries", method="send").inc(5)
    p = _dump(d, "trainer", 0, monkeypatch)
    doc = json.load(open(p))
    pts = doc["series"]["rpc.retries{method=send}"]["points"]
    assert [v for _, v in pts] == [3.0, 8.0]

    # a rank whose dump predates the field contributes no windows but
    # merges fine
    legacy = {"schema": 1, "proc": "pserver-1", "role": "pserver",
              "rank": 1, "restart": 0, "pid": 4242, "wrote_at": 0.0,
              "clock_offset_us": 0.0,
              "metrics": {"counters": {"rpc.retries{method=send}": 2}},
              "spans": [], "flight": []}
    with open(os.path.join(d, "pserver-1.json"), "w") as f:
        json.dump(legacy, f)

    mpath, _ = dist.merge_job_dir(d)
    merged = json.load(open(mpath))
    assert "series" in merged["processes"]["trainer-0"]
    assert "series" not in merged["processes"]["pserver-1"]
    win = merged["series_windows"]["rpc.retries{method=send}"]
    assert win["delta"] == pytest.approx(5.0)
    assert set(win["per_rank"]) == {"trainer-0"}
    # lifetime totals still sum across BOTH ranks
    assert merged["counters_total"]["rpc.retries{method=send}"] == 10


def test_merge_without_any_series_has_no_windows(tmp_path,
                                                 monkeypatch):
    d = str(tmp_path)
    # sampling off: dumps carry no series and the merged doc must not
    # grow an empty series_windows key (old-schema compatibility)
    obs.counter("rpc.retries", method="send").inc(1)
    _dump(d, "trainer", 0, monkeypatch)
    merged = json.load(open(dist.merge_job_dir(d)[0]))
    assert "series" not in merged["processes"]["trainer-0"]
    assert "series_windows" not in merged
