"""Op frequency statistics.

Parity: /root/reference/python/paddle/fluid/contrib/op_frequence.py
(op_freq_statistic: single-op counts + adjacent-pair counts over a
program, ordered most-frequent first).
"""
from __future__ import annotations

from collections import OrderedDict


def op_freq_statistic(program):
    """Returns (uni_op_freq, adj_op_freq) OrderedDicts sorted by count."""
    from .. import framework

    if not isinstance(program, framework.Program):
        raise TypeError("program should be a Program, got %r"
                        % type(program))
    uni, adj = {}, {}
    for block in program.blocks:
        prev = None
        for op in block.ops:
            uni[op.type] = uni.get(op.type, 0) + 1
            if prev is not None:
                key = "%s->%s" % (prev, op.type)
                adj[key] = adj.get(key, 0) + 1
            prev = op.type
    order = lambda d: OrderedDict(
        sorted(d.items(), key=lambda kv: -kv[1]))
    return order(uni), order(adj)
