"""AST-based dygraph_to_static conversion tests.

Parity: /root/reference/python/paddle/fluid/tests/unittests/
dygraph_to_static/ (test_ifelse.py, test_loop.py, test_logical.py,
test_for_enumerate.py). The contract under test: a tensor-dependent
``if``/``while``/``for range`` inside a ``@declarative`` function is
rewritten into graph control flow, so ONE program (one cache entry)
serves every tensor VALUE of the same signature — the property the
reference's AST pass provides over naive tracing.
"""
import warnings

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.dygraph.dygraph_to_static import declarative


def _f32(x):
    return np.asarray(x, dtype=np.float32)


class TestTensorIf:
    def test_both_branches_one_program(self):
        @declarative
        def f(x):
            if fluid.layers.reduce_sum(x) > 0:
                y = x + 1.0
            else:
                y = x - 1.0
            return y

        with warnings.catch_warnings():
            warnings.simplefilter("error")  # no trace-fallback warning
            a = f(_f32(np.ones((2, 3))))
            b = f(_f32(-np.ones((2, 3))))
        assert np.allclose(a.numpy(), 2.0)
        assert np.allclose(b.numpy(), -2.0)
        assert len(f._cache) == 1, "one program must serve both signs"

    def test_name_assigned_in_one_branch_keeps_outer_value(self):
        @declarative
        def f(x):
            y = x * 2.0
            if fluid.layers.reduce_sum(x) > 0:
                y = x * 3.0
            return y

        assert np.allclose(f(_f32([1.0, 1.0])).numpy(), 3.0)
        assert np.allclose(f(_f32([-1.0, -1.0])).numpy(), -2.0)

    def test_bool_ops_in_condition(self):
        @declarative
        def f(x, y):
            if fluid.layers.reduce_sum(x) > 0 and \
                    fluid.layers.reduce_sum(y) > 0:
                out = x + y
            else:
                out = x - y
            return out

        a = f(_f32([1.0]), _f32([2.0]))
        b = f(_f32([1.0]), _f32([-2.0]))
        assert np.allclose(a.numpy(), 3.0)
        assert np.allclose(b.numpy(), 3.0)  # 1 - (-2)

    def test_logical_not(self):
        @declarative
        def f(x):
            if not (fluid.layers.reduce_sum(x) > 0):
                out = x * 0.0
            else:
                out = x * 1.0
            return out

        assert np.allclose(f(_f32([5.0])).numpy(), 5.0)
        assert np.allclose(f(_f32([-5.0])).numpy(), 0.0)

    def test_python_condition_stays_python(self):
        @declarative
        def f(x, flag):
            if flag:
                return x + 10.0
            return x - 10.0

        # early return keeps the Python `if`; flag is in the signature
        assert np.allclose(f(_f32([1.0]), True).numpy(), 11.0)
        assert np.allclose(f(_f32([1.0]), False).numpy(), -9.0)
        assert len(f._cache) == 2


class TestTensorWhile:
    def test_while_compiles_to_while_op(self):
        @declarative
        def g(x):
            s = x
            while fluid.layers.reduce_sum(s) < 100.0:
                s = s * 2.0
            return s

        with warnings.catch_warnings():
            # the whole point is ONE compiled XLA program — an
            # interpreter fallback is a failure, not a warning
            warnings.filterwarnings(
                "error", message=".*falls back to op-by-op.*")
            r1 = g(_f32(np.full((4,), 1.0)))
        r2 = g(_f32(np.full((4,), 30.0)))
        assert np.allclose(r1.numpy(), 32.0)
        assert np.allclose(r2.numpy(), 30.0)  # already >= 100 total
        prog = g.get_program(_f32(np.full((4,), 1.0)))
        types = [op.type for op in prog.global_block().ops]
        assert "while" in types
        assert len(g._cache) == 1

    def test_scalar_counter_promoted(self):
        @declarative
        def g(n):
            i = 0
            acc = n * 0.0
            while i < fluid.layers.reduce_sum(n):
                acc = acc + 2.0
                i = i + 1
            return acc

        out = g(_f32([3.0]))
        assert np.allclose(out.numpy(), 6.0)

    def test_if_inside_while(self):
        @declarative
        def g(x):
            s = x
            while fluid.layers.reduce_sum(s) < 10.0:
                if fluid.layers.reduce_sum(s) < 5.0:
                    s = s + 2.0
                else:
                    s = s + 1.0
            return s

        # 1 -> 3 -> 5 -> 6 -> ... -> 10
        out = g(_f32([1.0]))
        assert np.allclose(out.numpy(), 10.0)

    def test_break_in_tensor_while(self):
        @declarative
        def f(x):
            s = x
            while fluid.layers.reduce_sum(s) < 100.0:
                s = s * 2.0
                if fluid.layers.reduce_sum(s) > 50.0:
                    break
                s = s + 1.0
            return s

        with warnings.catch_warnings():
            warnings.filterwarnings(
                "error", message=".*falls back to op-by-op.*")
            out = f(_f32(np.full((4,), 1.0)))
        # python: 1->2(+1)3 ->6(+1)7 ->14(sum56>50) break
        assert np.allclose(out.numpy(), 14.0), out.numpy()
        prog = f.get_program(_f32(np.full((4,), 1.0)))
        assert "while" in [op.type for op in prog.global_block().ops]

    def test_continue_in_tensor_while(self):
        def pyref():
            acc = 0.0
            while acc < 10.0:
                acc += 1.0
                if acc > 5.0:
                    continue
                acc += 1.0
            return acc

        @declarative
        def g(x):
            acc = x * 0.0
            while fluid.layers.reduce_sum(acc) < 10.0:
                acc = acc + 1.0
                if fluid.layers.reduce_sum(acc) > 5.0:
                    continue
                acc = acc + 1.0
            return acc

        out = g(_f32([0.0]))
        assert np.allclose(out.numpy(), pyref()), (out.numpy(), pyref())

    def test_break_python_mode_exact(self):
        @declarative
        def f(x, n):
            i = 0
            while i < n:
                if i == 3:
                    break
                x = x + 1.0
                i += 1
            return x

        assert np.allclose(f(_f32([0.0]), 10).numpy(), 3.0)

    def test_break_in_nested_for_else_binds_to_outer(self):
        """`break` in a nested for's else: clause belongs to the OUTER
        loop (Python semantics) — the converter must keep the Python
        loop, not emit a break outside any loop."""
        @declarative
        def f(x, n):
            i = 0
            while i < n:
                for j in range(3):
                    x = x + 1.0
                else:
                    break
            return x

        out = f(_f32([0.0, 0.0]), 5)
        assert np.allclose(out.numpy(), 3.0)

    def test_python_while_unchanged(self):
        @declarative
        def g(x, n):
            i = 0
            while i < n:
                x = x + 1.0
                i += 1
            return x

        assert np.allclose(g(_f32([0.0]), 4).numpy(), 4.0)


class TestForRange:
    def test_python_range_unrolls(self):
        @declarative
        def h(x):
            for i in range(3):
                x = x + 1.0
            return x

        assert np.allclose(h(_f32([0.0])).numpy(), 3.0)

    def test_tensor_range_lowers_to_while(self):
        @declarative
        def h(x):
            n = fluid.layers.cast(fluid.layers.reduce_sum(x), "int64")
            acc = x * 0.0
            for i in range(n):
                acc = acc + 3.0
            return acc

        out = h(_f32([2.0, 2.0]))  # n = 4
        assert np.allclose(out.numpy(), 12.0)
        prog = h.get_program(_f32([2.0, 2.0]))
        types = [op.type for op in prog.global_block().ops]
        assert "while" in types

    def test_negative_step_tensor_range(self):
        @declarative
        def h(x):
            n = fluid.layers.cast(fluid.layers.reduce_sum(x), "int64")
            acc = fluid.layers.fill_constant([1], "int64", 0)
            for i in range(n, 0, -1):
                acc = acc + i
            return acc

        with warnings.catch_warnings():
            warnings.filterwarnings(
                "error", message=".*falls back to op-by-op.*")
            out = h(_f32([2.0, 2.0]))  # 4+3+2+1
        assert int(np.asarray(out.numpy()).ravel()[0]) == 10

    def test_for_target_bound_after_loop(self):
        @declarative
        def h(x):
            for i in range(3):
                x = x + 1.0
            return x * i  # Python: i == 2 after the loop

        assert np.allclose(h(_f32([0.0])).numpy(), 6.0)

    def test_iteration_var_used_in_body(self):
        @declarative
        def h(x):
            n = fluid.layers.cast(fluid.layers.reduce_sum(x), "int64")
            acc = fluid.layers.fill_constant([1], "int64", 0)
            for i in range(n):
                acc = acc + i
            return acc

        out = h(_f32([2.0, 3.0]))  # n=5 -> 0+1+2+3+4
        assert int(np.asarray(out.numpy()).ravel()[0]) == 10


class TestErrorsAndGuards:
    def test_static_variable_bool_raises(self):
        prog = fluid.Program()
        with fluid.program_guard(prog, fluid.Program()):
            v = fluid.layers.fill_constant([1], "bool", 1.0)
            with pytest.raises(TypeError, match="boolean value"):
                bool(v)

    def test_varbase_bool_is_concrete(self):
        with fluid.dygraph.guard():
            v = fluid.dygraph.to_variable(_f32([3.0]))
            assert bool(v > 1.0)
            assert not bool(v > 5.0)
            # int tensor vs float threshold must not truncate
            iv = fluid.dygraph.to_variable(
                np.array([0], dtype=np.int32))
            assert bool(iv > -0.5)
            with pytest.raises(ValueError, match="ambiguous"):
                bool(fluid.dygraph.to_variable(_f32([1.0, 2.0])))

    def test_undefined_loop_var_raises(self):
        @declarative
        def g(x):
            while fluid.layers.reduce_sum(x) < 0.0:
                y = x + 1.0
                x = y
            return x

        # y undefined before the loop but assigned in body -> must be
        # a clear error in tensor mode, not a crash
        with pytest.raises(Exception, match="initialize|NameError|no value"):
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                g(_f32([1.0]))


class TestTraceFallback:
    def test_dygraph_layer_falls_back_to_trace(self):
        """Functions using dygraph Layers cannot build statically and
        must keep working through the trace path."""
        with fluid.dygraph.guard():
            fc = fluid.dygraph.Linear(4, 2)

            @declarative
            def model(x):
                return fluid.layers.reduce_sum(fc(x))

            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                out = model(fluid.dygraph.to_variable(
                    _f32(np.ones((1, 4)))))
            assert np.asarray(out.numpy()).shape in ((), (1,))


class TestNestedIf:
    """Advisor r4 (high): visit_If leaked synthetic _jst_pred_N
    temporaries into the branch-merge set, breaking any `if` nested
    inside a tensor-condition `if` branch."""

    def test_tensor_if_nested_in_tensor_if(self):
        @declarative
        def f(x):
            if fluid.layers.reduce_sum(x) > 0:
                if fluid.layers.reduce_sum(x) > 10.0:
                    y = x * 3.0
                else:
                    y = x * 2.0
            else:
                y = x - 1.0
            return y

        with warnings.catch_warnings():
            warnings.simplefilter("error")  # no silent trace fallback
            hi = f(_f32([6.0, 6.0]))       # sum=12 -> inner true
            mid = f(_f32([1.0, 1.0]))      # sum=2  -> inner false
            neg = f(_f32([-1.0, -1.0]))    # outer false
        assert np.allclose(hi.numpy(), 18.0)
        assert np.allclose(mid.numpy(), 2.0)
        assert np.allclose(neg.numpy(), -2.0)
        assert len(f._cache) == 1, "one program must serve all paths"

    def test_python_if_nested_in_tensor_if(self):
        """A Python-condition `if` inside a tensor-`if` branch must not
        raise about a '_jst_pred' temporary (it did, as a hard
        Dy2StaticError on valid code)."""
        @declarative
        def f(x):
            k = 2.0
            if fluid.layers.reduce_sum(x) > 0:
                if k > 1.0:
                    y = x * k
                else:
                    y = x
            else:
                y = x - 1.0
            return y

        with warnings.catch_warnings():
            warnings.simplefilter("error")
            pos = f(_f32([3.0]))
            neg = f(_f32([-3.0]))
        assert np.allclose(pos.numpy(), 6.0)
        assert np.allclose(neg.numpy(), -4.0)

    def test_equal_numpy_arrays_in_branches_merge(self):
        """Advisor r4 (low): both branches assigning equal numpy arrays
        used to crash with 'truth value of an array is ambiguous';
        equal arrays now merge and the program still compiles."""
        @declarative
        def f(x):
            if fluid.layers.reduce_sum(x) > 0:
                c = np.ones(2, dtype=np.float32)
                y = x + 1.0
            else:
                c = np.ones(2, dtype=np.float32)
                y = x - 1.0
            return y

        with warnings.catch_warnings():
            warnings.simplefilter("error")  # no trace fallback
            out = f(_f32([2.0]))
            out2 = f(_f32([-2.0]))
        assert np.allclose(out.numpy(), 3.0)
        assert np.allclose(out2.numpy(), -3.0)

    def test_differing_nontensor_branch_values_diagnose(self):
        """Differing non-mergeable branch values must still raise the
        clean Dy2StaticError diagnostic (not an ambiguity crash)."""
        from paddle_tpu.dygraph.ast_transform import Dy2StaticError

        @declarative
        def f(x):
            if fluid.layers.reduce_sum(x) > 0:
                c = np.ones(2, dtype=np.float32)
            else:
                c = np.zeros(2, dtype=np.float32)
            return x

        with pytest.raises(Dy2StaticError, match="differ between"):
            f(_f32([2.0]))


class TestProgramCacheBound:
    def test_cache_is_lru_bounded(self):
        """Advisor r4 (low): identity-keyed args must not grow the
        program cache (and its pinned objects) without bound."""
        @declarative
        def f(x, cfg):
            return x * 2.0

        f._cache_cap = 3
        objs = [object() for _ in range(6)]
        for o in objs:
            out = f(_f32([1.0]), o)
            assert np.allclose(out.numpy(), 2.0)
        assert len(f._cache) <= 3
        pinned = [p for e in f._cache.values() for p in e.get("pins", [])]
        assert len(pinned) <= 3, "evicted entries must drop their pins"

    def test_equal_lists_and_np_scalars_merge(self):
        """Equality merge must keep working for non-ndarray types the
        old `==` handled (lists, np scalars) — review r5."""
        @declarative
        def f(x):
            if fluid.layers.reduce_sum(x) > 0:
                c = [1, 2]
                d = np.float32(0.5)
                y = x + 1.0
            else:
                c = [1, 2]
                d = np.float32(0.5)
                y = x - 1.0
            return y

        with warnings.catch_warnings():
            warnings.simplefilter("error")
            assert np.allclose(f(_f32([2.0])).numpy(), 3.0)
            assert np.allclose(f(_f32([-2.0])).numpy(), -3.0)
