"""tree_conv (TBCNN) vs a hand-walked numpy oracle + finite-difference
gradients (tree_conv_op.cc / math/tree2col.cc)."""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu.ops.tree_ops import (_construct_patch, _construct_tree,
                                     _etas, _patch_matrix)


def _oracle(feats, edges, filt, max_depth):
    bsz, n_nodes, n_feat = feats.shape
    out_size, n_filters = filt.shape[2], filt.shape[3]
    w2 = filt.reshape(n_feat * 3, out_size * n_filters)
    out = np.zeros((bsz, n_nodes, out_size, n_filters), feats.dtype)
    for b in range(bsz):
        patch, _t, count = _patch_matrix(feats[b], edges[b], max_depth)
        if count:
            out[b, :count] = (patch @ w2).reshape(count, out_size,
                                                  n_filters)
    return out


def test_patch_construction_matches_reference_walk():
    # tree: 1 -> {2, 3}, 2 -> {4}
    edges = np.array([[1, 2], [1, 3], [2, 4], [0, 0]], "int32")
    tr, count = _construct_tree(edges)
    assert count == 4
    assert tr[1] == [2, 3] and tr[2] == [4]
    patch = _construct_patch(1, 2, tr)
    # depth limit 2: root + direct children only
    assert [p[0] for p in patch] == [1, 2, 3]
    # root coeffs: index=1 pclen=1 depth=0 -> eta_t=1, eta_l=eta_r=0
    el, er, et = _etas(1, 1, 0, 2)
    assert (el, er, et) == (0.0, 0.0, 1.0)
    # child 1 of 2: index=1 pclen=2 depth=1 -> eta_t=.5, temp=0
    el, er, et = _etas(1, 2, 1, 2)
    np.testing.assert_allclose([el, er, et], [0.0, 0.5, 0.5])


def test_tree_conv_op_and_grads():
    rng = np.random.RandomState(3)
    B, N, F, OUT, NF, DEPTH = 2, 5, 4, 3, 2, 2
    feats = rng.randn(B, N, F).astype("float32")
    edges = np.zeros((B, 4, 2), "int32")
    edges[0, :3] = [[1, 2], [1, 3], [2, 4]]
    edges[1, :2] = [[1, 2], [2, 3]]
    filt = rng.randn(F, 3, OUT, NF).astype("float32") * 0.3

    main, startup = fluid.Program(), fluid.Program()
    b = main.global_block()
    for n in ("tc_x", "tc_e", "tc_w"):
        v = b.create_var(name=n)
        v.stop_gradient = False
    b.append_op("tree_conv",
                {"NodesVector": ["tc_x"], "EdgeSet": ["tc_e"],
                 "Filter": ["tc_w"]},
                {"Out": ["tc_o"]}, {"max_depth": DEPTH},
                infer_shape=False)
    b.create_var(name="tc_o").stop_gradient = False
    lv = b.create_var(name="tc_loss", shape=(), dtype="float32")
    lv.stop_gradient = False
    b.append_op("reduce_sum", {"X": ["tc_o"]}, {"Out": ["tc_loss"]},
                {"dim": [], "keep_dim": False, "reduce_all": True},
                infer_shape=False)
    from paddle_tpu.backward import append_backward

    with fluid.program_guard(main, startup):
        append_backward(b.var("tc_loss"), parameter_list=["tc_x", "tc_w"])

    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(main, feed={"tc_x": feats, "tc_e": edges, "tc_w": filt},
                fetch_list=[])
        got = np.asarray(scope.find_var("tc_o").raw().array)
        gx = np.asarray(scope.find_var("tc_x@GRAD").raw().array)
        gw = np.asarray(scope.find_var("tc_w@GRAD").raw().array)

    np.testing.assert_allclose(got, _oracle(feats, edges, filt, DEPTH),
                               rtol=1e-5, atol=1e-6)

    # finite differences on sum(out)
    def loss(fe, wt):
        return float(_oracle(fe, edges, wt, DEPTH).sum())

    eps = 1e-3
    for _ in range(6):
        i = tuple(rng.randint(0, s) for s in feats.shape)
        fp = feats.copy().astype("float64")
        fm = feats.copy().astype("float64")
        fp[i] += eps
        fm[i] -= eps
        fd = (loss(fp.astype("float32"), filt)
              - loss(fm.astype("float32"), filt)) / (2 * eps)
        np.testing.assert_allclose(gx[i], fd, rtol=2e-2, atol=1e-3)
    for _ in range(6):
        i = tuple(rng.randint(0, s) for s in filt.shape)
        wp = filt.copy()
        wm = filt.copy()
        wp[i] += eps
        wm[i] -= eps
        fd = (loss(feats, wp) - loss(feats, wm)) / (2 * eps)
        np.testing.assert_allclose(gw[i], fd, rtol=2e-2, atol=1e-3)


def test_tree_conv_layer():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        nodes = fluid.data(name="tl_x", shape=[1, 6, 4], dtype="float32")
        edges = fluid.data(name="tl_e", shape=[1, 5, 2], dtype="int32")
        out = fluid.contrib.layers.tree_conv(nodes, edges,
                                             output_size=3,
                                             num_filters=2, max_depth=2)
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    e = np.zeros((1, 5, 2), "int32")
    e[0, :3] = [[1, 2], [1, 3], [3, 4]]
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        (o,) = exe.run(main,
                       feed={"tl_x": rng.randn(1, 6, 4).astype("f4"),
                             "tl_e": e},
                       fetch_list=[out])
    assert np.asarray(o).shape == (1, 6, 3, 2)
    assert np.isfinite(np.asarray(o)).all()
