#!/usr/bin/env Rscript
# R inference client for paddle_tpu (counterpart of the reference's
# r/example/mobilenet.r): drives the Python inference API through
# reticulate with the zero-copy tensor surface.
#
# Usage:
#   1. python r/example/uci_housing.py   # saves the model under data/
#   2. Rscript r/example/uci_housing.r

library(reticulate)

np <- import("numpy")
inference <- import("paddle_tpu.inference")

set_config <- function() {
    config <- inference$AnalysisConfig("")
    config$set_model("data/uci_housing_model")
    config$switch_use_feed_fetch_ops(FALSE)
    config$switch_specify_input_names(TRUE)
    return(config)
}

zero_copy_run_housing <- function() {
    config <- set_config()
    predictor <- inference$create_paddle_predictor(config)

    input_names <- predictor$get_input_names()
    input_tensor <- predictor$get_input_tensor(input_names[1])

    data <- np$loadtxt("data/uci_housing_model/data.txt")
    input_tensor$reshape(as.integer(c(1, 13)))
    input_tensor$copy_from_cpu(np_array(data, dtype = "float32"))

    predictor$zero_copy_run()

    output_names <- predictor$get_output_names()
    output_tensor <- predictor$get_output_tensor(output_names[1])
    output_data <- output_tensor$copy_to_cpu()
    print(np_array(output_data)$reshape(as.integer(-1)))
}

zero_copy_run_housing()
