"""Legacy high-level Inferencer API.

Parity: /root/reference/python/paddle/fluid/contrib/inferencer.py —
``Inferencer(infer_func, param_path)`` rebuilds the inference program
from a function returning the prediction var, loads params, and
``infer(inputs)`` runs it.
"""
from __future__ import annotations

from typing import Callable, Optional

from .. import framework, io
from ..executor import Executor
from ..core.scope import Scope
from .trainer import check_and_get_place

__all__ = ["Inferencer"]


class Inferencer:
    def __init__(self, infer_func: Callable, param_path: str,
                 place=None, parallel: bool = False):
        if parallel:
            raise NotImplementedError(
                "Inferencer(parallel=True) is not supported; the "
                "compiled predictor already uses the full device")
        self.param_path = param_path
        self.scope = Scope()
        self.place = check_and_get_place(place)
        self.inference_program = framework.Program()
        startup = framework.Program()
        with framework.program_guard(self.inference_program, startup):
            self.predict_var = infer_func()
        self.exe = Executor(self.place)
        from .. import scope_guard

        with scope_guard(self.scope):
            self.exe.run(startup)
            io.load_persistables(self.exe, param_path,
                                 main_program=self.inference_program)
        self.inference_program = self.inference_program.clone(
            for_test=True)

    def infer(self, inputs: dict, return_numpy: bool = True):
        from .. import scope_guard

        with scope_guard(self.scope):
            return self.exe.run(self.inference_program, feed=inputs,
                                fetch_list=[self.predict_var],
                                return_numpy=return_numpy)
