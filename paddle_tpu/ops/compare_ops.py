"""Comparison + logical ops.

Parity: /root/reference/paddle/fluid/operators/controlflow/{compare_op.cc,
logical_op.cc}.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.registry import In, Out, register_op


def _cmp(name, f):
    @register_op(
        name,
        inputs=[In("X", no_grad=True), In("Y", no_grad=True)],
        outputs=[Out("Out")],
        attrs={"axis": -1, "force_cpu": False},
        grad=None,
    )
    def _op(ins, attrs, _f=f):
        return {"Out": _f(ins["X"], ins["Y"])}

    return _op


_cmp("equal", jnp.equal)
_cmp("not_equal", jnp.not_equal)
_cmp("less_than", jnp.less)
_cmp("less_equal", jnp.less_equal)
_cmp("greater_than", jnp.greater)
_cmp("greater_equal", jnp.greater_equal)


def _logical(name, f, binary=True):
    ins_spec = [In("X", no_grad=True)] + ([In("Y", no_grad=True)] if binary else [])

    @register_op(name, inputs=ins_spec, outputs=[Out("Out")], grad=None)
    def _op(ins, attrs, _f=f, _binary=binary):
        if _binary:
            return {"Out": _f(ins["X"], ins["Y"])}
        return {"Out": _f(ins["X"])}

    return _op


_logical("logical_and", jnp.logical_and)
_logical("logical_or", jnp.logical_or)
_logical("logical_xor", jnp.logical_xor)
_logical("logical_not", jnp.logical_not, binary=False)


@register_op(
    "isinf",
    inputs=[In("X", no_grad=True)],
    outputs=[Out("Out")],
    grad=None,
)
def _isinf(ins, attrs):
    return {"Out": jnp.any(jnp.isinf(ins["X"])).reshape((1,))}


@register_op(
    "isnan",
    inputs=[In("X", no_grad=True)],
    outputs=[Out("Out")],
    grad=None,
)
def _isnan(ins, attrs):
    return {"Out": jnp.any(jnp.isnan(ins["X"])).reshape((1,))}
