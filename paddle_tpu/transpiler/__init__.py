"""Transpilers (reference python/paddle/fluid/transpiler/)."""
from ..parallel.transpiler import (  # noqa: F401
    insert_allreduce_ops,
    insert_local_sgd_ops,
)
from .distribute_transpiler import (  # noqa: F401
    DistributeTranspiler,
    DistributeTranspilerConfig,
    slice_variable,
)


class HashName:
    """RoundRobin/Hash pserver dispatchers (reference ps_dispatcher.py)."""

    def __init__(self, pserver_endpoints):
        self._eps = list(pserver_endpoints)

    def dispatch(self, varlist):
        return [self._eps[hash(v.name) % len(self._eps)] for v in varlist]


class RoundRobin:
    def __init__(self, pserver_endpoints):
        self._eps = list(pserver_endpoints)
        self._i = 0

    def dispatch(self, varlist):
        out = []
        for v in varlist:
            out.append(self._eps[self._i % len(self._eps)])
            self._i += 1
        return out

    def reset(self):
        self._i = 0
