"""Pallas flash attention vs dense oracle.

The pallas kernel runs in interpret mode on CPU (force_pallas) so the
exact streaming/log-sum-exp code path is exercised without TPU
hardware; on-device it compiles to the real kernel.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas.support import pallas_supported

if not pallas_supported(interpret=True):
    # backend-capability probe (ops/pallas/support.py — shared with the
    # fused-optimizer fallback): a host whose jax cannot execute pallas
    # interpret mode at all SKIPS the kernel suite instead of failing
    # it; the op-level flash_attention falls back to dense math there.
    pytest.skip("pallas interpret mode unavailable on this backend",
                allow_module_level=True)

from paddle_tpu.ops.pallas.flash_attention import (  # noqa: E402
    _dense_attention, flash_attention)

B, H, S, D = 2, 3, 32, 16


def _inputs(seed=0):
    rng = np.random.RandomState(seed)
    return (jnp.asarray(rng.randn(B, H, S, D).astype("float32")),
            jnp.asarray(rng.randn(B, H, S, D).astype("float32")),
            jnp.asarray(rng.randn(B, H, S, D).astype("float32")))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("block", [8, 16, 32])
def test_kernel_matches_dense(causal, block):
    q, k, v = _inputs(0)
    ref = _dense_attention(q, k, v, causal, float(D) ** -0.5)
    got = flash_attention(q, k, v, causal=causal, block_q=block,
                          block_k=block, force_pallas=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("block", [8, 16])
def test_backward_kernels_match_dense_vjp(causal, block):
    """The pallas dQ / dK+dV kernels (blockwise recompute from saved
    LSE) must agree with the dense-attention VJP on all three grads —
    including the causal masking and the non-uniform cotangent."""
    q, k, v = _inputs(2)
    rng = np.random.RandomState(3)
    ct = jnp.asarray(rng.randn(B, H, S, D).astype("float32"))
    scale = float(D) ** -0.5

    def flash_loss(q, k, v):
        out = flash_attention(q, k, v, causal=causal, block_q=block,
                              block_k=block, force_pallas=True)
        return jnp.sum(out * ct)

    def dense_loss(q, k, v):
        return jnp.sum(_dense_attention(q, k, v, causal, scale) * ct)

    g_flash = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_flash, g_dense, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4,
            err_msg="d%s mismatch (causal=%s block=%d)"
                    % (name, causal, block))


def test_backward_ragged_tail_falls_back_dense():
    """S not divisible by the block -> the fallback path must still
    deliver exact grads."""
    rng = np.random.RandomState(4)
    q = jnp.asarray(rng.randn(1, 2, 20, 8).astype("float32"))
    k = jnp.asarray(rng.randn(1, 2, 20, 8).astype("float32"))
    v = jnp.asarray(rng.randn(1, 2, 20, 8).astype("float32"))
    scale = 8.0 ** -0.5

    def flash_loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, block_q=16, block_k=16,
                                       force_pallas=True))

    def dense_loss(q, k, v):
        return jnp.sum(_dense_attention(q, k, v, False, scale))

    g_flash = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_grads_flow():
    q, k, v = _inputs(1)

    def loss(q, k, v):
        o = flash_attention(q, k, v, causal=True, block_q=16,
                            block_k=16, force_pallas=True)
        return (o.astype(jnp.float32) ** 2).sum()

    def loss_ref(q, k, v):
        o = _dense_attention(q, k, v, True, float(D) ** -0.5)
        return (o.astype(jnp.float32) ** 2).sum()

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_transformer_model_uses_flash_path():
    import paddle_tpu as fluid
    from paddle_tpu import models

    Bm, T, Dm = 2, 16, 32
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data(name="x", shape=[Bm, T, Dm], dtype="float32")
        out = models.transformer.multi_head_attention(
            x, num_heads=4, d_model=Dm, dropout=0.0, is_test=True)
    types = [op.type for op in prog.global_block().ops]
    assert "flash_attention" in types
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        (o,) = exe.run(
            prog,
            feed={"x": np.random.RandomState(0).randn(
                Bm, T, Dm).astype("float32")},
            fetch_list=[out])
    assert np.asarray(o).shape == (Bm, T, Dm)
    assert np.isfinite(np.asarray(o)).all()


def test_training_path_uses_flash_when_unmasked():
    """With the pallas backward kernels, TRAINING attention (no mask,
    no attention dropout) also routes through flash_attention, and a
    grad op for it lands in the program."""
    import paddle_tpu as fluid
    from paddle_tpu import models

    Bm, T, Dm = 2, 8, 16
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data(name="x", shape=[Bm, T, Dm], dtype="float32")
        out = models.transformer.multi_head_attention(
            x, num_heads=2, d_model=Dm, dropout=0.0, is_test=False,
            use_flash=True)  # auto only kicks in at T >= 2048
        loss = fluid.layers.reduce_mean(out)
        fluid.optimizer.SGD(0.1).minimize(loss)
    types = [op.type for op in prog.global_block().ops]
    assert "flash_attention" in types
    assert "flash_attention_grad" in types
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        x0 = np.random.RandomState(0).randn(Bm, T, Dm).astype("float32")
        l0 = exe.run(prog, feed={"x": x0}, fetch_list=[loss])[0]
        for _ in range(3):
            l1 = exe.run(prog, feed={"x": x0}, fetch_list=[loss])[0]
    assert np.isfinite(np.asarray(l1)).all()
    assert float(np.asarray(l1)) != float(np.asarray(l0))  # trained


def test_masked_path_still_dense():
    import paddle_tpu as fluid
    from paddle_tpu import models

    Bm, T, Dm = 2, 8, 16
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data(name="x", shape=[Bm, T, Dm], dtype="float32")
        bias = fluid.data(name="b", shape=[Bm, 1, T, T], dtype="float32")
        models.transformer.multi_head_attention(
            x, num_heads=2, d_model=Dm, attn_bias=bias, is_test=True)
    types = [op.type for op in prog.global_block().ops]
    assert "flash_attention" not in types
    assert "softmax" in types


def test_fit_block_shrinks_to_aligned_divisor():
    """S not a multiple of the tuned block must shrink the block, not
    silently drop to dense (advisor r4): 2560 with the 512/1024
    defaults stays on the flash path via 640-wide K blocks."""
    from paddle_tpu.ops.pallas.flash_attention import _fit_block

    assert _fit_block(2560, 512) == 512     # already divides
    assert _fit_block(2560, 1024) == 640    # largest 128-aligned divisor
    assert _fit_block(2688, 1024) == 896
    assert _fit_block(768, 512) == 384
    assert _fit_block(640, 512) == 128
    assert _fit_block(100, 512) == 100      # short seq: block = S
    assert _fit_block(200, 512) == 200
    assert _fit_block(48, 32) == 24         # sub-128: 8-aligned
    # no aligned divisor below the cap -> 0 (caller goes dense, warns)
    assert _fit_block(770, 512) == 0


def test_nonmultiple_seq_still_flash():
    """S=48 with block 32 previously fell back to dense silently; the
    fitted 16-wide block must keep the pallas path and stay exact."""
    rng = np.random.RandomState(7)
    q = jnp.asarray(rng.randn(1, 2, 48, 8).astype("float32"))
    k = jnp.asarray(rng.randn(1, 2, 48, 8).astype("float32"))
    v = jnp.asarray(rng.randn(1, 2, 48, 8).astype("float32"))
    ref = _dense_attention(q, k, v, False, 8.0 ** -0.5)
    got = flash_attention(q, k, v, block_q=32, block_k=32,
                          force_pallas=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_masked_flash_matches_dense(causal):
    """Per-row KV lengths (the padding mask, VERDICT r4 #7): masked
    rows must match the dense additive-mask oracle on visible QUERY
    rows, forward and backward."""
    Bm, Hm, Sm, Dm = 3, 2, 32, 16
    rng = np.random.RandomState(11)
    q = jnp.asarray(rng.randn(Bm, Hm, Sm, Dm).astype("float32"))
    k = jnp.asarray(rng.randn(Bm, Hm, Sm, Dm).astype("float32"))
    v = jnp.asarray(rng.randn(Bm, Hm, Sm, Dm).astype("float32"))
    lengths = jnp.asarray([32, 20, 7], dtype=jnp.int32)
    scale = float(Dm) ** -0.5
    ct = jnp.asarray(rng.randn(Bm, Hm, Sm, Dm).astype("float32"))
    # only visible query rows contribute (padded-query outputs are
    # unspecified, exactly like the additive-mask formulation)
    row_ok = np.zeros((Bm, 1, Sm, 1), dtype="float32")
    for b, L in enumerate([32, 20, 7]):
        row_ok[b, :, :L] = 1.0
    ctv = ct * jnp.asarray(row_ok)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, block_q=16,
                            block_k=16, force_pallas=True,
                            lengths=lengths)
        return jnp.sum(o * ctv)

    def loss_dense(q, k, v):
        o = _dense_attention(q, k, v, causal, scale, lengths=lengths)
        return jnp.sum(o * ctv)

    o_f = flash_attention(q, k, v, causal=causal, block_q=16,
                          block_k=16, force_pallas=True, lengths=lengths)
    o_d = _dense_attention(q, k, v, causal, scale, lengths=lengths)
    np.testing.assert_allclose(np.asarray(o_f) * row_ok,
                               np.asarray(o_d) * row_ok,
                               rtol=2e-5, atol=2e-5)
    g_f = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_d = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_f, g_d, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4,
            err_msg="d%s mismatch (causal=%s)" % (name, causal))


def test_masked_flash_zero_length_row():
    """A fully padded example must not NaN anything."""
    q = jnp.asarray(np.ones((2, 1, 16, 8), dtype="float32"))
    lengths = jnp.asarray([16, 0], dtype=jnp.int32)

    def loss(q):
        o = flash_attention(q, q, q, block_q=8, block_k=8,
                            force_pallas=True, lengths=lengths)
        return jnp.sum(o[0])   # loss over the valid example only

    g = jax.grad(loss)(q)
    assert np.isfinite(np.asarray(g)).all()


def test_masked_training_routes_flash():
    """With kv_lengths, MASKED training attention routes flash at any
    length — the round-4 gap (padding-masked training always fell
    dense) closed."""
    import paddle_tpu as fluid
    from paddle_tpu import models

    Bm, T, Dm = 2, 16, 32
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data(name="x", shape=[Bm, T, Dm], dtype="float32")
        lens = fluid.data(name="lens", shape=[Bm], dtype="int32")
        out = models.transformer.multi_head_attention(
            x, num_heads=4, d_model=Dm, dropout=0.0, is_test=False,
            kv_lengths=lens)
        loss = fluid.layers.reduce_mean(out)
        fluid.optimizer.SGD(0.1).minimize(loss)
    types = [op.type for op in prog.global_block().ops]
    assert "flash_attention" in types
    assert "flash_attention_grad" in types
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        rng = np.random.RandomState(0)
        l0 = exe.run(prog, feed={
            "x": rng.randn(Bm, T, Dm).astype("float32"),
            "lens": np.array([16, 9], dtype="int32")},
            fetch_list=[loss])[0]
    assert np.isfinite(np.asarray(l0)).all()


def test_wmt_model_with_lengths_routes_flash():
    import paddle_tpu as fluid
    from paddle_tpu import models

    Bm, T = 2, 16
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        src = fluid.data(name="src", shape=[Bm, T], dtype="int64")
        srcp = fluid.data(name="srcp", shape=[Bm, T], dtype="int64")
        tgt = fluid.data(name="tgt", shape=[Bm, T], dtype="int64")
        tgtp = fluid.data(name="tgtp", shape=[Bm, T], dtype="int64")
        slen = fluid.data(name="slen", shape=[Bm], dtype="int32")
        tlen = fluid.data(name="tlen", shape=[Bm], dtype="int32")
        logits = models.transformer.transformer_wmt(
            src, srcp, tgt, tgtp, vocab_size=64, max_len=T,
            num_layers=1, num_heads=2, d_model=16, d_ff=32,
            src_lengths=slen, tgt_lengths=tlen)
    types = [op.type for op in prog.global_block().ops]
    # encoder self-attn + decoder self-attn route flash; cross stays
    # dense (rectangular) with the additive bias
    assert types.count("flash_attention") == 2
    assert "softmax" in types


def test_dense_kv_lengths_mask_actually_masks():
    """Review r5: the additive pad bias computed (vis-1e9)*1e9 which
    collapses to the same float32 constant for visible AND masked keys
    (a silent no-op mask). Contract: with kv_lengths, the output on
    valid rows must be INVARIANT to the content of padded positions —
    checked on the forced-dense path (the flash path has its own
    oracle test)."""
    import paddle_tpu as fluid
    from paddle_tpu import models

    Bm, T, Dm = 2, 8, 16

    def run(x_np):
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            fluid.default_startup_program().random_seed = 5
            prog.random_seed = 5
            startup.random_seed = 5
            x = fluid.data(name="x", shape=[Bm, T, Dm], dtype="float32")
            lens = fluid.data(name="lens", shape=[Bm], dtype="int32")
            out = models.transformer.multi_head_attention(
                x, num_heads=2, d_model=Dm, dropout=0.0, is_test=True,
                kv_lengths=lens, use_flash=False)
        types = [op.type for op in prog.global_block().ops]
        assert "flash_attention" not in types  # the dense fallback
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            (o,) = exe.run(prog, feed={
                "x": x_np, "lens": np.array([8, 4], dtype="int32")},
                fetch_list=[out])
        return np.asarray(o)

    rng = np.random.RandomState(0)
    x1 = rng.randn(Bm, T, Dm).astype("float32")
    x2 = x1.copy()
    x2[1, 4:] = 77.0   # change ONLY padded positions of example 1
    np.random.seed(0)
    o1 = run(x1)
    np.random.seed(0)
    o2 = run(x2)
    # example 0 (full length) unchanged input -> identical output;
    # example 1 valid rows must ignore the padded-key change
    np.testing.assert_allclose(o1[0], o2[0], rtol=1e-5)
    np.testing.assert_allclose(o1[1, :4], o2[1, :4], rtol=1e-4,
                               atol=1e-4)
