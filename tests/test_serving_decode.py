"""Continuous-batching decode tier (paddle_tpu/serving/decode/ +
fleet streaming): paged KV cache block accounting, quantized storage,
the paged-attention kernel's dense/interpret parity, deterministic
regeneration (the failover contract), the per-token engine (TTFT/ITL,
preemption, dedup replay), cost-unit fleet admission, and token-level
exactly-once stream failover over real loopback replicas.

The multi-process SIGKILL drill lives in ``tools/serving_chaos.py``
(CI gate 8); here replicas die in-process (engine stop + socket close)
which exercises the same router-side failover path.
"""
import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from paddle_tpu import observability as obs
from paddle_tpu.ops.pallas.paged_attention import (
    paged_attention_reference, paged_decode_attention)
from paddle_tpu.serving import metrics as sm
from paddle_tpu.serving.decode import (DecodeConfig, DecodeEngine,
                                       KVCacheConfig, KVCacheFull,
                                       PagedKVCache, TinyDecodeLM)
from paddle_tpu.serving.fleet import FleetConfig, FleetRouter
from paddle_tpu.serving import (DeadlineExpired, RequestShed,
                                ServerOverloaded)
from paddle_tpu.serving.http import start_http_server


@pytest.fixture(autouse=True)
def _clean_registry():
    obs.reset()
    obs.enable()
    yield
    obs.reset()
    obs.disable()


def _cache(**kw):
    kw.setdefault("num_blocks", 8)
    kw.setdefault("block_tokens", 4)
    kw.setdefault("num_layers", 2)
    kw.setdefault("num_heads", 2)
    kw.setdefault("head_dim", 4)
    return PagedKVCache(KVCacheConfig(**kw))


def _kv(rng, n, layers=2, heads=2, dim=4):
    """``[T, layers, heads, dim]`` float32 rows for ``append``."""
    return rng.randn(n, layers, heads, dim).astype(np.float32)


# -- KV cache block accounting ------------------------------------------------

class TestKVCacheAccounting:
    def test_alloc_free_parity_join_leave(self):
        c = _cache()
        rng = np.random.RandomState(0)
        total = c.free_blocks()
        for round_ in range(3):
            ids = ["s%d_%d" % (round_, i) for i in range(3)]
            for sid in ids:
                c.register(sid)
                c.append(sid, _kv(rng, 5), _kv(rng, 5))
            c.check()
            for sid in ids:
                c.release(sid)
            assert c.free_blocks() == total
            c.check()

    def test_evict_readmit_parity(self):
        c = _cache(num_blocks=4)
        rng = np.random.RandomState(1)
        free0 = c.free_blocks()
        c.register("a")
        c.append("a", _kv(rng, 9), _kv(rng, 9))
        used = free0 - c.free_blocks()
        assert used == 3  # ceil(9/4)
        c.release("a")    # evicted under pressure
        assert c.free_blocks() == free0
        c.register("a")   # re-admitted: re-prefill from scratch
        c.append("a", _kv(rng, 9), _kv(rng, 9))
        assert free0 - c.free_blocks() == used
        c.release("a")
        assert c.free_blocks() == free0
        c.check()

    def test_reserve_is_atomic_when_full(self):
        c = _cache(num_blocks=2, num_layers=1)
        c.register("a")
        start = c.reserve("a", 7)  # 2 blocks of 4
        assert start == 0 and c.free_blocks() == 0
        with pytest.raises(KVCacheFull):
            c.reserve("a", 2)  # needs a 3rd block
        # nothing changed: same length, same free count
        assert c.seq_len("a") == 7 and c.free_blocks() == 0
        c.check()

    def test_seeded_churn_zero_leaks(self):
        """Randomized register/append/release churn; the partition
        invariant (free + owned == arena) must hold at every step and
        every block must come back at the end."""
        rng = np.random.RandomState(0xC4A0)
        c = _cache(num_blocks=16, num_layers=1)
        total = c.free_blocks()
        live = {}
        for step in range(300):
            op = rng.rand()
            if op < 0.45 and len(live) < 6:
                sid = "s%d" % step
                c.register(sid)
                live[sid] = 0
            elif op < 0.8 and live:
                sid = list(live)[rng.randint(len(live))]
                n = int(rng.randint(1, 6))
                try:
                    c.append(sid, _kv(rng, n, layers=1),
                             _kv(rng, n, layers=1))
                    live[sid] += n
                except KVCacheFull:
                    c.release(sid)  # preempt the victim
                    del live[sid]
            elif live:
                sid = list(live)[rng.randint(len(live))]
                c.release(sid)
                del live[sid]
            c.check()
            for sid, n in live.items():
                assert c.seq_len(sid) == n
        for sid in list(live):
            c.release(sid)
        assert c.free_blocks() == total
        c.check()

    def test_block_table_shapes_and_padding(self):
        c = _cache(num_layers=1)
        rng = np.random.RandomState(2)
        c.register("a")
        c.append("a", _kv(rng, 6, layers=1), _kv(rng, 6, layers=1))
        c.register("b")
        c.append("b", _kv(rng, 1, layers=1), _kv(rng, 1, layers=1))
        table, lens = c.block_table(["a", "b", "__pad__"])
        assert table.shape == (3, 2) and list(lens) == [6, 1, 0]
        assert table[0, 0] >= 0 and table[0, 1] >= 0
        assert table[1, 1] == -1          # b only owns one block
        assert list(table[2]) == [-1, -1]  # pad row owns nothing


# -- quantized storage --------------------------------------------------------

class TestQuantizedKV:
    @pytest.mark.parametrize("dtype,tol", [("bf16", 2e-2), ("int8", 6e-2)])
    def test_quantized_vs_f32_divergence_bounded(self, dtype, tol):
        rng = np.random.RandomState(3)
        k = _kv(rng, 11)
        v = _kv(rng, 11)
        exact = _cache(dtype="f32")
        quant = _cache(dtype=dtype)
        for c in (exact, quant):
            c.register("s")
            c.append("s", k, v)
        for layer in range(2):
            ke, ve = exact.gather("s", layer)
            kq, vq = quant.gather("s", layer)
            scale = max(np.abs(ke).max(), np.abs(ve).max())
            assert np.abs(ke - kq).max() / scale < tol
            assert np.abs(ve - vq).max() / scale < tol

    def test_int8_requantize_on_amax_growth(self):
        """A later row with much larger amax forces an in-place block
        requantize; earlier rows must stay within int8 resolution of
        the NEW scale, not collapse to garbage."""
        c = _cache(dtype="int8", num_layers=1)
        c.register("s")
        small = np.full((1, 1, 2, 4), 0.01, np.float32)
        big = np.full((1, 1, 2, 4), 10.0, np.float32)
        c.append("s", small, small)
        c.append("s", big, big)
        k, _ = c.gather("s", 0)
        # new scale = 10/127 => resolution ~0.079; 0.01 rounds to 0
        assert abs(k[1, 0, 0] - 10.0) < 0.1
        assert abs(k[0, 0, 0]) <= 10.0 / 127 + 1e-6

    def test_arena_bytes_ordering(self):
        f32 = KVCacheConfig(dtype="f32").arena_bytes()
        bf16 = KVCacheConfig(dtype="bf16").arena_bytes()
        i8 = KVCacheConfig(dtype="int8").arena_bytes()
        assert f32 > bf16 > i8


# -- paged attention kernel ---------------------------------------------------

class TestPagedAttention:
    def _setup(self, dtype="f32"):
        rng = np.random.RandomState(7)
        c = _cache(num_blocks=16, block_tokens=8, num_layers=1,
                   num_heads=2, head_dim=8, dtype=dtype)
        lens = [13, 1, 20]
        for i, n in enumerate(lens):
            c.register("s%d" % i)
            c.append("s%d" % i, _kv(rng, n, layers=1, dim=8),
                     _kv(rng, n, layers=1, dim=8))
        q = rng.randn(3, 2, 8).astype(np.float32)
        table, ln = c.block_table(["s0", "s1", "s2"])
        return c, q, table, ln

    def test_dense_matches_bruteforce(self):
        c, q, table, lens = self._setup()
        k_ar, v_ar, ks, vs = c.views(0)
        out = paged_attention_reference(q, k_ar, v_ar, table, lens,
                                        block_tokens=8)
        for i in range(3):
            k, v = c.gather("s%d" % i, 0)
            s = np.einsum("hd,thd->ht", q[i], k) / np.sqrt(8.0)
            p = np.exp(s - s.max(axis=1, keepdims=True))
            p /= p.sum(axis=1, keepdims=True)
            want = np.einsum("ht,thd->hd", p, v)
            np.testing.assert_allclose(out[i], want, rtol=1e-5,
                                       atol=1e-5)

    def test_pallas_interpret_parity(self):
        c, q, table, lens = self._setup()
        k_ar, v_ar, _, _ = c.views(0)
        dense = paged_decode_attention(q, k_ar, v_ar, table, lens,
                                       block_tokens=8, backend="dense")
        pallas = paged_decode_attention(q, k_ar, v_ar, table, lens,
                                        block_tokens=8,
                                        backend="pallas_interpret")
        np.testing.assert_allclose(pallas, dense, rtol=2e-5, atol=2e-5)

    def test_quantized_arena_attention(self):
        c, q, table, lens = self._setup(dtype="int8")
        k_ar, v_ar, ks, vs = c.views(0)
        out = paged_decode_attention(q, k_ar, v_ar, table, lens,
                                     block_tokens=8, k_scales=ks,
                                     v_scales=vs, backend="dense")
        cf, qf, tf, lf = self._setup(dtype="f32")
        kf, vf, _, _ = cf.views(0)
        exact = paged_decode_attention(qf, kf, vf, tf, lf,
                                       block_tokens=8, backend="dense")
        assert np.abs(out - exact).max() < 0.2


# -- deterministic regeneration (the failover contract) -----------------------

class TestDeterministicRegeneration:
    def _gen(self, prompt, n, chunks):
        c = _cache(num_blocks=32, block_tokens=4, num_layers=2,
                   num_heads=2, head_dim=8)
        m = TinyDecodeLM(c, eos_token=None)
        c.register("s")
        h = None
        i = 0
        for size in chunks:
            h = m.prefill_chunk("s", prompt[i:i + size])
            i += size
        logits = m.logits1(h, len(prompt))
        tok = int(np.argmax(logits))
        out = [tok]
        for _ in range(n - 1):
            _, nxt = m.decode_step(["s"], [tok])
            tok = int(nxt[0])
            out.append(tok)
        return out

    def test_chunking_invariance(self):
        prompt = list(range(1, 12))
        a = self._gen(prompt, 8, [11])
        b = self._gen(prompt, 8, [3, 5, 2, 1])
        d = self._gen(prompt, 8, [4, 7])
        assert a == b == d
        assert len(set(a)) > 2  # not a degenerate constant stream


# -- decode engine ------------------------------------------------------------

def _engine(**kw):
    kw.setdefault("kv_blocks", 64)
    kw.setdefault("eos_token", None)
    return DecodeEngine(DecodeConfig(**kw)).start()


class TestDecodeEngine:
    def test_stream_events_and_metrics(self):
        e = _engine()
        try:
            evs = list(e.submit([1, 2, 3], max_tokens=6))
            toks = [x for x in evs if x["type"] == "token"]
            assert [t["index"] for t in toks] == list(range(6))
            assert evs[-1] == {"type": "finish", "reason": "max_tokens",
                               "tokens": 6, "preemptions": 0}
            st = e.stats()
            assert st[sm.STREAMS] == 1
            assert st[sm.TOKENS] == 6
            assert st[sm.TTFT_MS]["count"] == 1
            assert st[sm.ITL_MS]["count"] == 5
        finally:
            e.stop()

    def test_dedup_replay_and_resume_from(self):
        e = _engine()
        try:
            first = list(e.submit([4, 5], max_tokens=5,
                                  request_id="rid1"))
            again = list(e.submit([4, 5], max_tokens=5,
                                  request_id="rid1"))
            assert again == first
            tail = list(e.submit([4, 5], max_tokens=5,
                                 request_id="rid1", resume_from=3))
            toks = [x for x in tail if x["type"] == "token"]
            assert [t["index"] for t in toks] == [3, 4]
            want = [x for x in first if x["type"] == "token"][3:]
            assert toks == want
        finally:
            e.stop()

    def test_mixed_length_concurrent_streams(self):
        e = _engine(max_batch_size=4)
        try:
            lens = [3, 9, 1, 6, 12, 2]
            streams = [e.submit([i + 1, i + 2], max_tokens=n,
                                request_id="m%d" % i)
                       for i, n in enumerate(lens)]
            outs = [list(s) for s in streams]
            for n, evs in zip(lens, outs):
                toks = [x for x in evs if x["type"] == "token"]
                assert [t["index"] for t in toks] == list(range(n))
                assert evs[-1]["reason"] == "max_tokens"
            # streams batched together decode the same values they
            # would alone (the whole point of the per-row model)
            solo = _engine(max_batch_size=1)
            try:
                alone = list(solo.submit([2, 3], max_tokens=9,
                                         request_id="m1"))
                assert [x for x in alone if x["type"] == "token"] == \
                    [x for x in outs[1] if x["type"] == "token"]
            finally:
                solo.stop()
        finally:
            e.stop()

    def test_deadline_finish_event(self):
        # a long prompt in tiny chunks: the deadline lands mid-prefill
        e = _engine(kv_blocks=128, prefill_chunk_tokens=4,
                    max_prompt_tokens=512)
        try:
            evs = list(e.submit([1] * 300, max_tokens=500,
                                deadline_s=0.05))
            assert evs[-1]["type"] == "finish"
            assert evs[-1]["reason"] == "deadline_expired"
            with pytest.raises(DeadlineExpired):
                e.submit([1] * 300, max_tokens=500,
                         deadline_s=0.05).result()
        finally:
            e.stop()

    def test_preemption_low_evicted_first_zero_leaks(self):
        # arena of 5 blocks * 4 tokens: two 12-token streams cannot
        # coexist; the LOW one must be evicted (re-prefilled later)
        e = _engine(kv_blocks=5, kv_block_tokens=4, num_layers=1,
                    max_batch_size=2, prefill_chunk_tokens=4)
        try:
            lo = e.submit([1, 2], max_tokens=14, cost_class="low",
                          request_id="lo")
            hi = e.submit([3, 4], max_tokens=14, cost_class="high",
                          request_id="hi")
            lo_evs, hi_evs = list(lo), list(hi)
            for evs in (lo_evs, hi_evs):
                assert evs[-1]["reason"] == "max_tokens"
                assert len([x for x in evs
                            if x["type"] == "token"]) == 14
            st = e.stats()
            assert st.get(sm.PREEMPTIONS, 0) >= 1
            from paddle_tpu.observability import flight
            ev = [f for _, kind, f in flight.events()
                  if kind == "serving.kv_preempt"]
            assert ev and ev[0]["priority"] == 2  # low shed first
            assert e.health_doc()["kv_occupancy"] == 0.0
        finally:
            e.stop()

    def test_overload_and_health_doc(self):
        e = _engine(max_waiting=1, max_batch_size=1,
                    prefill_chunk_tokens=2)
        try:
            doc = e.health_doc()
            assert doc["engine_kind"] == "decode"
            assert set(doc) >= {"status", "kv_occupancy", "kv_blocks",
                                "kv_dtype", "active_streams"}
            streams = []
            with pytest.raises(ServerOverloaded):
                for i in range(50):
                    streams.append(e.submit([1] * 30, max_tokens=50,
                                            request_id="ov%d" % i))
            for s in streams:
                s.cancel()
        finally:
            e.stop(drain=False)


# -- fleet cost-unit admission ------------------------------------------------

class TestFleetCostAdmission:
    def test_stream_units_pricing(self):
        cfg = FleetConfig(cost_unit_tokens=16, default_stream_tokens=16)
        assert cfg.stream_units(None) == 1
        assert cfg.stream_units(1) == 1
        assert cfg.stream_units(16) == 1
        assert cfg.stream_units(17) == 2
        assert cfg.stream_units(512) == 32

    def test_long_low_sheds_before_short_high(self):
        """The satellite contract: with cost-priced admission a LONG
        low-priority stream trips its watermark while a SHORT
        high-priority one still admits — at the very same queue
        state."""
        r = FleetRouter(["127.0.0.1:1"], FleetConfig(
            max_queue=32, cost_unit_tokens=16,
            num_dispatchers=1, health_interval_ms=10_000)).start()
        try:
            # low watermark = 16 units; 512 tokens = 32 units
            with pytest.raises(RequestShed):
                r.generate([1, 2], max_tokens=512, cost_class="low")
            # same length stream in the TOP lane: the hard bound (32)
            # still holds it, but a short low stream AND a long high
            # stream both admit
            short_low = r.generate([1, 2], max_tokens=16,
                                   cost_class="low")
            long_high = r.generate([1, 2], max_tokens=496,
                                   cost_class="high")
            assert r.stats()["queue_units"] == 32
            # the long high stream's 31 held units now push ONE-unit
            # low traffic over its watermark: expensive work pressures
            # cheap lanes, not the reverse
            with pytest.raises(RequestShed):
                r.submit({"x": np.zeros((1, 1))}, cost_class="low")
            assert obs.counter_value(sm.SHED, **{"class": "low"}) >= 1
            short_low.close()
            long_high.close()
            assert r.stats()["queue_units"] == 0
        finally:
            r.stop()

    def test_oneshot_admission_unchanged(self):
        """Every one-shot request is exactly one unit: the pre-decode
        watermark behavior is bit-compatible."""
        cfg = FleetConfig(max_queue=4, num_dispatchers=1,
                          health_interval_ms=10_000)
        r = FleetRouter(["127.0.0.1:1"], cfg)
        # admission accounting only: pin the queue by not draining it.
        # With a live dispatcher the pop (which releases the popped
        # request's unit) races the submits on a loaded box, and the
        # watermark trip becomes scheduling-dependent
        r._dispatch_loop = lambda: None
        r.start()
        try:
            for i in range(2):  # low watermark = round(0.5*4) = 2
                r.submit({"x": [1.0]}, cost_class="low",
                         deadline_ms=60_000)
            with pytest.raises(RequestShed):
                r.submit({"x": [1.0]}, cost_class="low",
                         deadline_ms=60_000)
        finally:
            r.stop()


# -- fleet stream failover ----------------------------------------------------

class _Fleet:
    def __init__(self, n=2, **cfg_kw):
        self.engines, self.servers, eps = [], [], []
        for _ in range(n):
            eng = DecodeEngine(DecodeConfig(
                kv_blocks=256, max_tokens_cap=1024,
                eos_token=None)).start()
            srv, _t = start_http_server(eng, port=0)
            self.engines.append(eng)
            self.servers.append(srv)
            eps.append("127.0.0.1:%d" % srv.server_address[1])
        cfg_kw.setdefault("health_interval_ms", 50)
        cfg_kw.setdefault("request_timeout_s", 60)
        cfg_kw.setdefault("stream_stall_s", 1.0)
        self.router = FleetRouter(eps, FleetConfig(**cfg_kw)).start()
        deadline = time.monotonic() + 5
        while (self.router.healthy_count() < n
               and time.monotonic() < deadline):
            time.sleep(0.02)

    def kill_active(self):
        for j, eng in enumerate(self.engines):
            if eng.health_doc()["active_streams"] > 0:
                self.servers[j].shutdown()
                eng.stop(drain=False)
                return j
        return None

    def close(self):
        self.router.stop()
        for s, e in zip(self.servers, self.engines):
            try:
                s.shutdown()
            except Exception:
                pass
            try:
                e.stop(drain=False)
            except Exception:
                pass


class TestFleetStreaming:
    def test_probe_learns_engine_kind(self):
        f = _Fleet(n=1)
        try:
            deadline = time.monotonic() + 3
            while (f.router.replicas[0].kind != "decode"
                   and time.monotonic() < deadline):
                time.sleep(0.02)
            snap = f.router.replicas[0].snapshot()
            assert snap["kind"] == "decode"
            assert snap["kv_occupancy"] is not None
        finally:
            f.close()

    def test_exactly_once_failover_bit_identical(self):
        """Kill the replica mid-stream: the router resumes on the
        survivor with zero lost, zero duplicated, zero diverged
        tokens — the failover contract the chaos drill asserts across
        processes."""
        f = _Fleet(n=2)
        try:
            n = 300
            got, fin = [], None
            killed = None
            for ev in f.router.generate([5, 6, 7], max_tokens=n,
                                        request_id="f1"):
                if ev["type"] == "token":
                    got.append(ev)
                    if len(got) == 5 and killed is None:
                        killed = f.kill_active()
                else:
                    fin = ev
            assert killed is not None
            assert [t["index"] for t in got] == list(range(n))
            assert fin["reason"] == "max_tokens"
            # same prompt on the survivor reproduces the stream
            # bit-for-bit: the spliced failover stream is the TRUE one
            redo = list(f.router.generate([5, 6, 7], max_tokens=n,
                                          request_id="f2"))
            assert [t["token"] for t in got] == \
                [x["token"] for x in redo if x["type"] == "token"]
            st = f.router.stats()
            assert st.get(sm.STREAM_RESUMES, 0) >= 1
        finally:
            f.close()

    def test_http_front_streams_via_fleet(self):
        """HTTP front mounted ON the router: /generate proxies the
        fleet's token-level stream, /healthz carries queue state."""
        f = _Fleet(n=1)
        front, _t = start_http_server(f.router, port=0)
        base = "http://127.0.0.1:%d" % front.server_address[1]
        try:
            body = json.dumps({"prompt": [9, 8], "max_tokens": 4}
                              ).encode()
            req = urllib.request.Request(base + "/generate", data=body,
                                         method="POST")
            with urllib.request.urlopen(req, timeout=30) as resp:
                evs = [json.loads(ln) for ln in resp if ln.strip()]
            toks = [e for e in evs if e["type"] == "token"]
            assert [t["index"] for t in toks] == [0, 1, 2, 3]
            assert evs[-1]["reason"] == "max_tokens"
        finally:
            front.shutdown()
            f.close()
