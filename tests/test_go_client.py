"""Go inference client over the C ABI (reference go/paddle/predictor.go).

The dev image has no Go toolchain (environment contract), so the build+
run path SKIPS without `go`; the binding source itself is still checked
for ABI drift against csrc/capi.cc either way.
"""
import os
import re
import shutil
import subprocess

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_go_binding_matches_c_abi():
    """Every extern symbol the Go client declares must exist in
    capi.cc with the same name (catches ABI drift without a Go
    toolchain)."""
    go_src = open(os.path.join(REPO, "go/paddle/predictor.go")).read()
    c_src = open(os.path.join(REPO, "csrc/capi.cc")).read()
    declared = set(re.findall(r"C\.(PD_[A-Za-z]+)\(", go_src))
    assert declared, "no PD_ symbols referenced by the Go client?"
    for sym in declared:
        assert sym in c_src, "Go client references %s absent from capi.cc" % sym


@pytest.mark.skipif(shutil.which("go") is None,
                    reason="no Go toolchain in this image")
def test_go_smoke_runs(tmp_path):
    import paddle_tpu as fluid
    from paddle_tpu import models

    B = 4
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data(name="x", shape=[B, 8], dtype="float32")
        pred = fluid.layers.fc(x, 3, act="softmax")
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        fluid.io.save_inference_model(str(tmp_path / "model"), ["x"],
                                      [pred], exe, main_program=main)
    build = subprocess.run(["bash", os.path.join(REPO, "go/build.sh")],
                           capture_output=True, text=True, timeout=600)
    assert build.returncode == 0, build.stderr[-2000:]
    run = subprocess.run(
        [os.path.join(REPO, "go/smoke/smoke"),
         str(tmp_path / "model"), "x", "%d,8" % B],
        capture_output=True, text=True, timeout=300)
    assert run.returncode == 0, run.stderr[-2000:]
    assert run.stdout.startswith("OK n=%d" % (B * 3)), run.stdout
    # softmax rows sum to 1 -> total == batch size
    total = float(run.stdout.split("sum=")[1])
    assert abs(total - B) < 1e-3, run.stdout
