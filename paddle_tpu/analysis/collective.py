"""Cross-rank collective-schedule consistency checking.

SPMD correctness rests on one invariant: **every rank of a mesh issues
the SAME sequence of collectives** — same order, same kind, same
ring/axis, same payload element count and dtype. A rank-divergent
order deadlocks (each rank blocks in a different collective); a
divergent payload silently corrupts (psum over misaligned buffers).
This module extracts each rank's static collective schedule from a
(rewritten) Program and checks:

- **single-program form** (the engine's first-run path): no collective
  may live under a ``while``/``conditional_block`` sub-block (a
  conditional collective is divergence waiting on data), and no
  payload may be reduced twice with no intervening write (a
  double-psum multiplies the value by nranks — would-corrupt);
- **cross-rank form** (``check_cross_rank``): one schedule (or
  program) per rank, compared position-by-position; the first
  divergence is reported with BOTH ops named — kind/order/ring
  mismatches classify as would-DEADLOCK, payload numel/dtype
  mismatches as would-CORRUPT.

``schedule_record`` packages the single-program check plus a schedule
digest for bench artifacts: two processes that should be running the
same plan can compare digests without shipping programs around.
"""
from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Sequence, Tuple

from .verifier import Finding, IRVerificationError, ERROR

__all__ = ["CollectiveSig", "CollectiveMismatchError",
           "extract_collective_schedule", "check_collective_schedule",
           "check_cross_rank", "schedule_record"]

# collective families that move payload; the stream-sync / comm-setup
# host ops (c_sync_*, c_gen_nccl_id, c_comm_init) carry none and are
# excluded — they cannot deadlock a mesh by themselves
_PAYLOAD_PREFIXES = ("c_allreduce", "c_bucket_allreduce",
                     "c_sharded_update", "c_broadcast", "c_allgather",
                     "c_reducescatter", "c_concat", "c_alltoall",
                     "c_sharded_lookup", "c_ring_attention")
_PAYLOAD_TYPES = ("allreduce", "broadcast")  # legacy op names


class CollectiveMismatchError(IRVerificationError):
    """Rank-divergent collective schedule: ``.kind`` is
    ``"would-deadlock"`` or ``"would-corrupt"``; ``.pair`` holds the
    two diverging (rank, position, sig) descriptions."""

    def __init__(self, message, kind="would-deadlock", pair=(),
                 findings=()):
        self.kind = kind
        self.pair = tuple(pair)
        super().__init__(message, findings)


class CollectiveSig:
    """One collective's schedule-relevant identity."""

    __slots__ = ("pos", "op_index", "op_type", "ring", "axis", "numel",
                 "dtype", "members")

    def __init__(self, pos, op_index, op_type, ring, axis, numel, dtype,
                 members):
        self.pos = pos            # position in the collective sequence
        self.op_index = op_index  # position in the block's op list
        self.op_type = op_type
        self.ring = ring          # ring_id attr (mesh axis id)
        self.axis = axis          # explicit shard_axis attr, if any
        self.numel = numel        # total payload elements (None=unknown)
        self.dtype = dtype
        self.members = members    # payload var count (bucket width)

    def key(self) -> Tuple:
        return (self.op_type, self.ring, self.axis, self.numel,
                self.dtype, self.members)

    def __str__(self):
        return ("%s(#%d: ring=%s%s, %s x %s elems, %d member%s)"
                % (self.op_type, self.op_index, self.ring,
                   ", axis=%s" % self.axis if self.axis else "",
                   self.dtype, self.numel, self.members,
                   "s" if self.members != 1 else ""))

    __repr__ = __str__


def _is_payload_collective(op_type: str) -> bool:
    if op_type.endswith("_await"):
        # the await half of an async pair slices a Pending buffer back
        # into its members — the wire payload (and the deadlock
        # surface) belongs to the matching _start op
        return False
    return (op_type.startswith(_PAYLOAD_PREFIXES)
            or op_type in _PAYLOAD_TYPES)


def _payload_names(op) -> List[str]:
    for slot in ("X", "Grad", "Q"):
        names = op.input(slot)
        if names:
            return [n for n in names if n]
    return [n for n in op.input_arg_names if n]


def _collectives_in_block(block) -> List[Tuple[int, str]]:
    """(op index, op type) of payload collectives anywhere under a
    block, recursing through nested sub-blocks."""
    out = []
    for i, op in enumerate(block.ops):
        if _is_payload_collective(op.type):
            out.append((i, op.type))
        sb = op.attrs.get("sub_block")
        if sb is not None:
            out.extend(_collectives_in_block(sb))
    return out


def extract_collective_schedule(program, scope=None
                                ) -> Tuple[List[CollectiveSig],
                                           List[Finding]]:
    """The static sequence of payload collectives the program's global
    block issues, plus findings for collectives hiding under
    conditional sub-blocks (which this schedule CANNOT represent — on
    a rank where the branch goes the other way the sequence differs)."""
    from ..parallel.collectives import _numel_and_dtype

    block = program.global_block()
    sigs: List[CollectiveSig] = []
    findings: List[Finding] = []
    for i, op in enumerate(block.ops):
        sb = op.attrs.get("sub_block")
        if sb is not None:
            for j, t in _collectives_in_block(sb):
                findings.append(Finding(
                    "conditional-collective", ERROR, block.idx, i,
                    op.type,
                    "collective %r (sub-block %d op #%d) executes "
                    "under a data-dependent branch — ranks taking "
                    "different branches issue different schedules "
                    "(would deadlock)" % (t, sb.idx, j)))
        if not _is_payload_collective(op.type):
            continue
        names = _payload_names(op)
        total = 0
        dtype = None
        unknown = False
        for n in names:
            k, dt = _numel_and_dtype(block, scope, n)
            if k is None:
                unknown = True
            else:
                total += k
            dtype = dtype or dt
        sigs.append(CollectiveSig(
            pos=len(sigs), op_index=i, op_type=op.type,
            ring=op.attrs.get("ring_id", 0),
            axis=op.attrs.get("shard_axis") or None,
            numel=None if unknown else total,
            dtype=dtype, members=len(names)))
    return sigs, findings


def _double_reduce_findings(program) -> List[Finding]:
    """An in-place psum applied twice to the same var with no
    non-collective write in between multiplies it by nranks."""
    block = program.global_block()
    findings: List[Finding] = []
    reduce_ops = ("c_allreduce", "c_bucket_allreduce")
    last_reduced_at: Dict[str, int] = {}
    for i, op in enumerate(block.ops):
        if op.type == "c_bucket_allreduce_await":
            # the await WRITES the reduced value its start produced —
            # neither a second reduction nor a mark-clearing fresh
            # write (a later re-reduce of the same grad must still
            # flag, so the start's mark survives the await)
            continue
        if op.type.startswith(reduce_ops):
            for n in _payload_names(op):
                prev = last_reduced_at.get(n)
                if prev is not None:
                    findings.append(Finding(
                        "double-reduce", ERROR, block.idx, i, op.type,
                        "%r is reduced again (already reduced by op "
                        "#%d %s, not rewritten since) — the payload "
                        "would be scaled by nranks twice (would "
                        "corrupt)" % (n, prev, block.ops[prev].type)))
                last_reduced_at[n] = i
        else:
            for n in op.output_arg_names:
                last_reduced_at.pop(n, None)
    return findings


def schedule_digest(sigs: Sequence[CollectiveSig]) -> str:
    h = hashlib.sha1()
    for s in sigs:
        h.update(repr(s.key()).encode())
    return h.hexdigest()


def check_collective_schedule(program, nranks: Optional[int] = None,
                              where: str = "", scope=None
                              ) -> List[CollectiveSig]:
    """Single-program form: extract the schedule and raise
    ``CollectiveMismatchError`` on conditional collectives or
    double-reduce hazards. Under SPMD every rank traces this same
    program, so a clean single-program schedule IS the cross-rank
    proof for a single-process mesh; multi-process meshes compare
    ``schedule_digest`` across processes instead."""
    sigs, findings = extract_collective_schedule(program, scope=scope)
    if nranks is not None and nranks <= 1:
        return sigs  # a one-rank "mesh" cannot diverge from itself
    findings += _double_reduce_findings(program)
    errors = [f for f in findings if f.severity == ERROR]
    if errors:
        # a pure double-reduce hazard corrupts (every rank still issues
        # the same sequence); any conditional collective can deadlock
        kind = ("would-corrupt"
                if all(f.invariant == "double-reduce" for f in errors)
                else "would-deadlock")
        raise CollectiveMismatchError(
            "collective schedule%s is rank-divergence-unsafe:\n  %s"
            % (" (%s)" % where if where else "",
               "\n  ".join(str(f) for f in errors)),
            kind=kind, findings=findings)
    return sigs


def _as_schedule(entry, scope=None) -> List[CollectiveSig]:
    if isinstance(entry, (list, tuple)):
        return list(entry)
    sigs, _ = extract_collective_schedule(entry, scope=scope)
    return sigs


def check_cross_rank(per_rank, where: str = "", scope=None) -> int:
    """Cross-rank form: ``per_rank`` is one schedule (or Program) per
    rank. Verifies all ranks would issue an identical collective
    sequence; raises ``CollectiveMismatchError`` naming the diverging
    op pair otherwise. Returns the common schedule length."""
    scheds = [_as_schedule(e, scope=scope) for e in per_rank]
    if not scheds:
        return 0
    ref = scheds[0]
    for r, sched in enumerate(scheds[1:], start=1):
        n = min(len(ref), len(sched))
        for k in range(n):
            a, b = ref[k], sched[k]
            if a.key() == b.key():
                continue
            same_op = (a.op_type == b.op_type and a.ring == b.ring
                       and a.axis == b.axis)
            kind = "would-corrupt" if same_op else "would-deadlock"
            consequence = (
                "payload mismatch silently corrupts the reduction"
                if same_op else
                "ranks block inside DIFFERENT collectives — deadlock")
            raise CollectiveMismatchError(
                "collective schedule%s diverges at position %d: "
                "rank 0 issues %s but rank %d issues %s — %s"
                % (" (%s)" % where if where else "", k, a, r, b,
                   consequence),
                kind=kind, pair=((0, k, a), (r, k, b)))
        if len(ref) != len(sched):
            rr, extra = (0, ref[n]) if len(ref) > len(sched) \
                else (r, sched[n])
            raise CollectiveMismatchError(
                "collective schedule%s diverges: rank %d issues %d "
                "collectives but rank %d issues %d — first unmatched "
                "op is rank %d's %s; the other rank never enters it "
                "(deadlock)"
                % (" (%s)" % where if where else "", 0, len(ref), r,
                   len(sched), rr, extra),
                kind="would-deadlock",
                pair=((rr, n, extra),))
    return len(ref)


def schedule_record(program, nranks: Optional[int] = None, scope=None
                    ) -> Dict:
    """Bench-artifact form: run the single-program check and return a
    JSON-able record (ok flag, schedule length, digest, and the error
    text when not ok) instead of raising — bench runs should report,
    not crash."""
    try:
        sigs = check_collective_schedule(program, nranks=nranks,
                                         scope=scope)
    except CollectiveMismatchError as e:
        sigs, _ = extract_collective_schedule(program, scope=scope)
        return {"ok": False, "kind": e.kind, "error": str(e)[:2000],
                "n_collectives": len(sigs),
                "digest": schedule_digest(sigs)}
    return {"ok": True, "n_collectives": len(sigs),
            "digest": schedule_digest(sigs)}
