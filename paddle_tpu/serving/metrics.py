"""Serving metric names + always-on recording helpers.

Unlike the training hot paths (which guard every instrumentation site
behind ``observability.enabled()`` because a step is microseconds of
host work), serving requests are milliseconds-scale network round trips
— a handful of dict lookups per request is noise. Serving therefore
records UNCONDITIONALLY into the process registry so ``GET /metrics``,
``ServingEngine.stats()`` and the CI smoke always see live numbers
without the operator remembering to export ``PADDLE_TPU_METRICS``.

Families (README "Serving"):

=================================  =======================================
``serving.requests``               counter: admitted requests
``serving.rejected``               counter: admission-control rejections
``serving.deadline_expired``       counter: dropped before dispatch
``serving.errors``                 counter: dispatch failures (per req)
``serving.batch_errors``           counter: predictor-failed batches
``serving.batches``                counter: dispatched micro-batches
``serving.padding_waste``          counter: padded rows (bucket - real)
``serving.batch_size``             histogram: real rows per micro-batch
``serving.queue_ms``               histogram: submit -> dispatch wait
``serving.total_ms``               histogram: submit -> result latency
``serving.queue_depth``            gauge: requests waiting right now
=================================  =======================================

Handles are re-fetched from the registry on every write (get-or-create
is a dict lookup) instead of cached at import: ``observability.reset()``
swaps the metric objects out from under any cached handle, and serving
must keep reporting into the registry a dump actually reads.
"""
from __future__ import annotations

from .. import observability as _obs

__all__ = [
    "REQUESTS", "REJECTED", "DEADLINE_EXPIRED", "ERRORS",
    "BATCH_ERRORS", "BATCHES", "PADDING_WASTE", "BATCH_SIZE",
    "QUEUE_MS", "TOTAL_MS", "QUEUE_DEPTH",
    "inc", "observe", "set_queue_depth", "snapshot",
]

REQUESTS = "serving.requests"
REJECTED = "serving.rejected"
DEADLINE_EXPIRED = "serving.deadline_expired"
ERRORS = "serving.errors"
BATCH_ERRORS = "serving.batch_errors"
BATCHES = "serving.batches"
PADDING_WASTE = "serving.padding_waste"
BATCH_SIZE = "serving.batch_size"
QUEUE_MS = "serving.queue_ms"
TOTAL_MS = "serving.total_ms"
QUEUE_DEPTH = "serving.queue_depth"


def inc(name: str, n: int = 1) -> None:
    _obs.counter(name).inc(n)


def observe(name: str, v) -> None:
    _obs.histogram(name).observe(v)


def set_queue_depth(n: int) -> None:
    _obs.gauge(QUEUE_DEPTH).set(n)


def snapshot() -> dict:
    """Current serving counters/latencies as a plain dict (the
    ``ServingEngine.stats()`` payload)."""
    out = {}
    for name in (REQUESTS, REJECTED, DEADLINE_EXPIRED, ERRORS,
                 BATCH_ERRORS, BATCHES, PADDING_WASTE):
        out[name] = _obs.counter_value(name)
    out[QUEUE_DEPTH] = _obs.gauge_value(QUEUE_DEPTH)
    for name in (BATCH_SIZE, QUEUE_MS, TOTAL_MS):
        out[name] = _obs.histogram(name).snapshot()
    return out
