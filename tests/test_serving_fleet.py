"""Replica-fleet front end (paddle_tpu/serving/fleet.py): shared
admission control, cost-class load shedding with priority lanes,
health-checked routing (draining beats connection-refusal), bounded
hedged retries with exactly-once semantics, deadline inheritance, and
the HTTP front over a fleet.

Replicas here are REAL loopback HTTP servers over stub predictors —
the fleet's transport, fault hooks, and lifecycle probing run exactly
as in production; only the model is a stub. The multi-process drill
(SIGKILL + supervisor relaunch + merged telemetry) lives in
``tools/serving_chaos.py`` (CI gate 8).
"""
import http.server
import json
import socket
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from paddle_tpu import observability as obs
from paddle_tpu import serving
from paddle_tpu.distributed import fault


@pytest.fixture(autouse=True)
def _clean_registry():
    obs.reset()
    obs.enable()
    yield
    obs.reset()
    obs.disable()


class _StubTensor:
    def __init__(self, name, data):
        self.name, self.data = name, data


class _StubPredictor:
    """y = 2x, optional per-dispatch delay (drives hedge/overload
    determinism)."""

    def __init__(self, delay=0.0):
        self.delay = delay
        self.calls = []

    def get_input_names(self):
        return ["x"]

    def run(self, feed):
        if self.delay:
            time.sleep(self.delay)
        x = np.asarray(feed["x"])
        self.calls.append(x.shape[0])
        return [_StubTensor("y", x * 2.0)]


def _replica(delay=0.0, **cfg):
    cfg.setdefault("max_batch_size", 8)
    cfg.setdefault("num_workers", 2)
    cfg.setdefault("warmup", False)
    stub = _StubPredictor(delay)
    eng = serving.ServingEngine(
        stub, serving.ServingConfig(**cfg),
        sample_feed={"x": np.zeros((1, 3), "float32")}).start()
    srv, _ = serving.start_http_server(eng)
    host, port = srv.server_address
    return eng, srv, stub, "%s:%d" % (host, port)


@pytest.fixture()
def two_replicas():
    reps = [_replica(), _replica()]
    yield reps
    for eng, srv, _, _ in reps:
        try:
            srv.shutdown()
            srv.server_close()
        except OSError:
            pass
        eng.stop()


def _router(endpoints, **cfg):
    cfg.setdefault("max_queue", 32)
    cfg.setdefault("num_dispatchers", 4)
    cfg.setdefault("health_interval_ms", 40)
    cfg.setdefault("hedge_after_ms", 100)
    return serving.FleetRouter(endpoints,
                               serving.FleetConfig(**cfg)).start()


X = np.arange(6, dtype="float32").reshape(2, 3)


# -- config ------------------------------------------------------------------

def test_fleet_config_validation():
    with pytest.raises(ValueError, match="admit fraction"):
        serving.FleetConfig(cost_classes=[("a", 0.0)])
    with pytest.raises(ValueError, match="duplicate"):
        serving.FleetConfig(cost_classes=[("a", 1.0), ("a", 0.5)])
    with pytest.raises(ValueError, match="default_class"):
        serving.FleetConfig(default_class="nope")
    cfg = serving.FleetConfig(max_queue=100)
    assert cfg.admit_depth("high") == 100
    assert cfg.admit_depth("low") == 50
    assert cfg.class_rank("high") < cfg.class_rank("low")
    with pytest.raises(ValueError, match="unknown cost class"):
        cfg.class_rank("bulk")


# -- routing + results -------------------------------------------------------

def test_fleet_roundtrip_and_spread(two_replicas):
    eps = [r[3] for r in two_replicas]
    fr = _router(eps)
    try:
        for _ in range(12):
            out = fr.predict({"x": X}, timeout=10)
            np.testing.assert_array_equal(out["y"], X * 2)
        served = {r["endpoint"]: r["served"]
                  for r in fr.stats()["replicas"]}
        # least-inflight + round-robin: both replicas took traffic
        assert all(v > 0 for v in served.values()), served
    finally:
        fr.stop()


def test_fleet_unknown_cost_class_rejected(two_replicas):
    fr = _router([r[3] for r in two_replicas])
    try:
        with pytest.raises(ValueError, match="unknown cost class"):
            fr.submit({"x": X}, cost_class="bulk")
    finally:
        fr.stop()


def test_fleet_admission_hard_bound():
    """A full shared queue rejects with typed ServerOverloaded (not a
    shed) and counts serving.rejected."""
    eng, srv, _, ep = _replica(delay=0.2, num_workers=1,
                               max_batch_size=1)
    fr = _router([ep], max_queue=2, num_dispatchers=1,
                 cost_classes=[("only", 1.0)], hedge_after_ms=None)
    try:
        futures, rejected = [], 0
        for _ in range(12):
            try:
                futures.append(fr.submit({"x": np.ones((1, 3), "f4")},
                                         cost_class="only"))
            except serving.RequestShed:
                pytest.fail("hard bound must raise ServerOverloaded, "
                            "not RequestShed")
            except serving.ServerOverloaded:
                rejected += 1
        assert rejected > 0
        assert obs.counter_value("serving.rejected") == rejected
        for f in futures:
            f.result(30)
    finally:
        fr.stop()
        srv.shutdown()
        eng.stop()


def test_fleet_shed_by_class_under_overload():
    """The acceptance property: under a synthetic burst the LOW lane
    sheds strictly more than the HIGH lane, high admits outnumber low
    admits, and sheds are typed + counted per class."""
    eng, srv, _, ep = _replica(delay=0.05, num_workers=1,
                               max_batch_size=4)
    fr = _router([ep], max_queue=12, num_dispatchers=2,
                 hedge_after_ms=None)
    try:
        shed = {"high": 0, "normal": 0, "low": 0}
        admitted = dict(shed)
        futures = []
        classes = ("high", "normal", "low")
        for i in range(90):
            cls = classes[i % 3]
            try:
                futures.append(fr.submit({"x": np.ones((1, 3), "f4")},
                                         cost_class=cls))
                admitted[cls] += 1
            except serving.RequestShed:
                shed[cls] += 1
            except serving.ServerOverloaded:
                shed[cls] += 1
        for f in futures:
            f.result(60)
        assert shed["low"] > shed["high"], (shed, admitted)
        assert admitted["high"] > admitted["low"], (shed, admitted)
        # typed + labeled: the watermark sheds are per-class counters
        assert obs.counter_value("serving.shed",
                                 **{"class": "low"}) > 0
    finally:
        fr.stop()
        srv.shutdown()
        eng.stop()


def test_fleet_priority_lane_dispatch_order():
    """Admitted high-priority work leaves the queue before admitted
    low-priority work that arrived EARLIER."""
    eng, srv, stub, ep = _replica(delay=0.05, num_workers=1,
                                  max_batch_size=1)
    fr = _router([ep], num_dispatchers=1, hedge_after_ms=None)
    try:
        order = []
        lock = threading.Lock()

        def track(f, tag):
            f.add_done_callback(
                lambda _: (lock.acquire(), order.append(tag),
                           lock.release()))

        # occupy the single dispatcher, then queue low before high
        busy = fr.submit({"x": np.ones((1, 3), "f4")},
                         cost_class="high")
        track(fr.submit({"x": np.ones((1, 3), "f4")},
                        cost_class="low"), "low")
        track(fr.submit({"x": np.ones((1, 3), "f4")},
                        cost_class="high"), "high")
        busy.result(10)
        deadline = time.monotonic() + 10
        while len(order) < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert order == ["high", "low"], order
    finally:
        fr.stop()
        srv.shutdown()
        eng.stop()


# -- deadlines ---------------------------------------------------------------

def test_fleet_queue_expiry_is_typed_not_silent():
    """A request whose deadline passes while QUEUED fails with the
    typed DeadlineExpired (counted) — never dispatched, never
    silently dropped."""
    eng, srv, stub, ep = _replica(delay=0.25, num_workers=1,
                                  max_batch_size=1)
    fr = _router([ep], num_dispatchers=1, hedge_after_ms=None)
    try:
        busy = fr.submit({"x": np.ones((1, 3), "f4")})  # occupies
        doomed = fr.submit({"x": np.ones((1, 3), "f4")},
                           deadline_ms=30)
        with pytest.raises(serving.DeadlineExpired, match="queued"):
            doomed.result(10)
        busy.result(10)
        assert obs.counter_value("serving.deadline_expired") >= 1
        # the doomed request never generated a dispatch
        assert len(stub.calls) <= 2
    finally:
        fr.stop()
        srv.shutdown()
        eng.stop()


class _RecordingReplica(threading.Thread):
    """A bare HTTP replica that RECORDS each /predict body (the
    deadline the fleet actually sent) and can stall before answering —
    the probe for deadline inheritance and hedge behavior."""

    def __init__(self, stall_s=0.0, healthz="serving"):
        super().__init__(daemon=True)
        self.bodies = []
        self.stall_s = stall_s
        outer = self

        class H(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # noqa: A003
                pass

            def do_GET(self):
                body = json.dumps({"status": healthz}).encode()
                code = 200 if healthz == "serving" else 503
                self.send_response(code)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                doc = json.loads(self.rfile.read(n) or b"{}")
                outer.bodies.append(doc)
                if outer.stall_s:
                    time.sleep(outer.stall_s)
                x = np.asarray(doc["inputs"]["x"], "float32")
                body = json.dumps(
                    {"outputs": {"y": (x * 2).tolist()}}).encode()
                try:
                    self.send_response(200)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except OSError:
                    pass  # hedge loser: client already hung up

        self.server = http.server.ThreadingHTTPServer(
            ("127.0.0.1", 0), H)
        self.server.daemon_threads = True
        self.endpoint = "127.0.0.1:%d" % self.server.server_address[1]

    def run(self):
        self.server.serve_forever()

    def close(self):
        self.server.shutdown()
        self.server.server_close()


def test_hedged_attempt_inherits_remaining_deadline():
    """Satellite 1: the hedge's wire deadline_ms must be the REMAINING
    budget at hedge time — strictly below the original attempt's."""
    slow = _RecordingReplica(stall_s=0.5)
    fast = _RecordingReplica(stall_s=0.0)
    slow.start()
    fast.start()
    fr = _router([slow.endpoint, fast.endpoint], num_dispatchers=1,
                 hedge_after_ms=80, max_hedges=1)
    try:
        out = fr.predict({"x": np.ones((1, 3), "f4")},
                         deadline_ms=5000, timeout=10)
        assert out["y"].shape == (1, 3)
        deadline = time.monotonic() + 5
        while not (slow.bodies and fast.bodies) \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        assert slow.bodies and fast.bodies, "hedge never fired"
        first = slow.bodies[0]["deadline_ms"]
        hedge = fast.bodies[0]["deadline_ms"]
        # the hedge launched >= 80ms later: its inherited budget must
        # be visibly smaller than the original's
        assert hedge < first - 50, (first, hedge)
        assert obs.counter_value("serving.hedges") == 1
    finally:
        fr.stop()
        slow.close()
        fast.close()


# -- hedging + exactly-once --------------------------------------------------

def test_fleet_hedge_straggler_exactly_once(two_replicas):
    """One replica straggles: the hedge wins on the other, the result
    surfaces EXACTLY once with correct values, and the request counts
    once on the fleet."""
    slow = _RecordingReplica(stall_s=1.0)
    slow.start()
    _, _, _, fast_ep = two_replicas[0]
    fr = _router([slow.endpoint, fast_ep], num_dispatchers=1,
                 hedge_after_ms=60, max_hedges=1)
    try:
        results = []
        f = fr.submit({"x": X}, deadline_ms=8000)
        f.add_done_callback(lambda fut: results.append(fut.result()))
        out = f.result(10)
        np.testing.assert_array_equal(out["y"], X * 2)
        time.sleep(0.1)
        assert len(results) == 1          # the latch: one surface, ever
        assert obs.counter_value("serving.hedges") >= 1
        # in-process registries are SHARED: 1 fleet admission + 1
        # winning-replica engine execution (the straggler is a
        # recording stub with no engine) — exactly 2, never 3
        assert obs.counter_value("serving.requests") == 2
    finally:
        fr.stop()
        slow.close()


def test_fleet_request_id_dedup(two_replicas):
    """Duplicate submits with one request id join the original future
    and never double-count."""
    fr = _router([r[3] for r in two_replicas])
    try:
        f1 = fr.submit({"x": X}, request_id="req-7")
        f2 = fr.submit({"x": X}, request_id="req-7")
        assert f1 is f2
        f1.result(10)
        # a LATE duplicate (original already done) still joins it
        f3 = fr.submit({"x": X}, request_id="req-7")
        assert f3 is f1
        # shared in-process registry: 1 fleet admission + 1 replica
        # engine execution; the duplicates joined, they never re-ran
        assert obs.counter_value("serving.requests") == 2
        assert obs.counter_value("serving.dedup_hits") == 2
    finally:
        fr.stop()


def test_engine_request_id_dedup_never_reruns_predictor():
    """Replica half of exactly-once: a duplicate DELIVERY (hedge, dup
    frame, retry) joins the original execution — the predictor runs
    once, even after the original completed."""
    stub = _StubPredictor()
    eng = serving.ServingEngine(
        stub, serving.ServingConfig(max_batch_size=4, num_workers=1,
                                    warmup=False),
        sample_feed={"x": np.zeros((1, 3), "float32")}).start()
    try:
        f1 = eng.submit({"x": X}, request_id="r1")
        f2 = eng.submit({"x": X}, request_id="r1")
        assert f1 is f2
        out = f1.result(10)
        np.testing.assert_array_equal(out["y"], X * 2)
        # late duplicate after completion: joined from the LRU, not
        # re-executed
        f3 = eng.submit({"x": X}, request_id="r1")
        assert f3 is f1
        assert len(stub.calls) == 1
        assert obs.counter_value("serving.requests") == 1
        assert obs.counter_value("serving.dedup_hits") == 2
    finally:
        eng.stop()


# -- health-checked routing --------------------------------------------------

def test_fleet_retry_on_dead_replica_and_ejection(two_replicas):
    """A replica whose socket refuses connections: requests still
    succeed via retry on the survivor, and the corpse is ejected in
    bounded time with cause=dead."""
    (e1, s1, _, ep1), (_, _, _, ep2) = two_replicas
    s1.shutdown()
    s1.server_close()
    e1.stop()
    fr = _router([ep1, ep2], eject_after=2, hedge_after_ms=None,
                 max_attempts=4)
    try:
        for _ in range(6):
            out = fr.predict({"x": X}, timeout=10)
            np.testing.assert_array_equal(out["y"], X * 2)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            states = {r["endpoint"]: r["state"]
                      for r in fr.stats()["replicas"]}
            if states[ep1] == "dead":
                break
            time.sleep(0.05)
        assert states[ep1] == "dead", states
        assert obs.counter_value("serving.replica_ejections",
                                 cause="dead") >= 1
    finally:
        fr.stop()


def test_fleet_stops_routing_at_draining_not_refusal(two_replicas):
    """Satellite 2: the router reads the replica's machine-readable
    lifecycle — a DRAINING replica (socket still accepting!) leaves
    rotation proactively, and every subsequent request lands on the
    healthy one."""
    (e1, s1, stub1, ep1), (_, _, stub2, ep2) = two_replicas
    fr = _router([ep1, ep2], health_interval_ms=30)
    try:
        fr.predict({"x": X}, timeout=10)
        e1.stop()          # draining; its HTTP server still answers
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            states = {r["endpoint"]: r["state"]
                      for r in fr.stats()["replicas"]}
            if states[ep1] == "draining":
                break
            time.sleep(0.02)
        assert states[ep1] == "draining", states
        n2 = len(stub2.calls)
        for _ in range(6):
            out = fr.predict({"x": X}, timeout=10)
            np.testing.assert_array_equal(out["y"], X * 2)
        assert len(stub2.calls) >= n2 + 6   # all on the survivor
        assert obs.counter_value("serving.replica_ejections",
                                 cause="draining") == 1
    finally:
        fr.stop()


def test_fleet_rejoin_after_replacement():
    """An ejected endpoint whose process comes back (same port) is
    re-admitted by the prober and serves again — the relaunch half of
    the chaos drill, in-process."""
    eng1, srv1, _, ep1 = _replica()
    port = int(ep1.rsplit(":", 1)[1])
    eng2, srv2, _, ep2 = _replica()
    fr = _router([ep1, ep2], eject_after=2, health_interval_ms=30,
                 hedge_after_ms=None)
    try:
        fr.predict({"x": X}, timeout=10)
        # kill replica 1 hard
        srv1.shutdown()
        srv1.server_close()
        eng1.stop()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            st = {r["endpoint"]: r["state"]
                  for r in fr.stats()["replicas"]}
            if st[ep1] == "dead":
                break
            time.sleep(0.02)
        assert st[ep1] == "dead", st
        # "relaunch" it on the SAME endpoint
        stub = _StubPredictor()
        eng3 = serving.ServingEngine(
            stub, serving.ServingConfig(max_batch_size=8,
                                        num_workers=1, warmup=False),
            sample_feed={"x": np.zeros((1, 3), "float32")}).start()
        srv3 = serving.ServingHTTPServer(eng3, "127.0.0.1", port)
        t = threading.Thread(target=srv3.serve_forever, daemon=True)
        t.start()
        try:
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                st = {r["endpoint"]: r["state"]
                      for r in fr.stats()["replicas"]}
                if st[ep1] == "serving":
                    break
                time.sleep(0.02)
            assert st[ep1] == "serving", st
            assert obs.counter_value("serving.replica_rejoins") == 1
            # and it takes traffic again
            deadline = time.monotonic() + 5
            while not stub.calls and time.monotonic() < deadline:
                fr.predict({"x": X}, timeout=10)
            assert stub.calls
        finally:
            srv3.shutdown()
            srv3.server_close()
            eng3.stop()
    finally:
        fr.stop()
        srv2.shutdown()
        srv2.server_close()
        eng2.stop()


def test_fleet_no_replica_fails_typed():
    """Nothing routable and the budget gone: the typed
    ReplicaUnavailable, not a hang."""
    port = _free_port()
    fr = _router(["127.0.0.1:%d" % port], max_attempts=2,
                 hedge_after_ms=None, request_timeout_s=1.5,
                 eject_after=1000)  # keep it routable: test the
    # attempt path, not the eject path
    try:
        with pytest.raises((serving.ReplicaUnavailable,
                            serving.DeadlineExpired)):
            fr.predict({"x": X}, deadline_ms=800, timeout=10)
    finally:
        fr.stop()


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# -- faults on the fleet RPC path -------------------------------------------

def test_fleet_absorbs_injected_rpc_faults(two_replicas, monkeypatch):
    """drop/delay/close on the dispatch path: every request still
    succeeds (hedge/retry), faults are counted, nothing is lost."""
    monkeypatch.setenv("PADDLE_TPU_FAULTS",
                       "send.drop:0.15,any.delay:0.1:5,send.close:0.05")
    monkeypatch.setenv("PADDLE_TPU_FAULT_SEED", "7")
    fault.reset_injector()
    try:
        fr = _router([r[3] for r in two_replicas], hedge_after_ms=50,
                     max_attempts=6)
        try:
            for i in range(20):
                out = fr.predict({"x": X}, deadline_ms=10000,
                                 timeout=30)
                np.testing.assert_array_equal(out["y"], X * 2)
        finally:
            fr.stop()
        assert obs.counter_value("serving.errors") == 0
        injected = sum(
            m.value for m in obs.metrics().all_metrics()
            if m.kind == "counter"
            and m.qualified_name.startswith("fault.injected"))
        assert injected > 0
    finally:
        fault.reset_injector()


# -- HTTP front over a fleet -------------------------------------------------

@pytest.fixture()
def fleet_http(two_replicas):
    fr = _router([r[3] for r in two_replicas])
    server, _ = serving.start_http_server(fr)
    host, port = server.server_address
    yield fr, "http://%s:%d" % (host, port)
    server.shutdown()
    server.server_close()
    fr.stop()


def _post(url, payload, headers=()):
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(dict(headers))
    req = urllib.request.Request(url, json.dumps(payload).encode(),
                                 hdrs)
    with urllib.request.urlopen(req, timeout=15) as r:
        return r.status, json.loads(r.read())


def test_http_front_serves_fleet(fleet_http):
    fr, base = fleet_http
    with urllib.request.urlopen(base + "/healthz", timeout=10) as r:
        assert r.status == 200
        assert json.loads(r.read())["status"] == "serving"
    status, body = _post(base + "/predict",
                         {"inputs": {"x": [[1, 2, 3]]},
                          "cost_class": "low"},
                         headers=[("X-Request-Id", "http-1")])
    assert status == 200
    np.testing.assert_array_equal(np.asarray(body["outputs"]["y"]),
                                  [[2, 4, 6]])
    # bad cost_class type is a 400, not a 500
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(base + "/predict", {"inputs": {"x": [[1, 2, 3]]},
                                  "cost_class": 3})
    assert ei.value.code == 400


def test_http_fleet_deadline_expired_504_typed():
    """Satellite 1 end-to-end: a queued-expired fleet request surfaces
    as HTTP 504 with the machine-readable type."""
    port = _free_port()  # a black-hole replica: accepts, never answers
    sink = socket.socket()
    sink.bind(("127.0.0.1", port))
    sink.listen(8)
    fr = _router(["127.0.0.1:%d" % port], hedge_after_ms=None,
                 max_attempts=1, eject_after=1000)
    server, _ = serving.start_http_server(fr)
    host, hport = server.server_address
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post("http://%s:%d/predict" % (host, hport),
                  {"inputs": {"x": [[1, 2, 3]]}, "deadline_ms": 300})
        assert ei.value.code == 504
        body = json.loads(ei.value.read())
        assert body["type"] == "DeadlineExpired"
    finally:
        server.shutdown()
        server.server_close()
        fr.stop()
        sink.close()


def test_http_fleet_shed_503_typed(two_replicas):
    """A shed lane surfaces as 503 with type=RequestShed and a
    Retry-After — distinguishable from the hard bound."""
    eng, srv, _, ep = _replica(delay=0.2, num_workers=1,
                               max_batch_size=1)
    fr = _router([ep], max_queue=4, num_dispatchers=1,
                 hedge_after_ms=None)
    server, _ = serving.start_http_server(fr)
    host, hport = server.server_address
    base = "http://%s:%d" % (host, hport)
    try:
        shed_seen = None
        threads = []
        for i in range(10):
            t = threading.Thread(target=lambda: _try_post(base))
            t.start()
            threads.append(t)
        for i in range(20):
            try:
                _post(base + "/predict",
                      {"inputs": {"x": [[1, 2, 3]]},
                       "cost_class": "low"})
            except urllib.error.HTTPError as e:
                if e.code == 503:
                    body = json.loads(e.read())
                    if body.get("type") == "RequestShed":
                        shed_seen = (e.headers.get("Retry-After"), body)
                        break
        for t in threads:
            t.join(30)
        assert shed_seen is not None, "no RequestShed surfaced"
        assert shed_seen[0] == "1"
    finally:
        server.shutdown()
        server.server_close()
        fr.stop()
        srv.shutdown()
        eng.stop()


def _try_post(base):
    try:
        _post(base + "/predict", {"inputs": {"x": [[1, 2, 3]]},
                                  "cost_class": "high"})
    except Exception:  # noqa: BLE001 — saturation traffic; errors fine
        pass
