"""Closed-loop serving benchmark + CI smoke.

Measures what the serving subsystem exists to prove: dynamic batching
through ``ServingEngine`` beats sequential per-request
``PaddlePredictor.run()`` throughput once there is real concurrency,
while the bucket ladder keeps XLA compiles bounded by ``len(ladder)``
instead of one per observed batch size.

Usage:
    python tools/serving_bench.py                 # full bench table
    python tools/serving_bench.py --smoke         # fast CI assertions
    python tools/serving_bench.py --json out.json # also dump raw numbers
    python tools/serving_bench.py --smoke --out r.json
        # ALSO write a bench_diff-compatible serving record
        # ({"configs": {"serving_smoke": ...}, "counters_total": ...})
        # so ci/check.sh can diff serving perf run-over-run exactly
        # like the training smokes (gate 5c)
    python tools/serving_bench.py --decode --out r.json
        # continuous-batching decode smoke: mixed-length streams
        # through the DecodeEngine vs a static wait-for-all baseline
        # on the SAME model; asserts per-token scheduling wins on
        # tokens/s and every stream is exactly-once; the record
        # carries the decode SLO axes (ttft/itl percentiles,
        # tokens_per_s, kv_occupancy_frac, preemptions) gate 5c
        # watches run-over-run

The bench is CLOSED-LOOP: each of C client threads fires its next
request only after the previous one completes — the concurrency level,
not an open-loop arrival rate, is the independent variable. Request row
counts cycle 1..4 so observed batch sizes are deliberately ragged (the
worst case the bucket ladder exists to absorb).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu import observability as obs  # noqa: E402
from paddle_tpu import serving  # noqa: E402
from paddle_tpu.observability.registry import reservoir_quantile  # noqa: E402
from paddle_tpu.inference import (  # noqa: E402
    AnalysisConfig, create_paddle_predictor)

DIM = 64


def build_predictor(tmpdir, hidden=128, classes=10):
    """Train-free tiny MLP saved + loaded through the real inference
    path (so the bench exercises exactly what production serves)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data(name="x", shape=[-1, DIM], dtype="float32")
        h = fluid.layers.fc(x, hidden, act="relu")
        pred = fluid.layers.fc(h, classes, act="softmax")
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        fluid.io.save_inference_model(tmpdir, ["x"], [pred], exe,
                                      main_program=main)
    config = AnalysisConfig(tmpdir)
    config.disable_gpu()
    return create_paddle_predictor(config), pred.name


def make_requests(n, rng):
    """Ragged request stream: row counts cycle 1..4."""
    return [rng.rand(1 + i % 4, DIM).astype("float32") for i in range(n)]


def run_clients(n_clients, requests, fire):
    """Closed-loop drive: split `requests` across n_clients threads,
    each calling fire(arr) back-to-back. Returns (wall_s, latencies)."""
    latencies = []
    lat_lock = threading.Lock()
    errors = []
    chunks = [requests[i::n_clients] for i in range(n_clients)]

    def client(chunk):
        local = []
        try:
            for arr in chunk:
                t0 = time.perf_counter()
                fire(arr)
                local.append((time.perf_counter() - t0) * 1e3)
        except Exception as e:  # noqa: BLE001
            errors.append(repr(e))
        with lat_lock:
            latencies.extend(local)

    threads = [threading.Thread(target=client, args=(c,)) for c in chunks]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    if errors:
        raise RuntimeError("client errors: %s" % errors[:3])
    return wall, sorted(latencies)


def summarize(mode, wall, lats, rows):
    return {
        "mode": mode,
        "wall_s": round(wall, 4),
        "rows_per_s": round(rows / wall, 1),
        "p50_ms": round(reservoir_quantile(lats, 0.5), 3),
        "p99_ms": round(reservoir_quantile(lats, 0.99), 3),
        "requests": len(lats),
    }


def bench(n_requests=256, concurrencies=(1, 8, 16), json_path=None):
    obs.enable()
    results = []
    with tempfile.TemporaryDirectory() as d:
        predictor, _ = build_predictor(d)
        rng = np.random.RandomState(0)
        requests = make_requests(n_requests, rng)
        rows = sum(r.shape[0] for r in requests)

        # warm the direct path so the baseline isn't paying compiles
        for b in (1, 2, 3, 4):
            predictor.run({"x": np.zeros((b, DIM), "float32")})

        wall, lats = run_clients(1, requests,
                                 lambda a: predictor.run({"x": a}))
        baseline = summarize("sequential run()", wall, lats, rows)
        results.append(baseline)

        for c in concurrencies:
            engine = serving.ServingEngine(
                predictor,
                serving.ServingConfig(max_batch_size=16,
                                      batch_timeout_ms=2.0,
                                      max_queue=256,
                                      num_workers=2)).start()
            traces0 = obs.counter_value("executor.jit_traces")
            wall, lats = run_clients(c, requests,
                                     lambda a: engine.predict({"x": a}))
            traces = obs.counter_value("executor.jit_traces") - traces0
            engine.stop()
            row = summarize("engine c=%d" % c, wall, lats, rows)
            row["new_jit_traces"] = traces
            results.append(row)

    print("%-20s %10s %10s %10s %10s" % ("mode", "rows/s", "p50 ms",
                                         "p99 ms", "traces+"))
    for r in results:
        print("%-20s %10s %10s %10s %10s"
              % (r["mode"], r["rows_per_s"], r["p50_ms"], r["p99_ms"],
                 r.get("new_jit_traces", "-")))
    best = max(r["rows_per_s"] for r in results[1:])
    speedup = best / results[0]["rows_per_s"]
    print("best engine throughput = %.2fx sequential baseline" % speedup)
    if json_path:
        with open(json_path, "w") as f:
            json.dump({"results": results, "speedup": speedup}, f, indent=2)
        print("wrote %s" % json_path)
    return results


class _Throttled:
    """Same predictor, artificial per-dispatch latency — makes the
    admission-control smoke deterministic on arbitrarily fast hosts."""

    def __init__(self, inner, delay_s):
        self._inner = inner
        self._delay = delay_s

    def get_input_names(self):
        return self._inner.get_input_names()

    def run(self, feed):
        time.sleep(self._delay)
        return self._inner.run(feed)


def serving_record(wall, lats, rows, traces):
    """A bench_diff-compatible record of the smoke's burst phase: the
    throughput/latency row plus the serving.* registry families the
    perf gate watches (queue wait, real batch size, padding waste,
    compile count, shed/hedge counters)."""
    q = obs.histogram("serving.queue_ms").snapshot()
    b = obs.histogram("serving.batch_size").snapshot()
    padded = obs.counter_value("serving.padding_waste")
    dispatched = (b["sum"] or 0) + padded
    rec = {
        "rows_per_s": round(rows / wall, 1),
        "p50_ms": round(reservoir_quantile(lats, 0.5), 3),
        "p99_ms": round(reservoir_quantile(lats, 0.99), 3),
        "serving_queue_ms_p50": q.get("p50"),
        "serving_queue_ms_p99": q.get("p99"),
        "serving_batch_size_mean": b.get("mean"),
        # padded rows as a fraction of all DISPATCHED rows — the
        # ladder-tuning number, scale-free so run sizes can change
        "serving_padding_waste_frac": (
            round(padded / dispatched, 4) if dispatched else 0.0),
        "jit_traces": traces,
    }
    counters = {}
    for name in ("serving.requests", "serving.rejected",
                 "serving.errors", "serving.batch_errors",
                 "serving.batches", "serving.padding_waste",
                 "serving.deadline_expired", "serving.hedges",
                 "serving.fleet_retries", "serving.dedup_hits"):
        counters[name] = obs.counter_value(name)
    return {"configs": {"serving_smoke": rec},
            "counters_total": counters}


def smoke(out_path=None):
    """CI gate 5b: warmup bounds compiles to the ladder; 64 concurrent
    ragged requests add zero compiles and zero errors; an undersized
    queue actually rejects (backpressure engages). With ``out_path``
    also writes the bench_diff record gate 5c diffs run-over-run."""
    failures = []
    obs.reset()
    obs.enable()
    with tempfile.TemporaryDirectory() as d:
        predictor, out_name = build_predictor(d, hidden=32, classes=4)
        # delta from here: building the model itself runs the startup
        # program (one trace) that is not the serving path's doing
        traces0 = obs.counter_value("executor.jit_traces")
        engine = serving.ServingEngine(
            predictor,
            serving.ServingConfig(max_batch_size=8, batch_timeout_ms=2.0,
                                  max_queue=128, num_workers=2)).start()
        ladder = engine.config.policy.ladder
        traces = obs.counter_value("executor.jit_traces") - traces0
        if engine.warmed_buckets != ladder:
            failures.append("warmed %s != ladder %s"
                            % (engine.warmed_buckets, ladder))
        if traces != len(ladder):
            failures.append("jit traces after warmup = %d, want %d (one "
                            "per bucket)" % (traces, len(ladder)))

        rng = np.random.RandomState(1)
        requests = make_requests(64, rng)
        wall, lats = run_clients(64, requests,
                                 lambda a: engine.predict({"x": a}))
        traffic_traces = (obs.counter_value("executor.jit_traces")
                          - traces0 - traces)
        if traffic_traces:
            failures.append(
                "%d fresh compiles under bucketed traffic (observed "
                "batch sizes must map onto warmed buckets)"
                % traffic_traces)
        errs = obs.counter_value("serving.errors")
        if errs:
            failures.append("serving.errors = %d" % errs)
        reqs = obs.counter_value("serving.requests")
        if reqs != 64:  # warmup bypasses submit(), so exactly the burst
            failures.append("serving.requests = %d, want 64" % reqs)
        # the perf-gate record snapshots HERE — the burst phase only,
        # before the deliberately-throttled backpressure engine below
        # pollutes the queue_ms distribution
        record = serving_record(wall, lats,
                                sum(r.shape[0] for r in requests),
                                traces)
        engine.stop()

        # backpressure: 1-row batches through a throttled predictor,
        # queue of 2 — most of a 30-request burst must be rejected
        tiny = serving.ServingEngine(
            _Throttled(predictor, 0.02),
            serving.ServingConfig(max_batch_size=1, max_queue=2,
                                  num_workers=1, warmup=False)).start()
        rejected = 0
        futures = []
        for _ in range(30):
            try:
                futures.append(tiny.submit(
                    {"x": np.ones((1, DIM), "float32")}))
            except serving.ServerOverloaded:
                rejected += 1
        for f in futures:
            f.result(30)
        tiny.stop()
        if rejected == 0 or obs.counter_value("serving.rejected") == 0:
            failures.append("undersized queue rejected nothing — "
                            "admission control is not engaging")

    if out_path:
        with open(out_path, "w") as f:
            json.dump(record, f, indent=2)
        print("wrote serving perf record: %s" % out_path)
    if failures:
        print("SERVING SMOKE FAILED:")
        for f in failures:
            print("  - %s" % f)
        return 1
    print("serving smoke OK: %d buckets warmed, %d jit traces total, "
          "64/64 concurrent requests served, %d/30 rejected under "
          "undersized queue" % (len(ladder), traces, rejected))
    return 0


def _static_waitforall(streams_spec, wave_size, model_kw):
    """The baseline continuous batching replaces: admit streams in
    fixed waves; every wave member decodes EVERY step until the
    longest member finishes (finished members keep burning compute and
    KV rows — the dead work per-token scheduling eliminates). Returns
    wall seconds for the whole stream set."""
    from paddle_tpu.serving.decode import (KVCacheConfig, PagedKVCache,
                                           TinyDecodeLM)
    t0 = time.perf_counter()
    for start in range(0, len(streams_spec), wave_size):
        wave = streams_spec[start:start + wave_size]
        cache = PagedKVCache(KVCacheConfig(**model_kw))
        model = TinyDecodeLM(cache, eos_token=None)
        ids, last = [], []
        for i, (prompt, _n) in enumerate(wave):
            sid = "w%d" % i
            cache.register(sid)
            h = model.prefill_chunk(sid, prompt)
            last.append(int(np.argmax(model.logits1(h, len(prompt)))))
            ids.append(sid)
        for _ in range(max(n for _, n in wave) - 1):
            _, nxt = model.decode_step(ids, last, pad_to=wave_size)
            last = [int(t) for t in nxt]
    return time.perf_counter() - t0


def decode_smoke(out_path=None):
    """CI decode gate: mixed-length streams through the continuous-
    batching ``DecodeEngine`` must (a) each deliver exactly-once,
    in-order token indices, (b) finish error-free, and (c) beat the
    static wait-for-all baseline on tokens/s — measured on the same
    tiny model in the same process, so the margin is pure scheduling.
    With ``out_path`` also writes the bench_diff decode record."""
    from paddle_tpu.serving import metrics as sm
    from paddle_tpu.serving.decode import DecodeConfig, DecodeEngine

    failures = []
    obs.reset()
    obs.enable()
    wave = 8
    model_kw = dict(num_blocks=64, block_tokens=16, num_layers=2,
                    num_heads=2, head_dim=8)
    lens = (4, 8, 16, 24, 32, 48)
    rng = np.random.RandomState(0xDECD)
    streams_spec = [
        ([int(t) for t in rng.randint(1, 90, size=2 + i % 5)],
         lens[i % len(lens)])
        for i in range(24)]
    total_tokens = sum(n for _, n in streams_spec)

    static_wall = _static_waitforall(streams_spec, wave, model_kw)
    static_tps = total_tokens / static_wall

    engine = DecodeEngine(DecodeConfig(
        kv_blocks=model_kw["num_blocks"],
        kv_block_tokens=model_kw["block_tokens"],
        num_layers=model_kw["num_layers"],
        num_heads=model_kw["num_heads"],
        head_dim=model_kw["head_dim"],
        max_batch_size=wave, max_waiting=64,
        eos_token=None)).start()
    occ_peak = [0.0]
    stop_evt = threading.Event()

    def poll_occupancy():
        while not stop_evt.is_set():
            occ_peak[0] = max(occ_peak[0],
                              engine.health_doc()["kv_occupancy"])
            time.sleep(0.002)

    poller = threading.Thread(target=poll_occupancy, daemon=True)
    poller.start()
    t0 = time.perf_counter()
    streams = [engine.submit(p, max_tokens=n, request_id="d%d" % i)
               for i, (p, n) in enumerate(streams_spec)]
    outs = [list(s) for s in streams]
    wall = time.perf_counter() - t0
    stop_evt.set()
    poller.join()

    for i, ((_p, n), evs) in enumerate(zip(streams_spec, outs)):
        toks = [e for e in evs if e["type"] == "token"]
        if [t["index"] for t in toks] != list(range(n)):
            failures.append(
                "stream %d: want indices 0..%d exactly once, got %s"
                % (i, n - 1, [t["index"] for t in toks][:8]))
        if evs[-1].get("reason") != "max_tokens":
            failures.append("stream %d finished %r, want max_tokens"
                            % (i, evs[-1].get("reason")))
    errs = obs.counter_value(sm.STREAM_ERRORS)
    if errs:
        failures.append("serving.stream_errors = %d" % errs)
    tps = total_tokens / wall
    if tps <= static_tps:
        failures.append(
            "continuous batching (%.0f tok/s) did not beat static "
            "wait-for-all (%.0f tok/s) — per-token scheduling is not "
            "reclaiming the dead work" % (tps, static_tps))
    occupancy_peak = occ_peak[0] or engine.health_doc()["kv_occupancy"]
    engine.stop()

    ttft = obs.histogram(sm.TTFT_MS).snapshot()
    itl = obs.histogram(sm.ITL_MS).snapshot()
    rec = {
        "tokens_per_s": round(tps, 1),
        "static_tokens_per_s": round(static_tps, 1),
        "decode_speedup_vs_static": round(tps / static_tps, 3),
        "ttft_p50_ms": round(ttft.get("p50") or 0.0, 2),
        "ttft_p99_ms": round(ttft.get("p99") or 0.0, 2),
        "itl_p50_ms": round(itl.get("p50") or 0.0, 3),
        "itl_p99_ms": round(itl.get("p99") or 0.0, 3),
        "kv_occupancy_frac": round(float(occupancy_peak), 4),
        "preemptions": obs.counter_value(sm.PREEMPTIONS),
        "streams": len(streams_spec),
        "total_tokens": total_tokens,
    }
    counters = {}
    for name in (sm.STREAMS, sm.TOKENS, sm.PREFILL_TOKENS,
                 sm.DECODE_STEPS, sm.PREEMPTIONS, sm.STREAM_RESUMES,
                 sm.STREAM_ERRORS, sm.DEADLINE_EXPIRED):
        counters[name] = obs.counter_value(name)
    record = {"configs": {"decode_smoke": rec},
              "counters_total": counters}
    if out_path:
        with open(out_path, "w") as f:
            json.dump(record, f, indent=2)
        print("wrote decode perf record: %s" % out_path)
    if failures:
        print("DECODE SMOKE FAILED:")
        for f in failures:
            print("  - %s" % f)
        return 1
    print("decode smoke OK: %d mixed-length streams, %d tokens, "
          "%.0f tok/s continuous vs %.0f tok/s static (%.2fx), "
          "ttft_p50=%.1fms itl_p50=%.2fms, kv occupancy peak %.0f%%, "
          "%d preemption(s)"
          % (len(streams_spec), total_tokens, tps, static_tps,
             tps / static_tps, ttft.get("p50") or 0.0,
             itl.get("p50") or 0.0, 100 * rec["kv_occupancy_frac"],
             rec["preemptions"]))
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true",
                    help="fast CI assertions instead of the bench")
    ap.add_argument("--decode", action="store_true",
                    help="continuous-batching decode smoke (vs static "
                         "wait-for-all baseline)")
    ap.add_argument("--requests", type=int, default=256)
    ap.add_argument("--json", dest="json_path", default=None)
    ap.add_argument("--out", dest="out_path", default=None,
                    help="(with --smoke/--decode) write a bench_diff-"
                         "compatible record here for the CI perf gate")
    args = ap.parse_args(argv)
    if args.decode:
        return decode_smoke(out_path=args.out_path)
    if args.smoke:
        return smoke(out_path=args.out_path)
    bench(n_requests=args.requests, json_path=args.json_path)
    return 0


if __name__ == "__main__":
    sys.exit(main())
