from .nn import (  # noqa: F401
    fused_elemwise_activation, fused_embedding_seq_pool, multiclass_nms2,
    partial_concat, partial_sum, shuffle_batch, tree_conv)
