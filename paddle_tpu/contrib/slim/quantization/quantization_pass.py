"""Quantization-aware-training graph passes.

Parity: /root/reference/python/paddle/fluid/contrib/slim/quantization/
quantization_pass.py (QuantizationTransformPass :110, the freeze /
int8-convert / mobile passes below it). Rewrites operate on the native
``paddle_tpu.ir.IrGraph``; the inserted fake-quant ops
(ops/quant_ops.py) carry straight-through-estimator gradients, so a
transformed program trains end-to-end inside one compiled XLA step.
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from .... import framework
from ....ir import IrGraph

_QUANTIZABLE = ["conv2d", "depthwise_conv2d", "mul"]

# which input slots get quantized, and which one is the weight whose
# scale folds into the output dequant (reference rewrite targets only
# the designated activation/weight slots — never Bias/ResidualData)
_QUANT_SLOTS = {
    "conv2d": ("Input", "Filter"),
    "depthwise_conv2d": ("Input", "Filter"),
    "mul": ("X", "Y"),
}
_WEIGHT_SLOT = {"conv2d": "Filter", "depthwise_conv2d": "Filter",
                "mul": "Y"}
# canonical output slot per quantizable op type — index-0 of
# output_arg_names() is only correct for single-output ops, and slot
# iteration order would pick an arbitrary output if quantizable_op_type
# ever grows a multi-output member
_OUT_SLOT = {"conv2d": "Output", "depthwise_conv2d": "Output",
             "mul": "Out"}


def _quantized_var_name(name):
    return "%s.quantized" % name


def _dequantized_var_name(name):
    return "%s.dequantized" % name


def _scale_var_name(name):
    return "%s.scale" % name


class QuantizationTransformPass:
    """Insert per-input fake quant + dequant around quantizable ops
    (reference quantization_pass.py:110). Weight inputs always use
    abs_max (or channel_wise_abs_max); activations use
    ``activation_quantize_type``."""

    def __init__(self, scope=None, place=None, weight_bits=8,
                 activation_bits=8, activation_quantize_type="abs_max",
                 weight_quantize_type="abs_max", window_size=10000,
                 moving_rate=0.9, quantizable_op_type=None,
                 skip_pattern="skip_quant"):
        if activation_quantize_type not in (
                "abs_max", "range_abs_max", "moving_average_abs_max"):
            raise ValueError("unknown activation_quantize_type %r"
                             % activation_quantize_type)
        if weight_quantize_type not in ("abs_max",
                                        "channel_wise_abs_max"):
            raise ValueError("unknown weight_quantize_type %r"
                             % weight_quantize_type)
        self._scope = scope
        self._place = place
        self._weight_bits = weight_bits
        self._activation_bits = activation_bits
        self._act_type = activation_quantize_type
        self._weight_type = weight_quantize_type
        self._window_size = window_size
        self._moving_rate = moving_rate
        self._ops = list(quantizable_op_type or _QUANTIZABLE)
        self._skip_pattern = skip_pattern

    def apply(self, graph: IrGraph) -> IrGraph:
        dequantized: Dict[str, str] = {}
        for op in list(graph.all_op_nodes()):
            if op.op_type() not in self._ops:
                continue
            scope_tag = op.attr("op_namescope") or ""
            if self._skip_pattern and self._skip_pattern in str(scope_tag):
                continue
            quant_slots = _QUANT_SLOTS.get(
                op.op_type(), tuple(op.input_slots()))
            for slot, names in op.input_slots().items():
                if slot not in quant_slots:
                    continue
                for name in names:
                    if name in dequantized:
                        op.rename_input(name, dequantized[name])
                        continue
                    var = (graph.var_node(name)
                           if graph.has_var_node(name) else None)
                    is_weight = bool(var is not None and var.persistable)
                    deq = self._insert_quant_dequant(
                        graph, name, var, is_weight, op)
                    dequantized[name] = deq
                    op.rename_input(name, deq)
        return graph

    # -- helpers -----------------------------------------------------------
    def _insert_quant_dequant(self, graph, name, var, is_weight, before):
        bits = self._weight_bits if is_weight else self._activation_bits
        qtype = (self._weight_type if is_weight else self._act_type)
        qname = _quantized_var_name(name)
        sname = _scale_var_name(name)
        shape = var.shape if var is not None else None
        dtype = var.dtype if var is not None else "float32"
        qvar = graph.create_var_node(qname, shape=shape, var_dtype=dtype)
        svar = graph.create_persistable_node(sname, shape=[1],
                                             var_dtype="float32")

        if qtype in ("abs_max", "channel_wise_abs_max"):
            op_type = ("fake_channel_wise_quantize_abs_max"
                       if qtype == "channel_wise_abs_max"
                       else "fake_quantize_abs_max")
            graph.create_op_node(
                op_type, {"bit_length": bits},
                {"X": [name]}, {"Out": [qname], "OutScale": [sname]},
                before=before)
        elif qtype == "range_abs_max":
            graph.set_initializer(sname, np.array([1e-3], "float32"))
            graph.create_op_node(
                "fake_quantize_range_abs_max",
                {"bit_length": bits, "window_size": self._window_size,
                 "is_test": graph._for_test},
                {"X": [name], "InScale": [sname]},
                {"Out": [qname], "OutScale": [sname]},
                before=before)
        else:  # moving_average_abs_max
            aname, stname = name + ".quant_accum", name + ".quant_state"
            graph.create_persistable_node(aname, shape=[1],
                                          var_dtype="float32")
            graph.create_persistable_node(stname, shape=[1],
                                          var_dtype="float32")
            graph.set_initializer(sname, np.array([1e-3], "float32"))
            graph.set_initializer(aname, np.array([1e-3], "float32"))
            graph.set_initializer(stname, np.array([1.0], "float32"))
            graph.create_op_node(
                "fake_quantize_moving_average_abs_max",
                {"bit_length": bits, "moving_rate": self._moving_rate,
                 "is_test": graph._for_test},
                {"X": [name], "InScale": [sname], "InAccum": [aname],
                 "InState": [stname]},
                {"Out": [qname], "OutScale": [sname],
                 "OutAccum": [aname], "OutState": [stname]},
                before=before)

        dname = _dequantized_var_name(name)
        graph.create_var_node(dname, shape=shape, var_dtype=dtype)
        graph.create_op_node(
            "fake_dequantize_max_abs",
            {"max_range": float((1 << (bits - 1)) - 1)},
            {"X": [qname], "Scale": [sname]}, {"Out": [dname]},
            before=before)
        return dname


class QuantizationFreezePass:
    """Fold trained quantization into an inference graph (reference
    QuantizationFreezePass): weights become stored integer levels, the
    per-input fake ops disappear, and one channel-combining dequantize
    lands after each quantized op's output."""

    def __init__(self, scope, place, weight_bits=8, activation_bits=8,
                 weight_quantize_type="abs_max",
                 quantizable_op_type=None):
        self._scope = scope
        self._place = place
        self._weight_bits = weight_bits
        self._activation_bits = activation_bits
        self._weight_type = weight_quantize_type
        self._ops = list(quantizable_op_type or _QUANTIZABLE)

    def apply(self, graph: IrGraph) -> IrGraph:
        remove = []
        # 1) strip fake quant ops; requantize weights in the scope (the
        # weight .scale vars stay behind for the output dequant in 3)
        for op in list(graph.all_op_nodes()):
            t = op.op_type()
            if t.startswith("fake_quantize") or \
                    t == "fake_channel_wise_quantize_abs_max":
                src = op.input("X")[0]
                sname = op.output("OutScale")[0]
                var = (graph.var_node(src)
                       if graph.has_var_node(src) else None)
                if var is not None and var.persistable:
                    self._quantize_weight_in_scope(src, sname)
                remove.append(op)
            elif t == "fake_dequantize_max_abs":
                remove.append(op)

        # 2) rewire consumers of dequantized names back to sources
        for op in graph.all_op_nodes():
            if op in remove:
                continue
            for name in list(op.input_arg_names()):
                if name.endswith(".dequantized"):
                    base = name[:-len(".dequantized")]
                    op.rename_input(name, base)

        # 3) after each quantizable op, dequantize its output with the
        # combined (weight_scale, act-implied) range
        bnt_w = float((1 << (self._weight_bits - 1)) - 1)
        for op in list(graph.all_op_nodes()):
            if op.op_type() not in self._ops or op in remove:
                continue
            w_scale = None
            wslot = _WEIGHT_SLOT.get(op.op_type())
            w_names = (op.input(wslot) if wslot
                       else op.input_arg_names())
            for name in w_names:
                if graph.has_var_node(name) and \
                        graph.var_node(name).persistable and \
                        graph.has_var_node(_scale_var_name(name)):
                    w_scale = _scale_var_name(name)
            if w_scale is None:
                continue
            oslot = _OUT_SLOT.get(op.op_type())
            out = (op.output(oslot)[0] if oslot
                   else op.output_arg_names()[0])
            deq_out = out + ".dequantized"
            graph.create_var_node(deq_out)
            # rename consumers BEFORE inserting the dequant op so its
            # default placement (before the earliest consumer of its
            # output) sees them — otherwise it lands at the end, after
            # its own readers
            for consumer in graph.all_op_nodes():
                if consumer is op or consumer in remove:
                    continue
                if out in consumer.input_arg_names():
                    consumer.rename_input(out, deq_out)
            graph.create_op_node(
                "fake_dequantize_max_abs", {"max_range": bnt_w},
                {"X": [out], "Scale": [w_scale]}, {"Out": [deq_out]})
        graph.safe_remove_nodes(remove)
        return graph

    def _quantize_weight_in_scope(self, wname, sname):
        if self._scope is None:
            return
        var = self._scope.find_var(wname)
        if var is None or not var.is_initialized():
            return
        import jax.numpy as jnp

        w = np.asarray(var.get_tensor().numpy())
        bnt = float((1 << (self._weight_bits - 1)) - 1)
        if self._weight_type == "channel_wise_abs_max":
            scale = np.abs(w.reshape(w.shape[0], -1)).max(axis=1)
            shaped = scale.reshape((-1,) + (1,) * (w.ndim - 1))
        else:
            scale = np.array([np.abs(w).max()], "float32")
            shaped = scale.reshape(())
        q = np.round(w / np.maximum(shaped, 1e-12) * bnt)
        var.get_tensor().set(jnp.asarray(q.astype("float32")))
        svar = self._scope.var(sname)
        svar.get_tensor().set(jnp.asarray(scale.astype("float32")))


class ConvertToInt8Pass:
    """Store frozen weights as int8 (reference ConvertToInt8Pass).
    Scope-side conversion; the graph keeps the same var names."""

    def __init__(self, scope, place, quantizable_op_type=None):
        self._scope = scope
        self._ops = list(quantizable_op_type or _QUANTIZABLE)

    def apply(self, graph: IrGraph) -> IrGraph:
        import jax.numpy as jnp

        for op in graph.all_op_nodes():
            if op.op_type() not in self._ops:
                continue
            for name in op.input_arg_names():
                if not graph.has_var_node(name):
                    continue
                if not graph.var_node(name).persistable:
                    continue
                var = self._scope.find_var(name) if self._scope else None
                if var is None or not var.is_initialized():
                    continue
                w = np.asarray(var.get_tensor().numpy())
                if np.abs(w - np.round(w)).max() < 1e-6 and \
                        np.abs(w).max() <= 127:
                    var.get_tensor().set(jnp.asarray(w.astype("int8")))
                    graph.var_node(name).dtype = "int8"
        return graph


class TransformForMobilePass:
    """Rename fake ops to the mobile runtime's quantize/dequantize
    (reference TransformForMobilePass)."""

    def apply(self, graph: IrGraph) -> IrGraph:
        for op in graph.all_op_nodes():
            if op.op_type().startswith("fake_quantize"):
                op._type = "quantize"
            elif op.op_type().startswith("fake_dequantize"):
                op._type = "dequantize"
        return graph


def apply_startup_inits(graph: IrGraph, scope):
    """Materialize the scale/accum/state vars a transform pass created."""
    import jax.numpy as jnp

    for name, value in graph.startup_inits:
        scope.var(name).get_tensor().set(jnp.asarray(value))
