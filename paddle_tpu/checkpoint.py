"""Atomic, verifiable, rotated checkpoints.

The reference guards training state with checkpoint_notify +
save/load on the pserver side; what it does NOT guard against — and
this module does — is the crash *mid-save*: a process killed inside
``io.save`` used to leave a half-written model dir that the next load
would read as garbage. The contract here:

- **atomicity** — a checkpoint is written into a temp dir next to its
  final name, every file is fsync'd, a manifest with per-file sha256
  is written last, and the temp dir renames into place. A crash never
  leaves a torn hybrid: a NEW checkpoint name (the rotation manager's
  only case) appears all-or-nothing; overwriting an existing name has
  one rename-wide window where only that name is absent — older
  rotations still serve ``load_latest``, and the next save sweeps the
  stranded dirs. Readers can never observe the temp dir (``.tmp-``
  names are skipped by the rotation scan).
- **verifiability** — ``verify_manifest`` recomputes each listed
  file's sha256; any mismatch/missing file raises the typed
  ``CheckpointCorrupt`` instead of a numpy parse error three frames
  deep.
- **rotation** — ``CheckpointManager`` keeps the newest ``keep``
  checkpoints under ``root/ckpt-<step>/`` with an atomically-updated
  ``latest`` pointer; ``load_latest`` walks newest-to-oldest past
  corrupt entries, so one bad shard costs one checkpoint, not the run.
- **incremental saves** (ISSUE 8) — ``save_incremental`` reuses
  unchanged shards from the previous checkpoint by content hash (or a
  caller-supplied fingerprint, which skips even producing the bytes):
  a reused shard is hardlinked (or copied) from the previous dir
  instead of re-serialized + re-fsynced, so at GB scale the cost of a
  checkpoint tracks what *changed*, not what *exists*. Every
  checkpoint dir stays fully self-contained in its namespace — the
  manifest, rotation, corrupt fallback, and every existing loader work
  unchanged — and the incremental path is gated bit-for-bit against
  the full-blob path by the ft test suite.

``checkpoint.save_ms`` / ``checkpoint.bytes`` land in the
observability registry unconditionally (saves are rare and CI reads
them); ``checkpoint.delta_bytes`` (freshly-written payload) and
``checkpoint.shards_reused`` measure what the incremental path saved.
"""
from __future__ import annotations

import contextlib
import hashlib
import json
import os
import shutil
import threading
import time
from typing import Callable, Dict, List, Optional

__all__ = ["CheckpointCorrupt", "MANIFEST_NAME", "SCOPE_VARS_NAME",
           "atomic_write_bytes", "atomic_checkpoint_dir",
           "write_manifest", "verify_manifest", "manifest_extra",
           "load_scope_snapshot",
           "CheckpointManager", "save_checkpoint", "load_checkpoint"]

MANIFEST_NAME = "__manifest__.json"
SCOPE_VARS_NAME = "__vars__.json"  # file name -> var name (snapshots)
_LATEST_NAME = "latest"
_CKPT_PREFIX = "ckpt-"


class CheckpointCorrupt(RuntimeError):
    """A checkpoint failed integrity verification (missing file, size
    or sha256 mismatch, unreadable manifest). Callers holding older
    rotations should fall back; callers without one should fail loudly
    rather than train from garbage."""


def _observe(name: str, v) -> None:
    from . import observability as _obs

    _obs.histogram(name).observe(v)


def _count(name: str, n: int = 1) -> None:
    from . import observability as _obs

    _obs.counter(name).inc(n)


def _fsync_file(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return  # platform without O_RDONLY dirs; rename is still atomic
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def atomic_write_bytes(path: str, data: bytes) -> None:
    """Write ``data`` to ``path`` via tmp-file + fsync + rename: the
    file at ``path`` is always either the old content or all of
    ``data``, never a prefix."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    # staging name unique per (process, thread, moment): concurrent
    # writers of the SAME path (racing manifest rewrites) must not
    # replace each other's staging file out from under the os.replace
    tmp = os.path.join(d, ".tmp-%s-%d-%d-%d" % (
        os.path.basename(path), os.getpid(),
        threading.get_ident() % 100000, time.monotonic_ns() % 1_000_000))
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(d)


def write_manifest(dirname: str, extra: Optional[Dict] = None,
                   files: Optional[List[str]] = None) -> Dict:
    """Hash files in ``dirname`` into ``__manifest__.json``, written
    atomically LAST — a dir with a valid manifest is a complete dir.
    ``files`` (names relative to ``dirname``) restricts the manifest
    to exactly what a save wrote; the default hashes every regular
    file (dedicated checkpoint dirs) — a save into a SHARED dir must
    pass ``files`` or it would pin unrelated, mutable files and make
    later verification fail spuriously."""
    names = files if files is not None else [
        fn for fn in sorted(os.listdir(dirname))
        if fn != MANIFEST_NAME and not fn.startswith(".tmp-")]
    listed = {}
    for fn in sorted(names):
        p = os.path.join(dirname, fn)
        if not os.path.isfile(p):
            continue
        _fsync_file(p)
        listed[fn] = {"sha256": _sha256(p),
                      "bytes": os.path.getsize(p)}
    doc = {"version": 1, "files": listed}
    if extra:
        doc.update(extra)
    atomic_write_bytes(os.path.join(dirname, MANIFEST_NAME),
                       json.dumps(doc, indent=1, sort_keys=True).encode())
    return doc


def manifest_extra(dirname: str) -> Dict:
    """The caller-supplied ``extra`` a save recorded in ``dirname``'s
    manifest — everything outside the reserved ``version``/``files``
    keys ({} when there is none, or the manifest is unreadable: the
    extra is advisory metadata, e.g. the PS shard map a trainer
    checkpoints so its relaunched incarnation resumes ROUTING from
    the checkpoint instead of rediscovering migrations through
    wrong_shard redirects; never load-bearing for the payload, which
    stays manifest-verified)."""
    try:
        with open(os.path.join(dirname, MANIFEST_NAME), "r",
                  encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return {}
    return {k: v for k, v in doc.items()
            if k not in ("version", "files")}


def verify_manifest(dirname: str, required: bool = True) -> Optional[Dict]:
    """Recompute and check every file listed in ``dirname``'s manifest.
    Raises ``CheckpointCorrupt`` on any mismatch; with
    ``required=False`` a missing manifest returns None (pre-manifest
    dirs stay loadable), otherwise it is itself corruption — an atomic
    save always writes one."""
    mpath = os.path.join(dirname, MANIFEST_NAME)
    if not os.path.exists(mpath):
        if not required:
            return None
        raise CheckpointCorrupt(
            "checkpoint dir %r has no %s — it was not written by an "
            "atomic save (or the save never completed)"
            % (dirname, MANIFEST_NAME))
    try:
        with open(mpath, "r", encoding="utf-8") as f:
            doc = json.load(f)
        listed = doc["files"]
    except (ValueError, KeyError, OSError) as e:
        raise CheckpointCorrupt(
            "checkpoint manifest %r is unreadable: %s" % (mpath, e)
        ) from e
    for fn, meta in listed.items():
        p = os.path.join(dirname, fn)
        if not os.path.exists(p):
            raise CheckpointCorrupt(
                "checkpoint %r is missing file %r listed in its "
                "manifest" % (dirname, fn))
        size = os.path.getsize(p)
        if size != int(meta.get("bytes", -1)):
            raise CheckpointCorrupt(
                "checkpoint file %r is %d bytes, manifest says %s"
                % (p, size, meta.get("bytes")))
        digest = _sha256(p)
        if digest != meta.get("sha256"):
            raise CheckpointCorrupt(
                "checkpoint file %r fails sha256 verification "
                "(got %s…, manifest says %s…)"
                % (p, digest[:12], str(meta.get("sha256"))[:12]))
    return doc


def load_scope_snapshot(executor, scope, dirname: str) -> int:
    """Restore a ``snapshot_scope_to_dir`` directory into ``scope``
    after verifying its manifest — the pserver rejoin catch-up path: a
    relaunched server must never boot off a torn snapshot, so any
    integrity failure raises the typed ``CheckpointCorrupt`` instead
    of loading garbage params. Var names come from ``__vars__.json``
    when present (dedicated snapshots write it) and fall back to the
    file names. Returns the number of vars restored."""
    from .core import proto_format

    verify_manifest(dirname, required=True)
    vmap_path = os.path.join(dirname, SCOPE_VARS_NAME)
    if os.path.exists(vmap_path):
        with open(vmap_path, "r", encoding="utf-8") as f:
            names = json.load(f)
    else:
        names = {fn: fn for fn in sorted(os.listdir(dirname))
                 if fn not in (MANIFEST_NAME, SCOPE_VARS_NAME)
                 and not fn.startswith(".tmp-")
                 and os.path.isfile(os.path.join(dirname, fn))}
    loaded = 0
    for fn, var in sorted(names.items()):
        with open(os.path.join(dirname, fn), "rb") as f:
            data = f.read()
        arr, _lod, _pos = proto_format.parse_lod_tensor(data)
        executor._write_var(scope, var, arr.copy())
        loaded += 1
    return loaded


@contextlib.contextmanager
def atomic_checkpoint_dir(final_dir: str, extra: Optional[Dict] = None):
    """Context manager: yields a temp dir to write checkpoint files
    into; on clean exit fsyncs everything, writes the manifest, and
    renames the temp dir to ``final_dir`` (replacing any previous
    version only after the new one is durable). On error the temp dir
    is removed and ``final_dir`` is untouched."""
    final_dir = os.path.abspath(final_dir).rstrip(os.sep)
    parent = os.path.dirname(final_dir)
    os.makedirs(parent, exist_ok=True)
    # sweep trash a SIGKILLed earlier save stranded (NOT .tmp- dirs: a
    # concurrent save of the same name may be live inside one; tmp
    # leftovers are invisible to scans and merely cost disk)
    base = os.path.basename(final_dir)
    for fn in os.listdir(parent):
        if fn.startswith(base + ".trash-"):
            shutil.rmtree(os.path.join(parent, fn), ignore_errors=True)
    tmp = "%s.tmp-%d-%d" % (final_dir, os.getpid(),
                            time.monotonic_ns() % 1_000_000)
    os.makedirs(tmp)
    t0 = time.monotonic()
    try:
        yield tmp
        doc = write_manifest(tmp, extra=extra)
        _fsync_dir(tmp)
        if os.path.isdir(final_dir):
            # rename-aside + rename-in, not rmtree-then-rename: the
            # no-checkpoint window shrinks to the instant between the
            # two renames (a SIGKILL exactly there costs only THIS
            # name — rotation siblings still serve load_latest; the
            # stranded trash/tmp dirs are swept by the next save)
            trash = "%s.trash-%d-%d" % (final_dir, os.getpid(),
                                        time.monotonic_ns() % 1_000_000)
            os.rename(final_dir, trash)
            os.rename(tmp, final_dir)
            shutil.rmtree(trash, ignore_errors=True)
        else:
            os.rename(tmp, final_dir)
        _fsync_dir(parent)
        total = sum(int(m["bytes"]) for m in doc["files"].values())
        _count("checkpoint.bytes", total)
        _observe("checkpoint.save_ms", (time.monotonic() - t0) * 1e3)
        from .observability import flight as _flight

        _flight.record("checkpoint.commit",
                       dir=os.path.basename(final_dir), bytes=total,
                       ms=round((time.monotonic() - t0) * 1e3, 3))
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise


class CheckpointManager:
    """Keep-last-k rotation under one root::

        root/
          ckpt-42/   __params__.npz  __manifest__.json
          ckpt-43/   ...
          latest     -> "ckpt-43"        (atomically updated pointer)

    ``save`` writes a new numbered checkpoint atomically, repoints
    ``latest``, and prunes beyond ``keep``. ``load_latest`` tries the
    pointer first, then remaining checkpoints newest-to-oldest,
    skipping (and counting) corrupt ones."""

    def __init__(self, root: str, keep: int = 3):
        self.root = os.path.abspath(root)
        self.keep = max(1, int(keep))

    # -- layout ------------------------------------------------------------

    def dir_for(self, step: int) -> str:
        return os.path.join(self.root, "%s%d" % (_CKPT_PREFIX, int(step)))

    def steps(self) -> List[int]:
        """Completed (renamed-into-place) checkpoint steps, ascending;
        temp/trash dirs are invisible by construction."""
        if not os.path.isdir(self.root):
            return []
        out = []
        for fn in os.listdir(self.root):
            if not fn.startswith(_CKPT_PREFIX):
                continue
            tail = fn[len(_CKPT_PREFIX):]
            if tail.isdigit() and os.path.isdir(
                    os.path.join(self.root, fn)):
                out.append(int(tail))
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        """The ``latest`` pointer's step when it names an existing
        checkpoint, else the newest numbered dir, else None."""
        ptr = os.path.join(self.root, _LATEST_NAME)
        try:
            with open(ptr, "r", encoding="utf-8") as f:
                name = f.read().strip()
            tail = name[len(_CKPT_PREFIX):]
            if (name.startswith(_CKPT_PREFIX) and tail.isdigit()
                    and os.path.isdir(os.path.join(self.root, name))):
                return int(tail)
        except OSError:
            pass
        steps = self.steps()
        return steps[-1] if steps else None

    # -- save / load -------------------------------------------------------

    def save(self, step: int, writer: Callable[[str], None],
             extra: Optional[Dict] = None) -> str:
        """Write checkpoint ``step`` atomically: ``writer(tmp_dir)``
        produces the files; manifest + rename + ``latest`` update +
        pruning happen here. Returns the final dir."""
        final = self.dir_for(step)
        meta = {"step": int(step)}
        if extra:
            meta.update(extra)
        with atomic_checkpoint_dir(final, extra=meta) as tmp:
            writer(tmp)
        atomic_write_bytes(os.path.join(self.root, _LATEST_NAME),
                           os.path.basename(final).encode())
        self._prune()
        return final

    def save_incremental(self, step: int, shards: Dict,
                         fingerprints: Optional[Dict[str, str]] = None,
                         extra: Optional[Dict] = None,
                         reuse: str = "link") -> str:
        """Write checkpoint ``step`` reusing unchanged shards from the
        previous checkpoint. ``shards`` maps file name -> bytes or a
        zero-arg callable producing bytes (lazy: never called when the
        shard is fingerprint-matched). A shard is reused — hardlinked
        (``reuse="link"``, the cheap default) or copied
        (``reuse="copy"``) from the previous checkpoint dir — when

        - ``fingerprints[name]`` matches the fingerprint the previous
          manifest recorded for it (the caller's cheap dirty-tracking:
          a version counter, the server's replication digest, ...), or
        - its produced bytes' sha256 matches the previous manifest
          entry (content dedupe — still skips the fresh write+fsync).

        Every dir remains self-contained in its NAMESPACE (loaders and
        ``verify_manifest`` are oblivious), atomic, and rotated as
        usual. Hardlink caveat: reused shards share an inode with the
        previous checkpoint, so in-PLACE corruption of one damages
        both (both detected by their manifests); corruption that
        replaces the file (the common torn-write case) breaks the link
        and costs one checkpoint. Use ``reuse="copy"`` where that
        blast radius matters more than the write savings.

        ``checkpoint.delta_bytes`` counts only the freshly-written
        payload; ``checkpoint.shards_reused`` counts the links — the
        pair is the incremental win, next to the full
        ``checkpoint.bytes``."""
        if reuse not in ("link", "copy"):
            raise ValueError("reuse must be 'link' or 'copy', got %r"
                             % reuse)
        fingerprints = dict(fingerprints or {})
        prev_step = self.latest_step()
        prev_dir = self.dir_for(prev_step) if prev_step is not None \
            else None
        prev_files: Dict = {}
        prev_fps: Dict = {}
        if prev_dir is not None:
            try:
                with open(os.path.join(prev_dir, MANIFEST_NAME),
                          encoding="utf-8") as f:
                    doc = json.load(f)
                prev_files = doc.get("files", {}) or {}
                prev_fps = doc.get("fingerprints", {}) or {}
            except (OSError, ValueError):
                prev_files, prev_fps = {}, {}  # unreadable: full save

        stats = {"reused": 0, "fresh_bytes": 0}

        def _reuse(src: str, dst: str) -> None:
            if reuse == "link":
                try:
                    os.link(src, dst)
                    return
                except OSError:
                    pass  # cross-device / fs without links: fall back
            shutil.copy2(src, dst)

        def writer(tmp: str) -> None:
            for fn in sorted(shards):
                prev_meta = prev_files.get(fn)
                prev_path = (os.path.join(prev_dir, fn)
                             if prev_dir is not None else None)
                have_prev = (prev_meta is not None and prev_path
                             and os.path.isfile(prev_path))
                fp = fingerprints.get(fn)
                if (have_prev and fp is not None
                        and prev_fps.get(fn) == fp):
                    _reuse(prev_path, os.path.join(tmp, fn))
                    stats["reused"] += 1
                    continue
                src = shards[fn]
                data = src() if callable(src) else bytes(src)
                if (have_prev and prev_meta.get("sha256")
                        == hashlib.sha256(data).hexdigest()):
                    _reuse(prev_path, os.path.join(tmp, fn))
                    stats["reused"] += 1
                    continue
                atomic_write_bytes(os.path.join(tmp, fn), data)
                stats["fresh_bytes"] += len(data)

        meta = dict(extra or {})
        meta["fingerprints"] = fingerprints
        final = self.save(step, writer, extra=meta)
        _count("checkpoint.delta_bytes", stats["fresh_bytes"])
        _count("checkpoint.shards_reused", stats["reused"])
        return final

    def _prune(self) -> None:
        steps = self.steps()
        for s in steps[:-self.keep]:
            shutil.rmtree(self.dir_for(s), ignore_errors=True)

    def load_latest(self, loader: Callable[[str], None]) -> Optional[int]:
        """Verify + load the newest valid checkpoint; walks past
        corrupt ones (counting ``checkpoint.corrupt``) so one bad
        shard falls back to the previous rotation. Returns the loaded
        step, or None when no checkpoint exists. Raises
        ``CheckpointCorrupt`` only when checkpoints exist but ALL fail
        verification."""
        candidates = sorted(self.steps(), reverse=True)
        latest = self.latest_step()
        if latest is not None and latest in candidates:
            candidates.remove(latest)
            candidates.insert(0, latest)
        if not candidates:
            return None
        errors = []
        for step in candidates:
            d = self.dir_for(step)
            try:
                verify_manifest(d, required=True)
                loader(d)
                return step
            except CheckpointCorrupt as e:
                _count("checkpoint.corrupt")
                errors.append(str(e))
                continue
        raise CheckpointCorrupt(
            "every checkpoint under %r failed verification: %s"
            % (self.root, "; ".join(errors)))


def save_checkpoint(executor, root: str, step: int, main_program=None,
                    keep: int = 3) -> str:
    """Atomic rotated persistables checkpoint for a static-graph
    program: ``io.save_persistables`` into ``root/ckpt-<step>/`` with
    manifest + ``latest`` pointer; keeps the newest ``keep``."""
    from . import io as _io

    mgr = CheckpointManager(root, keep=keep)
    return mgr.save(step, lambda d: _io.save_persistables(
        executor, d, main_program))


def load_checkpoint(executor, root: str, main_program=None):
    """Load the newest valid checkpoint saved by ``save_checkpoint``;
    returns its step, or None when ``root`` holds none."""
    from . import io as _io

    mgr = CheckpointManager(root)
    return mgr.load_latest(lambda d: _io.load_persistables(
        executor, d, main_program))
