"""Shared helpers for ops that consume host-side LoD metadata.

The executor passes each LoD input's table via ``attrs['_lod_<slot>']``
as nested tuples; these helpers are the single source of truth for
parsing it (used by sequence_ops, rnn_ops, detection_ops).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

LOD_ATTR_PREFIX = "_lod_"


def lod_offsets(attrs, slot, level=-1):
    """Last-level offset table [0, ...] for `slot`, or None if absent."""
    lods = attrs.get(LOD_ATTR_PREFIX + slot)
    if not lods or not lods[0]:
        return None
    return list(lods[0][level])


def seg_ids(offsets):
    """Row -> sequence-index map as a device array."""
    ids = np.zeros(offsets[-1], dtype=np.int32)
    for i in range(len(offsets) - 1):
        ids[offsets[i]:offsets[i + 1]] = i
    return jnp.asarray(ids)


def seq_lens(offsets):
    return np.diff(np.asarray(offsets))


def batch_ids_for(attrs, slot, n_rows):
    """Per-row batch assignment from the slot's LoD (zeros if absent)."""
    offsets = lod_offsets(attrs, slot)
    if offsets is None:
        return jnp.zeros(n_rows, dtype=jnp.int32)
    return seg_ids(offsets)
