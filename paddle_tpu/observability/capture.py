"""Sampled in-production capture: ``PADDLE_TPU_SAMPLE_EVERY=N``.

The PR-7 step profiler (``profiler.profile_step``) and PR-10 device
capture were built as *offline* tools — bench.py runs them once and a
human reads the report. A production job drifts: data distributions
shift, a quiet neighbor starts compiling, a new checkpoint changes the
backward timeline. This module runs the SAME machinery on every Nth
executor/engine step of a real job, writing a rolling per-process
profile report into the ``PADDLE_TPU_METRICS_DIR`` dump pipeline so
``merge_job_dir`` can surface per-rank phase/overlap/agreement drift —
the live telemetry the steering daemon watches.

Contract (gate-4 enforced by ``tools/obs_overhead.py``):

- default OFF — ``PADDLE_TPU_SAMPLE_EVERY`` unset/0 means the
  steady-state hook is one memoized-int load + a branch, well under
  the <1µs per-step budget;
- the capture itself must NEVER break a training step: every failure
  is swallowed into a ``capture.errors`` counter + flight event;
- reports are ROLLING: one ``<role>-<rank>.profile.json`` per process
  (atomic replace, newest sample wins) carrying a bounded history of
  compact summaries so the daemon can see a trend, not just a point.

The report file deliberately does NOT carry the process-dump schema —
``distributed.load_dumps`` skips it, ``load_sampled_profiles`` reads
it, and the merge attaches it to the process's section.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional

__all__ = ["sample_every", "sampling_enabled", "maybe_sample_step",
           "SAMPLED_PROFILE_SCHEMA", "HISTORY_CAP"]

SAMPLED_PROFILE_SCHEMA = "sampled_profile_v1"
HISTORY_CAP = 32

# memoized knob: None = env not read yet, 0 = off, N>0 = every Nth step.
# A single module-global load keeps the disabled hook sub-µs.
_SAMPLE_EVERY: Optional[int] = None

_lock = threading.Lock()
_counts: Dict[str, int] = {}       # engine kind -> steps seen
_history: Dict[str, list] = {}     # engine kind -> compact summaries


def sample_every() -> int:
    """``PADDLE_TPU_SAMPLE_EVERY`` as a non-negative int (0 = off),
    read once and memoized."""
    global _SAMPLE_EVERY
    n = _SAMPLE_EVERY
    if n is None:
        raw = os.environ.get("PADDLE_TPU_SAMPLE_EVERY", "").strip()
        try:
            n = max(0, int(raw)) if raw else 0
        except ValueError:
            n = 0
        _SAMPLE_EVERY = n
    return n


def sampling_enabled() -> bool:
    return sample_every() > 0


def _reset_for_tests() -> None:
    global _SAMPLE_EVERY
    with _lock:
        _SAMPLE_EVERY = None
        _counts.clear()
        _history.clear()


def maybe_sample_step(kind: str, program=None, scope=None, feed=None,
                      mesh=None, axis_name: str = "dp"
                      ) -> Optional[Dict]:
    """The per-step hook the executors call AFTER a successful step.
    Off: one global load + branch. On: every Nth call per ``kind``
    profiles the just-run (program, scope, feed) and rolls the report
    into the metrics dir. Returns the report on a sampled step (tests,
    callers that want it), else None."""
    n = _SAMPLE_EVERY
    if n is None:
        n = sample_every()
    if not n:
        return None
    if program is None or scope is None or feed is None:
        return None
    with _lock:
        c = _counts.get(kind, 0) + 1
        _counts[kind] = c
    if c % n:
        return None
    try:
        return _capture(kind, c, program, scope, feed, mesh, axis_name)
    except Exception as e:  # a broken capture must never break a step
        from . import inc as _inc
        from . import flight as _flight

        _inc("capture.errors", engine=kind)
        _flight.record("capture.error", engine=kind, step=c,
                       error="%s: %s" % (type(e).__name__, e))
        return None


def _capture(kind, step, program, scope, feed, mesh, axis_name):
    from . import inc as _inc
    from . import flight as _flight
    from . import profiler as _prof

    budget = float(os.environ.get("PADDLE_TPU_SAMPLE_BUDGET_S", "20")
                   or 20)
    t0 = time.monotonic()
    report = _prof.profile_step(program, scope, feed, mesh=mesh,
                                axis_name=axis_name, repeats=1,
                                budget_s=budget, max_bucket_cuts=6)
    capture_ms = (time.monotonic() - t0) * 1e3
    _inc("capture.samples", engine=kind)
    _flight.record("capture.sampled", engine=kind, step=step,
                   capture_ms=round(capture_ms, 3),
                   step_ms=report.get("step_ms"),
                   overlap_frac=report.get("overlap_frac"))
    try:
        # sampled phases join the process's chrome trace + gauges like
        # a bench-run profile would
        _prof._emit_profile(report)
    except Exception:
        # the report itself is still good — only the trace/gauge echo
        # failed; count it rather than losing the sample
        _inc("capture.emit_errors", engine=kind)
    _write_rolling_report(kind, step, report, capture_ms)
    return report


def _summary(step, report, capture_ms):
    out = {"step": step, "wrote_at": time.time(),
           "capture_ms": round(capture_ms, 3)}
    for k in ("step_ms", "overlap_frac", "critical_path_ms",
              "exposed_collective_ms", "feed_ms", "optimizer_ms",
              "host_device_agreement"):
        v = report.get(k)
        if isinstance(v, (int, float)):
            out[k] = v
    return out


def _write_rolling_report(kind, step, report, capture_ms) -> None:
    from .distributed import metrics_dir, process_identity
    from . import timeseries as _ts
    from ..checkpoint import atomic_write_bytes
    import json

    d = metrics_dir()
    if not d:
        return
    role, rank, restart = process_identity()
    base = "%s-%d" % (role, rank)
    if restart:
        base += ".r%d" % restart
    with _lock:
        hist = _history.setdefault(kind, [])
        summary = _summary(step, report, capture_ms)
        hist.append(summary)
        del hist[:-HISTORY_CAP]
    if _ts.series_enabled():
        # sampled-profile trends join the windowed rings so steering
        # rules can judge "step_ms over the last window" too
        for k, v in summary.items():
            if k in ("step", "wrote_at"):
                continue
            if isinstance(v, (int, float)):
                _ts.record_point("capture.%s{engine=%s}" % (k, kind),
                                 v, wall_ts=summary["wrote_at"])
    with _lock:
        doc = {
            "schema": SAMPLED_PROFILE_SCHEMA,
            "proc": base,
            "role": role, "rank": rank, "restart": restart,
            "engine": kind,
            "step": step,
            "sample_every": sample_every(),
            "samples": len(hist),
            "wrote_at": time.time(),
            "profile": report,
            "history": list(hist),
        }
    try:
        os.makedirs(d, exist_ok=True)
        atomic_write_bytes(
            os.path.join(d, base + ".profile.json"),
            json.dumps(doc, default=str).encode())
    except OSError:
        pass
