"""Text-matching + SSD-mining op family (registry-parity wave 5).

Parity targets:
- match_matrix_tensor_op.cc — bilinear text match over LoD pairs
- sequence_ops/sequence_topk_avg_pooling_op.h — top-k average pooling
  over per-pair score grids
- similarity_focus_op.h — greedy row/col focus mask
- lookup_table_dequant_op.h — embedding lookup decoding uint8-packed
  rows (min/max in the first two floats)
- detection/mine_hard_examples_op.cc — SSD OHEM negative mining
- detection/rpn_target_assign_op.cc:1032 retinanet_target_assign
"""
from __future__ import annotations

import struct
from typing import List

import numpy as np

from ..core.registry import In, Out, register_host_op
from ..core.tensor import LoDTensor


def _holder(scope, name):
    var = scope.find_var(name)
    return None if var is None or not var.is_initialized() else var.raw()


def _lod0(holder, n_rows):
    if hasattr(holder, "lod") and holder.lod():
        return list(holder.lod()[-1])
    return [0, n_rows]


@register_host_op(
    "lookup_table_dequant",
    inputs=[In("W", no_grad=True), In("Ids", no_grad=True)],
    outputs=[Out("Out")],
    attrs={"padding_idx": -1},
)
def _lookup_table_dequant(executor, op, scope):
    """lookup_table_dequant_op.h: each table row stores [min, max,
    packed...] where every packed float32's 4 BYTES are uint8 codes;
    out = code * (max - min) / 256 + min — a 4x-compressed embedding."""
    w = np.asarray(executor._read_var(scope, op.input("W")[0]))
    ids = np.asarray(executor._read_var(
        scope, op.input("Ids")[0])).reshape(-1)
    pad = int(op.attrs.get("padding_idx", -1))
    width = (w.shape[1] - 2) * 4
    out = []
    for i in ids:
        if pad >= 0 and int(i) == pad:
            out.append(np.zeros(width, np.float32))
            continue
        row = w[int(i)]
        lo, hi = float(row[0]), float(row[1])
        codes = np.frombuffer(
            np.asarray(row[2:], dtype=np.float32).tobytes(),
            dtype=np.uint8).astype(np.float32)
        out.append(codes * (hi - lo) / 256.0 + lo)
    executor._write_var(scope, op.output("Out")[0],
                        np.stack(out).astype("float32") if out
                        else np.zeros((0, (w.shape[1] - 2) * 4),
                                      "float32"))


@register_host_op(
    "match_matrix_tensor",
    inputs=[In("X"), In("Y"), In("W")],
    outputs=[Out("Out"), Out("Tmp")],
    attrs={"dim_t": 1},
)
def _match_matrix_tensor(executor, op, scope):
    """match_matrix_tensor_op.cc: per (x_seq, y_seq) pair and per
    channel t: score[i,j] = x_i . W_t . y_j; Out is the ragged stack of
    [dim_t, len_x, len_y] grids (one LoD segment per pair), Tmp caches
    x.W for the backward."""
    xh = _holder(scope, op.input("X")[0])
    yh = _holder(scope, op.input("Y")[0])
    x = np.asarray(xh.array)
    y = np.asarray(yh.array)
    w = np.asarray(executor._read_var(scope, op.input("W")[0]))
    w_t = w.transpose(1, 0, 2)  # [dim_t, h, h]
    x_lod = _lod0(xh, x.shape[0])
    y_lod = _lod0(yh, y.shape[0])
    outs, tmps, out_lod = [], [], [0]
    for i in range(len(x_lod) - 1):
        xs = x[x_lod[i]:x_lod[i + 1]]
        ys = y[y_lod[i]:y_lod[i + 1]]
        t = np.einsum("ih,thk->itk", xs, w_t)    # [lx, dim_t, h]
        tmps.append(t.reshape(-1, 1))
        grid = np.einsum("itk,jk->tij", t, ys)   # [dim_t, lx, ly]
        outs.append(grid.reshape(-1, 1))
        out_lod.append(out_lod[-1] + grid.size)
    out = (np.concatenate(outs) if outs
           else np.zeros((0, 1), x.dtype)).astype(x.dtype)
    t = LoDTensor(out)
    t.set_lod([out_lod])
    executor._write_var(scope, op.output("Out")[0], t)
    executor._write_var(scope, op.output("Tmp")[0],
                        (np.concatenate(tmps) if tmps
                         else np.zeros((0, 1), x.dtype)).astype(x.dtype))


def _match_matrix_grad_maker(block, op, pending, finalize):
    from .control_flow_ops import _bind_partial_grad

    og = finalize(op.output("Out")[0])
    if og is None:
        return
    gx = _bind_partial_grad(block, pending, op.input("X")[0])
    gy = _bind_partial_grad(block, pending, op.input("Y")[0])
    gw = _bind_partial_grad(block, pending, op.input("W")[0])
    block.append_op(
        "match_matrix_tensor_grad",
        {"X": [op.input("X")[0]], "Y": [op.input("Y")[0]],
         "W": [op.input("W")[0]], "Out@GRAD": [og]},
        {"X@GRAD": [gx], "Y@GRAD": [gy], "W@GRAD": [gw]},
        dict(op.attrs), infer_shape=False)


@register_host_op(
    "match_matrix_tensor_grad",
    inputs=[In("X", no_grad=True), In("Y", no_grad=True),
            In("W", no_grad=True), In("Out@GRAD", no_grad=True)],
    outputs=[Out("X@GRAD"), Out("Y@GRAD"), Out("W@GRAD")],
    attrs={"dim_t": 1},
)
def _match_matrix_tensor_grad(executor, op, scope):
    xh = _holder(scope, op.input("X")[0])
    yh = _holder(scope, op.input("Y")[0])
    x = np.asarray(xh.array)
    y = np.asarray(yh.array)
    w = np.asarray(executor._read_var(scope, op.input("W")[0]))
    og = np.asarray(executor._read_var(
        scope, op.input("Out@GRAD")[0])).reshape(-1)
    w_t = w.transpose(1, 0, 2)
    x_lod = _lod0(xh, x.shape[0])
    y_lod = _lod0(yh, y.shape[0])
    gx = np.zeros_like(x)
    gy = np.zeros_like(y)
    gw_t = np.zeros_like(w_t)
    off = 0
    for i in range(len(x_lod) - 1):
        xs = x[x_lod[i]:x_lod[i + 1]]
        ys = y[y_lod[i]:y_lod[i + 1]]
        lx, ly = xs.shape[0], ys.shape[0]
        dim_t = w.shape[1]
        n = ly * dim_t * lx
        g = og[off:off + n].reshape(dim_t, lx, ly)   # [t, i, j]
        off += n
        # score[t,i,j] = x_i W_t y_j
        gx[x_lod[i]:x_lod[i + 1]] += np.einsum(
            "tij,thk,jk->ih", g, w_t, ys)
        gy[y_lod[i]:y_lod[i + 1]] += np.einsum(
            "tij,ih,thk->jk", g, xs, w_t)
        gw_t += np.einsum("tij,ih,jk->thk", g, xs, ys)
    executor._write_var(scope, op.output("X@GRAD")[0], gx)
    executor._write_var(scope, op.output("Y@GRAD")[0], gy)
    executor._write_var(scope, op.output("W@GRAD")[0],
                        gw_t.transpose(1, 0, 2))


from ..core.registry import OpInfoMap  # noqa: E402

OpInfoMap.instance().get("match_matrix_tensor").grad = \
    _match_matrix_grad_maker


@register_host_op(
    "sequence_topk_avg_pooling",
    inputs=[In("X"), In("ROW", no_grad=True), In("COLUMN", no_grad=True)],
    outputs=[Out("Out"), Out("pos", no_grad=True)],
    attrs={"topks": [1], "channel_num": 1},
)
def _sequence_topk_avg_pooling(executor, op, scope):
    """sequence_topk_avg_pooling_op.h: X is the ragged stack of
    [channel, row, col] score grids (ROW/COLUMN carry the per-pair
    row/col lods); out[r, c, k] = mean of the top-k entries of row r of
    channel c. `pos` saves the top-k column indices for the backward."""
    xh = _holder(scope, op.input("X")[0])
    rh = _holder(scope, op.input("ROW")[0])
    ch = _holder(scope, op.input("COLUMN")[0])
    x = np.asarray(xh.array).reshape(-1)
    topks = [int(k) for k in op.attrs["topks"]]
    chan = int(op.attrs["channel_num"])
    max_k = topks[-1]
    k_num = len(topks)
    in_lod = _lod0(xh, x.shape[0])
    row_lod = _lod0(rh, np.asarray(rh.array).shape[0])
    col_lod = _lod0(ch, np.asarray(ch.array).shape[0])
    bs = len(row_lod) - 1
    total_rows = row_lod[-1]
    out = np.zeros((total_rows, chan * k_num), np.float32)
    pos = np.full(total_rows * chan * max_k, -1, np.int32)
    for i in range(bs):
        rs = row_lod[i + 1] - row_lod[i]
        cs = col_lod[i + 1] - col_lod[i]
        grid = x[in_lod[i]:in_lod[i + 1]].reshape(chan, rs, cs)
        for j in range(chan):
            for r in range(rs):
                rowd = grid[j, r]
                order = np.argsort(-rowd, kind="stable")[:max_k]
                p0 = ((row_lod[i] + r) * chan + j) * max_k
                pos[p0:p0 + len(order)] = order
                csum, run = [], 0.0
                for k in range(max_k):
                    if k < len(order):
                        run += rowd[order[k]]
                    csum.append(run)
                for kk, k in enumerate(topks):
                    out[row_lod[i] + r, j * k_num + kk] = \
                        csum[k - 1] / k
    t = LoDTensor(out)
    t.set_lod([list(row_lod)])
    executor._write_var(scope, op.output("Out")[0], t)
    executor._write_var(scope, op.output("pos")[0], pos)


def _topk_avg_grad_maker(block, op, pending, finalize):
    from .control_flow_ops import _bind_partial_grad

    og = finalize(op.output("Out")[0])
    if og is None:
        return
    gx = _bind_partial_grad(block, pending, op.input("X")[0])
    block.append_op(
        "sequence_topk_avg_pooling_grad",
        {"X": [op.input("X")[0]], "ROW": [op.input("ROW")[0]],
         "COLUMN": [op.input("COLUMN")[0]],
         "pos": [op.output("pos")[0]], "Out@GRAD": [og]},
        {"X@GRAD": [gx]}, dict(op.attrs), infer_shape=False)


@register_host_op(
    "sequence_topk_avg_pooling_grad",
    inputs=[In("X", no_grad=True), In("ROW", no_grad=True),
            In("COLUMN", no_grad=True), In("pos", no_grad=True),
            In("Out@GRAD", no_grad=True)],
    outputs=[Out("X@GRAD")],
    attrs={"topks": [1], "channel_num": 1},
)
def _sequence_topk_avg_pooling_grad(executor, op, scope):
    xh = _holder(scope, op.input("X")[0])
    rh = _holder(scope, op.input("ROW")[0])
    ch = _holder(scope, op.input("COLUMN")[0])
    x = np.asarray(xh.array).reshape(-1)
    og = np.asarray(executor._read_var(scope, op.input("Out@GRAD")[0]))
    pos = np.asarray(executor._read_var(scope, op.input("pos")[0]))
    topks = [int(k) for k in op.attrs["topks"]]
    chan = int(op.attrs["channel_num"])
    max_k = topks[-1]
    k_num = len(topks)
    in_lod = _lod0(xh, x.shape[0])
    row_lod = _lod0(rh, np.asarray(rh.array).shape[0])
    col_lod = _lod0(ch, np.asarray(ch.array).shape[0])
    gx = np.zeros_like(x, dtype=np.float32)
    og = og.reshape(row_lod[-1], chan * k_num)
    for i in range(len(row_lod) - 1):
        rs = row_lod[i + 1] - row_lod[i]
        cs = col_lod[i + 1] - col_lod[i]
        for j in range(chan):
            for r in range(rs):
                base = in_lod[i] + (j * rs + r) * cs
                p0 = ((row_lod[i] + r) * chan + j) * max_k
                for kk, k in enumerate(topks):
                    g = og[row_lod[i] + r, j * k_num + kk] / k
                    for k2 in range(min(k, max_k)):
                        c = pos[p0 + k2]
                        if c >= 0:
                            gx[base + c] += g
    executor._write_var(scope, op.output("X@GRAD")[0],
                        gx.reshape(np.asarray(xh.array).shape))


OpInfoMap.instance().get("sequence_topk_avg_pooling").grad = \
    _topk_avg_grad_maker


@register_host_op(
    "similarity_focus",
    inputs=[In("X", no_grad=True)],
    outputs=[Out("Out")],
    attrs={"axis": 1, "indexes": []},
)
def _similarity_focus(executor, op, scope):
    """similarity_focus_op.h: per batch item and per selected index on
    `axis`, greedily pick maxima of the remaining 2-D slice whose row
    AND column are both unused; broadcast a 1-mask along `axis` at each
    picked cell."""
    x = np.asarray(executor._read_var(scope, op.input("X")[0]))
    axis = int(op.attrs["axis"])
    indexes = [int(i) for i in op.attrs["indexes"]]
    out = np.zeros_like(x)
    other = [a for a in (1, 2, 3) if a != axis]
    for b in range(x.shape[0]):
        for index in indexes:
            sl = np.take(x[b], index, axis=axis - 1)  # 2-D [d_o1, d_o2]
            order = np.argsort(-sl.reshape(-1), kind="stable")
            tag1 = np.zeros(sl.shape[0], bool)
            tag2 = np.zeros(sl.shape[1], bool)
            picked = 0
            limit = min(sl.shape)
            for flat in order:
                i1, i2 = divmod(int(flat), sl.shape[1])
                if tag1[i1] or tag2[i2]:
                    continue
                tag1[i1] = tag2[i2] = True
                picked += 1
                idx = [b, None, None, None]
                idx[other[0]] = i1
                idx[other[1]] = i2
                sel = [slice(None) if v is None else v for v in idx]
                out[tuple(sel)] = 1
                if picked == limit:
                    break
    executor._write_var(scope, op.output("Out")[0], out)


@register_host_op(
    "mine_hard_examples",
    inputs=[In("ClsLoss", no_grad=True), In("LocLoss", dispensable=True,
                                            no_grad=True),
            In("MatchIndices", no_grad=True), In("MatchDist",
                                                 no_grad=True)],
    outputs=[Out("NegIndices"), Out("UpdatedMatchIndices")],
    attrs={"neg_pos_ratio": 1.0, "neg_dist_threshold": 0.5,
           "mining_type": "max_negative", "sample_size": 0},
)
def _mine_hard_examples(executor, op, scope):
    """detection/mine_hard_examples_op.cc: SSD OHEM — rank eligible
    priors by loss, keep the hardest negatives (ratio-capped for
    max_negative, sample_size-capped for hard_example)."""
    cls = np.asarray(executor._read_var(scope, op.input("ClsLoss")[0]))
    loc_names = op.input("LocLoss")
    loc = (np.asarray(executor._read_var(scope, loc_names[0]))
           if loc_names else None)
    mi = np.asarray(executor._read_var(
        scope, op.input("MatchIndices")[0])).astype(np.int32)
    md = np.asarray(executor._read_var(scope, op.input("MatchDist")[0]))
    ratio = float(op.attrs.get("neg_pos_ratio", 1.0))
    thresh = float(op.attrs.get("neg_dist_threshold", 0.5))
    mtype = op.attrs.get("mining_type", "max_negative")
    sample_size = int(op.attrs.get("sample_size", 0))
    B, P = mi.shape
    upd = mi.copy()
    neg_rows: List[np.ndarray] = []
    lod = [0]
    for n in range(B):
        if mtype == "max_negative":
            elig = np.where((mi[n] == -1) & (md[n] < thresh))[0]
        else:
            elig = np.arange(P)
        loss = cls[n, elig]
        if mtype == "hard_example" and loc is not None:
            loss = loss + loc[n, elig]
        if mtype == "max_negative":
            num_pos = int((mi[n] != -1).sum())
            neg_sel = min(int(num_pos * ratio), len(elig))
        else:
            neg_sel = min(sample_size, len(elig))
        order = np.argsort(-loss, kind="stable")[:neg_sel]
        sel = set(int(e) for e in elig[order])
        negs = []
        if mtype == "hard_example":
            for m in range(P):
                if mi[n, m] > -1:
                    if m not in sel:
                        upd[n, m] = -1
                elif m in sel:
                    negs.append(m)
        else:
            negs = sorted(sel)
        neg_rows.append(np.asarray(negs, np.int32))
        lod.append(lod[-1] + len(negs))
    out = (np.concatenate(neg_rows).reshape(-1, 1) if lod[-1]
           else np.zeros((0, 1), np.int32))
    t = LoDTensor(out)
    t.set_lod([lod])
    executor._write_var(scope, op.output("NegIndices")[0], t)
    executor._write_var(scope, op.output("UpdatedMatchIndices")[0], upd)


@register_host_op(
    "retinanet_target_assign",
    inputs=[In("Anchor", no_grad=True), In("GtBoxes", no_grad=True),
            In("GtLabels", no_grad=True), In("IsCrowd", no_grad=True),
            In("ImInfo", no_grad=True)],
    outputs=[Out("LocationIndex"), Out("ScoreIndex"), Out("TargetBBox"),
             Out("TargetLabel"), Out("BBoxInsideWeight"),
             Out("ForegroundNumber")],
    attrs={"positive_overlap": 0.5, "negative_overlap": 0.4},
)
def _retinanet_target_assign(executor, op, scope):
    """rpn_target_assign_op.cc RetinanetTargetAssignKernel: focal-loss
    target assignment — ALL anchors kept (no subsampling), fg labels
    come from GtLabels, bg labeled 0, per-image foreground count + 1."""
    from .proposal_ops import _box_to_delta, _iou_matrix, _score_assign

    anchors = np.asarray(executor._read_var(
        scope, op.input("Anchor")[0])).reshape(-1, 4)
    gbh = _holder(scope, op.input("GtBoxes")[0])
    glh = _holder(scope, op.input("GtLabels")[0])
    ich = _holder(scope, op.input("IsCrowd")[0])
    gt_all = np.asarray(gbh.array).reshape(-1, 4)
    lbl_all = np.asarray(glh.array).reshape(-1)
    crowd_all = np.asarray(ich.array).reshape(-1)
    im_info = np.asarray(executor._read_var(
        scope, op.input("ImInfo")[0])).reshape(-1, 3)
    gt_lod = _lod0(gbh, gt_all.shape[0])
    pos = float(op.attrs.get("positive_overlap", 0.5))
    neg = float(op.attrs.get("negative_overlap", 0.4))
    rng = np.random.RandomState(0)
    A = anchors.shape[0]
    loc_all, score_all, lbl_out, tgt_all, w_all, fg_all = \
        [], [], [], [], [], []
    for i in range(len(gt_lod) - 1):
        gts = gt_all[gt_lod[i]:gt_lod[i + 1]]
        lbls = lbl_all[gt_lod[i]:gt_lod[i + 1]]
        crowd = crowd_all[gt_lod[i]:gt_lod[i + 1]]
        keep = crowd == 0
        gts, lbls = gts[keep] * im_info[i, 2], lbls[keep]
        iou = _iou_matrix(anchors, gts)
        fg, bg, fg_fake, inside_w = _score_assign(
            iou, -1, -1.0, pos, neg, rng, False)
        argmax = (iou.argmax(axis=1) if gts.shape[0]
                  else np.zeros(A, np.int64))
        labels = np.concatenate([
            lbls[argmax[fg]].astype(np.int32) if len(fg)
            else np.zeros(0, np.int32),
            np.zeros(len(bg), np.int32)])
        loc_all.append((np.asarray(fg_fake, np.int64)
                        + i * A).astype("int32"))
        score_all.append((np.concatenate([fg, bg]).astype(np.int64)
                          + i * A).astype("int32")
                         if (fg or bg) else np.zeros(0, np.int32))
        lbl_out.append(labels)
        tgt_all.append(_box_to_delta(anchors[fg_fake], gts[argmax[fg_fake]])
                       if len(fg_fake) else np.zeros((0, 4)))
        w_all.append(np.asarray(inside_w, "float32").reshape(-1, 4))
        fg_all.append(len(fg_fake) + 1)
    executor._write_var(scope, op.output("LocationIndex")[0],
                        np.concatenate(loc_all).astype("int32")
                        if loc_all else np.zeros(0, np.int32))
    executor._write_var(scope, op.output("ScoreIndex")[0],
                        np.concatenate(score_all).astype("int32"))
    executor._write_var(scope, op.output("TargetLabel")[0],
                        np.concatenate(lbl_out).reshape(-1, 1)
                        .astype("int32"))
    executor._write_var(scope, op.output("TargetBBox")[0],
                        np.concatenate(tgt_all).astype("float32"))
    executor._write_var(scope, op.output("BBoxInsideWeight")[0],
                        np.concatenate(w_all).astype("float32"))
    executor._write_var(scope, op.output("ForegroundNumber")[0],
                        np.asarray(fg_all, np.int32).reshape(-1, 1))


@register_host_op(
    "generate_proposal_labels",
    inputs=[In("RpnRois", no_grad=True), In("GtClasses", no_grad=True),
            In("IsCrowd", no_grad=True), In("GtBoxes", no_grad=True),
            In("ImInfo", no_grad=True)],
    outputs=[Out("Rois"), Out("LabelsInt32"), Out("BboxTargets"),
             Out("BboxInsideWeights"), Out("BboxOutsideWeights")],
    attrs={"batch_size_per_im": 256, "fg_fraction": 0.25,
           "fg_thresh": 0.5, "bg_thresh_hi": 0.5, "bg_thresh_lo": 0.0,
           "bbox_reg_weights": [0.1, 0.1, 0.2, 0.2], "class_nums": 81,
           "use_random": True, "is_cascade_rcnn": False,
           "is_cls_agnostic": False, "seed": 0},
)
def _generate_proposal_labels(executor, op, scope):
    """detection/generate_proposal_labels_op.cc SampleRoisForOneImage:
    concat gts onto rpn rois (descaled by im_scale), IoU-classify
    fg/bg, subsample by fg_fraction, emit per-class-expanded regression
    targets + weights."""
    rh = _holder(scope, op.input("RpnRois")[0])
    rois_all = np.asarray(rh.array).reshape(-1, 4)
    gch = _holder(scope, op.input("GtClasses")[0])
    ich = _holder(scope, op.input("IsCrowd")[0])
    gbh = _holder(scope, op.input("GtBoxes")[0])
    gtc_all = np.asarray(gch.array).reshape(-1)
    crowd_all = np.asarray(ich.array).reshape(-1)
    gtb_all = np.asarray(gbh.array).reshape(-1, 4)
    im_info = np.asarray(executor._read_var(
        scope, op.input("ImInfo")[0])).reshape(-1, 3)
    r_lod = _lod0(rh, rois_all.shape[0])
    g_lod = _lod0(gbh, gtb_all.shape[0])

    bpi = int(op.attrs.get("batch_size_per_im", 256))
    frac = float(op.attrs.get("fg_fraction", 0.25))
    fg_t = float(op.attrs.get("fg_thresh", 0.5))
    bg_hi = float(op.attrs.get("bg_thresh_hi", 0.5))
    bg_lo = float(op.attrs.get("bg_thresh_lo", 0.0))
    wts = [float(w) for w in op.attrs.get("bbox_reg_weights",
                                          [0.1, 0.1, 0.2, 0.2])]
    cls_nums = int(op.attrs.get("class_nums", 81))
    cls_agnostic = bool(op.attrs.get("is_cls_agnostic", False))
    cascade = bool(op.attrs.get("is_cascade_rcnn", False))
    use_random = bool(op.attrs.get("use_random", True))
    rng = np.random.RandomState(int(op.attrs.get("seed", 0)))

    from .proposal_ops import _iou_matrix, _reservoir_sampling

    rois_out, lbl_out, tgt_out, iw_out, ow_out = [], [], [], [], []
    lod = [0]
    for i in range(len(g_lod) - 1):
        scale = im_info[i, 2]
        gts = gtb_all[g_lod[i]:g_lod[i + 1]]
        gtc = gtc_all[g_lod[i]:g_lod[i + 1]]
        crowd = crowd_all[g_lod[i]:g_lod[i + 1]]
        if cascade:
            # cascade R-CNN: previous-stage rois AS-IS (no descale, no
            # gt concat, no subsampling; degenerate boxes skipped)
            boxes = rois_all[r_lod[i]:r_lod[i + 1]].copy()
        else:
            rois = rois_all[r_lod[i]:r_lod[i + 1]] / scale
            boxes = np.concatenate([gts, rois], axis=0)
        iou = _iou_matrix(boxes, gts) if gts.shape[0] else \
            np.zeros((boxes.shape[0], 0))
        maxo = iou.max(axis=1) if gts.shape[0] else \
            np.zeros(boxes.shape[0])
        # crowd gts never become samples
        if not cascade:
            maxo[:len(crowd)][crowd.astype(bool)] = -1.0
        if cascade:
            degenerate = ((boxes[:, 2] - boxes[:, 0] + 1 <= 0)
                          | (boxes[:, 3] - boxes[:, 1] + 1 <= 0))
            maxo[degenerate] = -1.0
        argm = iou.argmax(axis=1) if gts.shape[0] else \
            np.zeros(boxes.shape[0], np.int64)
        fg = list(np.where(maxo >= fg_t)[0])
        gmap = [int(argm[k]) for k in fg]
        bg = list(np.where((maxo >= bg_lo) & (maxo < bg_hi))[0])
        if not cascade:
            fg_per = int(bpi * frac)
            n_fg = min(fg_per, len(fg))
            if use_random and len(fg) > n_fg:
                pair = list(zip(fg, gmap))
                kept = _reservoir_sampling(n_fg, pair, rng, True)
                fg = [p[0] for p in kept]
                gmap = [p[1] for p in kept]
            else:
                fg, gmap = fg[:n_fg], gmap[:n_fg]
            n_bg = min(bpi - len(fg), len(bg))
            bg = _reservoir_sampling(n_bg, bg, rng, use_random)
        sel = fg + list(bg)
        sb = boxes[sel]
        labels = np.concatenate([
            gtc[gmap].astype(np.int32) if gmap else np.zeros(0, np.int32),
            np.zeros(len(bg), np.int32)])
        # regression targets for fg rows
        tgt1 = np.zeros((len(sel), 4), np.float32)
        if fg:
            from .proposal_ops import _box_to_delta

            d = _box_to_delta(boxes[fg], gts[gmap])
            tgt1[:len(fg)] = d / np.asarray(wts, np.float32)[None, :]
        # per-class expansion
        tgt = np.zeros((len(sel), 4 * cls_nums), np.float32)
        iw = np.zeros_like(tgt)
        for k, lab in enumerate(labels):
            if lab > 0:
                c = 1 if cls_agnostic else int(lab)
                tgt[k, 4 * c:4 * c + 4] = tgt1[k]
                iw[k, 4 * c:4 * c + 4] = 1.0
        rois_out.append((sb * scale).astype("float32"))
        lbl_out.append(labels.reshape(-1, 1))
        tgt_out.append(tgt)
        iw_out.append(iw)
        ow_out.append(iw.copy())
        lod.append(lod[-1] + len(sel))

    def _write_lod(slot, arrays, width):
        arr = (np.concatenate(arrays) if lod[-1]
               else np.zeros((0, width), "float32"))
        t = LoDTensor(arr)
        t.set_lod([lod])
        executor._write_var(scope, op.output(slot)[0], t)

    _write_lod("Rois", rois_out, 4)
    arr = (np.concatenate(lbl_out) if lod[-1]
           else np.zeros((0, 1), np.int32))
    t = LoDTensor(arr)
    t.set_lod([lod])
    executor._write_var(scope, op.output("LabelsInt32")[0], t)
    _write_lod("BboxTargets", tgt_out, 4 * cls_nums)
    _write_lod("BboxInsideWeights", iw_out, 4 * cls_nums)
    _write_lod("BboxOutsideWeights", ow_out, 4 * cls_nums)


def _bilinear(data, w, h):
    """data [H, W]; clamped bilinear sample at (w, h) + the 4 corner
    weights (for the backward scatter)."""
    H, W = data.shape
    w1, h1 = int(np.floor(w)), int(np.floor(h))
    w2, h2 = min(w1 + 1, W - 1), min(h1 + 1, H - 1)
    dw, dh = w - w1, h - h1
    corners = [(h1, w1, (1 - dh) * (1 - dw)), (h1, w2, (1 - dh) * dw),
               (h2, w1, dh * (1 - dw)), (h2, w2, dh * dw)]
    val = sum(data[a, b] * c for a, b, c in corners)
    return val, corners


@register_host_op(
    "deformable_psroi_pooling",
    inputs=[In("Input"), In("ROIs", no_grad=True), In("Trans")],
    outputs=[Out("Output"), Out("TopCount", no_grad=True)],
    attrs={"no_trans": False, "spatial_scale": 1.0, "output_dim": 1,
           "group_size": [1, 1], "pooled_height": 1, "pooled_width": 1,
           "part_size": [1, 1], "sample_per_part": 1, "trans_std": 0.1},
)
def _deformable_psroi_pooling(executor, op, scope):
    """deformable_psroi_pooling_op.h forward: position-sensitive ROI
    pooling whose bin sampling windows shift by learned offsets (Trans),
    averaged over sample_per_part^2 clamped bilinear samples."""
    x = np.asarray(executor._read_var(scope, op.input("Input")[0]))
    rh = _holder(scope, op.input("ROIs")[0])
    rois = np.asarray(rh.array).reshape(-1, 4)
    trans = np.asarray(executor._read_var(scope, op.input("Trans")[0]))
    a = op.attrs
    no_trans = bool(a.get("no_trans", False))
    scale = float(a.get("spatial_scale", 1.0))
    out_dim = int(a.get("output_dim", 1))
    gh_n, gw_n = [int(v) for v in a.get("group_size", [1, 1])]
    ph_n, pw_n = int(a.get("pooled_height", 1)), int(a.get("pooled_width", 1))
    part_h, part_w = [int(v) for v in a.get("part_size", [1, 1])]
    spp = int(a.get("sample_per_part", 1))
    tstd = float(a.get("trans_std", 0.1))
    B, C, H, W = x.shape
    lod = _lod0(rh, rois.shape[0])
    batch_id = np.zeros(rois.shape[0], np.int64)
    for i in range(len(lod) - 1):
        batch_id[lod[i]:lod[i + 1]] = i
    num_classes = 1 if no_trans else trans.shape[1] // 2
    cec = max(out_dim // num_classes, 1)
    N = rois.shape[0]
    out = np.zeros((N, out_dim, ph_n, pw_n), np.float32)
    cnt = np.zeros_like(out)
    for n in range(N):
        rsw = round(rois[n, 0]) * scale - 0.5
        rsh = round(rois[n, 1]) * scale - 0.5
        rew = (round(rois[n, 2]) + 1.0) * scale - 0.5
        reh = (round(rois[n, 3]) + 1.0) * scale - 0.5
        rw, rhh = max(rew - rsw, 0.1), max(reh - rsh, 0.1)
        bh, bw = rhh / ph_n, rw / pw_n
        sbh, sbw = bh / spp, bw / spp
        for ctop in range(out_dim):
            cls = ctop // cec
            for ph in range(ph_n):
                for pw in range(pw_n):
                    p_h = int(np.floor(ph / ph_n * part_h))
                    p_w = int(np.floor(pw / pw_n * part_w))
                    tx = 0.0 if no_trans else \
                        trans[n, cls * 2, p_h, p_w] * tstd
                    ty = 0.0 if no_trans else \
                        trans[n, cls * 2 + 1, p_h, p_w] * tstd
                    ws = pw * bw + rsw + tx * rw
                    hs = ph * bh + rsh + ty * rhh
                    gw = min(max(int(np.floor(pw * gw_n / pw_n)), 0),
                             gw_n - 1)
                    gh = min(max(int(np.floor(ph * gh_n / ph_n)), 0),
                             gh_n - 1)
                    c = (ctop * gh_n + gh) * gw_n + gw
                    plane = x[batch_id[n], c]
                    s, ns = 0.0, 0
                    for ih in range(spp):
                        for iw in range(spp):
                            w = ws + iw * sbw
                            h = hs + ih * sbh
                            if (w < -0.5 or w > W - 0.5 or h < -0.5
                                    or h > H - 0.5):
                                continue
                            w = min(max(w, 0.0), W - 1.0)
                            h = min(max(h, 0.0), H - 1.0)
                            v, _ = _bilinear(plane, w, h)
                            s += v
                            ns += 1
                    out[n, ctop, ph, pw] = 0.0 if ns == 0 else s / ns
                    cnt[n, ctop, ph, pw] = ns
    executor._write_var(scope, op.output("Output")[0], out)
    executor._write_var(scope, op.output("TopCount")[0], cnt)


def _dpsroi_grad_maker(block, op, pending, finalize):
    from .control_flow_ops import _bind_partial_grad

    og = finalize(op.output("Output")[0])
    if og is None:
        return
    gx = _bind_partial_grad(block, pending, op.input("Input")[0])
    gt = _bind_partial_grad(block, pending, op.input("Trans")[0])
    block.append_op(
        "deformable_psroi_pooling_grad",
        {"Input": [op.input("Input")[0]], "ROIs": [op.input("ROIs")[0]],
         "Trans": [op.input("Trans")[0]],
         "TopCount": [op.output("TopCount")[0]], "Output@GRAD": [og]},
        {"Input@GRAD": [gx], "Trans@GRAD": [gt]},
        dict(op.attrs), infer_shape=False)


@register_host_op(
    "deformable_psroi_pooling_grad",
    inputs=[In("Input", no_grad=True), In("ROIs", no_grad=True),
            In("Trans", no_grad=True), In("TopCount", no_grad=True),
            In("Output@GRAD", no_grad=True)],
    outputs=[Out("Input@GRAD"), Out("Trans@GRAD")],
    attrs={"no_trans": False, "spatial_scale": 1.0, "output_dim": 1,
           "group_size": [1, 1], "pooled_height": 1, "pooled_width": 1,
           "part_size": [1, 1], "sample_per_part": 1, "trans_std": 0.1},
)
def _deformable_psroi_pooling_grad(executor, op, scope):
    """Backward (deformable_psroi_pooling_op.h Backward kernel):
    scatter the averaged cotangent through each sample's bilinear
    weights into Input; Trans grads from the spatial derivative of the
    bilinear surface times roi extent."""
    x = np.asarray(executor._read_var(scope, op.input("Input")[0]))
    rh = _holder(scope, op.input("ROIs")[0])
    rois = np.asarray(rh.array).reshape(-1, 4)
    trans = np.asarray(executor._read_var(scope, op.input("Trans")[0]))
    cnt = np.asarray(executor._read_var(scope, op.input("TopCount")[0]))
    og = np.asarray(executor._read_var(scope,
                                       op.input("Output@GRAD")[0]))
    a = op.attrs
    no_trans = bool(a.get("no_trans", False))
    scale = float(a.get("spatial_scale", 1.0))
    out_dim = int(a.get("output_dim", 1))
    gh_n, gw_n = [int(v) for v in a.get("group_size", [1, 1])]
    ph_n, pw_n = int(a.get("pooled_height", 1)), int(a.get("pooled_width", 1))
    part_h, part_w = [int(v) for v in a.get("part_size", [1, 1])]
    spp = int(a.get("sample_per_part", 1))
    tstd = float(a.get("trans_std", 0.1))
    B, C, H, W = x.shape
    lod = _lod0(rh, rois.shape[0])
    batch_id = np.zeros(rois.shape[0], np.int64)
    for i in range(len(lod) - 1):
        batch_id[lod[i]:lod[i + 1]] = i
    num_classes = 1 if no_trans else trans.shape[1] // 2
    cec = max(out_dim // num_classes, 1)
    gx = np.zeros_like(x)
    gt = np.zeros_like(trans)
    for n in range(rois.shape[0]):
        rsw = round(rois[n, 0]) * scale - 0.5
        rsh = round(rois[n, 1]) * scale - 0.5
        rew = (round(rois[n, 2]) + 1.0) * scale - 0.5
        reh = (round(rois[n, 3]) + 1.0) * scale - 0.5
        rw, rhh = max(rew - rsw, 0.1), max(reh - rsh, 0.1)
        bh, bw = rhh / ph_n, rw / pw_n
        sbh, sbw = bh / spp, bw / spp
        for ctop in range(out_dim):
            cls = ctop // cec
            for ph in range(ph_n):
                for pw in range(pw_n):
                    ns = cnt[n, ctop, ph, pw]
                    if ns == 0:
                        continue
                    g = og[n, ctop, ph, pw] / ns
                    p_h = int(np.floor(ph / ph_n * part_h))
                    p_w = int(np.floor(pw / pw_n * part_w))
                    tx = 0.0 if no_trans else \
                        trans[n, cls * 2, p_h, p_w] * tstd
                    ty = 0.0 if no_trans else \
                        trans[n, cls * 2 + 1, p_h, p_w] * tstd
                    ws = pw * bw + rsw + tx * rw
                    hs = ph * bh + rsh + ty * rhh
                    gw = min(max(int(np.floor(pw * gw_n / pw_n)), 0),
                             gw_n - 1)
                    gh = min(max(int(np.floor(ph * gh_n / ph_n)), 0),
                             gh_n - 1)
                    c = (ctop * gh_n + gh) * gw_n + gw
                    plane = x[batch_id[n], c]
                    for ih in range(spp):
                        for iw in range(spp):
                            w = ws + iw * sbw
                            h = hs + ih * sbh
                            if (w < -0.5 or w > W - 0.5 or h < -0.5
                                    or h > H - 0.5):
                                continue
                            w = min(max(w, 0.0), W - 1.0)
                            h = min(max(h, 0.0), H - 1.0)
                            _, corners = _bilinear(plane, w, h)
                            for hh, ww, cw in corners:
                                gx[batch_id[n], c, hh, ww] += g * cw
                            if not no_trans:
                                w1, h1 = int(np.floor(w)), int(np.floor(h))
                                w2 = min(w1 + 1, W - 1)
                                h2 = min(h1 + 1, H - 1)
                                dw, dh = w - w1, h - h1
                                dvdw = ((plane[h1, w2] - plane[h1, w1])
                                        * (1 - dh)
                                        + (plane[h2, w2] - plane[h2, w1])
                                        * dh)
                                dvdh = ((plane[h2, w1] - plane[h1, w1])
                                        * (1 - dw)
                                        + (plane[h2, w2] - plane[h1, w2])
                                        * dw)
                                gt[n, cls * 2, p_h, p_w] += \
                                    g * dvdw * tstd * rw
                                gt[n, cls * 2 + 1, p_h, p_w] += \
                                    g * dvdh * tstd * rhh
    executor._write_var(scope, op.output("Input@GRAD")[0], gx)
    executor._write_var(scope, op.output("Trans@GRAD")[0], gt)


OpInfoMap.instance().get("deformable_psroi_pooling").grad = \
    _dpsroi_grad_maker


def _perspective_matrix(tw, th, rx, ry):
    """get_transform_matrix (roi_perspective_transform_op.cc:110)."""
    x0, x1, x2, x3 = rx
    y0, y1, y2, y3 = ry
    len1 = np.hypot(x0 - x1, y0 - y1)
    len2 = np.hypot(x1 - x2, y1 - y2)
    len3 = np.hypot(x2 - x3, y2 - y3)
    len4 = np.hypot(x3 - x0, y3 - y0)
    est_h = (len2 + len4) / 2.0
    est_w = (len1 + len3) / 2.0
    nh = max(2, th)
    nw = int(round(est_w * (nh - 1) / max(est_h, 1e-5))) + 1
    nw = max(2, min(nw, tw))
    dx1, dx2, dx3 = x1 - x2, x3 - x2, x0 - x1 + x2 - x3
    dy1, dy2, dy3 = y1 - y2, y3 - y2, y0 - y1 + y2 - y3
    m = np.zeros(9)
    den = dx1 * dy2 - dx2 * dy1 + 1e-5
    m[6] = (dx3 * dy2 - dx2 * dy3) / den / (nw - 1)
    m[7] = (dx1 * dy3 - dx3 * dy1) / den / (nh - 1)
    m[8] = 1.0
    m[3] = (y1 - y0 + m[6] * (nw - 1) * y1) / (nw - 1)
    m[4] = (y3 - y0 + m[7] * (nh - 1) * y3) / (nh - 1)
    m[5] = y0
    m[0] = (x1 - x0 + m[6] * (nw - 1) * x1) / (nw - 1)
    m[1] = (x3 - x0 + m[7] * (nh - 1) * x3) / (nh - 1)
    m[2] = x0
    return m


def _in_quad(x, y, rx, ry):
    """Point-in-quadrilateral via the crossing test (edge-inclusive)."""
    inside = False
    j = 3
    for i in range(4):
        xi, yi, xj, yj = rx[i], ry[i], rx[j], ry[j]
        # on-edge check
        cross = (xj - xi) * (y - yi) - (yj - yi) * (x - xi)
        if abs(cross) < 1e-6 and min(xi, xj) - 1e-6 <= x <= \
                max(xi, xj) + 1e-6 and min(yi, yj) - 1e-6 <= y <= \
                max(yi, yj) + 1e-6:
            return True
        if (yi > y) != (yj > y) and \
                x < (xj - xi) * (y - yi) / (yj - yi) + xi:
            inside = not inside
        j = i
    return inside


def _rpt_geometry(rois, lod, scale, tw, th):
    batch_id = np.zeros(rois.shape[0], np.int64)
    for i in range(len(lod) - 1):
        batch_id[lod[i]:lod[i + 1]] = i
    mats, quads = [], []
    for n in range(rois.shape[0]):
        rx = [rois[n, 2 * k] * scale for k in range(4)]
        ry = [rois[n, 2 * k + 1] * scale for k in range(4)]
        mats.append(_perspective_matrix(tw, th, rx, ry))
        quads.append((rx, ry))
    return batch_id, mats, quads


@register_host_op(
    "roi_perspective_transform",
    inputs=[In("X"), In("ROIs", no_grad=True)],
    outputs=[Out("Out"), Out("Mask", no_grad=True),
             Out("TransformMatrix", no_grad=True),
             Out("Out2InIdx", no_grad=True, dispensable=True),
             Out("Out2InWeights", no_grad=True, dispensable=True)],
    attrs={"transformed_height": 1, "transformed_width": 1,
           "spatial_scale": 1.0},
)
def _roi_perspective_transform(executor, op, scope):
    """roi_perspective_transform_op.cc: warp each quadrilateral ROI
    (8 coords) to a [C, th, tw] rectangle via the estimated perspective
    matrix + bilinear sampling; Mask marks in-quad pixels."""
    x = np.asarray(executor._read_var(scope, op.input("X")[0]))
    rh = _holder(scope, op.input("ROIs")[0])
    rois = np.asarray(rh.array).reshape(-1, 8)
    th = int(op.attrs["transformed_height"])
    tw = int(op.attrs["transformed_width"])
    scale = float(op.attrs.get("spatial_scale", 1.0))
    B, C, H, W = x.shape
    lod = _lod0(rh, rois.shape[0])
    batch_id, mats, quads = _rpt_geometry(rois, lod, scale, tw, th)
    N = rois.shape[0]
    out = np.zeros((N, C, th, tw), np.float32)
    mask = np.zeros((N, 1, th, tw), np.int32)
    # per-output-pixel bilinear corner cache (the reference's
    # Out2InIdx/Out2InWeights): flat input positions + weights, shared
    # across channels; the grad op consumes these instead of re-deriving
    # the geometry
    o2i_idx = np.zeros((N * th * tw, 4), np.int64)
    o2i_w = np.zeros((N * th * tw, 4), np.float32)
    for n in range(N):
        m = mats[n]
        rx, ry = quads[n]
        for oh in range(th):
            for ow in range(tw):
                wdet = m[6] * ow + m[7] * oh + m[8]
                iw = (m[0] * ow + m[1] * oh + m[2]) / wdet
                ih = (m[3] * ow + m[4] * oh + m[5]) / wdet
                if not _in_quad(iw, ih, rx, ry):
                    continue
                if iw <= -0.5 or iw >= W - 0.5 or ih <= -0.5 \
                        or ih >= H - 0.5:
                    continue
                mask[n, 0, oh, ow] = 1
                plane_w = min(max(iw, 0.0), W - 1.0)
                plane_h = min(max(ih, 0.0), H - 1.0)
                flat = (n * th + oh) * tw + ow
                for k, (hh, ww, cw) in enumerate(
                        _bilinear(x[batch_id[n], 0], plane_w,
                                  plane_h)[1]):
                    o2i_idx[flat, k] = hh * W + ww
                    o2i_w[flat, k] = cw
                for c in range(C):
                    v, _ = _bilinear(x[batch_id[n], c], plane_w,
                                     plane_h)
                    out[n, c, oh, ow] = v
    executor._write_var(scope, op.output("Out")[0], out)
    executor._write_var(scope, op.output("Mask")[0], mask)
    executor._write_var(
        scope, op.output("TransformMatrix")[0],
        np.stack(mats).astype("float32") if mats
        else np.zeros((0, 9), "float32"))
    if op.output("Out2InIdx"):
        executor._write_var(scope, op.output("Out2InIdx")[0], o2i_idx)
    if op.output("Out2InWeights"):
        executor._write_var(scope, op.output("Out2InWeights")[0], o2i_w)


def _rpt_grad_maker(block, op, pending, finalize):
    from .control_flow_ops import _bind_partial_grad

    og = finalize(op.output("Out")[0])
    if og is None:
        return
    gx = _bind_partial_grad(block, pending, op.input("X")[0])
    block.append_op(
        "roi_perspective_transform_grad",
        {"X": [op.input("X")[0]], "ROIs": [op.input("ROIs")[0]],
         "Mask": [op.output("Mask")[0]],
         "Out2InIdx": list(op.output("Out2InIdx")),
         "Out2InWeights": list(op.output("Out2InWeights")),
         "Out@GRAD": [og]},
        {"X@GRAD": [gx]}, dict(op.attrs), infer_shape=False)


@register_host_op(
    "roi_perspective_transform_grad",
    inputs=[In("X", no_grad=True), In("ROIs", no_grad=True),
            In("Mask", no_grad=True),
            In("Out2InIdx", no_grad=True, dispensable=True),
            In("Out2InWeights", no_grad=True, dispensable=True),
            In("Out@GRAD", no_grad=True)],
    outputs=[Out("X@GRAD")],
    attrs={"transformed_height": 1, "transformed_width": 1,
           "spatial_scale": 1.0},
)
def _roi_perspective_transform_grad(executor, op, scope):
    """Scatter through the forward's cached bilinear corners
    (Out2InIdx/Out2InWeights) when present — guaranteeing the same
    geometry as the forward — else re-derive it."""
    x = np.asarray(executor._read_var(scope, op.input("X")[0]))
    rh = _holder(scope, op.input("ROIs")[0])
    rois = np.asarray(rh.array).reshape(-1, 8)
    mask = np.asarray(executor._read_var(scope, op.input("Mask")[0]))
    og = np.asarray(executor._read_var(scope, op.input("Out@GRAD")[0]))
    th = int(op.attrs["transformed_height"])
    tw = int(op.attrs["transformed_width"])
    scale = float(op.attrs.get("spatial_scale", 1.0))
    B, C, H, W = x.shape
    lod = _lod0(rh, rois.shape[0])
    batch_id = np.zeros(rois.shape[0], np.int64)
    for i in range(len(lod) - 1):
        batch_id[lod[i]:lod[i + 1]] = i
    idx_names = op.input("Out2InIdx")
    cached = bool(idx_names) and executor._read_var(
        scope, idx_names[0]) is not None
    if cached:
        o2i_idx = np.asarray(executor._read_var(scope, idx_names[0]))
        o2i_w = np.asarray(executor._read_var(
            scope, op.input("Out2InWeights")[0]))
    else:
        _bid, mats, _quads = _rpt_geometry(rois, lod, scale, tw, th)
    gx = np.zeros_like(x)
    for n in range(rois.shape[0]):
        for oh in range(th):
            for ow in range(tw):
                if mask[n, 0, oh, ow] == 0:
                    continue
                if cached:
                    flat = (n * th + oh) * tw + ow
                    for k in range(4):
                        hh, ww = divmod(int(o2i_idx[flat, k]), W)
                        cw = o2i_w[flat, k]
                        gx[batch_id[n], :, hh, ww] += og[n, :, oh, ow] * cw
                    continue
                m = mats[n]
                wdet = m[6] * ow + m[7] * oh + m[8]
                iw = (m[0] * ow + m[1] * oh + m[2]) / wdet
                ih = (m[3] * ow + m[4] * oh + m[5]) / wdet
                plane_w = min(max(iw, 0.0), W - 1.0)
                plane_h = min(max(ih, 0.0), H - 1.0)
                _, corners = _bilinear(x[batch_id[n], 0], plane_w,
                                       plane_h)
                for hh, ww, cw in corners:
                    gx[batch_id[n], :, hh, ww] += og[n, :, oh, ow] * cw
    executor._write_var(scope, op.output("X@GRAD")[0], gx)


OpInfoMap.instance().get("roi_perspective_transform").grad = \
    _rpt_grad_maker


def _rasterize_polys(polys, box, M):
    """Union of polygons clipped to ``box``, sampled on an M x M grid
    at pixel centers (Polys2MaskWrtBox — the reference rasterizes via
    COCO RLE upsampling; pixel-center crossing sampling matches it away
    from sub-pixel boundary ties, which is the documented difference)."""
    x0, y0, x1, y1 = box
    w = max(x1 - x0, 1e-5)
    h = max(y1 - y0, 1e-5)
    mask = np.zeros((M, M), np.int32)
    for poly in polys:
        pts = np.asarray(poly, np.float64).reshape(-1, 2)
        # roi-relative, scaled to the grid
        px = (pts[:, 0] - x0) * M / w
        py = (pts[:, 1] - y0) * M / h
        for gy in range(M):
            for gx_ in range(M):
                cx, cy = gx_ + 0.5, gy + 0.5
                inside = False
                j = len(px) - 1
                for i in range(len(px)):
                    if (py[i] > cy) != (py[j] > cy) and \
                            cx < (px[j] - px[i]) * (cy - py[i]) / \
                            (py[j] - py[i]) + px[i]:
                        inside = not inside
                    j = i
                if inside:
                    mask[gy, gx_] = 1
    return mask


@register_host_op(
    "generate_mask_labels",
    inputs=[In("ImInfo", no_grad=True), In("GtClasses", no_grad=True),
            In("IsCrowd", no_grad=True), In("GtSegms", no_grad=True),
            In("Rois", no_grad=True), In("LabelsInt32", no_grad=True)],
    outputs=[Out("MaskRois"), Out("RoiHasMaskInt32"), Out("MaskInt32")],
    attrs={"num_classes": 81, "resolution": 14},
)
def _generate_mask_labels(executor, op, scope):
    """generate_mask_labels_op.cc: per foreground roi, pick the
    max-overlap mask gt (by its polygons' bounding box), rasterize its
    polygons w.r.t. the roi, and expand into the per-class target
    layout Mask-RCNN trains against."""
    im_info = np.asarray(executor._read_var(
        scope, op.input("ImInfo")[0])).reshape(-1, 3)
    gch = _holder(scope, op.input("GtClasses")[0])
    ich = _holder(scope, op.input("IsCrowd")[0])
    sgh = _holder(scope, op.input("GtSegms")[0])
    roih = _holder(scope, op.input("Rois")[0])
    lblh = _holder(scope, op.input("LabelsInt32")[0])
    gtc = np.asarray(gch.array).reshape(-1)
    crowd = np.asarray(ich.array).reshape(-1)
    segs = np.asarray(sgh.array).reshape(-1, 2)
    rois = np.asarray(roih.array).reshape(-1, 4)
    labels = np.asarray(lblh.array).reshape(-1)
    res = int(op.attrs.get("resolution", 14))
    ncls = int(op.attrs.get("num_classes", 81))
    # GtSegms: the LAST two LoD levels are gt -> polys and poly ->
    # points (reference feeds carry a leading image -> gt level too,
    # the same tolerance _lod0 applies)
    slod = sgh.lod()
    lod1, lod2 = list(slod[-2]), list(slod[-1])
    g_lod = _lod0(gch, gtc.shape[0])
    r_lod = _lod0(roih, rois.shape[0])

    from .proposal_ops import _iou_matrix

    out_rois, out_has, out_mask, lod = [], [], [], [0]
    for b in range(len(g_lod) - 1):
        scale = im_info[b, 2]
        g0, g1 = g_lod[b], g_lod[b + 1]
        r0, r1 = r_lod[b], r_lod[b + 1]
        polys_per_gt, boxes = [], []
        for i in range(g0, g1):
            if gtc[i] > 0 and crowd[i] == 0:
                polys = []
                for j in range(lod1[i], lod1[i + 1]):
                    polys.append(segs[lod2[j]:lod2[j + 1]])
                polys_per_gt.append(polys)
                allp = np.concatenate(polys, axis=0)
                boxes.append([allp[:, 0].min(), allp[:, 1].min(),
                              allp[:, 0].max(), allp[:, 1].max()])
        boxes = np.asarray(boxes, np.float32).reshape(-1, 4)
        fg = [k for k in range(r0, r1) if labels[k] > 0]
        if fg and len(polys_per_gt):
            rois_fg = rois[fg] / scale
            iou = _iou_matrix(rois_fg, boxes)
            pick = iou.argmax(axis=1)
            masks = np.full((len(fg), ncls * res * res), -1, np.int32)
            for k, ridx in enumerate(fg):
                m = _rasterize_polys(polys_per_gt[pick[k]],
                                     rois_fg[k], res)
                c = int(labels[ridx])
                masks[k, c * res * res:(c + 1) * res * res] = \
                    m.reshape(-1)
            out_rois.append((rois_fg * scale).astype("float32"))
            out_has.append(np.asarray(fg, np.int32) - r0)
            out_mask.append(masks)
            lod.append(lod[-1] + len(fg))
        else:  # no fg: one bg placeholder with all -1 targets
            bg = next((k for k in range(r0, r1) if labels[k] == 0), None)
            # a zero-roi image still emits exactly ONE row so the LoD
            # stays in sync with the data across all three outputs
            row = (rois[bg:bg + 1] if bg is not None
                   else np.zeros((1, 4), rois.dtype))
            out_rois.append(row.astype("float32"))
            out_has.append(np.asarray(
                [bg - r0 if bg is not None else 0], np.int32))
            out_mask.append(np.full((1, ncls * res * res), -1,
                                    np.int32))
            lod.append(lod[-1] + 1)

    def _wl(slot, arrs):
        arr = np.concatenate(arrs)
        t = LoDTensor(arr)
        t.set_lod([lod])
        executor._write_var(scope, op.output(slot)[0], t)

    _wl("MaskRois", out_rois)
    _wl("RoiHasMaskInt32", [a.reshape(-1, 1) for a in out_has])
    _wl("MaskInt32", out_mask)
