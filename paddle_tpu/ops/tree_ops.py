"""tree_conv — tree-based convolution (TBCNN, arXiv:1409.5718).

Parity: /root/reference/paddle/fluid/operators/tree_conv_op.cc +
math/tree2col.cc. Host-tier: patch construction walks the tree
structure (data-dependent), the matmul itself is dense.

Shapes: NodesVector [B, N, F]; EdgeSet [B, E, 2] int32 (1-indexed
parent->child, a 0 terminates); Filter [F, 3, out_size, num_filters]
(the 3 axis orders eta_l, eta_r, eta_t); Out [B, N, out_size,
num_filters] (rows past the sample's node count stay zero).
"""
from __future__ import annotations

import numpy as np

from ..core.registry import In, Out, register_host_op


def _construct_tree(edges):
    """Adjacency (1-indexed) + node count (tree2col.cc:54
    construct_tree: counts edges with both endpoints nonzero, +1)."""
    node_count = 0
    for u, v in edges:
        if u != 0 and v != 0:
            node_count += 1
    node_count += 1
    tr = [[] for _ in range(node_count + 2)]
    for u, v in edges:
        if u != 0 and v != 0:
            tr[int(u)].append(int(v))
        else:
            break
    return tr, node_count


def _construct_patch(root, max_depth, tr):
    """DFS patch with (node, index, pclen, depth) entries — the exact
    stack walk of tree2col.cc:21 (patch stores 1-based child index)."""
    stack = [(root, 1, 1, 0)]
    patch = [(root, 1, 1, 0)]
    visited = {root}
    while stack:
        node, _idx, _pclen, depth = stack[-1]
        end = True
        kids = tr[node] if node < len(tr) else []
        sz = len(kids)
        for i, v in enumerate(kids):
            if v not in visited and depth + 1 < max_depth:
                visited.add(v)
                stack.append((v, i, sz, depth + 1))
                patch.append((v, i + 1, sz, depth + 1))
                end = False
        if end:
            stack.pop()
    return patch


def _etas(index, pclen, depth, max_depth):
    """tree2col.h:35-52: eta_t = (d_f - depth)/d_f; eta_l =
    (1-eta_t)*temp with temp the sibling position; eta_r =
    (1-eta_t)*(1 - eta_l) — note eta_l here is the FULL eta_l, not
    temp."""
    eta_t = (max_depth - depth) / float(max_depth)
    temp = 0.5 if pclen == 1 else (index - 1.0) / (pclen - 1.0)
    eta_l = (1.0 - eta_t) * temp
    eta_r = (1.0 - eta_t) * (1.0 - eta_l)
    return eta_l, eta_r, eta_t


def _patch_matrix(features, edges, max_depth):
    """[patch_count, F*3] column layout i*3 + {0:l, 1:r, 2:t}
    (tree2col.cc:113-121), plus the (u, v, coeffs) triples the backward
    scatter reuses."""
    f = features
    n_feat = f.shape[1]
    tr, node_count = _construct_tree(edges)
    rows = []
    triples = []
    for u in range(1, node_count + 1):
        patch = _construct_patch(u, max_depth, tr)
        row = np.zeros((n_feat, 3), f.dtype)
        for node, index, pclen, depth in patch:
            el, er, et = _etas(index, pclen, depth, max_depth)
            row[:, 0] += el * f[node - 1]
            row[:, 1] += er * f[node - 1]
            row[:, 2] += et * f[node - 1]
            triples.append((u - 1, node - 1, (el, er, et)))
        rows.append(row.reshape(-1))
    return (np.stack(rows) if rows
            else np.zeros((0, n_feat * 3), f.dtype)), triples, node_count


@register_host_op(
    "tree_conv",
    inputs=[In("NodesVector"), In("EdgeSet", no_grad=True),
            In("Filter")],
    outputs=[Out("Out")],
    attrs={"max_depth": 2},
)
def _tree_conv(executor, op, scope):
    feats = np.asarray(executor._read_var(scope,
                                          op.input("NodesVector")[0]))
    edges = np.asarray(executor._read_var(scope, op.input("EdgeSet")[0]))
    filt = np.asarray(executor._read_var(scope, op.input("Filter")[0]))
    max_depth = int(op.attrs.get("max_depth", 2))
    bsz, n_nodes, n_feat = feats.shape
    out_size, n_filters = filt.shape[2], filt.shape[3]
    w2 = filt.reshape(n_feat * 3, out_size * n_filters)
    out = np.zeros((bsz, n_nodes, out_size, n_filters), feats.dtype)
    for b in range(bsz):
        patch, _triples, count = _patch_matrix(feats[b], edges[b],
                                               max_depth)
        if count:
            out[b, :count] = (patch @ w2).reshape(count, out_size,
                                                  n_filters)
    executor._write_var(scope, op.output("Out")[0], out)


@register_host_op(
    "tree_conv_grad",
    inputs=[In("NodesVector", no_grad=True), In("EdgeSet", no_grad=True),
            In("Filter", no_grad=True), In("Out@GRAD", no_grad=True)],
    outputs=[Out("NodesVector@GRAD"), Out("Filter@GRAD")],
    attrs={"max_depth": 2},
)
def _tree_conv_grad(executor, op, scope):
    """dFilter = patchᵀ @ dOut; dNodes scatters the eta coefficients
    back (the Col2TreeFunctor transpose)."""
    feats = np.asarray(executor._read_var(scope,
                                          op.input("NodesVector")[0]))
    edges = np.asarray(executor._read_var(scope, op.input("EdgeSet")[0]))
    filt = np.asarray(executor._read_var(scope, op.input("Filter")[0]))
    og = np.asarray(executor._read_var(scope, op.input("Out@GRAD")[0]))
    max_depth = int(op.attrs.get("max_depth", 2))
    bsz, n_nodes, n_feat = feats.shape
    out_size, n_filters = filt.shape[2], filt.shape[3]
    w2 = filt.reshape(n_feat * 3, out_size * n_filters)
    d_filter = np.zeros_like(w2)
    d_nodes = np.zeros_like(feats)
    for b in range(bsz):
        patch, triples, count = _patch_matrix(feats[b], edges[b],
                                              max_depth)
        if not count:
            continue
        og_flat = og[b, :count].reshape(count, out_size * n_filters)
        d_filter += patch.T @ og_flat
        col = og_flat @ w2.T            # [count, F*3]
        col = col.reshape(count, n_feat, 3)
        for u, v, (el, er, et) in triples:
            d_nodes[b, v] += (el * col[u, :, 0] + er * col[u, :, 1]
                              + et * col[u, :, 2])
    outs = op.output("NodesVector@GRAD")
    if outs:
        executor._write_var(scope, outs[0], d_nodes)
    fouts = op.output("Filter@GRAD")
    if fouts:
        executor._write_var(scope, fouts[0],
                            d_filter.reshape(filt.shape))
