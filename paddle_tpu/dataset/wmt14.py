"""WMT14 en-fr reader creators (reference
python/paddle/dataset/wmt14.py).

Sample contract: (src_ids, trg_ids, trg_ids_next) with <s>/<e>/<unk>
at ids 0/1/2 (reference constants). Synthetic fallback: a reversible
toy translation (target = per-token mapped source), deterministic and
learnable by seq2seq book tests.
"""
from __future__ import annotations

import os
import tarfile

import numpy as np

from .common import DATA_HOME

__all__ = ["train", "test", "get_dict"]

START = "<s>"
END = "<e>"
UNK = "<unk>"
UNK_IDX = 2

_SRC_VOCAB = 30
_TRG_VOCAB = 30


def _archive():
    p = os.path.join(DATA_HOME, "wmt14", "wmt14.tgz")
    return p if os.path.exists(p) else None


def _synthetic_pairs(n, seed, dict_size):
    rng = np.random.RandomState(seed)
    usable = max(4, min(dict_size, _SRC_VOCAB) - 3)
    for _ in range(n):
        length = int(rng.randint(3, 9))
        src = [int(rng.randint(3, 3 + usable)) for _ in range(length)]
        # toy translation: shift each token by 1 inside the usable band
        trg = [3 + ((t - 3 + 1) % usable) for t in src]
        yield src, [0] + trg, trg + [1]  # (src, <s>+trg, trg+<e>)


def _reader_creator(tar_file, file_name, dict_size):
    def reader():
        src_dict, trg_dict = __read_dicts__(tar_file, dict_size)
        with tarfile.open(tar_file, mode="r") as f:
            names = [n for n in f.getnames() if file_name in n]
            for name in names:
                for line in f.extractfile(name):
                    cols = line.decode("utf-8").strip().split("\t")
                    if len(cols) != 2:
                        continue
                    src = [src_dict.get(w, UNK_IDX)
                           for w in cols[0].split()]
                    trg = [trg_dict.get(w, UNK_IDX)
                           for w in cols[1].split()]
                    yield src, [0] + trg, trg + [1]

    return reader


def __read_dicts__(tar_file, dict_size):
    with tarfile.open(tar_file, mode="r") as f:
        def load(name):
            d = {START: 0, END: 1, UNK: 2}
            for i, line in enumerate(f.extractfile(name)):
                if len(d) >= dict_size:
                    break
                d[line.decode("utf-8").strip()] = len(d)
            return d

        names = f.getnames()
        src = next(n for n in names if "src.dict" in n)
        trg = next(n for n in names if "trg.dict" in n)
        return load(src), load(trg)


def train(dict_size):
    if _archive() is not None:
        return _reader_creator(_archive(), "train/train", dict_size)
    return lambda: _synthetic_pairs(2000, seed=60, dict_size=dict_size)


def test(dict_size):
    if _archive() is not None:
        return _reader_creator(_archive(), "test/test", dict_size)
    return lambda: _synthetic_pairs(200, seed=61, dict_size=dict_size)


def get_dict(dict_size, reverse=True):
    """id<->word dicts; synthetic mode uses 'w<i>' tokens."""
    if _archive() is not None:
        src, trg = __read_dicts__(_archive(), dict_size)
    else:
        usable = max(4, min(dict_size, _SRC_VOCAB))
        src = {START: 0, END: 1, UNK: 2}
        for i in range(3, usable):
            src["w%d" % i] = i
        trg = dict(src)
    if reverse:
        src = {v: k for k, v in src.items()}
        trg = {v: k for k, v in trg.items()}
    return src, trg
