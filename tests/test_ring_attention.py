"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

Runs on the virtual 8-device CPU mesh (conftest.py). Oracle is dense
single-device attention; the parallel paths must match it to float32
tolerances (the math is exact, not approximate).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.parallel.mesh_utils import make_mesh
from paddle_tpu.parallel.ring_attention import (
    reference_attention, ring_attention, sequence_parallel_attention,
    ulysses_attention)

B, H, S, D = 2, 8, 32, 16  # S sharded 8-way -> S_local = 4


def _inputs(seed=0, dtype="float32"):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(B, H, S, D).astype(dtype))
    k = jnp.asarray(rng.randn(B, H, S, D).astype(dtype))
    v = jnp.asarray(rng.randn(B, H, S, D).astype(dtype))
    return q, k, v


@pytest.fixture(scope="module")
def mesh():
    return make_mesh([8], ["sp"])


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_dense(mesh, causal):
    q, k, v = _inputs(0)
    ref = reference_attention(q, k, v, causal=causal)
    out = sequence_parallel_attention(q, k, v, mesh, "sp", mode="ring",
                                      causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_dense(mesh, causal):
    q, k, v = _inputs(1)
    ref = reference_attention(q, k, v, causal=causal)
    out = sequence_parallel_attention(q, k, v, mesh, "sp", mode="ulysses",
                                      causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_bf16_smoke(mesh):
    q, k, v = _inputs(2, "float32")
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    out = sequence_parallel_attention(qb, kb, vb, mesh, "sp", causal=True)
    assert out.dtype == jnp.bfloat16
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float32), np.asarray(ref),
        rtol=5e-2, atol=5e-2)


def test_ring_differentiable(mesh):
    """Grads flow through the ppermute ring (training, not just serving)."""
    q, k, v = _inputs(3)

    def loss(q, k, v):
        out = sequence_parallel_attention(q, k, v, mesh, "sp", causal=True)
        return (out.astype(jnp.float32) ** 2).sum()

    def loss_ref(q, k, v):
        out = reference_attention(q, k, v, causal=True)
        return (out.astype(jnp.float32) ** 2).sum()

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_ring_dp_sp_2d_mesh():
    """dp x sp 2-D mesh: batch and sequence sharded simultaneously."""
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.parallel.mesh_utils import shard_map_compat

    mesh2 = make_mesh([2, 4], ["dp", "sp"])
    q, k, v = _inputs(4)

    def local(q, k, v):
        return ring_attention(q, k, v, "sp", causal=True, axis_size=4)

    spec = P("dp", None, "sp", None)
    smap = shard_map_compat(local, mesh2, in_specs=(spec,) * 3,
                            out_specs=spec)
    out = jax.jit(smap)(q, k, v)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
