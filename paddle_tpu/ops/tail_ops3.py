"""Registry-parity wave 4: the remaining reference op tail.

Each op's docstring cites its reference kernel. Pure-math ops are jax
fns (XLA-compiled, auto-VJP); scope/PS-coupled ones are host ops —
matching the reference's kernel-less OperatorBase split.
"""
from __future__ import annotations

import numpy as np

try:  # jax is lazy elsewhere; this module is import-time registered
    import jax
    import jax.numpy as jnp
except Exception:  # pragma: no cover
    jax = jnp = None

from ..core.registry import (In, Out, RNG_SEED_ATTR, OpInfoMap,
                             register_host_op, register_op)
from ..core.tensor import LoDTensor


@register_op(
    "maxout",
    inputs=[In("X")],
    outputs=[Out("Out")],
    attrs={"groups": 1, "axis": 1},
)
def _maxout(ins, attrs):
    """Channel-group max (math/maxouting.cc MaxOutFunctor): the channel
    axis splits into (C/groups, groups) and reduces max over groups."""
    x = ins["X"]
    g = int(attrs.get("groups", 1))
    axis = int(attrs.get("axis", 1))
    if axis < 0:
        axis += x.ndim
    c = x.shape[axis]
    shape = x.shape[:axis] + (c // g, g) + x.shape[axis + 1:]
    return {"Out": jnp.max(x.reshape(shape), axis=axis + 1)}


@register_op(
    "add_position_encoding",
    inputs=[In("X")],
    outputs=[Out("Out")],
    attrs={"alpha": 1.0, "beta": 1.0},
)
def _add_position_encoding(ins, attrs):
    """Sinusoidal position encoding over [B, T, E]
    (add_position_encoding_op.h): out[..., k] = alpha*x + beta*sin(val),
    out[..., half+k] = alpha*x + beta*cos(val),
    val = t / 10000^(k/(half-1))."""
    x = ins["X"]
    alpha = attrs.get("alpha", 1.0)
    beta = attrs.get("beta", 1.0)
    B, T, E = x.shape
    half = E // 2
    t = jnp.arange(T, dtype=x.dtype)[:, None]
    k = jnp.arange(half, dtype=x.dtype)[None, :]
    denom = jnp.power(10000.0, k / max(half - 1, 1))
    val = t / denom                                   # [T, half]
    pe = jnp.concatenate([jnp.sin(val), jnp.cos(val)], axis=1)  # [T, E]
    return {"Out": x * alpha + pe[None] * beta}


@register_op(
    "sampling_id",
    inputs=[In("X", no_grad=True)],
    outputs=[Out("Out")],
    attrs={"min": 0.0, "max": 1.0, "seed": 0},
    needs_rng=True,
    grad=None,
)
def _sampling_id(ins, attrs):
    """Sample one column id per row of a [B, C] probability matrix
    (sampling_id_op.h: u ~ uniform(min, max), the first prefix-sum >= u,
    defaulting to the LAST index when u exceeds the row total)."""
    x = ins["X"]
    key = jax.random.PRNGKey(ins[RNG_SEED_ATTR].astype(jnp.uint32))
    u = jax.random.uniform(key, (x.shape[0], 1), dtype=x.dtype,
                           minval=attrs.get("min", 0.0),
                           maxval=attrs.get("max", 1.0))
    cum = jnp.cumsum(x, axis=1)
    hit = cum >= u
    idx = jnp.where(jnp.any(hit, axis=1), jnp.argmax(hit, axis=1),
                    x.shape[1] - 1)
    return {"Out": idx.astype(jnp.int64)}


@register_op(
    "spp",
    inputs=[In("X")],
    outputs=[Out("Out")],
    attrs={"pyramid_height": 1, "pooling_type": "max"},
)
def _spp(ins, attrs):
    """Spatial pyramid pooling (spp_op.h): levels h=0..H-1 pool NCHW to
    2^h x 2^h bins; flattened bins concat to [N, C*(4^H-1)/3]."""
    x = ins["X"]
    n, c = x.shape[0], x.shape[1]
    ptype = attrs.get("pooling_type", "max")
    outs = []
    for h in range(int(attrs.get("pyramid_height", 1))):
        bins = 2 ** h
        ksize_h = -(-x.shape[2] // bins)
        ksize_w = -(-x.shape[3] // bins)
        pad_h = (ksize_h * bins - x.shape[2] + 1) // 2
        pad_w = (ksize_w * bins - x.shape[3] + 1) // 2
        from .conv_ops import _pool_impl

        p = _pool_impl(x, {"pooling_type": ptype,
                           "ksize": [ksize_h, ksize_w],
                           "strides": [ksize_h, ksize_w],
                           "paddings": [pad_h, pad_w],
                           "exclusive": False}, 2)
        outs.append(p.reshape(n, -1))
    return {"Out": jnp.concatenate(outs, axis=1)}


@register_op(
    "is_empty",
    inputs=[In("X", no_grad=True)],
    outputs=[Out("Out")],
    grad=None,
)
def _is_empty(ins, attrs):
    """is_empty_op.h: scalar bool, numel == 0."""
    return {"Out": jnp.asarray(ins["X"].size == 0)}


@register_op(
    "fill",
    inputs=[],
    outputs=[Out("Out")],
    attrs={"value": [], "shape": [], "dtype": 5, "force_cpu": False},
    grad=None,
)
def _fill(ins, attrs):
    """fill_op.cc: tensor from an explicit per-element value list."""
    from ..core import dtypes as _dt

    dt = _dt.to_numpy_dtype(attrs.get("dtype", 5))
    vals = np.asarray(attrs.get("value", []), dtype=dt)
    return {"Out": jnp.asarray(vals.reshape(tuple(attrs["shape"])))}


@register_op(
    "fill_zeros_like2",
    inputs=[In("X", no_grad=True)],
    outputs=[Out("Out")],
    attrs={"dtype": 5},
    grad=None,
)
def _fill_zeros_like2(ins, attrs):
    from ..core import dtypes as _dt

    return {"Out": jnp.zeros(ins["X"].shape,
                             _dt.to_numpy_dtype(attrs.get("dtype", 5)))}


def _batch_size_like_shape(x, attrs):
    shape = [int(s) for s in attrs["shape"]]
    in_idx = int(attrs.get("input_dim_idx", 0))
    out_idx = int(attrs.get("output_dim_idx", 0))
    shape[out_idx] = x.shape[in_idx]
    return tuple(shape)


@register_op(
    "gaussian_random_batch_size_like",
    inputs=[In("Input", no_grad=True)],
    outputs=[Out("Out")],
    attrs={"shape": [], "input_dim_idx": 0, "output_dim_idx": 0,
           "mean": 0.0, "std": 1.0, "seed": 0, "dtype": 5},
    needs_rng=True,
    grad=None,
)
def _gaussian_random_bsl(ins, attrs):
    """gaussian_random_batch_size_like_op.cc: normal noise whose batch
    dim copies the input's."""
    shape = _batch_size_like_shape(ins["Input"], attrs)
    key = jax.random.PRNGKey(ins[RNG_SEED_ATTR].astype(jnp.uint32))
    return {"Out": attrs.get("mean", 0.0)
            + attrs.get("std", 1.0) * jax.random.normal(
                key, shape, dtype=jnp.float32)}


@register_op(
    "uniform_random_batch_size_like",
    inputs=[In("Input", no_grad=True)],
    outputs=[Out("Out")],
    attrs={"shape": [], "input_dim_idx": 0, "output_dim_idx": 0,
           "min": -1.0, "max": 1.0, "seed": 0, "dtype": 5},
    needs_rng=True,
    grad=None,
)
def _uniform_random_bsl(ins, attrs):
    shape = _batch_size_like_shape(ins["Input"], attrs)
    key = jax.random.PRNGKey(ins[RNG_SEED_ATTR].astype(jnp.uint32))
    return {"Out": jax.random.uniform(
        key, shape, minval=attrs.get("min", -1.0),
        maxval=attrs.get("max", 1.0), dtype=jnp.float32)}


@register_op(
    "modified_huber_loss",
    inputs=[In("X"), In("Y", no_grad=True)],
    outputs=[Out("Out"), Out("IntermediateVal", no_grad=True)],
)
def _modified_huber_loss(ins, attrs):
    """modified_huber_loss_op.h: a = x*(2y-1);
    loss = -4a (a < -1) | (1-a)^2 (a < 1) | 0."""
    x, y = ins["X"], ins["Y"]
    a = x * (2.0 * y - 1.0)
    loss = jnp.where(a < -1.0, -4.0 * a,
                     jnp.where(a < 1.0, jnp.square(1.0 - a), 0.0))
    return {"Out": loss, "IntermediateVal": a}


@register_op(
    "dequantize_abs_max",
    inputs=[In("X", no_grad=True), In("Scale", no_grad=True)],
    outputs=[Out("Out")],
    attrs={"max_range": 127.0},
    grad=None,
)
def _dequantize_abs_max(ins, attrs):
    """dequantize_abs_max_op.cc: out = scale * x / max_range."""
    return {"Out": ins["X"].astype(jnp.float32)
            * ins["Scale"].reshape(()) / attrs.get("max_range", 127.0)}


@register_op(
    "dequantize_log",
    inputs=[In("X", no_grad=True), In("Dict", no_grad=True)],
    outputs=[Out("Out")],
    grad=None,
)
def _dequantize_log(ins, attrs):
    """dequantize_log_op.cc: 8-bit log-quantized codes; x < 0 indexes
    dict[x+128] positively, x >= 0 gives -dict[x]."""
    x, d = ins["X"], ins["Dict"].reshape(-1)
    xi = x.astype(jnp.int32)
    return {"Out": jnp.where(xi < 0, jnp.take(d, xi + 128),
                             -jnp.take(d, xi))}


@register_op(
    "seed",
    inputs=[],
    outputs=[Out("Out")],
    attrs={"seed": 0},
    grad=None,
)
def _seed(ins, attrs):
    """seed_op.cc: materialize the dropout seed as a tensor."""
    return {"Out": jnp.asarray([int(attrs.get("seed", 0))],
                               dtype=jnp.int32)}


# multiclass_nms2 (multiclass_nms2 registration in multiclass_nms_op.cc)
# shares the v1 kernel — v1 here already emits the optional Index output.
_nms_info = OpInfoMap.instance().get("multiclass_nms")
register_host_op(
    "multiclass_nms2",
    inputs=[In("BBoxes", no_grad=True), In("Scores", no_grad=True)],
    outputs=[Out("Out"), Out("Index", dispensable=True)],
    attrs=dict(_nms_info.attrs),
)(_nms_info.host_fn)

# infer-mode aliases (REGISTER_OPERATOR(conditional_block_infer, ...),
# merge_lod_tensor_infer): same kernels, pruned-grad registration
_cb = OpInfoMap.instance().get("conditional_block")
register_host_op("conditional_block_infer",
                 inputs=list(_cb.inputs), outputs=list(_cb.outputs),
                 attrs=dict(_cb.attrs))(_cb.host_fn)
_ml = OpInfoMap.instance().get("merge_lod_tensor")
register_host_op("merge_lod_tensor_infer",
                 inputs=list(_ml.inputs), outputs=list(_ml.outputs),
                 attrs=dict(_ml.attrs))(_ml.host_fn)


@register_host_op(
    "get_places",
    inputs=[],
    outputs=[Out("Out")],
    attrs={"device_count": 0, "device_type": "CPU"},
)
def _get_places(executor, op, scope):
    """get_places_op.cc: the device roster (device ordinals here — the
    reference returns a vector<Place>)."""
    import jax as _jax

    n = int(op.attrs.get("device_count", 0)) or len(_jax.devices())
    executor._write_var(scope, op.output("Out")[0],
                        np.arange(n, dtype=np.int64))


@register_host_op(
    "fake_init",
    inputs=[],
    outputs=[Out("Out")],
    attrs={"shape": [], "dtype": 5},
)
def _fake_init(executor, op, scope):
    """fake_init_op.cc: mark a (pserver-hosted) var initialized without
    allocating real content on the trainer."""
    from ..core import dtypes as _dt

    shape = tuple(int(s) for s in op.attrs.get("shape", [])) or (1,)
    executor._write_var(
        scope, op.output("Out")[0],
        np.zeros(shape, _dt.to_numpy_dtype(op.attrs.get("dtype", 5))))


@register_host_op(
    "delete_var",
    inputs=[In("X", duplicable=True, no_grad=True)],
    outputs=[],
)
def _delete_var(executor, op, scope):
    """delete_var_op.cc: explicit GC of scope vars."""
    for n in op.input("X"):
        if n:
            scope.erase(n)


@register_host_op(
    "lookup_sparse_table",
    inputs=[In("W", no_grad=True), In("Ids", no_grad=True)],
    outputs=[Out("Out")],
    attrs={"auto_grown_table": True, "padding_idx": -1},
)
def _lookup_sparse_table(executor, op, scope):
    """lookup_sparse_table_op.cc: lookup into a SelectedRows table
    (auto-grown: unseen ids read as zero rows)."""
    from ..core.tensor import SelectedRows

    w = scope.find_var(op.input("W")[0]).raw()
    ids = np.asarray(executor._read_var(
        scope, op.input("Ids")[0])).reshape(-1)
    if isinstance(w, SelectedRows):
        vals = np.asarray(w.get_tensor().array)
        rows = {int(r): i for i, r in enumerate(w.rows())}
        d = vals.shape[-1]
        out = np.zeros((ids.size, d), vals.dtype)
        for i, rid in enumerate(ids):
            j = rows.get(int(rid))
            if j is not None:
                out[i] = vals[j]
    else:
        vals = np.asarray(w.array)
        out = vals[np.clip(ids, 0, vals.shape[0] - 1)]
    executor._write_var(scope, op.output("Out")[0], out)


@register_host_op(
    "checkpoint_notify",
    inputs=[],
    outputs=[],
    attrs={"epmap": [], "dir": "", "lookup_table": ""},
)
def _checkpoint_notify(executor, op, scope):
    """checkpoint_notify_op.cc: tell each pserver to snapshot its
    persistable vars into ``dir``."""
    from ..distributed.ps_rpc import snapshot_scope_to_dir
    from .distributed_ops import _EMULATED_SERVERS, _rpc_client

    dirname = op.attrs.get("dir", "")
    for ep in op.attrs.get("epmap", []):
        server = _EMULATED_SERVERS.get(ep)
        if server is not None:
            snapshot_scope_to_dir(server["executor"], server["scope"],
                                  dirname)
        elif ep:
            _rpc_client(ep).checkpoint(dirname)


@register_host_op(
    "precision_recall",
    inputs=[In("MaxProbs", no_grad=True), In("Indices", no_grad=True),
            In("Labels", no_grad=True), In("Weights", dispensable=True,
                                           no_grad=True),
            In("StatesInfo", dispensable=True, no_grad=True)],
    outputs=[Out("BatchMetrics"), Out("AccumMetrics"),
             Out("AccumStatesInfo")],
    attrs={"class_number": 1},
)
def _precision_recall(executor, op, scope):
    """metrics/precision_recall_op.h: per-class TP/FP/TN/FN states ->
    [macro P, macro R, macro F1, micro P, micro R, micro F1], batch and
    accumulated."""
    c = int(op.attrs.get("class_number", 1))
    idx = np.asarray(executor._read_var(
        scope, op.input("Indices")[0])).reshape(-1)
    lab = np.asarray(executor._read_var(
        scope, op.input("Labels")[0])).reshape(-1)
    wname = op.input("Weights")
    w = (np.asarray(executor._read_var(scope, wname[0])).reshape(-1)
         if wname else np.ones_like(idx, dtype=np.float32))

    def batch_states():
        st = np.zeros((c, 4), np.float32)  # TP FP TN FN
        for i, l, wt in zip(idx, lab, w):
            i, l = int(i), int(l)
            if i == l:
                st[i, 0] += wt
                st[:, 2] += wt
                st[i, 2] -= wt
            else:
                st[l, 3] += wt
                st[i, 1] += wt
                st[:, 2] += wt
                st[i, 2] -= wt
                st[l, 2] -= wt
        return st

    def metrics(st):
        tp, fp, fn = st[:, 0], st[:, 1], st[:, 3]
        prec = np.where(tp + fp > 0, tp / np.maximum(tp + fp, 1e-12),
                        1.0 * (tp + fp == 0))
        rec = np.where(tp + fn > 0, tp / np.maximum(tp + fn, 1e-12),
                       1.0 * (tp + fn == 0))
        mp, mr = float(prec.mean()), float(rec.mean())
        mf1 = 2 * mp * mr / (mp + mr) if mp + mr > 0 else 0.0
        TP, FP, FN = tp.sum(), fp.sum(), fn.sum()
        up = float(TP / max(TP + FP, 1e-12)) if TP + FP > 0 else 1.0
        ur = float(TP / max(TP + FN, 1e-12)) if TP + FN > 0 else 1.0
        uf1 = 2 * up * ur / (up + ur) if up + ur > 0 else 0.0
        return np.asarray([mp, mr, mf1, up, ur, uf1], np.float32)

    bst = batch_states()
    sname = op.input("StatesInfo")
    prev = (np.asarray(executor._read_var(scope, sname[0]),
                       dtype=np.float32).reshape(c, 4)
            if sname and executor._read_var(scope, sname[0]) is not None
            else np.zeros((c, 4), np.float32))
    acc = prev + bst
    executor._write_var(scope, op.output("BatchMetrics")[0],
                        metrics(bst))
    executor._write_var(scope, op.output("AccumMetrics")[0],
                        metrics(acc))
    executor._write_var(scope, op.output("AccumStatesInfo")[0], acc)
