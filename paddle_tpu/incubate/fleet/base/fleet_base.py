"""Fleet interface (reference incubate/fleet/base/fleet_base.py:38).

The abstract surface user scripts program against: init(role),
is_worker()/is_server(), distributed_optimizer(), save_*; concrete
modes subclass it (collective/ here; the PS mode rides the
DistributeTranspiler rewrites in paddle_tpu.transpiler).
"""
from __future__ import annotations

import abc

from .role_maker import PaddleCloudRoleMaker, RoleMakerBase


class Fleet(metaclass=abc.ABCMeta):
    def __init__(self, mode):
        self._is_initialized = False
        self._mode = mode
        self._optimizer = None
        self._role_maker = None
        self._executor = None

    def init(self, role_maker=None):
        if role_maker is None:
            role_maker = PaddleCloudRoleMaker(
                is_collective=(self._mode == "collective"))
        if not isinstance(role_maker, RoleMakerBase):
            raise TypeError("role_maker must be a RoleMakerBase")
        self._role_maker = role_maker
        role_maker.generate_role()
        self._is_initialized = True

    def _check_init(self):
        if not self._is_initialized:
            raise RuntimeError("fleet.init(role) must be called first")

    def is_first_worker(self):
        self._check_init()
        return self._role_maker.is_first_worker()

    def worker_index(self):
        self._check_init()
        return self._role_maker.worker_index()

    def worker_num(self):
        self._check_init()
        return self._role_maker.worker_num()

    def is_worker(self):
        self._check_init()
        return self._role_maker.is_worker()

    def server_num(self):
        self._check_init()
        return self._role_maker.server_num()

    def server_index(self):
        self._check_init()
        return self._role_maker.server_index()

    def is_server(self):
        self._check_init()
        return self._role_maker.is_server()

    def worker_endpoints(self):
        self._check_init()
        return self._role_maker.get_trainer_endpoints()

    def server_endpoints(self):
        self._check_init()
        return self._role_maker.get_pserver_endpoints()

    @abc.abstractmethod
    def distributed_optimizer(self, optimizer, strategy=None):
        ...

    @abc.abstractmethod
    def init_worker(self):
        ...

    @abc.abstractmethod
    def init_server(self, model_dir=None):
        ...

    @abc.abstractmethod
    def run_server(self):
        ...

    @abc.abstractmethod
    def stop_worker(self):
        ...

    @abc.abstractmethod
    def save_inference_model(self, executor, dirname, feeded_var_names,
                             target_vars, main_program=None,
                             export_for_deployment=True):
        ...

    @abc.abstractmethod
    def save_persistables(self, executor, dirname, main_program=None):
        ...


class DistributedOptimizer(metaclass=abc.ABCMeta):
    def __init__(self, optimizer, strategy=None):
        self._optimizer = optimizer
        self._strategy = strategy

    @abc.abstractmethod
    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        ...
