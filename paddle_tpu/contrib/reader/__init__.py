from .distributed_reader import distributed_batch_reader  # noqa: F401
