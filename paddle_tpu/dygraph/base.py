"""dygraph.guard / to_variable / no_grad.

Parity: /root/reference/python/paddle/fluid/dygraph/base.py.
"""
from __future__ import annotations

import contextlib
import functools

import numpy as np

from .. import framework
from .tracer import Tracer, _set_tracer, current_tracer
from .varbase import VarBase

__all__ = ["guard", "enabled", "to_variable", "no_grad", "enable_dygraph",
           "disable_dygraph"]


def enabled():
    return framework.in_dygraph_mode()


def _lazy_default():
    from ..core.flags import flag

    return str(flag("dygraph_lazy")).lower() in ("1", "true", "yes", "on")


@contextlib.contextmanager
def guard(place=None, lazy=None):
    """``lazy=True`` queues eager ops and flushes them as ONE compiled
    dispatch per step (lazy.py) — the async/batched dispatch mode;
    default comes from FLAGS_dygraph_lazy."""
    tracer = Tracer(lazy=_lazy_default() if lazy is None else lazy)
    old_tracer = framework._dygraph_tracer_
    old_place = framework._dygraph_place_
    framework._dygraph_tracer_ = tracer
    framework._dygraph_place_ = place
    _set_tracer(tracer)
    try:
        yield
    finally:
        tracer.flush()
        framework._dygraph_tracer_ = old_tracer
        framework._dygraph_place_ = old_place
        _set_tracer(old_tracer)


def enable_dygraph(place=None, lazy=None):
    tracer = Tracer(lazy=_lazy_default() if lazy is None else lazy)
    framework._dygraph_tracer_ = tracer
    framework._dygraph_place_ = place
    _set_tracer(tracer)


def disable_dygraph():
    framework._dygraph_tracer_ = None
    framework._dygraph_place_ = None
    _set_tracer(None)


def to_variable(value, name=None, zero_copy=None):
    if isinstance(value, VarBase):
        return value
    return VarBase(np.asarray(value), name=name, stop_gradient=True)


def no_grad(fn=None):
    if fn is None:
        tracer = framework._dygraph_tracer()
        if tracer is None:
            return contextlib.nullcontext()
        return tracer.no_grad_guard()

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        tracer = framework._dygraph_tracer()
        if tracer is None:
            return fn(*args, **kwargs)
        with tracer.no_grad_guard():
            return fn(*args, **kwargs)

    return wrapper


def _init_eager_var(var, initializer):
    """Initialize a graph-declared var eagerly (LayerHelper
    set_variable_initializer in dygraph mode)."""
    from .varbase import ParamBase

    return ParamBase.create(var.name, var.shape, var.dtype, initializer)
