"""Filesystem layer: local + shell-driven HDFS.

Parity: /root/reference/paddle/fluid/framework/io/{fs.cc, shell.cc}
(LocalFS / HadoopFS command wrappers) and
python/paddle/fluid/incubate/fleet/utils/hdfs.py:68 (HDFSClient — every
operation shells out to ``hadoop fs`` with bounded retries). The
industrial CTR path stores dataset file lists and model dumps on HDFS;
trainers split the file list by rank (``split_files``).
"""
from __future__ import annotations

import os
import shutil
import subprocess
import time
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["LocalFS", "HDFSClient", "split_files"]


def split_files(files: Sequence[str], trainer_id: int, trainers: int):
    """Round-robin file split per trainer (reference hdfs.py:396)."""
    remainder = len(files) % trainers
    blocksize = len(files) // trainers
    blocks = [blocksize] * trainers
    for i in range(remainder):
        blocks[i] += 1
    trainer_files = [[]] * trainers
    begin = 0
    for i in range(trainers):
        trainer_files[i] = files[begin:begin + blocks[i]]
        begin += blocks[i]
    return trainer_files[trainer_id]


class LocalFS:
    """Reference framework/io/fs.cc local backend — same interface as
    HDFSClient so dataset/fleet code is storage-agnostic."""

    def ls_dir(self, path) -> Tuple[List[str], List[str]]:
        if not self.is_exist(path):
            return [], []
        dirs, files = [], []
        for n in sorted(os.listdir(path)):
            (dirs if os.path.isdir(os.path.join(path, n))
             else files).append(n)
        return dirs, files

    def ls(self, path) -> List[str]:
        dirs, files = self.ls_dir(path)
        return [os.path.join(path, n) for n in dirs + files]

    def cat(self, path) -> str:
        with open(path) as f:
            return f.read().rstrip("\n")

    def is_exist(self, path) -> bool:
        return os.path.exists(path)

    def is_dir(self, path) -> bool:
        return os.path.isdir(path)

    def is_file(self, path) -> bool:
        return os.path.isfile(path)

    def delete(self, path) -> bool:
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.remove(path)
        return True

    def rename(self, src, dst, overwrite=False) -> bool:
        if os.path.exists(dst):
            if not overwrite:
                raise FileExistsError(dst)
            self.delete(dst)
        os.replace(src, dst)
        # rename alone survives process death, not host crash: the new
        # dirent lives in the parent's page cache until it is synced
        from ..checkpoint import _fsync_dir

        _fsync_dir(os.path.dirname(os.path.abspath(dst)))
        return True

    def makedirs(self, path) -> bool:
        os.makedirs(path, exist_ok=True)
        return True

    mkdirs = makedirs

    def touch(self, path) -> bool:
        self.makedirs(os.path.dirname(path) or ".")
        with open(path, "a"):
            pass
        return True

    def download(self, hdfs_path, local_path, overwrite=False,
                 **kw) -> bool:
        # local backend: copy; overwrite REPLACES (merging into an
        # existing dir would keep stale files a reload then picks up)
        if overwrite and os.path.exists(local_path):
            self.delete(local_path)
        if os.path.isdir(hdfs_path):
            shutil.copytree(hdfs_path, local_path, dirs_exist_ok=True)
        else:
            self.makedirs(os.path.dirname(local_path) or ".")
            shutil.copy2(hdfs_path, local_path)
        return True

    def upload(self, hdfs_path, local_path, overwrite=False,
               **kw) -> bool:
        return self.download(local_path, hdfs_path,
                             overwrite=overwrite)


class HDFSClient:
    """``hadoop fs`` command wrapper (reference hdfs.py:68): every call
    shells out with retries; paths are plain HDFS paths. ``configs``
    become ``-D key=value`` pairs (fs.default.name, hadoop.job.ugi)."""

    def __init__(self, hadoop_home: str, configs: Optional[Dict] = None,
                 retry_times: int = 5, retry_sleep: float = 0.1):
        self.pre_commands: List[str] = []
        hadoop_bin = os.path.join(hadoop_home, "bin", "hadoop")
        self.pre_commands.append(hadoop_bin)
        dfs = "fs"
        self.pre_commands.append(dfs)
        for k, v in (configs or {}).items():
            self.pre_commands.append("-D%s=%s" % (k, v))
        self._retry_times = retry_times
        self._retry_sleep = retry_sleep

    def _run(self, commands: Sequence[str],
             retry_times: Optional[int] = None):
        """(returncode, stdout) with bounded retries (reference
        __run_hdfs_cmd, hdfs.py:79)."""
        cmd = list(self.pre_commands) + list(commands)
        retries = self._retry_times if retry_times is None else retry_times
        ret, out = 1, ""
        for attempt in range(max(retries, 1)):
            proc = subprocess.run(cmd, capture_output=True, text=True)
            ret, out = proc.returncode, proc.stdout
            if ret == 0:
                break
            time.sleep(self._retry_sleep)
        return ret, out

    # -- queries ----------------------------------------------------------
    def cat(self, hdfs_path) -> str:
        ret, out = self._run(["-cat", hdfs_path], retry_times=1)
        return out.rstrip("\n") if ret == 0 else ""

    def is_exist(self, hdfs_path) -> bool:
        # -test -e: a return code, not a full directory listing
        ret, _ = self._run(["-test", "-e", hdfs_path], retry_times=1)
        return ret == 0

    def is_dir(self, hdfs_path) -> bool:
        if not self.is_exist(hdfs_path):
            return False
        ret, _ = self._run(["-test", "-d", hdfs_path], retry_times=1)
        return ret == 0

    def is_file(self, hdfs_path) -> bool:
        if not self.is_exist(hdfs_path):
            return False
        ret, _ = self._run(["-test", "-f", hdfs_path], retry_times=1)
        return ret == 0

    def ls(self, hdfs_path) -> List[str]:
        ret, out = self._run(["-ls", hdfs_path], retry_times=1)
        if ret != 0:
            return []
        paths = []
        for line in out.splitlines():
            cols = line.split()
            if len(cols) >= 8:
                paths.append(cols[-1])
        return sorted(paths)

    def lsr(self, hdfs_path, excludes: Sequence[str] = ()) -> List[str]:
        ret, out = self._run(["-lsr", hdfs_path], retry_times=1)
        if ret != 0:
            return []
        paths = []
        for line in out.splitlines():
            cols = line.split()
            if len(cols) >= 8 and not cols[0].startswith("d"):
                p = cols[-1]
                if not any(e in p for e in excludes):
                    paths.append(p)
        return sorted(paths)

    # -- mutations --------------------------------------------------------
    def delete(self, hdfs_path) -> bool:
        # one JVM launch: recursive + force covers file/dir/missing
        ret, _ = self._run(["-rm", "-r", "-f", hdfs_path])
        return ret == 0

    def rename(self, src, dst, overwrite=False) -> bool:
        if overwrite and self.is_exist(dst):
            self.delete(dst)
        ret, _ = self._run(["-mv", src, dst])
        return ret == 0

    def makedirs(self, hdfs_path) -> bool:
        # -p: nested creation (hadoop 2+ refuses it otherwise; the
        # day/pass layout always creates multi-level paths)
        ret, _ = self._run(["-mkdir", "-p", hdfs_path])
        return ret == 0

    mkdirs = makedirs

    def touch(self, hdfs_path) -> bool:
        ret, _ = self._run(["-touchz", hdfs_path])
        return ret == 0

    def download(self, hdfs_path, local_path, multi_processes=1,
                 overwrite=False) -> bool:
        if overwrite and os.path.exists(local_path):
            LocalFS().delete(local_path)
        d = os.path.dirname(local_path)
        if d:
            os.makedirs(d, exist_ok=True)
        ret, _ = self._run(["-get", hdfs_path, local_path])
        return ret == 0

    def upload(self, hdfs_path, local_path, multi_processes=1,
               overwrite=False) -> bool:
        if overwrite and self.is_exist(hdfs_path):
            self.delete(hdfs_path)
        ret, _ = self._run(["-put", local_path, hdfs_path])
        return ret == 0

    def upload_dir(self, dest_dir, local_dir, overwrite=False) -> bool:
        return self.upload(dest_dir, local_dir, overwrite=overwrite)

    # static helper mirrored from the reference class
    split_files = staticmethod(split_files)
