"""Wave-3 ops: tensor rearrangement, vision utilities, losses, CTC.

Parity targets (reference /root/reference/paddle/fluid/operators/):
pixel_shuffle_op.cc, shuffle_channel_op.cc, space_to_depth_op.cc,
temporal_shift_op.cc, shard_index_op.cc, multiplex_op.cc, crop_op.cc,
affine_channel_op.cc, unfold_op.cc, grid_sampler_op.cc,
affine_grid_op.cc, selu_op.cc, mean_iou_op.cc,
bilinear_tensor_product_op.cc, cos_sim_op.cc, bpr_loss_op.cc,
teacher_student_sigmoid_loss_op.cc, sigmoid_focal_loss (detection/),
row_conv_op.cc, warpctc_op.cc, edit_distance_op.cc,
ctc_align_op.cc (ctc_greedy_decoder), hash_op.cc, unique_op.cc,
reverse_op.cc, scatter_nd_op (via scatter_nd_add), fsp_op.cc.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import In, Out, register_host_op, register_op


@register_op("reverse", inputs=[In("X")], outputs=[Out("Out")],
             attrs={"axis": []})
def _reverse(ins, attrs):
    x = ins["X"]
    axes = attrs.get("axis", [])
    for a in (axes if isinstance(axes, (list, tuple)) else [axes]):
        x = jnp.flip(x, axis=int(a))
    return {"Out": x}


@register_op("pixel_shuffle", inputs=[In("X")], outputs=[Out("Out")],
             attrs={"upscale_factor": 1})
def _pixel_shuffle(ins, attrs):
    x = ins["X"]  # [N, C*r*r, H, W]
    r = int(attrs.get("upscale_factor", 1))
    n, c, h, w = x.shape
    oc = c // (r * r)
    x = x.reshape(n, oc, r, r, h, w)
    x = jnp.transpose(x, (0, 1, 4, 2, 5, 3))
    return {"Out": x.reshape(n, oc, h * r, w * r)}


@register_op("shuffle_channel", inputs=[In("X")], outputs=[Out("Out")],
             attrs={"group": 1})
def _shuffle_channel(ins, attrs):
    x = ins["X"]
    g = int(attrs.get("group", 1))
    n, c, h, w = x.shape
    x = x.reshape(n, g, c // g, h, w)
    return {"Out": jnp.swapaxes(x, 1, 2).reshape(n, c, h, w)}


@register_op("space_to_depth", inputs=[In("X")], outputs=[Out("Out")],
             attrs={"blocksize": 1})
def _space_to_depth(ins, attrs):
    x = ins["X"]
    b = int(attrs.get("blocksize", 1))
    n, c, h, w = x.shape
    x = x.reshape(n, c, h // b, b, w // b, b)
    x = jnp.transpose(x, (0, 3, 5, 1, 2, 4))
    return {"Out": x.reshape(n, c * b * b, h // b, w // b)}


@register_op("temporal_shift", inputs=[In("X")], outputs=[Out("Out")],
             attrs={"seg_num": 1, "shift_ratio": 0.25})
def _temporal_shift(ins, attrs):
    x = ins["X"]  # [N*T, C, H, W]
    t = int(attrs.get("seg_num", 1))
    ratio = attrs.get("shift_ratio", 0.25)
    nt, c, h, w = x.shape
    n = nt // t
    c1 = int(c * ratio)
    c2 = int(c * 2 * ratio)
    x = x.reshape(n, t, c, h, w)
    fwd = jnp.concatenate([x[:, 1:, :c1], jnp.zeros_like(x[:, :1, :c1])],
                          axis=1)
    back = jnp.concatenate([jnp.zeros_like(x[:, :1, c1:c2]),
                            x[:, :-1, c1:c2]], axis=1)
    keep = x[:, :, c2:]
    out = jnp.concatenate([fwd, back, keep], axis=2)
    return {"Out": out.reshape(nt, c, h, w)}


@register_op("shard_index", inputs=[In("X", no_grad=True)],
             outputs=[Out("Out")],
             attrs={"index_num": 0, "nshards": 1, "shard_id": 0,
                    "ignore_value": -1}, grad=None)
def _shard_index(ins, attrs):
    x = ins["X"]
    index_num = int(attrs["index_num"])
    nshards = int(attrs["nshards"])
    shard_id = int(attrs["shard_id"])
    ignore = attrs.get("ignore_value", -1)
    shard_size = (index_num + nshards - 1) // nshards
    in_shard = (x // shard_size) == shard_id
    return {"Out": jnp.where(in_shard, x % shard_size, ignore)}


@register_op("multiplex",
             inputs=[In("X", duplicable=True), In("Ids", no_grad=True)],
             outputs=[Out("Out")])
def _multiplex(ins, attrs):
    xs = jnp.stack(ins["X"], axis=0)  # [K, N, ...]
    ids = ins["Ids"].reshape(-1).astype(jnp.int32)  # [N]
    rows = jnp.arange(ids.shape[0])
    return {"Out": xs[ids, rows]}


@register_op("crop", inputs=[In("X"), In("Y", dispensable=True,
                                         no_grad=True),
                             In("Offsets", dispensable=True, no_grad=True)],
             outputs=[Out("Out")],
             attrs={"offsets": [], "shape": []})
def _crop(ins, attrs):
    x = ins["X"]
    shape = attrs.get("shape") or list(ins["Y"].shape)
    offsets = attrs.get("offsets") or [0] * x.ndim
    slices = tuple(slice(int(o), int(o) + int(s))
                   for o, s in zip(offsets, shape))
    return {"Out": x[slices]}


@register_op("affine_channel",
             inputs=[In("X"), In("Scale"), In("Bias")],
             outputs=[Out("Out")], attrs={"data_layout": "NCHW"})
def _affine_channel(ins, attrs):
    x, scale, bias = ins["X"], ins["Scale"], ins["Bias"]
    c_axis = 1 if attrs.get("data_layout", "NCHW") == "NCHW" else x.ndim - 1
    shape = [1] * x.ndim
    shape[c_axis] = x.shape[c_axis]
    return {"Out": x * scale.reshape(shape) + bias.reshape(shape)}


@register_op("unfold", inputs=[In("X")], outputs=[Out("Y")],
             attrs={"kernel_sizes": [1, 1], "strides": [1, 1],
                    "paddings": [0, 0, 0, 0], "dilations": [1, 1]})
def _unfold(ins, attrs):
    """im2col (reference unfold_op.cc): [N,C,H,W] ->
    [N, C*kh*kw, L]."""
    x = ins["X"]
    kh, kw = attrs["kernel_sizes"]
    sh, sw = attrs.get("strides", [1, 1])
    pt, pl, pb, pr = (attrs.get("paddings", [0, 0, 0, 0]) + [0] * 4)[:4]
    dh, dw = attrs.get("dilations", [1, 1])
    n, c, h, w = x.shape
    x = jnp.pad(x, ((0, 0), (0, 0), (pt, pb), (pl, pr)))
    oh = (h + pt + pb - (dh * (kh - 1) + 1)) // sh + 1
    ow = (w + pl + pr - (dw * (kw - 1) + 1)) // sw + 1
    patches = []
    for i in range(kh):
        for j in range(kw):
            sub = x[:, :, i * dh:i * dh + oh * sh:sh,
                    j * dw:j * dw + ow * sw:sw]
            patches.append(sub)
    out = jnp.stack(patches, axis=2)  # [N, C, kh*kw, oh, ow]
    return {"Y": out.reshape(n, c * kh * kw, oh * ow)}


@register_op("affine_grid", inputs=[In("Theta"),
                                    In("OutputShape", dispensable=True,
                                       no_grad=True)],
             outputs=[Out("Output")],
             attrs={"output_shape": [], "align_corners": True})
def _affine_grid(ins, attrs):
    theta = ins["Theta"]  # [N, 2, 3]
    shape = attrs.get("output_shape") or [int(v) for v in
                                          np.asarray(ins["OutputShape"])]
    n, c, h, w = shape
    ys = jnp.linspace(-1.0, 1.0, h)
    xs = jnp.linspace(-1.0, 1.0, w)
    xg, yg = jnp.meshgrid(xs, ys)  # [h, w]
    ones = jnp.ones_like(xg)
    base = jnp.stack([xg, yg, ones], axis=-1)  # [h, w, 3]
    grid = jnp.einsum("hwk,njk->nhwj", base, theta)  # [n, h, w, 2]
    return {"Output": grid}


@register_op("selu", inputs=[In("X")], outputs=[Out("Out")],
             attrs={"scale": 1.0507009873554805,
                    "alpha": 1.6732632423543772})
def _selu(ins, attrs):
    x = ins["X"]
    scale = attrs.get("scale", 1.0507009873554805)
    alpha = attrs.get("alpha", 1.6732632423543772)
    return {"Out": scale * jnp.where(x > 0, x, alpha * (jnp.exp(x) - 1))}


@register_op("mean_iou",
             inputs=[In("Predictions", no_grad=True),
                     In("Labels", no_grad=True)],
             outputs=[Out("OutMeanIou"), Out("OutWrong"), Out("OutCorrect")],
             attrs={"num_classes": 2}, grad=None)
def _mean_iou(ins, attrs):
    pred = ins["Predictions"].reshape(-1).astype(jnp.int32)
    label = ins["Labels"].reshape(-1).astype(jnp.int32)
    k = int(attrs["num_classes"])
    correct = jnp.zeros(k, jnp.int32).at[jnp.where(
        pred == label, pred, k - 1)].add(
            (pred == label).astype(jnp.int32))
    pred_cnt = jnp.zeros(k, jnp.int32).at[pred].add(1)
    label_cnt = jnp.zeros(k, jnp.int32).at[label].add(1)
    union = pred_cnt + label_cnt - correct
    present = union > 0
    iou = jnp.where(present, correct / jnp.maximum(union, 1), 0.0)
    miou = iou.sum() / jnp.maximum(present.sum(), 1)
    # reference mean_iou_op.h counts a mismatch against BOTH classes
    wrong = (pred_cnt - correct) + (label_cnt - correct)
    return {"OutMeanIou": miou.astype(jnp.float32),
            "OutWrong": wrong,
            "OutCorrect": correct}


@register_op("bilinear_tensor_product",
             inputs=[In("X"), In("Y"), In("Weight"),
                     In("Bias", dispensable=True)],
             outputs=[Out("Out")])
def _bilinear_tensor_product(ins, attrs):
    x, y, w = ins["X"], ins["Y"], ins["Weight"]  # w: [size, dx, dy]
    out = jnp.einsum("bi,oij,bj->bo", x, w, y)
    if ins.get("Bias") is not None:
        out = out + ins["Bias"].reshape(1, -1)
    return {"Out": out}


@register_op("cos_sim", inputs=[In("X"), In("Y")],
             outputs=[Out("Out"), Out("XNorm", no_grad=True),
                      Out("YNorm", no_grad=True)])
def _cos_sim(ins, attrs):
    x, y = ins["X"], ins["Y"]
    xn = jnp.sqrt(jnp.sum(jnp.square(x), axis=-1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(jnp.square(y), axis=-1, keepdims=True))
    sim = jnp.sum(x * y, axis=-1, keepdims=True) / \
        jnp.maximum(xn * yn, 1e-12)
    return {"Out": sim, "XNorm": xn, "YNorm": yn}


@register_op("bpr_loss", inputs=[In("X"), In("Label", no_grad=True)],
             outputs=[Out("Y")])
def _bpr_loss(ins, attrs):
    """Bayesian personalized ranking loss (reference bpr_loss_op.cc)."""
    x = ins["X"]  # [N, C] scores
    label = ins["Label"].reshape(-1).astype(jnp.int32)
    n, c = x.shape
    pos = x[jnp.arange(n), label][:, None]
    diff = x - pos
    lse = jnp.logaddexp(0.0, diff)  # stable log(1+e^x)
    mask = jnp.ones((n, c)).at[jnp.arange(n), label].set(0.0)
    return {"Y": (lse * mask).sum(axis=1, keepdims=True) / (c - 1)}


@register_op("teacher_student_sigmoid_loss",
             inputs=[In("X"), In("Label", no_grad=True)],
             outputs=[Out("Y")],
             attrs={"soft_max_up_bound": 15.0,
                    "soft_max_lower_bound": -15.0})
def _ts_sigmoid_loss(ins, attrs):
    """Exact reference piecewise formula
    (teacher_student_sigmoid_loss_op.h:44): label < -1 -> sp;
    label in [-1,0) -> sp - x; label in [0,1) -> sp + sp - x*label;
    label >= 1 -> (sp - x) + (sp - x*(label-1))."""
    x = ins["X"].reshape(-1)
    label = ins["Label"].reshape(-1)
    sp = jnp.maximum(x, 0.0) + jnp.logaddexp(0.0, -jnp.abs(x))
    y = jnp.where(
        label < -1.0, sp,
        jnp.where(label < 0.0, sp - x,
                  jnp.where(label < 1.0, sp + sp - x * label,
                            (sp - x) + (sp - x * (label - 1.0)))))
    return {"Y": y.reshape(-1, 1)}


@register_op("sigmoid_focal_loss",
             inputs=[In("X"), In("Label", no_grad=True),
                     In("FgNum", no_grad=True)],
             outputs=[Out("Out")],
             attrs={"gamma": 2.0, "alpha": 0.25})
def _sigmoid_focal_loss(ins, attrs):
    """Reference detection/sigmoid_focal_loss_op.cu: per-class focal
    loss; Label in [0, C] with 0 = background."""
    x = ins["X"]  # [N, C]
    label = ins["Label"].reshape(-1).astype(jnp.int32)  # [N]
    fg = jnp.maximum(ins["FgNum"].reshape(()).astype(x.dtype), 1.0)
    gamma = attrs.get("gamma", 2.0)
    alpha = attrs.get("alpha", 0.25)
    n, c = x.shape
    cls = jnp.arange(1, c + 1)[None, :]
    t = (label[:, None] == cls).astype(x.dtype)  # one-hot over classes
    p = jax.nn.sigmoid(x)
    ce = jnp.logaddexp(0.0, -jnp.abs(x)) + jnp.maximum(x, 0.0) - x * t
    # focal modulation
    pt = jnp.where(t > 0, p, 1 - p)
    af = jnp.where(t > 0, alpha, 1 - alpha)
    valid = (label[:, None] >= 0).astype(x.dtype)
    return {"Out": af * (1 - pt) ** gamma * ce * valid / fg}


@register_op("row_conv", inputs=[In("X"), In("Filter")],
             outputs=[Out("Out")])
def _row_conv(ins, attrs):
    """Lookahead row convolution over [N, T, D] with filter
    [future_ctx, D] (reference row_conv_op.cc, dense layout)."""
    x, f = ins["X"], ins["Filter"]
    ctx = f.shape[0]
    outs = jnp.zeros_like(x)
    for k in range(ctx):
        shifted = jnp.pad(x[:, k:], ((0, 0), (0, k), (0, 0)))
        outs = outs + shifted * f[k][None, None, :]
    return {"Out": outs}


@register_op("fsp", inputs=[In("X"), In("Y")], outputs=[Out("Out")])
def _fsp(ins, attrs):
    """Flow-of-solution-procedure matrix (reference fsp_op.cc):
    [N,C1,H,W] x [N,C2,H,W] -> [N,C1,C2]."""
    x, y = ins["X"], ins["Y"]
    n, c1, h, w = x.shape
    return {"Out": jnp.einsum("nchw,ndhw->ncd", x, y) / (h * w)}


@register_op("hash", inputs=[In("X", no_grad=True)], outputs=[Out("Out")],
             attrs={"num_hash": 1, "mod_by": 100000000}, grad=None)
def _hash(ins, attrs):
    """Multiplicative int hashing (reference hash_op.cc uses xxhash;
    the contract is a deterministic bucket id per (row, hash_idx))."""
    x = ins["X"].astype(jnp.uint32)  # [N, D] int ids
    num_hash = int(attrs.get("num_hash", 1))
    mod = int(attrs.get("mod_by", 100000000))
    outs = []
    for i in range(num_hash):
        seed = jnp.uint32(0x9E3779B1 * (i + 1) | 1)
        h = jnp.zeros(x.shape[:-1], jnp.uint32)
        for d in range(x.shape[-1]):
            h = (h ^ (x[..., d] * seed)) * jnp.uint32(0x85EBCA77)
        outs.append((h % jnp.uint32(mod)).astype(jnp.int64))
    out = jnp.stack(outs, axis=-1)[..., None]
    return {"Out": out}


@register_host_op("unique",
                  inputs=[In("X", no_grad=True)],
                  outputs=[Out("Out"), Out("Index")],
                  attrs={"dtype": 2})
def _unique(executor, op, scope):
    from ..core import dtypes as _dt

    x = np.asarray(executor._read_var(scope, op.input("X")[0])).reshape(-1)
    uniq, inv = np.unique(x, return_inverse=True)
    idx_dt = _dt.to_numpy_dtype(op.attrs.get("dtype", 2))
    executor._write_var(scope, op.output("Out")[0], uniq)
    executor._write_var(scope, op.output("Index")[0],
                        inv.astype(idx_dt))


@register_host_op("edit_distance",
                  inputs=[In("Hyps", no_grad=True),
                          In("Refs", no_grad=True)],
                  outputs=[Out("Out"), Out("SequenceNum")],
                  attrs={"normalized": True})
def _edit_distance(executor, op, scope):
    """Levenshtein distance per sequence pair (reference
    edit_distance_op.h). LoD inputs or same-length dense batches."""
    from ..core.tensor import LoDTensor

    def seqs(name):
        v = scope.find_var(name).raw()
        arr = np.asarray(v.array if isinstance(v, LoDTensor) else v)
        if isinstance(v, LoDTensor) and v.lod():
            off = v.lod()[-1]
            return [arr[off[i]:off[i + 1]].reshape(-1)
                    for i in range(len(off) - 1)]
        return [row.reshape(-1) for row in arr]

    hyps = seqs(op.input("Hyps")[0])
    refs = seqs(op.input("Refs")[0])
    out = []
    for h, r in zip(hyps, refs):
        m, n = len(h), len(r)
        dp = np.zeros((m + 1, n + 1), np.float32)
        dp[:, 0] = np.arange(m + 1)
        dp[0, :] = np.arange(n + 1)
        for i in range(1, m + 1):
            for j in range(1, n + 1):
                cost = 0 if h[i - 1] == r[j - 1] else 1
                dp[i, j] = min(dp[i - 1, j] + 1, dp[i, j - 1] + 1,
                               dp[i - 1, j - 1] + cost)
        d = dp[m, n]
        if op.attrs.get("normalized", True) and n > 0:
            d = d / n
        out.append([d])
    executor._write_var(scope, op.output("Out")[0],
                        np.asarray(out, np.float32))
    executor._write_var(scope, op.output("SequenceNum")[0],
                        np.asarray([len(out)], np.int64))


@register_op(
    "warpctc",
    inputs=[In("Logits"), In("Label", no_grad=True),
            In("LogitsLength", dispensable=True, no_grad=True)],
    outputs=[Out("Loss"), Out("WarpCTCGrad", dispensable=True,
                              no_grad=True)],
    attrs={"blank": 0, "norm_by_times": False},
)
def _warpctc(ins, attrs):
    """CTC loss over DENSE [B, T, C] logits and [B, L] labels
    (reference warpctc_op.cc wraps warp-ctc; here the forward algorithm
    runs as a lax.scan over time — pure XLA, trainable via auto-VJP).
    Label padding value must be negative or >= C (ignored)."""
    logits = ins["Logits"]
    labels = ins["Label"].astype(jnp.int32)
    blank = int(attrs.get("blank", 0))
    if logits.ndim == 2:
        logits = logits[None]
        labels = labels.reshape(1, -1)
    b, t, c = logits.shape
    log_probs = jax.nn.log_softmax(logits, axis=-1)
    if ins.get("LogitsLength") is not None:
        # padded timesteps emit blank with probability 1 (log-prob 0):
        # trailing forced blanks collapse, leaving the true-path prob
        lens = ins["LogitsLength"].reshape(-1).astype(jnp.int32)
        tmask = jnp.arange(t)[None, :] < lens[:, None]  # [b, t]
        blank_row = jnp.full((c,), -1e30).at[int(attrs.get("blank",
                                                           0))].set(0.0)
        log_probs = jnp.where(tmask[:, :, None], log_probs,
                              blank_row[None, None, :])
    L = labels.shape[1]
    valid_lab = (labels >= 0) & (labels < c)  # pad = negative or >= C
    # extended label sequence: blank l1 blank l2 ... blank, length 2L+1
    ext = jnp.full((b, 2 * L + 1), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(jnp.where(valid_lab, labels, blank))
    lab_len = valid_lab.sum(axis=1)
    s_len = 2 * lab_len + 1
    neg_inf = jnp.float32(-1e30)

    # can transition s-2 -> s when ext[s] != blank and ext[s] != ext[s-2]
    skip_ok = jnp.zeros((b, 2 * L + 1), bool)
    if L > 0:
        skip_ok = skip_ok.at[:, 2:].set(
            (ext[:, 2:] != blank) & (ext[:, 2:] != ext[:, :-2]))

    alpha0 = jnp.full((b, 2 * L + 1), neg_inf)
    alpha0 = alpha0.at[:, 0].set(log_probs[:, 0, blank])
    if L > 0:
        alpha0 = alpha0.at[:, 1].set(
            jnp.where(lab_len > 0,
                      log_probs[jnp.arange(b), 0, ext[:, 1]], neg_inf))

    def step(alpha, lp_t):
        stay = alpha
        prev1 = jnp.concatenate(
            [jnp.full((b, 1), neg_inf), alpha[:, :-1]], axis=1)
        prev2 = jnp.concatenate(
            [jnp.full((b, 2), neg_inf), alpha[:, :-2]], axis=1)
        prev2 = jnp.where(skip_ok, prev2, neg_inf)
        merged = jnp.logaddexp(jnp.logaddexp(stay, prev1), prev2)
        emit = jnp.take_along_axis(lp_t, ext, axis=1)
        return merged + emit, None

    lp_seq = jnp.swapaxes(log_probs, 0, 1)  # [T, B, C]
    alpha, _ = jax.lax.scan(step, alpha0, lp_seq[1:])
    last = jnp.take_along_axis(alpha, (s_len - 1)[:, None],
                               axis=1)[:, 0]
    last2 = jnp.take_along_axis(
        alpha, jnp.maximum(s_len - 2, 0)[:, None], axis=1)[:, 0]
    ll = jnp.logaddexp(last, jnp.where(s_len >= 2, last2, neg_inf))
    return {"Loss": (-ll).reshape(b, 1)}


@register_host_op(
    "ctc_align",
    inputs=[In("Input", no_grad=True)],
    outputs=[Out("Output")],
    attrs={"blank": 0, "merge_repeated": True},
)
def _ctc_align(executor, op, scope):
    """CTC greedy-decode output alignment (reference ctc_align_op.h):
    merge repeats, drop blanks. Dense [B, T] argmax ids in, LoD out."""
    from ..core.tensor import LoDTensor

    ids = np.asarray(executor._read_var(scope, op.input("Input")[0]))
    blank = op.attrs.get("blank", 0)
    merge = op.attrs.get("merge_repeated", True)
    rows, lod = [], [0]
    for row in ids:
        prev = None
        seq = []
        for v in row.reshape(-1):
            if merge and prev is not None and v == prev:
                prev = v
                continue
            prev = v
            if v != blank:
                seq.append(v)
        rows.extend(seq)
        lod.append(len(rows))
    out = np.asarray(rows, ids.dtype).reshape(-1, 1) if rows else \
        np.full((1, 1), -1, ids.dtype)
    if not rows:
        lod = [0, 1]
    t = LoDTensor(out)
    t.set_lod([lod])
    executor._write_var(scope, op.output("Output")[0], t)


@register_op("sequence_reverse", inputs=[In("X")], outputs=[Out("Y")],
             needs_lod=True, infer_lod="propagate")
def _sequence_reverse(ins, attrs):
    """Reverse each LoD sequence (reference
    sequence_ops/sequence_reverse_op.h); dense inputs flip axis 0."""
    from .lod_utils import lod_offsets

    x = ins["X"]
    offsets = lod_offsets(attrs, "X")
    if offsets is None:
        return {"Y": jnp.flip(x, axis=0)}
    segs = [jnp.flip(x[offsets[i]:offsets[i + 1]], axis=0)
            for i in range(len(offsets) - 1)]
    return {"Y": jnp.concatenate(segs, axis=0)}


@register_host_op("lod_reset",
                  inputs=[In("X"), In("Y", dispensable=True,
                                      no_grad=True)],
                  outputs=[Out("Out")],
                  attrs={"target_lod": []})
def _lod_reset(executor, op, scope):
    """Re-stamp LoD from attr or Y's lod/values (reference
    lod_reset_op.h)."""
    from ..core.tensor import LoDTensor

    xv = scope.find_var(op.input("X")[0]).raw()
    arr = np.asarray(xv.array if isinstance(xv, LoDTensor) else xv)
    target = list(op.attrs.get("target_lod") or [])
    if not target and op.input("Y"):
        yv = scope.find_var(op.input("Y")[0]).raw()
        if isinstance(yv, LoDTensor) and yv.lod():
            target = list(yv.lod()[-1])
        else:
            target = [int(v) for v in np.asarray(
                yv.array if isinstance(yv, LoDTensor) else yv).reshape(-1)]
    t = LoDTensor(arr)
    t.set_lod([target])
    executor._write_var(scope, op.output("Out")[0], t)


@register_op(
    "linear_chain_crf",
    inputs=[In("Emission"), In("Transition"), In("Label", no_grad=True)],
    outputs=[Out("Alpha", no_grad=True), Out("EmissionExps", no_grad=True),
             Out("TransitionExps", no_grad=True), Out("LogLikelihood")],
)
def _linear_chain_crf(ins, attrs):
    """Linear-chain CRF negative log-likelihood over DENSE [B, T, K]
    emissions (reference linear_chain_crf_op.h works on LoD sequences;
    the padded-batch form is the TPU-native layout — pad with repeated
    last label and length masking upstream).

    Transition: [K+2, K] — row 0 start weights, row 1 end weights, rows
    2.. the KxK transition matrix, the reference's exact layout."""
    em = ins["Emission"]
    if em.ndim == 2:
        em = em[None]
    labels = ins["Label"].astype(jnp.int32)
    labels = labels.reshape(em.shape[0], -1)
    trans = ins["Transition"]
    k = em.shape[-1]
    start, end, T_mat = trans[0], trans[1], trans[2:]
    b, t, _ = em.shape

    # log partition via forward algorithm
    alpha0 = start[None, :] + em[:, 0]

    def fwd(alpha, e_t):
        scores = alpha[:, :, None] + T_mat[None, :, :] + e_t[:, None, :]
        return jax.nn.logsumexp(scores, axis=1), None

    alpha, _ = jax.lax.scan(fwd, alpha0,
                            jnp.swapaxes(em[:, 1:], 0, 1))
    log_z = jax.nn.logsumexp(alpha + end[None, :], axis=1)

    # gold path score
    rows = jnp.arange(b)
    gold = start[labels[:, 0]] + em[rows, 0, labels[:, 0]]
    for i in range(1, t):
        gold = gold + T_mat[labels[:, i - 1], labels[:, i]] + \
            em[rows, i, labels[:, i]]
    gold = gold + end[labels[:, -1]]
    return {"LogLikelihood": (log_z - gold).reshape(b, 1),
            "Alpha": alpha, "EmissionExps": jnp.exp(em),
            "TransitionExps": jnp.exp(trans)}


@register_op(
    "crf_decoding",
    inputs=[In("Emission", no_grad=True), In("Transition", no_grad=True),
            In("Label", dispensable=True, no_grad=True)],
    outputs=[Out("ViterbiPath")],
    grad=None,
)
def _crf_decoding(ins, attrs):
    """Viterbi decode (reference crf_decoding_op.h) over dense
    [B, T, K] emissions; returns the best path [B, T] (or a 0/1 match
    mask against Label when provided, like the reference)."""
    em = ins["Emission"]
    if em.ndim == 2:
        em = em[None]
    trans = ins["Transition"]
    start, end, T_mat = trans[0], trans[1], trans[2:]
    b, t, k = em.shape

    delta0 = start[None, :] + em[:, 0]

    def step(delta, e_t):
        scores = delta[:, :, None] + T_mat[None, :, :]
        best = jnp.max(scores, axis=1) + e_t
        arg = jnp.argmax(scores, axis=1)
        return best, arg

    delta, back = jax.lax.scan(step, delta0,
                               jnp.swapaxes(em[:, 1:], 0, 1))
    last = jnp.argmax(delta + end[None, :], axis=1)  # [b]

    def backtrack(state, bp_t):
        prev = jnp.take_along_axis(bp_t, state[:, None], axis=1)[:, 0]
        return prev, prev

    _, path_rev = jax.lax.scan(backtrack, last, back, reverse=True)
    path = jnp.concatenate([jnp.swapaxes(path_rev, 0, 1),
                            last[:, None]], axis=1)  # [b, t]
    if ins.get("Label") is not None:
        lab = ins["Label"].astype(jnp.int32).reshape(b, t)
        return {"ViterbiPath": (path == lab).astype(jnp.int64)}
    return {"ViterbiPath": path.astype(jnp.int64)}


@register_op("gather_tree",
             inputs=[In("Ids", no_grad=True), In("Parents", no_grad=True)],
             outputs=[Out("Out")], grad=None)
def _gather_tree(ins, attrs):
    """Beam-search backtrace (reference gather_tree_op.cc): walk parent
    pointers from the last step, yielding full beams [T, B, W]."""
    ids, parents = ins["Ids"], ins["Parents"]
    t, b, w = ids.shape
    beams = jnp.arange(w)[None, :].repeat(b, axis=0)  # [B, W]

    def step(state, tp):
        id_t, par_t = tp
        out_t = jnp.take_along_axis(id_t, state, axis=1)
        nxt = jnp.take_along_axis(par_t, state, axis=1)
        return nxt, out_t

    _, outs = jax.lax.scan(step, beams, (ids, parents), reverse=True)
    return {"Out": outs}


@register_op("random_crop",
             inputs=[In("X"), In("Seed", dispensable=True, no_grad=True)],
             outputs=[Out("Out"), Out("SeedOut", dispensable=True,
                                      no_grad=True)],
             attrs={"shape": [], "startup_seed": 0}, needs_rng=True,
             grad=None)
def _random_crop(ins, attrs):
    """Random spatial crop to attrs['shape'] (trailing dims; reference
    random_crop_op.h)."""
    from ..core.registry import RNG_SEED_ATTR

    x = ins["X"]
    shape = [int(s) for s in attrs["shape"]]
    nd = len(shape)
    key = jax.random.PRNGKey(ins[RNG_SEED_ATTR])
    starts = []
    for i, (full, want) in enumerate(zip(x.shape[-nd:], shape)):
        key, sub = jax.random.split(key)
        starts.append(jax.random.randint(sub, (), 0, full - want + 1))
    out = x
    for i, (st, want) in enumerate(zip(starts, shape)):
        axis = x.ndim - nd + i
        out = jax.lax.dynamic_slice_in_dim(out, st, want, axis=axis)
    return {"Out": out}


@register_op("spectral_norm",
             inputs=[In("Weight"), In("U", no_grad=True),
                     In("V", no_grad=True)],
             outputs=[Out("Out"), Out("UOut", no_grad=True),
                      Out("VOut", no_grad=True)],
             attrs={"dim": 0, "power_iters": 1, "eps": 1e-12})
def _spectral_norm(ins, attrs):
    """Weight / sigma_max via power iteration (reference
    spectral_norm_op.h). UOut/VOut are bound by the layer to the same
    persistable U/V vars, so the iterates warm-start across steps as the
    reference's in-place CalcMatrixSigmaAndNormWeight does; u/v are
    gradient-stopped before sigma, matching the reference grad kernel
    which treats the saved U/V as constants."""
    w = ins["Weight"]
    dim = int(attrs.get("dim", 0))
    eps = attrs.get("eps", 1e-12)
    mat = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
    u, v = ins["U"].reshape(-1), ins["V"].reshape(-1)
    for _ in range(int(attrs.get("power_iters", 1))):
        v = mat.T @ u
        v = v / (jnp.linalg.norm(v) + eps)
        u = mat @ v
        u = u / (jnp.linalg.norm(u) + eps)
    u = jax.lax.stop_gradient(u)
    v = jax.lax.stop_gradient(v)
    sigma = u @ mat @ v
    return {"Out": w / (sigma + eps), "UOut": u, "VOut": v}


def _data_norm_grad_maker(block, op, pending, finalize):
    """Grad maker for data_norm mirroring the reference's
    DataNormGradMaker (data_norm_op.cc:458-470): the grad op's
    BatchSize/BatchSum/BatchSquareSum OUTPUTS are bound to the forward's
    stat vars themselves, so each backward pass replaces the running
    stats with this batch's (N, Σx, Σ(x-mean)²+N·ε) — that in-place
    rebind IS the reference's stat-update rule."""
    from .. import framework
    from ..backward import _ensure_grad_var

    y_name = op.output("Y")[0]
    g_y = finalize(y_name)
    if g_y is None:
        return
    x_name = op.input("X")[0]
    if x_name in pending and pending[x_name]:
        gname = "%s@GRAD@RENAME@%d" % (x_name, len(pending[x_name]))
    else:
        gname = framework.grad_var_name(x_name)
    _ensure_grad_var(block, x_name, gname)
    pending.setdefault(x_name, []).append(gname)
    block.append_op(
        "data_norm_grad",
        inputs={"X": [x_name], "Means": [op.output("Means")[0]],
                "Scales": [op.output("Scales")[0]], "Y@GRAD": [g_y]},
        outputs={"X@GRAD": [gname],
                 "BatchSize": [op.input("BatchSize")[0]],
                 "BatchSum": [op.input("BatchSum")[0]],
                 "BatchSquareSum": [op.input("BatchSquareSum")[0]]},
        attrs=dict(op.attrs), infer_shape=False)


@register_op("data_norm",
             inputs=[In("X"), In("BatchSize", no_grad=True),
                     In("BatchSum", no_grad=True),
                     In("BatchSquareSum", no_grad=True)],
             outputs=[Out("Y"), Out("Means", no_grad=True),
                      Out("Scales", no_grad=True)],
             attrs={"epsilon": 1e-4},
             grad=_data_norm_grad_maker)
def _data_norm(ins, attrs):
    """Normalization by accumulated batch statistics (reference
    data_norm_op.cc): mean = sum/size, scale = sqrt(size/square_sum)."""
    x = ins["X"]
    eps = attrs.get("epsilon", 1e-4)
    size = ins["BatchSize"]
    mean = ins["BatchSum"] / size
    # reference data_norm_op.cc:209: scale = sqrt(size / square_sum)
    scale = jnp.sqrt(size / (ins["BatchSquareSum"] + eps))
    return {"Y": (x - mean[None, :]) * scale[None, :],
            "Means": mean, "Scales": scale}


@register_op("data_norm_grad",
             inputs=[In("X", no_grad=True), In("Means", no_grad=True),
                     In("Scales", no_grad=True), In("Y@GRAD", no_grad=True)],
             outputs=[Out("X@GRAD", no_grad=True),
                      Out("BatchSize", no_grad=True),
                      Out("BatchSum", no_grad=True),
                      Out("BatchSquareSum", no_grad=True)],
             attrs={"epsilon": 1e-4}, grad=None)
def _data_norm_grad(ins, attrs):
    """reference data_norm_op.cc:392-397 (dX = dY·scale) and :440-449
    (default non-slot stat update): size=N, sum=Σx,
    square_sum=Σ(x-mean)²+N·ε."""
    x = ins["X"]
    dy = ins["Y@GRAD"]
    eps = attrs.get("epsilon", 1e-4)
    n = float(x.shape[0])
    dx = dy * ins["Scales"][None, :]
    mean = ins["Means"]
    return {"X@GRAD": dx,
            "BatchSize": jnp.full((x.shape[-1],), n, x.dtype),
            "BatchSum": x.sum(axis=0),
            "BatchSquareSum": ((x - mean[None, :]) ** 2).sum(axis=0)
            + n * eps}


@register_op("center_loss",
             inputs=[In("X"), In("Label", no_grad=True),
                     In("Centers", no_grad=True),
                     In("CenterUpdateRate", no_grad=True)],
             outputs=[Out("CentersOut", no_grad=True), Out("SampleCenterDiff"),
                      Out("Loss")],
             attrs={"cluster_num": 0, "need_update": True})
def _center_loss(ins, attrs):
    """Center loss (reference center_loss_op.h): pull features toward
    per-class centers; centers update by the mean residual."""
    x = ins["X"]
    label = ins["Label"].reshape(-1).astype(jnp.int32)
    centers = ins["Centers"]
    alpha = ins["CenterUpdateRate"].reshape(())
    picked = centers[label]
    diff = x - picked
    loss = 0.5 * jnp.sum(jnp.square(diff), axis=-1, keepdims=True)
    if attrs.get("need_update", True):
        counts = jnp.zeros(centers.shape[0], x.dtype).at[label].add(1.0)
        sums = jnp.zeros_like(centers).at[label].add(diff)
        update = sums / (1.0 + counts)[:, None]
        centers = centers + alpha * update
    return {"CentersOut": centers, "SampleCenterDiff": diff,
            "Loss": loss}


@register_host_op("tensor_array_to_tensor",
                  inputs=[In("X", no_grad=True)],
                  outputs=[Out("Out"), Out("OutIndex")],
                  attrs={"axis": 0, "use_stack": False})
def _tensor_array_to_tensor(executor, op, scope):
    """Concat/stack a LoDTensorArray (reference
    tensor_array_to_tensor_op.cc)."""
    arr = scope.find_var(op.input("X")[0]).get_lod_tensor_array()
    axis = op.attrs.get("axis", 0)
    mats = [np.asarray(t.array if hasattr(t, "array") else t)
            for t in arr]
    if op.attrs.get("use_stack", False):
        out = np.stack(mats, axis=axis)
    else:
        out = np.concatenate(mats, axis=axis)
    executor._write_var(scope, op.output("Out")[0], out)
    executor._write_var(scope, op.output("OutIndex")[0],
                        np.asarray([m.shape[axis] for m in mats],
                                   np.int32))


@register_op("shuffle_batch",
             inputs=[In("X")],
             outputs=[Out("Out"), Out("ShuffleIdx", no_grad=True),
                      Out("SeedOut", no_grad=True, dispensable=True)],
             attrs={"startup_seed": 0}, needs_rng=True, grad=None)
def _shuffle_batch(ins, attrs):
    """Random shuffle of rows over all leading dims (reference
    contrib shuffle_batch_op.cc); last dim kept intact. startup_seed
    folds into the per-step stream (it seeds the engine, it does NOT
    freeze the permutation — each step still draws a fresh shuffle,
    matching the reference's evolving seed)."""
    from ..core.registry import RNG_SEED_ATTR

    x = ins["X"]
    lead = 1
    for s in x.shape[:-1]:
        lead *= s
    flat = x.reshape(lead, x.shape[-1])
    key = jax.random.fold_in(jax.random.PRNGKey(ins[RNG_SEED_ATTR]),
                             int(attrs.get("startup_seed", 0)))
    perm = jax.random.permutation(key, lead)
    # int32: jax's default int width here (int64 would truncate with a
    # warning unless x64 is enabled)
    return {"Out": flat[perm].reshape(x.shape),
            "ShuffleIdx": perm.astype(jnp.int32),
            "SeedOut": jnp.zeros((1,), jnp.int32)}


@register_op("shuffle_batch_grad",
             inputs=[In("ShuffleIdx", no_grad=True),
                     In("Out@GRAD", no_grad=True)],
             outputs=[Out("X@GRAD", no_grad=True)],
             attrs={"startup_seed": 0}, grad=None)
def _shuffle_batch_grad(ins, attrs):
    """Un-permute the gradient (reference shuffle_batch_op.cc grad:
    dX[perm[i]] = dOut[i])."""
    dout = ins["Out@GRAD"]
    perm = ins["ShuffleIdx"].reshape(-1).astype(jnp.int32)
    lead = perm.shape[0]
    flat = dout.reshape(lead, -1)
    dx = jnp.zeros_like(flat).at[perm].set(flat)
    return {"X@GRAD": dx.reshape(dout.shape)}


def _partial_slice(xs, start, length):
    outs = []
    for x in xs:
        s = start + x.shape[1] if start < 0 else start
        end = x.shape[1] if length < 0 else s + length
        outs.append(x[:, s:end])
    return outs


@register_op("partial_concat",
             inputs=[In("X", duplicable=True)], outputs=[Out("Out")],
             attrs={"start_index": 0, "length": -1})
def _partial_concat(ins, attrs):
    """Concat a column slice of every input (reference contrib
    partial_concat_op.cc)."""
    parts = _partial_slice(ins["X"], int(attrs.get("start_index", 0)),
                           int(attrs.get("length", -1)))
    return {"Out": jnp.concatenate(parts, axis=1)}


@register_op("partial_sum",
             inputs=[In("X", duplicable=True)], outputs=[Out("Out")],
             attrs={"start_index": 0, "length": -1})
def _partial_sum(ins, attrs):
    """Sum a column slice across inputs (reference contrib
    partial_sum_op.cc)."""
    parts = _partial_slice(ins["X"], int(attrs.get("start_index", 0)),
                           int(attrs.get("length", -1)))
    out = parts[0]
    for p in parts[1:]:
        out = out + p
    return {"Out": out}
