"""MQ2007 learning-to-rank reader creators (reference
python/paddle/dataset/mq2007.py).

Sample contracts (reference Dataset.format): "pointwise" yields
(score float, feature float32[46]); "pairwise" yields (pos_features,
neg_features); "listwise" yields (query_list_of_labels, features).
Synthetic fallback: per-query documents whose relevance is a linear
function of a fixed hidden weight plus noise, deterministic.
"""
from __future__ import annotations

import os

import numpy as np

from .common import DATA_HOME

__all__ = ["train", "test"]

_N_FEATURES = 46


def _synthetic_queries(n_queries, seed):
    rng = np.random.RandomState(seed)
    w = np.random.RandomState(7).randn(_N_FEATURES)
    for _ in range(n_queries):
        n_docs = int(rng.randint(4, 10))
        feats = rng.rand(n_docs, _N_FEATURES).astype("float32")
        scores = feats @ w + rng.randn(n_docs) * 0.1
        rel = np.clip(np.digitize(scores, np.percentile(
            scores, [50, 80])), 0, 2)
        yield rel.astype("float32"), feats


def _reader_creator(format, n_queries, seed):
    def pointwise():
        for rel, feats in _synthetic_queries(n_queries, seed):
            for r, f in zip(rel, feats):
                yield float(r), f

    def pairwise():
        for rel, feats in _synthetic_queries(n_queries, seed):
            order = np.argsort(-rel)
            for i in order:
                for j in order:
                    if rel[i] > rel[j]:
                        yield feats[i], feats[j]

    def listwise():
        for rel, feats in _synthetic_queries(n_queries, seed):
            yield list(rel), feats

    return {"pointwise": pointwise, "pairwise": pairwise,
            "listwise": listwise}[format]


def train(format="pairwise"):
    d = os.path.join(DATA_HOME, "MQ2007")
    if os.path.exists(os.path.join(d, "MQ2007.rar")):
        raise NotImplementedError(
            "real MQ2007 .rar parsing is not supported offline; remove "
            "%s to use the synthetic reader" % d)
    return _reader_creator(format, 120, seed=100)


def test(format="pairwise"):
    return _reader_creator(format, 24, seed=101)
