"""Collective fleet mode (reference incubate/fleet/collective/__init__.py
:45 Collective(Fleet), :182 CollectiveOptimizer, :134 DistributedStrategy).

TPU-native semantics: distributed_optimizer().minimize() runs the normal
minimize then the collective transpiler (loss-grad 1/nranks scaling +
per-grad c_allreduce_sum); main_program executes through the mesh engine
(CompiledProgram.with_data_parallel), whose shard_map lowers the
collectives to lax.psum over ICI. Multi-host: the same program under
jax.distributed initialization — no NCCL rings to bootstrap.
"""
from __future__ import annotations

from ....compiler import BuildStrategy, CompiledProgram, ExecutionStrategy
from ..base.fleet_base import DistributedOptimizer, Fleet


class DistributedStrategy:
    """Knobs (reference DistributedStrategy extends BuildStrategy)."""

    def __init__(self):
        self.build_strategy = BuildStrategy()
        self.exec_strategy = ExecutionStrategy()
        self.nccl_comm_num = 1
        self.use_local_sgd = False
        self.local_sgd_k_steps = 1
        self.forward_recompute = False
        self.recompute_checkpoints = []
        self.use_amp = False
        self.amp_loss_scaling = 1.0


class Collective(Fleet):
    def __init__(self):
        super().__init__("collective")
        self._main_program = None
        self._compiled_program = None
        self._loss = None

    def distributed_optimizer(self, optimizer, strategy=None):
        self._optimizer = CollectiveOptimizer(optimizer, strategy, self)
        return self._optimizer

    def init_worker(self):
        pass

    def init_server(self, model_dir=None):
        raise NotImplementedError(
            "Collective mode has no servers; use the transpiler PS mode")

    def run_server(self):
        raise NotImplementedError(
            "Collective mode has no servers; use the transpiler PS mode")

    def stop_worker(self):
        pass

    def save_inference_model(self, executor, dirname, feeded_var_names,
                             target_vars, main_program=None,
                             export_for_deployment=True):
        from .... import io

        io.save_inference_model(dirname, feeded_var_names, target_vars,
                                executor, main_program or self._main_program)

    def save_persistables(self, executor, dirname, main_program=None):
        from .... import io

        io.save_persistables(executor, dirname,
                             main_program or self._main_program)

    @property
    def main_program(self):
        """The mesh-executable program (reference: fleet.main_program is
        the compiled data-parallel program)."""
        return self._compiled_program or self._main_program


class CollectiveOptimizer(DistributedOptimizer):
    def __init__(self, optimizer, strategy=None, fleet_instance=None):
        super().__init__(optimizer, strategy or DistributedStrategy())
        self._fleet = fleet_instance

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from ....parallel.transpiler import (insert_allreduce_ops,
                                             insert_local_sgd_ops)

        opt = self._optimizer
        strategy = self._strategy
        if getattr(strategy, "use_amp", False):
            from ....contrib import mixed_precision as mp

            opt = mp.decorate(opt)
        if getattr(strategy, "forward_recompute", False):
            from ....optimizer import RecomputeOptimizer

            opt = RecomputeOptimizer(opt)
            opt._set_checkpoints(strategy.recompute_checkpoints)
        optimize_ops, params_grads = opt.minimize(
            loss, startup_program, parameter_list, no_grad_set)

        program = loss.block.program
        nranks = self._fleet.worker_num() if self._fleet else 1
        if nranks > 1:
            insert_allreduce_ops(program, nranks)
            if getattr(strategy, "use_local_sgd", False):
                insert_local_sgd_ops(program, nranks,
                                     strategy.local_sgd_k_steps)
        if self._fleet is not None:
            self._fleet._main_program = program
            self._fleet._loss = loss
            self._fleet._compiled_program = CompiledProgram(
                program).with_data_parallel(
                    loss_name=loss.name,
                    build_strategy=strategy.build_strategy,
                    exec_strategy=strategy.exec_strategy)
        return optimize_ops, params_grads


fleet = Collective()
