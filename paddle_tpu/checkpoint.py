"""Atomic, verifiable, rotated checkpoints.

The reference guards training state with checkpoint_notify +
save/load on the pserver side; what it does NOT guard against — and
this module does — is the crash *mid-save*: a process killed inside
``io.save`` used to leave a half-written model dir that the next load
would read as garbage. The contract here:

- **atomicity** — a checkpoint is written into a temp dir next to its
  final name, every file is fsync'd, a manifest with per-file sha256
  is written last, and the temp dir renames into place. A crash never
  leaves a torn hybrid: a NEW checkpoint name (the rotation manager's
  only case) appears all-or-nothing; overwriting an existing name has
  one rename-wide window where only that name is absent — older
  rotations still serve ``load_latest``, and the next save sweeps the
  stranded dirs. Readers can never observe the temp dir (``.tmp-``
  names are skipped by the rotation scan).
- **verifiability** — ``verify_manifest`` recomputes each listed
  file's sha256; any mismatch/missing file raises the typed
  ``CheckpointCorrupt`` instead of a numpy parse error three frames
  deep.
- **rotation** — ``CheckpointManager`` keeps the newest ``keep``
  checkpoints under ``root/ckpt-<step>/`` with an atomically-updated
  ``latest`` pointer; ``load_latest`` walks newest-to-oldest past
  corrupt entries, so one bad shard costs one checkpoint, not the run.
- **incremental saves** (ISSUE 8) — ``save_incremental`` reuses
  unchanged shards from the previous checkpoint by content hash (or a
  caller-supplied fingerprint, which skips even producing the bytes):
  a reused shard is hardlinked (or copied) from the previous dir
  instead of re-serialized + re-fsynced, so at GB scale the cost of a
  checkpoint tracks what *changed*, not what *exists*. Every
  checkpoint dir stays fully self-contained in its namespace — the
  manifest, rotation, corrupt fallback, and every existing loader work
  unchanged — and the incremental path is gated bit-for-bit against
  the full-blob path by the ft test suite.

``checkpoint.save_ms`` / ``checkpoint.bytes`` land in the
observability registry unconditionally (saves are rare and CI reads
them); ``checkpoint.delta_bytes`` (freshly-written payload) and
``checkpoint.shards_reused`` measure what the incremental path saved.
"""
from __future__ import annotations

import contextlib
import hashlib
import json
import os
import shutil
import threading
import time
from typing import Callable, Dict, List, Optional

__all__ = ["CheckpointCorrupt", "RestoreMissingShard", "MANIFEST_NAME",
           "SCOPE_VARS_NAME", "ROUND_PREFIX", "JOB_MANIFEST_NAME",
           "atomic_write_bytes", "atomic_checkpoint_dir",
           "makedirs_durable",
           "write_manifest", "verify_manifest", "manifest_extra",
           "load_scope_snapshot", "RoundStore", "job_restore_round",
           "job_has_durable_state", "read_job_manifest",
           "write_job_manifest",
           "CheckpointManager", "save_checkpoint", "load_checkpoint"]

MANIFEST_NAME = "__manifest__.json"
SCOPE_VARS_NAME = "__vars__.json"  # file name -> var name (snapshots)
_LATEST_NAME = "latest"
_CKPT_PREFIX = "ckpt-"
ROUND_PREFIX = "round-"         # RoundStore frame dirs
_ROUND_BLOB = "blob.bin"        # the frame's concatenated var payload
_OPLOG_NAME = "oplog.jsonl"     # async-mode op tail (RoundStore)
JOB_MANIFEST_NAME = "job.json"  # whole-job restore manifest (launcher)


class CheckpointCorrupt(RuntimeError):
    """A checkpoint failed integrity verification (missing file, size
    or sha256 mismatch, unreadable manifest). Callers holding older
    rotations should fall back; callers without one should fail loudly
    rather than train from garbage."""


class RestoreMissingShard(RuntimeError):
    """Whole-job restore needs a round that exists on EVERY shard, and
    this shard contributed none: its durable dir is missing, or every
    round frame in it is torn/corrupt. Names the shard so the operator
    knows which group's disk to recover (a mixed cut must never be
    loaded silently)."""

    def __init__(self, shard: int, root: str, why: str):
        self.shard = int(shard)
        super().__init__(
            "cannot restore the job: shard %d has no usable durable "
            "rounds under %r (%s)" % (self.shard, root, why))


def _observe(name: str, v) -> None:
    from . import observability as _obs

    _obs.histogram(name).observe(v)


def _count(name: str, n: int = 1, **labels) -> None:
    from . import observability as _obs

    _obs.counter(name, **labels).inc(n)


def _fsync_file(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return  # platform without O_RDONLY dirs; rename is still atomic
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def makedirs_durable(path: str) -> None:
    """``os.makedirs`` whose result survives a HOST crash: every level
    that was actually created gets its parent directory fsynced.
    ``makedirs`` alone only survives process death — the new dirent
    lives in the parent's page cache until the parent is synced, so a
    power cut could erase the directory a checkpoint was just renamed
    into (satellite of ISSUE 19)."""
    path = os.path.abspath(path)
    missing = []
    p = path
    while p and not os.path.isdir(p):
        missing.append(p)
        nxt = os.path.dirname(p)
        if nxt == p:
            break
        p = nxt
    os.makedirs(path, exist_ok=True)
    for created in reversed(missing):
        _fsync_dir(os.path.dirname(created))


def atomic_write_bytes(path: str, data: bytes) -> None:
    """Write ``data`` to ``path`` via tmp-file + fsync + rename: the
    file at ``path`` is always either the old content or all of
    ``data``, never a prefix."""
    d = os.path.dirname(os.path.abspath(path))
    makedirs_durable(d)
    # staging name unique per (process, thread, moment): concurrent
    # writers of the SAME path (racing manifest rewrites) must not
    # replace each other's staging file out from under the os.replace
    tmp = os.path.join(d, ".tmp-%s-%d-%d-%d" % (
        os.path.basename(path), os.getpid(),
        threading.get_ident() % 100000, time.monotonic_ns() % 1_000_000))
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(d)


def write_manifest(dirname: str, extra: Optional[Dict] = None,
                   files: Optional[List[str]] = None) -> Dict:
    """Hash files in ``dirname`` into ``__manifest__.json``, written
    atomically LAST — a dir with a valid manifest is a complete dir.
    ``files`` (names relative to ``dirname``) restricts the manifest
    to exactly what a save wrote; the default hashes every regular
    file (dedicated checkpoint dirs) — a save into a SHARED dir must
    pass ``files`` or it would pin unrelated, mutable files and make
    later verification fail spuriously."""
    names = files if files is not None else [
        fn for fn in sorted(os.listdir(dirname))
        if fn != MANIFEST_NAME and not fn.startswith(".tmp-")]
    listed = {}
    for fn in sorted(names):
        p = os.path.join(dirname, fn)
        if not os.path.isfile(p):
            continue
        _fsync_file(p)
        listed[fn] = {"sha256": _sha256(p),
                      "bytes": os.path.getsize(p)}
    doc = {"version": 1, "files": listed}
    if extra:
        doc.update(extra)
    atomic_write_bytes(os.path.join(dirname, MANIFEST_NAME),
                       json.dumps(doc, indent=1, sort_keys=True).encode())
    return doc


def manifest_extra(dirname: str) -> Dict:
    """The caller-supplied ``extra`` a save recorded in ``dirname``'s
    manifest — everything outside the reserved ``version``/``files``
    keys ({} when there is none, or the manifest is unreadable: the
    extra is advisory metadata, e.g. the PS shard map a trainer
    checkpoints so its relaunched incarnation resumes ROUTING from
    the checkpoint instead of rediscovering migrations through
    wrong_shard redirects; never load-bearing for the payload, which
    stays manifest-verified)."""
    try:
        with open(os.path.join(dirname, MANIFEST_NAME), "r",
                  encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return {}
    return {k: v for k, v in doc.items()
            if k not in ("version", "files")}


def verify_manifest(dirname: str, required: bool = True) -> Optional[Dict]:
    """Recompute and check every file listed in ``dirname``'s manifest.
    Raises ``CheckpointCorrupt`` on any mismatch; with
    ``required=False`` a missing manifest returns None (pre-manifest
    dirs stay loadable), otherwise it is itself corruption — an atomic
    save always writes one."""
    mpath = os.path.join(dirname, MANIFEST_NAME)
    if not os.path.exists(mpath):
        if not required:
            return None
        raise CheckpointCorrupt(
            "checkpoint dir %r has no %s — it was not written by an "
            "atomic save (or the save never completed)"
            % (dirname, MANIFEST_NAME))
    try:
        with open(mpath, "r", encoding="utf-8") as f:
            doc = json.load(f)
        listed = doc["files"]
    except (ValueError, KeyError, OSError) as e:
        raise CheckpointCorrupt(
            "checkpoint manifest %r is unreadable: %s" % (mpath, e)
        ) from e
    for fn, meta in listed.items():
        p = os.path.join(dirname, fn)
        if not os.path.exists(p):
            raise CheckpointCorrupt(
                "checkpoint %r is missing file %r listed in its "
                "manifest" % (dirname, fn))
        size = os.path.getsize(p)
        if size != int(meta.get("bytes", -1)):
            raise CheckpointCorrupt(
                "checkpoint file %r is %d bytes, manifest says %s"
                % (p, size, meta.get("bytes")))
        digest = _sha256(p)
        if digest != meta.get("sha256"):
            raise CheckpointCorrupt(
                "checkpoint file %r fails sha256 verification "
                "(got %s…, manifest says %s…)"
                % (p, digest[:12], str(meta.get("sha256"))[:12]))
    return doc


def load_scope_snapshot(executor, scope, dirname: str) -> int:
    """Restore a ``snapshot_scope_to_dir`` directory into ``scope``
    after verifying its manifest — the pserver rejoin catch-up path: a
    relaunched server must never boot off a torn snapshot, so any
    integrity failure raises the typed ``CheckpointCorrupt`` instead
    of loading garbage params. Var names come from ``__vars__.json``
    when present (dedicated snapshots write it) and fall back to the
    file names. Returns the number of vars restored."""
    from .core import proto_format

    verify_manifest(dirname, required=True)
    vmap_path = os.path.join(dirname, SCOPE_VARS_NAME)
    if os.path.exists(vmap_path):
        with open(vmap_path, "r", encoding="utf-8") as f:
            names = json.load(f)
    else:
        names = {fn: fn for fn in sorted(os.listdir(dirname))
                 if fn not in (MANIFEST_NAME, SCOPE_VARS_NAME)
                 and not fn.startswith(".tmp-")
                 and os.path.isfile(os.path.join(dirname, fn))}
    loaded = 0
    for fn, var in sorted(names.items()):
        with open(os.path.join(dirname, fn), "rb") as f:
            data = f.read()
        arr, _lod, _pos = proto_format.parse_lod_tensor(data)
        executor._write_var(scope, var, arr.copy())
        loaded += 1
    return loaded


@contextlib.contextmanager
def atomic_checkpoint_dir(final_dir: str, extra: Optional[Dict] = None):
    """Context manager: yields a temp dir to write checkpoint files
    into; on clean exit fsyncs everything, writes the manifest, and
    renames the temp dir to ``final_dir`` (replacing any previous
    version only after the new one is durable). On error the temp dir
    is removed and ``final_dir`` is untouched."""
    final_dir = os.path.abspath(final_dir).rstrip(os.sep)
    parent = os.path.dirname(final_dir)
    makedirs_durable(parent)
    # sweep trash a SIGKILLed earlier save stranded (NOT .tmp- dirs: a
    # concurrent save of the same name may be live inside one; tmp
    # leftovers are invisible to scans and merely cost disk)
    base = os.path.basename(final_dir)
    for fn in os.listdir(parent):
        if fn.startswith(base + ".trash-"):
            shutil.rmtree(os.path.join(parent, fn), ignore_errors=True)
    tmp = "%s.tmp-%d-%d" % (final_dir, os.getpid(),
                            time.monotonic_ns() % 1_000_000)
    os.makedirs(tmp)
    t0 = time.monotonic()
    try:
        yield tmp
        doc = write_manifest(tmp, extra=extra)
        _fsync_dir(tmp)
        if os.path.isdir(final_dir):
            # rename-aside + rename-in, not rmtree-then-rename: the
            # no-checkpoint window shrinks to the instant between the
            # two renames (a SIGKILL exactly there costs only THIS
            # name — rotation siblings still serve load_latest; the
            # stranded trash/tmp dirs are swept by the next save)
            trash = "%s.trash-%d-%d" % (final_dir, os.getpid(),
                                        time.monotonic_ns() % 1_000_000)
            os.rename(final_dir, trash)
            os.rename(tmp, final_dir)
            shutil.rmtree(trash, ignore_errors=True)
        else:
            os.rename(tmp, final_dir)
        _fsync_dir(parent)
        total = sum(int(m["bytes"]) for m in doc["files"].values())
        _count("checkpoint.bytes", total)
        _observe("checkpoint.save_ms", (time.monotonic() - t0) * 1e3)
        from .observability import flight as _flight

        _flight.record("checkpoint.commit",
                       dir=os.path.basename(final_dir), bytes=total,
                       ms=round((time.monotonic() - t0) * 1e3, 3))
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise


class CheckpointManager:
    """Keep-last-k rotation under one root::

        root/
          ckpt-42/   __params__.npz  __manifest__.json
          ckpt-43/   ...
          latest     -> "ckpt-43"        (atomically updated pointer)

    ``save`` writes a new numbered checkpoint atomically, repoints
    ``latest``, and prunes beyond ``keep``. ``load_latest`` tries the
    pointer first, then remaining checkpoints newest-to-oldest,
    skipping (and counting) corrupt ones."""

    def __init__(self, root: str, keep: int = 3):
        self.root = os.path.abspath(root)
        self.keep = max(1, int(keep))

    # -- layout ------------------------------------------------------------

    def dir_for(self, step: int) -> str:
        return os.path.join(self.root, "%s%d" % (_CKPT_PREFIX, int(step)))

    def steps(self) -> List[int]:
        """Completed (renamed-into-place) checkpoint steps, ascending;
        temp/trash dirs are invisible by construction."""
        if not os.path.isdir(self.root):
            return []
        out = []
        for fn in os.listdir(self.root):
            if not fn.startswith(_CKPT_PREFIX):
                continue
            tail = fn[len(_CKPT_PREFIX):]
            if tail.isdigit() and os.path.isdir(
                    os.path.join(self.root, fn)):
                out.append(int(tail))
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        """The ``latest`` pointer's step when it names an existing
        checkpoint, else the newest numbered dir, else None."""
        ptr = os.path.join(self.root, _LATEST_NAME)
        try:
            with open(ptr, "r", encoding="utf-8") as f:
                name = f.read().strip()
            tail = name[len(_CKPT_PREFIX):]
            if (name.startswith(_CKPT_PREFIX) and tail.isdigit()
                    and os.path.isdir(os.path.join(self.root, name))):
                return int(tail)
        except OSError:
            pass
        steps = self.steps()
        return steps[-1] if steps else None

    # -- save / load -------------------------------------------------------

    def save(self, step: int, writer: Callable[[str], None],
             extra: Optional[Dict] = None) -> str:
        """Write checkpoint ``step`` atomically: ``writer(tmp_dir)``
        produces the files; manifest + rename + ``latest`` update +
        pruning happen here. Returns the final dir."""
        final = self.dir_for(step)
        meta = {"step": int(step)}
        if extra:
            meta.update(extra)
        with atomic_checkpoint_dir(final, extra=meta) as tmp:
            writer(tmp)
        atomic_write_bytes(os.path.join(self.root, _LATEST_NAME),
                           os.path.basename(final).encode())
        self._prune()
        return final

    def save_incremental(self, step: int, shards: Dict,
                         fingerprints: Optional[Dict[str, str]] = None,
                         extra: Optional[Dict] = None,
                         reuse: str = "link") -> str:
        """Write checkpoint ``step`` reusing unchanged shards from the
        previous checkpoint. ``shards`` maps file name -> bytes or a
        zero-arg callable producing bytes (lazy: never called when the
        shard is fingerprint-matched). A shard is reused — hardlinked
        (``reuse="link"``, the cheap default) or copied
        (``reuse="copy"``) from the previous checkpoint dir — when

        - ``fingerprints[name]`` matches the fingerprint the previous
          manifest recorded for it (the caller's cheap dirty-tracking:
          a version counter, the server's replication digest, ...), or
        - its produced bytes' sha256 matches the previous manifest
          entry (content dedupe — still skips the fresh write+fsync).

        Every dir remains self-contained in its NAMESPACE (loaders and
        ``verify_manifest`` are oblivious), atomic, and rotated as
        usual. Hardlink caveat: reused shards share an inode with the
        previous checkpoint, so in-PLACE corruption of one damages
        both (both detected by their manifests); corruption that
        replaces the file (the common torn-write case) breaks the link
        and costs one checkpoint. Use ``reuse="copy"`` where that
        blast radius matters more than the write savings.

        ``checkpoint.delta_bytes`` counts only the freshly-written
        payload; ``checkpoint.shards_reused`` counts the links — the
        pair is the incremental win, next to the full
        ``checkpoint.bytes``."""
        if reuse not in ("link", "copy"):
            raise ValueError("reuse must be 'link' or 'copy', got %r"
                             % reuse)
        fingerprints = dict(fingerprints or {})
        prev_step = self.latest_step()
        prev_dir = self.dir_for(prev_step) if prev_step is not None \
            else None
        prev_files: Dict = {}
        prev_fps: Dict = {}
        if prev_dir is not None:
            try:
                with open(os.path.join(prev_dir, MANIFEST_NAME),
                          encoding="utf-8") as f:
                    doc = json.load(f)
                prev_files = doc.get("files", {}) or {}
                prev_fps = doc.get("fingerprints", {}) or {}
            except (OSError, ValueError):
                prev_files, prev_fps = {}, {}  # unreadable: full save

        stats = {"reused": 0, "fresh_bytes": 0}

        def _reuse(src: str, dst: str) -> None:
            if reuse == "link":
                try:
                    os.link(src, dst)
                    return
                except OSError:
                    pass  # cross-device / fs without links: fall back
            shutil.copy2(src, dst)

        def writer(tmp: str) -> None:
            for fn in sorted(shards):
                prev_meta = prev_files.get(fn)
                prev_path = (os.path.join(prev_dir, fn)
                             if prev_dir is not None else None)
                have_prev = (prev_meta is not None and prev_path
                             and os.path.isfile(prev_path))
                fp = fingerprints.get(fn)
                if (have_prev and fp is not None
                        and prev_fps.get(fn) == fp):
                    _reuse(prev_path, os.path.join(tmp, fn))
                    stats["reused"] += 1
                    continue
                src = shards[fn]
                data = src() if callable(src) else bytes(src)
                if (have_prev and prev_meta.get("sha256")
                        == hashlib.sha256(data).hexdigest()):
                    _reuse(prev_path, os.path.join(tmp, fn))
                    stats["reused"] += 1
                    continue
                atomic_write_bytes(os.path.join(tmp, fn), data)
                stats["fresh_bytes"] += len(data)

        meta = dict(extra or {})
        meta["fingerprints"] = fingerprints
        final = self.save(step, writer, extra=meta)
        _count("checkpoint.delta_bytes", stats["fresh_bytes"])
        _count("checkpoint.shards_reused", stats["reused"])
        return final

    def _prune(self) -> None:
        steps = self.steps()
        for s in steps[:-self.keep]:
            shutil.rmtree(self.dir_for(s), ignore_errors=True)

    def load_latest(self, loader: Callable[[str], None]) -> Optional[int]:
        """Verify + load the newest valid checkpoint; walks past
        corrupt ones (counting ``checkpoint.corrupt``) so one bad
        shard falls back to the previous rotation. Returns the loaded
        step, or None when no checkpoint exists. Raises
        ``CheckpointCorrupt`` only when checkpoints exist but ALL fail
        verification."""
        candidates = sorted(self.steps(), reverse=True)
        latest = self.latest_step()
        if latest is not None and latest in candidates:
            candidates.remove(latest)
            candidates.insert(0, latest)
        if not candidates:
            return None
        errors = []
        for step in candidates:
            d = self.dir_for(step)
            try:
                verify_manifest(d, required=True)
                loader(d)
                return step
            except CheckpointCorrupt as e:
                _count("checkpoint.corrupt")
                errors.append(str(e))
                continue
        raise CheckpointCorrupt(
            "every checkpoint under %r failed verification: %s"
            % (self.root, "; ".join(errors)))

    def load_at_or_before(self, step: int,
                          loader: Callable[[str], None]) -> Optional[int]:
        """Like ``load_latest`` but clamped to checkpoints at or below
        ``step`` — the whole-job cold-restart case (ISSUE 19): the
        launcher's common restore cut can sit BEHIND this process's
        newest checkpoint (a sister shard's newest round was torn and
        the job fell back one round), and resuming ahead of the
        servers would re-drive nothing while the servers wait for
        rounds the trainer thinks already happened. Walks the eligible
        checkpoints newest-to-oldest past corrupt ones; returns the
        loaded step or None when none qualify."""
        step = int(step)
        candidates = [s for s in sorted(self.steps(), reverse=True)
                      if s <= step]
        if not candidates:
            return None
        errors = []
        for s in candidates:
            d = self.dir_for(s)
            try:
                verify_manifest(d, required=True)
                loader(d)
                return s
            except CheckpointCorrupt as e:
                _count("checkpoint.corrupt")
                errors.append(str(e))
                continue
        raise CheckpointCorrupt(
            "every checkpoint at or before step %d under %r failed "
            "verification: %s" % (step, self.root, "; ".join(errors)))


# -- round-fenced durable snapshots (ISSUE 19) -------------------------------
#
# The sharded PS survives PARTIAL failures through live replication;
# a correlated loss (every member of a group, or the whole job) needs
# state on DISK, cut at a round boundary. RoundStore persists, per
# shard group, the exact frame the primary ships to its backups at
# each round commit — full anchors every PADDLE_PS_ANCHOR_EVERY
# rounds, row/chunk deltas in between — so per-round durable bytes
# ride the same <1%-of-table delta path as the wire
# (``checkpoint.round_bytes{mode=full|delta}``). Restore replays the
# newest anchor chain up to a target round with the same splice
# semantics a backup applies, and ``job_restore_round`` computes the
# newest round present on EVERY shard (never a mixed cut), walking
# round-aware past torn newest frames.


class RoundStore:
    """Durable round frames for ONE shard group::

        root/shard-<k>/
          round-41/  blob.bin  __manifest__.json   (mode=full anchor)
          round-42/  blob.bin  __manifest__.json   (mode=delta, base 41)
          oplog.jsonl                              (async op tail)

    Each frame dir is written atomically (manifest last, rename in,
    parent fsynced) with the frame metadata — round, mode, base round,
    fencing epoch, dedup watermark, var headers, and the shard-map /
    migration extras — in the manifest's ``extra``; ``blob.bin`` is
    the concatenated var payload. A frame is *restorable* when its own
    manifest verifies AND (for deltas) its base round is restorable —
    a torn newest frame therefore silently falls back to the previous
    complete round instead of failing restore. Retention keeps the
    newest ``keep_anchors`` anchor chains (fallback needs at least the
    previous one)."""

    def __init__(self, root: str, shard: int = 0,
                 keep_anchors: Optional[int] = None):
        self.root = os.path.abspath(root)
        self.shard = int(shard)
        self.dir = os.path.join(self.root, "shard-%d" % self.shard)
        if keep_anchors is None:
            keep_anchors = int(os.environ.get(
                "PADDLE_PS_DURABLE_KEEP_ANCHORS", "2"))
        self.keep_anchors = max(2, int(keep_anchors))
        self._oplog_path = os.path.join(self.dir, _OPLOG_NAME)
        self._oplog_fp = None
        self._meta_cache: Dict[int, Optional[Dict]] = {}

    # -- layout ------------------------------------------------------------

    def round_dir(self, round_no: int) -> str:
        return os.path.join(self.dir,
                            "%s%d" % (ROUND_PREFIX, int(round_no)))

    def rounds(self) -> List[int]:
        """Renamed-into-place round numbers, ascending (temp/trash
        dirs are invisible by construction)."""
        if not os.path.isdir(self.dir):
            return []
        out = []
        for fn in os.listdir(self.dir):
            if not fn.startswith(ROUND_PREFIX):
                continue
            tail = fn[len(ROUND_PREFIX):]
            if tail.isdigit() and os.path.isdir(
                    os.path.join(self.dir, fn)):
                out.append(int(tail))
        return sorted(out)

    def meta(self, round_no: int) -> Optional[Dict]:
        """Verified frame metadata for ``round_no`` (None when the
        frame is absent, torn, or corrupt). Verification results are
        cached — a frame dir is immutable once renamed into place."""
        round_no = int(round_no)
        if round_no in self._meta_cache:
            return self._meta_cache[round_no]
        d = self.round_dir(round_no)
        meta: Optional[Dict] = None
        try:
            verify_manifest(d, required=True)
            meta = manifest_extra(d)
        except CheckpointCorrupt:
            _count("checkpoint.corrupt")
            meta = None
        self._meta_cache[round_no] = meta
        return meta

    # -- persist -----------------------------------------------------------

    def put_round(self, round_no: int, headers: List[Dict], raw: bytes,
                  watermark: Dict, mode: str = "full",
                  base_round: Optional[int] = None, epoch: int = 0,
                  extra: Optional[Dict] = None) -> str:
        """Persist one applied round's replication frame atomically.
        ``checkpoint.round_bytes{mode=}`` counts the payload — CI
        watches that delta rounds stay a sliver of anchors."""
        meta = {"round": int(round_no), "mode": str(mode),
                "base_round": (-1 if base_round is None
                               else int(base_round)),
                "epoch": int(epoch), "shard": self.shard,
                "watermark": {str(k): int(v)
                              for k, v in (watermark or {}).items()},
                "vars": list(headers)}
        if extra:
            meta["repl_extra"] = extra
        final = self.round_dir(round_no)
        with atomic_checkpoint_dir(final, extra=meta) as tmp:
            atomic_write_bytes(os.path.join(tmp, _ROUND_BLOB), raw)
        self._meta_cache[int(round_no)] = meta
        _count("checkpoint.round_bytes", len(raw), mode=str(mode))
        self._prune()
        return final

    def _prune(self) -> None:
        """Drop frames older than the ``keep_anchors``-newest anchor
        (every kept anchor's delta chain stays whole — restore may
        legitimately fall back to the PREVIOUS chain)."""
        rounds = self.rounds()
        anchors = [r for r in rounds
                   if (self.meta(r) or {}).get("mode") == "full"]
        if len(anchors) <= self.keep_anchors:
            return
        floor = anchors[-self.keep_anchors]
        for r in rounds:
            if r < floor:
                shutil.rmtree(self.round_dir(r), ignore_errors=True)
                self._meta_cache.pop(r, None)

    # -- restore -----------------------------------------------------------

    def restorable_rounds(self) -> List[int]:
        """Rounds whose whole anchor→delta chain verifies, ascending —
        the rounds this shard can contribute to a job-wide cut. A
        delta whose base is missing/corrupt (or whose own frame is
        torn) drops out, along with everything chained past it."""
        good: set = set()
        for r in self.rounds():
            m = self.meta(r)
            if m is None:
                continue
            if m.get("mode") == "full":
                good.add(r)
            elif int(m.get("base_round", -2)) == r - 1 and (r - 1) in good:
                good.add(r)
        return sorted(good)

    def load_round(self, target: int, apply_fn) -> int:
        """Replay the newest anchor chain ending at ``target``:
        ``apply_fn(meta, raw)`` is called for the anchor and every
        delta after it in order, with the same splice semantics a
        replication backup uses. Raises ``CheckpointCorrupt`` when
        ``target`` is not restorable here."""
        target = int(target)
        if target not in set(self.restorable_rounds()):
            raise CheckpointCorrupt(
                "shard %d cannot restore round %d from %r (rounds on "
                "disk: %s)" % (self.shard, target, self.dir,
                               self.rounds()))
        chain = []
        r = target
        while True:
            m = self.meta(r)
            chain.append((r, m))
            if m.get("mode") == "full":
                break
            r -= 1
        for r, m in reversed(chain):
            with open(os.path.join(self.round_dir(r), _ROUND_BLOB),
                      "rb") as f:
                raw = f.read()
            apply_fn(m, raw)
        return target

    # -- async op tail (geo/async mode, ISSUE 19) --------------------------

    def append_op(self, entry: Dict) -> None:
        """Durably append one applied async op (flush + fsync: the op
        was acked to the client — it must survive a whole-job kill).
        ``entry`` carries the op payload plus its dedup token and the
        synthetic round that will fold it (``round``); the tail is
        truncated whenever that round's frame lands."""
        makedirs_durable(self.dir)
        if self._oplog_fp is None:
            self._oplog_fp = open(self._oplog_path, "ab")
        self._oplog_fp.write(
            (json.dumps(entry, sort_keys=True) + "\n").encode())
        self._oplog_fp.flush()
        os.fsync(self._oplog_fp.fileno())

    def clear_ops_through(self, round_no: int) -> None:
        """Drop logged ops folded into round ``round_no``'s frame (they
        are now covered by the frame itself)."""
        keep = [e for e in self.pending_ops()
                if int(e.get("round", 0)) > int(round_no)]
        if self._oplog_fp is not None:
            self._oplog_fp.close()
            self._oplog_fp = None
        if not keep and os.path.exists(self._oplog_path):
            os.unlink(self._oplog_path)
            _fsync_dir(self.dir)
            return
        if keep:
            atomic_write_bytes(
                self._oplog_path,
                b"".join((json.dumps(e, sort_keys=True) + "\n").encode()
                         for e in keep))

    def pending_ops(self, after_round: Optional[int] = None) -> List[Dict]:
        """Logged ops newer than ``after_round`` (all of them when
        None), oldest first; a torn final line (killed mid-append) is
        ignored — that op was never acked durable."""
        out = []
        try:
            with open(self._oplog_path, "rb") as f:
                for line in f:
                    try:
                        e = json.loads(line.decode("utf-8"))
                    except ValueError:
                        continue  # torn tail
                    if after_round is None \
                            or int(e.get("round", 0)) > int(after_round):
                        out.append(e)
        except OSError:
            return []
        return out


def job_restore_round(root: str, expected_shards: int) -> Optional[int]:
    """The newest round restorable on EVERY shard group under
    ``root`` — the only cut a whole-job cold restart may load. Walks
    each shard round-aware (torn newest frames fall out of that
    shard's restorable set, pulling the job cut back with them).
    Raises the typed ``RestoreMissingShard`` — naming the shard — when
    a group's durable dir is missing or holds no complete round; a
    mixed or partial restore must never happen silently. Returns None
    only when no round is common to all shards (shouldn't happen with
    per-round persistence; callers treat it as nothing-to-restore)."""
    common: Optional[set] = None
    for k in range(max(1, int(expected_shards))):
        store = RoundStore(root, k)
        if not os.path.isdir(store.dir):
            raise RestoreMissingShard(
                k, root, "durable dir %r does not exist" % store.dir)
        good = set(store.restorable_rounds())
        if not good:
            raise RestoreMissingShard(
                k, root, "no complete round frame (all torn or corrupt)")
        common = good if common is None else (common & good)
    if not common:
        return None
    return max(common)


def job_has_durable_state(root: str) -> bool:
    """True when ANY shard group left round frames under ``root`` —
    the launcher's restore auto-detect probe (cheap: no verification)."""
    if not root or not os.path.isdir(root):
        return False
    for fn in os.listdir(root):
        d = os.path.join(root, fn)
        if fn.startswith("shard-") and os.path.isdir(d):
            for sub in os.listdir(d):
                if sub.startswith(ROUND_PREFIX) and os.path.isdir(
                        os.path.join(d, sub)):
                    return True
    return False


def read_job_manifest(root: str) -> Dict:
    """The launcher-written ``job.json`` under the durable root ({}
    when absent/unreadable): incarnation counter + the restore cut the
    job booted from."""
    try:
        with open(os.path.join(root, JOB_MANIFEST_NAME), "r",
                  encoding="utf-8") as f:
            doc = json.load(f)
        return doc if isinstance(doc, dict) else {}
    except (OSError, ValueError):
        return {}


def write_job_manifest(root: str, doc: Dict) -> str:
    path = os.path.join(root, JOB_MANIFEST_NAME)
    atomic_write_bytes(path, json.dumps(
        doc, indent=1, sort_keys=True).encode())
    return path


def save_checkpoint(executor, root: str, step: int, main_program=None,
                    keep: int = 3) -> str:
    """Atomic rotated persistables checkpoint for a static-graph
    program: ``io.save_persistables`` into ``root/ckpt-<step>/`` with
    manifest + ``latest`` pointer; keeps the newest ``keep``."""
    from . import io as _io

    mgr = CheckpointManager(root, keep=keep)
    return mgr.save(step, lambda d: _io.save_persistables(
        executor, d, main_program))


def load_checkpoint(executor, root: str, main_program=None):
    """Load the newest valid checkpoint saved by ``save_checkpoint``;
    returns its step, or None when ``root`` holds none."""
    from . import io as _io

    mgr = CheckpointManager(root)
    return mgr.load_latest(lambda d: _io.load_persistables(
        executor, d, main_program))
