"""Wide&Deep CTR model (the fleet north-star config 5).

Parity model: /root/reference/python/paddle/fluid/tests/unittests/
dist_ctr.py (sparse embeddings over hashed ids + wide LR part + deep
MLP part, sigmoid CTR head).
"""
from __future__ import annotations

from .. import layers
from ..param_attr import ParamAttr


def wide_deep(dense_input, sparse_ids, vocab_size, embed_dim=16,
              hidden_sizes=(64, 32), is_sparse=False,
              is_distributed=False, shared_table_name=None):
    """dense_input [N, Dd]; sparse_ids [N, S] int64 feature ids.
    Returns (predict [N, 2] softmax, feature list).
    ``is_distributed`` marks the embedding tables for the PS sparse-table
    path (row-sliced over pservers at transpile); ``shared_table_name``
    makes all slots share ONE table (the dist_ctr.py layout)."""
    # deep: embeddings + MLP
    embs = []
    s = int(sparse_ids.shape[1])
    for i in range(s):
        ids = layers.slice(sparse_ids, axes=[1], starts=[i], ends=[i + 1])
        emb = layers.embedding(
            ids, size=[vocab_size, embed_dim], is_sparse=is_sparse,
            is_distributed=is_distributed,
            param_attr=(None if shared_table_name is None else
                        ParamAttr(name=shared_table_name)))
        embs.append(layers.reshape(emb, [-1, embed_dim]))
    deep = layers.concat(embs + [dense_input], axis=1)
    for h in hidden_sizes:
        deep = layers.fc(deep, size=h, act="relu")
    # wide: linear over dense features
    wide = layers.fc(dense_input, size=8, act=None)
    merged = layers.concat([wide, deep], axis=1)
    return layers.fc(merged, size=2, act="softmax")
