"""Runtime tensor types.

TPU-native counterparts of the reference runtime types:

- ``LoDTensor`` (/root/reference/paddle/fluid/framework/lod_tensor.h:104):
  here a thin host-side wrapper over a ``jax.Array``. The LoD (level of
  detail — nested sequence offsets) stays *host metadata only*, because XLA
  programs are static-shape: variable-length ops lower to padded/masked
  dense compute and consult the LoD at trace time.
- ``SelectedRows`` (/root/reference/paddle/fluid/framework/selected_rows.h:32):
  sparse row-set gradients (embedding tables). Kept as (rows, values,
  height); optimizers either scatter-apply them or densify via segment-sum.

Unlike the reference there is no mutable_data/Resize protocol — arrays are
immutable jax values and "mutation" is rebinding inside a Scope.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from . import dtypes

LoD = List[List[int]]  # vector of offset vectors, like the reference's LoD


def _check_lod(lod: LoD, first_dim: int) -> None:
    for level in lod:
        if len(level) < 1 or level[0] != 0:
            raise ValueError("each LoD level must start with 0: %r" % (lod,))
        if any(b > a for a, b in zip(level[1:], level[:-1])):
            raise ValueError("LoD offsets must be non-decreasing: %r" % (lod,))
    if lod and lod[-1][-1] != first_dim:
        raise ValueError(
            "last LoD level must end at dim0=%d, got %r" % (first_dim, lod)
        )


class LoDTensor:
    """A dense device array plus optional host-side LoD metadata."""

    __slots__ = ("_array", "_lod")

    def __init__(self, array=None, lod: Optional[LoD] = None):
        self._array = array
        self._lod = [list(l) for l in lod] if lod else []

    # -- array ------------------------------------------------------------
    @property
    def array(self):
        return self._array

    def set(self, value, place=None):
        """Accept numpy/jax input; device placement is handled lazily by
        jax (op execution commits arrays to the op's place)."""
        import jax.numpy as jnp

        if isinstance(value, np.ndarray):
            self._array = jnp.asarray(value)
        else:
            self._array = value
        return self

    def numpy(self) -> np.ndarray:
        a = self._array
        if not getattr(a, "is_fully_addressable", True):
            # only a REPLICATED global Array can be read locally (each
            # shard is the full value); a sharded one would be silently
            # truncated to one shard's rows
            if a.sharding.is_fully_replicated:
                a = a.addressable_shards[0].data
            else:
                raise RuntimeError(
                    "cannot convert a multi-process SHARDED array to "
                    "numpy locally; gather it first "
                    "(multihost_utils.process_allgather)")
        return np.asarray(a)

    def __array__(self, dtype=None):
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    # -- shape/dtype ------------------------------------------------------
    def shape(self) -> Sequence[int]:
        return tuple(self._array.shape) if self._array is not None else ()

    def dtype(self) -> str:
        return dtypes.convert_dtype(self._array.dtype) if self._array is not None else "float32"

    def _is_initialized(self) -> bool:
        return self._array is not None

    # -- lod --------------------------------------------------------------
    def lod(self) -> LoD:
        return self._lod

    def set_lod(self, lod: LoD):
        if self._array is not None:
            _check_lod(lod, int(self._array.shape[0]))
        self._lod = [list(l) for l in lod]
        return self

    def recursive_sequence_lengths(self) -> List[List[int]]:
        return [
            [b - a for a, b in zip(level[:-1], level[1:])] for level in self._lod
        ]

    def set_recursive_sequence_lengths(self, lengths: List[List[int]]):
        lod = []
        for level in lengths:
            offsets = [0]
            for n in level:
                offsets.append(offsets[-1] + int(n))
            lod.append(offsets)
        self._lod = lod
        return self

    def has_valid_recursive_sequence_lengths(self) -> bool:
        try:
            _check_lod(self._lod, int(self._array.shape[0]))
            return True
        except (ValueError, AttributeError):
            return False

    def __repr__(self):
        return "LoDTensor(shape=%s, dtype=%s, lod=%s)" % (
            self.shape(),
            self.dtype(),
            self._lod,
        )


class SelectedRows:
    """Sparse row-set tensor: ``value[i]`` is the update for row ``rows[i]``
    of a dense tensor with ``height`` rows.

    Parity: /root/reference/paddle/fluid/framework/selected_rows.h:32.
    TPU-native design decision (SURVEY.md §7 hard part (c)): under
    whole-program compilation, embedding gradients stay DENSE —
    lookup_table_grad lowers to an XLA scatter-add the compiler fuses
    into the update, which on TPU beats materializing a ragged row set
    on the host. SelectedRows therefore serves (a) host-side API parity
    (merge_selected_rows / get_tensor_from_selected_rows ops and the
    save/load surface), and (b) the parameter-server path, where large
    sparse tables shard across workers and route rows via all-to-all
    (parallel/sharded_embedding) instead of PS pull/push."""

    __slots__ = ("_rows", "_value", "_height")

    def __init__(self, rows=None, height: int = 0, value=None):
        self._rows = list(rows) if rows is not None else []
        self._height = int(height)
        self._value = value if value is not None else LoDTensor()

    def rows(self):
        return self._rows

    def set_rows(self, rows):
        self._rows = list(rows)

    def height(self):
        return self._height

    def set_height(self, h):
        self._height = int(h)

    def get_tensor(self) -> LoDTensor:
        return self._value

    def to_dense(self):
        """Densify via segment-sum (duplicate rows accumulate)."""
        import jax.numpy as jnp

        val = self._value.array
        dense_shape = (self._height,) + tuple(val.shape[1:])
        out = jnp.zeros(dense_shape, dtype=val.dtype)
        idx = jnp.asarray(self._rows, dtype=jnp.int32)
        return out.at[idx].add(val)

    def __repr__(self):
        return "SelectedRows(height=%d, rows=%s, value=%r)" % (
            self._height,
            self._rows[:8],
            self._value,
        )


class LoDTensorArray(list):
    """A growable list of LoDTensors (reference: vector<LoDTensor>), used by
    while-loop bodies and fetch results."""

    pass
