"""slim prune / distillation / NAS (reference contrib/slim/tests/:
test_prune_strategy (prune-then-finetune recovers), test_distillation
(distilled student beats scratch), SA controller convergence)."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.contrib.slim.distillation import (
    L2Distiller, SoftLabelDistiller, fsp_matrix, merge_programs)
from paddle_tpu.contrib.slim.nas import SANAS, SearchSpace
from paddle_tpu.contrib.slim.prune import (
    SensitivePruneStrategy, StructurePruner, UniformPruneStrategy,
    compute_sensitivities, prune_parameter)
from paddle_tpu.contrib.slim.searcher import SAController


# ---------------------------------------------------------------------------
# pruner units
# ---------------------------------------------------------------------------


def test_cal_pruned_idx_l1():
    p = StructurePruner({"*": 0}, {"*": "l1_norm"})
    w = np.array([[3.0, 3.0], [0.1, 0.1], [1.0, 1.0], [0.2, 0.2]],
                 dtype="float32")
    idx = p.cal_pruned_idx("w", w, 0.5)
    assert sorted(idx.tolist()) == [1, 3]  # smallest l1 rows


def test_prune_tensor_hard_and_lazy():
    p = StructurePruner()
    t = np.arange(12, dtype="float32").reshape(4, 3)
    hard = p.prune_tensor(t, [1, 3], 0)
    np.testing.assert_array_equal(hard, t[[0, 2]])
    lazy = p.prune_tensor(t, [2], 1, lazy=True)
    assert lazy.shape == t.shape and np.all(lazy[:, 2] == 0)


# ---------------------------------------------------------------------------
# prune-then-finetune on a real Program
# ---------------------------------------------------------------------------


def _mlp_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        x = fluid.data(name="x", shape=[16, 8], dtype="float32")
        y = fluid.data(name="y", shape=[16, 1], dtype="float32")
        h = fluid.layers.fc(x, size=32, act="relu",
                            param_attr=fluid.ParamAttr(name="fc1_w"),
                            bias_attr=fluid.ParamAttr(name="fc1_b"))
        pred = fluid.layers.fc(h, size=1,
                               param_attr=fluid.ParamAttr(name="fc2_w"),
                               bias_attr=fluid.ParamAttr(name="fc2_b"))
        loss = fluid.layers.reduce_mean(
            fluid.layers.square(fluid.layers.elementwise_sub(pred, y)))
        fluid.optimizer.SGD(0.05).minimize(loss)
    return main, startup, loss


def _toy_data(n=16):
    rng = np.random.RandomState(0)
    x = rng.randn(n, 8).astype("float32")
    w = rng.randn(8, 1).astype("float32")
    return x, (x @ w).astype("float32")


def test_prune_then_finetune_recovers():
    main, startup, loss = _mlp_program()
    x, y = _toy_data()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        for _ in range(80):
            (l,) = exe.run(main, feed={"x": x, "y": y},
                           fetch_list=[loss])
        trained = float(np.asarray(l))

        # uniform 50% structured prune of the hidden layer
        UniformPruneStrategy(target_ratio=0.5,
                             params=["fc1_w"]).apply(main, scope)
        w1 = np.asarray(scope.find_var("fc1_w").raw().array)
        w2 = np.asarray(scope.find_var("fc2_w").raw().array)
        b1 = np.asarray(scope.find_var("fc1_b").raw().array)
        assert w1.shape == (8, 16)      # out channels halved
        assert b1.shape[-1] == 16       # bias followed
        assert w2.shape == (16, 1)      # consumer in-dim followed

        (l,) = exe.run(main, feed={"x": x, "y": y}, fetch_list=[loss])
        pruned_loss = float(np.asarray(l))
        for _ in range(120):
            (l,) = exe.run(main, feed={"x": x, "y": y},
                           fetch_list=[loss])
        finetuned = float(np.asarray(l))
    assert np.isfinite(pruned_loss)
    # finetune must recover most of the damage
    assert finetuned < max(pruned_loss * 0.5, trained * 3), (
        trained, pruned_loss, finetuned)


def test_prune_conv_bn_chain():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        img = fluid.data(name="img", shape=[2, 3, 8, 8],
                         dtype="float32")
        c1 = fluid.layers.conv2d(
            img, num_filters=8, filter_size=3, padding=1,
            param_attr=fluid.ParamAttr(name="c1_w"))
        bn = fluid.layers.batch_norm(c1)
        c2 = fluid.layers.conv2d(
            bn, num_filters=4, filter_size=3, padding=1,
            param_attr=fluid.ParamAttr(name="c2_w"))
        out = fluid.layers.reduce_mean(c2)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        prune_parameter(main, scope, "c1_w", 0.25)
        assert np.asarray(
            scope.find_var("c1_w").raw().array).shape == (6, 3, 3, 3)
        assert np.asarray(
            scope.find_var("c2_w").raw().array).shape == (4, 6, 3, 3)
        (o,) = exe.run(main, feed={
            "img": np.random.RandomState(0).rand(
                2, 3, 8, 8).astype("float32")}, fetch_list=[out])
    assert np.isfinite(np.asarray(o)).all()


def test_sensitivity_ranks_useless_layer_lower():
    """A branch multiplied by ~0 must measure less sensitive than the
    load-bearing branch, and the greedy plan prunes it harder."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        x = fluid.data(name="x", shape=[16, 8], dtype="float32")
        y = fluid.data(name="y", shape=[16, 1], dtype="float32")
        h_good = fluid.layers.fc(
            x, size=16, act="relu",
            param_attr=fluid.ParamAttr(name="good_w"))
        h_dead = fluid.layers.scale(fluid.layers.fc(
            x, size=16, act="relu",
            param_attr=fluid.ParamAttr(name="dead_w")), scale=1e-4)
        pred = fluid.layers.fc(
            fluid.layers.concat([h_good, h_dead], axis=1), size=1)
        loss = fluid.layers.reduce_mean(
            fluid.layers.square(fluid.layers.elementwise_sub(pred, y)))
        fluid.optimizer.SGD(0.05).minimize(loss)
    x_np, y_np = _toy_data()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        for _ in range(60):
            exe.run(main, feed={"x": x_np, "y": y_np},
                    fetch_list=[loss])

        def eval_fn(prog, sc):
            (l,) = exe.run(prog, feed={"x": x_np, "y": y_np},
                           fetch_list=[loss])
            return -float(np.asarray(l))   # higher is better

        sens = compute_sensitivities(main, scope, eval_fn,
                                     ["good_w", "dead_w"],
                                     ratios=(0.5,))
    assert sens["dead_w"][0.5] < sens["good_w"][0.5] + 1e-6, sens


# ---------------------------------------------------------------------------
# distillation
# ---------------------------------------------------------------------------


def _train_teacher(x, y, seed=None):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        xin = fluid.data(name="x", shape=[32, 4], dtype="float32")
        yin = fluid.data(name="y", shape=[32, 1], dtype="float32")
        h = fluid.layers.fc(xin, size=32, act="relu")
        pred = fluid.layers.fc(h, size=1)
        loss = fluid.layers.reduce_mean(fluid.layers.square(
            fluid.layers.elementwise_sub(pred, yin)))
        fluid.optimizer.AdamOptimizer(1e-2).minimize(loss)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        if seed is not None:
            exe._core.rng.seed = seed
            exe._core.rng.step = 0
        exe.run(startup)
        for _ in range(150):
            exe.run(main, feed={"x": x, "y": y}, fetch_list=[loss])
    # inference-only teacher program
    infer, istart = fluid.Program(), fluid.Program()
    with fluid.program_guard(infer, istart), fluid.unique_name.guard():
        xin = fluid.data(name="x", shape=[32, 4], dtype="float32")
        h = fluid.layers.fc(xin, size=32, act="relu")
        pred = fluid.layers.fc(h, size=1)
    return infer, scope, pred.name


def test_l2_distillation_pulls_student_to_teacher():
    """The distiller's contract: the merged-teacher L2 term pulls the
    student onto the TEACHER's function. The teacher is deliberately
    trained on y+1 so "near the teacher" and "near the labels" are a
    full unit apart — the margin cannot be noise."""
    rng = np.random.RandomState(1)
    x = rng.randn(32, 4).astype("float32")
    y = np.tanh(x @ rng.randn(4, 1)).astype("float32")
    teacher_prog, teacher_scope, t_pred = _train_teacher(
        x, (y + 1.0).astype("float32"))

    def student(with_teacher):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup), \
                fluid.unique_name.guard():
            xin = fluid.data(name="x", shape=[32, 4], dtype="float32")
            yin = fluid.data(name="y", shape=[32, 1], dtype="float32")
            h = fluid.layers.fc(xin, size=8, act="relu")
            pred = fluid.layers.fc(h, size=1)
            student_loss = fluid.layers.reduce_mean(fluid.layers.square(
                fluid.layers.elementwise_sub(pred, yin)))
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            np.random.seed(7)
            with fluid.program_guard(main, startup):
                if with_teacher:
                    merge_programs(main, teacher_prog, scope,
                                   teacher_scope=teacher_scope,
                                   feed_map={"x": "x"})
                    # distill-only objective: the pass under test
                    loss = L2Distiller(
                        pred.name, t_pred,
                        distillation_loss_weight=1.0).distiller_loss(
                        main)
                else:
                    loss = student_loss
                fluid.optimizer.AdamOptimizer(5e-3).minimize(loss)
            exe.run(startup)
            l0 = float(np.asarray(exe.run(
                main, feed={"x": x, "y": y}, fetch_list=[loss])[0]))
            for _ in range(120):
                (l,) = exe.run(main, feed={"x": x, "y": y},
                               fetch_list=[loss])
            l1 = float(np.asarray(l))
            (out,) = exe.run(main, feed={"x": x, "y": y},
                             fetch_list=[pred.name])
        return np.asarray(out), l0, l1

    # teacher outputs (the distillation target)
    t_exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(teacher_scope):
        (t_out,) = t_exe.run(teacher_prog, feed={"x": x},
                             fetch_list=[t_pred])
    t_out = np.asarray(t_out)

    out_d, l0_d, l1_d = student(True)
    out_s, _, _ = student(False)
    assert l1_d < l0_d, "distillation loss must decrease"
    dist_d = float(np.mean((out_d - t_out) ** 2))
    dist_s = float(np.mean((out_s - t_out) ** 2))
    # scratch lands on y (a full unit from the teacher); distilled
    # must land on the teacher
    assert dist_s > 0.3, dist_s
    assert dist_d < 0.1, dist_d
    assert dist_d < dist_s * 0.3, (dist_d, dist_s)


def test_soft_label_distiller_builds_and_trains():
    rng = np.random.RandomState(2)
    x = rng.randn(32, 4).astype("float32")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        xin = fluid.data(name="x", shape=[32, 4], dtype="float32")
        s_logits = fluid.layers.fc(xin, size=5, name="stu")
        t_logits = fluid.layers.fc(xin, size=5, name="tea")
        t_logits.stop_gradient = True
        loss = SoftLabelDistiller(
            s_logits.name, t_logits.name, student_temperature=1.0,
            teacher_temperature=2.0).distiller_loss(main)
        fluid.optimizer.SGD(0.1).minimize(loss)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        l0 = float(np.asarray(exe.run(main, feed={"x": x},
                                      fetch_list=[loss])[0]))
        for _ in range(40):
            (l,) = exe.run(main, feed={"x": x}, fetch_list=[loss])
    assert float(np.asarray(l)) < l0


def test_fsp_matrix_matches_numpy():
    rng = np.random.RandomState(3)
    a = rng.randn(2, 3, 4, 4).astype("float32")
    b = rng.randn(2, 5, 4, 4).astype("float32")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        av = fluid.data(name="a", shape=[2, 3, 4, 4], dtype="float32")
        bv = fluid.data(name="b", shape=[2, 5, 4, 4], dtype="float32")
        f = fsp_matrix(av, bv)
    exe = fluid.Executor(fluid.CPUPlace())
    (got,) = exe.run(main, feed={"a": a, "b": b}, fetch_list=[f])
    ref = np.einsum("nchw,ndhw->ncd", a, b) / 16.0
    np.testing.assert_allclose(np.asarray(got), ref, rtol=1e-5,
                               atol=1e-6)


# ---------------------------------------------------------------------------
# SA controller / NAS
# ---------------------------------------------------------------------------


def test_sa_controller_converges():
    target = [3, 1, 4, 1]
    ctl = SAController(range_table=[8, 8, 8, 8], seed=0,
                       init_temperature=10.0, reduce_rate=0.9)
    ctl.reset([8, 8, 8, 8], init_tokens=[0, 0, 0, 0])
    best, reward = ctl.search(
        lambda t: -sum((a - b) ** 2 for a, b in zip(t, target)),
        iterations=400)
    assert reward == 0 and best == target, (best, reward)


def test_sanas_driver():
    class Space(SearchSpace):
        def init_tokens(self):
            return [0, 0]

        def range_table(self):
            return [6, 6]

    nas = SANAS(Space(), search_steps=200, seed=1,
                init_temperature=5.0)
    best, reward = nas.search(lambda t: -abs(t[0] - 5) - abs(t[1] - 2))
    assert best == [5, 2] and reward == 0


def test_sa_constraint_respected():
    ctl = SAController(range_table=[10, 10], seed=2)
    ctl.reset([10, 10], init_tokens=[1, 1],
              constrain_func=lambda t: sum(t) <= 8)
    for _ in range(50):
        t = ctl.next_tokens()
        assert sum(t) <= 8
        ctl.update(t, -abs(sum(t) - 8))


def test_prune_shrinks_optimizer_state():
    """Pruning must follow the optimizer accumulators (moment/velocity)
    or the first Adam finetune step shape-crashes (caught by the
    round-5 verify drive)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        x = fluid.data(name="x", shape=[16, 8], dtype="float32")
        y = fluid.data(name="y", shape=[16, 1], dtype="float32")
        h = fluid.layers.fc(x, size=32, act="relu",
                            param_attr=fluid.ParamAttr(name="aw"))
        pred = fluid.layers.fc(h, size=1)
        loss = fluid.layers.reduce_mean(fluid.layers.square(
            fluid.layers.elementwise_sub(pred, y)))
        fluid.optimizer.AdamOptimizer(1e-2).minimize(loss)
    x_np, y_np = _toy_data()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        for _ in range(30):
            exe.run(main, feed={"x": x_np, "y": y_np},
                    fetch_list=[loss])
        prune_parameter(main, scope, "aw", 0.5)
        m1 = np.asarray(scope.find_var("aw_moment1_0").raw().array)
        assert m1.shape == (8, 16), m1.shape
        for _ in range(30):   # finetune must not shape-crash
            (l,) = exe.run(main, feed={"x": x_np, "y": y_np},
                           fetch_list=[loss])
    assert np.isfinite(float(np.asarray(l)))


# ---------------------------------------------------------------------------
# Compressor orchestration (reference slim/core/compressor.py)
# ---------------------------------------------------------------------------


def test_compressor_prune_schedule():
    """Epoch 0 trains dense; epoch 1 prunes 50% then finetunes; the
    eval history shows the damage and the recovery."""
    from paddle_tpu.contrib.slim.core import (Compressor,
                                              PruneStrategySchedule)

    main, startup, loss = _mlp_program()
    x, y = _toy_data()

    def reader():
        for _ in range(60):
            yield {"x": x, "y": y}

    eval_progs = {}

    def eval_func(prog, scope):
        # a PURE measurement: the optimizer ops must not run (clone
        # keyed by program identity — pruning bumps versions)
        key = id(prog)
        if key not in eval_progs:
            eval_progs[key] = prog.clone(for_test=True)
        (l,) = fluid.Executor(fluid.CPUPlace()).run(
            eval_progs[key], feed={"x": x, "y": y}, fetch_list=[loss])
        return -float(np.asarray(l))

    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        fluid.Executor(fluid.CPUPlace()).run(startup)
    comp = Compressor(fluid.CPUPlace(), scope, main, startup, loss,
                      reader, epoch=3,
                      strategies=[PruneStrategySchedule(
                          UniformPruneStrategy(target_ratio=0.5,
                                               params=["fc1_w"]),
                          start_epoch=1)],
                      eval_func=eval_func)
    comp.run()
    w1 = np.asarray(scope.find_var("fc1_w").raw().array)
    assert w1.shape == (8, 16)          # pruned at epoch 1
    evals = dict(comp.eval_history)
    assert evals[2] >= evals[1] - 1e-3  # finetune recovers
    assert len(evals) == 3


def test_compressor_distillation_schedule():
    """Distill epochs minimize the merged teacher loss; the student
    lands near the teacher's (deliberately shifted) function."""
    from paddle_tpu.contrib.slim.core import (
        Compressor, DistillationStrategySchedule)

    rng = np.random.RandomState(1)
    x = rng.randn(32, 4).astype("float32")
    y = np.tanh(x @ rng.randn(4, 1)).astype("float32")
    # the executor RNG seeds itself from the GLOBAL numpy RNG
    # (executor_core.py) when unpinned, so teacher and student inits
    # vary per run — and the 80 distill steps leave a landing margin
    # (measured 0.05..0.15) that straddles the 0.1 bar on unlucky
    # draws. Pin both inits; the schedule itself stays the subject.
    teacher_prog, teacher_scope, t_pred = _train_teacher(
        x, (y + 1.0).astype("float32"), seed=90)
    with fluid.scope_guard(teacher_scope):
        (t_out,) = fluid.Executor(fluid.CPUPlace()).run(
            teacher_prog, feed={"x": x}, fetch_list=[t_pred])
    t_out = np.asarray(t_out)

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        xin = fluid.data(name="x", shape=[32, 4], dtype="float32")
        yin = fluid.data(name="y", shape=[32, 1], dtype="float32")
        h = fluid.layers.fc(xin, size=8, act="relu")
        pred = fluid.layers.fc(h, size=1)
        student_loss = fluid.layers.reduce_mean(fluid.layers.square(
            fluid.layers.elementwise_sub(pred, yin)))
        fluid.optimizer.AdamOptimizer(5e-3).minimize(student_loss)

    def reader():
        for _ in range(40):
            yield {"x": x, "y": y}

    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe._core.rng.seed = 91
        exe._core.rng.step = 0
        exe.run(startup)
    strat = DistillationStrategySchedule(
        L2Distiller(pred.name, t_pred), teacher_prog, teacher_scope,
        fluid.optimizer.AdamOptimizer(5e-3), start_epoch=0,
        end_epoch=2, feed_map={"x": "x"})
    comp = Compressor(fluid.CPUPlace(), scope, main, startup,
                      student_loss, reader, epoch=2,
                      strategies=[strat])
    comp.run()
    with fluid.scope_guard(scope):
        (out,) = fluid.Executor(fluid.CPUPlace()).run(
            main, feed={"x": x, "y": y}, fetch_list=[pred])
    dist = float(np.mean((np.asarray(out) - t_out) ** 2))
    assert dist < 0.1, dist   # landed on the (shifted) teacher
