"""Auto-generated unary layer wrappers.

Parity: /root/reference/python/paddle/fluid/layers/ops.py, which generates
these from OpProtos via layer_function_generator; here they are generated
from the registry the same way.
"""
from __future__ import annotations

from ..layer_helper import LayerHelper

_UNARY = [
    "exp", "tanh", "sqrt", "rsqrt", "abs", "ceil", "floor", "cos", "sin",
    "tan", "acos", "asin", "atan", "sinh", "cosh", "round", "reciprocal",
    "square", "softplus", "softsign", "log", "log1p", "sigmoid", "logsigmoid",
    "erf", "gelu", "sign", "softshrink_placeholder",
]

__all__ = [n for n in _UNARY if not n.endswith("_placeholder")] + [
    "scale", "pow", "stanh", "hard_shrink", "soft_shrink",
    "thresholded_relu", "cumsum", "increment",
]


def _make_unary(op_type):
    def layer(x, name=None):
        helper = LayerHelper(op_type, input=x, name=name)
        out = helper.create_variable_for_type_inference(x.dtype)
        helper.append_op(op_type, inputs={"X": [x]}, outputs={"Out": [out]})
        return out

    layer.__name__ = op_type
    layer.__doc__ = "Elementwise %s (see paddle_tpu/ops/activation_ops.py)" % op_type
    return layer


for _name in _UNARY:
    if _name.endswith("_placeholder"):
        continue
    globals()[_name] = _make_unary(_name)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    helper = LayerHelper("scale", input=x, act=act, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "scale",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"scale": float(scale), "bias": float(bias),
               "bias_after_scale": bias_after_scale},
    )
    return helper.append_activation(out)


def pow(x, factor=1.0, name=None):
    helper = LayerHelper("pow", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("pow", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"factor": float(factor)})
    return out


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    helper = LayerHelper("stanh", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("stanh", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"scale_a": scale_a, "scale_b": scale_b})
    return out


def hard_shrink(x, threshold=0.5):
    helper = LayerHelper("hard_shrink", input=x)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("hard_shrink", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"threshold": threshold})
    return out


def soft_shrink(x, threshold=0.5):
    helper = LayerHelper("soft_shrink", input=x)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("soft_shrink", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"lambda": threshold})
    return out


def thresholded_relu(x, threshold=1.0):
    helper = LayerHelper("thresholded_relu", input=x)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("thresholded_relu", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"threshold": threshold})
    return out


def cumsum(x, axis=-1, exclusive=False, reverse=False, name=None):
    helper = LayerHelper("cumsum", input=x, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("cumsum", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"axis": axis, "exclusive": exclusive,
                            "reverse": reverse})
    return out


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment", input=x)
    out = x if in_place else helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("increment", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"step": float(value)})
    return out
