"""Native data-feed pipeline + async host->device staging.

Two halves of "the input pipeline never serializes with the device":

- ctypes binding for the C++ multi-slot reader (csrc/data_feed.cc):
  builds the shared library on first use (g++, baked into the image)
  and falls back cleanly (load() returns None) when no toolchain is
  available so the Python feed path takes over;
- ``AsyncDeviceFeeder``: a bounded double-buffer that stages the NEXT
  step's feed dict onto the device from a background thread while the
  device computes the current step. The compiled executor passes
  jax.Array feeds straight through (compiler_engine feed staging), so
  a feeder-supplied batch costs the step's critical path only the
  queue pop — ``feed.wait_ms`` measures exactly the stall that
  remains, which is the number ``PADDLE_TPU_ASYNC_FEED`` exists to
  drive to ~0.
"""
from __future__ import annotations

import ctypes
import os
import queue
import subprocess
import threading
import time

import numpy as np


# fast path for the gate-4 disabled-path budget: probe os.environ's
# backing dict directly (the _Environ mapping's encodekey + dispatch
# costs ~1us under load — right at the budget). Same recipe, same
# monkeypatch-safety argument, as analysis.verify_enabled.
try:
    _ENV_DATA = os.environ._data
    _ENV_KEY = os.environ.encodekey("PADDLE_TPU_ASYNC_FEED")
except Exception:  # non-CPython / exotic platform
    _ENV_DATA = None
    _ENV_KEY = None


def async_feed_enabled() -> bool:
    """``PADDLE_TPU_ASYNC_FEED``: opt-in double-buffered host feed
    (default off — one dict probe, gate-4 disabled-path budget)."""
    if _ENV_DATA is not None:
        raw = _ENV_DATA.get(_ENV_KEY)
    else:
        raw = os.environ.get("PADDLE_TPU_ASYNC_FEED")
    if not raw:
        return False
    if isinstance(raw, bytes):
        raw = raw.decode("utf-8", "ignore")
    return raw.strip().lower() in ("1", "true", "yes", "on")


class AsyncDeviceFeeder:
    """Double-buffered host->device feed staging.

    Wraps an iterator of ``{name: np.ndarray}`` batches; a background
    thread keeps up to ``depth`` batches staged on ``device`` (via
    jax.device_put — async dispatch, so the transfer itself also
    overlaps the thread's next parse). Iterating yields dicts of
    jax.Arrays ready to feed ``Executor.run``; the consumer-side stall
    is recorded as ``feed.wait_ms`` and the per-batch staging cost as
    ``feed.stage_ms`` — the before/after pair for the async-feed win.

    ``close()`` (or exhaustion) joins the thread; the feeder is also a
    context manager. A ``depth`` of 2 is the classic double buffer:
    one batch in flight to the device while one is being consumed.
    """

    _DONE = object()

    def __init__(self, batches, depth: int = 2, device=None):
        if depth < 1:
            raise ValueError("AsyncDeviceFeeder depth must be >= 1")
        self._q: "queue.Queue" = queue.Queue(maxsize=depth)
        self._device = device
        self._err = None
        self._closed = False
        self._thread = threading.Thread(
            target=self._pump, args=(iter(batches),), daemon=True)
        self._thread.start()

    def _stage(self, batch):
        import jax

        t0 = time.perf_counter()
        staged = {k: jax.device_put(v, self._device)
                  for k, v in batch.items()}
        from .. import observability as _obs

        if _obs.enabled():
            _obs.observe("feed.stage_ms",
                         (time.perf_counter() - t0) * 1e3)
        return staged

    def _put(self, item) -> bool:
        """Bounded put that re-checks the close flag: a close() racing
        a full queue must never strand this thread on a blocking put
        (at depth=1 the drain in close() and an in-flight put can
        refill the single slot — the classic shutdown deadlock)."""
        while not self._closed:
            try:
                self._q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _pump(self, it):
        try:
            for batch in it:
                if self._closed or not self._put(self._stage(batch)):
                    return
        except Exception as e:  # surfaced to the consumer on next()
            self._err = e
        finally:
            self._put(self._DONE)

    def __iter__(self):
        return self

    def __next__(self):
        t0 = time.perf_counter()
        item = self._q.get()
        from .. import observability as _obs

        if _obs.enabled():
            _obs.observe("feed.wait_ms",
                         (time.perf_counter() - t0) * 1e3)
        if item is self._DONE:
            if self._err is not None:
                err, self._err = self._err, None
                raise err
            raise StopIteration
        return item

    def close(self):
        self._closed = True
        # drain so the pump thread's bounded put unblocks promptly
        # (it also re-checks _closed itself, so even a refilled queue
        # cannot strand it)
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

_lock = threading.Lock()
_lib = None
_tried = False

_CSRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "csrc")
_SRC = os.path.join(_CSRC, "data_feed.cc")
_SO = os.path.join(_CSRC, "libptfeed.so")


def load():
    """The loaded library, building it if needed; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO) or (
                os.path.exists(_SRC)
                and os.path.getmtime(_SRC) > os.path.getmtime(_SO)):
            if not os.path.exists(_SRC):
                return None
            try:
                subprocess.run(
                    ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
                     _SRC, "-o", _SO, "-pthread"],
                    check=True, capture_output=True, timeout=120)
            except Exception:
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        lib.ptfeed_create.restype = ctypes.c_void_p
        lib.ptfeed_create.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_int,
            ctypes.POINTER(ctypes.c_int), ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int]
        lib.ptfeed_next.restype = ctypes.c_int64
        lib.ptfeed_next.argtypes = [ctypes.c_void_p]
        lib.ptfeed_slot_size.restype = ctypes.c_int64
        lib.ptfeed_slot_size.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.ptfeed_slot_fvals.restype = ctypes.POINTER(ctypes.c_float)
        lib.ptfeed_slot_fvals.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.ptfeed_slot_ivals.restype = ctypes.POINTER(ctypes.c_int64)
        lib.ptfeed_slot_ivals.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.ptfeed_slot_offsets.restype = ctypes.POINTER(ctypes.c_int64)
        lib.ptfeed_slot_offsets.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.ptfeed_slot_num_offsets.restype = ctypes.c_int64
        lib.ptfeed_slot_num_offsets.argtypes = [ctypes.c_void_p,
                                                ctypes.c_int]
        lib.ptfeed_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


class NativeMultiSlotFeed:
    """Iterates (slot arrays, slot lod offsets) batches parsed by the
    C++ reader threads. slot_types: 'float' | 'int64' per slot."""

    def __init__(self, filelist, slot_types, batch_size, num_threads=2,
                 queue_capacity=16):
        lib = load()
        if lib is None:
            raise RuntimeError("native feed library unavailable")
        self._lib = lib
        self._types = [0 if t in ("float", "float32") else 1
                       for t in slot_types]
        files = (ctypes.c_char_p * len(filelist))(
            *[f.encode() for f in filelist])
        types = (ctypes.c_int * len(self._types))(*self._types)
        self._h = lib.ptfeed_create(files, len(filelist), types,
                                    len(self._types), batch_size,
                                    num_threads, queue_capacity)
        self._closed = False

    def __iter__(self):
        return self

    def __next__(self):
        if self._closed:
            raise StopIteration
        n = self._lib.ptfeed_next(self._h)
        if n == 0:
            raise StopIteration
        slots = []
        for s in range(len(self._types)):
            size = self._lib.ptfeed_slot_size(self._h, s)
            noff = self._lib.ptfeed_slot_num_offsets(self._h, s)
            offs = np.ctypeslib.as_array(
                self._lib.ptfeed_slot_offsets(self._h, s),
                shape=(noff,)).copy()
            if self._types[s] == 0:
                vals = np.ctypeslib.as_array(
                    self._lib.ptfeed_slot_fvals(self._h, s),
                    shape=(size,)).copy()
            else:
                vals = np.ctypeslib.as_array(
                    self._lib.ptfeed_slot_ivals(self._h, s),
                    shape=(size,)).copy()
            slots.append((vals, offs))
        return slots

    def close(self):
        if not self._closed and self._h:
            self._lib.ptfeed_destroy(self._h)
            self._closed = True

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
