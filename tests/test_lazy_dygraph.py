"""Lazy (queued) dygraph dispatch vs eager parity.

The contract (dygraph/lazy.py): with ``guard(lazy=True)`` every eager
op queues onto a LazyEngine; a flush compiles the queued graph into one
jitted call, cached by structure, so steady-state training is ONE
device dispatch per step — while numerics match the eager tracer
exactly (same op fns, same tape-walk backward).
"""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.dygraph import Linear, to_variable


def _train(lazy, iters=5, opt_name="sgd", read_mid=False):
    with fluid.dygraph.guard(lazy=lazy):
        np.random.seed(0)
        fluid.default_startup_program().random_seed = 7
        l1 = Linear(16, 32, act="relu")
        l2 = Linear(32, 4)
        params = l1.parameters() + l2.parameters()
        if opt_name == "sgd":
            opt = fluid.optimizer.SGDOptimizer(0.1, parameter_list=params)
        else:
            opt = fluid.optimizer.AdamOptimizer(1e-2,
                                                parameter_list=params)
        rng = np.random.RandomState(1)
        x = rng.rand(8, 16).astype("float32")
        y = rng.randint(0, 4, (8, 1)).astype("int64")
        losses = []
        for i in range(iters):
            h = l1(to_variable(x))
            if read_mid:
                # host read mid-step: forces a partial flush; the rest
                # of the step must still work (tape-held activations
                # materialize)
                assert np.isfinite(h.numpy()).all()
            logits = l2(h)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(
                    logits, to_variable(y)))
            loss.backward()
            opt.minimize(loss, parameter_list=params)
            for p in params:
                p.clear_gradient()
            losses.append(float(loss.numpy()))
        return losses, [np.asarray(p.numpy()) for p in params]


@pytest.mark.parametrize("opt_name", ["sgd", "adam"])
def test_lazy_matches_eager(opt_name):
    le, pe = _train(False, opt_name=opt_name)
    ll, pl = _train(True, opt_name=opt_name)
    np.testing.assert_allclose(le, ll, rtol=1e-5, atol=1e-6)
    for a, b in zip(pe, pl):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_midstep_host_read_partial_flush():
    le, _ = _train(False, read_mid=True)
    ll, _ = _train(True, read_mid=True)
    np.testing.assert_allclose(le, ll, rtol=1e-5, atol=1e-6)


def test_steady_state_is_one_compile():
    """After the first step, later steps must HIT the structure-keyed
    jit cache (that cache hit is the whole point: 1 dispatch/step)."""
    with fluid.dygraph.guard(lazy=True):
        l1 = Linear(8, 8)
        params = l1.parameters()
        opt = fluid.optimizer.SGDOptimizer(0.1, parameter_list=params)
        tracer = fluid.framework._dygraph_tracer()
        rng = np.random.RandomState(0)
        x = rng.rand(4, 8).astype("float32")
        for i in range(4):
            loss = fluid.layers.mean(l1(to_variable(x)))
            loss.backward()
            opt.minimize(loss, parameter_list=params)
            for p in params:
                p.clear_gradient()
            float(loss.numpy())
        n_graphs = len(tracer.lazy_engine._jit_cache)
        assert n_graphs <= 2, (
            "expected steady-state cache hits, got %d distinct graphs"
            % n_graphs)


def test_gradient_read_forces_flush():
    with fluid.dygraph.guard(lazy=True):
        l1 = Linear(8, 4)
        params = l1.parameters()
        x = to_variable(np.ones((2, 8), dtype="float32"))
        loss = fluid.layers.mean(l1(x))
        loss.backward()
        g = params[0].gradient()
        assert g is not None and g.shape == (8, 4)
        assert np.isfinite(g).all()


def test_dropout_rng_varies_per_step():
    """RNG seeds are external inputs: masks must vary per step WITHOUT
    recompiling (cache stays hot)."""
    with fluid.dygraph.guard(lazy=True):
        tracer = fluid.framework._dygraph_tracer()
        x = to_variable(np.ones((4, 64), dtype="float32"))
        outs = []
        for _ in range(3):
            d = fluid.layers.dropout(x, dropout_prob=0.5)
            outs.append(d.numpy())
        assert not np.allclose(outs[0], outs[1])
        assert len(tracer.lazy_engine._jit_cache) <= 1


def test_lazy_shapes_without_flush():
    """Shape/dtype reads must not force a flush."""
    with fluid.dygraph.guard(lazy=True):
        tracer = fluid.framework._dygraph_tracer()
        x = to_variable(np.ones((4, 8), dtype="float32"))
        y = fluid.layers.relu(x)
        assert y.shape == (4, 8)
        assert y.dtype in ("float32",)
        assert len(tracer.lazy_engine.nodes) == 1  # still queued
        assert np.allclose(y.numpy(), 1.0)          # forces
        assert len(tracer.lazy_engine.nodes) == 0


def test_getitem_stays_queued():
    """x[...] must queue, not flush (review r5): slicing per step is a
    common pattern (CLS-token pooling) and a flush would defeat the
    one-dispatch-per-step contract."""
    with fluid.dygraph.guard(lazy=True):
        tracer = fluid.framework._dygraph_tracer()
        x = to_variable(np.arange(24, dtype="float32").reshape(4, 6))
        y = fluid.layers.relu(x)
        z = y[:, 0]
        assert len(tracer.lazy_engine.nodes) == 2  # relu + getitem
        np.testing.assert_allclose(z.numpy(),
                                   np.arange(24).reshape(4, 6)[:, 0])


def test_getitem_grads_under_lazy():
    from paddle_tpu.dygraph import Linear

    def run(lazy):
        with fluid.dygraph.guard(lazy=lazy):
            np.random.seed(0)
            l1 = Linear(6, 6)
            params = l1.parameters()
            x = to_variable(np.ones((4, 6), dtype="float32"))
            h = l1(x)
            loss = fluid.layers.mean(h[:, 0])
            loss.backward()
            return params[0].gradient()

    np.testing.assert_allclose(run(False), run(True), rtol=1e-6)


def test_dygraph_grad_api_under_lazy():
    """fluid.dygraph.grad() must work (first-order) under lazy mode
    and match eager (review r5: it crashed with NoneType call)."""
    from paddle_tpu.dygraph import Linear

    def run(lazy):
        with fluid.dygraph.guard(lazy=lazy):
            np.random.seed(0)
            l1 = Linear(5, 3)
            x = to_variable(np.ones((2, 5), dtype="float32"))
            x.stop_gradient = False
            y = fluid.layers.reduce_sum(l1(x))
            (g,) = fluid.dygraph.grad(y, x)
            return g.numpy()

    np.testing.assert_allclose(run(False), run(True), rtol=1e-6)


def test_dygraph_grad_create_graph_raises_clearly_under_lazy():
    with fluid.dygraph.guard(lazy=True):
        x = to_variable(np.ones((2, 2), dtype="float32"))
        x.stop_gradient = False
        y = fluid.layers.reduce_sum(x * x)
        with pytest.raises(NotImplementedError, match="lazy=False"):
            fluid.dygraph.grad(y, x, create_graph=True)


def test_max_nodes_valve_is_conservative():
    """Review r5: the safety-valve flush fires before owners attach;
    it must materialize everything (a precise-liveness flush there
    loses the in-flight node's outputs)."""
    from paddle_tpu.dygraph import Linear

    def run(cap):
        with fluid.dygraph.guard(lazy=True):
            np.random.seed(0)
            tracer = fluid.framework._dygraph_tracer()
            if cap:
                tracer.lazy_engine.MAX_NODES = cap
            l1 = Linear(8, 8)
            params = l1.parameters()
            opt = fluid.optimizer.SGDOptimizer(0.1,
                                               parameter_list=params)
            x = to_variable(np.ones((2, 8), dtype="float32"))
            for _ in range(2):
                loss = fluid.layers.mean(l1(l1(l1(x))))
                loss.backward()
                opt.minimize(loss, parameter_list=params)
                for p in params:
                    p.clear_gradient()
            return float(loss.numpy())

    ref = run(None)
    # valve fires many times mid-step (including mid-backward)
    assert np.allclose(run(3), ref, rtol=1e-5)
    assert np.allclose(run(7), ref, rtol=1e-5)
