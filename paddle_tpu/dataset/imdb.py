"""IMDB sentiment reader creators (reference
python/paddle/dataset/imdb.py).

Sample contract: (list of word ids, label 0/1). ``word_idx`` maps word
-> id with '<unk>' as the last id, exactly like the reference
build_dict. Synthetic fallback: a small sentiment grammar over a fixed
vocabulary (positive/negative keyword mixtures), deterministic and
separable.
"""
from __future__ import annotations

import os
import re
import tarfile

import numpy as np

from .common import DATA_HOME

__all__ = ["build_dict", "train", "test", "word_dict"]

_POS = ["good", "great", "excellent", "wonderful", "best", "love",
        "superb", "amazing"]
_NEG = ["bad", "awful", "terrible", "worst", "boring", "hate", "poor",
        "dull"]
_FILL = ["movie", "film", "plot", "actor", "scene", "story", "the", "a",
         "it", "was", "very", "really"]


def _archive():
    p = os.path.join(DATA_HOME, "imdb", "aclImdb_v1.tar.gz")
    return p if os.path.exists(p) else None


def _tokenize(text):
    return re.sub(r"[^a-z0-9 ]", " ", text.lower()).split()


def _archive_docs(pattern):
    tar = _archive()
    assert tar is not None
    with tarfile.open(tar, mode="r") as f:
        for name in sorted(f.getnames()):
            if bool(pattern.match(name)):
                yield _tokenize(
                    f.extractfile(name).read().decode("utf-8",
                                                      errors="ignore"))


def _synthetic_docs(n, seed):
    rng = np.random.RandomState(seed)
    for _ in range(n):
        label = int(rng.randint(0, 2))
        keywords = _POS if label == 0 else _NEG
        words = []
        for _ in range(int(rng.randint(8, 20))):
            src = keywords if rng.rand() < 0.4 else _FILL
            words.append(src[rng.randint(0, len(src))])
        yield words, label


def build_dict(pattern=None, cutoff=0):
    """word -> id, '<unk>' last (reference imdb.py build_dict)."""
    from collections import Counter

    counts = Counter()
    if _archive() is not None and pattern is not None:
        for words in _archive_docs(pattern):
            counts.update(words)
    else:
        for words, _ in _synthetic_docs(2000, seed=20):
            counts.update(words)
    counts = {w: c for w, c in counts.items() if c > cutoff}
    ordered = sorted(counts.items(), key=lambda x: (-x[1], x[0]))
    word_idx = {w: i for i, (w, _) in enumerate(ordered)}
    word_idx["<unk>"] = len(word_idx)
    return word_idx


def word_dict():
    return build_dict(re.compile(r"aclImdb/((train)|(test))/((pos)|(neg))/.*\.txt$"))


def _reader_creator(word_idx, is_train, n, seed):
    unk = word_idx["<unk>"]

    def reader():
        tar = _archive()
        if tar is not None:
            sub = "train" if is_train else "test"
            for senti, label in (("pos", 0), ("neg", 1)):
                pat = re.compile(
                    r"aclImdb/%s/%s/.*\.txt$" % (sub, senti))
                for words in _archive_docs(pat):
                    yield [word_idx.get(w, unk) for w in words], label
        else:
            for words, label in _synthetic_docs(n, seed):
                yield [word_idx.get(w, unk) for w in words], label

    return reader


def train(word_idx):
    return _reader_creator(word_idx, True, 2000, seed=21)


def test(word_idx):
    return _reader_creator(word_idx, False, 400, seed=22)
