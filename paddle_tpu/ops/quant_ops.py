"""Fake-quantization op family (QAT + PTQ support).

Parity: /root/reference/paddle/fluid/operators/fake_quantize_op.cc
(ClipAndFakeQuantFunctor, FindAbsMaxFunctor, FindRangeAbsMaxFunctor,
FindMovingAverageAbsMaxFunctor) and fake_dequantize_op.cc; consumed by
contrib/slim/quantization/quantization_pass.py.

TPU-native gradient design: the reference registers identity grad
kernels per fake-quant op (straight-through estimator). Here each
forward is written as ``linear_part + stop_gradient(rounded -
linear_part)`` so the auto-VJP yields exactly the reference's STE
composite gradients — no custom grad kernels, and the whole QAT step
still compiles to one XLA program.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import In, Out, register_op


def _bnt(bits) -> float:
    return float((1 << (int(bits) - 1)) - 1)


def _ste_round(x):
    """round(x) with identity gradient."""
    return x + jax.lax.stop_gradient(jnp.round(x) - x)


def _quant_levels(x, scale, bits):
    """clip(round(x/scale*bnt)) in [-bnt, bnt], STE grads."""
    bnt = _bnt(bits)
    inv = bnt / jnp.maximum(scale, 1e-12)
    y = _ste_round(x * inv)
    return jnp.clip(y, -bnt, bnt)


@register_op("fake_quantize_abs_max",
             inputs=[In("X")],
             outputs=[Out("Out"), Out("OutScale", no_grad=True)],
             attrs={"bit_length": 8})
def _fake_quantize_abs_max(ins, attrs):
    x = ins["X"]
    scale = jax.lax.stop_gradient(jnp.max(jnp.abs(x)))
    return {"Out": _quant_levels(x, scale, attrs["bit_length"]),
            "OutScale": scale.reshape(1)}


@register_op("fake_channel_wise_quantize_abs_max",
             inputs=[In("X")],
             outputs=[Out("Out"), Out("OutScale", no_grad=True)],
             attrs={"bit_length": 8})
def _fake_channel_wise_quantize_abs_max(ins, attrs):
    """Per-output-channel (axis 0) scales — conv/mul weights."""
    x = ins["X"]
    flat = jnp.abs(x).reshape(x.shape[0], -1)
    scale = jax.lax.stop_gradient(flat.max(axis=1))
    shaped = scale.reshape((-1,) + (1,) * (x.ndim - 1))
    return {"Out": _quant_levels(x, shaped, attrs["bit_length"]),
            "OutScale": scale}


@register_op("fake_quantize_range_abs_max",
             inputs=[In("X"), In("InScale", no_grad=True),
                     In("Iter", dispensable=True, no_grad=True)],
             outputs=[Out("Out"), Out("OutScale", no_grad=True),
                      Out("OutScales", dispensable=True, no_grad=True)],
             attrs={"bit_length": 8, "window_size": 10000,
                    "is_test": False})
def _fake_quantize_range_abs_max(ins, attrs):
    """Training keeps a running max of batch scales (the reference's
    window-reset bookkeeping collapses to a running max under a traced
    step counter; deviation documented); test mode uses InScale."""
    x = ins["X"]
    in_scale = ins["InScale"].reshape(())
    if attrs.get("is_test", False):
        scale = in_scale
    else:
        cur = jnp.max(jnp.abs(x))
        scale = jnp.maximum(in_scale, cur)
    scale = jax.lax.stop_gradient(scale)
    return {"Out": _quant_levels(x, scale, attrs["bit_length"]),
            "OutScale": scale.reshape(1),
            "OutScales": scale.reshape(1)}


@register_op("fake_quantize_moving_average_abs_max",
             inputs=[In("X"), In("InScale", no_grad=True),
                     In("InAccum", dispensable=True, no_grad=True),
                     In("InState", dispensable=True, no_grad=True)],
             outputs=[Out("Out"), Out("OutScale", no_grad=True),
                      Out("OutAccum", dispensable=True, no_grad=True),
                      Out("OutState", dispensable=True, no_grad=True)],
             attrs={"bit_length": 8, "moving_rate": 0.9, "is_test": False})
def _fake_quantize_moving_average_abs_max(ins, attrs):
    """state = state*rate + 1; accum = accum*rate + max|x|;
    scale = accum/state (fake_quantize_op.cc
    FindMovingAverageAbsMaxFunctor)."""
    x = ins["X"]
    in_scale = ins["InScale"].reshape(())
    rate = attrs.get("moving_rate", 0.9)
    if attrs.get("is_test", False):
        scale = jax.lax.stop_gradient(in_scale)
        accum = ins.get("InAccum")
        state = ins.get("InState")
        out = {"Out": _quant_levels(x, scale, attrs["bit_length"]),
               "OutScale": scale.reshape(1)}
        if accum is not None:
            out["OutAccum"] = accum
        if state is not None:
            out["OutState"] = state
        return out
    accum = (ins.get("InAccum") if ins.get("InAccum") is not None
             else in_scale.reshape(1))
    state = (ins.get("InState") if ins.get("InState") is not None
             else jnp.ones((1,), x.dtype))
    cur = jnp.max(jnp.abs(x))
    new_state = state * rate + 1.0
    new_accum = accum * rate + cur
    scale = jax.lax.stop_gradient((new_accum / new_state).reshape(()))
    return {"Out": _quant_levels(x, scale, attrs["bit_length"]),
            "OutScale": scale.reshape(1),
            "OutAccum": jax.lax.stop_gradient(new_accum),
            "OutState": jax.lax.stop_gradient(new_state)}


@register_op("fake_quantize_dequantize_moving_average_abs_max",
             inputs=[In("X"), In("InScale", no_grad=True),
                     In("InAccum", dispensable=True, no_grad=True),
                     In("InState", dispensable=True, no_grad=True)],
             outputs=[Out("Out"), Out("OutScale", no_grad=True),
                      Out("OutAccum", dispensable=True, no_grad=True),
                      Out("OutState", dispensable=True, no_grad=True)],
             attrs={"bit_length": 8, "moving_rate": 0.9, "is_test": False})
def _fake_quantize_dequantize_moving_average_abs_max(ins, attrs):
    """Quant-dequant in one op (used on activations whose consumers want
    float): Out = round(x/s*bnt)*s/bnt with STE identity grads."""
    res = _fake_quantize_moving_average_abs_max(ins, attrs)
    bnt = _bnt(attrs["bit_length"])
    scale = res["OutScale"].reshape(())
    res["Out"] = res["Out"] * scale / bnt
    return res


@register_op("fake_dequantize_max_abs",
             inputs=[In("X"), In("Scale", no_grad=True)],
             outputs=[Out("Out")],
             attrs={"max_range": 127.0})
def _fake_dequantize_max_abs(ins, attrs):
    """Out = X * scale / max_range (fake_dequantize_op.cc)."""
    scale = ins["Scale"].reshape(())
    return {"Out": ins["X"] * scale / attrs["max_range"]}


@register_op("fake_channel_wise_dequantize_max_abs",
             inputs=[In("X"), In("Scales", duplicable=True, no_grad=True)],
             outputs=[Out("Out")],
             attrs={"quant_bits": [8, 8]})
def _fake_channel_wise_dequantize_max_abs(ins, attrs):
    """Out = X * prod(scales_i) / prod(bnt_i); first scale is
    per-channel (axis 0 for conv weights / axis -1 after mul)."""
    x = ins["X"]
    scales = ins["Scales"]
    bits = attrs.get("quant_bits", [8, 8])
    ch = scales[0]
    if ch.shape[0] == x.shape[0]:
        shaped = ch.reshape((-1,) + (1,) * (x.ndim - 1))
    else:
        shaped = ch.reshape((1,) * (x.ndim - 1) + (-1,))
    out = x * shaped / _bnt(bits[0])
    for extra, b in zip(scales[1:], bits[1:]):
        out = out * extra.reshape(()) / _bnt(b)
    return {"Out": out}


@register_op("moving_average_abs_max_scale",
             inputs=[In("X"), In("InAccum", dispensable=True, no_grad=True),
                     In("InState", dispensable=True, no_grad=True)],
             outputs=[Out("Out", dispensable=True),
                      Out("OutScale", no_grad=True),
                      Out("OutAccum", dispensable=True, no_grad=True),
                      Out("OutState", dispensable=True, no_grad=True)],
             attrs={"moving_rate": 0.9, "is_test": False})
def _moving_average_abs_max_scale(ins, attrs):
    """Scale observer only — passes X through untouched."""
    x = ins["X"]
    rate = attrs.get("moving_rate", 0.9)
    accum = (ins.get("InAccum") if ins.get("InAccum") is not None
             else jnp.zeros((1,), x.dtype))
    state = (ins.get("InState") if ins.get("InState") is not None
             else jnp.zeros((1,), x.dtype))
    if attrs.get("is_test", False):
        scale = jnp.where(state.reshape(()) > 0,
                          accum.reshape(()) / jnp.maximum(
                              state.reshape(()), 1e-12),
                          jnp.max(jnp.abs(x)))
        return {"Out": x, "OutScale": scale.reshape(1),
                "OutAccum": accum, "OutState": state}
    cur = jnp.max(jnp.abs(x))
    new_state = state * rate + 1.0
    new_accum = accum * rate + cur
    scale = new_accum / new_state
    return {"Out": x, "OutScale": scale.reshape(1),
            "OutAccum": new_accum, "OutState": new_state}
