// Standalone C++ training demo.
//
// Parity: /root/reference/paddle/fluid/train/demo/demo_trainer.cc — a
// C++ program that loads a program saved from Python and runs the
// train loop with no Python *script* in charge. Here the runtime under
// the loop is the embedded CPython + JAX/XLA stack (the TPU-native
// executor), driven entirely from C++: load program, feed batches,
// fetch the loss.
//
// Build:
//   g++ -O2 -std=c++17 train_demo.cc -o train_demo \
//       $(python3-config --includes --ldflags --embed)
// Run:
//   ./train_demo <saved_program_dir>
// where the dir contains a save_inference_model-style program whose
// feeds are x [B,4] float32 / y [B,1] float32 and that fetches a
// scalar loss var named in fetch targets, trained in-place by the
// program's optimizer ops (see tests/test_capi_demo.py for the saver).

#include <Python.h>

#include <cstdio>
#include <string>
#include <vector>

static int fail(const char *msg) {
  PyErr_Print();
  std::fprintf(stderr, "train_demo: %s\n", msg);
  return 1;
}

int main(int argc, char **argv) {
  if (argc < 2) {
    std::fprintf(stderr, "usage: %s <saved_program_dir>\n", argv[0]);
    return 2;
  }
  Py_InitializeEx(0);

  // Pass the path as an object attribute — never spliced into source
  // (a quote in the path must not become Python syntax).
  {
    PyObject *main_mod = PyImport_AddModule("__main__");
    PyObject *path = PyUnicode_DecodeFSDefault(argv[1]);
    if (!path || PyObject_SetAttrString(main_mod, "_dirname", path) != 0)
      return fail("could not set model dir");
    Py_DECREF(path);
  }

  // Drive the public API exactly as a user script would, but from C++.
  std::string bootstrap = R"PY(
import numpy as np
import paddle_tpu as fluid

_exe = fluid.Executor(fluid.CPUPlace())
_scope = fluid.Scope()
with fluid.scope_guard(_scope):
    _prog, _feeds, _fetches = fluid.io.load_inference_model(_dirname, _exe)

_rng = np.random.RandomState(0)
_W = _rng.randn(4, 1).astype("float32")

def train_steps(n):
    losses = []
    with fluid.scope_guard(_scope):
        for _ in range(n):
            xb = _rng.randn(16, 4).astype("float32")
            out, = _exe.run(_prog,
                            feed={"x": xb, "y": xb @ _W},
                            fetch_list=_fetches)
            losses.append(float(np.asarray(out).ravel()[0]))
    return losses[0], losses[-1]
)PY";

  if (PyRun_SimpleString(bootstrap.c_str()) != 0)
    return fail("bootstrap failed (is paddle_tpu importable?)");

  PyObject *main_mod = PyImport_AddModule("__main__");
  PyObject *fn = PyObject_GetAttrString(main_mod, "train_steps");
  if (!fn) return fail("train_steps missing");
  PyObject *res = PyObject_CallFunction(fn, "i", 60);
  if (!res) return fail("training failed");
  double first = PyFloat_AsDouble(PyTuple_GetItem(res, 0));
  double last = PyFloat_AsDouble(PyTuple_GetItem(res, 1));
  Py_DECREF(res);
  Py_DECREF(fn);
  std::printf("first_loss=%.6f last_loss=%.6f\n", first, last);
  int ok = last < first * 0.5 ? 0 : 3;
  if (ok != 0) std::fprintf(stderr, "train_demo: loss did not converge\n");
  Py_FinalizeEx();
  return ok;
}
