"""Steering drill: the self-driving runtime's CI gate (ISSUE 16).

One seeded, in-process run of the full sense → propose → canary →
decide loop, gated on the invariants that make "self-driving" safe to
ship:

1. SAMPLED CAPTURE — with ``PADDLE_TPU_SAMPLE_EVERY=2`` armed, a real
   executor job emits rolling ``*.profile.json`` reports on exactly
   every Nth step, and ``merge_job_dir`` surfaces them (plus the
   cross-rank drift block) in the merged ``metrics.json``.
2. DAEMON HYSTERESIS — the steering daemon, fed a scripted metric
   sequence, proposes exactly ONCE for a sustained breach: a single
   noisy poll does not trigger, an oscillating metric never
   accumulates, and the post-proposal cooldown prevents a replan
   storm while the breach persists.
3. CANARY DECISIONS — a PLANTED REGRESSION (a ladder that pads every
   batch to the max) ROLLS BACK, and a PLANTED IMPROVEMENT (the
   daemon's own quantile-ladder proposal) PROMOTES, both measured
   with the real serving padding math over one seeded request trace
   and compared by the shared ``observability/comparator.py``.
4. AUDIT CLOSURE — every decision is bit-audited: the plan digests in
   ``steering_audit.json``, the flight ring's ``steering.proposed`` /
   ``canary.*`` instants, the proposal artifact, and the PlanStore's
   active-plan pointer all agree; the number of active-plan installs
   equals the number of PROMOTED audit entries (zero un-audited plan
   switches); and the PlanStore structurally refuses a switch without
   its promotion entry.

Seeded and fast (~tens of seconds) — this is ci/check.sh's steering
gate, not a benchmark.

Usage:
    python tools/steering_drill.py [--seed 0]
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

_CHECKS = []


def _check(what: str, passed: bool, detail: str = "") -> bool:
    _CHECKS.append((what, bool(passed)))
    print("[steer] %s: %s%s" % ("PASS" if passed else "FAIL", what,
                                (" — " + detail) if detail else ""))
    return bool(passed)


# -- leg 1: sampled in-production capture -----------------------------------

def _small_program(fluid, batch=32, hidden=32):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        x = fluid.data(name="sx", shape=[batch, 16], dtype="float32")
        y = fluid.data(name="sy", shape=[batch, 1], dtype="int64")
        h = fluid.layers.fc(x, hidden, act="relu")
        pred = fluid.layers.fc(h, 10, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, y))
        fluid.optimizer.MomentumOptimizer(0.1, 0.9).minimize(loss)
    return main, startup, loss


def leg_sampled_capture(rng, workdir: str) -> None:
    metrics_dir = os.path.join(workdir, "capture")
    os.makedirs(metrics_dir, exist_ok=True)
    os.environ["PADDLE_TPU_METRICS_DIR"] = metrics_dir
    os.environ["PADDLE_TPU_SAMPLE_EVERY"] = "2"
    os.environ["PADDLE_TPU_SAMPLE_BUDGET_S"] = "20"

    import paddle_tpu as fluid
    from paddle_tpu import observability as obs
    from paddle_tpu.observability import capture
    from paddle_tpu.observability import distributed as odist
    from paddle_tpu.observability import flight

    obs.reset()
    obs.enable()
    flight.clear()
    capture._reset_for_tests()
    _check("capture: knob armed", capture.sampling_enabled()
           and capture.sample_every() == 2)

    main, startup, loss = _small_program(fluid)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)  # executor step 1 — not a sample multiple
        feed = {"sx": rng.random((32, 16)).astype("float32"),
                "sy": rng.integers(0, 10, (32, 1)).astype("int64")}
        for _ in range(4):  # steps 2..5 — samples fire on 2 and 4
            exe.run(main, feed=feed, fetch_list=[loss])

    n_samples = obs.counter_value("capture.samples", engine="executor")
    _check("capture: fired on every Nth executor step",
           n_samples == 2, "samples=%r (want 2 of 5 steps @ N=2)"
           % (n_samples,))
    _check("capture: zero capture errors",
           not obs.counter_value("capture.errors", engine="executor"))

    reports = glob.glob(os.path.join(metrics_dir, "*.profile.json"))
    ok = len(reports) == 1
    doc = {}
    if ok:
        with open(reports[0], "r", encoding="utf-8") as f:
            doc = json.load(f)
        ok = (doc.get("schema") == capture.SAMPLED_PROFILE_SCHEMA
              and doc.get("engine") == "executor"
              and doc.get("sample_every") == 2
              and doc.get("samples") == 2
              and isinstance((doc.get("profile") or {}).get("step_ms"),
                             (int, float))
              and len(doc.get("history") or []) == 2)
    _check("capture: rolling profile report on disk", ok,
           "files=%d samples=%r history=%d"
           % (len(reports), doc.get("samples"),
              len(doc.get("history") or [])))

    kinds = [k for _, k, _ in flight.events()]
    _check("capture: flight-recorded", kinds.count("capture.sampled") == 2)

    # the dump pipeline must surface the sampled reports + drift
    odist.dump_process()
    merged = odist.merge_job_dir(metrics_dir)
    with open(os.path.join(metrics_dir, "metrics.json"), "r",
              encoding="utf-8") as f:
        mdoc = json.load(f)
    sp = mdoc.get("sampled_profiles") or {}
    drift = mdoc.get("sampled_profile_drift") or {}
    _check("capture: merged metrics.json surfaces sampled profiles",
           len(sp) == 1 and "step_ms" in drift
           and isinstance(drift["step_ms"].get("spread"), (int, float)),
           "procs=%d drift_keys=%d" % (len(sp), len(drift)))
    del merged

    os.environ.pop("PADDLE_TPU_SAMPLE_EVERY", None)
    capture._reset_for_tests()
    _check("capture: disarms back to off", not capture.sampling_enabled())


# -- leg 2: daemon hysteresis (no replan storm) -----------------------------

def _write_metrics(metrics_dir: str, waste: float,
                   batches: int = 100) -> None:
    doc = {"counters_total": {
        "serving.batches": batches,
        "serving.padding_waste": waste * batches,
    }}
    with open(os.path.join(metrics_dir, "metrics.json"), "w",
              encoding="utf-8") as f:
        json.dump(doc, f)


def leg_daemon_hysteresis(rng, workdir: str):
    """Scripted waste-ratio sequence through a real daemon: exactly
    one proposal despite noise, oscillation, and a sustained breach
    under cooldown. Returns the proposal for the canary leg."""
    from paddle_tpu import observability as obs
    from paddle_tpu.observability import flight, steering
    from paddle_tpu.observability import steering_daemon as sdmod

    metrics_dir = os.path.join(workdir, "daemon")
    os.makedirs(metrics_dir, exist_ok=True)
    obs.enable()

    # bimodal seeded traffic: mostly small batches + a big mode the
    # power-of-two ladder straddles badly
    trace = np.concatenate([
        rng.integers(3, 5, 60), rng.integers(11, 14, 40)])
    rng.shuffle(trace)
    trace = [int(r) for r in trace]

    rule = sdmod.WatchRule(
        "serving_padding_waste",
        sdmod.counter_ratio("serving.padding_waste", "serving.batches",
                            min_den=8),
        direction=-1, threshold=0.25, floor=0.10,
        steerer="serving_ladder")
    daemon = sdmod.SteeringDaemon(
        metrics_dir, rules=[rule], hysteresis=2, cooldown=3,
        merge=False,
        context={"serving_ladder": {"max_batch_size": 16,
                                    "batch_rows": trace}})

    # (waste_ratio, want_proposal_after_this_poll)
    script = [
        (0.20, 0),  # poll 1: baseline
        (0.20, 0),  # poll 2: clean
        (0.55, 0),  # poll 3: breach #1 — hysteresis holds
        (0.20, 0),  # poll 4: clean — MUST reset the breach count
        (0.55, 0),  # poll 5: breach #1 again (not #2)
        (0.60, 1),  # poll 6: breach #2 — PROPOSE
        (0.60, 1),  # polls 7..9: breach persists, cooldown holds
        (0.60, 1),
        (0.60, 1),
        (0.60, 1),  # poll 10: cooldown over, but rebaselined — clean
    ]
    total = 0
    storm_free = True
    for waste, want in script:
        _write_metrics(metrics_dir, waste)
        total += len(daemon.poll_once())
        storm_free = storm_free and (total == want)
    _check("daemon: one proposal, no storm", storm_free and total == 1,
           "proposals=%d over %d polls" % (total, daemon.polls))

    prop = daemon.proposals[0] if daemon.proposals else None
    art_path = os.path.join(metrics_dir, "proposed-serving_ladder.json")
    art = None
    if os.path.exists(art_path):
        with open(art_path, "r", encoding="utf-8") as f:
            art = json.load(f)
    ok = (prop is not None and art is not None
          and art["schema"] == sdmod.PROPOSAL_SCHEMA
          and art["plan_digest"] == prop["plan_digest"]
          and art["metric"] == "serving_padding_waste"
          and tuple(art["plan"]) == tuple(prop["plan"])
          and art["plan"][-1] == 16)
    _check("daemon: proposal artifact matches in-memory proposal", ok)

    proposed_events = [f for _, k, f in flight.events()
                       if k == "steering.proposed"]
    ok = (len(proposed_events) == 1 and prop is not None
          and proposed_events[0]["plan_digest"] == prop["plan_digest"])
    _check("daemon: steering.proposed flight instant carries the "
           "digest", ok)
    _check("daemon: proposals counter", obs.counter_value(
        "steering.proposals", steerer="serving_ladder") == 1)

    # registry contract the daemon leans on
    try:
        steering.steer("definitely_not_registered", None)
        unknown_ok = False
    except KeyError:
        unknown_ok = True
    _check("daemon: unknown steerer is a KeyError", unknown_ok)
    return prop, trace


# -- leg 3: canary decisions + audit closure --------------------------------

def _measure_ladder(ladder, trace):
    """The real padding math over the seeded request trace: each batch
    lands in the smallest rung covering it (pick_bucket), waste is the
    padded fraction, throughput falls as padding rises."""
    from paddle_tpu.serving.batcher import pick_bucket

    padded = real = 0
    for rows in trace:
        b = pick_bucket(ladder, rows)
        padded += b
        real += rows
    waste = (padded - real) / float(padded)
    return {"extras": {"serving": {
        "serving_padding_waste_frac": waste,
        "rows_per_s": 1000.0 * (1.0 - waste),
        "serving_batch_size_mean": real / float(len(trace)),
    }}}


def leg_canary(proposal, trace, workdir: str) -> None:
    from paddle_tpu import observability as obs
    from paddle_tpu.observability import canary, flight, steering
    from paddle_tpu.serving.batcher import default_ladder

    cdir = os.path.join(workdir, "canary")
    os.makedirs(cdir, exist_ok=True)
    audit = canary.AuditTrail(cdir)
    store = canary.PlanStore(cdir, "serving_ladder")
    incumbent_ladder = default_ladder(16)
    incumbent = _measure_ladder(incumbent_ladder, trace)
    applied = {"plan": None}

    def apply_fn(plan):
        applied["plan"] = tuple(plan)

    # planted regression: a one-rung ladder pads EVERY batch to 16
    bad_plan = (16,)
    bad = canary.run_canary(
        {"plan": list(bad_plan),
         "plan_digest": steering.plan_digest(list(bad_plan)),
         "steerer": "serving_ladder", "metric": "planted_regression"},
        incumbent, lambda plan: _measure_ladder(tuple(plan), trace),
        apply_fn=apply_fn, rollback_fn=lambda plan: None,
        plan_store=store, audit=audit)
    _check("canary: planted regression ROLLS BACK",
           not bad.promoted and bad.decision == "rolled_back"
           and "serving_padding_waste_frac" in
           bad.comparison.regressed_metrics,
           "reason=%s regressed=%s" % (bad.reason,
                                       bad.comparison.regressed_metrics))
    _check("canary: rollback installed nothing", store.installs == 0
           and store.read() is None)

    # planted improvement: the daemon's own quantile-ladder proposal
    good = canary.run_canary(
        proposal, incumbent,
        lambda plan: _measure_ladder(tuple(plan), trace),
        apply_fn=apply_fn, plan_store=store, audit=audit,
        require_improvement="serving_padding_waste_frac",
        min_improvement=0.05)
    _check("canary: planted improvement PROMOTES",
           good.promoted and good.decision == "promoted"
           and applied["plan"] == tuple(proposal["plan"]),
           "reason=%s" % good.reason)

    # audit closure: trail <-> flight ring <-> active-plan pointer
    entries = audit.entries()
    ok = (len(entries) == 2
          and entries[0]["decision"] == "rolled_back"
          and entries[1]["decision"] == "promoted"
          and entries[0]["seq"] == 0 and entries[1]["seq"] == 1
          and entries[0]["plan_digest"] == steering.plan_digest(
              list(bad_plan))
          and entries[1]["plan_digest"] == proposal["plan_digest"]
          and all(e["schema"] == canary.AUDIT_SCHEMA for e in entries))
    _check("audit: both decisions on the trail, digests bit-exact", ok)

    fl = {k: f for _, k, f in flight.events()
          if k in ("canary.promoted", "canary.rolled_back")}
    ok = (fl.get("canary.rolled_back", {}).get("plan_digest")
          == entries[0]["plan_digest"] if len(entries) == 2 else False)
    ok = ok and (fl.get("canary.promoted", {}).get("plan_digest")
                 == entries[1]["plan_digest"])
    _check("audit: flight instants bit-match the trail", ok)

    active = store.read()
    promoted_entries = [e for e in entries
                        if e["decision"] == "promoted"]
    ok = (store.installs == len(promoted_entries) == 1
          and isinstance(active, dict)
          and active["plan_digest"] == proposal["plan_digest"]
          and active["audit_seq"] == promoted_entries[0]["seq"])
    _check("audit: installs == promoted entries (zero un-audited "
           "plan switches)", ok,
           "installs=%d promoted=%d" % (store.installs,
                                        len(promoted_entries)))

    # structural refusals: a plan switch cannot skip the audit trail
    try:
        store.install(list(proposal["plan"]),
                      {"decision": "rolled_back"})
        refused = False
    except ValueError:
        refused = True
    _check("audit: PlanStore refuses a non-promotion entry", refused)
    try:
        canary.run_canary(proposal, incumbent,
                          lambda plan: _measure_ladder(tuple(plan),
                                                       trace),
                          plan_store=store, audit=None,
                          steerer="serving_ladder")
        refused = False
    except ValueError:
        refused = True
    _check("audit: promotion with a PlanStore but no AuditTrail "
           "refuses", refused)
    _check("audit: decision counters", obs.counter_value(
        "canary.promoted", steerer="serving_ladder") == 1
        and obs.counter_value("canary.rolled_back",
                              steerer="serving_ladder") == 1)

    # satellite 3: the decisions land in the merged chrome trace
    from paddle_tpu.observability import distributed as odist

    os.environ["PADDLE_TPU_METRICS_DIR"] = cdir
    odist.dump_process()
    odist.merge_job_dir(cdir)
    with open(os.path.join(cdir, "trace.json"), "r",
              encoding="utf-8") as f:
        rows = json.load(f).get("traceEvents", [])
    instants = {r["name"]: r for r in rows
                if r.get("ph") == "i" and r.get("name") in
                ("steering.proposed", "canary.promoted",
                 "canary.rolled_back")}
    ok = (set(instants) == {"steering.proposed", "canary.promoted",
                            "canary.rolled_back"}
          and all(r.get("args", {}).get("plan_digest")
                  for r in instants.values()))
    _check("trace: steering/canary instants with digests in merged "
           "trace.json", ok, "found=%s" % sorted(instants))


# -- leg 4 (--drift): drifting load vs interleaved A/B objective ------------

def leg_drifting_load(rng, workdir: str) -> None:
    """Seeded drifting-load scenario (ISSUE 20). Closed-loop serving
    throughput drifts UP +4% per measurement window — the box is
    warming up, traffic is ramping, nobody changed a plan. A
    candidate ladder that is objectively WORSE (more padding, lower
    true throughput, but each delta under the flat comparator's
    absolute noise floors) is canaried two ways:

    - the legacy flat ``run_canary`` against a STALE incumbent record
      (measured 11 drift windows earlier) PROMOTES it — accumulated
      drift masquerades as a +40% throughput win and no flat
      threshold catches the real regressions;
    - the interleaved A/B objective canary measures incumbent and
      candidate in ADJACENT windows, so drift contributes at most one
      window (+4%) to each pairwise delta while the true effect
      (-6.4% rows/s, +23% waste) dominates the weighted score: every
      pair votes regression, 0/N, ROLL BACK.

    The same A/B canary then PROMOTES a genuinely-better plan (the
    quantile ladder) in the same run, proving the protocol is not
    just "reject everything under drift". Every window, pairwise
    verdict, and objective term is asserted present in
    ``steering_audit.json``."""
    from paddle_tpu import observability as obs
    from paddle_tpu.observability import canary, comparator, steering
    from paddle_tpu.serving.batcher import default_ladder, plan_ladder

    import ft_timeline

    ddir = os.path.join(workdir, "drift")
    os.makedirs(ddir, exist_ok=True)
    obs.enable()

    trace = np.concatenate([
        rng.integers(3, 5, 60), rng.integers(11, 14, 40)])
    rng.shuffle(trace)
    trace = [int(r) for r in trace]

    incumbent_ladder = default_ladder(16)      # (1, 2, 4, 8, 16)
    bad_plan = (5, 16)   # slightly worse everywhere, each delta
    #                      under the flat absolute noise floors
    good_plan = plan_ladder(16, trace)         # fitted quantile ladder

    true_inc = _measure_ladder(incumbent_ladder, trace)
    true_bad = _measure_ladder(bad_plan, trace)
    true_good = _measure_ladder(good_plan, trace)

    def _w(rec):
        return rec["extras"]["serving"]["serving_padding_waste_frac"]

    _check("drift: candidate plans bracket the incumbent (ground "
           "truth, no drift)",
           _w(true_bad) > _w(true_inc) > _w(true_good),
           "waste bad=%.3f inc=%.3f good=%.3f"
           % (_w(true_bad), _w(true_inc), _w(true_good)))
    _check("drift: bad plan hides under the flat noise floor",
           0 < _w(true_bad) - _w(true_inc)
           < comparator.ABS_NOISE_FLOOR["serving_padding_waste_frac"],
           "delta=%.3f floor=%.2f"
           % (_w(true_bad) - _w(true_inc),
              comparator.ABS_NOISE_FLOOR["serving_padding_waste_frac"]))

    # monotone load drift: throughput inflates +4% per window, no
    # matter whose plan is being measured
    DRIFT = 0.04
    clock = {"win": 0}

    def measure(plan):
        ladder = tuple(plan) if plan is not None else incumbent_ladder
        rec = _measure_ladder(ladder, trace)
        srv = rec["extras"]["serving"]
        srv["rows_per_s"] *= (1.0 + DRIFT) ** clock["win"]
        clock["win"] += 1
        return rec

    objective = comparator.Objective(
        {"rows_per_s": 2.0, "serving_padding_waste_frac": 1.0},
        floors={"serving_padding_waste_frac": 0.02})

    def _proposal(plan, with_objective=True):
        art = {"plan": list(plan),
               "plan_digest": steering.plan_digest(list(plan)),
               "steerer": "serving_ladder",
               "metric": "serving_padding_waste"}
        if with_objective:
            # the shape WatchRule(objective=, ab_pairs=) emits
            art["objective"] = objective.to_dict()
            art["ab_pairs"] = 3
        return art

    # -- the cautionary tale: flat canary on a stale incumbent -------
    flat_dir = os.path.join(ddir, "flat")
    os.makedirs(flat_dir, exist_ok=True)
    incumbent_rec = measure(None)       # window 0
    clock["win"] += 10                  # proposal sits unactioned
    flat = canary.run_canary(
        _proposal(bad_plan, with_objective=False),  # legacy protocol
        incumbent_rec, measure,
        plan_store=canary.PlanStore(flat_dir, "serving_ladder"),
        audit=canary.AuditTrail(flat_dir),
        require_improvement="rows_per_s", min_improvement=0.05)
    _check("drift: FLAT comparator PROMOTES the objectively-worse "
           "plan (drift masquerades as a win)", flat.promoted,
           "decision=%s reason=%s" % (flat.decision, flat.reason))

    # -- the fix: interleaved A/B windows + weighted objective -------
    ab_dir = os.path.join(ddir, "ab")
    os.makedirs(ab_dir, exist_ok=True)
    audit = canary.AuditTrail(ab_dir)
    store = canary.PlanStore(ab_dir, "serving_ladder")
    bad = canary.run_ab_canary(_proposal(bad_plan), measure,
                               audit=audit, plan_store=store)
    _check("drift: A/B objective canary ROLLS BACK the same plan "
           "under the same drift", not bad.promoted,
           "reason=%s score=%s"
           % (bad.reason, bad.audit_entry.get("objective_score")))

    good = canary.run_ab_canary(_proposal(good_plan), measure,
                                audit=audit, plan_store=store)
    _check("drift: A/B objective canary PROMOTES the genuinely-"
           "better plan in the same run", good.promoted,
           "reason=%s score=%s"
           % (good.reason, good.audit_entry.get("objective_score")))
    _check("drift: only the good plan is installed",
           store.installs == 1 and store.active_digest()
           == steering.plan_digest(list(good_plan)))

    # -- audit closure: windows, pairwise verdicts, objective terms --
    entries = [e for e in audit.entries()
               if e.get("protocol") == canary.AB_PROTOCOL]
    ok = len(entries) == 2
    for e in entries:
        ok = (ok and len(e.get("windows") or []) == 2 * e["pairs"]
              and len(e.get("pair_verdicts") or []) == e["pairs"]
              and all(w.get("t_close") >= w.get("t_open")
                      and w.get("phase") in ("incumbent", "candidate")
                      for w in e["windows"])
              and all(isinstance(p.get("objective_score"), float)
                      and (p.get("comparison") or {}).get("objective")
                      for p in e["pair_verdicts"])
              and isinstance(e.get("objective_score"), float)
              and isinstance(e.get("objective"), dict))
        for p in (e.get("pair_verdicts") or []):
            terms = ((p["comparison"]["objective"].get("result")
                      or {}).get("terms")) or []
            ok = ok and {t["metric"] for t in terms} == {
                "rows_per_s", "serving_padding_waste_frac"}
    _check("drift: every window, pairwise verdict and objective term "
           "is on the audit trail", ok,
           "ab_entries=%d" % len(entries))

    exp_windows = sum(e["pairs"] for e in entries)
    _check("drift: canary.windows{phase=} counters",
           obs.counter_value("canary.windows", phase="incumbent",
                             steerer="serving_ladder") == exp_windows
           and obs.counter_value("canary.windows", phase="candidate",
                                 steerer="serving_ladder")
           == exp_windows)
    _check("drift: steering.objective_score gauge follows the last "
           "decision", obs.gauge_value(
               "steering.objective_score",
               steerer="serving_ladder") > 0)

    # the human-readable read of the same trail (satellite: ft_timeline)
    lines = ft_timeline.format_ab_timeline(
        ft_timeline.load_ab_entries(ab_dir))
    for ln in lines:
        print("[steer]   %s" % ln)
    _check("drift: ft_timeline renders the A/B window timeline",
           sum(1 for ln in lines if ln.lstrip().startswith("ab #")) == 2
           and any("verdict=objective_regression" in ln for ln in lines)
           and any("verdict=objective_improved" in ln for ln in lines)
           and any(ln.lstrip().startswith("objective:")
                   for ln in lines))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--drift", action="store_true",
                    help="run ONLY the seeded drifting-load A/B leg "
                         "(ISSUE 20 CI gate variant)")
    args = ap.parse_args()
    rng = np.random.default_rng(args.seed)

    with tempfile.TemporaryDirectory(prefix="steer_drill_") as workdir:
        saved = os.environ.get("PADDLE_TPU_METRICS_DIR")
        try:
            if args.drift:
                leg_drifting_load(rng, workdir)
            else:
                leg_sampled_capture(rng, workdir)
                proposal, trace = leg_daemon_hysteresis(rng, workdir)
                if proposal is None:
                    _check("canary: skipped — daemon emitted no "
                           "proposal", False)
                else:
                    leg_canary(proposal, trace, workdir)
        finally:
            if saved is None:
                os.environ.pop("PADDLE_TPU_METRICS_DIR", None)
            else:
                os.environ["PADDLE_TPU_METRICS_DIR"] = saved
            os.environ.pop("PADDLE_TPU_SAMPLE_EVERY", None)

    failed = [w for w, p in _CHECKS if not p]
    if failed:
        print("[steer] %d/%d checks FAILED" % (len(failed),
                                               len(_CHECKS)))
        return 1
    print("[steer] ALL %d CHECKS PASS" % len(_CHECKS))
    return 0


if __name__ == "__main__":
    sys.exit(main())
