"""Memory facade + DLPack interop (§2.4 memory row, §2.1 dlpack row)."""
import os

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.core import dlpack, memory


def test_configure_allocator_maps_flags():
    saved = {k: os.environ.get(k) for k in
             ("XLA_PYTHON_CLIENT_MEM_FRACTION",
              "XLA_PYTHON_CLIENT_PREALLOCATE",
              "XLA_PYTHON_CLIENT_ALLOCATOR")}
    saved_flag = fluid.get_flags("FLAGS_fraction_of_gpu_memory_to_use")
    try:
        applied = memory.configure_allocator(fraction=0.5,
                                             strategy="auto_growth")
        assert os.environ["XLA_PYTHON_CLIENT_MEM_FRACTION"] == "0.5"
        assert os.environ["XLA_PYTHON_CLIENT_PREALLOCATE"] == "false"
        assert applied["XLA_PYTHON_CLIENT_ALLOCATOR"] == "bfc"
        applied = memory.configure_allocator(fraction=0.9,
                                             strategy="naive_best_fit")
        assert os.environ["XLA_PYTHON_CLIENT_PREALLOCATE"] == "true"
        # flag-registry defaults drive the no-arg call
        fluid.set_flags({"FLAGS_fraction_of_gpu_memory_to_use": 0.25})
        applied = memory.configure_allocator()
        assert applied["XLA_PYTHON_CLIENT_MEM_FRACTION"] == "0.25"
    finally:
        fluid.set_flags(saved_flag)
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def test_alloc_and_stats():
    buf = memory.alloc(fluid.CPUPlace(), 1024)
    assert buf.shape == (1024,) and str(buf.dtype) == "uint8"
    usage = memory.memory_usage(fluid.CPUPlace())
    assert set(usage) == {"allocated", "reserved", "peak", "limit"}
    assert all(isinstance(v, int) for v in usage.values())
    memory.release_all()


def test_dlpack_roundtrip_with_torch():
    """Real cross-framework exchange against torch (cpu), the contract
    dlpack_tensor.cc covers with its DLPack tests."""
    import torch

    from paddle_tpu.core.tensor import LoDTensor

    src = np.arange(12, dtype="float32").reshape(3, 4)
    t = LoDTensor()
    t.set(src)

    # paddle_tpu -> torch
    th = torch.utils.dlpack.from_dlpack(dlpack.to_dlpack(t))
    np.testing.assert_array_equal(th.numpy(), src)

    # torch -> paddle_tpu
    th2 = torch.arange(6, dtype=torch.float32).reshape(2, 3) * 2
    back = dlpack.from_dlpack(th2)
    np.testing.assert_array_equal(np.asarray(back.array),
                                  th2.numpy())
    # and it behaves as a normal LoDTensor in a program
    prog = fluid.Program()
    b = prog.global_block()
    b.create_var(name="dl_x")
    b.append_op("scale", {"X": ["dl_x"]}, {"Out": ["dl_y"]},
                {"scale": 3.0}, infer_shape=False)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        (out,) = exe.run(prog, feed={"dl_x": back}, fetch_list=["dl_y"])
    np.testing.assert_allclose(np.asarray(out), th2.numpy() * 3.0)
