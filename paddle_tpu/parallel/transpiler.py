"""Collective program rewrites.

Parity: /root/reference/python/paddle/fluid/transpiler/collective.py
(GradAllReduce: loss-grad scale 1/nranks :190-213 + per-grad
c_allreduce_sum :215-250; LocalSGD :270) — the same pass over the
Python-native IR. ring_id stays in the op attrs; at execution the mesh
engine maps it to a named axis.
"""
from __future__ import annotations

from typing import Optional, Set

from ..analysis.contracts import checked_rewrite
from ..core.registry import GRAD_SUFFIX, OpInfoMap

OPTIMIZER_OP_TYPES = {
    "sgd", "momentum", "lars_momentum", "adam", "adamw", "adamax", "adagrad",
    "decayed_adagrad", "adadelta", "rmsprop", "ftrl", "lamb", "dpsgd",
    "proximal_gd",
}


def _is_loss_grad_seed(op):
    return (op.type == "fill_constant"
            and op.output("Out")
            and op.output("Out")[0].endswith(GRAD_SUFFIX)
            and float(op.attrs.get("value", 0.0)) == 1.0)


@checked_rewrite("insert_allreduce")
def insert_allreduce_ops(program, nranks: int, ring_id: int = 0,
                         scale_loss: bool = True, skip_grads=None):
    """Rewrite a training program for data parallelism: scale the loss
    grad by 1/nranks and allreduce every grad consumed by an optimizer op.
    Returns the set of grad var names allreduced. Idempotent: a program
    is rewritten at most once (fleet may transpile before the mesh
    engine sees the program). ``skip_grads``: grads of mesh-SHARDED
    params (sharded embedding rows, local experts) — their collective
    transposes already accumulate every shard's contribution, and an
    extra allreduce over the data axes would corrupt them."""
    if getattr(program, "_grads_allreduced", False):
        return set()
    program._grads_allreduced = True
    skip = set(skip_grads or ())
    block = program.global_block()
    if scale_loss:
        for op in block.ops:
            if _is_loss_grad_seed(op):
                op.attrs["value"] = 1.0 / nranks
    grad_names: Set[str] = set()
    for op in block.ops:
        if op.type in OPTIMIZER_OP_TYPES:
            for g in op.input("Grad"):
                if g not in skip:
                    grad_names.add(g)

    new_ops = []
    inserted: Set[str] = set()
    for op in block.ops:
        if op.type in OPTIMIZER_OP_TYPES:
            for g in op.input("Grad"):
                if g not in inserted and g not in skip:
                    from .. import framework

                    ar = framework.Operator(
                        block, "c_allreduce_sum",
                        {"X": [g]}, {"Out": [g]},
                        {"ring_id": ring_id, "use_calc_stream": True})
                    ar._id = program._next_op_id()
                    new_ops.append(ar)
                    inserted.add(g)
        new_ops.append(op)
    block.ops = new_ops
    return grad_names


def insert_local_sgd_ops(program, nranks: int, k_steps: int = 1,
                         ring_id: int = 0):
    """LocalSGD-style periodic parameter averaging (collective.py:270):
    every step here (k-step gating arrives with the step-counter wave),
    params are psum-averaged after the optimizer ops."""
    from .. import framework

    block = program.global_block()
    params = [p.name for p in program.all_parameters()]
    for name in params:
        ar = framework.Operator(block, "c_allreduce_sum", {"X": [name]},
                                {"Out": [name]}, {"ring_id": ring_id})
        ar._id = program._next_op_id()
        block.ops.append(ar)
        sc = framework.Operator(block, "scale", {"X": [name]},
                                {"Out": [name]}, {"scale": 1.0 / nranks,
                                                  "bias": 0.0})
        sc._id = program._next_op_id()
        block.ops.append(sc)
    return params


# -- hybrid parallelism passes (tensor / sequence / expert) -----------------
# The reference reaches distribution by program rewrite
# (transpiler/collective.py:92-131); these passes are the same pattern
# for the axes the reference lacks: ops are swapped for their
# collective-aware twins (ops/hybrid_parallel_ops.py) BEFORE backward
# generation, so append_backward differentiates through the collectives
# via auto-VJP. Each pass records mesh metadata on the program:
#   _var_shard_specs:  var name -> per-dim mesh-axis tuple
#   _feed_shard_specs: feed name -> per-dim mesh-axis tuple
#   _data_axes:        axes the batch is sharded over (loss/grad scale)
#   _allreduce_skip_grads: grads of SHARDED params (their collective
#       transposes already total every shard's contribution)


def _mark_shard(program, name: str, spec):
    specs = getattr(program, "_var_shard_specs", None)
    if specs is None:
        specs = {}
        program._var_shard_specs = specs
    specs[name] = tuple(spec)


def _skip_grad(program, grad_name: str, axes):
    """Record that ``grad_name`` belongs to a param sharded over
    ``axes``. The engine skips its data-axis allreduce ONLY when the
    shard axis IS a data axis (expert parallel: the all_to_all transpose
    already totals every shard's contribution); a grad sharded over an
    orthogonal model axis (mp table blocks under dp x mp) still needs
    the psum over dp."""
    skips = getattr(program, "_allreduce_skip_grads", None)
    if skips is None:
        skips = {}
        program._allreduce_skip_grads = skips
    skips[grad_name] = tuple(a for a in axes if a)


def _bump_version(program):
    # attr-only rewrites must still invalidate the engine's
    # program-version-keyed trace caches
    program._next_op_id()


def _merge_data_axes(program, axes):
    """Union (order-preserving) with axes recorded by earlier passes —
    a later pass must not clobber another's data axes (an MoE
    transformer with long context runs sp AND ep passes)."""
    cur = list(getattr(program, "_data_axes", None) or ())
    for a in axes:
        if a not in cur:
            cur.append(a)
    program._data_axes = tuple(cur)


@checked_rewrite("sharded_embedding")
def apply_sharded_embedding(program, axis: str = "mp", degree: int = 0,
                            startup_program=None):
    """Tensor parallelism for embedding tables: every lookup_table[_v2]
    op becomes c_sharded_lookup with its table row-sharded over ``axis``
    (the pslib sparse-PS replacement, fleet_wrapper.h:84 — here one
    gather+psum pair on ICI). Call BEFORE minimize(). Returns the
    sharded table names.

    Uneven vocab (V % degree != 0): the table var is PADDED to the next
    multiple of ``degree`` — lookups never touch pad rows (ids < V), so
    their grads are zero and the optimizer leaves them at init. The
    startup program's init op is re-shaped to match, which is why it
    must be passed when vocab is uneven."""
    block = program.global_block()
    tables = []
    for op in block.ops:
        if op.type not in ("lookup_table", "lookup_table_v2"):
            continue
        w = op.input("W")[0]
        v = block._find_var_recursive(w)
        vocab = int(v.shape[0]) if v is not None and v.shape else 0
        if degree and vocab and vocab % degree:
            v_pad = -(-vocab // degree) * degree
            if startup_program is None:
                raise ValueError(
                    "sharded embedding %r: vocab %d not divisible by "
                    "mp degree %d — pass startup_program so the table "
                    "can be padded to %d rows"
                    % (w, v.shape[0], degree, v_pad))
            _pad_table_rows(program, startup_program, w, v, v_pad)
        if op.attrs.get("is_sparse"):
            # mesh sharding REPLACES the SelectedRows sparse-grad path:
            # the local block grad is dense [V/mp, D] (the design — one
            # gather/psum pair instead of sparse push RPC), which is a
            # deliberate, visible semantics change for is_sparse tables
            import warnings

            warnings.warn(
                "sharded embedding %r: is_sparse=True becomes a dense "
                "row-sharded gradient under tensor parallelism" % w)
        squeeze = op.type == "lookup_table"  # v2 keeps the trailing dim
        op.type = "c_sharded_lookup"
        op.attrs = {"shard_axis": axis,
                    "padding_idx": int(op.attrs.get("padding_idx", -1)),
                    "squeeze_last": squeeze,
                    # the TRUE vocab (captured before pad-row growth)
                    "vocab_size": vocab}
        _mark_shard(program, w, (axis,))
        _skip_grad(program, w + GRAD_SUFFIX, (axis,))
        tables.append(w)
    _merge_data_axes(program, ("dp",))
    _bump_version(program)
    return tables


def _pad_table_rows(program, startup_program, name, var, v_pad):
    """Grow an embedding var to ``v_pad`` rows in BOTH programs (main
    var shape + every startup init op writing it); pad rows are inert:
    never looked up, zero grad."""
    new_shape = (v_pad,) + tuple(var.shape[1:])
    var.shape = new_shape
    for blk in ([startup_program.global_block()]
                + [program.global_block()]):
        for op in blk.ops:
            if name in op.output_arg_names and "shape" in op.attrs:
                op.attrs["shape"] = list(new_shape)
    sv = startup_program.global_block()._find_var_recursive(name)
    if sv is not None:
        sv.shape = new_shape


@checked_rewrite("sequence_parallel")
def apply_sequence_parallel(program, axis: str = "sp", degree: int = 0,
                            feed_specs=None):
    """Sequence/context parallelism: flash_attention ops become
    c_ring_attention over ``axis`` (K/V shards rotate the ring via
    ppermute — long-context training). ``feed_specs`` declares how data
    feeds are laid out over the mesh, e.g. {"x": ("dp", None, "sp")} for
    [B, H, S, D] with batch over dp and sequence over sp. ``degree``
    (when given) validates the attention sequence length divides evenly
    — a clear error here beats a cryptic shard_map one at run time.
    Call BEFORE minimize()."""
    block = program.global_block()
    n = 0
    for op in block.ops:
        if op.type != "flash_attention":
            continue
        # a Lengths (padding) input carries straight through: ring
        # attention masks GLOBAL key positions >= lengths[b], the same
        # contract as the masked flash kernels. The [B] lengths var is
        # BATCH-aligned: pin it to the 'dp' axis so the engine's
        # default data-axis sharding can never split it over the ring
        # (an sp-only mesh would otherwise shard [B] over sp and mask
        # with the wrong example's length — with that pin, an sp-only
        # mesh fails loudly on the missing 'dp' axis instead)
        for ln in op.input("Lengths"):
            fs = getattr(program, "_feed_shard_specs", None)
            if fs is None:
                fs = {}
                program._feed_shard_specs = fs
            fs.setdefault(ln, ("dp",))
        if degree:
            q = block._find_var_recursive(op.input("Q")[0])
            if (q is not None and q.shape is not None and len(q.shape) >= 3
                    and q.shape[2] and q.shape[2] % degree):
                raise ValueError(
                    "sequence parallel: attention seq len %d not "
                    "divisible by sp degree %d (Q=%r)"
                    % (q.shape[2], degree, op.input("Q")[0]))
        op.type = "c_ring_attention"
        op.attrs = {"shard_axis": axis,
                    "causal": bool(op.attrs.get("causal")),
                    "scale": float(op.attrs.get("scale", 0.0))}
        n += 1
    if feed_specs:
        fs = getattr(program, "_feed_shard_specs", None)
        if fs is None:
            fs = {}
            program._feed_shard_specs = fs
        fs.update({k: tuple(v) for k, v in feed_specs.items()})
    _merge_data_axes(program, ("dp", axis))
    _bump_version(program)
    return n


@checked_rewrite("expert_parallel")
def apply_expert_parallel(program, axis: str = "ep", degree: int = 1):
    """Expert parallelism: moe ops route tokens to device-local expert
    shards via two all_to_alls over ``axis``; tokens (the batch) are
    sharded over the same axis. Dense runs of the transpiled program
    chunk routing into ``degree`` groups so both paths drop identical
    tokens. Call BEFORE minimize()."""
    block = program.global_block()
    experts = []
    for op in block.ops:
        if op.type != "moe":
            continue
        w_in, w_out = op.input("WIn")[0], op.input("WOut")[0]
        for w in (w_in, w_out):
            v = block._find_var_recursive(w)
            if v is not None and v.shape and v.shape[0] % degree:
                raise ValueError(
                    "expert parallel %r: %d experts not divisible by "
                    "ep degree %d" % (w, v.shape[0], degree))
            _mark_shard(program, w, (axis,))
            _skip_grad(program, w + GRAD_SUFFIX, (axis,))
        op.attrs = dict(op.attrs)
        op.attrs["shard_axis"] = axis
        op.attrs["num_groups"] = int(degree)
        experts.append((w_in, w_out))
    _merge_data_axes(program, (axis,))
    _bump_version(program)
    return experts


def shard_optimizer_state(program):
    """After minimize(): optimizer accumulators of a sharded param
    (momentum velocity, adam moments) are elementwise-paired with it and
    must shard identically. Matches by optimizer-op Param input + shape."""
    specs = getattr(program, "_var_shard_specs", None)
    if not specs:
        return
    block = program.global_block()
    for op in block.ops:
        if op.type not in OPTIMIZER_OP_TYPES:
            continue
        params = op.input("Param")
        if not params or params[0] not in specs:
            continue
        spec = specs[params[0]]
        pvar = block._find_var_recursive(params[0])
        pshape = tuple(pvar.shape) if pvar is not None else None
        grads = set(op.input("Grad"))
        for name in op.input_arg_names:
            if name in specs or name == params[0] or name in grads:
                continue
            v = block._find_var_recursive(name)
            if (v is not None and v.shape is not None
                    and tuple(v.shape) == pshape):
                specs[name] = spec


def mark_sync_batch_norm(program, enable=True):
    """BuildStrategy.sync_batch_norm: tag batch_norm ops so their batch
    statistics pmean across the mesh axis (reference
    ir/sync_batch_norm_pass.cc rewriting batch_norm -> sync_batch_norm).
    Applies the CURRENT strategy value each call (the engine keys its
    compile cache on it, so flipping the knob between runs retraces)."""
    if getattr(program, "_sync_bn_marked", None) == enable:
        return
    program._sync_bn_marked = enable
    for block in program.blocks:
        for op in block.ops:
            if op.type == "batch_norm":
                op.attrs["_sync_stats"] = bool(enable)
